"""File scan layer: Parquet / CSV / ORC readers behind a strategy SPI.

Reference analog: L8 (SURVEY.md) — ``GpuParquetScan.scala`` parses footers on
CPU, reassembles column chunks into one host buffer, then decodes on-device
via ``Table.readParquet``.  Three strategies (reference:
GpuParquetScan.scala:824,1145; RapidsConf.scala:513,540):

  * PERFILE      — one read per file
  * COALESCING   — many small files glued into one host read per batch
  * MULTITHREADED— thread-pool prefetch for high-latency (cloud) stores

Here decode happens on host via Arrow C++ behind the same reader interface,
exactly the fallback position SURVEY.md §7 phase 3 prescribes; a Pallas
device decoder can swap in behind ``_read_one`` without touching callers.
The strategy selection and row-group batching structure is preserved.
"""

from __future__ import annotations

import concurrent.futures as cf
import os
from typing import Iterator, List, Optional
from urllib.parse import urlparse

import pyarrow as pa
import pyarrow.csv as pacsv
import pyarrow.orc as paorc
import pyarrow.parquet as papq

from spark_rapids_tpu import config as cfg
from spark_rapids_tpu.config import RapidsTpuConf
from spark_rapids_tpu.obs import registry as obsreg
from spark_rapids_tpu.exec.base import PhysicalPlan
from spark_rapids_tpu.plan.logical import FileScan, Schema


_EXTS = {"parquet": (".parquet", ".parq"), "csv": (".csv",),
         "orc": (".orc",)}


def expand_paths(fmt: str, paths: List[str]):
    """Expand directories into part files + Hive partition values.

    Reference analog: partition discovery + partition-value columns
    appended by ColumnarPartitionReaderWithPartitionValues.
    """
    import glob
    exts = _EXTS[fmt]
    files: List[str] = []
    part_values: List[dict] = []
    for p in paths:
        if os.path.isdir(p):
            hits = sorted(
                f for f in glob.glob(
                    os.path.join(glob.escape(p), "**", "*"),
                    recursive=True)
                if os.path.isfile(f) and (
                    f.endswith(exts) or "part-" in os.path.basename(f))
                and not os.path.basename(f).startswith(("_", ".")))
            for f in hits:
                files.append(f)
                part_values.append(dir_part_values(p, f))
        else:
            files.append(p)
            part_values.append({})
    return files, part_values


def dir_part_values(root: str, f: str) -> dict:
    """Hive partition values encoded in ``f``'s path below ``root`` —
    the ONE parser for `key=value` path segments, shared by
    ``expand_paths`` and the incremental maintainer's stamp-derived
    file lists (exec/incremental.py) so the two can't drift."""
    rel = os.path.relpath(os.path.dirname(f), root)
    pv: dict = {}
    if rel != ".":
        for seg in rel.split(os.sep):
            if "=" in seg:
                k, v = seg.split("=", 1)
                pv[k] = None if v == "__HIVE_DEFAULT_PARTITION__" else v
    return pv


def scan_file_indices(scan) -> List[int]:
    """File indices a scan should actually read: all of them, unless a
    ``file_subset`` restriction is stamped in the scan options (the
    incremental delta path, exec/incremental.py).  Index-based so
    ``part_values``/``part_fields`` alignment survives the
    restriction."""
    subset = scan.options.get("file_subset")
    if subset is None:
        return list(range(len(scan.paths)))
    keep = {os.path.abspath(p) for p in subset}
    return [i for i, p in enumerate(scan.paths)
            if os.path.abspath(p) in keep]


def _partition_fields(part_values: List[dict]):
    """Infer partition column types (int64 if every value parses)."""
    from spark_rapids_tpu import dtypes as dt
    keys: List[str] = []
    for pv in part_values:
        for k in pv:
            if k not in keys:
                keys.append(k)
    fields = []
    for k in keys:
        vals = [pv.get(k) for pv in part_values]
        all_int = all(v is None or _is_int(v) for v in vals) and \
            any(v is not None for v in vals)
        fields.append((k, dt.INT64 if all_int else dt.STRING))
    return fields


def _is_int(s: str) -> bool:
    # strict digits only: int() would also accept '1_2' and ' 7 ', which
    # must stay strings lest the partition value silently change
    import re
    return isinstance(s, str) and re.fullmatch(r"[+-]?\d+", s) is not None


def infer_schema(fmt: str, paths: List[str],
                 options: Optional[dict] = None) -> Schema:
    options = options or {}
    if fmt == "parquet":
        # one footer parse serves schema inference AND the scan: the
        # cached FooterInfo is what TpuParquetScanExec re-opens
        from spark_rapids_tpu.io import scan_cache as sc
        return Schema.from_arrow(sc.get_footer(paths[0]).schema_arrow)
    if fmt == "orc":
        return Schema.from_arrow(paorc.ORCFile(paths[0]).schema)
    if fmt == "csv":
        t = _read_csv(paths[0], options)
        return Schema.from_arrow(t.schema)
    raise ValueError(f"unknown format {fmt}")


def _read_csv(path: str, options: dict) -> pa.Table:
    read_opts = pacsv.ReadOptions(
        autogenerate_column_names=not options.get("header", True))
    parse_opts = pacsv.ParseOptions(
        delimiter=options.get("sep", ","))
    convert_opts = pacsv.ConvertOptions(
        null_values=[options.get("nullValue", "")],
        strings_can_be_null=True)
    return pacsv.read_csv(path, read_options=read_opts,
                          parse_options=parse_opts,
                          convert_options=convert_opts)


def _normalize(t: pa.Table, schema: Schema,
               permissive: bool = False) -> pa.Table:
    """Cast to the scan schema (timestamps to us/UTC etc.).

    ``permissive`` applies Spark's permissive-CSV semantics to numeric
    narrowing: values an integer column cannot hold become null instead
    of raising — used by every CSV path so the per-column device
    fallback, the whole-file fallback and the CPU scan agree."""
    target = pa.schema([pa.field(f.name, f.dtype.to_arrow(), f.nullable)
                        for f in schema.fields])
    cols = []
    for f in target:
        col = t.column(f.name) if f.name in t.column_names else None
        if col is None:
            cols.append(pa.nulls(t.num_rows, f.type))
        elif permissive:
            cols.append(_permissive_cast(col, f.type))
        else:
            cols.append(col.cast(f.type))
    return pa.Table.from_arrays(cols, schema=target)


def _permissive_cast(col: pa.ChunkedArray, typ: pa.DataType):
    """Arrow cast with Spark's permissive-CSV overflow semantics:
    integer-column values out of range (int source) or out of
    range/non-integral (float source) become null rather than raising
    (stock safe cast) or wrapping (unsafe cast)."""
    import numpy as np
    import pyarrow.compute as pc
    try:
        return col.cast(typ)
    except (pa.ArrowInvalid, pa.ArrowNotImplementedError):
        if not pa.types.is_integer(typ):
            raise
        info = np.iinfo(typ.to_pandas_dtype())
        if pa.types.is_floating(col.type):
            # float(int64.max) rounds UP to 2^63, which is NOT a valid
            # int64 — use a strict compare when the bound rounded so the
            # boundary value nulls out instead of raising in the cast
            hi = float(info.max)
            hi_cmp = pc.less if int(hi) > info.max else pc.less_equal
            ok = pc.and_kleene(
                pc.equal(col, pc.trunc(col)),
                pc.and_kleene(
                    pc.greater_equal(col, pa.scalar(float(info.min),
                                                    type=col.type)),
                    hi_cmp(col, pa.scalar(hi, type=col.type))))
        elif pa.types.is_integer(col.type):
            ok = pc.and_kleene(
                pc.greater_equal(col, pa.scalar(int(info.min),
                                                type=col.type)),
                pc.less_equal(col, pa.scalar(int(info.max),
                                             type=col.type)))
        else:
            raise
        return pc.if_else(ok, col,
                          pa.scalar(None, type=col.type)).cast(typ)


class CpuFileScanExec(PhysicalPlan):
    """v1-style file scan exec (GpuFileSourceScanExec analog)."""

    def __init__(self, scan: FileScan, conf: RapidsTpuConf):
        super().__init__()
        self.scan = scan
        self.conf = conf
        self._schema = scan.schema
        self.columns = scan.options.get("columns")
        self.reader_type = self._select_reader_type()
        self.max_rows = conf.get(cfg.MAX_READER_BATCH_SIZE_ROWS)

    def _select_reader_type(self) -> str:
        rt = str(self.conf.get(cfg.PARQUET_READER_TYPE)).upper()
        if rt != "AUTO":
            return rt
        cloud = {s.strip() for s in
                 str(self.conf.get(cfg.CLOUD_SCHEMES)).split(",")}
        schemes = {urlparse(p).scheme for p in self.scan.paths}
        if schemes & cloud:
            return "MULTITHREADED"
        if len(self.scan.paths) > 4:
            return "COALESCING"
        return "PERFILE"

    @property
    def schema(self) -> Schema:
        return self._schema

    def _read_one(self, file_index: int) -> pa.Table:
        """Decode one file, multicast through the shared-scan window
        when enabled: concurrent queries decoding the same stampable
        file (same projection/options) share one decode — the host
        (legacy v1) analog of device_scan's fused-scan sharing.  A
        file that can't be stamped (vanished between plan and decode,
        non-local path) is never shared and counts
        ``scan.shared.ineligible.legacy``."""
        key = self._share_key(file_index)
        if key is None:
            return self._decode_one(file_index)
        from spark_rapids_tpu.io import scan_share
        share = scan_share.get_share(
            int(self.conf.get(cfg.SCAN_SHARED_WINDOW_BYTES)))
        role, entry = share.claim(key)
        if role == "join":
            try:
                t = share.wait(entry)
            finally:
                share.release(entry)
            if t is not None:   # wait() counted the deduped decode
                return t
            # leader failed/was cancelled: decode locally
            return self._decode_one(file_index)
        try:
            t = self._decode_one(file_index)
        except BaseException as e:
            share.fail(entry, e)
            share.release(entry)
            raise
        share.publish(entry, t)
        share.release(entry)
        return t

    def _share_key(self, file_index: int):
        """Content identity of one host-scan file decode, or None when
        sharing is off or the file can't be stamped."""
        if not bool(self.conf.get(cfg.SCAN_SHARED_ENABLED)):
            return None
        from spark_rapids_tpu.io import scan_cache as sc
        path = self.scan.paths[file_index]
        stamp = sc.file_key(path)
        if stamp is None:
            obsreg.get_registry().inc("scan.shared.ineligible.legacy")
            return None
        pv_list = self.scan.options.get("part_values") or []
        pv = pv_list[file_index] if file_index < len(pv_list) else {}
        opts = {k: v for k, v in self.scan.options.items()
                if k not in ("part_values",)}
        return ("cpu", stamp, self.scan.fmt,
                tuple(self.columns or ()),
                tuple(sorted((str(k), str(v)) for k, v in pv.items())),
                repr(sorted(opts.items(), key=lambda kv: str(kv[0]))),
                repr(self._schema))

    def _decode_one(self, file_index: int) -> pa.Table:
        path = self.scan.paths[file_index]
        fmt = self.scan.fmt
        part_fields = dict(self.scan.options.get("part_fields") or [])
        if self.columns:
            # only materialize partition columns the projection keeps
            part_fields = {k: d for k, d in part_fields.items()
                           if k in self.columns}
        file_cols = self.columns
        if file_cols:
            file_cols = [c for c in file_cols if c not in part_fields]
        if fmt == "parquet":
            t = papq.read_table(path, columns=file_cols)
        elif fmt == "orc":
            t = paorc.ORCFile(path).read(columns=file_cols)
        elif fmt == "csv":
            t = _read_csv(path, self.scan.options)
            if file_cols:
                t = t.select(file_cols)
        else:
            raise ValueError(fmt)
        # append Hive partition-value columns for this file
        # (ColumnarPartitionReaderWithPartitionValues analog)
        pv_list = self.scan.options.get("part_values") or []
        pv = pv_list[file_index] if file_index < len(pv_list) else {}
        for k, d in part_fields.items():
            if k in t.column_names:
                # the partition value wins over a same-named file column
                t = t.drop_columns([k])
            raw = pv.get(k)
            if raw is None:
                col = pa.nulls(t.num_rows, d.to_arrow())
            else:
                val = int(raw) if d.to_arrow() == pa.int64() else raw
                col = pa.array([val] * t.num_rows, type=d.to_arrow())
            t = t.append_column(k, col)
        schema = self._schema if not self.columns else Schema(
            [self._schema.field(c) for c in self.columns])
        return _normalize(t, schema, permissive=(fmt == "csv"))

    def _batches(self, t: pa.Table) -> Iterator[pa.Table]:
        for off in range(0, max(t.num_rows, 1), self.max_rows):
            yield t.slice(off, self.max_rows)
            if t.num_rows == 0:
                break

    def execute(self) -> List[Iterator[pa.Table]]:
        indices = scan_file_indices(self.scan)
        if self.reader_type == "MULTITHREADED":
            nthreads = self.conf.get(
                cfg.PARQUET_MULTITHREAD_READ_NUM_THREADS)

            def run_all():
                with cf.ThreadPoolExecutor(max_workers=nthreads) as pool:
                    for fut in [pool.submit(self._read_one, i)
                                for i in indices]:
                        yield from self._batches(fut.result())
            return [run_all()]
        if self.reader_type == "COALESCING":
            def run_all():
                pending: List[pa.Table] = []
                pending_rows = 0
                for i in indices:
                    t = self._read_one(i)
                    pending.append(t)
                    pending_rows += t.num_rows
                    if pending_rows >= self.max_rows:
                        yield from self._batches(
                            pa.concat_tables(pending))
                        pending, pending_rows = [], 0
                if pending:
                    yield from self._batches(pa.concat_tables(pending))
            return [run_all()]

        # PERFILE: one partition per file
        def part(i):
            from spark_rapids_tpu.exec.context import set_input_file
            path = self.scan.paths[i]
            try:
                for b in self._batches(self._read_one(i)):
                    # set right before the yield so the consumer
                    # evaluates input_file_name() against THIS batch's
                    # file even when two scans are drained interleaved
                    set_input_file(path)
                    yield b
            finally:
                set_input_file("")
        return [part(i) for i in indices]

    def simple_string(self) -> str:
        return (f"CpuFileScanExec({self.scan.fmt}, "
                f"files={len(self.scan.paths)}, {self.reader_type})")
