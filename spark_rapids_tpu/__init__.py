"""spark-rapids-tpu: a TPU-native columnar SQL/ETL engine.

From-scratch rebuild of the capability set of NVIDIA's RAPIDS Accelerator
for Apache Spark (spark-rapids v0.3.0) with TPU-first architecture:
plan override/tag/fallback/explain, HBM-resident Arrow-layout columnar
batches, expressions compiled to XLA, sort-based segmented-reduce
aggregation, total-order key-encoded sorts, ICI-collective shuffle, and a
device->host->disk spill framework.  See SURVEY.md at the repo root for the
full blueprint and reference mapping.
"""

import os as _os

import jax as _jax

# SQL engines need exact int64/float64; enable before anything traces.
_jax.config.update("jax_enable_x64", True)

def _enable_compile_cache(cache_dir=None) -> None:
    """Persistent XLA compilation cache for ACCELERATOR backends.

    ``cache_dir`` overrides the location (the fleet's shared
    compile-cache directory); otherwise SPARK_RAPIDS_TPU_COMPILE_CACHE
    or the per-user default applies.

    The engine plans fresh exec trees per query and fresh processes per
    benchmark run; re-loading compiled executables beats recompiling
    (especially with remote/tunneled compilation).  CPU is deliberately
    excluded: under a remote-compilation service, XLA:CPU AOT results
    target the *server's* CPU features and can SIGILL on the local host.
    Opt out with SPARK_RAPIDS_TPU_NO_COMPILE_CACHE=1.

    Called lazily (session init) once the backend platform is known.
    """
    if _os.environ.get("SPARK_RAPIDS_TPU_NO_COMPILE_CACHE"):
        return
    try:
        platform = _jax.default_backend()
        if platform == "cpu" and not _os.environ.get(
                "SPARK_RAPIDS_TPU_CPU_COMPILE_CACHE"):
            # CPU stays opt-in: under a REMOTE compilation service,
            # XLA:CPU AOT results target the server's CPU features and
            # can SIGILL locally.  The test suite opts in explicitly
            # (tests/conftest.py) where JAX_PLATFORMS=cpu guarantees a
            # local compile.
            return
        cache_dir = cache_dir or _os.environ.get(
            "SPARK_RAPIDS_TPU_COMPILE_CACHE",
            _os.path.expanduser("~/.cache/spark_rapids_tpu/xla-"
                                + platform))
        _os.makedirs(cache_dir, exist_ok=True)
        _jax.config.update("jax_compilation_cache_dir", cache_dir)
        _jax.config.update("jax_persistent_cache_min_compile_time_secs",
                           0.0)
        _jax.config.update("jax_persistent_cache_min_entry_size_bytes",
                           0)
        # jax initializes the persistent cache AT MOST ONCE, on the
        # first compile of the process (compilation_cache
        # ._initialize_cache's _cache_initialized latch): any jit call
        # before this session configured the dir pins the cache OFF
        # for the whole process — the dir update above is silently
        # ignored, warm runs re-pay full compiles, and the compile
        # observatory reports 'fresh' where the operator expects
        # 'persistent'.  Un-latch an initialized-but-empty decision so
        # the just-configured dir takes effect (a live cache object is
        # left alone).
        from jax._src import compilation_cache as _jcc
        if (getattr(_jcc, "_cache_initialized", False) and
                getattr(_jcc, "_cache", None) is None) or \
                (getattr(_jcc, "_cache_checked", False) and
                 not getattr(_jcc, "_cache_used", True)):
            _jcc.reset_cache()
    except Exception:  # cache is an optimization, never a hard failure
        pass

from spark_rapids_tpu.api.session import TpuSparkSession  # noqa: E402,F401
from spark_rapids_tpu.api.column import Column, col, lit  # noqa: E402,F401
from spark_rapids_tpu.api import functions  # noqa: E402,F401

__version__ = "0.1.0"
