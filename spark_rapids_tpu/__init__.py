"""spark-rapids-tpu: a TPU-native columnar SQL/ETL engine.

From-scratch rebuild of the capability set of NVIDIA's RAPIDS Accelerator
for Apache Spark (spark-rapids v0.3.0) with TPU-first architecture:
plan override/tag/fallback/explain, HBM-resident Arrow-layout columnar
batches, expressions compiled to XLA, sort-based segmented-reduce
aggregation, total-order key-encoded sorts, ICI-collective shuffle, and a
device->host->disk spill framework.  See SURVEY.md at the repo root for the
full blueprint and reference mapping.
"""

import jax as _jax

# SQL engines need exact int64/float64; enable before anything traces.
_jax.config.update("jax_enable_x64", True)

from spark_rapids_tpu.api.session import TpuSparkSession  # noqa: E402,F401
from spark_rapids_tpu.api.column import Column, col, lit  # noqa: E402,F401
from spark_rapids_tpu.api import functions  # noqa: E402,F401

__version__ = "0.1.0"
