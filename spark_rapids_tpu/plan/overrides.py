"""Plan-override layer: wrap -> tag -> convert, with explain and CPU fallback.

This is the TPU analog of the heart of the reference design (reference:
GpuOverrides.scala:2047-2066 apply; RapidsMeta.scala:66-306 the meta tree;
``willNotWorkOnGpu`` reason recording at RapidsMeta.scala:132,194-230;
``convertIfNeeded`` at RapidsMeta.scala:605-624; per-class ReplacementRule
registry at GpuOverrides.scala:65-277).

Flow, identical to the reference:
  1. the CPU physical plan (our "stock Spark" plan) is wrapped in a meta tree
  2. tagging walks the tree recording ``will_not_work_on_tpu`` reasons:
     per-op kill-switch confs (auto-derived key
     ``spark.rapids.tpu.sql.exec.<SparkName>`` /
     ``...sql.expression.<Name>``, reference: GpuOverrides.scala:131-139),
     unsupported dtypes (reference: isSupportedType GpuOverrides.scala:459),
     unsupported expressions, incompat ops gated behind
     ``incompatibleOps.enabled``
  3. conversion replaces only fully-supported nodes with Tpu execs and
     inserts HostToDevice/DeviceToHost transitions at currency boundaries
     (the GpuTransitionOverrides role, GpuTransitionOverrides.scala:454-481)
  4. ``explain`` renders the per-node decisions
     (``spark.rapids.tpu.sql.explain=NOT_ON_TPU|ALL``)
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Type

from spark_rapids_tpu import config as cfg
from spark_rapids_tpu import dtypes as dt
from spark_rapids_tpu.config import RapidsTpuConf
from spark_rapids_tpu.exec import cpu as cpux
from spark_rapids_tpu.exec import tpu_basic as tpub
from spark_rapids_tpu.exec.base import PhysicalPlan
from spark_rapids_tpu.exec.tpu_aggregate import TpuHashAggregateExec
from spark_rapids_tpu.exec.tpu_sort import TpuSortExec
from spark_rapids_tpu.expr import eval_tpu, ir


# ---------------------------------------------------------------------------
# Expression support checks
# ---------------------------------------------------------------------------

_LITERAL_ARG_EXPRS = {
    # the pattern tokenizes at trace time; a per-row pattern column
    # would need a dynamic NFA — fall back (matches the reference's
    # GpuLike literal-regex restriction, Spark300Shims.scala:183)
    ir.Like: "LIKE pattern must be a literal",
}


_TPU_AGG_FNS = (ir.Count, ir.Sum, ir.Min, ir.Max, ir.Average, ir.First,
                ir.Last)


def _check_expr_node(e: ir.Expression, conf: RapidsTpuConf
                     ) -> Optional[str]:
    """Return a fallback reason if this single node can't run on TPU."""
    if isinstance(e, ir.AggregateExpression):
        # aggregates are evaluated by the aggregate exec's update/merge
        # specs, not the row-wise evaluator
        if not isinstance(e, _TPU_AGG_FNS):
            return (f"aggregate {type(e).__name__} is not supported on TPU")
    elif not eval_tpu.supported_on_tpu(type(e)):
        return f"expression {type(e).__name__} is not supported on TPU"
    key = f"spark.rapids.tpu.sql.expression.{type(e).__name__}"
    if not conf.is_operator_enabled(key, incompat=False,
                                   disabled_by_default=False):
        return f"expression {type(e).__name__} disabled by {key}"
    if type(e) in _LITERAL_ARG_EXPRS:
        if not isinstance(e.children[1], ir.Literal):
            return _LITERAL_ARG_EXPRS[type(e)]
    if isinstance(e, ir.RegExpReplace):
        pat = e.children[1]
        rep = e.children[2]
        if not isinstance(pat, ir.Literal) or pat.value is None or \
                not isinstance(rep, ir.Literal) or rep.value is None:
            return "regexp_replace pattern/replacement must be literals"
        from spark_rapids_tpu.expr.eval_tpu import _REGEX_META
        if "$" in rep.value or "\\" in rep.value:
            return ("regexp replacement with $group/backslash "
                    "references is not supported on TPU")
        if not pat.value or any(ch in _REGEX_META for ch in pat.value):
            # real regex: device NFA subset (expr/device_regex.py);
            # alternation replace diverges from Java's leftmost-branch
            # pick and empty-matchable patterns insert at every gap
            from spark_rapids_tpu.expr import device_regex as dr
            try:
                cr = dr.compile_pattern(pat.value or "")
            except dr.Unsupported as ex:
                return (f"regexp pattern '{pat.value}' outside the "
                        f"device regex subset: {ex}")
            if not cr.replace_safe:
                return ("regexp_replace pattern where Java greedy "
                        "semantics may differ from longest-match "
                        "(alternation, empty-matchable, or multiple "
                        "variable-length elements) — not on TPU")
    if isinstance(e, ir.RLike):
        pat = e.children[1]
        if not isinstance(pat, ir.Literal):
            return "rlike pattern must be a literal"
        if pat.value is not None:
            from spark_rapids_tpu.expr import device_regex as dr
            try:
                dr.compile_pattern(pat.value)
            except dr.Unsupported as ex:
                return (f"rlike pattern '{pat.value}' outside the "
                        f"device regex subset: {ex}")
    if isinstance(e, ir.StringLocate):
        if not isinstance(e.children[0], ir.Literal) or \
           not isinstance(e.children[2], ir.Literal):
            return "locate substr/start must be literals"
    if isinstance(e, (ir.LPad, ir.RPad)):
        if not isinstance(e.children[1], ir.Literal) or \
           not isinstance(e.children[2], ir.Literal):
            return "pad length/fill must be literals"
    if isinstance(e, ir.Cast):
        src = e.children[0].dtype
        if src is not None and src != e.to and src != dt.NULL:
            if src.is_string and e.to.is_floating and \
                    not conf.get(cfg.CAST_STRING_TO_FLOAT) and \
                    not conf.get(cfg.INCOMPATIBLE_OPS):
                return ("cast string->float can differ from Spark in "
                        "the last ulp; enable "
                        f"{cfg.CAST_STRING_TO_FLOAT.key}")
            if src.is_string and e.to.id == dt.TypeId.TIMESTAMP_US and \
                    not conf.get(cfg.ALLOW_INCOMPAT_UTC_ONLY):
                return ("cast string->timestamp is UTC-only on TPU; "
                        f"enable {cfg.ALLOW_INCOMPAT_UTC_ONLY.key}")
            if src.is_string and not (
                    e.to.is_integral or e.to.is_floating or
                    e.to.is_bool or
                    e.to.id in (dt.TypeId.DATE32,
                                dt.TypeId.TIMESTAMP_US)):
                return f"cast string->{e.to.name} not supported on TPU yet"
            if e.to.is_string and src.is_floating and \
                    not conf.get(cfg.CAST_FLOAT_TO_STRING):
                return ("cast float->string disabled; enable "
                        f"{cfg.CAST_FLOAT_TO_STRING.key}")
            if e.to.is_string and not (
                    src.is_bool or src.is_integral or src.is_floating or
                    src.id in (dt.TypeId.DATE32,
                               dt.TypeId.TIMESTAMP_US)):
                return f"cast {src.name}->string not supported on TPU yet"
    if isinstance(e, (ir.Sum, ir.Average)) and e.child is not None and \
            e.child.dtype is not None and e.child.dtype.is_floating:
        if not conf.get(cfg.VARIABLE_FLOAT_AGG) and \
           not conf.get(cfg.INCOMPATIBLE_OPS):
            return ("float/double aggregation order differs from Spark; "
                    "enable spark.rapids.tpu.sql.variableFloatAgg.enabled")
    return None


def check_exprs(exprs: List[ir.Expression], conf: RapidsTpuConf
                ) -> List[str]:
    reasons: List[str] = []

    def walk(e: ir.Expression):
        r = _check_expr_node(e, conf)
        if r:
            reasons.append(r)
        for c in e.children:
            walk(c)
    for e in exprs:
        walk(e)
    return reasons


# ---------------------------------------------------------------------------
# Exec replacement rules
# ---------------------------------------------------------------------------

@dataclass
class ExecRule:
    spark_name: str                      # key used for kill-switch + explain
    description: str
    exprs_of: Callable[[PhysicalPlan], List[ir.Expression]]
    convert: Callable[[PhysicalPlan, List[PhysicalPlan], RapidsTpuConf],
                      PhysicalPlan]
    extra_tag: Optional[Callable[[PhysicalPlan, RapidsTpuConf],
                                 List[str]]] = None
    incompat: bool = False
    disabled_by_default: bool = False


def _no_exprs(n: PhysicalPlan) -> List[ir.Expression]:
    return []


_EXEC_RULES: Dict[Type[PhysicalPlan], ExecRule] = {}


def register_exec_rule(cpu_cls: Type[PhysicalPlan], rule: ExecRule) -> None:
    _EXEC_RULES[cpu_cls] = rule


def _sort_unsupported_types(n: cpux.CpuSortExec, conf) -> List[str]:
    out = []
    for o in n.orders:
        if o.expr.dtype is not None and o.expr.dtype.is_floating and \
                not conf.get(cfg.ENABLE_FLOAT_SORT):
            out.append("float sort disabled")
    out.extend(_nested_key_reasons((o.expr for o in n.orders), "sort"))
    return out


def _nested_key_reasons(exprs, role: str) -> List[str]:
    out = []
    for e in exprs:
        if e is not None and e.dtype is not None and e.dtype.is_nested:
            out.append(f"nested type {e.dtype.name} not supported as a "
                       f"{role} key on TPU")
    return out


register_exec_rule(cpux.CpuScanExec, ExecRule(
    "InMemoryScan", "in-memory table scan feeding the device",
    _no_exprs,
    # scan stays on CPU; the host->device transition makes it device-feeding
    convert=lambda n, ch, conf: n))

register_exec_rule(cpux.CpuProjectExec, ExecRule(
    "ProjectExec", "TPU projection (bound-expression columnar eval)",
    lambda n: list(n.exprs),
    convert=lambda n, ch, conf: tpub.TpuProjectExec(ch[0], n.exprs, n.schema)))

register_exec_rule(cpux.CpuFilterExec, ExecRule(
    "FilterExec", "TPU filter (mask + stream compaction)",
    lambda n: [n.condition],
    convert=lambda n, ch, conf: tpub.TpuFilterExec(ch[0], n.condition)))

register_exec_rule(cpux.CpuRangeExec, ExecRule(
    "RangeExec", "TPU range generation",
    _no_exprs,
    convert=lambda n, ch, conf: tpub.TpuRangeExec(
        n.start, n.end, n.step, n.num_partitions)))

register_exec_rule(cpux.CpuUnionExec, ExecRule(
    "UnionExec", "TPU union (partition concatenation)",
    _no_exprs,
    convert=lambda n, ch, conf: tpub.TpuUnionExec(ch)))

register_exec_rule(cpux.CpuLimitExec, ExecRule(
    "GlobalLimitExec", "TPU global limit",
    _no_exprs,
    convert=lambda n, ch, conf: tpub.TpuGlobalLimitExec(ch[0], n.n)))

register_exec_rule(cpux.CpuSortExec, ExecRule(
    "SortExec", "TPU total sort (total-order key encode + lexsort)",
    lambda n: [o.expr for o in n.orders],
    convert=lambda n, ch, conf: TpuSortExec(ch[0], n.orders,
                                            n.partitionwise),
    extra_tag=_sort_unsupported_types))

def _convert_hash_agg(n, ch, conf):
    out = TpuHashAggregateExec(ch[0], n.groupings, n.aggregates,
                               n.schema, per_partition=n.per_partition)
    # incremental-maintenance stamp threaded from the logical plan
    # (exec/incremental.py via planner.plan_cpu)
    inc = getattr(n, "_incremental", None)
    if inc is not None:
        out._incremental = inc
    return out


register_exec_rule(cpux.CpuHashAggregateExec, ExecRule(
    "HashAggregateExec",
    "TPU hash aggregate (sort-based segmented reduction)",
    lambda n: list(n.groupings) + list(n.aggregates),
    convert=_convert_hash_agg,
    extra_tag=lambda n, conf: _nested_key_reasons(n.groupings, "grouping")))

register_exec_rule(cpux.CpuExpandExec, ExecRule(
    "ExpandExec", "TPU expand (N projections per row)",
    lambda n: [e for p in n.projections for e in p],
    convert=lambda n, ch, conf: tpub.TpuExpandExec(ch[0], n.projections, n.schema)))


def _tag_window(n, conf) -> List[str]:
    out = []
    for we in n.window_exprs:
        out.extend(_nested_key_reasons(we.partition_exprs,
                                       "window partition"))
        out.extend(_nested_key_reasons(we.order_exprs, "window order"))
        out.extend(_nested_key_reasons(we.function.children,
                                       "window input"))
        fn = we.function
        fr = we.frame
        finite_range = fr.kind == "range" and not (
            fr.start is None and fr.end in (0, None))
        if finite_range:
            # device range frames binary-search the single numeric/
            # temporal order key (cudf aggregateWindowsOverTimeRanges
            # analog)
            if len(we.order_exprs) != 1:
                out.append("finite RANGE frames require exactly one "
                           "ORDER BY expression")
            else:
                od = we.order_exprs[0].dtype
                if od is not None and not (od.is_numeric or od.is_temporal):
                    out.append(f"finite RANGE frames need a numeric or "
                               f"temporal order key, got {od.name}")
        if isinstance(fn, ir.AggregateExpression):
            if not isinstance(fn, (ir.Count, ir.Sum, ir.Average, ir.Min,
                                   ir.Max)):
                out.append(f"window aggregate {type(fn).__name__} not "
                           f"supported on TPU")
            if fn.child is not None and fn.child.dtype is not None and \
                    fn.child.dtype.is_string:
                out.append("string window aggregates not supported on TPU")
        elif not isinstance(fn, (ir.RowNumber, ir.Rank, ir.DenseRank,
                                 ir.Lead, ir.Lag)):
            out.append(f"window function {type(fn).__name__} not "
                       f"supported on TPU")
    return out


def _register_window_rule():
    from spark_rapids_tpu.exec.cpu_window import CpuWindowExec
    from spark_rapids_tpu.exec.tpu_window import TpuWindowExec
    def _win_exprs(n) -> List[ir.Expression]:
        # check partition/order exprs and the function's inputs; the
        # window function node itself is vetted by _tag_window
        out: List[ir.Expression] = []
        for we in n.window_exprs:
            out.extend(we.partition_exprs)
            out.extend(we.order_exprs)
            out.extend(we.function.children)
        return out

    register_exec_rule(CpuWindowExec, ExecRule(
        "WindowExec",
        "TPU window functions (lexsort + segmented scans/prefix sums)",
        _win_exprs,
        convert=lambda n, ch, conf: TpuWindowExec(ch[0], n.window_exprs,
                                            n.out_names, n.schema,
                                            n.partitionwise),
        extra_tag=_tag_window))


_register_window_rule()


def _convert_join(n: cpux.CpuJoinExec, ch, conf):
    from spark_rapids_tpu.exec.join_partition import resolve_oocore
    from spark_rapids_tpu.exec.tpu_join import (
        TpuBroadcastNestedLoopJoinExec, TpuShuffledHashJoinExec)
    if n.how == "cross":
        return TpuBroadcastNestedLoopJoinExec(ch[0], ch[1], n.condition,
                                              n.schema)
    j = TpuShuffledHashJoinExec(ch[0], ch[1], n.left_keys, n.right_keys,
                                n.how, n.condition, n.schema)
    # out-of-core budget resolved at conversion time (conf is a session
    # object; execute() must not depend on it) — None = today's
    # unconditional gather
    j._oocore = resolve_oocore(conf)
    return j


def _tag_join(n: cpux.CpuJoinExec, conf) -> List[str]:
    out = []
    if n.how != "cross" and not n.left_keys:
        out.append("non-equi join without keys requires nested loop "
                   "(only cross supported on TPU)")
    for kd in (n.key_dtypes or []):
        if kd is not None and kd.is_nested:
            out.append(f"nested type {kd.name} not supported as a join "
                       f"key on TPU")
    return out


def _join_exprs(n: cpux.CpuJoinExec) -> List[ir.Expression]:
    return [n.condition] if n.condition is not None else []


register_exec_rule(cpux.CpuJoinExec, ExecRule(
    "ShuffledHashJoinExec",
    "TPU equi-join (sort-merge over total-order keys, two-pass sizing)",
    _join_exprs,
    convert=_convert_join,
    extra_tag=_tag_join))


def _register_join_strategy_rules():
    from spark_rapids_tpu.exec.tpu_join import (
        TpuBroadcastHashJoinExec, TpuBroadcastNestedLoopJoinExec,
        TpuCartesianProductExec, TpuShuffledHashJoinExec)

    def _convert_shuffled_join(n, ch, conf):
        # AQE analog: both exchange children share one coordinated spec
        # list (coalesce + skew split) so co-partitioning survives
        from spark_rapids_tpu.exec.adaptive import wrap_join_children
        from spark_rapids_tpu.exec.join_partition import resolve_oocore
        left, right = wrap_join_children(ch[0], ch[1], n.how, conf)
        j = TpuShuffledHashJoinExec(
            left, right, n.left_keys, n.right_keys, n.how, n.condition,
            n.schema)
        j._oocore = resolve_oocore(conf)
        return j

    register_exec_rule(cpux.CpuShuffledHashJoinExec, ExecRule(
        "ShuffledHashJoinExec",
        "TPU partitioned equi-join over co-partitioned exchanges",
        _join_exprs,
        convert=_convert_shuffled_join,
        extra_tag=_tag_join))

    register_exec_rule(cpux.CpuBroadcastHashJoinExec, ExecRule(
        "BroadcastHashJoinExec",
        "TPU broadcast equi-join (build side gathered once, stream side "
        "stays partitioned)",
        _join_exprs,
        convert=lambda n, ch, conf: TpuBroadcastHashJoinExec(
            ch[0], ch[1], n.left_keys, n.right_keys, n.how, n.condition,
            n.schema, build_side=n.build_side,
            transport=conf.get(cfg.SHUFFLE_TRANSPORT)),
        extra_tag=_tag_join))

    register_exec_rule(cpux.CpuBroadcastNestedLoopJoinExec, ExecRule(
        "BroadcastNestedLoopJoinExec",
        "TPU broadcast nested-loop join (cross product + filter)",
        _join_exprs,
        convert=lambda n, ch, conf: TpuBroadcastNestedLoopJoinExec(
            ch[0], ch[1], n.condition, n.schema,
            build_side=n.build_side)))

    register_exec_rule(cpux.CpuCartesianProductExec, ExecRule(
        "CartesianProductExec",
        "TPU partition-pairwise cartesian product",
        _join_exprs,
        convert=lambda n, ch, conf: TpuCartesianProductExec(
            ch[0], ch[1], n.condition, n.schema)))


_register_join_strategy_rules()


def _register_generate_rule():
    from spark_rapids_tpu.exec.generate import (CpuGenerateExec,
                                                TpuGenerateExec)

    def _tag_generate(n, conf) -> List[str]:
        out = []
        d = n.generator.children[0].dtype
        if d is None or not d.is_list or not dt.device_supported(d):
            out.append(f"generator input type "
                       f"{d.name if d else '?'} not supported on TPU")
        return out

    register_exec_rule(CpuGenerateExec, ExecRule(
        "GenerateExec",
        "TPU explode/posexplode (two-pass count-then-emit element gather)",
        lambda n: list(n.generator.children),
        convert=lambda n, ch, conf: TpuGenerateExec(ch[0], n.generator,
                                                    n.schema),
        extra_tag=_tag_generate))


_register_generate_rule()


def _tag_exchange(n, conf) -> List[str]:
    from spark_rapids_tpu.shuffle import exchange as ex
    out = []
    if isinstance(n.partitioning, ex.RangePartitioning):
        for o in n.partitioning.orders:
            if o.expr.dtype is not None and o.expr.dtype.is_floating and \
                    not conf.get(cfg.ENABLE_FLOAT_SORT):
                out.append("float range partitioning disabled")
    out.extend(_nested_key_reasons(n.partitioning.exprs(), "partitioning"))
    return out


def _register_exchange_rule():
    from spark_rapids_tpu.shuffle import exchange as ex

    register_exec_rule(ex.CpuCoalescePartitionsExec, ExecRule(
        "CoalesceExec",
        "TPU partition coalesce (iterator regrouping, no data movement)",
        _no_exprs,
        convert=lambda n, ch, conf: ex.TpuCoalescePartitionsExec(
            ch[0], n.num_partitions)))

    register_exec_rule(ex.CpuShuffleExchangeExec, ExecRule(
        "ShuffleExchangeExec",
        "TPU shuffle exchange (on-device partition slicing; local Arrow-IPC "
        "or device-resident data plane)",
        lambda n: n.partitioning.exprs(),
        convert=_make_tpu_exchange,
        extra_tag=_tag_exchange))


def _make_tpu_exchange(n, ch, conf):
    # user repartition exchanges keep their exact partition count
    # (Spark's REPARTITION_BY_NUM exemption from AQE); the adaptive
    # reader only wraps planner-inserted join exchanges — see
    # _convert_shuffled_join
    from spark_rapids_tpu.shuffle.exchange import TpuShuffleExchangeExec
    return TpuShuffleExchangeExec(ch[0], n.partitioning, conf)


_register_exchange_rule()


def _register_file_scan_rule():
    from spark_rapids_tpu.io.readers import CpuFileScanExec
    from spark_rapids_tpu.io.device_scan import (TpuOrcScanExec,
                                                 TpuParquetScanExec)

    def _tag_scan(n, conf) -> List[str]:
        out = []
        if n.scan.fmt == "parquet":
            if not conf.get(cfg.PARQUET_DEVICE_DECODE):
                out.append("parquet device decode disabled by "
                           f"{cfg.PARQUET_DEVICE_DECODE.key}")
        elif n.scan.fmt == "orc":
            if not conf.get(cfg.ORC_DEVICE_DECODE):
                out.append("orc device decode disabled by "
                           f"{cfg.ORC_DEVICE_DECODE.key}")
        elif n.scan.fmt == "csv":
            if not conf.get(cfg.CSV_DEVICE_DECODE):
                out.append("csv device decode disabled by "
                           f"{cfg.CSV_DEVICE_DECODE.key}")
            elif n.scan.options.get("part_fields"):
                out.append("csv device decode does not yet append "
                           "Hive partition columns")
        else:
            out.append(f"{n.scan.fmt} scans decode on host "
                       "(device decode is parquet/orc/csv-only)")
        return out

    def _convert_scan(n, ch, conf):
        if n.scan.fmt == "orc":
            return TpuOrcScanExec(n.scan, conf)
        if n.scan.fmt == "csv":
            from spark_rapids_tpu.io.device_scan import TpuCsvScanExec
            return TpuCsvScanExec(n.scan, conf)
        return TpuParquetScanExec(n.scan, conf)

    register_exec_rule(CpuFileScanExec, ExecRule(
        "FileSourceScanExec",
        "TPU parquet/ORC scan: packed pages/streams upload, "
        "RLE/dictionary/def-level decode in HBM (Table.readParquet / "
        "GpuOrcScan analog)",
        _no_exprs,
        convert=_convert_scan,
        extra_tag=_tag_scan))


_register_file_scan_rule()


def _register_cache_scan_rule():
    from spark_rapids_tpu.exec.cache import (CpuInMemoryTableScanExec,
                                             TpuInMemoryTableScanExec)

    def _tag_cache(n, conf) -> List[str]:
        if not conf.get(cfg.CACHE_DEVICE_DECODE):
            return ["cached-batch device decode disabled by "
                    f"{cfg.CACHE_DEVICE_DECODE.key}"]
        return []

    register_exec_rule(CpuInMemoryTableScanExec, ExecRule(
        "InMemoryTableScanExec",
        "TPU cached-batch scan: parquet blobs decode in HBM "
        "(GpuInMemoryTableScanExec / ParquetCachedBatchSerializer analog)",
        _no_exprs,
        convert=lambda n, ch, conf: TpuInMemoryTableScanExec(
            n.relation, conf),
        extra_tag=_tag_cache))


_register_cache_scan_rule()


# ---------------------------------------------------------------------------
# Meta tree
# ---------------------------------------------------------------------------

@dataclass
class ExecMeta:
    node: PhysicalPlan
    rule: Optional[ExecRule]
    children: List["ExecMeta"] = field(default_factory=list)
    reasons: List[str] = field(default_factory=list)

    def will_not_work_on_tpu(self, reason: str) -> None:
        if reason not in self.reasons:
            self.reasons.append(reason)

    @property
    def can_run_on_tpu(self) -> bool:
        return self.rule is not None and not self.reasons

    def explain_lines(self, all_: bool, depth: int = 0) -> List[str]:
        name = self.rule.spark_name if self.rule else \
            type(self.node).__name__
        pad = "  " * depth
        lines = []
        if self.can_run_on_tpu:
            if all_:
                lines.append(f"{pad}*Exec <{name}> will run on TPU")
        else:
            why = "; ".join(self.reasons) or "no TPU replacement rule"
            lines.append(f"{pad}!Exec <{name}> cannot run on TPU because "
                         f"{why}")
        for c in self.children:
            lines.extend(c.explain_lines(all_, depth + 1))
        return lines


def _supported_schema_reasons(node: PhysicalPlan) -> List[str]:
    out = []
    for f in node.schema.fields:
        if not dt.device_supported(f.dtype):
            out.append(f"unsupported type {f.dtype} for column {f.name}")
    return out


def wrap_and_tag(node: PhysicalPlan, conf: RapidsTpuConf) -> ExecMeta:
    rule = _EXEC_RULES.get(type(node))
    meta = ExecMeta(node, rule)
    meta.children = [wrap_and_tag(c, conf) for c in node.children]
    if rule is None:
        meta.will_not_work_on_tpu(
            f"no TPU replacement for {type(node).__name__}")
        return meta
    if not conf.sql_enabled:
        meta.will_not_work_on_tpu("TPU SQL acceleration is disabled")
        return meta
    key = f"spark.rapids.tpu.sql.exec.{rule.spark_name}"
    if not conf.is_operator_enabled(key, rule.incompat,
                                   rule.disabled_by_default):
        meta.will_not_work_on_tpu(f"disabled by {key}")
    for r in _supported_schema_reasons(node):
        meta.will_not_work_on_tpu(r)
    for r in check_exprs(rule.exprs_of(node), conf):
        meta.will_not_work_on_tpu(r)
    if rule.extra_tag is not None:
        for r in rule.extra_tag(node, conf):
            meta.will_not_work_on_tpu(r)
    return meta


# ---------------------------------------------------------------------------
# Conversion with transition insertion
# ---------------------------------------------------------------------------

def _convert(meta: ExecMeta, conf: RapidsTpuConf) -> PhysicalPlan:
    """Bottom-up conversion; returns a plan whose output currency is device
    (TpuExec) or host (PhysicalPlan)."""
    children = [_convert(c, conf) for c in meta.children]

    # a CPU scan feeding a TPU subtree is handled by the parent transition;
    # scans themselves never convert (device decode arrives with the io layer)
    if meta.can_run_on_tpu and not isinstance(meta.node, cpux.CpuScanExec):
        # device inputs required
        min_bucket = conf.get(cfg.MIN_BUCKET_ROWS)
        dev_children = [
            c if c.is_tpu else tpub.HostToDeviceExec(c, min_bucket)
            for c in children]
        return meta.rule.convert(meta.node, dev_children, conf)

    # CPU node: host inputs required
    host_children = [
        c if not c.is_tpu else tpub.DeviceToHostExec(c)
        for c in children]
    node = meta.node
    if host_children and tuple(host_children) != tuple(node.children):
        node.children = tuple(host_children)
    return node


def _plan_uses_input_file(plan: PhysicalPlan) -> bool:
    """Does any expression anywhere in the plan read input_file_name()?"""
    from spark_rapids_tpu.expr import ir as _ir
    found: List[bool] = []

    def walk_expr(e):
        if isinstance(e, _ir.InputFileName):
            found.append(True)
        for c in getattr(e, "children", ()):
            walk_expr(c)

    def visit(n):
        for v in vars(n).values():
            if isinstance(v, _ir.Expression):
                walk_expr(v)
            elif isinstance(v, (list, tuple)):
                for x in v:
                    if isinstance(x, _ir.Expression):
                        walk_expr(x)
                    elif hasattr(x, "expr") and \
                            isinstance(getattr(x, "expr"), _ir.Expression):
                        walk_expr(x.expr)  # SortOrder-like wrappers

    plan.foreach(visit)
    return bool(found)


class TpuOverrides:
    """The ColumnarRule analog: apply() rewrites the CPU physical plan."""

    @staticmethod
    def apply(cpu_plan: PhysicalPlan, conf: RapidsTpuConf
              ) -> "OverrideResult":
        meta = wrap_and_tag(cpu_plan, conf)
        plan = _convert(meta, conf)
        if conf.get(cfg.FUSION_ENABLED):
            # whole-stage fusion: collapse Project/Filter chains into
            # single dispatches and inline aggregate prologues
            # (plan/fusion.py) before the lone-filter post-pass below
            from spark_rapids_tpu.plan.fusion import fuse_stages
            plan = fuse_stages(plan, conf)
        if conf.get(cfg.AGG_FUSED_FILTER):
            _fuse_filters_into_aggregates(plan)
        if plan.is_tpu:
            plan = tpub.DeviceToHostExec(plan)
        # stamp the session's donation setting on every node: execs read
        # their OWN plan's flag (fused_stage.donate_ok), so concurrent
        # sessions with different sql.fusion.donateInputs stay
        # independent and fragments shipped to executor processes carry
        # the driver's conf through pickle
        donate = bool(conf.get(cfg.FUSION_DONATE))
        # kernel backend rides the same per-plan stamp: the aggregate /
        # scan execs read their OWN plan's backend (kernels.resolve),
        # so concurrent sessions with different kernel.backend settings
        # stay independent (the donation-stamp lesson, PR 4 review r3)
        kbackend = str(conf.get(cfg.KERNEL_BACKEND) or "pallas")

        def _stamp(n):
            n._donate_enabled = donate
            n._kernel_backend = kbackend
        plan.foreach(_stamp)
        if kbackend == "pallas":
            # kernel 2 (fused dictionary-decode+filter): push eligible
            # filter conditions into a directly-below fused parquet
            # scan so filtered-out rows never materialize decoded
            # dictionary values (kernels/filter_decode.py)
            _push_scan_filters(plan)
        if _plan_uses_input_file(cpu_plan):
            # fused multi-file batches can't answer input_file_name();
            # reference: GpuParquetScan falls back from the coalescing
            # reader to PERFILE under the same condition
            from spark_rapids_tpu.io.device_scan import TpuParquetScanExec

            def _disable(n):
                if isinstance(n, TpuParquetScanExec):
                    n.allow_fused = False
            plan.foreach(_disable)
        explain = conf.explain
        if explain in ("NOT_ON_TPU", "ALL"):
            lines = meta.explain_lines(all_=(explain == "ALL"))
            if lines:
                print("\n".join(lines))
        return OverrideResult(plan, meta)


def _fuse_filters_into_aggregates(plan: PhysicalPlan) -> None:
    """Post-conversion pass: a TpuFilterExec DIRECTLY under a
    TpuHashAggregateExec becomes a fused mask inside the aggregate's
    update kernel (see TpuHashAggregateExec.fused_condition).  The
    reference keeps the nodes separate because cudf compacts cheaply;
    on TPU the compact's per-column full-capacity gathers cost more
    than the whole masked aggregation."""
    from spark_rapids_tpu.exec.tpu_aggregate import TpuHashAggregateExec
    from spark_rapids_tpu.exec.tpu_basic import TpuFilterExec
    # the aggregate's update kernel runs WITHOUT the task context a
    # standalone filter threads through, so a partition-dependent or
    # position-dependent condition must stay outside (same barrier set
    # the whole-stage fusion pass enforces for its R2 inlining)
    from spark_rapids_tpu.plan.fusion import _AGG_BARRIERS, _has_barrier

    def rec(n: PhysicalPlan) -> None:
        if isinstance(n, TpuHashAggregateExec) and \
                n.fused_condition is None and \
                isinstance(n.children[0], TpuFilterExec) and \
                not _has_barrier([n.children[0].condition], _AGG_BARRIERS):
            f = n.children[0]
            n.fused_condition = f.condition
            n.children = (f.children[0],)
        for c in n.children:
            rec(c)

    rec(plan)


def _push_scan_filters(plan: PhysicalPlan) -> None:
    """Kernel-2 planner hook (``kernel.backend=pallas`` only): when a
    filtering consumer sits DIRECTLY on a fused parquet scan, stamp the
    combined condition onto the scan (``_pushed_filter``) so the fused
    decode can defer dictionary gathers until after the mask is known —
    rows the consumer will drop never materialize decoded values
    (kernels/filter_decode.py).

    Soundness: the stamp only ZEROES deferred dictionary values on
    mask-false rows; the consumer re-evaluates the same deterministic
    row-wise condition over the same (never-deferred) operand columns
    and drops/masks exactly those rows, so downstream never observes a
    zeroed value.  Gates:

      * consumer is a ``TpuFusedStageExec`` with a condition, a
        ``TpuHashAggregateExec`` with a fused_condition, or a plain
        ``TpuFilterExec`` — each one's condition is already bound over
        the scan's output schema;
      * the condition carries no barrier expression (the R2 set:
        position/partition-dependent or non-deterministic nodes whose
        re-evaluation inside the scan kernel could diverge);
      * the scan has exactly ONE consumer (parent-edge refcounts — a
        shared scan feeding a second consumer must keep real values);
      * per-kernel fallback stays downstream: the scan ignores the
        stamp whenever the Pallas filter-decode can't cover the batch
        (prepare-time checks in io/parquet_fused.py), which is always
        correct — the stamp is an optimization hint, never a contract.
    """
    from spark_rapids_tpu.exec.fused_stage import TpuFusedStageExec
    from spark_rapids_tpu.exec.tpu_aggregate import TpuHashAggregateExec
    from spark_rapids_tpu.exec.tpu_basic import TpuFilterExec
    from spark_rapids_tpu.io.device_scan import (TpuOrcScanExec,
                                                 TpuParquetScanExec)
    from spark_rapids_tpu.plan.fusion import (_AGG_BARRIERS, _has_barrier,
                                              _refcounts)

    refs = _refcounts(plan)

    def cond_of(n):
        if isinstance(n, TpuFusedStageExec):
            return n.condition
        if isinstance(n, TpuHashAggregateExec):
            return n.fused_condition
        if isinstance(n, TpuFilterExec):
            return n.condition
        return None

    seen = set()

    def rec(n: PhysicalPlan) -> None:
        if id(n) in seen:
            return
        seen.add(id(n))
        cond = cond_of(n)
        if cond is not None and n.children:
            scan = n.children[0]
            if (isinstance(scan, TpuParquetScanExec) and
                    not isinstance(scan, TpuOrcScanExec) and
                    scan.allow_fused and
                    refs.get(id(scan), 1) <= 1 and
                    getattr(scan, "_pushed_filter", None) is None and
                    not _has_barrier([cond], _AGG_BARRIERS)):
                scan._pushed_filter = cond
        for c in n.children:
            rec(c)

    rec(plan)


@dataclass
class OverrideResult:
    plan: PhysicalPlan
    meta: ExecMeta

    def explain_string(self, all_: bool = True) -> str:
        return "\n".join(self.meta.explain_lines(all_))


def assert_is_on_tpu(plan: PhysicalPlan, allowed_non_tpu: List[str]) -> None:
    """Test-mode assertion (reference: GpuTransitionOverrides.scala:389-446
    assertIsOnTheGpu gated by spark.rapids.sql.test.enabled)."""
    always_ok = {"CpuScanExec", "CpuFileScanExec", "HostToDeviceExec",
                 "DeviceToHostExec"}
    bad: List[str] = []

    def visit(n: PhysicalPlan):
        name = type(n).__name__
        if not n.is_tpu and name not in always_ok and \
                name not in allowed_non_tpu:
            bad.append(name)
    plan.foreach(visit)
    if bad:
        raise AssertionError(
            f"plan contains CPU nodes not allowed in test mode: {bad}")
