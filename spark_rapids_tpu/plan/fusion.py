"""Whole-stage fusion pass: collapse Project/Filter chains into single
XLA dispatches.

Runs inside ``TpuOverrides.apply`` (the physical-plan rewrite point the
planner pipeline funnels through — session ``_plan_physical`` ->
``prune_columns`` -> ``plan_cpu`` -> overrides -> THIS), after
conversion produced Tpu execs and before the lone-filter-under-
aggregate post-pass.  Two rewrites, both gated by
``spark.rapids.tpu.sql.fusion.enabled``:

**R1 — chain collapse.**  A maximal chain of single-consumer
``TpuProjectExec`` / ``TpuFilterExec`` nodes becomes one
``TpuFusedStageExec`` (exec/fused_stage.py): every filter condition is
rewritten over the chain INPUT schema by substituting the projections
below it and AND-combined into one mask (one compaction at most); the
composed output projection evaluates after the compaction, so the
chain's intermediate columns are never materialized.  A chain whose
composition degenerates to pure column selection becomes a
zero-dispatch passthrough stage.

**R2 — aggregate prologue inlining.**  Projections (and filters)
directly under a ``TpuHashAggregateExec`` are the aggregate's
expression-evaluation prologue: their expressions substitute straight
into the grouping keys / aggregate arguments (filters AND into
``fused_condition``, the update kernel's row mask), eliminating those
dispatches entirely — the fused q6 shape is ONE update kernel per
batch for scan->project->filter->aggregate.

Fusion barriers (a chain stops at, and never crosses):
  * position-dependent expressions — ``MonotonicallyIncreasingID``,
    ``Rand`` key on row position, which a fused compaction reorders;
  * non-deterministic / CPU-only payloads — ``PythonUDF``,
    ``InputFileName`` (scan-scoped context);
  * multi-consumer subtrees — a node referenced by two parents must
    keep its identity (each parent drains its iterators);
  * ``SparkPartitionID`` additionally bars R2 only: the aggregate's
    update kernel runs without the task context the fused stage
    threads through (the stage itself fuses it fine);
  * the composed DAG exceeding ``sql.fusion.maxExprs`` nodes
    (substitution duplicates shared subtrees; compile breadth is the
    TPC-DS bill, PERF.md).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from spark_rapids_tpu import config as cfg
from spark_rapids_tpu.config import RapidsTpuConf
from spark_rapids_tpu.exec.base import PhysicalPlan
from spark_rapids_tpu.exec.fused_stage import TpuFusedStageExec
from spark_rapids_tpu.exec.tpu_basic import TpuFilterExec, TpuProjectExec
from spark_rapids_tpu.expr import ir

# position-dependent or otherwise unfusable expression nodes
_STAGE_BARRIERS = (ir.MonotonicallyIncreasingID, ir.Rand, ir.PythonUDF,
                   ir.InputFileName, ir.AggregateExpression,
                   ir.WindowExpression)
# the aggregate update kernel runs without a task context
_AGG_BARRIERS = _STAGE_BARRIERS + (ir.SparkPartitionID,)


def _has_barrier(exprs, barriers) -> bool:
    return any(
        ir.collect(e, lambda n: isinstance(n, barriers)) for e in exprs
        if e is not None)


def _strip_alias(e: ir.Expression) -> ir.Expression:
    while isinstance(e, ir.Alias):
        e = e.children[0]
    return e


def _subst(e: ir.Expression,
           mapping: List[ir.Expression]) -> ir.Expression:
    """Rewrite ``e`` (over the mapping's output schema) into an
    expression over the mapping's INPUT schema.  Shared subtrees stay
    shared — expressions are read-only at eval time."""
    def repl(n):
        if isinstance(n, ir.BoundReference):
            return mapping[n.ordinal]
        return None
    return ir.transform(e, repl)


def _n_nodes(e: Optional[ir.Expression]) -> int:
    if e is None:
        return 0
    return 1 + sum(_n_nodes(c) for c in e.children)


def _mk_and(a: ir.Expression, b: ir.Expression) -> ir.Expression:
    out = ir.And(a, b)
    out.resolve()
    return out


def _identity_mapping(schema) -> List[ir.Expression]:
    return [ir.BoundReference(i, f.dtype, f.nullable, name_=f.name)
            for i, f in enumerate(schema.fields)]


def _refcounts(plan: PhysicalPlan) -> Dict[int, int]:
    """Parent-edge counts by node identity; >1 marks a multi-consumer
    subtree no chain may consume.  Recurse into a node only on first
    visit: re-walking a shared subtree once per parent would count
    root-to-node PATHS, inflating every descendant of a multi-consumer
    node past 1 and silently barring single-consumer chains below it
    from ever fusing (and is exponential on stacked shared nodes)."""
    refs: Dict[int, int] = {}

    def rec(n: PhysicalPlan) -> None:
        for c in n.children:
            first = id(c) not in refs
            refs[id(c)] = refs[id(c)] + 1 if not first else 1
            if first:
                rec(c)
    rec(plan)
    return refs


def _node_exprs(n: PhysicalPlan) -> List[ir.Expression]:
    if isinstance(n, TpuProjectExec):
        return list(n.exprs)
    return [n.condition]


def _collect_chain(head: PhysicalPlan,
                   refs: Dict[int, int]) -> List[PhysicalPlan]:
    """Maximal fusable chain starting at ``head``, top-down."""
    seq: List[PhysicalPlan] = []
    n = head
    while isinstance(n, (TpuProjectExec, TpuFilterExec)) and \
            refs.get(id(n), 0) <= 1 and \
            not _has_barrier(_node_exprs(n), _STAGE_BARRIERS):
        seq.append(n)
        n = n.children[0]
    return seq


def _compose(seq: List[PhysicalPlan], max_nodes: int
             ) -> Optional[Tuple[List[ir.Expression],
                                 Optional[ir.Expression]]]:
    """Compose a top-down chain into (out_exprs, condition) over the
    chain input schema; None when past the maxExprs guard."""
    mapping = _identity_mapping(seq[-1].children[0].schema)
    cond: Optional[ir.Expression] = None
    for n in reversed(seq):
        if isinstance(n, TpuFilterExec):
            c = _subst(n.condition, mapping)
            cond = c if cond is None else _mk_and(cond, c)
        else:
            mapping = [_subst(_strip_alias(e), mapping) for e in n.exprs]
    total = sum(_n_nodes(e) for e in mapping) + _n_nodes(cond)
    if total > max_nodes or not mapping:
        return None
    return mapping, cond


def _worthwhile(seq: List[PhysicalPlan], out_exprs: List[ir.Expression],
                cond: Optional[ir.Expression]) -> bool:
    """Fuse only when the stage costs fewer dispatches than the chain:
    >= 2 chain nodes collapse to one dispatch; a single pure-select
    project collapses to zero (passthrough)."""
    if len(seq) >= 2:
        return True
    pure = cond is None and all(isinstance(e, ir.BoundReference)
                                for e in out_exprs)
    return pure and len(seq) >= 1


def _try_collapse(head: PhysicalPlan, refs: Dict[int, int],
                  max_nodes: int) -> Optional[TpuFusedStageExec]:
    seq = _collect_chain(head, refs)
    if not seq:
        return None
    composed = _compose(seq, max_nodes)
    if composed is None:
        return None
    out_exprs, cond = composed
    if not _worthwhile(seq, out_exprs, cond):
        return None
    return TpuFusedStageExec(
        seq[-1].children[0], out_exprs, seq[0].schema, cond,
        fused=[type(n).__name__ for n in seq])


def _absorb_agg_prologue(agg, refs: Dict[int, int],
                         max_nodes: int,
                         allow_filter: bool = True) -> int:
    """R2: inline the Project/Filter prologue directly under a hash
    aggregate into its grouping/aggregate-argument expressions and
    ``fused_condition`` row mask.  Returns execs absorbed."""
    from spark_rapids_tpu.exec.tpu_aggregate import TpuHashAggregateExec
    assert isinstance(agg, TpuHashAggregateExec)
    absorbed = 0
    while True:
        child = agg.children[0]
        if refs.get(id(child), 0) > 1:
            break
        if isinstance(child, TpuFilterExec):
            if not allow_filter or \
                    _has_barrier([child.condition], _AGG_BARRIERS):
                break
            # a filter sitting DIRECTLY under the aggregate in the
            # original plan is absorbed by the legacy
            # _fuse_filters_into_aggregates post-pass even with fusion
            # off (same agg.fusedFilter gate), so it is not a dispatch
            # fusion saves — don't let it inflate dispatchesSaved
            legacy_would_absorb = (absorbed == 0
                                   and agg.fused_condition is None)
            cond = child.condition if agg.fused_condition is None \
                else _mk_and(agg.fused_condition, child.condition)
            if (_n_nodes(cond) + sum(_n_nodes(g) for g in agg.groupings)
                    + sum(_n_nodes(c) for a in agg.aggregates
                          for c in a.children)) > max_nodes:
                break
            agg.fused_condition = cond
            agg.children = (child.children[0],)
            agg.fused_prologue_execs += 1
            if not legacy_would_absorb:
                agg.fused_prologue_saved += 1
        elif isinstance(child, TpuProjectExec):
            exprs = [_strip_alias(e) for e in child.exprs]
            if _has_barrier(exprs, _AGG_BARRIERS):
                break
            new_groupings = [_subst(g, exprs) for g in agg.groupings]
            # CLONE the aggregate nodes (with_children) — the
            # AggregateExpression objects are shared with the logical
            # plan, and mutating their children in place would poison
            # the NEXT planning of the same DataFrame (the second
            # collect() would substitute already-substituted ordinals
            # through a different projection)
            new_aggs = [a.with_children(
                tuple(_subst(c, exprs) for c in a.children))
                for a in agg.aggregates]
            new_cond = None if agg.fused_condition is None \
                else _subst(agg.fused_condition, exprs)
            total = (sum(_n_nodes(g) for g in new_groupings)
                     + sum(_n_nodes(c) for a in new_aggs
                           for c in a.children)
                     + _n_nodes(new_cond))
            if total > max_nodes:
                break
            from spark_rapids_tpu.exec.tpu_aggregate import make_spec
            agg.groupings[:] = new_groupings
            agg.aggregates[:] = new_aggs
            # specs wrap the aggregate nodes; rebuild over the clones
            agg.specs[:] = [make_spec(a) for a in new_aggs]
            agg.fused_condition = new_cond
            agg.children = (child.children[0],)
            agg.fused_prologue_execs += 1
            agg.fused_prologue_saved += 1  # legacy never absorbs projects
        else:
            break
        absorbed += 1
    return absorbed


class _Holder(PhysicalPlan):
    """Transient root wrapper so the real root can head a chain."""

    def __init__(self, child: PhysicalPlan):
        self.children = (child,)


def fuse_stages(plan: PhysicalPlan, conf: RapidsTpuConf) -> PhysicalPlan:
    """Apply R2 then R1 over the whole converted plan; returns the
    (possibly new) root.  Plan-shape counters land in the obs registry
    (``fusion.stages`` / ``fusion.execsFused`` /
    ``fusion.aggProloguesInlined``) so each query's profile carves its
    own delta; the runtime counter ``fusion.dispatchesSaved``
    accumulates per dispatched batch inside the stage."""
    from spark_rapids_tpu.exec.tpu_aggregate import TpuHashAggregateExec
    from spark_rapids_tpu.obs import registry as obsreg

    max_nodes = int(conf.get(cfg.FUSION_MAX_EXPRS))
    allow_filter = bool(conf.get(cfg.AGG_FUSED_FILTER))
    refs = _refcounts(plan)
    reg = obsreg.get_registry()
    holder = _Holder(plan)
    # a shared subtree is rewritten ONCE (both parents keep pointing at
    # the same mutated object); re-walking it per parent would re-run
    # the agg-prologue absorption and double the plan-shape counters
    seen = set()

    def rec(n: PhysicalPlan) -> None:
        if id(n) in seen:
            return
        seen.add(id(n))
        if isinstance(n, TpuHashAggregateExec):
            inlined = _absorb_agg_prologue(n, refs, max_nodes,
                                           allow_filter)
            if inlined:
                reg.inc("fusion.aggProloguesInlined", inlined)
        new_children = []
        for c in n.children:
            stage = _try_collapse(c, refs, max_nodes)
            if stage is not None:
                reg.inc("fusion.stages")
                reg.inc("fusion.execsFused", stage.n_fused())
                new_children.append(stage)
            else:
                new_children.append(c)
        if tuple(new_children) != tuple(n.children):
            n.children = tuple(new_children)
        for c in n.children:
            rec(c)

    rec(holder)
    return holder.children[0]
