"""Physical planner: logical plan -> CPU physical plan.

Plays the role Spark's query planner plays above the reference plugin: it
produces the "stock" CPU physical plan that TpuOverrides then rewrites
(reference call stack: SURVEY.md §3.1).
"""

from __future__ import annotations

from spark_rapids_tpu.config import RapidsTpuConf
from spark_rapids_tpu import config as cfg
from spark_rapids_tpu.exec import cpu as cpux
from spark_rapids_tpu.exec.base import PhysicalPlan
from spark_rapids_tpu.plan import logical as lp


def plan_cpu(node: lp.LogicalPlan, conf: RapidsTpuConf) -> PhysicalPlan:
    if isinstance(node, lp.InMemoryScan):
        return cpux.CpuScanExec(node.table, node.num_partitions,
                                conf.get(cfg.MAX_READER_BATCH_SIZE_ROWS))
    if isinstance(node, lp.FileScan):
        from spark_rapids_tpu.io.readers import CpuFileScanExec
        return CpuFileScanExec(node, conf)
    if isinstance(node, lp.Project):
        child = plan_cpu(node.children[0], conf)
        return cpux.CpuProjectExec(child, node.exprs, node.schema)
    if isinstance(node, lp.Filter):
        child = plan_cpu(node.children[0], conf)
        return cpux.CpuFilterExec(child, node.condition)
    if isinstance(node, lp.Sort):
        child = plan_cpu(node.children[0], conf)
        return cpux.CpuSortExec(child, node.orders)
    if isinstance(node, lp.Aggregate):
        child = plan_cpu(node.children[0], conf)
        from spark_rapids_tpu.expr import ir
        aggs = []
        for a in node.aggregates:
            inner = a.children[0] if isinstance(a, ir.Alias) else a
            if not isinstance(inner, ir.AggregateExpression):
                raise NotImplementedError(
                    "aggregate expressions must be plain aggregate "
                    "functions (optionally aliased) for now")
            aggs.append(inner)
        return cpux.CpuHashAggregateExec(child, node.groupings, aggs,
                                         node.schema)
    if isinstance(node, lp.Limit):
        child = plan_cpu(node.children[0], conf)
        return cpux.CpuLimitExec(child, node.n)
    if isinstance(node, lp.Union):
        return cpux.CpuUnionExec([plan_cpu(c, conf) for c in node.children])
    if isinstance(node, lp.Join):
        left = plan_cpu(node.children[0], conf)
        right = plan_cpu(node.children[1], conf)
        return cpux.CpuJoinExec(left, right, node.left_keys, node.right_keys,
                                node.how, node.condition, node.schema,
                                node.key_dtypes)
    if isinstance(node, lp.Range):
        return cpux.CpuRangeExec(node.start, node.end, node.step,
                                 node.num_partitions)
    if isinstance(node, lp.Expand):
        child = plan_cpu(node.children[0], conf)
        return cpux.CpuExpandExec(child, node.projections, node.schema)
    if isinstance(node, lp.Window):
        from spark_rapids_tpu.exec.cpu_window import CpuWindowExec
        child = plan_cpu(node.children[0], conf)
        return CpuWindowExec(child, node.window_exprs, node.out_names,
                             node.schema)
    raise NotImplementedError(f"planner: {type(node).__name__}")
