"""Physical planner: logical plan -> CPU physical plan.

Plays the role Spark's query planner plays above the reference plugin: it
produces the "stock" CPU physical plan that TpuOverrides then rewrites
(reference call stack: SURVEY.md §3.1).

Planning pipeline (session ``_plan_physical``): ``prune_columns``
(plan/optimizer.py) -> ``plan_cpu`` (here) -> ``TpuOverrides.apply``
(plan/overrides.py), which converts to Tpu execs and then runs the
whole-stage fusion pass (plan/fusion.py) — Project/Filter chains
collapse into single-dispatch ``TpuFusedStageExec`` nodes and
aggregate prologues inline into the update kernel.  Fusion must see
the CONVERTED plan (it fuses Tpu execs, not the CPU nodes built
here), which is why it lives behind the overrides rather than in this
module.
"""

from __future__ import annotations

from spark_rapids_tpu.config import RapidsTpuConf
from spark_rapids_tpu import config as cfg
from spark_rapids_tpu.exec import cpu as cpux
from spark_rapids_tpu.exec.base import PhysicalPlan
from spark_rapids_tpu.plan import logical as lp


def plan_cpu(node: lp.LogicalPlan, conf: RapidsTpuConf) -> PhysicalPlan:
    if isinstance(node, lp.InMemoryScan):
        return cpux.CpuScanExec(node.table, node.num_partitions,
                                conf.get(cfg.MAX_READER_BATCH_SIZE_ROWS))
    if isinstance(node, lp.FileScan):
        from spark_rapids_tpu.io.readers import CpuFileScanExec
        return CpuFileScanExec(node, conf)
    if isinstance(node, lp.CachedRelation):
        from spark_rapids_tpu.exec.cache import CpuInMemoryTableScanExec
        return CpuInMemoryTableScanExec(node, conf)
    if isinstance(node, lp.Project):
        child = plan_cpu(node.children[0], conf)
        return _plan_project(node, child, conf)
    if isinstance(node, lp.Filter):
        child = plan_cpu(node.children[0], conf)
        return _plan_filter(node, child, conf)
    if isinstance(node, lp.Sort):
        child = plan_cpu(node.children[0], conf)
        return _plan_sort(node, child, conf)
    if isinstance(node, lp.Aggregate):
        child = plan_cpu(node.children[0], conf)
        from spark_rapids_tpu.expr import ir
        aggs = []
        for a in node.aggregates:
            inner = a.children[0] if isinstance(a, ir.Alias) else a
            if not isinstance(inner, ir.AggregateExpression):
                raise NotImplementedError(
                    "aggregate expressions must be plain aggregate "
                    "functions (optionally aliased) for now")
            aggs.append(inner)
        # pandas UDFs in grouping keys / aggregate args evaluate in an
        # ArrowEvalPython stage below the aggregate
        flat = list(node.groupings) + \
            [a.children[0] for a in aggs if a.children]
        new_flat, child = _extract_pandas_udfs(flat, child)
        groupings = new_flat[:len(node.groupings)]
        k = len(node.groupings)
        for a in aggs:
            if a.children:
                a.children = (new_flat[k],)
                k += 1
        # distributed plan shape: hash-exchange on the grouping keys, then
        # a per-partition (complete) aggregate — Spark's partial/final
        # split restructured so the exchange is a planner-visible node the
        # ICI data plane can ride (reference: aggregate.scala partial/
        # final stage pair around GpuShuffleExchangeExec)
        two_stage = bool(groupings) and (
            conf.get(cfg.AGG_EXCHANGE)
            or str(conf.get(cfg.SHUFFLE_TRANSPORT)) in ("ici", "ici_ring",
                                                        "process"))
        if two_stage and all(g.dtype is not None and not g.dtype.is_nested
                             for g in groupings):
            from spark_rapids_tpu.shuffle import exchange as ex
            child = ex.CpuShuffleExchangeExec(
                child, ex.HashPartitioning(conf.shuffle_partitions,
                                           list(groupings)))
            return cpux.CpuHashAggregateExec(child, groupings, aggs,
                                             node.schema,
                                             per_partition=True)
        agg_exec = cpux.CpuHashAggregateExec(child, groupings, aggs,
                                             node.schema)
        # incremental-maintenance stamp (exec/incremental.py): ride the
        # logical node's partial-capture/retained-state hooks through
        # to the physical aggregate; a private attr so the plan digest
        # and expression enumeration never see it
        inc = getattr(node, "_incremental", None)
        if inc is not None:
            agg_exec._incremental = inc
        return agg_exec
    if isinstance(node, lp.Limit):
        child = plan_cpu(node.children[0], conf)
        return cpux.CpuLimitExec(child, node.n)
    if isinstance(node, lp.Union):
        return cpux.CpuUnionExec([plan_cpu(c, conf) for c in node.children])
    if isinstance(node, lp.Join):
        return _plan_join(node, conf)
    if isinstance(node, lp.Repartition):
        from spark_rapids_tpu.shuffle import exchange as ex
        child = plan_cpu(node.children[0], conf)
        n = node.num_partitions
        if node.kind == "hash":
            part = ex.HashPartitioning(n, node.exprs)
        elif node.kind == "range":
            part = ex.RangePartitioning(n, node.orders)
        elif node.kind == "single":
            part = ex.SinglePartitioning(n)
        else:
            part = ex.RoundRobinPartitioning(n)
        return ex.CpuShuffleExchangeExec(child, part)
    if isinstance(node, lp.CoalescePartitions):
        from spark_rapids_tpu.shuffle.exchange import \
            CpuCoalescePartitionsExec
        child = plan_cpu(node.children[0], conf)
        return CpuCoalescePartitionsExec(child, node.num_partitions)
    if isinstance(node, lp.Range):
        return cpux.CpuRangeExec(node.start, node.end, node.step,
                                 node.num_partitions)
    if isinstance(node, lp.Expand):
        child = plan_cpu(node.children[0], conf)
        return cpux.CpuExpandExec(child, node.projections, node.schema)
    if isinstance(node, lp.Generate):
        from spark_rapids_tpu.exec.generate import CpuGenerateExec
        child = plan_cpu(node.children[0], conf)
        return CpuGenerateExec(child, node.generator, node.schema)
    if isinstance(node, lp.Window):
        from spark_rapids_tpu.exec.cpu_window import CpuWindowExec
        child = plan_cpu(node.children[0], conf)
        # distributed plan shape: when every window spec shares the same
        # non-empty PARTITION BY, hash-exchange on those keys and run
        # the window per partition (Spark's ClusteredDistribution
        # requirement under GpuWindowExec, restructured so the exchange
        # is a planner-visible node the ICI plane can ride)
        dist = conf.get(cfg.WINDOW_EXCHANGE) or \
            str(conf.get(cfg.SHUFFLE_TRANSPORT)) in ("ici", "ici_ring")
        if dist and node.window_exprs:
            psigs = {tuple(e.sql() for e in we.partition_exprs)
                     for we in node.window_exprs}
            pk = list(node.window_exprs[0].partition_exprs)
            if len(psigs) == 1 and pk and \
                    all(e.dtype is not None and not e.dtype.is_nested
                        for e in pk):
                from spark_rapids_tpu.shuffle import exchange as ex
                child = ex.CpuShuffleExchangeExec(
                    child, ex.HashPartitioning(conf.shuffle_partitions,
                                               pk))
                return CpuWindowExec(child, node.window_exprs,
                                     node.out_names, node.schema,
                                     partitionwise=True)
        return CpuWindowExec(child, node.window_exprs, node.out_names,
                             node.schema)
    if isinstance(node, lp.MapInPandas):
        from spark_rapids_tpu.pyworker.execs import CpuMapInPandasExec
        child = plan_cpu(node.children[0], conf)
        return CpuMapInPandasExec(child, node.fn, node.schema)
    if isinstance(node, lp.FlatMapGroupsInPandas):
        from spark_rapids_tpu.pyworker.execs import \
            CpuFlatMapGroupsInPandasExec
        child = plan_cpu(node.children[0], conf)
        return CpuFlatMapGroupsInPandasExec(child, node.keys, node.fn,
                                            node.schema)
    if isinstance(node, lp.CoGroupedMapInPandas):
        from spark_rapids_tpu.pyworker.execs import \
            CpuFlatMapCoGroupsInPandasExec
        return CpuFlatMapCoGroupsInPandasExec(
            plan_cpu(node.children[0], conf),
            plan_cpu(node.children[1], conf),
            node.left_keys, node.right_keys, node.fn, node.schema)
    if isinstance(node, lp.AggregateInPandas):
        from spark_rapids_tpu.pyworker.execs import CpuAggregateInPandasExec
        child = plan_cpu(node.children[0], conf)
        return CpuAggregateInPandasExec(child, node.keys, node.fn,
                                        node.args, node.out_field)
    if isinstance(node, lp.WindowInPandas):
        from spark_rapids_tpu.pyworker.execs import CpuWindowInPandasExec
        child = plan_cpu(node.children[0], conf)
        return CpuWindowInPandasExec(child, node.part_keys, node.fn,
                                     node.args, node.out_field)
    raise NotImplementedError(f"planner: {type(node).__name__}")


def _is_pandas_udf(x) -> bool:
    from spark_rapids_tpu.expr import ir
    return isinstance(x, ir.PythonUDF) and getattr(x, "vectorized", False)


def _extract_pandas_udfs(exprs, child: PhysicalPlan):
    """ExtractPythonUDFs-rule analog: peel vectorized PythonUDFs out of
    ``exprs`` into ArrowEvalPython execs below, innermost-first in waves
    (so chained pandas UDFs each get their own eval stage, like Spark's
    batched extraction above GpuArrowEvalPythonExec).

    Returns (rewritten_exprs, new_child).
    """
    from spark_rapids_tpu.expr import ir
    from spark_rapids_tpu.pyworker.execs import CpuArrowEvalPythonExec

    counter = [0]
    while True:
        # innermost wave = vectorized UDFs with no vectorized descendant
        wave: list = []

        def visit(x):
            has_nested = False
            for c in x.children:
                has_nested |= visit(c)
            me = _is_pandas_udf(x)
            if me and not has_nested and not any(y is x for y in wave):
                wave.append(x)
            return me or has_nested

        found_any = False
        for e in exprs:
            found_any |= visit(e)
        if not found_any:
            return exprs, child
        base_n = len(child.schema)
        names = []
        for _u in wave:
            names.append(f"_pandas_udf_{counter[0]}")
            counter[0] += 1
        child = CpuArrowEvalPythonExec(child, list(zip(names, wave)))

        def replace(x):
            for i, u in enumerate(wave):
                if x is u:
                    return ir.BoundReference(base_n + i, u.return_type,
                                             True, name_=names[i])
            return None

        exprs = [ir.transform(e, replace) for e in exprs]


def _plan_project(node: lp.Project, child: PhysicalPlan,
                  conf: RapidsTpuConf) -> PhysicalPlan:
    """Extract vectorized (pandas) PythonUDFs out of projections into
    ArrowEvalPython execs below the project."""
    exprs, child = _extract_pandas_udfs(node.exprs, child)
    return cpux.CpuProjectExec(child, exprs, node.schema)


def _plan_filter(node: lp.Filter, child: PhysicalPlan,
                 conf: RapidsTpuConf) -> PhysicalPlan:
    """Filter conditions may contain pandas UDFs too: extract them below
    the filter, then drop the eval columns with a project so the output
    schema is unchanged."""
    from spark_rapids_tpu.expr import ir
    (cond,), eval_child = _extract_pandas_udfs([node.condition], child)
    if eval_child is child:
        return cpux.CpuFilterExec(child, node.condition)
    filt = cpux.CpuFilterExec(eval_child, cond)
    keep = [ir.BoundReference(i, f.dtype, f.nullable, name_=f.name)
            for i, f in enumerate(child.schema.fields)]
    return cpux.CpuProjectExec(filt, keep, child.schema)


def _plan_sort(node: lp.Sort, child: PhysicalPlan,
               conf: RapidsTpuConf) -> PhysicalPlan:
    """Sort keys may contain pandas UDFs: evaluate them below the sort,
    then project the eval columns away."""
    from spark_rapids_tpu.expr import ir
    exprs = [o.expr for o in node.orders]
    new_exprs, eval_child = _extract_pandas_udfs(exprs, child)
    if eval_child is child:
        # distributed plan shape: a RANGE exchange on the sort keys,
        # then per-partition sorts — partition p holds range-bucket p,
        # so partition-ordered concatenation IS the total order and the
        # exchange can ride the ICI plane (reference:
        # GpuRangePartitioning + per-shard GpuSortExec)
        dist = bool(node.orders) and (
            conf.get(cfg.SORT_EXCHANGE)
            or str(conf.get(cfg.SHUFFLE_TRANSPORT)) in ("ici",
                                                        "ici_ring"))
        if dist and all(
                o.expr.dtype is not None and not o.expr.dtype.is_nested
                for o in node.orders):
            from spark_rapids_tpu.shuffle import exchange as ex
            exch = ex.CpuShuffleExchangeExec(
                child, ex.RangePartitioning(conf.shuffle_partitions,
                                            node.orders))
            return cpux.CpuSortExec(exch, node.orders,
                                    partitionwise=True)
        return cpux.CpuSortExec(child, node.orders)
    orders = [lp.SortOrder(e, o.ascending, o.nulls_first)
              for e, o in zip(new_exprs, node.orders)]
    srt = cpux.CpuSortExec(eval_child, orders)
    keep = [ir.BoundReference(i, f.dtype, f.nullable, name_=f.name)
            for i, f in enumerate(child.schema.fields)]
    return cpux.CpuProjectExec(srt, keep, child.schema)


def _plan_join(node, conf: RapidsTpuConf):
    """Join strategy selection (the role Spark's JoinSelection strategy +
    EnsureRequirements play above the reference plugin).

    broadcast-hash when a side is hinted or estimated under
    spark.rapids.tpu.sql.autoBroadcastJoinThreshold (Spark build-side
    validity rules), else shuffled-hash with a hash exchange inserted on
    both sides; cross joins become broadcast-nested-loop (small side) or
    a partitionwise cartesian product.
    """
    from spark_rapids_tpu.shuffle import exchange as ex
    from spark_rapids_tpu.expr import ir

    left = plan_cpu(node.children[0], conf)
    right = plan_cpu(node.children[1], conf)
    threshold = conf.get(cfg.AUTO_BROADCAST_THRESHOLD)
    lsize = lp.size_estimate(node.children[0])
    rsize = lp.size_estimate(node.children[1])
    args = (node.left_keys, node.right_keys, node.how, node.condition,
            node.schema, node.key_dtypes)

    if node.how == "cross" or not node.left_keys:
        small = min(lsize, rsize)
        if node.hint == "broadcast_left" or (
                node.hint is None and small <= threshold and lsize <= rsize):
            return cpux.CpuBroadcastNestedLoopJoinExec(
                left, right, *args, build_side="left")
        if node.hint == "broadcast_right" or (
                node.hint is None and small <= threshold):
            return cpux.CpuBroadcastNestedLoopJoinExec(
                left, right, *args, build_side="right")
        return cpux.CpuCartesianProductExec(left, right, *args)

    # Spark build-side validity: inner/cross either; left/semi/anti build
    # right only; right outer build left only; full outer no broadcast
    can_build_right = node.how in ("inner", "left", "semi", "anti")
    can_build_left = node.how in ("inner", "right")
    build = None
    if node.hint == "broadcast_right" and can_build_right:
        build = "right"
    elif node.hint == "broadcast_left" and can_build_left:
        build = "left"
    elif can_build_right and rsize <= threshold and \
            (not can_build_left or rsize <= lsize):
        build = "right"
    elif can_build_left and lsize <= threshold:
        build = "left"
    if build is not None:
        return cpux.CpuBroadcastHashJoinExec(left, right, *args,
                                             build_side=build)

    n = conf.shuffle_partitions

    def bound_keys(side_plan, names):
        s = side_plan.schema
        out = []
        for k, kd in zip(names, node.key_dtypes):
            e = ir.bind(ir.UnresolvedAttribute(k), s.names, s.dtypes,
                        s.nullables)
            if e.dtype != kd:
                # both sides must hash the promoted key type identically
                e = ir.Cast(e, kd)
                e.resolve()
            out.append(e)
        return out

    lex = ex.CpuShuffleExchangeExec(
        left, ex.HashPartitioning(n, bound_keys(node.children[0],
                                                node.left_keys)))
    rex = ex.CpuShuffleExchangeExec(
        right, ex.HashPartitioning(n, bound_keys(node.children[1],
                                                 node.right_keys)))
    return cpux.CpuShuffledHashJoinExec(lex, rex, *args)
