"""Logical optimizations: column pruning.

Reference analog: Spark's ``ColumnPruning`` rule, which the reference
plugin inherits from Catalyst before ``GpuOverrides`` ever sees the
plan — scans read only referenced columns.  This engine owns its whole
stack, so the rule lives here: a top-down required-ordinal analysis over
the bound logical plan, then a bottom-up rebuild that narrows
``FileScan``/``InMemoryScan`` leaves and remaps every ancestor's
``BoundReference`` ordinals through the changed schemas.

Pruning a scan matters twice on TPU: the device parquet decode skips
whole column chunks (the q6 bench decodes 4 of 6 columns), and
in-memory uploads skip the HBM transfer entirely.
"""

from __future__ import annotations

import copy
from typing import Dict, List, Optional, Sequence, Set, Tuple

from spark_rapids_tpu.expr import ir
from spark_rapids_tpu.plan import logical as lp
from spark_rapids_tpu.plan.logical import Schema

# mapping: old output ordinal -> new output ordinal; None = unchanged
_Mapping = Optional[Dict[int, int]]


def _refs(exprs) -> Set[int]:
    out: Set[int] = set()
    for e in exprs:
        if e is None:
            continue
        for b in ir.collect(e, lambda n: isinstance(n, ir.BoundReference)):
            out.add(b.ordinal)
    return out


def _remap_expr(e: ir.Expression, mapping: Dict[int, int]
                ) -> ir.Expression:
    if isinstance(e, ir.BoundReference):
        if e.ordinal not in mapping:
            raise KeyError(f"pruned column referenced: {e.sql()}")
        return ir.BoundReference(mapping[e.ordinal], e.dtype, e.nullable,
                                 e.ref_name)
    if not e.children:
        return e
    new_children = tuple(_remap_expr(c, mapping) for c in e.children)
    if all(n is o for n, o in zip(new_children, e.children)):
        return e
    e2 = copy.copy(e)
    e2.children = new_children
    return e2


def _remap_all(exprs, mapping):
    return [None if e is None else _remap_expr(e, mapping)
            for e in exprs]


def _shallow(node, **attrs):
    n2 = copy.copy(node)
    for k, v in attrs.items():
        setattr(n2, k, v)
    return n2


def _all(node) -> Set[int]:
    return set(range(len(node.schema.names)))


def prune_columns(plan: lp.LogicalPlan) -> lp.LogicalPlan:
    """Return an equivalent plan whose scans read only needed columns."""
    try:
        new, mapping = _rewrite(plan, None)
    except KeyError:
        return plan          # a reference the analysis missed: bail out
    # the root's output schema must be unchanged (needed=None = all)
    return plan if mapping is not None else new


def _rewrite(node: lp.LogicalPlan, needed: Optional[Set[int]]
             ) -> Tuple[lp.LogicalPlan, _Mapping]:
    if needed is not None and needed >= _all(node):
        needed = None

    # ---- leaves -----------------------------------------------------------
    if isinstance(node, lp.FileScan):
        if needed is None or node.options.get("columns"):
            return node, None
        keep = sorted(needed)
        if not keep:                       # COUNT(*)-style: keep one
            keep = [0]
        names = [node.schema.names[o] for o in keep]
        # the logical schema must narrow too: ancestors that derive
        # their schema from child.schema (Join, Window) otherwise
        # compute ordinal offsets from the unpruned column list
        new = lp.FileScan(node.fmt, node.paths,
                          Schema([node.schema.field(c) for c in names]),
                          dict(node.options, columns=names))
        return new, {o: i for i, o in enumerate(keep)}
    if isinstance(node, lp.InMemoryScan):
        if needed is None:
            return node, None
        keep = sorted(needed)
        if not keep:
            keep = [0]
        names = [node.schema.names[o] for o in keep]
        new = lp.InMemoryScan(node.table.select(names),
                              node.num_partitions)
        return new, {o: i for i, o in enumerate(keep)}
    if not node.children:
        return node, None

    # ---- single-child nodes ----------------------------------------------
    if isinstance(node, lp.Project):
        child, m = _rewrite(node.children[0], _refs(node.exprs))
        if m is None:
            if child is node.children[0]:
                return node, None
            return _shallow(node, children=(child,)), None
        return _shallow(node, children=(child,),
                        exprs=_remap_all(node.exprs, m)), None
    if isinstance(node, lp.Aggregate):
        child, m = _rewrite(node.children[0],
                            _refs(node.groupings) |
                            _refs(node.aggregates))
        if m is None:
            if child is node.children[0]:
                return node, None
            return _shallow(node, children=(child,)), None
        return _shallow(
            node, children=(child,),
            groupings=_remap_all(node.groupings, m),
            aggregates=_remap_all(node.aggregates, m)), None
    if isinstance(node, lp.Filter):
        child_need = None if needed is None else \
            set(needed) | _refs([node.condition])
        child, m = _rewrite(node.children[0], child_need)
        if m is None:
            if child is node.children[0]:
                return node, None
            return _shallow(node, children=(child,)), None
        return _shallow(node, children=(child,),
                        condition=_remap_expr(node.condition, m)), m
    if isinstance(node, lp.Sort):
        child_need = None if needed is None else \
            set(needed) | _refs([o.expr for o in node.orders])
        child, m = _rewrite(node.children[0], child_need)
        if m is None:
            if child is node.children[0]:
                return node, None
            return _shallow(node, children=(child,)), None
        orders = [lp.SortOrder(_remap_expr(o.expr, m), o.ascending,
                               o.nulls_first) for o in node.orders]
        return _shallow(node, children=(child,), orders=orders), m
    if isinstance(node, (lp.Limit, lp.CoalescePartitions)):
        child, m = _rewrite(node.children[0], needed)
        if m is None:
            if child is node.children[0]:
                return node, None
            return _shallow(node, children=(child,)), None
        return _shallow(node, children=(child,)), m
    if isinstance(node, lp.Repartition):
        child_need = None if needed is None else (
            set(needed) | _refs(node.exprs)
            | _refs([o.expr for o in node.orders]))
        child, m = _rewrite(node.children[0], child_need)
        if m is None:
            if child is node.children[0]:
                return node, None
            return _shallow(node, children=(child,)), None
        orders = [lp.SortOrder(_remap_expr(o.expr, m), o.ascending,
                               o.nulls_first) for o in node.orders]
        return _shallow(node, children=(child,),
                        exprs=_remap_all(node.exprs, m),
                        orders=orders), m
    if isinstance(node, lp.Window):
        n_child = len(node.children[0].schema.names)
        if needed is None:
            child_need = None
        else:
            child_need = {o for o in needed if o < n_child} | \
                _refs(node.window_exprs)
        child, m = _rewrite(node.children[0], child_need)
        if m is None:
            if child is node.children[0]:
                return node, None
            return _shallow(node, children=(child,)), None
        wexprs = _remap_all(node.window_exprs, m)
        new_fields = list(child.schema.fields) + \
            [lp.Field(n, e.dtype, e.nullable)
             for n, e in zip(node.out_names, wexprs)]
        out_map = {o: m[o] for o in sorted(m)}
        n_new_child = len(child.schema.names)
        for i, _ in enumerate(node.out_names):
            out_map[n_child + i] = n_new_child + i
        return _shallow(node, children=(child,), window_exprs=wexprs,
                        _schema=Schema(new_fields)), out_map

    # ---- multi-child nodes ------------------------------------------------
    if isinstance(node, lp.Union):
        if needed is None:
            outs = [_rewrite(c, None) for c in node.children]
            # needed=None passes through, so no branch can narrow its
            # OUTPUT (mapping None) — but a branch may still have pruned
            # scans deeper down (e.g. below its own Project)
            assert all(m is None for _, m in outs)
            if all(c is o for (c, _), o in zip(outs, node.children)):
                return node, None
            return _shallow(node,
                            children=tuple(c for c, _ in outs)), None
        # positional schemas: same ordinals for every branch; narrow the
        # union output only when every branch narrows identically —
        # otherwise keep each branch's internal pruning but present the
        # full output (re-rewrite with needed=None)
        outs = [_rewrite(c, set(needed)) for c in node.children]
        maps = [m for _, m in outs]
        if all(m is None for m in maps):
            if all(c is o for (c, _), o in zip(outs, node.children)):
                return node, None
            return _shallow(node,
                            children=tuple(c for c, _ in outs)), None
        if any(m is None for m in maps) or len({tuple(sorted(m.items()))
                                                for m in maps}) != 1:
            outs = [_rewrite(c, None) for c in node.children]
            if all(c is o for (c, _), o in zip(outs, node.children)):
                return node, None
            return _shallow(node,
                            children=tuple(c for c, _ in outs)), None
        return _shallow(node, children=tuple(c for c, _ in outs)), \
            maps[0]
    if isinstance(node, lp.Join):
        lnames = node.children[0].schema.names
        rnames = node.children[1].schema.names
        n_l = len(lnames)
        semi = node.how in ("semi", "anti")
        if needed is None:
            l_need: Optional[Set[int]] = None
            r_need: Optional[Set[int]] = None
        else:
            l_need = {o for o in needed if o < n_l}
            r_need = set() if semi else \
                {o - n_l for o in needed if o >= n_l}
        cond_refs = _refs([node.condition])
        if l_need is not None:
            l_need |= {lnames.index(k) for k in node.left_keys}
            l_need |= {o for o in cond_refs if o < n_l}
        if r_need is not None:
            r_need |= {rnames.index(k) for k in node.right_keys}
            r_need |= {o - n_l for o in cond_refs if o >= n_l}
        lc, lm = _rewrite(node.children[0], l_need)
        rc, rm = _rewrite(node.children[1], r_need)
        if lm is None and rm is None:
            if lc is node.children[0] and rc is node.children[1]:
                return node, None
            return _shallow(node, children=(lc, rc)), None
        lm = lm if lm is not None else {i: i for i in range(n_l)}
        n_l_new = len(lc.schema.names)
        rm = rm if rm is not None else {i: i for i in range(len(rnames))}
        # rebuild through the constructor: it rederives the output
        # schema, key dtypes, and binds the (unbound-equivalent)
        # condition — remap the old condition to the new joined space
        joined_map = dict(lm)
        for o, n in rm.items():
            joined_map[n_l + o] = n_l_new + n
        cond = None if node.condition is None else \
            _remap_expr(node.condition, joined_map)
        new = copy.copy(node)
        new.children = (lc, rc)
        new.condition = cond
        lf, rf = lc.schema.fields, rc.schema.fields
        if semi:
            new._schema = Schema(list(lf))
        else:
            nullable_l = node.how in ("right", "full")
            nullable_r = node.how in ("left", "full")
            new._schema = Schema(
                [lp.Field(f.name, f.dtype, f.nullable or nullable_l)
                 for f in lf] +
                [lp.Field(f.name, f.dtype, f.nullable or nullable_r)
                 for f in rf])
        if semi:
            return new, (None if lm == {i: i for i in range(n_l)}
                         else lm)
        return new, (None if joined_map ==
                     {i: i for i in range(len(node.schema.names))}
                     else joined_map)

    # unhandled node kinds (Generate, Expand, pandas nodes, caches, …):
    # require everything below, never narrow through
    new_children = []
    changed = False
    for c in node.children:
        nc, m = _rewrite(c, None)
        changed = changed or nc is not c
        assert m is None
        new_children.append(nc)
    if not changed:
        return node, None
    return _shallow(node, children=tuple(new_children)), None
