"""Canonical plan digest: one stable identity per logical query shape.

The engine already canonicalizes *expressions* for the process-wide
kernel cache (``exec/kernel_cache.expr_sig``: ordinals and dtypes,
never column/alias names — the PR 4 alias-dedup contract).  This module
lifts that same canonicalization to whole logical plans:

  * :func:`plan_digest` — a stable hex digest of the plan's canonical
    structure.  Insensitive to aliasing/renaming (two queries that
    differ only in intermediate or output names share a digest, exactly
    as they share compiled kernels), sensitive to everything that can
    change the *result*: literal values, source files, join kinds,
    sort orders, limits.
  * :func:`plan_fingerprint` — the digest plus what the serving tier's
    result-set cache needs to key on it safely: the referenced file
    sources (stamped at lookup time by ``io/scan_cache``) and a
    ``cacheable`` verdict (False for non-deterministic expressions,
    opaque user functions, or sources whose content can't be stamped).

Surfaces: the ``plan_digest`` column on QueryProfile and the
``/queries`` table (obs), the result-set cache key (serve), and the
prepared-statement template identity.
"""

from __future__ import annotations

import hashlib
import weakref
from dataclasses import dataclass
from typing import Any, Iterator, Optional, Tuple

from spark_rapids_tpu.exec.kernel_cache import expr_sig
from spark_rapids_tpu.expr import ir
from spark_rapids_tpu.plan import logical as lp

# expression classes whose value depends on more than their inputs —
# a plan containing any of these must never be served from a result
# cache (conservative: SparkPartitionID/InputFileName are deterministic
# for a fixed layout, but a cache hit must never be a judgement call)
_NONDETERMINISTIC_EXPRS = frozenset({
    "Rand", "Randn", "MonotonicallyIncreasingID", "Uuid",
    "CurrentTimestamp", "CurrentDate", "Now",
    "PythonUDF", "PandasUDF", "SparkPartitionID", "InputFileName",
})

# content-hash in-memory tables up to this size; beyond it identity
# (not content) keys the digest and the plan is marked non-cacheable
_INMEM_HASH_CAP = 64 << 20

# id(table) -> sha1 of its IPC payload, computed once per object;
# pa.Table is unhashable so WeakKeyDictionary is out — key by id with a
# finalizer evicting the entry when the table dies, so a recycled id
# can never serve another table's hash
_TABLE_HASH: dict = {}

# plan-node attributes that only carry *names* (output labels) or
# redundant unbound copies of bound expressions — never result content
_SKIP_ATTRS = frozenset({
    "children", "raw_groupings", "raw_aggregates",
    "out_names", "blobs", "device_encoded",
})


@dataclass(frozen=True)
class PlanFingerprint:
    """What the result-set cache keys on (see module docstring)."""

    digest: str
    sources: Tuple[str, ...]
    cacheable: bool


# ---------------------------------------------------------------------------
# Expression enumeration (shared by digest, prepared-statement binding)
# ---------------------------------------------------------------------------

def iter_node_exprs(node: lp.LogicalPlan) -> Iterator[ir.Expression]:
    """Every bound expression root hanging off one plan node's public
    attributes (lists/tuples and SortOrder wrappers included)."""
    for k in sorted(vars(node)):
        if k.startswith("_") or k in _SKIP_ATTRS:
            continue
        yield from _exprs_in(vars(node)[k])


def _exprs_in(v: Any) -> Iterator[ir.Expression]:
    if isinstance(v, ir.Expression):
        yield v
    elif isinstance(v, (list, tuple)):
        for x in v:
            yield from _exprs_in(x)
    elif isinstance(v, lp.SortOrder):
        yield v.expr


def iter_plan_exprs(plan: lp.LogicalPlan) -> Iterator[ir.Expression]:
    """Every bound expression root in the whole plan tree."""
    for node in walk(plan):
        yield from iter_node_exprs(node)


def walk(plan: lp.LogicalPlan) -> Iterator[lp.LogicalPlan]:
    """Every node, first-visit only: plans are DAGs (a CTE referenced
    twice is one shared subtree with two parents), so a naive tree walk
    re-visits shared subtrees once per path and goes exponential on
    stacked CTEs — the same path-counting trap plan/fusion._refcounts
    already fixed for the fusion pass."""
    seen: set = set()

    def _walk(node: lp.LogicalPlan) -> Iterator[lp.LogicalPlan]:
        if id(node) in seen:
            return
        seen.add(id(node))
        yield node
        for c in node.children:
            yield from _walk(c)

    return _walk(plan)


# ---------------------------------------------------------------------------
# Canonical structure
# ---------------------------------------------------------------------------

def _table_sig(table) -> Tuple:
    """Content signature of an in-memory Arrow table (InMemoryScan):
    IPC-payload hash for small tables (cached per object), identity for
    large ones — identity keeps the digest stable within a process but
    bars result caching (see :func:`plan_fingerprint`)."""
    meta = (tuple(table.schema.names),
            tuple(str(t) for t in table.schema.types),
            int(table.num_rows))
    if table.nbytes > _INMEM_HASH_CAP:
        return ("inmem-id", meta, id(table))
    key = id(table)
    h = _TABLE_HASH.get(key)
    if h is None:
        import pyarrow as pa
        sink = pa.BufferOutputStream()
        with pa.ipc.new_stream(sink, table.schema) as w:
            for b in table.to_batches():
                w.write_batch(b)
        h = hashlib.sha1(sink.getvalue()).hexdigest()
        _TABLE_HASH[key] = h
        weakref.finalize(table, _TABLE_HASH.pop, key, None)
    return ("inmem", meta, h)


def _value_sig(v: Any) -> Any:
    """Deterministic hashable signature for non-expression attribute
    values (the plan-level sibling of kernel_cache._value_sig, with
    dict support for scan options)."""
    if isinstance(v, (str, int, float, bool, bytes, type(None))):
        return v
    if isinstance(v, ir.Expression):
        return expr_sig(v)
    if isinstance(v, (list, tuple)):
        return tuple(_value_sig(x) for x in v)
    if isinstance(v, dict):
        return tuple(sorted((str(k), _value_sig(x)) for k, x in v.items()))
    if isinstance(v, lp.SortOrder):
        return ("SortOrder", expr_sig(v.expr), v.ascending,
                v.nulls_first_resolved)
    if hasattr(v, "name") and not callable(v):       # DType-like
        return getattr(v, "name")
    if callable(v):
        return ("callable", id(v))
    return ("repr", type(v).__name__, repr(v)[:128])


def _node_hash(node: lp.LogicalPlan, memo: dict) -> str:
    """Merkle-style per-node hash: children contribute their HASHES,
    not their expanded signatures, and shared subtrees hash once (memo
    by node identity).  Plans are DAGs — a CTE referenced twice is one
    subtree with two parents — so both a naive tree walk AND an
    expanded-tuple repr go exponential on stacked CTEs (the
    path-counting trap plan/fusion._refcounts already fixed for the
    fusion pass); hashing per node keeps the digest linear in unique
    nodes while preserving structural identity."""
    hit = memo.get(id(node))
    if hit is not None:
        return hit
    parts: list = [type(node).__name__]
    if isinstance(node, lp.InMemoryScan):
        parts.append(_table_sig(node.table))
        parts.append(node.num_partitions)
    elif isinstance(node, lp.FileScan):
        import os
        parts.append(node.fmt)
        roots = node.options.get("source_roots")
        if roots:
            # watched scan: the recorded roots are the dataset's
            # identity.  The expanded snapshot (and its per-file
            # part_values) drifts with every append, so digesting it
            # would hand each session its own digest for the same
            # directory — the source stamps, which key the result
            # cache alongside this digest, carry the content identity
            parts.append(("roots",
                          tuple(os.path.abspath(p) for p in roots)))
            parts.append(_value_sig(
                {k: v for k, v in node.options.items()
                 if k != "part_values"}))
        else:
            parts.append(tuple(os.path.abspath(p) for p in node.paths))
            parts.append(_value_sig(node.options))
        # the inferred schema participates: re-reading the same paths
        # after a rewrite with new columns must change the digest even
        # before the stamps do
        parts.append(tuple((f.name, f.dtype.name)
                           for f in node.schema.fields))
    else:
        for k in sorted(vars(node)):
            if k.startswith("_") or k in _SKIP_ATTRS:
                continue
            parts.append((k, _value_sig(vars(node)[k])))
    parts.append(tuple(_node_hash(c, memo) for c in node.children))
    h = hashlib.sha1(repr(tuple(parts)).encode()).hexdigest()
    memo[id(node)] = h
    return h


def plan_digest(plan: lp.LogicalPlan) -> str:
    """Stable hex digest of the plan's canonical structure (module
    docstring).  Raises only on truly malformed plans; callers on the
    query hot path should use :func:`safe_plan_digest`."""
    return _node_hash(plan, {})


def safe_plan_digest(plan) -> Optional[str]:
    """``plan_digest`` that never raises — observability attribution
    must not be able to fail a query."""
    try:
        return plan_digest(plan)
    except Exception:
        return None


def plan_fingerprint(plan: lp.LogicalPlan) -> PlanFingerprint:
    """Digest + result-cache admissibility (module docstring)."""
    digest = plan_digest(plan)
    sources: list = []
    cacheable = True
    for node in walk(plan):
        if isinstance(node, lp.FileScan):
            import os
            sources.extend(os.path.abspath(p) for p in node.paths)
        elif isinstance(node, lp.InMemoryScan):
            if node.table.nbytes > _INMEM_HASH_CAP:
                cacheable = False
        elif getattr(node, "fn", None) is not None:
            cacheable = False          # opaque user function (pandas/UDF)
        for e in iter_node_exprs(node):
            if ir.collect(e, lambda n: type(n).__name__
                          in _NONDETERMINISTIC_EXPRS):
                cacheable = False
    return PlanFingerprint(digest=digest,
                           sources=tuple(sorted(set(sources))),
                           cacheable=cacheable)
