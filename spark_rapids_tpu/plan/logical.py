"""Logical plans produced by the DataFrame API.

Role analog: Spark's Catalyst logical plans, which sit *above* the reference
plugin (the reference only rewrites physical plans; reference:
SURVEY.md L3, GpuOverrides.scala:2047).  We are standalone, so we own this
layer too — it stays deliberately thin: resolution here, optimization and
device placement in the physical planner/overrides.
"""

from __future__ import annotations

import copy
from dataclasses import dataclass
from typing import List, Optional, Sequence, Tuple

import pyarrow as pa

from spark_rapids_tpu import dtypes as dt
from spark_rapids_tpu.expr import ir


@dataclass(frozen=True)
class Field:
    name: str
    dtype: dt.DType
    nullable: bool = True


class Schema:
    def __init__(self, fields: Sequence[Field]):
        self.fields = list(fields)

    @property
    def names(self) -> List[str]:
        return [f.name for f in self.fields]

    @property
    def dtypes(self) -> List[dt.DType]:
        return [f.dtype for f in self.fields]

    @property
    def nullables(self) -> List[bool]:
        return [f.nullable for f in self.fields]

    def field(self, name: str) -> Field:
        for f in self.fields:
            if f.name == name:
                return f
        raise KeyError(name)

    def __len__(self) -> int:
        return len(self.fields)

    def __repr__(self) -> str:
        inner = ", ".join(f"{f.name}:{f.dtype.name}" for f in self.fields)
        return f"Schema({inner})"

    @staticmethod
    def from_arrow(schema: pa.Schema) -> "Schema":
        fields = []
        for f in schema:
            d = dt.from_arrow(f.type)
            if d is None:
                raise TypeError(f"unsupported Arrow type {f.type} for "
                                f"column {f.name}")
            fields.append(Field(f.name, d if d != dt.NULL else dt.BOOL,
                                f.nullable))
        return Schema(fields)


class LogicalPlan:
    children: Tuple["LogicalPlan", ...] = ()

    @property
    def schema(self) -> Schema:
        raise NotImplementedError

    def bind(self, e: ir.Expression) -> ir.Expression:
        """Bind an expression against the *child* schema."""
        s = self.children[0].schema if self.children else self.schema
        return ir.bind(e, s.names, s.dtypes, s.nullables)

    def tree_string(self, indent: int = 0) -> str:
        pad = "  " * indent
        lines = [f"{pad}{self.simple_string()}"]
        for c in self.children:
            lines.append(c.tree_string(indent + 1))
        return "\n".join(lines)

    def simple_string(self) -> str:
        return type(self).__name__


class InMemoryScan(LogicalPlan):
    def __init__(self, table: pa.Table, num_partitions: int = 1):
        self.table = table
        self.num_partitions = max(1, num_partitions)
        self._schema = Schema.from_arrow(table.schema)

    @property
    def schema(self) -> Schema:
        return self._schema

    def simple_string(self) -> str:
        return (f"InMemoryScan(rows={self.table.num_rows}, "
                f"parts={self.num_partitions})")


class FileScan(LogicalPlan):
    """Parquet/CSV/ORC file scan. Schema inferred from footer/header."""

    def __init__(self, fmt: str, paths: Sequence[str], schema: Schema,
                 options: Optional[dict] = None):
        self.fmt = fmt
        self.paths = list(paths)
        self._schema = schema
        self.options = dict(options or {})

    @property
    def schema(self) -> Schema:
        return self._schema

    def simple_string(self) -> str:
        return f"FileScan({self.fmt}, files={len(self.paths)})"


class Project(LogicalPlan):
    def __init__(self, child: LogicalPlan, exprs: Sequence[ir.Expression]):
        self.children = (child,)
        self.exprs = [self.bind(e) for e in exprs]
        self._schema = Schema([
            Field(ir.output_name(raw), b.dtype, b.nullable)
            for raw, b in zip(exprs, self.exprs)])

    @property
    def schema(self) -> Schema:
        return self._schema


class Filter(LogicalPlan):
    def __init__(self, child: LogicalPlan, condition: ir.Expression):
        self.children = (child,)
        self.condition = self.bind(condition)
        if self.condition.dtype != dt.BOOL:
            raise TypeError("filter condition must be boolean")

    @property
    def schema(self) -> Schema:
        return self.children[0].schema


@dataclass(frozen=True)
class SortOrder:
    expr: ir.Expression
    ascending: bool = True
    nulls_first: Optional[bool] = None  # default: first if asc, last if desc

    @property
    def nulls_first_resolved(self) -> bool:
        if self.nulls_first is None:
            return self.ascending
        return self.nulls_first


class Sort(LogicalPlan):
    def __init__(self, child: LogicalPlan, orders: Sequence[SortOrder]):
        self.children = (child,)
        self.orders = [SortOrder(self.bind(o.expr), o.ascending,
                                 o.nulls_first) for o in orders]

    @property
    def schema(self) -> Schema:
        return self.children[0].schema


class Aggregate(LogicalPlan):
    def __init__(self, child: LogicalPlan,
                 groupings: Sequence[ir.Expression],
                 aggregates: Sequence[ir.Expression]):
        self.children = (child,)
        self.groupings = [self.bind(g) for g in groupings]
        self.raw_groupings = list(groupings)
        self.aggregates = [self.bind(a) for a in aggregates]
        self.raw_aggregates = list(aggregates)
        fields = []
        for raw, b in zip(groupings, self.groupings):
            fields.append(Field(ir.output_name(raw), b.dtype, b.nullable))
        for raw, b in zip(aggregates, self.aggregates):
            fields.append(Field(ir.output_name(raw), b.dtype, b.nullable))
        self._schema = Schema(fields)

    @property
    def schema(self) -> Schema:
        return self._schema


class Limit(LogicalPlan):
    def __init__(self, child: LogicalPlan, n: int):
        self.children = (child,)
        self.n = int(n)

    @property
    def schema(self) -> Schema:
        return self.children[0].schema

    def simple_string(self) -> str:
        return f"Limit({self.n})"


def widen_union_branches(children: Sequence["LogicalPlan"]
                         ) -> List["LogicalPlan"]:
    """Spark's WidenSetOperationTypes: mismatched numeric columns across
    UNION branches promote to a common type via inserted cast
    projections; non-promotable mismatches raise as before."""
    schemas = [c.schema for c in children]
    n = len(schemas[0].names)
    if any(len(s.names) != n for s in schemas[1:]):
        raise TypeError("UNION requires the same column count")
    targets = []
    for i in range(n):
        t = schemas[0].dtypes[i]
        for s in schemas[1:]:
            d = s.dtypes[i]
            if d == t:
                continue
            if d.is_numeric and t.is_numeric:
                t = dt.promote(t, d)
            else:
                raise TypeError(
                    f"UNION column {schemas[0].names[i]!r}: "
                    f"incompatible types {t.name} vs {d.name}")
        targets.append(t)
    out = []
    for c, s in zip(children, schemas):
        if list(s.dtypes) == targets:
            out.append(c)
            continue
        exprs = []
        for i, name in enumerate(s.names):
            e: ir.Expression = ir.UnresolvedAttribute(name)
            if s.dtypes[i] != targets[i]:
                e = ir.Cast(e, targets[i])
            exprs.append(ir.Alias(e, name))
        out.append(Project(c, exprs))
    return out


class Union(LogicalPlan):
    def __init__(self, children: Sequence[LogicalPlan]):
        children = widen_union_branches(list(children))
        self.children = tuple(children)
        s0 = children[0].schema
        for c in children[1:]:
            if c.schema.dtypes != s0.dtypes:
                raise TypeError("UNION requires matching schemas")

    @property
    def schema(self) -> Schema:
        # a column is nullable if ANY branch's is (Spark unions
        # nullability the same way); taking branch 0's alone mis-marks
        # e.g. lit("x") UNION lit(None) as non-nullable, which breaks
        # every downstream null-aware path (sort null placement,
        # null-flag key encoding)
        s0 = self.children[0].schema
        fields = []
        for i, f in enumerate(s0.fields):
            nullable = any(c.schema.fields[i].nullable
                           for c in self.children)
            fields.append(Field(f.name, f.dtype, nullable))
        return Schema(fields)


def rewrite_distinct_aggregates(plan: LogicalPlan, groupings, exprs):
    """DISTINCT-aggregate rewrite shared by the DataFrame and SQL
    frontends (Spark's RewriteDistinctAggregates, single-distinct shape):
    dedup on (grouping keys, child) with an inner Aggregate, then
    aggregate plainly over the deduped values.

    ``exprs`` are the aggregate-bearing output expressions (plus HAVING,
    if any).  Returns (plan, groupings, exprs) — unchanged when no
    distinct aggregate is present; otherwise the inner Aggregate plan,
    name-reference groupings, and exprs with distinct stripped and
    grouping subtrees replaced by their output-name references.
    """
    all_aggs = [a for e in exprs for a in ir.collect(
        e, lambda n: isinstance(n, ir.AggregateExpression))]
    distincts = [a for a in all_aggs if getattr(a, "distinct", False)]
    if not distincts:
        return plan, groupings, exprs
    if any(a.child is None for a in distincts):
        raise ValueError("DISTINCT requires an aggregate child "
                         "expression")
    same_child = all(ir.expr_eq(a.child, distincts[0].child)
                     for a in distincts[1:])
    if not same_child or len(distincts) != len(all_aggs):
        return _rewrite_multi_distinct(plan, groupings, exprs)
    x = distincts[0].child
    xname = "__distinct_val"
    inner = Aggregate(plan, list(groupings) + [ir.Alias(x, xname)], [])
    new_groupings = [ir.UnresolvedAttribute(ir.output_name(g))
                     for g in groupings]

    def repl(node):
        for g in groupings:
            if ir.expr_eq(node, g):
                return ir.UnresolvedAttribute(ir.output_name(g))
        if isinstance(node, ir.AggregateExpression) and \
                getattr(node, "distinct", False):
            r = node.with_children([ir.UnresolvedAttribute(xname)])
            r.distinct = False
            return r
        return None

    new_exprs = [ir.transform(e, repl) for e in exprs]
    return inner, new_groupings, new_exprs


def expand_grouping_sets(plan: LogicalPlan,
                         exprs: Sequence[ir.Expression],
                         sets: Sequence[tuple]):
    """Lower rollup/cube-style grouping sets to an Expand (GpuExpandExec
    analog): one projection per set with the excluded keys nulled and a
    Spark-compatible grouping-id bitmask (bit i set = key i aggregated
    away).  Returns (expanded_plan, internal_group_refs, renames) where
    ``internal_group_refs`` are the (keys..., __gid) grouping
    expressions for the downstream Aggregate and ``renames`` maps the
    internal key names back to their public output names.  Keeping the
    gid in the grouping keys keeps natural null key values at the
    detail level from merging with subtotal rows."""
    s = plan.schema
    k = len(exprs)
    bound = [ir.bind(copy.deepcopy(e), s.names, s.dtypes, s.nullables)
             for e in exprs]
    g_internal = [f"__gset{i}" for i in range(k)]
    projections = []
    for S in sets:
        gid = sum(1 << (k - 1 - i) for i in range(k) if i not in S)
        projections.append(
            [ir.UnresolvedAttribute(n) for n in s.names] +
            [copy.deepcopy(exprs[i]) if i in S
             else ir.Literal(None, bound[i].dtype) for i in range(k)] +
            [ir.Literal(gid, dt.INT64)])
    expanded = Expand(plan, projections,
                      list(s.names) + g_internal + ["__gid"])
    refs = [ir.UnresolvedAttribute(n) for n in g_internal] + \
        [ir.UnresolvedAttribute("__gid")]
    renames = dict(zip(g_internal, [ir.output_name(e) for e in exprs]))
    return expanded, refs, renames


def _rewrite_multi_distinct(plan: LogicalPlan, groupings, exprs):
    """Expand-based multi-distinct rewrite (Spark's
    RewriteDistinctAggregates general shape,
    RewriteDistinctAggregates.scala): replicate each input row once per
    distinct-child group with a ``gid`` tag (Expand), pre-aggregate on
    (grouping keys, gid, distinct values) so each distinct value
    survives once per group, then finish with gid-filtered plain
    aggregates — ``AGG(if(gid = j, d_j, null))`` for the distinct
    functions and merge forms over the gid-0 partials for the plain
    ones (Average splits into Sum/Count partials)."""
    all_aggs = [a for e in exprs for a in ir.collect(
        e, lambda n: isinstance(n, ir.AggregateExpression))]
    distincts = [a for a in all_aggs if getattr(a, "distinct", False)]
    plains = [a for a in all_aggs if not getattr(a, "distinct", False)]
    for a in plains:
        if not isinstance(a, (ir.Count, ir.Sum, ir.Average, ir.Min,
                              ir.Max, ir.First, ir.Last)):
            raise NotImplementedError(
                f"{type(a).__name__} alongside DISTINCT aggregates is "
                f"not supported")

    # unique distinct children -> gid groups 1..k
    dchildren: List[ir.Expression] = []
    for a in distincts:
        if not any(ir.expr_eq(a.child, c) for c in dchildren):
            dchildren.append(a.child)

    g_names = [ir.output_name(g) for g in groupings]
    d_names = [f"__d{j}" for j in range(len(dchildren))]
    schema = plan.schema

    def b(e):
        return ir.bind(e, schema.names, schema.dtypes, schema.nullables)

    d_dtypes = [b(copy.deepcopy(c)).dtype for c in dchildren]
    # plain-agg inputs (Count(*) needs no input column)
    p_names: List[str] = []
    p_children: List[ir.Expression] = []
    for m, a in enumerate(plains):
        p_names.append(f"__p{m}")
        p_children.append(a.child)
    p_dtypes = [dt.INT32 if c is None else b(copy.deepcopy(c)).dtype
                for c in p_children]

    # Expand projections over [g..., gid, d..., p...]
    out_names = g_names + ["__gid"] + d_names + p_names
    projections = []
    base = [copy.deepcopy(g) for g in groupings]
    proj0 = base + [ir.Literal(0, dt.INT32)] + \
        [ir.Literal(None, d) for d in d_dtypes] + \
        [ir.Literal(1, dt.INT32) if c is None else copy.deepcopy(c)
         for c in p_children]
    projections.append(proj0)
    for j, c in enumerate(dchildren):
        projections.append(
            [copy.deepcopy(g) for g in groupings] +
            [ir.Literal(j + 1, dt.INT32)] +
            [copy.deepcopy(c) if jj == j else ir.Literal(None, d)
             for jj, d in enumerate(d_dtypes)] +
            [ir.Literal(None, d) for d in p_dtypes])
    expanded = Expand(plan, projections, out_names)

    # inner pre-aggregate: group by (g, gid, d...), partials for plains
    inner_groupings: List[ir.Expression] = [
        ir.UnresolvedAttribute(n) for n in g_names + ["__gid"] + d_names]
    inner_aggs: List[ir.Expression] = []
    buf_names: List[List[str]] = []
    for m, a in enumerate(plains):
        pm = ir.UnresolvedAttribute(p_names[m])
        if isinstance(a, ir.Count):
            # Count(*) counts the gid-0 lit(1); Count(x) counts
            # non-null x — both are Count over __pm (null elsewhere)
            bufs = [(f"__b{m}_0", ir.Count(pm))]
        elif isinstance(a, ir.Average):
            bufs = [(f"__b{m}_0", ir.Sum(pm)),
                    (f"__b{m}_1", ir.Count(pm))]
        else:
            bufs = [(f"__b{m}_0", type(a)(pm))]
        buf_names.append([n for n, _ in bufs])
        inner_aggs.extend(ir.Alias(e, n) for n, e in bufs)
    inner = Aggregate(expanded, inner_groupings, inner_aggs)

    # outer: group by g, gid-filtered aggregates
    gid = ir.UnresolvedAttribute("__gid")

    def _if_gid(j: int, value: ir.Expression, d: dt.DType):
        return ir.If(ir.EqualTo(copy.deepcopy(gid), ir.Literal(j, dt.INT32)),
                     value, ir.Literal(None, d))

    new_groupings = [ir.UnresolvedAttribute(n) for n in g_names]
    inner_schema = inner.schema

    def repl(node):
        for gi, g in enumerate(groupings):
            if ir.expr_eq(node, g):
                return ir.UnresolvedAttribute(g_names[gi])
        if isinstance(node, ir.AggregateExpression) and \
                getattr(node, "distinct", False):
            j = next(jj for jj, c in enumerate(dchildren)
                     if ir.expr_eq(node.child, c))
            r = node.with_children([_if_gid(
                j + 1, ir.UnresolvedAttribute(d_names[j]),
                d_dtypes[j])])
            r.distinct = False
            return r
        if isinstance(node, ir.AggregateExpression):
            m = next(mm for mm, a in enumerate(plains)
                     if a is node or ir.expr_eq(a, node))
            bufs = buf_names[m]

            def buf(i):
                d = inner_schema.field(bufs[i]).dtype
                return _if_gid(0, ir.UnresolvedAttribute(bufs[i]), d)
            a = plains[m]
            if isinstance(a, ir.Count):
                return ir.Sum(buf(0))
            if isinstance(a, ir.Average):
                return ir.Divide(
                    ir.Cast(ir.Sum(buf(0)), dt.FLOAT64),
                    ir.Cast(ir.Sum(buf(1)), dt.FLOAT64))
            return type(a)(buf(0))
        return None

    new_exprs = [ir.transform(e, repl) for e in exprs]
    # groupings must reach the Expand by their original shapes: alias
    # them in a pre-projection so complex grouping exprs stay intact
    return inner, new_groupings, new_exprs


def split_join_condition(condition: ir.Expression, lnames, rnames):
    """Split a boolean join condition into equi key pairs + residual.

    Conjuncts of the form ``EqualTo(left_col, right_col)`` become key
    pairs, resolved by which side owns each column name (the analyzer
    role; reference: GpuHashJoin equi keys + optional condition).  A name
    owned by both sides is ambiguous and raises.  Returns
    ``(left_keys, right_keys, residual_or_None)``.
    """
    lset, rset = set(lnames), set(rnames)
    conjuncts: List[ir.Expression] = []
    stack = [condition]
    while stack:
        c = stack.pop()
        if isinstance(c, ir.And):
            stack.extend(c.children)
        else:
            conjuncts.append(c)

    def side(e: ir.Expression) -> Optional[str]:
        names = [n.attr_name for n in ir.collect(
            e, lambda x: isinstance(x, ir.UnresolvedAttribute))]
        for n in names:
            if n in lset and n in rset:
                raise ValueError(
                    f"ambiguous column '{n}' appears on both sides of "
                    f"the join; rename one side or use a same-name "
                    f"equi key")
        if names and all(n in lset for n in names):
            return "l"
        if names and all(n in rset for n in names):
            return "r"
        return None

    left_keys: List[str] = []
    right_keys: List[str] = []
    residual: List[ir.Expression] = []
    for c in conjuncts:
        if isinstance(c, ir.EqualTo):
            a, b = c.children
            if (isinstance(a, ir.UnresolvedAttribute)
                    and isinstance(b, ir.UnresolvedAttribute)):
                sa, sb = side(a), side(b)
                if sa == "l" and sb == "r":
                    left_keys.append(a.attr_name)
                    right_keys.append(b.attr_name)
                    continue
                if sa == "r" and sb == "l":
                    left_keys.append(b.attr_name)
                    right_keys.append(a.attr_name)
                    continue
        residual.append(c)
    cond = None
    for c in residual:
        cond = c if cond is None else ir.And(cond, c)
    return left_keys, right_keys, cond


class Join(LogicalPlan):
    """Equi-join on named key pairs; how in inner/left/right/full/semi/anti,
    cross for cartesian."""

    def __init__(self, left: LogicalPlan, right: LogicalPlan,
                 left_keys: Sequence[str], right_keys: Sequence[str],
                 how: str = "inner",
                 condition: Optional[ir.Expression] = None,
                 hint: Optional[str] = None):
        self.children = (left, right)
        self.left_keys = list(left_keys)
        self.right_keys = list(right_keys)
        self.how = how
        # "broadcast_left"/"broadcast_right" (functions.broadcast analog)
        self.hint = hint
        if how != "cross" and not self.left_keys and how != "inner":
            # a keyless outer/semi/anti join is a nested-loop join with
            # outer semantics we don't implement; refusing beats silently
            # computing a cross product
            raise NotImplementedError(
                f"{how} join without keys is not supported; only "
                f"inner/cross joins may omit join keys")
        lf, rf = left.schema.fields, right.schema.fields
        # Spark promotes mismatched numeric key pairs to a common type
        # before comparing; record the promoted dtype per key pair
        self.key_dtypes = []
        for lk, rk in zip(self.left_keys, self.right_keys):
            ld = left.schema.field(lk).dtype
            rd = right.schema.field(rk).dtype
            if ld == rd:
                self.key_dtypes.append(ld)
            elif ld.is_numeric and rd.is_numeric:
                self.key_dtypes.append(dt.promote(ld, rd))
            else:
                raise TypeError(
                    f"join key type mismatch: {lk}:{ld.name} vs "
                    f"{rk}:{rd.name}")
        if how in ("semi", "anti"):
            self._schema = Schema(lf)
        else:
            nullable_l = how in ("right", "full")
            nullable_r = how in ("left", "full")
            self._schema = Schema(
                [Field(f.name, f.dtype, f.nullable or nullable_l)
                 for f in lf] +
                [Field(f.name, f.dtype, f.nullable or nullable_r)
                 for f in rf])
        self.condition = None
        if condition is not None:
            if how not in ("inner", "cross"):
                raise NotImplementedError(
                    f"join condition is only supported for inner/cross "
                    f"joins, not {how}")
            # bind against the joined output (left fields then right fields)
            joined = Schema(lf + rf)
            self.condition = ir.bind(condition, joined.names,
                                     joined.dtypes, joined.nullables)
            if self.condition.dtype != dt.BOOL:
                raise TypeError("join condition must be boolean")

    @property
    def schema(self) -> Schema:
        return self._schema

    def simple_string(self) -> str:
        return (f"Join({self.how}, {list(zip(self.left_keys, self.right_keys))})")


class CachedRelation(LogicalPlan):
    """df.cache(): the child's output materialized once as parquet blobs
    (one per partition) and served from them afterwards.

    Reference analog: ``ParquetCachedBatchSerializer``
    (shims/spark310/.../ParquetCachedBatchSerializer.scala:253 —
    ``compressColumnarBatchWithParquet`` at :333) + GpuInMemoryTableScanExec.
    Delta: blob encode happens on host via Arrow (the reference encodes on
    device via Table.writeParquetChunked); decode runs on device through
    the same pallas/XLA parquet decoder as file scans.
    """

    def __init__(self, child: LogicalPlan):
        self.children = (child,)
        self.blobs: Optional[List[bytes]] = None   # one per partition
        self.device_encoded = False

    @property
    def schema(self) -> Schema:
        return self.children[0].schema

    @property
    def materialized(self) -> bool:
        return self.blobs is not None

    def simple_string(self) -> str:
        state = "materialized" if self.materialized else "pending"
        return f"CachedRelation({state})"


class Range(LogicalPlan):
    """spark.range analog (reference: GpuRangeExec,
    basicPhysicalOperators.scala:187)."""

    def __init__(self, start: int, end: int, step: int = 1,
                 num_partitions: int = 1):
        self.start, self.end, self.step = start, end, step
        self.num_partitions = max(1, num_partitions)
        self._schema = Schema([Field("id", dt.INT64, False)])

    @property
    def schema(self) -> Schema:
        return self._schema

    def simple_string(self) -> str:
        return f"Range({self.start}, {self.end}, {self.step})"


class Window(LogicalPlan):
    """Append window-expression columns (GpuWindowExec analog).

    Output = child columns + one column per window expression, in the
    (partition, order)-sorted row order like Spark's WindowExec.
    """

    def __init__(self, child: LogicalPlan,
                 window_exprs: Sequence[ir.Expression],
                 names: Sequence[str]):
        self.children = (child,)
        self.window_exprs = [self.bind(e) for e in window_exprs]
        self.out_names = list(names)
        for e in self.window_exprs:
            if not isinstance(e, ir.WindowExpression):
                raise TypeError("Window node requires WindowExpression")
            fr = e.frame
            finite_range = fr.kind == "range" and not (
                fr.start is None and fr.end in (0, None))
            if finite_range:
                # Spark: range frames with offsets need exactly one
                # numeric/temporal ORDER BY column
                oe = e.order_exprs
                if len(oe) != 1 or oe[0].dtype is None or not (
                        oe[0].dtype.is_numeric or oe[0].dtype.is_temporal):
                    raise TypeError(
                        "RANGE frame with offsets requires exactly one "
                        "numeric or temporal ORDER BY column")
        self._schema = Schema(
            list(child.schema.fields) +
            [Field(n, e.dtype, e.nullable)
             for n, e in zip(self.out_names, self.window_exprs)])

    @property
    def schema(self) -> Schema:
        return self._schema


class Expand(LogicalPlan):
    """N projections per input row (rollup/cube building block; reference:
    GpuExpandExec.scala:67)."""

    def __init__(self, child: LogicalPlan,
                 projections: Sequence[Sequence[ir.Expression]],
                 names: Sequence[str]):
        self.children = (child,)
        self.projections = [[self.bind(e) for e in p] for p in projections]
        p0 = self.projections[0]
        self._schema = Schema([
            Field(n, b.dtype, True) for n, b in zip(names, p0)])

    @property
    def schema(self) -> Schema:
        return self._schema


class Repartition(LogicalPlan):
    """Explicit exchange: df.repartition(n[, cols]) / repartitionByRange /
    coalesce.  kind in {"hash", "range", "roundrobin", "single"}.

    Planned as a ShuffleExchangeExec (reference:
    GpuShuffleExchangeExec.scala:143 + the four partitionings §2d)."""

    def __init__(self, child: LogicalPlan, kind: str, num_partitions: int,
                 exprs: Sequence[ir.Expression] = (),
                 orders: Sequence[SortOrder] = ()):
        self.children = (child,)
        self.kind = kind
        self.num_partitions = max(1, int(num_partitions))
        self.exprs = [self.bind(e) for e in exprs]
        self.orders = [SortOrder(self.bind(o.expr), o.ascending,
                                 o.nulls_first) for o in orders]

    @property
    def schema(self) -> Schema:
        return self.children[0].schema

    def simple_string(self) -> str:
        return f"Repartition({self.kind}, n={self.num_partitions})"


def size_estimate(node: LogicalPlan) -> int:
    """Rough plan-size statistic in bytes, for broadcast-join selection
    (the role of Spark's plan statistics feeding
    spark.sql.autoBroadcastJoinThreshold)."""
    import os
    if isinstance(node, InMemoryScan):
        return node.table.nbytes
    if isinstance(node, FileScan):
        total = 0
        for p in node.paths:
            try:
                total += os.path.getsize(p)
            except OSError:
                return 1 << 62
        # parquet/orc are compressed on disk; assume 3x in-memory growth
        return total * (3 if node.fmt in ("parquet", "orc") else 1)
    if isinstance(node, Range):
        step = node.step if node.step else 1
        n = (node.end - node.start + step + (-1 if step > 0 else 1)) // step
        return max(0, n) * 8
    if isinstance(node, Filter):
        return size_estimate(node.children[0]) // 2
    if isinstance(node, (Aggregate, Limit)):
        return size_estimate(node.children[0]) // 2
    if isinstance(node, Join):
        return sum(size_estimate(c) for c in node.children)
    if not node.children:
        return 1 << 62
    return max(size_estimate(c) for c in node.children)


class Generate(LogicalPlan):
    """Row-generating node for explode/posexplode (reference:
    GpuGenerateExec.scala:101 — per-row list explode).

    Output = child columns + generated columns (``pos`` first for
    posexplode, then the element column)."""

    def __init__(self, child: LogicalPlan, generator: ir.Generator,
                 out_names: Sequence[str]):
        self.children = (child,)
        g = self.bind(generator)
        if g.children[0].dtype is None or not g.children[0].dtype.is_list:
            raise TypeError("explode/posexplode requires an array column")
        self.generator = g
        self.out_names = list(out_names)
        gen_fields = []
        if isinstance(g, ir.PosExplode):
            gen_fields.append(Field(self.out_names[0], dt.INT32, False))
            gen_fields.append(Field(self.out_names[1],
                                    g.children[0].dtype.element, True))
        else:
            gen_fields.append(Field(self.out_names[0],
                                    g.children[0].dtype.element, True))
        self._schema = Schema(list(child.schema.fields) + gen_fields)

    @property
    def schema(self) -> Schema:
        return self._schema

    def simple_string(self) -> str:
        return f"Generate({type(self.generator).__name__})"


class CoalescePartitions(LogicalPlan):
    """df.coalesce(n): merge contiguous partitions without a shuffle
    (reference: GpuCoalesceExec, basicPhysicalOperators.scala:346)."""

    def __init__(self, child: LogicalPlan, num_partitions: int):
        self.children = (child,)
        self.num_partitions = max(1, int(num_partitions))

    @property
    def schema(self) -> Schema:
        return self.children[0].schema

    def simple_string(self) -> str:
        return f"CoalescePartitions({self.num_partitions})"


# ---------------------------------------------------------------------------
# Pandas-UDF nodes (reference: SURVEY.md §2d Pandas/Python execs,
# sql-plugin/.../execution/python/*)
# ---------------------------------------------------------------------------

def _parse_udf_schema(schema) -> Schema:
    """Accept a Schema, a pyarrow.Schema, or a list of (name, DType)."""
    if isinstance(schema, Schema):
        return schema
    if isinstance(schema, pa.Schema):
        return Schema.from_arrow(schema)
    return Schema([Field(n, d, True) for n, d in schema])


class MapInPandas(LogicalPlan):
    """df.map_in_pandas(fn, schema) — GpuMapInPandasExec analog."""

    def __init__(self, child: LogicalPlan, fn, schema):
        self.children = (child,)
        self.fn = fn
        self._schema = _parse_udf_schema(schema)

    @property
    def schema(self) -> Schema:
        return self._schema


class FlatMapGroupsInPandas(LogicalPlan):
    """group_by(keys).apply_in_pandas(fn, schema) —
    GpuFlatMapGroupsInPandasExec analog."""

    def __init__(self, child: LogicalPlan, keys: Sequence[str], fn, schema):
        self.children = (child,)
        for k in keys:
            child.schema.field(k)  # raises KeyError if missing
        self.keys = list(keys)
        self.fn = fn
        self._schema = _parse_udf_schema(schema)

    @property
    def schema(self) -> Schema:
        return self._schema


class CoGroupedMapInPandas(LogicalPlan):
    """cogroup(...).apply_in_pandas(fn, schema) —
    GpuFlatMapCoGroupsInPandasExec analog."""

    def __init__(self, left: LogicalPlan, right: LogicalPlan,
                 left_keys: Sequence[str], right_keys: Sequence[str],
                 fn, schema):
        if len(left_keys) != len(right_keys):
            raise ValueError("cogroup key lists must have equal length")
        self.children = (left, right)
        self.left_keys = list(left_keys)
        self.right_keys = list(right_keys)
        self.fn = fn
        self._schema = _parse_udf_schema(schema)

    @property
    def schema(self) -> Schema:
        return self._schema


class AggregateInPandas(LogicalPlan):
    """group_by(keys).agg_in_pandas(fn, args, name, dtype) —
    GpuAggregateInPandasExec analog."""

    def __init__(self, child: LogicalPlan, keys: Sequence[str], fn,
                 args: Sequence[ir.Expression], out_name: str,
                 out_dtype: dt.DType):
        self.children = (child,)
        self.keys = list(keys)
        self.fn = fn
        self.args = [self.bind(a) for a in args]
        self.out_field = Field(out_name, out_dtype, True)
        self._schema = Schema(
            [child.schema.field(k) for k in self.keys] + [self.out_field])

    @property
    def schema(self) -> Schema:
        return self._schema


class WindowInPandas(LogicalPlan):
    """Unbounded-frame pandas window UDF — GpuWindowInPandasExec analog."""

    def __init__(self, child: LogicalPlan, part_keys: Sequence[str], fn,
                 args: Sequence[ir.Expression], out_name: str,
                 out_dtype: dt.DType):
        self.children = (child,)
        for k in part_keys:
            child.schema.field(k)
        self.part_keys = list(part_keys)
        self.fn = fn
        self.args = [self.bind(a) for a in args]
        self.out_field = Field(out_name, out_dtype, True)
        self._schema = Schema(list(child.schema.fields) + [self.out_field])

    @property
    def schema(self) -> Schema:
        return self._schema
