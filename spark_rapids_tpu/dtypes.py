"""Logical SQL dtypes and the dtype bridge: SQL <-> Arrow <-> JAX/XLA.

TPU analog of the reference's cudf<->Spark type map
(reference: sql-plugin/src/main/java/com/nvidia/spark/rapids/GpuColumnVector.java:153-197)
and the central type-support gate ``isSupportedType``
(reference: GpuOverrides.scala:459-504 — no decimal/binary/calendar-interval/
nested by default; timestamps UTC-only, GpuOverrides.scala:490).

On TPU, device columns are jax arrays:
  * numeric/bool/date/timestamp -> 1-D array of the mapped jnp dtype
  * string -> (uint8 [rows, max_len] byte matrix, int32 [rows] lengths)

Timestamps are int64 microseconds since epoch UTC; dates are int32 days since
epoch — identical to Arrow's ``timestamp[us, UTC]`` / ``date32`` physical
layout, so host<->device conversion is a reinterpret, not a convert.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass
from typing import Optional

import numpy as np
import pyarrow as pa


class TypeId(enum.Enum):
    BOOL = "boolean"
    INT8 = "tinyint"
    INT16 = "smallint"
    INT32 = "int"
    INT64 = "bigint"
    FLOAT32 = "float"
    FLOAT64 = "double"
    STRING = "string"
    DATE32 = "date"
    TIMESTAMP_US = "timestamp"
    LIST = "array"
    MAP = "map"
    NULL = "void"


@dataclass(frozen=True)
class DType:
    id: TypeId
    # element type for LIST; (key, value) live in element/value for MAP
    element: Optional["DType"] = None
    value: Optional["DType"] = None

    @property
    def name(self) -> str:
        if self.id == TypeId.LIST:
            return f"array<{self.element.name}>"
        if self.id == TypeId.MAP:
            return f"map<{self.element.name},{self.value.name}>"
        return self.id.value

    # -- classification -----------------------------------------------------
    @property
    def is_numeric(self) -> bool:
        return self.id in (TypeId.INT8, TypeId.INT16, TypeId.INT32,
                           TypeId.INT64, TypeId.FLOAT32, TypeId.FLOAT64)

    @property
    def is_integral(self) -> bool:
        return self.id in (TypeId.INT8, TypeId.INT16, TypeId.INT32, TypeId.INT64)

    @property
    def is_floating(self) -> bool:
        return self.id in (TypeId.FLOAT32, TypeId.FLOAT64)

    @property
    def is_string(self) -> bool:
        return self.id == TypeId.STRING

    @property
    def is_temporal(self) -> bool:
        return self.id in (TypeId.DATE32, TypeId.TIMESTAMP_US)

    @property
    def is_bool(self) -> bool:
        return self.id == TypeId.BOOL

    @property
    def is_list(self) -> bool:
        return self.id == TypeId.LIST

    @property
    def is_map(self) -> bool:
        return self.id == TypeId.MAP

    @property
    def is_nested(self) -> bool:
        return self.id in (TypeId.LIST, TypeId.MAP)

    @property
    def has_lengths(self) -> bool:
        """Device layout uses (2-D padded payload, per-row lengths)."""
        return self.id == TypeId.STRING or self.id == TypeId.LIST

    # -- physical mapping ----------------------------------------------------
    def to_np(self) -> np.dtype:
        """Numpy/JAX physical dtype of the data buffer (the padded element
        payload for STRING/LIST)."""
        if self.id == TypeId.LIST:
            return self.element.to_np()
        return _NP_MAP[self.id]

    def to_arrow(self) -> pa.DataType:
        if self.id == TypeId.LIST:
            return pa.list_(self.element.to_arrow())
        if self.id == TypeId.MAP:
            return pa.map_(self.element.to_arrow(), self.value.to_arrow())
        return _ARROW_MAP[self.id]

    @property
    def byte_width(self) -> int:
        if self.id == TypeId.STRING:
            return 16  # planning estimate; actual is data-dependent
        if self.id == TypeId.LIST:
            return self.element.byte_width * 8
        if self.id == TypeId.MAP:
            return (self.element.byte_width + self.value.byte_width) * 8
        return _NP_MAP[self.id].itemsize

    def __repr__(self) -> str:
        return f"DType({self.name})"


def list_of(element: DType) -> DType:
    return DType(TypeId.LIST, element=element)


def map_of(key: DType, value: DType) -> DType:
    return DType(TypeId.MAP, element=key, value=value)


BOOL = DType(TypeId.BOOL)
INT8 = DType(TypeId.INT8)
INT16 = DType(TypeId.INT16)
INT32 = DType(TypeId.INT32)
INT64 = DType(TypeId.INT64)
FLOAT32 = DType(TypeId.FLOAT32)
FLOAT64 = DType(TypeId.FLOAT64)
STRING = DType(TypeId.STRING)
DATE32 = DType(TypeId.DATE32)
TIMESTAMP_US = DType(TypeId.TIMESTAMP_US)
NULL = DType(TypeId.NULL)

ALL_TYPES = [BOOL, INT8, INT16, INT32, INT64, FLOAT32, FLOAT64, STRING,
             DATE32, TIMESTAMP_US]

_NP_MAP = {
    TypeId.BOOL: np.dtype(np.bool_),
    TypeId.INT8: np.dtype(np.int8),
    TypeId.INT16: np.dtype(np.int16),
    TypeId.INT32: np.dtype(np.int32),
    TypeId.INT64: np.dtype(np.int64),
    TypeId.FLOAT32: np.dtype(np.float32),
    TypeId.FLOAT64: np.dtype(np.float64),
    TypeId.STRING: np.dtype(np.uint8),   # byte matrix payload
    TypeId.DATE32: np.dtype(np.int32),
    TypeId.TIMESTAMP_US: np.dtype(np.int64),
    TypeId.NULL: np.dtype(np.bool_),
}

_ARROW_MAP = {
    TypeId.BOOL: pa.bool_(),
    TypeId.INT8: pa.int8(),
    TypeId.INT16: pa.int16(),
    TypeId.INT32: pa.int32(),
    TypeId.INT64: pa.int64(),
    TypeId.FLOAT32: pa.float32(),
    TypeId.FLOAT64: pa.float64(),
    TypeId.STRING: pa.string(),
    TypeId.DATE32: pa.date32(),
    TypeId.TIMESTAMP_US: pa.timestamp("us", tz="UTC"),
    TypeId.NULL: pa.null(),
}


def from_arrow(t: pa.DataType) -> Optional[DType]:
    """Map an Arrow type to a logical DType; None if unsupported.

    The None path is the analog of ``isSupportedType`` returning false
    (reference: GpuOverrides.scala:459-504): decimal, binary, nested, and
    non-UTC timestamps are unsupported and force CPU fallback.
    """
    if pa.types.is_boolean(t):
        return BOOL
    if pa.types.is_int8(t):
        return INT8
    if pa.types.is_int16(t):
        return INT16
    if pa.types.is_int32(t):
        return INT32
    if pa.types.is_int64(t):
        return INT64
    if pa.types.is_float32(t):
        return FLOAT32
    if pa.types.is_float64(t):
        return FLOAT64
    if pa.types.is_string(t) or pa.types.is_large_string(t):
        return STRING
    if pa.types.is_date32(t):
        return DATE32
    if pa.types.is_timestamp(t):
        if t.unit == "us" and t.tz in (None, "UTC"):
            return TIMESTAMP_US
        return None  # non-UTC / non-us timestamps unsupported (UTC-only rule)
    if pa.types.is_null(t):
        return NULL
    if pa.types.is_list(t) or pa.types.is_large_list(t):
        el = from_arrow(t.value_type)
        if el is None or el.is_nested:
            return None  # only one nesting level (reference rejects deeper)
        return list_of(el)
    if pa.types.is_map(t):
        k = from_arrow(t.key_type)
        v = from_arrow(t.item_type)
        if k is None or v is None or k.is_nested or v.is_nested:
            return None
        return map_of(k, v)
    return None


def device_supported(d: DType) -> bool:
    """Can this dtype live in a DeviceBatch?  Lists of fixed-width
    primitives share the string layout (padded 2-D payload + lengths);
    lists of strings and maps are host-only (CPU fallback)."""
    if d.is_map:
        return False
    if d.is_list:
        return d.element is not None and (d.element.is_numeric or
                                          d.element.is_bool)
    return d in ALL_TYPES


# numeric promotion ladder for binary arithmetic (Spark's semantics)
_PROMOTE_ORDER = [INT8, INT16, INT32, INT64, FLOAT32, FLOAT64]


def promote(a: DType, b: DType) -> DType:
    """Binary-op result type for two numeric types (Spark promotion rules)."""
    if a == b:
        return a
    if not (a.is_numeric and b.is_numeric):
        raise TypeError(f"cannot promote {a} and {b}")
    # int64 + float32 -> float64 in Spark (to preserve precision-ish)
    pair = {a.id, b.id}
    if TypeId.FLOAT32 in pair and TypeId.INT64 in pair:
        return FLOAT64
    ia, ib = _PROMOTE_ORDER.index(a), _PROMOTE_ORDER.index(b)
    return _PROMOTE_ORDER[max(ia, ib)]
