"""Flight recorder: a bounded ring of recent engine events plus
self-contained diagnostic bundles on query failure.

A long-lived serving engine is undebuggable post-hoc: when a query
dies, the context that explains it (what admission decided, what
spilled, whether the device OOM-retried) died with it.  The recorder
keeps the last ``obs.recorder.maxEvents`` engine events in memory —
scheduler admission decisions, spill/arena traffic, OOM retries,
donation disarms, query lifecycle marks — and on query **failure,
timeout, or cancellation** (via the QueryExecutionListener failure
path) writes a self-contained bundle to ``obs.recorder.dir``:

  ``<dir>/q<id>-<reason>-<YYYYmmdd-HHMMSS>-p<pid>-<n>/``
      ``profile.json``   the query's QueryProfile (plan, metrics, spans)
      ``trace.json``     the query's span window as a Chrome trace
      ``events.jsonl``   the event ring (one JSON object per line)
      ``config.json``    the session conf snapshot
      ``registry.json``  the full MetricsRegistry snapshot at dump time

A *successful* query that needed an HBM OOM-retry (``mem.oomRetries``
moved) also dumps a bundle — a query that only survived by evicting
the whole device tier is a diagnosis waiting to happen.

Disabled path: ``record_event`` is a module function behind one bool
check — with no ``obs.recorder.dir`` configured the hooks in
admission/spill/session cost nothing measurable.  Configuration is
process-wide, last session wins (the trace/scan-cache configure
idiom).
"""

from __future__ import annotations

import itertools
import json
import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

from spark_rapids_tpu.obs import registry as obsreg
from spark_rapids_tpu.obs import trace as obstrace

DEFAULT_MAX_EVENTS = 4096

_enabled = False
_RECORDER: Optional["FlightRecorder"] = None
_LOCK = threading.Lock()


def record_event(kind: str, **fields: Any) -> None:
    """Append one event to the recorder ring.  One bool check when the
    recorder is disabled (the hot-path contract shared with
    trace.record)."""
    if not _enabled:
        return
    r = _RECORDER
    if r is not None:
        r.record(kind, fields)


def is_enabled() -> bool:
    return _enabled


def get_recorder() -> Optional["FlightRecorder"]:
    return _RECORDER


def configure(out_dir: str, max_events: int = DEFAULT_MAX_EVENTS,
              config_snapshot: Optional[Dict[str, Any]] = None
              ) -> "FlightRecorder":
    """Install the process-wide recorder (session init; last session
    wins)."""
    global _enabled, _RECORDER
    with _LOCK:
        _RECORDER = FlightRecorder(out_dir, max_events=max_events,
                                   config_snapshot=config_snapshot)
        _enabled = True
        return _RECORDER


def disable() -> None:
    global _enabled, _RECORDER
    with _LOCK:
        _enabled = False
        _RECORDER = None


def _classify(exc: Optional[BaseException]) -> str:
    """Bundle reason from the failure exception, by type NAME so the
    obs layer stays import-leaf (sched imports obs, never the
    reverse)."""
    if exc is None:
        return "oom-retry"
    names = {c.__name__ for c in type(exc).__mro__}
    if "QueryRejectedError" in names:
        return "rejected"         # refused before admission (queue full)
    if "QueryTimeoutError" in names:
        return "timeout"
    if "QueryCancelledError" in names:
        return "cancelled"
    return "failure"


class FlightRecorder:
    """Bounded event ring + bundle writer; doubles as a
    QueryExecutionListener (obs/listener.py duck type) so the session's
    existing failure fan-out is the wiring."""

    def __init__(self, out_dir: str,
                 max_events: int = DEFAULT_MAX_EVENTS,
                 config_snapshot: Optional[Dict[str, Any]] = None):
        self.out_dir = str(out_dir)
        self._ring: deque = deque(maxlen=max(16, int(max_events)))
        self._lock = threading.Lock()
        self._bundle_seq = itertools.count(1)
        self._config_snapshot = dict(config_snapshot or {})
        # oom-retry watermark: a success whose window moved this
        # counter still gets a bundle (localization, not accounting —
        # the registry-delta contract)
        self._oom_seen = obsreg.get_registry().counter("mem.oomRetries")
        self.last_bundle_path: Optional[str] = None

    # -- the ring ----------------------------------------------------------
    def record(self, kind: str, fields: Dict[str, Any]) -> None:
        evt = {"ts_unix": time.time(),
               "t_ns": time.perf_counter_ns(),
               "kind": kind}
        if fields:
            evt.update(fields)
        with self._lock:
            self._ring.append(evt)

    def events(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._ring)

    # -- listener surface --------------------------------------------------
    def _stale(self) -> bool:
        """True once a LATER session reconfigured/disabled the
        process-wide recorder: this instance's listener may still be
        registered on its own session, but its frozen event ring would
        produce a bundle that misleadingly claims to show recent engine
        activity — stand down instead."""
        return get_recorder() is not self

    def on_success(self, profile) -> None:
        if self._stale():
            return
        reg = obsreg.get_registry()
        oom = reg.counter("mem.oomRetries")
        if oom > self._oom_seen:
            self._oom_seen = oom
            self.dump_bundle(profile, reason="oom-retry")

    def on_failure(self, profile, exception: BaseException) -> None:
        if self._stale():
            return
        self._oom_seen = obsreg.get_registry().counter("mem.oomRetries")
        self.dump_bundle(profile, reason=_classify(exception))

    # -- the bundle --------------------------------------------------------
    def dump_bundle(self, profile, reason: str = "failure",
                    extra: Optional[Dict[str, Any]] = None) -> str:
        """Write one self-contained diagnostic bundle; returns its
        directory.  An IO error here cannot fail the query: the
        listener fan-out (obs/listener.notify) swallows listener
        exceptions by contract.  ``extra``, when given, lands in
        ``sentinel.json`` — the drift sentinel attaches the breached
        window and its ledger top-talkers there."""
        qid = getattr(profile, "query_id", 0)
        # name must be unique ACROSS engine restarts: query ids and the
        # bundle counter both restart at 1 per process, and a flight
        # recorder that overwrites the previous crash's bundle destroys
        # exactly what it exists to preserve
        stamp = time.strftime("%Y%m%d-%H%M%S")
        name = (f"q{int(qid):05d}-{reason}-{stamp}"
                f"-p{os.getpid()}-{next(self._bundle_seq)}")
        bundle = os.path.join(self.out_dir, name)
        os.makedirs(bundle, exist_ok=True)

        def dump(fname: str, obj: Any) -> None:
            with open(os.path.join(bundle, fname), "w") as f:
                json.dump(obj, f, indent=2, default=str)

        dump("profile.json",
             profile.to_dict() if profile is not None else None)
        dump("trace.json", obstrace.chrome_trace(
            getattr(profile, "_raw_spans", []) or []))
        with open(os.path.join(bundle, "events.jsonl"), "w") as f:
            for evt in self.events():
                f.write(json.dumps(evt, default=str) + "\n")
        dump("config.json", self._config_snapshot)
        dump("registry.json", obsreg.get_registry().snapshot())
        if extra is not None:
            dump("sentinel.json", extra)
        self.record("recorder.bundle", {"path": bundle,
                                        "reason": reason,
                                        "query": qid})
        obsreg.get_registry().inc("recorder.bundles")
        self.last_bundle_path = bundle
        return bundle
