"""Low-overhead span tracer with a Chrome trace-event exporter.

Design constraints (the engine's hot paths run this per batch):

  * **Zero-allocation no-op path when disabled** — ``span()`` returns a
    shared singleton context manager and ``record()`` returns
    immediately; the only cost is one module-global bool check.
  * **Bounded memory** — spans land in a ring buffer
    (``collections.deque`` with ``maxlen``); when a query outruns the
    buffer the oldest spans drop, never the process.
  * **Thread-safe** — partition iterators drain on the task pool and
    prefetch threads record concurrently; ``deque.append`` is atomic
    and the monotonic sequence counter hands out carve marks.

Spans are recorded at *exit* with monotonic-ns timestamps (so recording
order is children-before-parents); the Chrome exporter re-derives the
nesting per thread from the intervals and emits matched ``B``/``E``
event pairs a Perfetto / chrome://tracing load renders as a flame
graph.
"""

from __future__ import annotations

import itertools
import json
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Sequence, Tuple

DEFAULT_BUFFER_SPANS = 65536

# one span record:
#   (seq, tid, name, cat, t0_ns, dur_ns, depth, args)
Span = Tuple[int, int, str, str, int, int, int, Optional[Dict[str, Any]]]

_enabled = False
_ring: deque = deque(maxlen=DEFAULT_BUFFER_SPANS)
_seq = itertools.count()
_lock = threading.Lock()
_tls = threading.local()

# cross-process stitching state: synthetic lane ids for spans merged
# from other processes (executor map stages), plus human labels the
# Chrome exporter renders as thread_name metadata.  Real tids are
# CPython thread idents (pthread pointers, far above this range), so
# small synthetic ids cannot collide with them.  Bounded: labels embed
# executor pids, so a long-lived driver restarting pools mints fresh
# keys — past _MAX_LANES the oldest mapping evicts (its spans keep the
# label in args["lane"]; only the chrome thread_name metadata for a
# lane that old is lost).
_MAX_LANES = 1024
_lane_ids = itertools.count(1)
_lane_map: Dict[Tuple[str, int], int] = {}   # (label, foreign tid) -> lane
_lane_counts: Dict[str, int] = {}            # label -> lanes minted
_tid_labels: Dict[int, str] = {}


def configure(enabled: bool, buffer_spans: Optional[int] = None) -> None:
    """Process-wide tracer switch (called by TpuSparkSession from the
    ``spark.rapids.tpu.obs.trace.*`` knobs; last session wins, the
    scan-cache ``configure`` idiom)."""
    global _enabled, _ring
    with _lock:
        if buffer_spans is not None and \
                int(buffer_spans) != (_ring.maxlen or 0):
            _ring = deque(_ring, maxlen=max(16, int(buffer_spans)))
        _enabled = bool(enabled)


def is_enabled() -> bool:
    return _enabled


def clear() -> None:
    with _lock:
        _ring.clear()


def mark() -> int:
    """Monotonic carve mark: ``spans_since(mark())`` returns only spans
    recorded after this call (per-query span windows)."""
    return next(_seq)


def record(name: str, t0_ns: int, dur_ns: int, cat: str = "exec",
           args: Optional[Dict[str, Any]] = None,
           depth: Optional[int] = None) -> None:
    """Record one completed span. No-op (one bool check) when disabled."""
    if not _enabled:
        return
    if depth is None:
        depth = getattr(_tls, "depth", 0)
    _ring.append((next(_seq), threading.get_ident(), name, cat,
                  int(t0_ns), int(dur_ns), depth, args))


class _NoopSpan:
    """Shared do-nothing context manager for the disabled path."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *a):
        return False


_NOOP = _NoopSpan()


class _Span:
    __slots__ = ("name", "cat", "args", "t0", "_depth")

    def __init__(self, name: str, cat: str,
                 args: Optional[Dict[str, Any]]):
        self.name = name
        self.cat = cat
        self.args = args

    def __enter__(self):
        d = getattr(_tls, "depth", 0)
        self._depth = d + 1
        _tls.depth = self._depth
        self.t0 = time.perf_counter_ns()
        return self

    def __exit__(self, *a):
        dur = time.perf_counter_ns() - self.t0
        _tls.depth = self._depth - 1
        record(self.name, self.t0, dur, self.cat, self.args,
               depth=self._depth)
        return False


def span(name: str, cat: str = "exec",
         args: Optional[Dict[str, Any]] = None):
    """``with span("scan.decode"):`` — a nested, thread-local span.
    Returns the shared no-op singleton when tracing is disabled."""
    if not _enabled:
        return _NOOP
    return _Span(name, cat, args)


def record_foreign(spans: Sequence[Span], offset_ns: int,
                   label: str) -> int:
    """Merge spans recorded in ANOTHER process into this ring (the
    cross-process trace stitch): each foreign timestamp is shifted by
    ``offset_ns`` (foreign clock -> this process's perf_counter_ns
    domain, aligned by the caller from the request/reply envelope) and
    each foreign thread maps to a stable synthetic lane labeled
    ``label`` (``label/t0``, ``label/t1``, ... when the foreign process
    used several threads) that the Chrome exporter names via
    thread_name metadata — executor map stages render as their own
    lanes in Perfetto.  Returns the number of spans merged.  No-op when
    tracing is disabled."""
    if not _enabled or not spans:
        return 0
    n = 0
    with _lock:
        for s in spans:
            seq_, ftid, name, cat, t0, dur, depth, args = s
            key = (label, ftid)
            lane = _lane_map.get(key)
            if lane is None:
                lane = next(_lane_ids)
                _lane_map[key] = lane
                nth = _lane_counts.get(label, 0)
                _lane_counts[label] = nth + 1
                _tid_labels[lane] = (label if nth == 0
                                     else f"{label}/t{nth}")
                while len(_lane_map) > _MAX_LANES:
                    old_key = next(iter(_lane_map))
                    _tid_labels.pop(_lane_map.pop(old_key), None)
                    # drop a label's mint counter with its last lane —
                    # labels embed executor pids, so a long-lived
                    # driver would otherwise leak one counter per pool
                    # generation forever
                    old_label = old_key[0]
                    if all(k[0] != old_label for k in _lane_map):
                        _lane_counts.pop(old_label, None)
            a = dict(args) if args else {}
            a.setdefault("lane", _tid_labels[lane])
            _ring.append((next(_seq), lane, name, cat,
                          int(t0) + int(offset_ns), int(dur),
                          int(depth), a))
            n += 1
    return n


def lane_label(tid: int) -> Optional[str]:
    """Human label of a synthetic (stitched) lane; None for real
    threads."""
    return _tid_labels.get(tid)


def snapshot() -> List[Span]:
    with _lock:
        return list(_ring)


def spans_since(seq_mark: int) -> List[Span]:
    return [s for s in snapshot() if s[0] >= seq_mark]


def span_dicts(spans: Sequence[Span]) -> List[Dict[str, Any]]:
    """JSON-friendly rendering (the QueryProfile ``spans`` section)."""
    out = []
    for seq, tid, name, cat, t0, dur, depth, args in spans:
        d = {"name": name, "cat": cat, "tid": tid, "ts_ns": t0,
             "dur_ns": dur, "depth": depth}
        if args:
            d["args"] = args
        out.append(d)
    return out


# ---------------------------------------------------------------------------
# Chrome trace-event export
# ---------------------------------------------------------------------------

def chrome_trace(spans: Optional[Sequence[Span]] = None
                 ) -> Dict[str, Any]:
    """Render spans as a Chrome trace-event object (the ``traceEvents``
    duration-event format: matched ``B``/``E`` pairs, ``ts`` in
    microseconds).

    Spans were recorded at exit (children before parents), so per
    thread the nesting forest is rebuilt from the intervals: pre-order
    sort ``(t0, -t1, seq)``, then an explicit stack walk emits every
    ``E`` exactly when the next span starts outside it — matched pairs
    by construction, properly nested for stack-based (per-thread)
    producers."""
    if spans is None:
        spans = snapshot()
    events: List[Dict[str, Any]] = []
    by_tid: Dict[int, List[Span]] = {}
    for s in spans:
        by_tid.setdefault(s[1], []).append(s)
    # stitched executor lanes get their human name (thread_name
    # metadata events — Perfetto renders the label on the lane)
    for tid in sorted(by_tid):
        label = _tid_labels.get(tid)
        if label is not None:
            events.append({"name": "thread_name", "ph": "M", "pid": 0,
                           "tid": tid, "args": {"name": label}})
    for tid, ss in sorted(by_tid.items()):
        ivs = sorted(((s[4], s[4] + s[5], s[0], s) for s in ss),
                     key=lambda x: (x[0], -x[1], x[2]))
        stack: List[Tuple[int, int, int, Span]] = []

        def emit(ph: str, s: Span, ts_ns: int) -> None:
            ev = {"name": s[2], "cat": s[3], "ph": ph, "pid": 0,
                  "tid": tid, "ts": ts_ns / 1e3}
            if ph == "B" and s[7]:
                ev["args"] = s[7]
            events.append(ev)

        for t0, t1, _seq, s in ivs:
            while stack and stack[-1][1] <= t0:
                pt0, pt1, _pseq, ps = stack.pop()
                emit("E", ps, pt1)
            emit("B", s, t0)
            stack.append((t0, t1, _seq, s))
        while stack:
            pt0, pt1, _pseq, ps = stack.pop()
            emit("E", ps, pt1)
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def dump_chrome_trace(path: str,
                      spans: Optional[Sequence[Span]] = None) -> str:
    """Write the Chrome trace JSON to ``path`` (open it in Perfetto or
    chrome://tracing).  Returns the path."""
    with open(path, "w") as f:
        json.dump(chrome_trace(spans), f)
    return path
