"""Drift sentinel: a background watcher that notices the engine
getting worse before a human reads ``BENCH_trend.json``.

On a fixed interval (``obs.sentinel.intervalMs``) the sentinel
snapshots the process MetricsRegistry, forms the **window delta**
against the previous tick, and evaluates a rule set against a trailing
EWMA baseline (updated only on healthy windows, so a regression cannot
poison its own reference):

  ``latency``    windowed p95 of ``slo.latencyMs`` (interpolated from
                 the bucketed histogram's count deltas) exceeds
                 ``factor`` x baseline — the p95 regression rule.
  ``slow``       ``obs.slowQueries`` window count spikes past
                 ``factor`` x baseline rate.
  ``cacheHit``   result-cache hit rate (hits / (hits+misses) in the
                 window) collapses below ``drop`` x baseline.
  ``compile``    ``kernel.cache.compiles`` window count spikes — the
                 compile-storm rule (shape churn, cache wipe).
  ``spill``      ``spill.deviceToHostBytes`` window bytes surge.

A rule must breach ``sustain`` consecutive windows before it fires —
one noisy window is weather, a streak is drift.  Firing opens an
**episode**: exactly one flight-recorder bundle (reason ``"slo"``)
with the breached window, rule verdicts, and the ledger's window
top-talkers attached (``sentinel.json``), one
``obs.sentinel.breaches`` / ``obs.sentinel.breaches.<rule>`` counter
increment, and one structured JSONL line (size-rotated, the
slow-query-log writer).  The episode closes when the rule goes a full
window without breaching; only then can it fire again.

Rules grammar (``obs.sentinel.rules``): semicolon-separated
``rule:key=val,key=val`` specs — ``"latency:factor=2,sustain=2"``
enables ONLY the latency rule with those overrides; the empty string
enables every rule at defaults.

Disabled (``obs.sentinel.enabled=false``, the default): nothing is
constructed and no thread runs — the one-bool contract.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Any, Dict, List, Optional

from spark_rapids_tpu.obs import accounting as acct
from spark_rapids_tpu.obs import jsonl as obsjsonl
from spark_rapids_tpu.obs import recorder as obsrec
from spark_rapids_tpu.obs import registry as obsreg

# per-rule defaults; every value is overridable from the rules spec
DEFAULT_RULES: Dict[str, Dict[str, float]] = {
    # windowed p95 latency > factor x EWMA baseline (and > floor_ms,
    # so microsecond workloads can't alarm on scheduler jitter)
    "latency": {"factor": 2.0, "min": 4, "floor_ms": 5.0, "sustain": 2},
    # slow-query count spike: >= min in the window AND > factor x
    # baseline window rate
    "slow": {"factor": 2.0, "min": 3, "sustain": 2},
    # hit-rate collapse: window rate < drop x baseline rate, with at
    # least min lookups in the window
    "cacheHit": {"drop": 0.5, "min": 8, "sustain": 2},
    # compile storm: fresh-compile count spike
    "compile": {"factor": 3.0, "min": 8, "sustain": 2},
    # spill surge: device->host bytes spike
    "spill": {"factor": 3.0, "min": float(1 << 20), "sustain": 2},
}

_EWMA_ALPHA = 0.3


def parse_rules(spec: str) -> Dict[str, Dict[str, float]]:
    """``"latency:factor=2;slow"`` -> enabled-rule dict with defaults
    merged.  Empty spec = all rules at defaults.  Unknown rule names
    and malformed pairs raise ``ValueError`` (a config typo must not
    silently disable the watcher)."""
    spec = (spec or "").strip()
    if not spec:
        return {k: dict(v) for k, v in DEFAULT_RULES.items()}
    rules: Dict[str, Dict[str, float]] = {}
    for part in spec.split(";"):
        part = part.strip()
        if not part:
            continue
        name, _, kvs = part.partition(":")
        name = name.strip()
        if name not in DEFAULT_RULES:
            raise ValueError(f"unknown sentinel rule {name!r} "
                             f"(known: {sorted(DEFAULT_RULES)})")
        params = dict(DEFAULT_RULES[name])
        for kv in kvs.split(","):
            kv = kv.strip()
            if not kv:
                continue
            k, eq, v = kv.partition("=")
            if not eq or k.strip() not in params:
                raise ValueError(
                    f"bad sentinel param {kv!r} for rule {name!r} "
                    f"(known: {sorted(params)})")
            params[k.strip()] = float(v)
        rules[name] = params
    return rules


class _RuleState:
    __slots__ = ("ewma", "streak", "in_episode")

    def __init__(self):
        self.ewma: Optional[float] = None
        self.streak = 0
        self.in_episode = False


def _counter_delta(cur: Dict[str, Any], prev: Dict[str, Any],
                   name: str) -> float:
    return (cur["counters"].get(name, 0.0)
            - prev["counters"].get(name, 0.0))


def _latency_window(cur: Dict[str, Any], prev: Dict[str, Any]):
    """(sample count, p95 ms) of slo.latencyMs over the window, from
    bucket-count deltas; (0, None) when the histogram is absent."""
    h = cur.get("bucket_histograms", {}).get("slo.latencyMs")
    if h is None:
        return 0, None
    p = prev.get("bucket_histograms", {}).get("slo.latencyMs")
    counts = list(h["counts"]) if p is None else \
        [c - q for c, q in zip(h["counts"], p["counts"])]
    n = sum(counts)
    if n <= 0:
        return 0, None
    return n, obsreg.bucket_quantile(h["bounds"], counts, 0.95)


class DriftSentinel:
    """One per session when ``obs.sentinel.enabled=true`` (the
    PrecompileService lifecycle shape: ``start`` a daemon thread,
    ``stop`` sets an event the interval-wait observes, ``tick()`` is
    the synchronous unit the thread loops — and what deterministic
    tests call directly)."""

    def __init__(self, interval_ms: int = 1000, rules: str = "",
                 jsonl_path: str = "", jsonl_max_bytes: int = 0):
        self.interval_s = max(1, int(interval_ms)) / 1e3
        self.rules = parse_rules(rules)
        self.jsonl_path = str(jsonl_path or "")
        self.jsonl_max_bytes = int(jsonl_max_bytes)
        self._states = {name: _RuleState() for name in self.rules}
        self._prev: Optional[Dict[str, Any]] = None
        self._prev_ledger: Optional[Dict[str, Any]] = None
        self._stop_evt = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()
        self._stats = {"ticks": 0, "breaches": 0, "episodes": 0}

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._run, name="obs-sentinel", daemon=True)
        self._thread.start()

    def stop(self, timeout: Optional[float] = 5.0) -> None:
        self._stop_evt.set()
        t = self._thread
        if t is not None:
            t.join(timeout)

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return dict(self._stats)

    def _run(self) -> None:
        while not self._stop_evt.wait(self.interval_s):
            try:
                self.tick()
            except Exception:
                # the watcher must never take the engine down with it
                obsreg.get_registry().inc("obs.sentinel.tickErrors")

    # -- evaluation ---------------------------------------------------------
    def tick(self) -> List[str]:
        """Evaluate one window; returns the rules that OPENED an
        episode this tick (usually empty)."""
        reg = obsreg.get_registry()
        cur = reg.snapshot()
        cur_ledger = acct.snapshot() if acct.is_enabled() else None
        prev, prev_ledger = self._prev, self._prev_ledger
        self._prev, self._prev_ledger = cur, cur_ledger
        with self._lock:
            self._stats["ticks"] += 1
        reg.inc("obs.sentinel.ticks")
        if prev is None:
            return []                      # first tick only arms it
        fired: List[str] = []
        verdicts: Dict[str, Dict[str, Any]] = {}
        for name, params in self.rules.items():
            st = self._states[name]
            breached, obs_v = self._evaluate(name, params, cur, prev,
                                             st.ewma)
            verdicts[name] = {"breached": breached, "observed": obs_v,
                              "baseline": st.ewma,
                              "streak": st.streak}
            if breached:
                st.streak += 1
                if st.streak >= int(params.get("sustain", 2)) \
                        and not st.in_episode:
                    st.in_episode = True
                    fired.append(name)
            else:
                st.streak = 0
                st.in_episode = False
                # baseline learns only from healthy windows — a
                # sustained regression must not become the new normal
                if obs_v is not None:
                    st.ewma = obs_v if st.ewma is None else (
                        _EWMA_ALPHA * obs_v
                        + (1 - _EWMA_ALPHA) * st.ewma)
        if fired:
            self._emit(fired, verdicts, prev_ledger)
        return fired

    @staticmethod
    def _evaluate(name: str, params: Dict[str, float],
                  cur: Dict[str, Any], prev: Dict[str, Any],
                  baseline: Optional[float]):
        """(breached, observed value) for one rule over one window."""
        if name == "latency":
            n, p95 = _latency_window(cur, prev)
            if n < params["min"] or p95 is None:
                return False, None
            if baseline is None:
                return False, p95          # warmup window
            threshold = max(params["floor_ms"],
                            baseline * params["factor"])
            return p95 > threshold, p95
        if name == "slow":
            d = _counter_delta(cur, prev, "obs.slowQueries")
            if d < params["min"]:
                return False, d if d > 0 else None
            base = baseline or 0.0
            return d > base * params["factor"], d
        if name == "cacheHit":
            hits = _counter_delta(cur, prev, "serve.resultCacheHits")
            misses = _counter_delta(cur, prev,
                                    "serve.resultCacheMisses")
            total = hits + misses
            if total < params["min"]:
                return False, None
            rate = hits / total
            if baseline is None:
                return False, rate
            return rate < baseline * params["drop"], rate
        if name == "compile":
            d = _counter_delta(cur, prev, "kernel.cache.compiles")
            if d < params["min"]:
                return False, d if d > 0 else None
            base = baseline or 0.0
            return d > base * params["factor"], d
        if name == "spill":
            d = _counter_delta(cur, prev, "spill.deviceToHostBytes")
            if d < params["min"]:
                return False, d if d > 0 else None
            base = baseline or 0.0
            return d > base * params["factor"], d
        return False, None

    # -- emission -----------------------------------------------------------
    def _emit(self, fired: List[str],
              verdicts: Dict[str, Dict[str, Any]],
              prev_ledger: Optional[Dict[str, Any]]) -> None:
        reg = obsreg.get_registry()
        pairs = [("obs.sentinel.breaches", len(fired))]
        pairs += [(f"obs.sentinel.breaches.{r}", 1) for r in fired]
        reg.inc_many(*pairs)
        with self._lock:
            self._stats["breaches"] += len(fired)
            self._stats["episodes"] += 1
        talkers = acct.top_talkers(base=prev_ledger) \
            if acct.is_enabled() else []
        payload = {
            "unix": time.time(),
            "rules": fired,
            "verdicts": verdicts,
            "interval_s": self.interval_s,
            "top_talkers": talkers,
        }
        rec = obsrec.get_recorder()
        if rec is not None:
            payload["bundle"] = rec.dump_bundle(
                None, reason="slo", extra=payload)
        obsrec.record_event("sentinel.breach", rules=fired)
        if self.jsonl_path:
            try:
                obsjsonl.rotating_append(
                    self.jsonl_path, json.dumps(payload, default=str),
                    self.jsonl_max_bytes)
            except OSError:
                pass                       # breach log is best-effort
