"""Compile observatory: per-compile attribution, shape-churn analytics,
and a precompile corpus.

The TPC-DS-99 compile bill — 2,639 distinct (kernel, shape) programs,
3,431 s cold / 613 s warm (PERF.md) — is the ROADMAP's named
serving-SLO blocker, yet compilation was the one hot path the obs
stack could not see: a compile surfaced only as an anomalously long
dispatch span with no family, shape signature, cache tier, or
triggering query attached.  This module is the instrument the
shape-erased-ABI refactor (ROADMAP item 2) will be driven by.

Every first call of a (kernel-cache key, arg-shape) program through
``exec/kernel_cache.get_kernel`` records one **CompileEvent**:

  * kernel family + cache-key repr + canonical shape/dtype signature
  * backend the executable was built under (``pallas``/``xla``)
  * compile wall (trace + XLA compile + one dispatch; on the tunneled
    runtime the dispatch share is negligible)
  * cache tier — ``fresh`` (a real XLA compile) vs ``persistent`` (the
    executable reloaded from the persistent XLA compilation cache),
    classified from jax's own ``/jax/compilation_cache/*`` monitoring
    events counted thread-locally around the call (in-memory kernel
    cache hits never reach this module at all — they are counted as
    ``kernel.cache.memHits`` by get_kernel)
  * the triggering query id (from the thread's installed CancelToken)
    and its canonical plan digest (registered by sched/service at
    submit time)

Events land in a bounded ring plus process-lifetime aggregates:
per-family program/signature-cardinality counts (with a width-bucketed
projection estimating the collapse a shape-erased ABI would buy) and a
bounded per-query attribution table.  Surfaces:

  * ``kernel.compile`` spans in the Chrome trace (compiles stop
    masquerading as slow dispatches)
  * ``kernel.compile.*`` counters + the ``kernel.compile.wallMs``
    histogram on ``/metrics``, and the cache-tier split
    ``kernel.cache.memHits`` / ``.persistentHits`` / ``.compiles``
  * the ``/compiles`` endpoint route (obs/server.py): live ledger
    table + churn report + per-query attribution
  * a "compile" QueryProfile section and ``compile_s`` in
    ``wall_breakdown`` (obs/profile.py)
  * flight-recorder ``compile.storm`` events when one query compiles
    more than ``obs.compile.stormThreshold`` programs (once per query)
  * the precompile corpus: ``obs.compile.corpusPath`` appends one
    JSONL record of (plan digest, kernel signature set) per distinct
    plan — the replay artifact for an AOT precompile service

Disabled path (``obs.compile.enabled=false``): the get_kernel wrapper
checks one module bool and dispatches straight through — no shape
signature is computed.  Configuration is process-wide, last session
wins (the trace/recorder configure idiom).

Layering: this module imports only obs siblings at load time.  Query
attribution needs the scheduler's thread-local CancelToken, which is
imported inside the lookup function only — sched imports obs at module
level, never the reverse, so the package stays an import leaf.
"""

from __future__ import annotations

import json
import threading
import time
from collections import OrderedDict, deque
from typing import Any, Dict, List, Optional, Sequence, Tuple

from spark_rapids_tpu.obs import recorder as obsrec
from spark_rapids_tpu.obs import registry as obsreg
from spark_rapids_tpu.obs import trace as obstrace

DEFAULT_RING_EVENTS = 4096
DEFAULT_STORM_THRESHOLD = 64

# bounds on the process-lifetime aggregates: the TPC-DS-99 bill is
# ~2.6k programs, so these caps are headroom, not expected operation —
# past them a family's signature sets stop growing and flag overflow
# (counts keep accumulating; only *distinctness* saturates)
_MAX_SIGS_PER_FAMILY = 8192
_MAX_QUERIES = 256
_MAX_PROGRAMS_PER_QUERY = 1024

TIER_FRESH = "fresh"
TIER_PERSISTENT = "persistent"

_enabled = True                       # obs.compile.enabled default
_storm_threshold = DEFAULT_STORM_THRESHOLD
_corpus_path = ""
_corpus_replay = True                 # obs.compile.corpusReplay default

_LOCK = threading.Lock()
_ring: deque = deque(maxlen=DEFAULT_RING_EVENTS)
_seq = 0
# family -> {programs, fresh, persistent, wall_ns, sigs, bucketed,
#            sig_overflow}
_families: Dict[str, Dict[str, Any]] = {}
# query id -> {digest, compiled, persistent, wall_ns, storm, programs}
_queries: "OrderedDict[int, Dict[str, Any]]" = OrderedDict()
# accounting closure for the per-query table: fresh compiles recorded
# with NO token on the thread (engine warm-up, direct exec paths), and
# compiled counts lost to finished-record eviction — so
# sum(per_query kernels_compiled) + unattributed + evicted always
# equals the kernel.cache.compiles counter (the bench attribution
# cross-check leans on this identity)
_unattributed_fresh = 0
_evicted_compiled = 0
_corpus_seen: set = set()
_corpus_lock = threading.Lock()


def configure(enabled: bool,
              ring_events: int = DEFAULT_RING_EVENTS,
              storm_threshold: int = DEFAULT_STORM_THRESHOLD,
              corpus_path: str = "",
              corpus_replay: bool = True) -> None:
    """Session-init hook (``obs.compile.*`` knobs; last session wins).
    Resizing the ring preserves its newest events; process-lifetime
    aggregates are never reset by reconfiguration."""
    global _enabled, _storm_threshold, _corpus_path, _ring, \
        _corpus_replay
    with _LOCK:
        ring_events = max(16, int(ring_events))
        if ring_events != (_ring.maxlen or 0):
            _ring = deque(_ring, maxlen=ring_events)
        _enabled = bool(enabled)
        _storm_threshold = max(1, int(storm_threshold))
        _corpus_path = str(corpus_path or "")
        _corpus_replay = bool(corpus_replay)


def corpus_replay_enabled() -> bool:
    return _corpus_replay


def is_enabled() -> bool:
    return _enabled


def reset() -> None:
    """Test hook: drop the ring, aggregates, query table and corpus
    dedup state (configuration is left alone)."""
    global _seq, _unattributed_fresh, _evicted_compiled
    with _LOCK:
        _ring.clear()
        _families.clear()
        _queries.clear()
        _seq = 0
        _unattributed_fresh = 0
        _evicted_compiled = 0
    with _corpus_lock:
        _corpus_seen.clear()


# ---------------------------------------------------------------------------
# cache-tier classification: jax monitoring events, counted per thread
# ---------------------------------------------------------------------------
# jax's compiler records '/jax/compilation_cache/cache_hits' when an
# executable is RELOADED from the persistent compilation cache and
# '.../cache_misses' when it actually compiles (both synchronously on
# the compiling thread).  A thread-local counter pair bracketing the
# first call therefore classifies the tier exactly — concurrent
# compiles on other threads cannot bleed into this thread's window.

_tls = threading.local()
_listener_installed = False


def _jax_cache_listener(event: str, **kwargs) -> None:
    if event == "/jax/compilation_cache/cache_hits":
        _tls.pc_hits = getattr(_tls, "pc_hits", 0) + 1
    elif event == "/jax/compilation_cache/cache_misses":
        _tls.pc_misses = getattr(_tls, "pc_misses", 0) + 1


def _ensure_listener() -> None:
    global _listener_installed
    if _listener_installed:
        return
    with _LOCK:
        if _listener_installed:
            return
        try:
            from jax._src import monitoring
            monitoring.register_event_listener(_jax_cache_listener)
        except Exception:
            pass                      # tier degrades to 'fresh' for all
        _listener_installed = True


def probe_begin() -> Tuple[int, int]:
    """Snapshot this thread's persistent-cache event counters before a
    potential compile; pass the result to :func:`classify_tier`."""
    _ensure_listener()
    return (getattr(_tls, "pc_hits", 0), getattr(_tls, "pc_misses", 0))


def classify_tier(probe: Tuple[int, int]) -> str:
    """``fresh`` when any real XLA compile happened in the window,
    ``persistent`` when the window saw only persistent-cache reloads.
    A window with neither event (persistent cache not configured, or a
    program jax already held in memory) reports ``fresh`` — the
    conservative reading for a compile-bill instrument."""
    h0, m0 = probe
    if getattr(_tls, "pc_misses", 0) - m0 > 0:
        return TIER_FRESH
    if getattr(_tls, "pc_hits", 0) - h0 > 0:
        return TIER_PERSISTENT
    return TIER_FRESH


# ---------------------------------------------------------------------------
# query attribution
# ---------------------------------------------------------------------------

def _current_query_id() -> Optional[int]:
    # function-level import: see the layering note in the module
    # docstring (sched.cancel itself imports nothing from obs, so this
    # cannot cycle at runtime either)
    try:
        from spark_rapids_tpu.sched import cancel as _cancel
        tok = _cancel.current()
        return tok.query_id if tok is not None else None
    except Exception:
        return None


def _new_query_rec() -> Dict[str, Any]:
    return {"digest": None, "compiled": 0, "persistent": 0,
            "wall_ns": 0, "storm": False, "finished": False,
            "programs": []}


def _evict_queries_locked() -> None:
    """Bound the per-query table by evicting FINISHED records oldest
    first — a long-running query's record (its digest binding and
    accumulating attribution) must survive any number of short
    neighbours completing around it.  Live records are bounded by the
    scheduler's own queue/concurrency caps, so skipping them cannot
    grow the table unboundedly."""
    global _evicted_compiled
    if len(_queries) <= _MAX_QUERIES:
        return
    for qid in list(_queries):
        if _queries[qid]["finished"]:
            _evicted_compiled += _queries[qid]["compiled"]
            del _queries[qid]
            if len(_queries) <= _MAX_QUERIES:
                return


def _query_rec_locked(qid: Optional[int]) -> Optional[Dict[str, Any]]:
    if qid is None:
        return None
    q = _queries.get(qid)
    if q is None:
        # attribution without registration (a query path that bypassed
        # sched/service): track it anyway, digest unknown
        q = _queries[qid] = _new_query_rec()
        _evict_queries_locked()
    return q


def register_query(query_id: int, plan_digest: Optional[str]) -> None:
    """Bind a query id to its canonical plan digest for the lifetime of
    the query (called by sched/service.submit for every submission, so
    compile events fired on any thread carrying the query's CancelToken
    can be stamped with both)."""
    if query_id is None:
        return
    with _LOCK:
        q = _query_rec_locked(query_id)
        if q is not None and plan_digest is not None:
            q["digest"] = plan_digest


def finish_query(query_id: int) -> None:
    """Query-completion hook (sched/service worker, success or not):
    emits the precompile-corpus record for a distinct plan digest that
    compiled at least one program.  The per-query attribution record
    stays in the bounded table for the /queries + /compiles surfaces.
    Never raises."""
    try:
        with _LOCK:
            q = _queries.get(query_id)
            path = _corpus_path
            if q is None:
                return
            q["finished"] = True        # now evictable (_MAX_QUERIES)
            _evict_queries_locked()
            digest = q["digest"]
            programs = list(q["programs"])
        if not path or not digest or not programs:
            return
        with _corpus_lock:
            if digest in _corpus_seen:
                return
            record = {"plan_digest": digest, "query_id": query_id,
                      "ts_unix": time.time(),
                      "programs": programs}
            with open(path, "a") as f:
                f.write(json.dumps(record, default=str) + "\n")
            # mark seen only AFTER the append succeeded: a transient
            # write failure must leave the record emittable by the
            # plan's next completion, not drop it for the process life
            _corpus_seen.add(digest)
        obsreg.get_registry().inc("kernel.compile.corpusPlans")
    except Exception:
        pass


def query_stats(query_id: int) -> Optional[Dict[str, Any]]:
    """Per-query compile attribution (None when the query never
    compiled nor registered): fresh-compiled program count, persistent
    reload count, compile wall ms, storm flag."""
    with _LOCK:
        q = _queries.get(query_id)
        if q is None:
            return None
        return {"kernels_compiled": q["compiled"],
                "persistent_reloads": q["persistent"],
                "compile_ms": q["wall_ns"] / 1e6,
                "storm": q["storm"]}


def row_fields(query_id: int) -> Dict[str, Any]:
    """The ``kernels_compiled``/``compile_ms`` field pair shared by the
    ``/queries`` table rows and the slow-query JSONL — ONE derivation
    (fresh compiles + persistent reloads, both paid on the query's
    wall; null when zero) so the two surfaces cannot drift."""
    stats = query_stats(query_id)
    compiled = (stats["kernels_compiled"] +
                stats["persistent_reloads"]) if stats else 0
    compile_ms = stats["compile_ms"] if stats else 0.0
    return {"kernels_compiled": compiled or None,
            "compile_ms": round(compile_ms, 3) if compile_ms else None}


# ---------------------------------------------------------------------------
# signatures + width-bucketing projection
# ---------------------------------------------------------------------------

def _leaf_str(leaf: Any) -> str:
    if isinstance(leaf, tuple) and len(leaf) == 2 and \
            isinstance(leaf[0], tuple):
        shape, dty = leaf
        return f"{dty}[{','.join(str(d) for d in shape)}]"
    return str(leaf)[:32]


def canonical_signature(leaves: Sequence[Any]) -> str:
    """Compact ``dtype[shape]`` rendering of a program's argument
    leaves — the shape/dtype signature CompileEvents carry."""
    return ";".join(_leaf_str(x) for x in leaves)


def _pow2_bucket(n: int) -> int:
    return n if n <= 1 else 1 << (int(n) - 1).bit_length()


def _dtype_class(dty: str) -> str:
    d = str(dty)
    for cls in ("int", "uint", "float", "bool", "complex"):
        if d.startswith(cls):
            return cls
    return d


def _bucket_leaf(leaf: Any) -> Any:
    if isinstance(leaf, tuple) and len(leaf) == 2 and \
            isinstance(leaf[0], tuple):
        shape, dty = leaf
        return (tuple(_pow2_bucket(d) for d in shape),
                _dtype_class(dty))
    return "op"


def _bucket_key(key: Any) -> Any:
    """Width-bucketed projection of a kernel-cache key: integer
    components >= 16 (capacities, widths, row counts that leaked into
    keys) round up to powers of two.  This models what a shape-erased
    ABI with width-bucketed layouts would collapse — an ESTIMATE for
    the churn report, not a semantic statement about the keys."""
    if isinstance(key, tuple):
        return tuple(_bucket_key(k) for k in key)
    if isinstance(key, bool):
        return key
    if isinstance(key, int) and key >= 16:
        return _pow2_bucket(key)
    return key


# ---------------------------------------------------------------------------
# the ledger
# ---------------------------------------------------------------------------

def record_compile(key: Any, family: str, backend: str,
                   leaves: Sequence[Any], t0_ns: int, dur_ns: int,
                   tier: str, replay: Optional[str] = None) -> None:
    """Record one CompileEvent (called by the kernel-cache observe
    wrapper on the first call of each (key, shape) program).
    ``replay`` is the optional AOT replay payload (base64, built by
    kernel_cache._replay_payload) that rides the program's corpus
    record only — never the ring or the /compiles events (payloads are
    KBs each)."""
    if not _enabled:
        return
    global _seq
    qid = _current_query_id()
    sig = canonical_signature(leaves)
    key_repr = repr(key)[:200]
    storm_fired = None
    try:
        bkey = _bucket_key(key)
        bleaves = tuple(_bucket_leaf(x) for x in leaves)
    except Exception:
        bkey, bleaves = key_repr, sig
    with _LOCK:
        _seq += 1
        q = _query_rec_locked(qid)
        digest = q["digest"] if q is not None else None
        evt = {"seq": _seq, "ts_unix": time.time(),
               "family": family, "key": key_repr,
               "signature": sig, "backend": backend, "tier": tier,
               "wall_ms": round(dur_ns / 1e6, 3),
               "query_id": qid, "plan_digest": digest}
        _ring.append(evt)
        fam = _families.get(family)
        if fam is None:
            fam = _families[family] = {
                "programs": 0, "fresh": 0, "persistent": 0,
                "wall_ns": 0, "wall_fresh_ns": 0,
                "wall_persistent_ns": 0, "sigs": set(),
                "bucketed": set(), "sig_overflow": False}
        fam["programs"] += 1
        eff_tier = tier if tier in (TIER_FRESH, TIER_PERSISTENT) \
            else TIER_FRESH
        fam[eff_tier] += 1
        fam["wall_ns"] += int(dur_ns)
        # per-tier wall split: the persistent share is the "warm
        # compile" bill a replica restart pays (reload, not re-compile)
        # — the number the precompile service exists to move off the
        # serving path (tracked per run in BENCH_trend.json)
        fam["wall_fresh_ns" if eff_tier == TIER_FRESH
            else "wall_persistent_ns"] += int(dur_ns)
        if len(fam["sigs"]) < _MAX_SIGS_PER_FAMILY:
            fam["sigs"].add((key_repr, sig))
            fam["bucketed"].add((bkey, bleaves))
        else:
            fam["sig_overflow"] = True
        if q is None:
            if tier != TIER_PERSISTENT:
                global _unattributed_fresh
                _unattributed_fresh += 1
        else:
            if tier == TIER_PERSISTENT:
                q["persistent"] += 1
            else:
                q["compiled"] += 1
            q["wall_ns"] += int(dur_ns)
            if len(q["programs"]) < _MAX_PROGRAMS_PER_QUERY:
                prog = {"family": family, "key": key_repr,
                        "signature": sig, "backend": backend}
                if replay is not None:
                    prog["replay"] = replay
                q["programs"].append(prog)
            total = q["compiled"] + q["persistent"]
            if total > _storm_threshold and not q["storm"]:
                q["storm"] = True
                storm_fired = total
    # registry counters + trace span outside the ledger lock (both
    # have their own locking; holding two at once buys nothing)
    tier_counter = ("kernel.cache.compiles" if tier != TIER_PERSISTENT
                    else "kernel.cache.persistentHits")
    obsreg.get_registry().inc_many(
        ("kernel.compile.events", 1),
        (f"kernel.compile.events.{family}", 1),
        ("kernel.compile.wallNs", int(dur_ns)),
        (tier_counter, 1))
    obsreg.get_registry().observe("kernel.compile.wallMs", dur_ns / 1e6)
    # ledger: compile wall bills the owning tenant (same qid binding;
    # one bool inside when accounting is off)
    from spark_rapids_tpu.obs import accounting as _acct
    _acct.charge_qid(qid, "kernel.compile.wallNs", int(dur_ns))
    obstrace.record("kernel.compile", t0_ns, dur_ns, cat="kernel",
                    args={"family": family, "tier": tier,
                          "backend": backend, "query": qid,
                          "signature": sig})
    if storm_fired is not None:
        obsreg.get_registry().inc("kernel.compile.storms")
        obsrec.record_event("compile.storm", query=qid,
                            programs=storm_fired,
                            threshold=_storm_threshold,
                            plan_digest=digest)


# ---------------------------------------------------------------------------
# reports
# ---------------------------------------------------------------------------

def _churn_rows_locked() -> List[Dict[str, Any]]:
    rows = []
    for family, a in _families.items():
        distinct = len(a["sigs"])
        bucketed = len(a["bucketed"])
        rows.append({
            "family": family,
            "programs": a["programs"],
            "fresh": a["fresh"],
            "persistent": a["persistent"],
            "compile_wall_ms": round(a["wall_ns"] / 1e6, 3),
            "distinct_signatures": distinct,
            "est_programs_width_bucketed": bucketed,
            "est_collapse_savings": distinct - bucketed,
            "sig_overflow": a["sig_overflow"],
        })
    rows.sort(key=lambda r: (-r["distinct_signatures"],
                             -r["compile_wall_ms"], r["family"]))
    return rows


def _totals_locked() -> Dict[str, Any]:
    fresh = sum(a["fresh"] for a in _families.values())
    persistent = sum(a["persistent"] for a in _families.values())
    wall_ns = sum(a["wall_ns"] for a in _families.values())
    distinct = sum(len(a["sigs"]) for a in _families.values())
    bucketed = sum(len(a["bucketed"]) for a in _families.values())
    return {"events": fresh + persistent, "fresh": fresh,
            "persistent": persistent,
            "compile_wall_ms": round(wall_ns / 1e6, 3),
            "compile_wall_fresh_ms": round(sum(
                a["wall_fresh_ns"] for a in _families.values()) / 1e6,
                3),
            "compile_wall_persistent_ms": round(sum(
                a["wall_persistent_ns"]
                for a in _families.values()) / 1e6, 3),
            "distinct_programs": distinct,
            "width_bucketed_projection": bucketed,
            "families": len(_families),
            "queries_tracked": len(_queries),
            # closure terms for the attribution identity (see the
            # _unattributed_fresh comment): per-query compiled totals
            # + these two == the kernel.cache.compiles counter
            "unattributed_fresh": _unattributed_fresh,
            "evicted_compiled": _evicted_compiled}


def _events_locked(max_events: Optional[int]) -> List[Dict[str, Any]]:
    out = list(_ring)
    if max_events is None:
        return out
    return out[-max_events:] if max_events > 0 else []


def churn_report() -> List[Dict[str, Any]]:
    """Shape-churn analytics, ranked by signature cardinality: for each
    kernel family, the distinct (key, shape) program count, the
    estimated program count after width-bucketing (powers-of-two shape
    dims + dtype classes + bucketed key capacities), and the estimated
    collapse savings — the candidates ROADMAP item 2's shape-erased
    ABI should attack first."""
    with _LOCK:
        return _churn_rows_locked()


def totals() -> Dict[str, Any]:
    with _LOCK:
        return _totals_locked()


def events(max_events: Optional[int] = None) -> List[Dict[str, Any]]:
    """The newest ``max_events`` ring events (all when None; an
    explicit 0 means none — a scraper asking for totals only)."""
    with _LOCK:
        return _events_locked(max_events)


def snapshot(max_events: int = 256) -> Dict[str, Any]:
    """The ``/compiles`` endpoint payload: config, totals, the newest
    ring events, per-query attribution, and the churn report —
    assembled under ONE lock acquisition so a scrape racing a compile
    cannot observe totals/events/churn from different instants."""
    with _LOCK:
        per_query = {
            str(qid): {"plan_digest": q["digest"],
                       "kernels_compiled": q["compiled"],
                       "persistent_reloads": q["persistent"],
                       "compile_ms": round(q["wall_ns"] / 1e6, 3),
                       "storm": q["storm"]}
            for qid, q in _queries.items()}
        return {"enabled": _enabled, "ring_capacity": _ring.maxlen,
                "storm_threshold": _storm_threshold,
                "corpus_path": _corpus_path or None,
                "totals": _totals_locked(),
                "events": _events_locked(max_events),
                "per_query": per_query,
                "churn": _churn_rows_locked()}


def corpus_path() -> str:
    return _corpus_path
