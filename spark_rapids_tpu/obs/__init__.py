"""Observability subsystem: span tracing, unified metrics registry,
query profiles, and query-execution listeners.

The reference plugin's debuggability story is per-operator GpuMetrics in
the Spark UI plus plan-time ``explain`` fallback reasons
(GpuExec.scala:27-56, GpuOverrides).  This package is the whole-query
view the rebuild needs on top of that: where wall time went, which
partition stalled, what the prefetcher / device semaphore / spill
catalog were doing — the Theseus lesson (PAPERS.md) that accelerated
query engines bottleneck on *data movement between stages*, which
per-operator counters alone cannot show.

Layers (leaf modules only — nothing here imports the engine, so every
engine layer may import ``obs`` freely):

  * :mod:`spark_rapids_tpu.obs.trace` — low-overhead span tracer with a
    Chrome trace-event exporter (open in Perfetto / chrome://tracing).
  * :mod:`spark_rapids_tpu.obs.registry` — process-wide metrics
    registry (counters / gauges / time histograms) that per-query views
    are carved out of.
  * :mod:`spark_rapids_tpu.obs.profile` — the per-query
    :class:`QueryProfile` assembled after each collect.
  * :mod:`spark_rapids_tpu.obs.listener` — QueryExecutionListener
    analog registered on the session.
  * :mod:`spark_rapids_tpu.obs.recorder` — flight recorder: bounded
    ring of recent engine events + self-contained diagnostic bundles
    on query failure/timeout/cancellation (opt-in via
    ``obs.recorder.dir``).
  * :mod:`spark_rapids_tpu.obs.server` — live telemetry endpoint:
    Prometheus ``/metrics``, ``/queries``, ``/profiles/<qid>``,
    ``/compiles`` from a background daemon thread (opt-in via
    ``obs.http.enabled``).
  * :mod:`spark_rapids_tpu.obs.compile` — compile observatory:
    per-compile attribution ledger (family, shape signature, cache
    tier, triggering query), shape-churn analytics with
    width-bucketing collapse estimates, compile-storm detection, and
    the precompile corpus (default-on via ``obs.compile.enabled``).

(``server`` holds a reference to the session it serves but imports no
engine module; the package stays an import leaf.)
"""

from spark_rapids_tpu.obs import registry, trace  # noqa: F401
