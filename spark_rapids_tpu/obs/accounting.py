"""Per-tenant resource metering: the ResourceLedger.

ROADMAP item 1's per-tenant quotas and item 2's elastic executors both
need a trustworthy answer to "who is consuming the chip".  The ledger
attributes every metered resource — kernel dispatches, compile wall,
scan bytes walked/uploaded, shuffle wire bytes, result-cache
hits/misses, HBM high-water byte-seconds, queue wait — to the owning
**tenant**: ``(session_id, statement_template | plan_digest)``.
In-process submissions bill to the ``(in-process)`` session;
charges fired on a thread that carries no query token bill to the
``(unattributed)`` tenant row, so the accounting identity

    sum over tenant rows of metric M  ==  total charged at M's sites

holds **by construction** — nothing is dropped, nothing is counted
twice (the obs/compile accounting-closure idiom).  Every charging site
bumps the global registry counter and the ledger with the same ``n``,
so the CI exactness gate can assert the per-tenant sum against the
global counter delta over any window.

Attribution mechanics (the compile-observatory pattern,
obs/compile.py):

* ``register_query`` (sched/service.submit) binds qid -> tenant for
  the query's lifetime; ``charge`` resolves the current qid from the
  thread's installed CancelToken (sched/cancel.py) via a lazy
  function-level import, keeping obs an import leaf.
* Charges accumulate on the per-query record and **fold into the
  tenant row** at ``finish_query`` (or at eviction — finished records
  only, bounded table), so a mid-flight settle can still re-split
  them.
* **Single-flight followers** (sched.dedup.*): ``settle_flight``
  splits the leader's bill equally across leader + followers — dedup
  must not hide a tenant's true consumption.  Shares are floats; they
  sum exactly to the leader's original bill.
* **Batched prepared statements** (serve/batching.py): the coalesced
  execution registers with ``hold=True`` so its bill is retained
  un-folded; ``settle_batch`` splits it across the member tenants by
  per-binding result-row share.

Disabled path (``obs.accounting.enabled=false``): every public entry
is one module-bool check, the existing ``obs.compile`` pattern.
"""

from __future__ import annotations

import hashlib
import threading
import time
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Sequence, Tuple

from spark_rapids_tpu.obs import registry as obsreg

_MAX_QUERIES = 256          # per-query records (finished evict first)
_MAX_TENANTS = 512          # tenant rows (LRU-fold into "(evicted)")
_MAX_TEMPLATES = 256        # distinct SLO template labels

IN_PROCESS = "(in-process)"
UNATTRIBUTED = ("-", "(unattributed)")
EVICTED = ("-", "(evicted)")

_enabled = True             # obs.accounting.enabled default
_LOCK = threading.Lock()
_queries: "OrderedDict[int, Dict[str, Any]]" = OrderedDict()
_tenants: "OrderedDict[Tuple[str, str], Dict[str, Any]]" = OrderedDict()
# template label -> short metric key (slo.latencyMs.tpl.<key>), capped
_template_keys: Dict[str, str] = {}

# log-spaced millisecond boundaries shared by every SLO histogram
SLO_BOUNDS_MS = obsreg.DEFAULT_MS_BOUNDS


def configure(enabled: bool) -> None:
    """Session init (last session wins, the trace/recorder idiom)."""
    global _enabled
    _enabled = bool(enabled)


def is_enabled() -> bool:
    return _enabled


def reset() -> None:
    """Test hook: drop all ledger state."""
    with _LOCK:
        _queries.clear()
        _tenants.clear()
        _template_keys.clear()


def _current_query_id() -> Optional[int]:
    # lazy import: sched imports obs, never the reverse (the
    # obs/compile layering note)
    try:
        from spark_rapids_tpu.sched import cancel as _cancel
        tok = _cancel.current()
        return tok.query_id if tok is not None else None
    except Exception:
        return None


def tenant_of(session_id: Optional[str], template: Optional[str],
              plan_digest: Optional[str]) -> Tuple[str, str]:
    """The ledger's tenant key: owning session x workload identity.
    Prepared statements bill under their template; ad-hoc plans under
    their canonical digest."""
    sid = str(session_id) if session_id else IN_PROCESS
    if template:
        return (sid, str(template))
    if plan_digest:
        return (sid, f"digest:{str(plan_digest)[:16]}")
    return (sid, "(ad-hoc)")


def _tenant_row_locked(tenant: Tuple[str, str]) -> Dict[str, Any]:
    row = _tenants.get(tenant)
    if row is None:
        row = _tenants[tenant] = {"usage": {}, "queries": 0,
                                  "first_unix": time.time(),
                                  "last_unix": time.time()}
        # LRU bound: fold the coldest row into "(evicted)" so the
        # accounting identity survives the eviction
        while len(_tenants) > _MAX_TENANTS:
            old_key, old = next(iter(_tenants.items()))
            if old_key == tenant:
                break
            del _tenants[old_key]
            ev = _tenants.get(EVICTED)
            if ev is None:
                ev = _tenants[EVICTED] = {
                    "usage": {}, "queries": 0,
                    "first_unix": time.time(),
                    "last_unix": time.time()}
            for m, v in old["usage"].items():
                ev["usage"][m] = ev["usage"].get(m, 0.0) + v
            ev["queries"] += old["queries"]
            ev["last_unix"] = time.time()
    else:
        _tenants.move_to_end(tenant)
        row["last_unix"] = time.time()
    return row


def _add_usage(usage: Dict[str, float], metric: str, n: float) -> None:
    usage[metric] = usage.get(metric, 0.0) + float(n)


def _evict_queries_locked() -> None:
    """Bound the per-query table by folding FINISHED (or abandoned
    held) records into their tenant rows oldest first — the
    obs/compile eviction contract: live records survive, and nothing
    escapes the tenant table."""
    if len(_queries) <= _MAX_QUERIES:
        return
    for qid in list(_queries):
        rec = _queries[qid]
        if rec["finished"] or rec["hold"]:
            _fold_locked(rec)
            del _queries[qid]
            if len(_queries) <= _MAX_QUERIES:
                return


def _fold_locked(rec: Dict[str, Any]) -> None:
    if not rec["usage"]:
        return
    row = _tenant_row_locked(rec["tenant"])
    for m, v in rec["usage"].items():
        _add_usage(row["usage"], m, v)
    rec["usage"] = {}


def register_query(query_id: int, session_id: Optional[str] = None,
                   template: Optional[str] = None,
                   plan_digest: Optional[str] = None,
                   hold: bool = False) -> None:
    """Bind qid -> tenant for the query's lifetime (sched/service
    .submit, beside the compile observatory's register_query).
    ``hold=True`` marks a coalesced batch execution whose bill must
    stay un-folded until ``settle_batch`` re-splits it."""
    if not _enabled or query_id is None:
        return
    tenant = tenant_of(session_id, template, plan_digest)
    with _LOCK:
        rec = _queries.get(query_id)
        if rec is None:
            rec = _queries[query_id] = {
                "tenant": tenant, "usage": {}, "finished": False,
                "hold": bool(hold)}
            _evict_queries_locked()
        else:
            rec["tenant"] = tenant
            rec["hold"] = bool(hold)
        row = _tenant_row_locked(tenant)
        row["queries"] += 1


def charge(metric: str, n: float = 1.0) -> None:
    """Attribute ``n`` of ``metric`` to the query installed on the
    current thread; a token-less thread bills "(unattributed)" so the
    sum identity holds regardless."""
    if not _enabled:
        return
    charge_qid(_current_query_id(), metric, n)


def charge_qid(query_id: Optional[int], metric: str, n: float) -> None:
    if not _enabled or not n:
        return
    with _LOCK:
        if query_id is not None:
            rec = _queries.get(query_id)
            if rec is None:
                # attribution without registration (a path that
                # bypassed sched/service): track anyway, tenant unknown
                rec = _queries[query_id] = {
                    "tenant": UNATTRIBUTED, "usage": {},
                    "finished": False, "hold": False}
                _evict_queries_locked()
            _add_usage(rec["usage"], metric, n)
            return
        row = _tenant_row_locked(UNATTRIBUTED)
        _add_usage(row["usage"], metric, n)


def charge_tenant(session_id: Optional[str], template: Optional[str],
                  plan_digest: Optional[str], metric: str,
                  n: float = 1.0) -> None:
    """Direct tenant charge for work that never passes the scheduler
    (the serve result-cache hit path)."""
    if not _enabled or not n:
        return
    tenant = tenant_of(session_id, template, plan_digest)
    with _LOCK:
        row = _tenant_row_locked(tenant)
        _add_usage(row["usage"], metric, n)


def finish_query(query_id: int) -> None:
    """Fold the query's accumulated bill into its tenant row
    (idempotent; held batch executions keep their bill for
    settle_batch).  Never raises."""
    if not _enabled:
        return
    try:
        with _LOCK:
            rec = _queries.get(query_id)
            if rec is None:
                return
            rec["finished"] = True
            if not rec["hold"]:
                _fold_locked(rec)
            _evict_queries_locked()
    except Exception:
        pass


def settle_flight(leader_qid: int,
                  follower_qids: Sequence[int]) -> None:
    """Split the leader's CURRENT bill equally across leader +
    followers (sched/service._finish_exec, before the followers
    resolve).  Follower shares land on the followers' own records and
    fold into their tenants at their finish — shares sum exactly to
    the leader's original bill."""
    if not _enabled or not follower_qids:
        return
    with _LOCK:
        leader = _queries.get(leader_qid)
        if leader is None or not leader["usage"]:
            return
        share = 1.0 / (1 + len(follower_qids))
        shared = {m: v * share for m, v in leader["usage"].items()}
        leader["usage"] = dict(shared)
        for fq in follower_qids:
            rec = _queries.get(fq)
            if rec is None:
                rec = _queries[fq] = {
                    "tenant": UNATTRIBUTED, "usage": {},
                    "finished": False, "hold": False}
            for m, v in shared.items():
                _add_usage(rec["usage"], m, v)
        _evict_queries_locked()
    obsreg.get_registry().inc("obs.accounting.flightSettles")


def settle_batch(exec_qid: int,
                 members: Sequence[Tuple[Tuple[str, str], float]]
                 ) -> None:
    """Split a held coalesced execution's bill across the member
    tenants by weight (per-binding result-row share;
    serve/batching._run_coalesced).  Weights normalize; zero/absent
    weights degrade to an equal split.  The exec record's hold drops
    so it can no longer double-bill."""
    if not _enabled or not members:
        return
    with _LOCK:
        rec = _queries.get(exec_qid)
        if rec is None:
            return
        usage = rec["usage"]
        rec["usage"] = {}
        rec["hold"] = False
        if rec["finished"]:
            _evict_queries_locked()
        total_w = sum(max(0.0, float(w)) for _, w in members)
        n = len(members)
        for tenant, w in members:
            frac = (max(0.0, float(w)) / total_w) if total_w > 0 \
                else 1.0 / n
            if frac <= 0.0:
                continue
            row = _tenant_row_locked(tuple(tenant))
            for m, v in usage.items():
                _add_usage(row["usage"], m, v * frac)
    obsreg.get_registry().inc("obs.accounting.batchSettles")


# ---------------------------------------------------------------------------
# SLO histogram helper (bounded per-template cardinality)
# ---------------------------------------------------------------------------

def template_key(label: str) -> str:
    """Short stable metric-name key for a statement template / digest
    label, capped at _MAX_TEMPLATES distinct labels (overflow pools
    under "other")."""
    with _LOCK:
        key = _template_keys.get(label)
        if key is None:
            if len(_template_keys) >= _MAX_TEMPLATES:
                return "other"
            key = hashlib.sha1(
                label.encode("utf-8", "replace")).hexdigest()[:10]
            _template_keys[label] = key
        return key


def template_labels() -> Dict[str, str]:
    """key -> full template label (the /slo payload's legend)."""
    with _LOCK:
        return {v: k for k, v in _template_keys.items()}


def observe_slo(metric: str, ms: float,
                template: Optional[str] = None) -> None:
    """One SLO observation: the global bucketed histogram plus the
    per-template series when a template label is known.  One bool when
    the ledger is off."""
    if not _enabled:
        return
    reg = obsreg.get_registry()
    reg.observe_bucket(metric, ms)
    if template:
        reg.observe_bucket(f"{metric}.tpl.{template_key(template)}", ms)


# ---------------------------------------------------------------------------
# the /tenants surface
# ---------------------------------------------------------------------------

def snapshot() -> Dict[str, Any]:
    """Ledger table under ONE lock (the /compiles snapshot idiom):
    tenant rows with folded usage PLUS each live query's un-folded
    bill merged in, so a mid-flight scrape still sums to the global
    counters."""
    with _LOCK:
        merged: Dict[Tuple[str, str], Dict[str, Any]] = {}
        for tenant, row in _tenants.items():
            merged[tenant] = {"usage": dict(row["usage"]),
                              "queries": row["queries"],
                              "first_unix": row["first_unix"],
                              "last_unix": row["last_unix"]}
        live = 0
        for rec in _queries.values():
            if not rec["usage"]:
                continue
            live += 1
            t = rec["tenant"]
            m = merged.get(t)
            if m is None:
                m = merged[t] = {"usage": {}, "queries": 0,
                                 "first_unix": time.time(),
                                 "last_unix": time.time()}
            for k, v in rec["usage"].items():
                m["usage"][k] = m["usage"].get(k, 0.0) + v
        rows: List[Dict[str, Any]] = []
        for (sid, workload), m in merged.items():
            rows.append({"session_id": sid, "workload": workload,
                         **m})
    rows.sort(key=lambda r: -sum(r["usage"].values()))
    return {"enabled": _enabled, "tenants": rows,
            "live_queries": live, "tenant_count": len(rows)}


def top_talkers(base: Optional[Dict[str, Any]] = None,
                limit: int = 5) -> List[Dict[str, Any]]:
    """Tenant rows ranked by window consumption: current snapshot
    minus ``base`` (a previous snapshot; None ranks lifetime totals)
    — the sentinel attaches this to its breach bundles."""
    cur = snapshot()["tenants"]
    base_usage = {}
    for r in (base or {}).get("tenants", []):
        base_usage[(r["session_id"], r["workload"])] = r["usage"]
    out = []
    for r in cur:
        prev = base_usage.get((r["session_id"], r["workload"]), {})
        delta = {m: v - prev.get(m, 0.0) for m, v in r["usage"].items()
                 if v - prev.get(m, 0.0) > 0}
        if delta:
            out.append({"session_id": r["session_id"],
                        "workload": r["workload"], "window": delta})
    out.sort(key=lambda r: -sum(r["window"].values()))
    return out[:max(1, int(limit))]
