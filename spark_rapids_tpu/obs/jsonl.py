"""Size-rotated JSONL appends, shared by the slow-query log and the
drift sentinel's breach stream.

An append-forever JSONL file on a long-lived serving engine grows
unbounded; the rotation contract here is deliberately minimal (the
logrotate keep-1 shape): when an append would push the file past
``max_bytes``, the current file is atomically renamed to
``<path>.1`` (replacing any previous ``.1``) and the append starts a
fresh file.  At most ``2 x max_bytes`` ever sits on disk per log, the
newest records are always in ``<path>``, and a crash mid-rotation
loses nothing — ``os.replace`` is atomic on POSIX.

``max_bytes <= 0`` disables rotation (the pre-rotation append-only
behaviour).  Concurrent appenders within one process serialize on a
module lock; rotation across processes is last-writer-wins, which is
the slow-query log's existing multi-session semantics.
"""

from __future__ import annotations

import os
import threading

_LOCK = threading.Lock()


def rotating_append(path: str, line: str, max_bytes: int = 0) -> None:
    """Append ``line`` (newline added) to ``path``, rotating first when
    the append would exceed ``max_bytes``."""
    data = line + "\n"
    with _LOCK:
        if max_bytes and max_bytes > 0:
            try:
                size = os.path.getsize(path)
            except OSError:
                size = 0
            if size and size + len(data) > max_bytes:
                try:
                    os.replace(path, path + ".1")
                except OSError:
                    pass     # rotation failure must not drop the record
        with open(path, "a") as f:
            f.write(data)
