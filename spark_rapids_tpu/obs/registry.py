"""Process-wide metrics registry: counters, gauges, time histograms.

Unifies the engine's three previously-disjoint stat channels —
``exec/base.Metrics.extra`` (per-exec), ``shuffle/faults
.ShuffleFaultStats`` (per-process recovery counters), and the
scan-cache hit/miss counters — behind one namespace that per-query
views are carved out of.

Naming convention: ``<section>.<metric>`` where the section prefix
(``scan``, ``shuffle``, ``semaphore``, ``spill``, ``pyworker``)
groups the metric into its QueryProfile section.  Time-valued metrics
end in ``Ns`` and hold nanoseconds; byte-valued metrics end in
``Bytes``; report-time rendering converts to ``*_s`` explicitly
(the Metrics unit contract — see exec/base.py).

Per-query carving: the registry is process-global (one executor, many
concurrent queries), so a query's view is a **snapshot delta** —
``view = get_registry().view()`` at query start,
``view.delta()`` at the end.  Concurrent queries sharing the process
can see each other's increments in their deltas; that is localization,
not accounting (the ShuffleFaultStats stamping contract).
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple

# log-spaced millisecond boundaries for SLO bucket histograms (+Inf is
# implicit as the final bucket) — fixed process-wide so windowed deltas
# and Prometheus `_bucket` series are always comparable
DEFAULT_MS_BOUNDS: Tuple[float, ...] = (
    1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0, 500.0,
    1000.0, 2500.0, 5000.0, 10000.0, 30000.0)


class Histogram:
    """count/sum/min/max summary of observed values (time histograms
    observe nanoseconds)."""

    __slots__ = ("count", "sum", "min", "max")

    def __init__(self):
        self.count = 0
        self.sum = 0.0
        self.min: Optional[float] = None
        self.max: Optional[float] = None

    def observe_locked(self, v: float) -> None:
        self.count += 1
        self.sum += v
        self.min = v if self.min is None else min(self.min, v)
        self.max = v if self.max is None else max(self.max, v)

    def to_dict(self) -> Dict[str, Any]:
        return {"count": self.count, "sum": self.sum,
                "min": self.min, "max": self.max,
                "mean": (self.sum / self.count) if self.count else None}


class BucketHistogram:
    """Fixed-boundary bucketed histogram (Prometheus `histogram` type:
    cumulative ``_bucket{le=...}`` series render from it, and windowed
    p50/p95/p99 interpolate from bucket-count deltas).  Boundaries are
    fixed at creation — observations land in the first bucket whose
    upper bound is >= the value; the final slot is +Inf."""

    __slots__ = ("bounds", "counts", "sum", "count")

    def __init__(self, bounds: Sequence[float] = DEFAULT_MS_BOUNDS):
        self.bounds: Tuple[float, ...] = tuple(float(b) for b in bounds)
        self.counts: List[int] = [0] * (len(self.bounds) + 1)
        self.sum = 0.0
        self.count = 0

    def observe_locked(self, v: float) -> None:
        self.count += 1
        self.sum += v
        for i, b in enumerate(self.bounds):
            if v <= b:
                self.counts[i] += 1
                return
        self.counts[-1] += 1

    def to_dict(self) -> Dict[str, Any]:
        return {"bounds": list(self.bounds),
                "counts": list(self.counts),
                "sum": self.sum, "count": self.count}


def bucket_quantile(bounds: Sequence[float], counts: Sequence[int],
                    q: float) -> Optional[float]:
    """Quantile estimate from bucket counts (linear interpolation
    inside the containing bucket, the Prometheus histogram_quantile
    rule); None with no observations.  The +Inf bucket clamps to its
    lower bound — an estimate, never an invention."""
    total = sum(counts)
    if total <= 0:
        return None
    rank = q * total
    cum = 0.0
    for i, c in enumerate(counts):
        if c <= 0:
            continue
        if cum + c >= rank:
            hi = bounds[i] if i < len(bounds) else None
            lo = bounds[i - 1] if i > 0 else 0.0
            if hi is None:
                return float(lo)
            frac = (rank - cum) / c
            return float(lo + (hi - lo) * frac)
        cum += c
    return float(bounds[-1]) if bounds else None


class MetricsRegistry:
    """Thread-safe registry; one per process via :func:`get_registry`."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: Dict[str, float] = {}
        self._gauges: Dict[str, float] = {}
        self._hists: Dict[str, Histogram] = {}
        self._bhists: Dict[str, BucketHistogram] = {}

    # -- counters ----------------------------------------------------------
    def inc(self, name: str, n: float = 1) -> None:
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + n

    def inc_many(self, *pairs) -> None:
        """Several counter increments under ONE lock acquisition — for
        hot paths that bump multiple counters per event (the device
        semaphore)."""
        with self._lock:
            for name, n in pairs:
                self._counters[name] = self._counters.get(name, 0) + n

    def counter(self, name: str) -> float:
        with self._lock:
            return self._counters.get(name, 0)

    # -- gauges ------------------------------------------------------------
    def set_gauge(self, name: str, v: float) -> None:
        with self._lock:
            self._gauges[name] = v

    def gauge_max(self, name: str, v: float) -> None:
        """High-water-mark gauge: keeps the max ever set."""
        with self._lock:
            old = self._gauges.get(name)
            if old is None or v > old:
                self._gauges[name] = v

    def gauge(self, name: str) -> Optional[float]:
        with self._lock:
            return self._gauges.get(name)

    # -- histograms --------------------------------------------------------
    def observe(self, name: str, v: float) -> None:
        with self._lock:
            h = self._hists.get(name)
            if h is None:
                h = self._hists[name] = Histogram()
            h.observe_locked(v)

    def observe_bucket(self, name: str, v: float,
                       bounds: Optional[Sequence[float]] = None) -> None:
        """Observe into a fixed-boundary bucketed histogram (created on
        first observation; ``bounds`` applies only then)."""
        with self._lock:
            h = self._bhists.get(name)
            if h is None:
                h = self._bhists[name] = BucketHistogram(
                    bounds if bounds is not None else DEFAULT_MS_BOUNDS)
            h.observe_locked(v)

    # -- snapshots / views -------------------------------------------------
    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "counters": dict(self._counters),
                "gauges": dict(self._gauges),
                "histograms": {k: h.to_dict()
                               for k, h in self._hists.items()},
                "bucket_histograms": {k: h.to_dict()
                                      for k, h in self._bhists.items()},
            }

    def view(self) -> "RegistryView":
        return RegistryView(self)


class RegistryView:
    """Snapshot-delta carve of the process registry for one query."""

    def __init__(self, registry: MetricsRegistry):
        self._registry = registry
        self._base = registry.snapshot()

    def delta(self) -> Dict[str, Any]:
        """Counters/histograms as *deltas* since the view was taken
        (zero-delta entries dropped); gauges as their CURRENT values
        (high-water marks are process-lifetime by design)."""
        cur = self._registry.snapshot()
        base = self._base
        counters = {}
        for k, v in cur["counters"].items():
            d = v - base["counters"].get(k, 0)
            if d:
                counters[k] = d
        hists = {}
        for k, h in cur["histograms"].items():
            b = base["histograms"].get(k, {"count": 0, "sum": 0.0})
            dc = h["count"] - b["count"]
            if dc:
                hists[k] = {"count": dc, "sum": h["sum"] - b["sum"],
                            "mean": (h["sum"] - b["sum"]) / dc}
        bhists = {}
        for k, h in cur.get("bucket_histograms", {}).items():
            b = base.get("bucket_histograms", {}).get(k)
            dc = h["count"] - (b["count"] if b else 0)
            if dc:
                counts = list(h["counts"]) if b is None else \
                    [c - p for c, p in zip(h["counts"], b["counts"])]
                bhists[k] = {"bounds": h["bounds"], "counts": counts,
                             "count": dc,
                             "sum": h["sum"] - (b["sum"] if b else 0.0)}
        return {"counters": counters, "gauges": dict(cur["gauges"]),
                "histograms": hists, "bucket_histograms": bhists}


_REGISTRY: Optional[MetricsRegistry] = None
_REGISTRY_LOCK = threading.Lock()


def get_registry() -> MetricsRegistry:
    """The process-wide registry (executor-singleton idiom)."""
    global _REGISTRY
    with _REGISTRY_LOCK:
        if _REGISTRY is None:
            _REGISTRY = MetricsRegistry()
        return _REGISTRY


def reset_registry() -> None:
    """Test hook: fresh registry (counters are process-lifetime
    otherwise)."""
    global _REGISTRY
    with _REGISTRY_LOCK:
        _REGISTRY = MetricsRegistry()
