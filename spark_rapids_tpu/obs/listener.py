"""QueryExecutionListener analog (Spark's
``spark.listenerManager.register`` surface).

Listeners registered on the session fire after every action:
``on_success(profile)`` with the assembled :class:`QueryProfile` (which
carries the annotated plan), ``on_failure(profile, exception)`` with a
partial profile (``status="failure"``, the error string stamped) and
the raised exception.  Listener exceptions are swallowed (a broken
listener must not fail the query — Spark's ExecutionListenerManager
contract)."""

from __future__ import annotations

from typing import List, Optional


class QueryExecutionListener:
    """Subclass-and-override base; both hooks default to no-ops."""

    def on_success(self, profile) -> None:  # pragma: no cover - default
        pass

    def on_failure(self, profile,
                   exception: BaseException) -> None:  # pragma: no cover
        pass


def notify(listeners: List[QueryExecutionListener], profile,
           exception: Optional[BaseException]) -> None:
    """Fan a finished query out to every listener, swallowing listener
    errors (reported nowhere — the query result must win)."""
    for listener in list(listeners):
        try:
            if exception is None:
                listener.on_success(profile)
            else:
                listener.on_failure(profile, exception)
        except Exception:
            pass
