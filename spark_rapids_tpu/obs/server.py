"""Always-on operational telemetry endpoint (opt-in HTTP server).

PR 3's obs layer is per-query and post-hoc; a long-lived multi-tenant
engine (ROADMAP item 1) needs its live state scrapeable while queries
run.  ``ObsHttpServer`` serves, from a background daemon thread:

  ``GET /metrics``          Prometheus text exposition (version 0.0.4)
                            of the process MetricsRegistry — counters,
                            gauges, histograms (as ``_count``/``_sum``
                            summaries) — with the scheduler's live
                            queued/running/admitted-bytes gauges
                            refreshed at scrape time.
  ``GET /queries``          JSON: the QueryService's live table —
                            queued/running plus a bounded
                            recently-completed window, with states,
                            priorities, admitted estimates and queue
                            wait (sched/service.QueryService
                            .query_table).
  ``GET /profiles/<qid>``   QueryProfile JSON from the session's
                            profile ring; 404 once evicted or unknown.
  ``GET /compiles``         compile-observatory ledger (obs/compile
                            .py): totals, the newest CompileEvents
                            (family, signature, tier, wall, query id +
                            plan digest), per-query attribution, the
                            shape-churn report ranked by signature
                            cardinality with width-bucketing collapse
                            estimates, and the kernel-backend
                            selection counters.  ``?n=`` bounds the
                            event count (default 256).
  ``GET /resultcache``      JSON: per-entry inspection of the serving
                            result cache (serve/result_cache.py) —
                            digest prefix, output names, bytes, age,
                            source count, and the entry's CURRENT
                            stamp drift (rewritten/deleted file
                            counts), so operators can see what the
                            incremental refresher keeps warm.
  ``GET /tenants``          JSON: the per-tenant ResourceLedger table
                            (obs/accounting.py) — kernel dispatches,
                            compile wall, scan/shuffle bytes, cache
                            hits/misses, HBM byte-seconds and queue
                            wait attributed to (session, workload),
                            single-flight followers and batched
                            members billed their fair share.
  ``GET /slo``              JSON: p50/p95/p99 interpolated from the
                            fixed-boundary SLO bucket histograms
                            (e2e latency, queue wait, first chunk;
                            global + per statement template), plus
                            the template-key legend.
  ``GET /healthz``          liveness probe.

Off by default (``obs.http.enabled=false``): nothing binds a socket
and no code on the query path changes.  The endpoint is read-only and
unauthenticated — it binds loopback unless ``obs.http.host`` says
otherwise.
"""

from __future__ import annotations

import json
import re
import threading
import weakref
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, Optional

from spark_rapids_tpu.obs import registry as obsreg

_NAME_PREFIX = "spark_rapids_tpu_"
_SANITIZE = re.compile(r"[^a-zA-Z0-9_:]")


def _prom_name(name: str) -> str:
    return _NAME_PREFIX + _SANITIZE.sub("_", name)


def _prom_value(v: Any) -> str:
    f = float(v)
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def _prom_le(bound: float) -> str:
    f = float(bound)
    if f == int(f):
        return str(int(f))
    return repr(f)


def render_prometheus(snapshot: Dict[str, Any]) -> str:
    """Render a MetricsRegistry snapshot as Prometheus text exposition
    (one ``# TYPE`` line per family; summary histograms surface as
    ``_count``/``_sum`` plus ``_min``/``_max`` gauges; bucketed SLO
    histograms render as REAL ``histogram`` families with cumulative
    ``_bucket{le=...}`` series ending in ``le="+Inf"``)."""
    lines = []
    for name in sorted(snapshot.get("counters", {})):
        n = _prom_name(name)
        lines.append(f"# TYPE {n} counter")
        lines.append(f"{n} {_prom_value(snapshot['counters'][name])}")
    for name in sorted(snapshot.get("gauges", {})):
        n = _prom_name(name)
        lines.append(f"# TYPE {n} gauge")
        lines.append(f"{n} {_prom_value(snapshot['gauges'][name])}")
    for name in sorted(snapshot.get("histograms", {})):
        h = snapshot["histograms"][name]
        n = _prom_name(name)
        lines.append(f"# TYPE {n} summary")
        lines.append(f"{n}_count {_prom_value(h.get('count', 0))}")
        lines.append(f"{n}_sum {_prom_value(h.get('sum', 0))}")
        for bound in ("min", "max"):
            if h.get(bound) is not None:
                lines.append(f"# TYPE {n}_{bound} gauge")
                lines.append(f"{n}_{bound} {_prom_value(h[bound])}")
    for name in sorted(snapshot.get("bucket_histograms", {})):
        h = snapshot["bucket_histograms"][name]
        n = _prom_name(name)
        lines.append(f"# TYPE {n} histogram")
        cum = 0
        for bound, c in zip(h["bounds"], h["counts"]):
            cum += c
            lines.append(
                f'{n}_bucket{{le="{_prom_le(bound)}"}} {cum}')
        lines.append(
            f'{n}_bucket{{le="+Inf"}} {_prom_value(h["count"])}')
        lines.append(f"{n}_sum {_prom_value(h.get('sum', 0))}")
        lines.append(f"{n}_count {_prom_value(h.get('count', 0))}")
    return "\n".join(lines) + "\n"


_SAMPLE_LINE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^}]*\})? [-+0-9.eEnaif]+$")


def parse_prometheus(text: str) -> Dict[str, float]:
    """Validate Prometheus text exposition and return the unlabeled
    samples as ``{name: value}``.  Raises ``ValueError`` on a malformed
    sample line or an empty exposition — the single validator the tests
    and the ci.sh scrape both lean on, so the format check cannot
    silently diverge from the renderer."""
    samples: Dict[str, float] = {}
    n = 0
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        if not _SAMPLE_LINE.match(line):
            raise ValueError(f"bad exposition line: {line!r}")
        n += 1
        if "{" not in line:
            name, value = line.split(" ", 1)
            samples[name] = float(value)
    if n == 0:
        raise ValueError("empty exposition")
    return samples


_LE_LABEL = re.compile(r'le="([^"]+)"')


def lint_exposition(text: str) -> Dict[str, float]:
    """Strict structural lint of a Prometheus exposition, on top of the
    per-line validation in :func:`parse_prometheus`:

      * every sample's family has a preceding ``# TYPE`` line (bucket /
        sum / count samples resolve to their ``histogram`` family, and
        sum / count also to a ``summary`` family);
      * every ``histogram`` family carries ``_bucket`` series that are
        cumulative (monotone non-decreasing in ``le`` order), end with
        ``le="+Inf"``, and the +Inf bucket equals ``_count``.

    Raises ``ValueError`` on any violation; returns the unlabeled
    samples like ``parse_prometheus``.  ci.sh runs this on EVERY
    scrape so a malformed family cannot ship behind a passing smoke.
    """
    samples = parse_prometheus(text)
    types: Dict[str, str] = {}
    hist_buckets: Dict[str, list] = {}
    hist_counts: Dict[str, float] = {}
    for line in text.splitlines():
        if line.startswith("# TYPE "):
            parts = line.split()
            if len(parts) != 4:
                raise ValueError(f"bad TYPE line: {line!r}")
            types[parts[2]] = parts[3]
            continue
        if not line or line.startswith("#"):
            continue
        name = line.split("{", 1)[0].split(" ", 1)[0]
        family = name
        for suffix in ("_bucket", "_sum", "_count"):
            if not name.endswith(suffix):
                continue
            base_type = types.get(name[: -len(suffix)])
            if base_type == "histogram" or \
                    (base_type == "summary" and suffix != "_bucket"):
                family = name[: -len(suffix)]
                break
        if family not in types:
            raise ValueError(f"sample without # TYPE: {line!r}")
        if types[family] == "histogram":
            value = float(line.rsplit(" ", 1)[1])
            if name == family + "_bucket":
                m = _LE_LABEL.search(line)
                if not m:
                    raise ValueError(f"bucket without le=: {line!r}")
                hist_buckets.setdefault(family, []).append(
                    (m.group(1), value))
            elif name == family + "_count":
                hist_counts[family] = value
    for family, t in types.items():
        if t != "histogram":
            continue
        buckets = hist_buckets.get(family)
        if not buckets:
            raise ValueError(f"histogram {family} has no _bucket series")
        if buckets[-1][0] != "+Inf":
            raise ValueError(
                f"histogram {family} buckets do not end at le=+Inf")
        prev = -1.0
        for le, v in buckets:
            if v < prev:
                raise ValueError(
                    f"histogram {family} buckets not cumulative at "
                    f"le={le}")
            prev = v
        if family not in hist_counts:
            raise ValueError(f"histogram {family} missing _count")
        if buckets[-1][1] != hist_counts[family]:
            raise ValueError(
                f"histogram {family} +Inf bucket {buckets[-1][1]} != "
                f"_count {hist_counts[family]}")
    return samples


class ObsHttpServer:
    """One per session when ``obs.http.enabled=true`` (api/session.py
    keeps it on ``session.obs_server``); ``port`` is the bound port
    (ephemeral when ``obs.http.port=0``)."""

    def __init__(self, session, host: str = "127.0.0.1",
                 port: int = 0):
        # weakref: the serving thread must not pin the session (and its
        # profile ring full of results) forever — when the session is
        # collected, the finalizer stops the server and frees the port
        self._session_ref = weakref.ref(session)
        handler = self._make_handler()
        self._httpd = ThreadingHTTPServer((host, int(port)), handler)
        self._httpd.daemon_threads = True
        self.host = host
        self.port = int(self._httpd.server_address[1])
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name=f"obs-http-{self.port}", daemon=True)
        self._thread.start()
        self._finalizer = weakref.finalize(
            session, ObsHttpServer._shutdown_httpd, self._httpd)

    @staticmethod
    def _shutdown_httpd(httpd) -> None:
        try:
            httpd.shutdown()
            httpd.server_close()
        except OSError:
            pass

    def _session_obj(self):
        """The served session, or None once it was collected (the
        finalizer is stopping the server; a racing request gets 503)."""
        return self._session_ref()

    # -- route payloads ----------------------------------------------------
    def _metrics_text(self, session) -> str:
        reg = obsreg.get_registry()
        try:
            # live scheduler gauges at scrape time: a scrape between
            # queries must still see the current queue/running levels,
            # not the last admission's stale publish
            st = session.scheduler.controller.stats()
            reg.set_gauge("sched.queued", st["queued"])
            reg.set_gauge("sched.running", st["running"])
            reg.set_gauge("sched.admittedBytes", st["admitted_bytes"])
            # saturation gauge set — the elastic-executor input signal
            # (ROADMAP item 2): queue depth plus admitted/running as
            # fractions of their budgets, refreshed at scrape time so a
            # scaler polling /metrics always sees the live level
            ctrl = session.scheduler.controller
            reg.set_gauge("sched.queueDepth", st["queued"])
            budget = float(getattr(ctrl, "memory_budget", 0) or 0)
            reg.set_gauge(
                "sched.admittedFraction",
                (st["admitted_bytes"] / budget) if budget > 0 else 0.0)
            slots = float(getattr(ctrl, "max_concurrent", 0) or 0)
            reg.set_gauge(
                "sched.runningFraction",
                (st["running"] / slots) if slots > 0 else 0.0)
        except Exception:
            pass
        try:
            # serving-tier gauges, refreshed at scrape time like the
            # scheduler's (a scrape between requests must see current
            # session/cache levels, not the last mutation's publish)
            srv = getattr(session, "serve_server", None)
            if srv is not None:
                from spark_rapids_tpu.serve import result_cache
                reg.set_gauge("serve.activeSessions",
                              len(srv.sessions()))
                rc = result_cache.stats()
                reg.set_gauge("serve.resultCacheBytes", rc["bytes"])
                reg.set_gauge("serve.resultCacheEntries", rc["entries"])
                reg.set_gauge("serve.resultCache.oldestEntryAgeSec",
                              result_cache.oldest_entry_age_s())
                # live leak-audit gauges: connections, streamer
                # threads and the retained-stream resume window (the
                # chaos gate asserts these return to zero after drain)
                leaks = srv.leak_stats()
                reg.set_gauge("serve.connections",
                              leaks["connections"])
                reg.set_gauge("serve.streamerThreads",
                              leaks["streamer_threads"])
                reg.set_gauge("serve.retainedStreams",
                              leaks["retained_streams"])
                reg.set_gauge("serve.retainedStreamBytes",
                              leaks["retained_bytes"])
        except Exception:
            pass
        return render_prometheus(reg.snapshot())

    @staticmethod
    def _resultcache_json() -> str:
        """Per-entry inspection (the /queries idiom applied to the
        result cache): age, bytes, stamped sources, and the current
        stamp DRIFT per entry — how many of its files changed/vanished
        and how many new files appeared since it was frozen — so an
        operator can see exactly what the incremental refresher is
        keeping warm and what will fall back to a full recompute."""
        from spark_rapids_tpu.io import scan_cache as sc
        from spark_rapids_tpu.serve import result_cache
        rows = result_cache.entries_info()
        for row in rows:
            old = [tuple(s) for s in row.pop("stamps")]
            paths = [s[1] for s in old]
            cur = sc.source_stamps(paths)
            if cur is None:
                # at least one file is gone: stamp each survivor
                cur = tuple(k for k in (sc.file_key(p) for p in paths)
                            if k is not None)
            delta = sc.classify_stamp_delta(old, cur)
            row["sources"] = len(paths)
            row["stamp_drift"] = {
                "kind": delta.kind,
                "appended": len(delta.appended),
                "rewritten": len(delta.rewritten),
                "deleted": len(delta.deleted),
                "drifted_files": len(delta.rewritten)
                + len(delta.deleted) + len(delta.appended),
            }
        return json.dumps({"entries": rows,
                           "stats": result_cache.stats()})

    @staticmethod
    def _queries_json(session) -> str:
        return json.dumps(
            {"queries": session.scheduler.query_table()},
            default=str)

    @staticmethod
    def _compiles_json(max_events: int = 256) -> str:
        # function-level imports (the serve.result_cache idiom in
        # _metrics_text): the handler reaches sideways only when the
        # route is actually hit, so the module stays load-order safe
        from spark_rapids_tpu.kernels import backend as kernel_backend
        from spark_rapids_tpu.obs import compile as obscompile
        payload = obscompile.snapshot(max_events=max_events)
        payload["selection"] = kernel_backend.selection_snapshot()
        return json.dumps(payload, default=str)

    @staticmethod
    def _tenants_json() -> str:
        """Resource-ledger table: one row per (session, workload)
        tenant, assembled under the ledger's ONE lock (the /compiles
        idiom) so concurrent scrapes see a consistent snapshot even
        while queries charge mid-flight."""
        from spark_rapids_tpu.obs import accounting as acct
        return json.dumps(acct.snapshot(), default=str)

    @staticmethod
    def _slo_json() -> str:
        """Per-template SLO quantiles interpolated from the bucketed
        histograms (one registry snapshot = one lock), plus the
        template-key legend so short keys resolve back to statement
        text."""
        from spark_rapids_tpu.obs import accounting as acct
        snap = obsreg.get_registry().snapshot()
        hists = {}
        for name, h in sorted(snap.get("bucket_histograms", {}).items()):
            hists[name] = {
                "count": h["count"],
                "sum_ms": h["sum"],
                "p50": obsreg.bucket_quantile(h["bounds"], h["counts"],
                                              0.50),
                "p95": obsreg.bucket_quantile(h["bounds"], h["counts"],
                                              0.95),
                "p99": obsreg.bucket_quantile(h["bounds"], h["counts"],
                                              0.99),
            }
        return json.dumps({"histograms": hists,
                           "bounds_ms": list(obsreg.DEFAULT_MS_BOUNDS),
                           "templates": acct.template_labels()})

    @staticmethod
    def _healthz_json(session) -> str:
        """Liveness + serve-plane lifecycle: a draining or drained
        serve tier used to answer the same body as a live one, so no
        load balancer could take the replica out of rotation before
        the kill — the fleet router keys placement on ``state`` and
        falls back to in-flight draining on ``inflight``."""
        state, inflight = "serving", 0
        try:
            srv = getattr(session, "serve_server", None)
            if srv is not None:
                state = srv.state()
                inflight = srv.inflight_count()
        except Exception:
            pass
        return json.dumps(
            {"ok": True, "state": state, "inflight": inflight,
             "routes": ["/metrics", "/queries", "/profiles/<qid>",
                        "/compiles", "/resultcache", "/tenants",
                        "/slo", "/healthz"]})

    @staticmethod
    def _profile_json(session, qid: int) -> Optional[str]:
        prof = session.query_profile(qid)
        if prof is None:
            return None
        return prof.to_json(indent=None)

    def _make_handler(self):
        server = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):   # no stderr chatter per scrape
                pass

            def _send(self, code: int, body: str,
                      ctype: str = "application/json") -> None:
                payload = body.encode("utf-8")
                self.send_response(code)
                self.send_header("Content-Type",
                                 ctype + "; charset=utf-8")
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)

            def do_GET(self):
                try:
                    session = server._session_obj()
                    if session is None:
                        self._send(503, json.dumps(
                            {"error": "session gone; server stopping"}))
                        return
                    raw_path, _, query = self.path.partition("?")
                    path = raw_path.rstrip("/") or "/"
                    if path == "/metrics":
                        # version 0.0.4 — the text exposition content
                        # type Prometheus scrapers negotiate
                        self._send(200, server._metrics_text(session),
                                   "text/plain; version=0.0.4")
                    elif path == "/queries":
                        self._send(200, server._queries_json(session))
                    elif path == "/compiles":
                        n = 256
                        for part in query.split("&"):
                            if part.startswith("n=") and \
                                    part[2:].isdigit():
                                n = int(part[2:])
                        self._send(200, server._compiles_json(n))
                    elif path == "/resultcache":
                        self._send(200, server._resultcache_json())
                    elif path == "/tenants":
                        self._send(200, server._tenants_json())
                    elif path == "/slo":
                        self._send(200, server._slo_json())
                    elif path.startswith("/profiles/"):
                        tail = path.rsplit("/", 1)[1]
                        body = (server._profile_json(session, int(tail))
                                if tail.isdigit() else None)
                        if body is None:
                            self._send(404, json.dumps(
                                {"error": f"no profile for {tail!r} "
                                          "(evicted or unknown)"}))
                        else:
                            self._send(200, body)
                    elif path in ("/", "/healthz"):
                        self._send(200, server._healthz_json(session))
                    else:
                        self._send(404, json.dumps(
                            {"error": f"unknown route {path!r}"}))
                except (BrokenPipeError, ConnectionResetError):
                    pass
                except Exception as e:   # a bad scrape must not kill
                    try:                 # the serving thread
                        self._send(500, json.dumps(
                            {"error": f"{type(e).__name__}: {e}"}))
                    except OSError:
                        pass

        return Handler

    def shutdown(self) -> None:
        """Stop serving and release the socket (idempotent; also fired
        automatically when the served session is garbage-collected)."""
        self._shutdown_httpd(self._httpd)
