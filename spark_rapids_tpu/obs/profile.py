"""Per-query profiles: the whole-query view assembled after each action.

A :class:`QueryProfile` is the engine's answer to "where did this query
spend its time": the physical plan tree annotated per-exec with
rows/batches/time/extra, a wall-clock breakdown (host prep vs upload vs
dispatch vs shuffle vs semaphore wait), the per-query registry delta
grouped into sections (scan / shuffle / semaphore / spill / pyworker),
spill and arena high-water marks, the plan-time ``explain`` fallback
report, and the query's span window (exportable as a Chrome trace).

Assembly: :class:`QueryRun` is opened by ``TpuSparkSession._execute``
before planning; ``finish()`` carves the registry delta and span window
and walks the executed plan.  Surfaces:
``session.last_query_profile()``, ``DataFrame.explain("profile")``,
``profile.to_json()`` and ``profile.dump_chrome_trace(path)``.
"""

from __future__ import annotations

import contextlib
import json
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from spark_rapids_tpu.obs import registry as obsreg
from spark_rapids_tpu.obs import trace as obstrace

# registry sections the profile always surfaces, even when empty — the
# acceptance contract is "includes scan, shuffle, semaphore, and spill
# sections" whether or not the query touched them
SECTIONS = ("scan", "shuffle", "semaphore", "spill", "pyworker",
            "fusion", "sched", "kernel", "compile", "incremental",
            "sharing", "join")

# work-sharing metrics routed into one "sharing" section even though
# their names span three prefixes (sched.dedup.*, scan.shared.*,
# serve.batch.*): the per-query work-sharing story — flights joined,
# scan batches multicast, statements coalesced — reads as one section
_SHARING_PREFIXES = ("sched.dedup.", "scan.shared.", "serve.batch.")

# the out-of-core/skew join story reads as ONE section: grace-join
# counters (join.grace.* — activations, partitions, restreams, spilled
# build bytes, recursion depth) route by their natural prefix, and the
# shuffle-boundary skew-split counters (shuffle.skew.* — hot buckets
# detected, splits, broadcast-vs-replicate decisions) are pulled in
# beside them so a skewed join's whole mitigation record sits together
_JOIN_PREFIXES = ("join.", "shuffle.skew.")

# compile-observatory metrics routed into the "compile" section even
# though their names carry the kernel. prefix: the per-query compile
# story (programs compiled, cache tiers, compile wall) reads as one
# section instead of drowning in the dispatch counters
_COMPILE_SECTION = ("kernel.cache.compiles", "kernel.cache.memHits",
                    "kernel.cache.persistentHits")


def _section_of(name: str) -> str:
    if name.startswith("kernel.compile.") or name in _COMPILE_SECTION:
        return "compile"
    if name.startswith(_SHARING_PREFIXES):
        return "sharing"
    if name.startswith(_JOIN_PREFIXES):
        return "join"
    return name.split(".", 1)[0]


@dataclass
class ExecNodeProfile:
    """One physical-plan node's annotated metrics."""

    name: str
    is_tpu: bool
    rows: int
    batches: int
    time_ns: int
    peak_dev_memory: int
    extra: Dict[str, Any]
    children: List["ExecNodeProfile"] = field(default_factory=list)

    @classmethod
    def from_plan(cls, node) -> "ExecNodeProfile":
        m = node.metrics
        return cls(
            name=node.simple_string(),
            is_tpu=bool(node.is_tpu),
            rows=int(m.num_output_rows),
            batches=int(m.num_output_batches),
            time_ns=int(m.total_time_ns),
            peak_dev_memory=int(m.peak_dev_memory),
            extra=dict(m.extra),
            children=[cls.from_plan(c) for c in node.children])

    def to_dict(self) -> Dict[str, Any]:
        extra = {}
        for k, v in self.extra.items():
            extra[k] = v
            # time-valued extras are ns internally (the Metrics unit
            # contract); render the explicit seconds view alongside
            if isinstance(v, (int, float)) and (
                    k.endswith("Time") or k.endswith("Ns")):
                extra[k + "_s"] = v / 1e9
        return {"name": self.name, "is_tpu": self.is_tpu,
                "rows": self.rows, "batches": self.batches,
                "time_ns": self.time_ns,
                "time_s": self.time_ns / 1e9,
                "peak_dev_memory": self.peak_dev_memory,
                "extra": extra,
                "children": [c.to_dict() for c in self.children]}

    def tree_lines(self, depth: int = 0) -> List[str]:
        pad = "  " * depth
        bits = [f"rows={self.rows}", f"batches={self.batches}",
                f"time={self.time_ns / 1e9:.4f}s"]
        for k in sorted(self.extra):
            v = self.extra[k]
            if isinstance(v, (int, float)) and (
                    k.endswith("Time") or k.endswith("Ns")):
                bits.append(f"{k}={v / 1e9:.4f}s")
            else:
                bits.append(f"{k}={v}")
        star = "*" if self.is_tpu else " "
        lines = [f"{pad}{star}{self.name} [{', '.join(bits)}]"]
        for c in self.children:
            lines.extend(c.tree_lines(depth + 1))
        return lines

    def walk(self):
        yield self
        for c in self.children:
            yield from c.walk()


@dataclass
class QueryProfile:
    """The whole-query observability record (see module docstring)."""

    query_id: int
    status: str                      # "success" | "failure"
    error: Optional[str]
    result_rows: Optional[int]
    wall_ns: int
    phases: Dict[str, int]           # phase name -> ns
    plan: Optional[ExecNodeProfile]
    metrics: Dict[str, Dict[str, Any]]   # section -> flat metric dict
    wall_breakdown: Dict[str, float]     # phase -> seconds
    explain_lines: List[str]
    spans: List[Dict[str, Any]]
    # canonical logical-plan digest (plan/digest.py): alias-insensitive
    # identity shared with the kernel-cache keys and the serving tier's
    # result-set cache; also a /queries column
    plan_digest: Optional[str] = None
    _raw_spans: List[Any] = field(default_factory=list, repr=False)

    # -- rendering ---------------------------------------------------------
    def to_dict(self) -> Dict[str, Any]:
        return {
            "query_id": self.query_id,
            "plan_digest": self.plan_digest,
            "status": self.status,
            "error": self.error,
            "result_rows": self.result_rows,
            "wall_s": self.wall_ns / 1e9,
            "phases": {k: v / 1e9 for k, v in self.phases.items()},
            "plan": self.plan.to_dict() if self.plan else None,
            "metrics": self.metrics,
            "wall_breakdown": self.wall_breakdown,
            "explain_lines": self.explain_lines,
            "spans": self.spans,
        }

    def to_json(self, indent: Optional[int] = 2) -> str:
        return json.dumps(self.to_dict(), indent=indent, default=str)

    def tree_string(self) -> str:
        head = [f"QueryProfile #{self.query_id} [{self.status}] "
                f"wall={self.wall_ns / 1e9:.4f}s "
                f"rows={self.result_rows}"]
        for k, v in self.wall_breakdown.items():
            head.append(f"  {k}: {v:.4f}" +
                        ("" if k.endswith("bytes") else "s"))
        if self.plan is not None:
            head.extend(self.plan.tree_lines(1))
        return "\n".join(head)

    def dump_chrome_trace(self, path: str) -> str:
        """Write this query's span window as Chrome trace-event JSON."""
        return obstrace.dump_chrome_trace(path, self._raw_spans)


def _sectioned(delta: Dict[str, Any]) -> Dict[str, Dict[str, Any]]:
    """Group a registry delta's flat names into profile sections by
    prefix; the canonical sections always exist."""
    out: Dict[str, Dict[str, Any]] = {s: {} for s in SECTIONS}
    for kind in ("counters", "gauges"):
        for name, v in delta.get(kind, {}).items():
            d = out.setdefault(_section_of(name), {})
            d[name] = v
            if isinstance(v, (int, float)) and name.endswith("Ns"):
                d[name + "_s"] = v / 1e9
    for name, h in delta.get("histograms", {}).items():
        out.setdefault(_section_of(name), {})[name] = h
    return out


def _compile_attr_s(query_id: Optional[int],
                    sections: Dict[str, Dict[str, Any]]) -> float:
    """Compile wall this query triggered, in seconds: the compile
    observatory's exact token-based attribution (the same source the
    /queries rows and slow-query log use), falling back to the
    registry-window delta only when the ledger never saw the query —
    a window delta alone would bleed a concurrent neighbour's compiles
    into this breakdown."""
    if query_id is not None:
        with contextlib.suppress(Exception):
            from spark_rapids_tpu.obs import compile as obscompile
            stats = obscompile.query_stats(query_id)
            if stats is not None:
                return stats["compile_ms"] / 1e3
    return sections.get("compile", {}).get(
        "kernel.compile.wallNs", 0) / 1e9


def _breakdown(plan: Optional[ExecNodeProfile],
               sections: Dict[str, Dict[str, Any]],
               wall_ns: int,
               query_id: Optional[int] = None) -> Dict[str, float]:
    """Wall-clock breakdown in seconds: host prep vs upload vs dispatch
    vs shuffle vs semaphore wait, plus spill traffic in bytes."""
    host_prep = upload = dispatch = shuffle = fused = 0.0
    shuf_map = shuf_transfer = shuf_decode = 0.0
    if plan is not None:
        for n in plan.walk():
            host_prep += n.extra.get("scan.hostPrepTime", 0) / 1e9
            upload += n.extra.get("scan.uploadTime", 0) / 1e9
            # the shuffle wall SPLIT: map-stage compute vs DCN transfer
            # vs reduce-side decode+upload (exchange extras, ns; the
            # map leg is ONE fleet-wide wall in both launch modes —
            # first submit to last submit out — never a per-thread
            # sum).  The legs are walls of possibly-CONCURRENT phases
            # — with the pipelined exchange their sum exceeds
            # shuffle_s exactly when overlap is working
            # (shuffle.pipeline.overlapNs is the headline for how
            # much)
            shuf_map += n.extra.get("exchange.mapStages", 0) / 1e9
            shuf_transfer += n.extra.get("exchange.transfer", 0) / 1e9
            shuf_decode += n.extra.get("exchange.upload", 0) / 1e9
            if "Exchange" in n.name or "Shuffle" in n.name:
                shuffle += n.time_ns / 1e9
            elif n.is_tpu:
                dispatch += n.time_ns / 1e9
                if n.name.startswith("TpuFusedStageExec"):
                    # fused-stage share of dispatch time, so the
                    # whole-stage fusion layer's cost/benefit is
                    # attributable per query (also counted in
                    # dispatch_s — this is an attribution, not a
                    # disjoint phase)
                    fused += n.time_ns / 1e9
    sem = sections.get("semaphore", {})
    spill = sections.get("spill", {})
    sched = sections.get("sched", {})
    return {
        "wall_s": wall_ns / 1e9,
        "queue_wait_s": sched.get("sched.queueWaitNs", 0) / 1e9,
        "host_prep_s": host_prep,
        "upload_s": upload,
        "dispatch_s": dispatch,
        "fused_stage_s": fused,
        # compile wall this query triggered (obs/compile.py; first
        # (kernel, shape) calls — an attribution inside dispatch_s and
        # the exec node times, not a disjoint phase)
        "compile_s": _compile_attr_s(query_id, sections),
        "shuffle_s": shuffle,
        "shuffle_map_s": shuf_map,
        "shuffle_transfer_s": shuf_transfer,
        "shuffle_decode_s": shuf_decode,
        "semaphore_wait_s": sem.get("semaphore.waitNs", 0) / 1e9,
        "spill_device_to_host_bytes":
            spill.get("spill.deviceToHostBytes", 0),
        "spill_host_to_disk_bytes":
            spill.get("spill.hostToDiskBytes", 0),
    }


class _Phase:
    __slots__ = ("run", "name", "t0")

    def __init__(self, run: "QueryRun", name: str):
        self.run = run
        self.name = name

    def __enter__(self):
        self.t0 = time.perf_counter_ns()
        return self

    def __exit__(self, *a):
        dur = time.perf_counter_ns() - self.t0
        self.run.phases[self.name] = \
            self.run.phases.get(self.name, 0) + dur
        obstrace.record(f"query.{self.name}", self.t0, dur, cat="query")


class QueryRun:
    """Per-query capture opened by the session before planning."""

    def __init__(self, query_id: int,
                 sched_extra: Optional[Dict[str, Any]] = None,
                 plan_digest: Optional[str] = None):
        self.query_id = query_id
        self.plan_digest = plan_digest
        self.phases: Dict[str, int] = {}
        # the session stashes the planner's OverrideResult here as soon
        # as planning succeeds, so a mid-execution failure still
        # profiles the plan (the on_failure contract carries the tree)
        self.planned = None
        # scheduler attribution (queue wait, admission estimate,
        # priority) — recorded by the QueryService BEFORE this run
        # opened its registry view, so it rides the profile explicitly
        # instead of the (later) per-query delta carve
        self.sched_extra: Dict[str, Any] = dict(sched_extra or {})
        self._view = obsreg.get_registry().view()
        self._span_mark = obstrace.mark()
        self._t0 = time.perf_counter_ns()
        wait = self.sched_extra.get("sched.queueWaitNs", 0)
        if wait:
            # re-record the pre-execution queue wait inside this
            # query's span window, so its trace shows the wait
            obstrace.record("sched.queueWait", self._t0 - int(wait),
                            int(wait), cat="sched",
                            args={"query": query_id})

    def phase(self, name: str) -> _Phase:
        return _Phase(self, name)

    def finish(self, result=None, table=None,
               error: Optional[BaseException] = None) -> QueryProfile:
        """Assemble the QueryProfile.  ``result`` is the planner's
        OverrideResult (may be None when planning itself failed);
        ``table`` the collected Arrow table on success."""
        wall_ns = time.perf_counter_ns() - self._t0
        plan_prof = None
        explain_lines: List[str] = []
        if result is not None:
            with contextlib.suppress(Exception):
                plan_prof = ExecNodeProfile.from_plan(result.plan)
            with contextlib.suppress(Exception):
                explain_lines = result.meta.explain_lines(all_=True)
        delta = self._view.delta()
        sections = _sectioned(delta)
        if self.sched_extra:
            sec = sections.setdefault("sched", {})
            for k, v in self.sched_extra.items():
                sec[k] = v
                if isinstance(v, (int, float)) and k.endswith("Ns"):
                    sec[k + "_s"] = v / 1e9
        # arena / spill high-water marks ride the spill section
        with contextlib.suppress(Exception):
            from spark_rapids_tpu.mem import spill as spillmod
            if spillmod.is_enabled():
                cat = spillmod.get_catalog()
                sections["spill"]["spill.deviceBytesNow"] = \
                    cat.device_bytes
                sections["spill"]["spill.hostBytesNow"] = cat.host_bytes
                sections["spill"]["spill.arenaPeakBytes"] = \
                    cat.host_arena.peak()
        raw_spans = obstrace.spans_since(self._span_mark)
        prof = QueryProfile(
            query_id=self.query_id,
            plan_digest=self.plan_digest,
            status="failure" if error is not None else "success",
            error=(f"{type(error).__name__}: {error}"
                   if error is not None else None),
            result_rows=(table.num_rows if table is not None else None),
            wall_ns=wall_ns,
            phases=dict(self.phases),
            plan=plan_prof,
            metrics=sections,
            wall_breakdown=_breakdown(plan_prof, sections, wall_ns,
                                      query_id=self.query_id),
            explain_lines=explain_lines,
            spans=obstrace.span_dicts(raw_spans),
            _raw_spans=raw_spans)
        return prof
