"""API audit: Cpu*Exec ↔ Tpu*Exec constructor-signature drift detection.

Reference analog: the ``api_validation`` module
(api_validation/.../ApiValidation.scala:181) reflectively diffs every
``Gpu*Exec`` constructor against its Spark counterpart per shim version to
catch upstream signature drift.  Here the "upstream" is our own CPU engine:
every TPU exec must stay constructible from the same planning information
as the CPU exec it replaces, so the per-class diff below catches the same
kind of drift the reference's auditor does.

Run: ``python -m spark_rapids_tpu.api_validation`` (prints a report,
exit code 1 on unexpected drift), or call :func:`audit` from tests.
"""

from __future__ import annotations

import inspect
from typing import Dict, List, Tuple, Type

# (cpu param names that the tpu side is allowed to add/substitute) —
# conf-like trailing params carry engine configuration, not plan info,
# and key_dtypes is pre-resolved promotion info only the CPU oracle needs
_ALLOWED_EXTRA = {"conf", "conf_obj", "min_bucket", "max_batch_rows",
                  "key_dtypes"}

# documented, deliberate signature deltas (reference's audit likewise
# prints a report of knowns rather than failing on them)
_KNOWN_DIFFS = {
    # broadcast/nested-loop CPU execs are thin *args wrappers over
    # CpuJoinExec; the TPU classes take the join fields directly
    "CpuBroadcastHashJoinExec",
    "CpuBroadcastNestedLoopJoinExec",
    # cartesian on CPU shares CpuJoinExec's full signature; the TPU exec
    # only needs (left, right, condition, schema) since a cross join has
    # no keys
    "CpuCartesianProductExec",
}


def _exec_classes() -> Dict[str, Type]:
    import spark_rapids_tpu.exec.cpu as cpux
    import spark_rapids_tpu.exec.cache as cachex
    import spark_rapids_tpu.exec.cpu_window as cpuw
    import spark_rapids_tpu.exec.generate as genx
    import spark_rapids_tpu.exec.tpu_aggregate as tpa
    import spark_rapids_tpu.exec.tpu_basic as tpb
    import spark_rapids_tpu.exec.tpu_join as tpj
    import spark_rapids_tpu.exec.tpu_sort as tps
    import spark_rapids_tpu.exec.tpu_window as tpw
    import spark_rapids_tpu.io.device_scan as devscan
    import spark_rapids_tpu.io.readers as readers
    import spark_rapids_tpu.pyworker.execs as pyx
    import spark_rapids_tpu.shuffle.exchange as ex

    out: Dict[str, Type] = {}
    for mod in (cpux, cachex, cpuw, genx, tpa, tpb, tpj, tps, tpw,
                devscan, readers, pyx, ex):
        for name, cls in vars(mod).items():
            if inspect.isclass(cls) and name.endswith("Exec") and \
                    (name.startswith("Cpu") or name.startswith("Tpu")):
                out.setdefault(name, cls)
    return out


def _params(cls: Type) -> List[str]:
    sig = inspect.signature(cls.__init__)
    return [p for p in sig.parameters if p != "self"]


def audit() -> Tuple[List[str], List[str], List[str]]:
    """Returns (problems, knowns, audited_pairs)."""
    classes = _exec_classes()
    problems: List[str] = []
    knowns: List[str] = []
    pairs: List[str] = []
    for name, cpu_cls in sorted(classes.items()):
        if not name.startswith("Cpu"):
            continue
        tpu_name = "Tpu" + name[3:]
        tpu_cls = classes.get(tpu_name)
        if tpu_cls is None:
            # CPU-only execs are legitimate (they're the fallback), but
            # record them so a missing TPU counterpart is a visible,
            # deliberate state rather than silent drift
            continue
        pairs.append(f"{name} <-> {tpu_name}")
        cpu_p, tpu_p = _params(cpu_cls), _params(tpu_cls)
        cpu_core = [p for p in cpu_p if p not in _ALLOWED_EXTRA]
        tpu_core = [p for p in tpu_p if p not in _ALLOWED_EXTRA]
        if cpu_core != tpu_core:
            msg = (f"{name}({', '.join(cpu_p)}) vs "
                   f"{tpu_name}({', '.join(tpu_p)}): plan-info params "
                   f"differ: {cpu_core} != {tpu_core}")
            (knowns if name in _KNOWN_DIFFS else problems).append(msg)
    return problems, knowns, pairs


def main() -> int:
    problems, knowns, pairs = audit()
    print(f"audited {len(pairs)} Cpu<->Tpu exec pairs")
    for p in pairs:
        print(f"  ok  {p}")
    for p in knowns:
        print(f"  known  {p}")
    if problems:
        print("SIGNATURE DRIFT:")
        for p in problems:
            print(f"  !!  {p}")
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
