"""Cooperative query cancellation: tokens + checkpoints.

A :class:`CancelToken` is created per submitted query by the
:mod:`spark_rapids_tpu.sched.service` layer and *installed* on every
thread that does work for that query — the service worker itself, the
session task pool (``_drain_partitions``), scan prefetch threads, and
exchange map-stage submit threads.  Hot paths call
:func:`check_current` (one thread-local read + one bool check when no
cancellation is pending) and unwind with :class:`QueryCancelledError`
/ :class:`QueryTimeoutError` when the token fires, so a cancelled or
timed-out query releases its admission slot, drains its prefetcher,
cancels in-flight shuffle fetches, and frees spill-catalog entries
through the same ``finally`` paths an ordinary failure takes.

Reference analog: Spark's ``TaskContext.isInterrupted`` checked by
long-running task loops (the reference plugin inherits it); on this
engine queries are driver-side thread trees, so the token is the task
kill flag.
"""

from __future__ import annotations

import contextlib
import threading
from typing import Callable, List, Optional


class QueryCancelledError(RuntimeError):
    """The query's CancelToken fired (user cancel() or unwind)."""


class QueryTimeoutError(QueryCancelledError):
    """The query's deadline elapsed (``sched.defaultTimeoutMs`` or the
    per-submit override); subclasses :class:`QueryCancelledError` so
    every cancellation checkpoint raises the precise type without
    knowing why the token fired."""


class CancelToken:
    """Per-query cancellation flag with wake-up callbacks.

    ``cancel()`` is idempotent (first caller wins, returns True);
    callbacks registered via :meth:`add_callback` run exactly once —
    on the cancelling thread, or immediately at registration when the
    token already fired — so blocked waiters (admission condition
    variables, shuffle completion queues) can be woken event-driven
    instead of polled.  Callback exceptions are swallowed: a broken
    waker must not mask the cancellation itself.
    """

    __slots__ = ("query_id", "reason", "_cancelled", "_timed_out",
                 "_lock", "_callbacks")

    def __init__(self, query_id: Optional[int] = None):
        self.query_id = query_id
        self.reason: Optional[str] = None
        self._cancelled = False
        self._timed_out = False
        self._lock = threading.Lock()
        self._callbacks: List[Callable[[], None]] = []

    def cancel(self, reason: str = "cancelled",
               timed_out: bool = False) -> bool:
        with self._lock:
            if self._cancelled:
                return False
            self._cancelled = True
            self._timed_out = timed_out
            self.reason = reason
            callbacks, self._callbacks = self._callbacks, []
        for fn in callbacks:
            try:
                fn()
            except Exception:
                pass
        return True

    @property
    def is_cancelled(self) -> bool:
        return self._cancelled

    @property
    def timed_out(self) -> bool:
        return self._timed_out

    def add_callback(self, fn: Callable[[], None]) -> None:
        with self._lock:
            if not self._cancelled:
                self._callbacks.append(fn)
                return
        try:
            fn()
        except Exception:
            pass

    def remove_callback(self, fn: Callable[[], None]) -> None:
        with self._lock:
            with contextlib.suppress(ValueError):
                self._callbacks.remove(fn)

    def check(self) -> None:
        """Raise the precise cancellation exception if fired."""
        if self._cancelled:
            qid = f"query {self.query_id}: " if self.query_id else ""
            if self._timed_out:
                raise QueryTimeoutError(qid + (self.reason or "timed out"))
            raise QueryCancelledError(qid + (self.reason or "cancelled"))


# ---------------------------------------------------------------------------
# Thread-local current token (explicit capture/install, because the
# engine's thread pools — task pool, scan prefetcher, map-stage submit
# threads — do not propagate contextvars automatically)
# ---------------------------------------------------------------------------

_TLS = threading.local()


def current() -> Optional[CancelToken]:
    """The token installed on this thread (None outside any query)."""
    return getattr(_TLS, "token", None)


@contextlib.contextmanager
def install(token: Optional[CancelToken]):
    """Install ``token`` as this thread's current query token.  Pool
    workers capture ``current()`` on the submitting thread and install
    it in the worker (the explicit-capture idiom)."""
    prev = getattr(_TLS, "token", None)
    _TLS.token = token
    try:
        yield token
    finally:
        _TLS.token = prev


def check_current() -> None:
    """The cancellation checkpoint the exec hot paths call per batch:
    one thread-local read + one bool check when nothing is cancelled."""
    tok = getattr(_TLS, "token", None)
    if tok is not None and tok._cancelled:
        tok.check()
