"""Memory-budget-aware query admission control + the device task gate.

Replaces the bare ``concurrentGpuTasks`` counting semaphore as the
engine's concurrency authority (reference: GpuSemaphore.scala:27-161),
split into the two layers the reference conflates:

* :class:`AdmissionController` — **inter-query**: each submitted query
  declares an HBM working-set estimate; the controller admits from a
  priority + FIFO wait queue while ``sum(estimates) <= memory_budget``
  with ``max_concurrent`` as a hard cap, and degrades gracefully by
  queueing (never by letting an over-committed fleet OOM).  Theseus
  (arXiv:2508.05029) and the Presto-GPU port both gate multi-query
  throughput this way: memory-aware admission + cross-query overlap of
  host prep with device dispatch, not per-query kernel speed.
* :class:`TaskGate` — **intra-query**: how many tasks of admitted
  queries may concurrently build device working sets (the original
  ``tpu_semaphore`` role, now re-entrant-aware:
  ``mem/device.tpu_semaphore`` keeps its surface and delegates here).

Estimates refine across runs: :class:`EstimateBook` keys the observed
device-bytes peak GROWTH over the query's run (the spill catalog's
arena accounting, ``HighWaterTracker.delta``) by *plan shape*, so the
second run of a query shape is admitted on what it actually added
rather than the conservative ``batchSize x concurrent scan/shuffle
depth`` derivation.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, Optional

from spark_rapids_tpu.obs import recorder as obsrec
from spark_rapids_tpu.obs import registry as obsreg
from spark_rapids_tpu.obs import trace as obstrace
from spark_rapids_tpu.sched import cancel as _cancel
from spark_rapids_tpu.sched.queue import WaitEntry, WaitQueue


class QueryRejectedError(RuntimeError):
    """Submission refused outright (wait queue at ``sched.maxQueued``)."""


# ---------------------------------------------------------------------------
# Intra-query device task gate (the tpu_semaphore backing store)
# ---------------------------------------------------------------------------

class TaskGate:
    """Re-entrant-aware device-concurrency gate.

    A thread that already holds a slot re-enters for free (scan
    prefetch finishing under an exchange used to take a SECOND slot —
    deadlocking at 1 slot and double-counting blocked-ns under
    contention); only the outermost acquire touches the semaphore.
    Blocking acquires poll in short slices so a cancelled query stops
    waiting for a device slot instead of parking on it."""

    def __init__(self, slots: int):
        self.slots = max(1, int(slots))
        self._sem = threading.BoundedSemaphore(self.slots)
        self._tls = threading.local()

    def acquire(self) -> tuple:
        """Returns ``(wait_ns, reentrant)``; raises the cancellation
        exception instead of blocking when this query's token fires."""
        depth = getattr(self._tls, "depth", 0)
        if depth:
            self._tls.depth = depth + 1
            return 0, True
        wait_ns = 0
        if not self._sem.acquire(blocking=False):
            t0 = time.perf_counter_ns()
            while not self._sem.acquire(timeout=0.05):
                _cancel.check_current()
            wait_ns = time.perf_counter_ns() - t0
        self._tls.depth = 1
        return wait_ns, False

    def release(self) -> None:
        depth = getattr(self._tls, "depth", 0)
        if depth > 1:
            self._tls.depth = depth - 1
            return
        self._tls.depth = 0
        self._sem.release()

    @property
    def held_by_current_thread(self) -> bool:
        return getattr(self._tls, "depth", 0) > 0

    def available(self) -> int:
        """Free slots right now (test/diagnostic surface)."""
        return self._sem._value


# ---------------------------------------------------------------------------
# Plan-shape keyed estimate refinement
# ---------------------------------------------------------------------------

def plan_shape_key(plan) -> Any:
    """Structural signature of a logical plan: node class names +
    output column names, recursively.  Two queries with the same shape
    share an estimate-book entry (literal values intentionally ignored
    — a changed filter constant rarely changes the working set
    class)."""
    try:
        names = tuple(plan.schema.names)
    except Exception:
        names = ()
    return (type(plan).__name__, names,
            tuple(plan_shape_key(c) for c in plan.children))


class EstimateBook:
    """Bounded map of plan shape -> observed device-bytes high water.

    ``record`` takes a new high observation as-is but DECAYS toward
    lower ones (halfway per run) instead of keeping the max forever —
    one run that overlapped a heavyweight neighbour must not
    permanently serialize a cheap shape; ``estimate`` returns the
    observation padded with headroom.  LRU eviction at
    ``max_entries``."""

    HEADROOM = 1.25
    FLOOR = 16 << 20

    def __init__(self, max_entries: int = 256):
        from collections import OrderedDict
        self._max = max_entries
        self._book: "OrderedDict[Any, int]" = OrderedDict()
        self._lock = threading.Lock()

    def estimate(self, shape_key: Any) -> Optional[int]:
        with self._lock:
            obs = self._book.get(shape_key)
            if obs is None:
                return None
            self._book.move_to_end(shape_key)
            return max(int(obs * self.HEADROOM), self.FLOOR)

    def record(self, shape_key: Any, observed_bytes: int) -> None:
        if observed_bytes <= 0:
            return
        with self._lock:
            old = self._book.get(shape_key)
            obs = int(observed_bytes)
            self._book[shape_key] = obs if old is None or obs >= old \
                else (old + obs) // 2
            self._book.move_to_end(shape_key)
            while len(self._book) > self._max:
                self._book.popitem(last=False)

    def __len__(self) -> int:
        with self._lock:
            return len(self._book)


# ---------------------------------------------------------------------------
# Admission controller
# ---------------------------------------------------------------------------

class AdmissionRequest:
    """One query's admission claim."""

    __slots__ = ("query_id", "estimate", "priority", "token",
                 "enqueue_ns", "queue_wait_ns")

    def __init__(self, query_id: int, estimate: int, priority: int = 0,
                 token: Optional[_cancel.CancelToken] = None):
        self.query_id = query_id
        self.estimate = max(0, int(estimate))
        self.priority = int(priority)
        self.token = token
        self.enqueue_ns = 0
        self.queue_wait_ns = 0


class AdmissionSlot:
    """Held admission: release exactly once (context-manager friendly)."""

    __slots__ = ("_controller", "_request", "_released")

    def __init__(self, controller: "AdmissionController",
                 request: AdmissionRequest):
        self._controller = controller
        self._request = request
        self._released = False

    def release(self) -> None:
        if not self._released:
            self._released = True
            self._controller._release(self._request)

    def __enter__(self):
        return self

    def __exit__(self, *a):
        self.release()


class AdmissionController:
    """Priority wait queue + memory-budget admission (module docstring).

    Invariants:
      * at most ``max_concurrent`` queries admitted;
      * ``admitted_bytes + estimate <= memory_budget`` — EXCEPT when
        nothing is running, where the head always admits (progress
        guarantee: a query estimated over the whole budget still runs,
        alone, leaning on the spill catalog instead of deadlocking);
      * strict head-of-line order within the priority bands.

    ``pressure_cb(bytes_needed)`` (wired to
    ``mem/spill.handle_memory_pressure``) fires when an admission lands
    in the top of the budget, proactively spilling registered batches so
    the admitted query's working set has real HBM behind its estimate.
    """

    # admissions that leave less than this fraction of the budget free
    # trigger the memory-pressure callback
    PRESSURE_FRACTION = 0.2

    def __init__(self, memory_budget: int, max_concurrent: int,
                 max_queued: int = 1024,
                 pressure_cb: Optional[Callable[[int], int]] = None):
        self.memory_budget = max(1, int(memory_budget))
        self.max_concurrent = max(1, int(max_concurrent))
        self.max_queued = max(1, int(max_queued))
        self._pressure_cb = pressure_cb
        self._cond = threading.Condition()
        self._queue = WaitQueue()
        self._running: Dict[int, int] = {}       # query_id -> estimate
        self.admitted_bytes = 0

    # -- introspection (tests, gauges) --------------------------------------
    def stats(self) -> Dict[str, int]:
        with self._cond:
            return {"queued": len(self._queue),
                    "running": len(self._running),
                    "admitted_bytes": self.admitted_bytes}

    def _publish_locked(self) -> None:
        reg = obsreg.get_registry()
        reg.set_gauge("sched.queued", len(self._queue))
        reg.set_gauge("sched.running", len(self._running))
        reg.set_gauge("sched.admittedBytes", self.admitted_bytes)
        reg.gauge_max("sched.runningHwm", len(self._running))

    def _can_admit_locked(self, estimate: int) -> bool:
        if len(self._running) >= self.max_concurrent:
            return False
        if not self._running:
            return True   # progress guarantee (see class docstring)
        return self.admitted_bytes + estimate <= self.memory_budget

    # -- the blocking acquire ------------------------------------------------
    def acquire(self, req: AdmissionRequest) -> AdmissionSlot:
        """Block until admitted; raises QueryRejectedError (queue full),
        QueryCancelledError / QueryTimeoutError (token fired while
        queued — the deadline timer cancels the token)."""
        reg = obsreg.get_registry()
        entry = WaitEntry(req.priority, req)
        req.enqueue_ns = time.perf_counter_ns()

        def wake() -> None:
            with self._cond:
                self._cond.notify_all()

        with self._cond:
            if len(self._queue) >= self.max_queued:
                reg.inc("sched.rejected")
                obsrec.record_event("sched.rejected",
                                    query=req.query_id,
                                    queued=len(self._queue))
                raise QueryRejectedError(
                    f"query {req.query_id}: wait queue full "
                    f"({self.max_queued} queued)")
            self._queue.push(entry)
            self._publish_locked()
        if req.token is not None:
            req.token.add_callback(wake)
        blocked = False
        try:
            with self._cond:
                while True:
                    if req.token is not None and req.token.is_cancelled:
                        raise self._queued_cancel_exc(req, reg)
                    if (self._queue.peek() is entry and
                            self._can_admit_locked(req.estimate)):
                        self._queue.pop_head()
                        self._running[req.query_id] = req.estimate
                        self.admitted_bytes += req.estimate
                        reg.inc("sched.admitted")
                        obsrec.record_event(
                            "sched.admitted", query=req.query_id,
                            estimate_bytes=req.estimate,
                            priority=req.priority,
                            running=len(self._running),
                            admitted_bytes=self.admitted_bytes)
                        self._publish_locked()
                        # wake the NEW head: budget may fit it too —
                        # without this, back-to-back admissions staircase
                        # on the defensive wait timeout
                        self._cond.notify_all()
                        break
                    # defensive timeout: a lost notify must not park the
                    # query forever (cancel/release both notify_all)
                    blocked = True
                    self._cond.wait(timeout=0.25)
        except BaseException:
            with self._cond:
                self._queue.remove(entry)
                self._publish_locked()
                self._cond.notify_all()
            raise
        finally:
            if req.token is not None:
                req.token.remove_callback(wake)
        # wait is attributed only when admission actually blocked — an
        # instantly admitted query reports 0 instead of clock-read noise
        # (keeps the ci smoke's `any(wait > 0)` assertion meaningful and
        # uncontended queries out of the queueWait span/histogram)
        req.queue_wait_ns = (time.perf_counter_ns() - req.enqueue_ns
                             if blocked else 0)
        if req.queue_wait_ns:
            reg.inc("sched.queueWaitNs", req.queue_wait_ns)
            reg.observe("sched.queueWait", req.queue_wait_ns)
            obstrace.record("sched.queueWait", req.enqueue_ns,
                            req.queue_wait_ns, cat="sched",
                            args={"query": req.query_id,
                                  "priority": req.priority})
        self._maybe_pressure(req.estimate)
        return AdmissionSlot(self, req)

    def _queued_cancel_exc(self, req: AdmissionRequest, reg):
        if req.token.timed_out:
            reg.inc("sched.timedOut")
        else:
            reg.inc("sched.cancelled")
        obsrec.record_event(
            "sched.cancelledWhileQueued", query=req.query_id,
            timed_out=bool(req.token.timed_out))
        try:
            req.token.check()
        except _cancel.QueryCancelledError as e:
            return e
        return _cancel.QueryCancelledError(
            f"query {req.query_id}: cancelled while queued")

    def _maybe_pressure(self, estimate: int) -> None:
        """Outside the lock: when the admission lands in the top of the
        budget, ask the spill catalog to free real HBM up front."""
        if self._pressure_cb is None:
            return
        with self._cond:
            headroom = self.memory_budget - self.admitted_bytes
        if headroom < self.memory_budget * self.PRESSURE_FRACTION:
            try:
                freed = self._pressure_cb(max(estimate, -headroom))
            except Exception:
                return
            if freed:
                obsreg.get_registry().inc("sched.pressureSpillBytes",
                                          freed)
                obsrec.record_event("sched.pressureSpill",
                                    freed_bytes=freed,
                                    headroom_bytes=headroom)

    def _release(self, req: AdmissionRequest) -> None:
        with self._cond:
            est = self._running.pop(req.query_id, None)
            if est is not None:
                self.admitted_bytes -= est
            self._publish_locked()
            self._cond.notify_all()
        if est is not None:
            obsrec.record_event("sched.released", query=req.query_id,
                                estimate_bytes=est)
