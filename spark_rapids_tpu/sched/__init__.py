"""Concurrent query scheduler: async submission, memory-aware
admission control, cooperative cancellation.

Layers (each its own module):

* :mod:`~spark_rapids_tpu.sched.cancel` — per-query
  :class:`CancelToken` + the thread-local checkpoint the exec hot
  paths poll.
* :mod:`~spark_rapids_tpu.sched.queue` — priority + FIFO wait queue.
* :mod:`~spark_rapids_tpu.sched.admission` — memory-budget admission
  (``sched.memoryBudget`` / ``sched.maxConcurrent``) with plan-shape
  estimate refinement, plus the re-entrant device :class:`TaskGate`
  behind ``mem/device.tpu_semaphore``.
* :mod:`~spark_rapids_tpu.sched.service` — per-session
  :class:`QueryService`: ``submit() -> QueryFuture``, deadlines,
  profile attachment; ``DataFrame.collect()`` == ``submit().result()``.
"""

from spark_rapids_tpu.sched.admission import (AdmissionController,  # noqa
                                              AdmissionRequest,
                                              EstimateBook,
                                              QueryRejectedError,
                                              TaskGate, plan_shape_key)
from spark_rapids_tpu.sched.cancel import (CancelToken,  # noqa
                                           QueryCancelledError,
                                           QueryTimeoutError)
from spark_rapids_tpu.sched.service import (QueryFuture,  # noqa
                                            QueryService, QueryState)
