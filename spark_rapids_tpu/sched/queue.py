"""Priority + FIFO-within-priority wait queue for query admission.

A small lazy-deletion binary heap: entries order by (-priority, seq) so
a higher ``priority`` value runs first and equal priorities keep strict
submit order (the seq is a process-wide monotonic counter).  Removal
(cancel / timeout while queued) marks the entry dead; dead heads pop
lazily on the next ``peek``.  The admission controller serves strictly
from the head — no smaller-query bypass — so a large query at the head
of its priority band cannot be starved by a stream of small ones
(head-of-line admission, the trade the reference's bare semaphore also
makes, just without the priority bands).
"""

from __future__ import annotations

import heapq
import itertools
import threading
from typing import Any, Optional

_seq = itertools.count()


class WaitEntry:
    """One queued admission request."""

    __slots__ = ("priority", "seq", "payload", "removed")

    def __init__(self, priority: int, payload: Any = None):
        self.priority = int(priority)
        self.seq = next(_seq)
        self.payload = payload
        self.removed = False

    def __lt__(self, other: "WaitEntry") -> bool:
        # heapq ordering: higher priority first, then FIFO
        if self.priority != other.priority:
            return self.priority > other.priority
        return self.seq < other.seq


class WaitQueue:
    """Thread-compatible (caller holds the admission lock) wait queue."""

    def __init__(self):
        self._heap: list = []

    def push(self, entry: WaitEntry) -> None:
        heapq.heappush(self._heap, entry)

    def peek(self) -> Optional[WaitEntry]:
        """The live head (dead entries pop lazily)."""
        while self._heap and self._heap[0].removed:
            heapq.heappop(self._heap)
        return self._heap[0] if self._heap else None

    def pop_head(self) -> Optional[WaitEntry]:
        head = self.peek()
        if head is not None:
            heapq.heappop(self._heap)
        return head

    def remove(self, entry: WaitEntry) -> None:
        """Lazy removal: O(1) now, reclaimed at the next peek."""
        entry.removed = True

    def __len__(self) -> int:
        return sum(1 for e in self._heap if not e.removed)

    def __bool__(self) -> bool:
        return self.peek() is not None
