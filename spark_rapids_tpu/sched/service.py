"""Per-session concurrent query execution service.

``QueryService.submit(plan)`` returns a :class:`QueryFuture`
immediately; the query runs on its own daemon thread once the
memory-aware :class:`~spark_rapids_tpu.sched.admission
.AdmissionController` admits it.  ``DataFrame.collect()`` is now
literally ``submit().result()`` and ``DataFrame.collect_async()``
exposes the future — the execution-service layer between the API and
the exec layer the ROADMAP's multi-tenant north star hangs off.

Lifecycle of one query::

    submit -> QUEUED --admission--> RUNNING --+--> SUCCESS (result+profile)
        |         |                           +--> FAILED  (exception)
        |         +--> TIMED_OUT / CANCELLED (unwound via CancelToken
        |              checkpoints: admission slot released, prefetcher
        +--> rejected  drained, shuffle fetches cancelled, spill-catalog
                       entries freed)

Deadlines: ``sched.defaultTimeoutMs`` (0 = none) or the per-submit
``timeout_ms`` arm a ``threading.Timer`` that fires the query's
CancelToken with ``timed_out=True`` — one mechanism covers both a
query stuck in the wait queue and one already running.

Nested execution: a collect issued from INSIDE a running query (a
listener, user code in a pandas UDF callback) executes inline under the
parent's admission slot and token — re-admitting it would deadlock a
``maxConcurrent=1`` engine on its own child.
"""

from __future__ import annotations

import enum
import threading
import time
from typing import Any, Dict, Optional

from collections import deque

from spark_rapids_tpu.obs import accounting as obsacct
from spark_rapids_tpu.obs import compile as obscompile
from spark_rapids_tpu.obs import recorder as obsrec
from spark_rapids_tpu.obs import registry as obsreg
from spark_rapids_tpu.sched import cancel as _cancel
from spark_rapids_tpu.sched.admission import (AdmissionController,
                                              AdmissionRequest,
                                              EstimateBook,
                                              plan_shape_key)


class QueryState(enum.Enum):
    QUEUED = "queued"
    RUNNING = "running"
    SUCCESS = "success"
    FAILED = "failed"
    CANCELLED = "cancelled"
    TIMED_OUT = "timed_out"


class QueryFuture:
    """Handle to one submitted query.

    ``result(timeout)`` blocks for completion and re-raises the query's
    own exception; a ``timeout`` elapsing raises the stdlib
    :class:`TimeoutError` WITHOUT cancelling the query (call
    ``cancel()`` for that).  ``profile`` carries the QueryProfile once
    the query completes (None while running or when
    ``obs.profile.enabled=false``)."""

    def __init__(self, query_id: int, token: _cancel.CancelToken):
        self.query_id = query_id
        self.token = token
        self._cond = threading.Condition()
        self._state = QueryState.QUEUED
        self._result = None
        self._error: Optional[BaseException] = None
        self.profile = None
        self.queue_wait_ns = 0
        # single-flight wiring (sched.dedup.*): followers carry the
        # leader's query id; both leader and followers hold their
        # _Flight so cancel() can route through promotion/detachment
        self.dedup_of: Optional[int] = None
        self._flight = None
        self._timer = None
        self._submitted_ns = time.monotonic_ns()

    # -- inspection ----------------------------------------------------------
    @property
    def state(self) -> QueryState:
        with self._cond:
            return self._state

    def done(self) -> bool:
        with self._cond:
            return self._state not in (QueryState.QUEUED,
                                       QueryState.RUNNING)

    def cancelled(self) -> bool:
        with self._cond:
            return self._state in (QueryState.CANCELLED,
                                   QueryState.TIMED_OUT)

    # -- control -------------------------------------------------------------
    def cancel(self, reason: str = "cancelled by user") -> bool:
        """Fire the query's CancelToken.  True when the query had not
        completed yet (cancellation will take effect at its next
        checkpoint); False when it already finished.

        Deduped queries route through the flight instead: cancelling a
        follower detaches it and leaves the flight running; cancelling
        a leader that has followers detaches the leader and promotes a
        follower (the execution itself is never killed while anyone
        still wants the result)."""
        if self.done():
            return False
        fl = self._flight
        if fl is not None:
            return fl.service._cancel_via_flight(self, reason)
        self.token.cancel(reason)
        return True

    def result(self, timeout: Optional[float] = None):
        with self._cond:
            if not self._cond.wait_for(self.done, timeout=timeout):
                raise TimeoutError(
                    f"query {self.query_id} still "
                    f"{self._state.value} after {timeout}s")
            if self._error is not None:
                raise self._error
            return self._result

    def exception(self, timeout: Optional[float] = None
                  ) -> Optional[BaseException]:
        with self._cond:
            if not self._cond.wait_for(self.done, timeout=timeout):
                raise TimeoutError(
                    f"query {self.query_id} still "
                    f"{self._state.value} after {timeout}s")
            return self._error

    # -- service side --------------------------------------------------------
    def _set_running(self) -> None:
        with self._cond:
            if self._state is QueryState.QUEUED:
                self._state = QueryState.RUNNING

    def _finish(self, state: QueryState, result=None,
                error: Optional[BaseException] = None,
                profile=None) -> None:
        with self._cond:
            if self._state not in (QueryState.QUEUED,
                                   QueryState.RUNNING):
                # first terminal state wins: a leader detached by
                # cancel() keeps CANCELLED even though its execution
                # thread later lands SUCCESS for the flight's followers
                return
            self._state = state
            self._result = result
            self._error = error
            if profile is not None:
                self.profile = profile
            self._cond.notify_all()


class _Flight:
    """One in-flight execution of a (digest, output-names) key: the
    leader future whose thread actually runs the plan, plus follower
    futures that resolve from the leader's execution outcome."""

    __slots__ = ("key", "leader", "exec_qid", "followers", "done",
                 "promoted_to", "service", "settled_state",
                 "settled_result", "settled_error", "chunk_feed",
                 "had_followers")

    def __init__(self, key, leader: QueryFuture, exec_qid: int,
                 service: "QueryService"):
        self.key = key
        self.leader = leader
        self.exec_qid = exec_qid
        self.followers: list = []
        self.done = False
        self.promoted_to: Optional[int] = None
        self.service = service
        self.settled_state: Optional[QueryState] = None
        self.settled_result = None
        self.settled_error: Optional[BaseException] = None
        # serving-tier chunk relay (serve/server.py _ChunkFeed): the
        # leader's streamer publishes encoded result chunks here so
        # follower streams send per-chunk in leader lockstep instead of
        # re-encoding after the whole flight settles.  had_followers
        # stays True once anyone joined — the leader only pays the
        # chunk-buffer memory when dedup actually occurred
        self.chunk_feed = None
        self.had_followers = False


class QueryService:
    """One per TpuSparkSession (see module docstring)."""

    def __init__(self, session):
        from spark_rapids_tpu import config as cfg
        self._session = session
        conf = session.conf
        budget = int(conf.get(cfg.SCHED_MEMORY_BUDGET))
        if budget <= 0:
            budget = self._derived_budget()
        self.memory_budget = budget
        self.max_concurrent = int(conf.get(cfg.SCHED_MAX_CONCURRENT))
        self.default_timeout_ms = int(
            conf.get(cfg.SCHED_DEFAULT_TIMEOUT_MS))
        self._default_estimate = int(
            conf.get(cfg.SCHED_QUERY_ESTIMATE_BYTES))
        from spark_rapids_tpu.mem import spill
        self.controller = AdmissionController(
            budget, self.max_concurrent,
            max_queued=int(conf.get(cfg.SCHED_MAX_QUEUED)),
            pressure_cb=spill.handle_memory_pressure)
        self.book = EstimateBook()
        self._tls = threading.local()
        # live query table (the /queries telemetry surface): every
        # submitted future while queued/running, plus a bounded
        # recently-completed window
        self._track_lock = threading.Lock()
        self._active: Dict[int, Dict[str, Any]] = {}
        self._recent: "deque" = deque(maxlen=64)
        # single-flight registry: (digest, output names) -> _Flight
        self.dedup_enabled = bool(conf.get(cfg.SCHED_DEDUP_ENABLED))
        self._flights: Dict[Any, _Flight] = {}
        self._flights_lock = threading.Lock()

    @staticmethod
    def _derived_budget() -> int:
        """Default budget: the device manager's HBM pool (XLA's
        bytes_limit x pool fraction; 8 GiB when the backend reports no
        limit — the CPU test platform)."""
        try:
            from spark_rapids_tpu.mem.device import TpuDeviceManager
            return int(TpuDeviceManager.get().hbm_budget)
        except Exception:
            return 8 << 30

    # -- estimates -----------------------------------------------------------
    def _estimate(self, plan, explicit: Optional[int]) -> int:
        """Working-set estimate in bytes: explicit per-submit override >
        refined observation for this plan shape > conservative
        derivation (batch size x concurrent scan/shuffle depth), all
        capped at the budget so a single query always remains
        admissible."""
        from spark_rapids_tpu import config as cfg
        if explicit is not None:
            return min(max(0, int(explicit)), self.memory_budget)
        if self._default_estimate > 0:
            # an operator-pinned fixed estimate beats refinement
            return min(self._default_estimate, self.memory_budget)
        refined = self.book.estimate(plan_shape_key(plan))
        if refined is not None:
            return min(refined, self.memory_budget)
        conf = self._session.conf
        depth = (int(conf.get(cfg.CONCURRENT_TPU_TASKS)) +
                 int(conf.get(cfg.SCAN_PREFETCH_DEPTH)))
        derived = int(conf.get(cfg.BATCH_SIZE_BYTES)) * max(1, depth)
        # join shapes hold a gathered build side (plus the skew/grace
        # planes' buffered buckets) on top of the streaming working set:
        # pad the unrefined derivation per join so first-run admission
        # doesn't overcommit — observed high-water refinement takes over
        # from the second run of the shape
        joins = self._count_joins(plan)
        if joins:
            derived *= 1 + min(joins, 3)
        return min(derived, self.memory_budget)

    @classmethod
    def _count_joins(cls, plan) -> int:
        n = 1 if type(plan).__name__ in ("Join", "AsOfJoin") else 0
        return n + sum(cls._count_joins(c)
                       for c in getattr(plan, "children", ()))

    def _observe(self, plan, hwm_bytes: int) -> None:
        self.book.record(plan_shape_key(plan), hwm_bytes)

    def has_live_queries(self) -> bool:
        """True while any query is queued or running — the signal the
        low-priority background services (sched/precompile replay, the
        serve incremental refresher) yield to."""
        with self._track_lock:
            return bool(self._active)

    # -- live query table (the /queries telemetry surface) -------------------
    def _track(self, fut: QueryFuture, req: AdmissionRequest,
               meta: Optional[Dict[str, Any]] = None) -> None:
        with self._track_lock:
            self._active[fut.query_id] = {
                "future": fut, "request": req, "meta": dict(meta or {}),
                "submitted_unix": time.time()}

    def _untrack(self, fut: QueryFuture) -> None:
        with self._track_lock:
            info = self._active.pop(fut.query_id, None)
            if info is not None:
                info["finished_unix"] = time.time()
                # freeze to the scalar row NOW: keeping the future
                # would pin its materialized result table (and
                # span-laden profile) in the recent window for up to
                # 64 queries after the caller dropped it
                self._recent.append(self._table_row(info))

    @staticmethod
    def _table_row(info: Dict[str, Any]) -> Dict[str, Any]:
        fut, req = info["future"], info["request"]
        meta = info.get("meta") or {}
        row = {
            "query_id": fut.query_id,
            "state": fut.state.value,
            "priority": req.priority,
            "estimate_bytes": req.estimate,
            "queue_wait_ms": round(req.queue_wait_ns / 1e6, 3),
            "submitted_unix": info["submitted_unix"],
            # serving attribution: which client session/address this
            # query belongs to (None for in-process submissions), and
            # the canonical plan digest (plan/digest.py)
            "session_id": meta.get("session_id"),
            "client_addr": meta.get("client_addr"),
            "plan_digest": meta.get("plan_digest"),
        }
        if meta.get("dedup_of") is not None:
            row["deduped"] = True
            row["leader_query_id"] = meta["dedup_of"]
        # compile attribution (obs/compile.py): null when zero, so
        # compile-bound outliers stand out in the table; the same
        # shared derivation feeds the slow-query JSONL
        row.update(obscompile.row_fields(fut.query_id))
        fin = info.get("finished_unix")
        if fin is not None:
            row["finished_unix"] = fin
            row["wall_s"] = round(fin - info["submitted_unix"], 4)
            err = fut._error
            if err is not None:
                row["error"] = f"{type(err).__name__}: {err}"
        return row

    def query_table(self) -> list:
        """Queued/running queries plus the recently-completed window,
        as JSON-friendly rows (state, priority, admitted estimate,
        queue wait) — the ``/queries`` endpoint payload.  Completed
        rows are pre-frozen scalar snapshots (see ``_untrack``)."""
        with self._track_lock:
            live = sorted(self._active.values(),
                          key=lambda i: i["future"].query_id)
            done = list(self._recent)
        return [self._table_row(i) for i in live] + done

    # -- submission ----------------------------------------------------------
    def submit(self, plan, priority: int = 0,
               timeout_ms: Optional[int] = None,
               estimate_bytes: Optional[int] = None,
               meta: Optional[Dict[str, Any]] = None) -> QueryFuture:
        """``meta`` carries serving attribution (``session_id``,
        ``client_addr`` — serve/server.py) into the live query table,
        the QueryProfile and the slow-query log; in-process submissions
        leave it None."""
        reg = obsreg.get_registry()
        qid = self._session._next_query_id()
        meta = dict(meta or {})
        if "plan_digest" not in meta:
            # the serving tier already digested the plan for its
            # result-cache key and passes it in meta — don't walk the
            # plan a second time on its behalf; one fingerprint walk
            # yields both the digest and the dedup admissibility
            from spark_rapids_tpu.plan.digest import plan_fingerprint
            try:
                fp = plan_fingerprint(plan)
                meta["plan_digest"] = fp.digest
                meta.setdefault("plan_cacheable", fp.cacheable)
            except Exception:
                meta["plan_digest"] = None
                meta["plan_cacheable"] = False
        digest = meta["plan_digest"]
        # compile observatory: bind qid -> digest so CompileEvents
        # fired on any thread carrying this query's token are stamped
        # with both (obs/compile.py; compiles inside a NESTED query
        # attribute to the parent, whose token those threads carry)
        obscompile.register_query(qid, digest)
        # resource ledger: bind qid -> tenant (session x template |
        # digest).  A coalesced batch execution registers with
        # hold=True so its bill stays un-folded until the batcher
        # settles it across the member tenants (obs/accounting.py).
        obsacct.register_query(
            qid, session_id=meta.get("session_id"),
            template=meta.get("statement_template"),
            plan_digest=digest,
            hold=bool(meta.get("batched_statements")))
        # nested collect inside a running query: execute inline under
        # the parent's slot/token (re-admission would self-deadlock)
        if getattr(self._tls, "in_query", False):
            tok = _cancel.current() or _cancel.CancelToken(qid)
            fut = QueryFuture(qid, tok)
            fut._set_running()
            # nested runs ride the live table too (zero-estimate: they
            # execute under the parent's admission slot)
            self._track(fut, AdmissionRequest(qid, 0, priority=priority,
                                              token=tok), meta)
            try:
                table, prof = self._session._execute_attributed(
                    plan, query_id=qid,
                    sched_extra=self._sched_extra_base(
                        meta, {"sched.nested": 1}),
                    plan_digest=digest)
            except BaseException as e:
                fut._finish(QueryState.FAILED, error=e,
                            profile=self._session.query_profile(qid))
                obscompile.finish_query(qid)
                obsacct.finish_query(qid)
                self._untrack(fut)
                raise
            fut._finish(QueryState.SUCCESS, result=table, profile=prof)
            obscompile.finish_query(qid)
            obsacct.finish_query(qid)
            self._untrack(fut)
            return fut
        reg.inc("sched.submitted")
        token = _cancel.CancelToken(qid)
        fut = QueryFuture(qid, token)
        ms = self.default_timeout_ms if timeout_ms is None \
            else int(timeout_ms)
        # single-flight: identical deterministic plans already in
        # flight are joined, not re-executed.  The key must include the
        # output names — the digest is alias-insensitive (two queries
        # differing only in output labels share kernels but not result
        # schemas), exactly the result cache's (digest, names) rule.
        if (self.dedup_enabled and digest is not None
                and meta.get("plan_cacheable")
                and not meta.pop("no_dedup", False)):
            key = self._flight_key(plan, digest)
            if key is not None:
                if self._join_or_lead(fut, key, priority, ms, meta):
                    return fut
                reg.inc("sched.dedup.flights")
        req = AdmissionRequest(
            qid, self._estimate(plan, estimate_bytes),
            priority=priority, token=token)
        self._track(fut, req, meta)
        obsrec.record_event("sched.submitted", query=qid,
                            priority=req.priority,
                            estimate_bytes=req.estimate)
        timer = None
        if ms and ms > 0:
            timer = threading.Timer(
                ms / 1e3, token.cancel,
                kwargs={"reason": f"deadline {ms}ms exceeded",
                        "timed_out": True})
            timer.daemon = True
            timer.start()
        t = threading.Thread(target=self._run,
                             args=(fut, plan, req, timer, meta),
                             name=f"sched-q{qid}", daemon=True)
        t.start()
        return fut

    @staticmethod
    def _flight_key(plan, digest: str):
        try:
            return (digest, tuple(plan.schema.names))
        except Exception:
            return None

    def _join_or_lead(self, fut: QueryFuture, key, priority: int,
                      ms: int, meta: Dict[str, Any]) -> bool:
        """Atomically join an existing live flight as a follower (True)
        or install ``fut`` as the leader of a new flight (False).
        Follower registration — tracking included — happens under the
        flights lock so a settling flight can never miss it."""
        with self._flights_lock:
            fl = self._flights.get(key)
            if (fl is None or fl.done
                    or fl.leader.token.is_cancelled):
                nfl = _Flight(key, fut, fut.query_id, self)
                fut._flight = nfl
                self._flights[key] = nfl
                return False
            fut.dedup_of = fl.exec_qid
            fut._flight = fl
            fmeta = dict(meta)
            fmeta["dedup_of"] = fl.exec_qid
            # zero-estimate: a follower consumes no admission budget
            self._track(fut, AdmissionRequest(fut.query_id, 0,
                                              priority=priority,
                                              token=fut.token), fmeta)
            fl.followers.append(fut)
            fl.had_followers = True
        obsreg.get_registry().inc("sched.dedup.hits")
        obsrec.record_event("sched.dedup.joined", query=fut.query_id,
                            leader=fut.dedup_of)
        if ms and ms > 0:
            fut._timer = threading.Timer(
                ms / 1e3, self._timeout_follower, args=(fut, ms))
            fut._timer.daemon = True
            fut._timer.start()
        return True

    def _timeout_follower(self, fut: QueryFuture, ms: int) -> None:
        fl = fut._flight
        with self._flights_lock:
            if fl.done or fut not in fl.followers:
                return
            fl.followers.remove(fut)
        obsreg.get_registry().inc("sched.timedOut")
        self._finish_follower(
            fut, QueryState.TIMED_OUT, None,
            _cancel.QueryTimeoutError(
                f"query {fut.query_id}: deadline {ms}ms exceeded "
                f"waiting on deduped flight {fl.exec_qid}"))

    def _cancel_via_flight(self, fut: QueryFuture, reason: str) -> bool:
        """Flight-aware cancel (see QueryFuture.cancel)."""
        fl = fut._flight
        reg = obsreg.get_registry()
        promoted = None
        with self._flights_lock:
            if fl.done:
                return False
            if fut.dedup_of is not None:
                # follower: detach; the flight keeps running
                if fut not in fl.followers:
                    return False
                fl.followers.remove(fut)
                mode = "follower"
            elif fl.followers:
                # leader with followers: detach the leader, promote the
                # first follower as the flight's nominal owner — the
                # execution itself continues untouched
                promoted = fl.followers[0]
                fl.promoted_to = promoted.query_id
                mode = "leader"
            else:
                mode = "kill"
        if mode == "kill":
            fut.token.cancel(reason)
            return True
        err = _cancel.QueryCancelledError(
            f"query {fut.query_id}: {reason}")
        if mode == "follower":
            reg.inc("sched.cancelled")
            self._finish_follower(fut, QueryState.CANCELLED, None, err)
            return True
        reg.inc("sched.cancelled")
        reg.inc("sched.dedup.promotions")
        obsrec.record_event("sched.dedup.promoted", query=fl.exec_qid,
                            cancelled_leader=fut.query_id,
                            promoted_follower=promoted.query_id)
        # the leader future detaches (first terminal state wins); its
        # _run thread later settles the flight with the execution's
        # real outcome for the followers
        fut._finish(QueryState.CANCELLED, error=err)
        return True

    def _finish_exec(self, fut: QueryFuture, state: QueryState,
                     result=None,
                     error: Optional[BaseException] = None,
                     profile=None) -> None:
        """Terminal finish on the execution (leader) path: resolve the
        leader future (unless it detached first) and fan the execution
        outcome to every follower of its flight."""
        fut._finish(state, result=result, error=error, profile=profile)
        fl = fut._flight
        if fl is None:
            return
        with self._flights_lock:
            fl.done = True
            fl.settled_state = state
            fl.settled_result = result
            fl.settled_error = error
            if self._flights.get(fl.key) is fl:
                del self._flights[fl.key]
            followers = list(fl.followers)
            fl.followers = []
        if followers:
            # fair-share the leader's bill across the joined tenants
            # BEFORE any record folds (the leader's own fold happens in
            # _run's finally, after this) — dedup must not hide a
            # tenant's true consumption
            obsacct.settle_flight(fut.query_id,
                                  [f.query_id for f in followers])
        for f in followers:
            self._finish_follower(f, state, result, error)

    def _finish_follower(self, fut: QueryFuture, state: QueryState,
                         result, error) -> None:
        if fut._timer is not None:
            fut._timer.cancel()
        prof = None
        try:
            prof = self._session._record_dedup_follower(
                fut.query_id, fut.dedup_of, state, error,
                self._meta_of(fut),
                max(0, time.monotonic_ns() - fut._submitted_ns),
                result)
        except Exception:
            prof = None
        fut._finish(state, result=result, error=error, profile=prof)
        obscompile.finish_query(fut.query_id)
        obsacct.finish_query(fut.query_id)
        self._untrack(fut)
        obsrec.record_event("sched.finished", query=fut.query_id,
                            state=fut.state.value)

    def _meta_of(self, fut: QueryFuture) -> Dict[str, Any]:
        with self._track_lock:
            info = self._active.get(fut.query_id)
            return dict(info.get("meta") or {}) if info else {}

    @staticmethod
    def _sched_extra_base(meta: Dict[str, Any],
                          extra: Optional[Dict[str, Any]] = None
                          ) -> Dict[str, Any]:
        out = dict(extra or {})
        if meta.get("session_id") is not None:
            out["sched.sessionId"] = meta["session_id"]
        if meta.get("client_addr") is not None:
            out["sched.clientAddr"] = meta["client_addr"]
        return out

    # -- the worker ----------------------------------------------------------
    def _run(self, fut: QueryFuture, plan, req: AdmissionRequest,
             timer, meta: Optional[Dict[str, Any]] = None) -> None:
        reg = obsreg.get_registry()
        meta = dict(meta or {})
        self._tls.in_query = True
        tracker = None
        try:
            try:
                slot = self.controller.acquire(req)
            except _cancel.QueryCancelledError as e:
                self._finish_exec(
                    fut, QueryState.TIMED_OUT
                    if isinstance(e, _cancel.QueryTimeoutError)
                    else QueryState.CANCELLED, error=e)
                return
            except BaseException as e:   # rejected / internal
                self._finish_exec(fut, QueryState.FAILED, error=e)
                from spark_rapids_tpu.sched.admission import \
                    QueryRejectedError
                if isinstance(e, QueryRejectedError):
                    # queue-full rejection happens BEFORE admission:
                    # without this hook the flight recorder and
                    # slow-query log never hear about the query at all
                    # — serving overload would be undiagnosable
                    self._session._record_rejection(fut.query_id, e,
                                                    req, meta)
                return
            fut.queue_wait_ns = req.queue_wait_ns
            # queue wait: global counter + tenant ledger (same n) +
            # SLO bucket observation — the saturation signals
            reg.inc("sched.queueWaitNs", req.queue_wait_ns)
            obsacct.charge_qid(fut.query_id, "sched.queueWaitNs",
                               req.queue_wait_ns)
            obsacct.observe_slo("slo.queueWaitMs",
                                req.queue_wait_ns / 1e6,
                                template=meta.get("statement_template"))
            fut._set_running()
            sched_extra = self._sched_extra_base(meta, {
                "sched.queueWaitNs": req.queue_wait_ns,
                "sched.estimateBytes": req.estimate,
                "sched.priority": req.priority,
            })
            try:
                from spark_rapids_tpu.mem import spill
                if spill.is_enabled():
                    tracker = spill.get_catalog().track_high_water()
                with slot, _cancel.install(fut.token):
                    table, prof = self._session._execute_attributed(
                        plan, query_id=fut.query_id,
                        sched_extra=sched_extra,
                        plan_digest=meta.get("plan_digest"))
            except _cancel.QueryCancelledError as e:
                timed = isinstance(e, _cancel.QueryTimeoutError) or \
                    fut.token.timed_out
                reg.inc("sched.timedOut" if timed else "sched.cancelled")
                self._finish_exec(
                    fut, QueryState.TIMED_OUT if timed
                    else QueryState.CANCELLED, error=e,
                    profile=self._session.query_profile(fut.query_id))
                return
            except BaseException as e:
                reg.inc("sched.failed")
                self._finish_exec(
                    fut, QueryState.FAILED, error=e,
                    profile=self._session.query_profile(fut.query_id))
                return
            reg.inc("sched.completed")
            if tracker is not None:
                hw = tracker.delta()
                self._observe(plan, hw)
                if hw:
                    # HBM residency bill: peak-growth bytes x query
                    # wall — the "who parked on the chip" metric
                    wall_s = max(0.0, (time.monotonic_ns()
                                       - fut._submitted_ns) / 1e9)
                    bs = float(hw) * wall_s
                    reg.inc("hbm.byteSeconds", bs)
                    obsacct.charge_qid(fut.query_id,
                                       "hbm.byteSeconds", bs)
            # corpus emission BEFORE the future resolves: a caller that
            # observes result() may immediately read the corpus file,
            # and this thread's finally block runs after the wake-up
            obscompile.finish_query(fut.query_id)
            self._finish_exec(fut, QueryState.SUCCESS, result=table,
                              profile=prof)
        finally:
            if tracker is not None:
                tracker.close()
            if timer is not None:
                timer.cancel()
            self._tls.in_query = False
            # backstop for the failure/cancel exits (idempotent: the
            # corpus dedups on digest), and attribution freeze BEFORE
            # the table row is frozen by _untrack (which reads the
            # per-query stats)
            obscompile.finish_query(fut.query_id)
            # in-process e2e latency (serve requests observe at the
            # serve layer with their own t0 — never both); then fold
            # the ledger bill, AFTER _finish_exec ran settle_flight
            if meta.get("session_id") is None:
                obsacct.observe_slo(
                    "slo.latencyMs",
                    max(0, time.monotonic_ns() - fut._submitted_ns)
                    / 1e6)
            obsacct.finish_query(fut.query_id)
            self._untrack(fut)
            obsrec.record_event("sched.finished", query=fut.query_id,
                                state=fut.state.value)
