"""Background AOT precompile service: replay the compile corpus off
the serving path.

The compile observatory (obs/compile.py) appends one JSONL record per
distinct plan digest to ``obs.compile.corpusPath``, and — with
``obs.compile.corpusReplay`` on — each program record carries a replay
payload: the pickled traceable, its jit kwargs, and the abstract
argument shapes (``jax.ShapeDtypeStruct`` leaves) of the exact program
the serving path compiled.  This service walks that corpus in a fresh
process and re-lowers + re-compiles every payload through jax's AOT
API:

  * programs already in the persistent XLA compilation cache RELOAD —
    the "warm compile" cost (613 s for the full TPC-DS-99 suite,
    PERF.md) is paid HERE, on a background thread, instead of on the
    first queries a restarted replica serves;
  * programs missing from the cache compile fresh and are WRITTEN, so
    a corpus alone can warm an empty cache for a brand-new replica.

Low-priority contract: between programs the service sleeps
``sched.precompile.idleWaitMs`` and, whenever the scheduler has live
(queued or running) queries, it pauses until the queue drains — replay
never competes with serving for the compile threads or the device.

What replay does NOT do: it does not touch the in-process kernel cache
(exec/kernel_cache) — the serving path still traces each kernel on
first use, but that trace's compile classifies ``persistent`` (a cache
read, milliseconds) instead of ``fresh`` (the CI corpus-replay gate
asserts exactly this on ``/compiles``).  Donating kernels are absent
from the corpus by design: they are barred from the persistent cache
(jax 0.4.37 reload mis-applies donation aliasing — see
exec/kernel_cache._no_persistent_cache) and pay one fresh compile per
process instead.

Registry counters: ``sched.precompile.plans`` / ``.programs`` /
``.warmed`` / ``.skipped`` (no payload) / ``.failed`` / ``.dedup``.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Dict, List, Optional

from spark_rapids_tpu.obs import recorder as obsrec
from spark_rapids_tpu.obs import registry as obsreg


class PrecompileService:
    """Replays a precompile corpus JSONL (see module docstring).

    ``start()`` launches the replay on a daemon thread (the session
    init path); ``replay()`` runs it synchronously (tests, CI gates);
    ``wait(timeout)`` blocks until the background replay finishes."""

    def __init__(self, session, corpus_path: str,
                 idle_wait_ms: int = 25):
        self._session = session
        self.corpus_path = str(corpus_path or "")
        self.idle_wait_s = max(0, int(idle_wait_ms)) / 1e3
        self._done = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._stop = False
        self._stats = {"plans": 0, "programs": 0, "warmed": 0,
                       "skipped": 0, "failed": 0, "dedup": 0,
                       "wall_s": 0.0}
        self._lock = threading.Lock()

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._run, name="sched-precompile", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop = True

    def wait(self, timeout: Optional[float] = None) -> bool:
        """Block until the background replay finishes (True) or the
        timeout elapses (False).  Synchronous ``replay()`` callers
        don't need this."""
        return self._done.wait(timeout)

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return dict(self._stats)

    # -- replay -------------------------------------------------------------
    def _run(self) -> None:
        try:
            self.replay()
        finally:
            self._done.set()

    def _busy(self) -> bool:
        """Live (queued or running) queries in this session's
        scheduler — the signal replay yields to."""
        try:
            return self._session._query_service.has_live_queries()
        except Exception:
            return False

    def _yield_to_serving(self) -> None:
        while not self._stop and self._busy():
            time.sleep(self.idle_wait_s or 0.005)

    def replay(self) -> Dict[str, Any]:
        """Walk the corpus once, lower+compile every replayable
        program (deduplicated on (key, signature) across records).
        Returns the stats dict; never raises on per-program failures
        (counted as ``failed``)."""
        import jax

        from spark_rapids_tpu.exec import kernel_cache as kc
        t0 = time.perf_counter()
        reg = obsreg.get_registry()
        seen = set()
        records = []
        # a DIRECTORY corpus replays every *.jsonl inside it — the
        # fleet warm-join shape, where each replica appends its own
        # corpus file under the shared store's corpus/ dir and the
        # (key, signature) dedup below collapses the overlap
        paths: List[str] = []
        if os.path.isdir(self.corpus_path):
            try:
                paths = sorted(
                    os.path.join(self.corpus_path, n)
                    for n in os.listdir(self.corpus_path)
                    if n.endswith(".jsonl"))
            except OSError:
                paths = []
        elif self.corpus_path:
            paths = [self.corpus_path]
        for path in paths:
            try:
                with open(path) as f:
                    for line in f:
                        line = line.strip()
                        if not line:
                            continue
                        try:
                            records.append(json.loads(line))
                        except Exception:
                            continue      # torn tail line: skip
            except OSError:
                continue
        obsrec.record_event("precompile.start",
                            corpus=self.corpus_path,
                            plans=len(records))
        for rec in records:
            if self._stop:
                break
            with self._lock:
                self._stats["plans"] += 1
            reg.inc("sched.precompile.plans")
            for prog in rec.get("programs") or []:
                if self._stop:
                    break
                dedup = (prog.get("key"), prog.get("signature"))
                if dedup in seen:
                    with self._lock:
                        self._stats["dedup"] += 1
                    reg.inc("sched.precompile.dedup")
                    continue
                seen.add(dedup)
                with self._lock:
                    self._stats["programs"] += 1
                reg.inc("sched.precompile.programs")
                payload = prog.get("replay")
                if not payload:
                    with self._lock:
                        self._stats["skipped"] += 1
                    reg.inc("sched.precompile.skipped")
                    continue
                self._yield_to_serving()
                try:
                    spec = kc.load_replay_payload(payload)
                    jitted = jax.jit(spec["fn"], **(spec["jit"] or {}))
                    jitted.lower(*spec["args"],
                                 **(spec["kwargs"] or {})).compile()
                    with self._lock:
                        self._stats["warmed"] += 1
                    reg.inc("sched.precompile.warmed")
                except Exception:
                    with self._lock:
                        self._stats["failed"] += 1
                    reg.inc("sched.precompile.failed")
                if self.idle_wait_s:
                    time.sleep(self.idle_wait_s)
        with self._lock:
            self._stats["wall_s"] = round(time.perf_counter() - t0, 3)
            stats = dict(self._stats)
        obsrec.record_event("precompile.done", **stats)
        return stats
