"""Typed, self-documenting configuration registry.

TPU-native analog of the reference's ``RapidsConf`` system
(reference: sql-plugin/.../RapidsConf.scala:269-281 — ``ConfEntry`` registry with
typed builders, defaults, and doc generation via ``RapidsConf.main`` emitting
docs/configs.md).

Keys live under ``spark.rapids.tpu.*``.  Per-operator enable keys are derived
automatically from exec/expression class names (reference:
GpuOverrides.scala:131-139) — see :mod:`spark_rapids_tpu.plan.overrides`.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

_REGISTRY: Dict[str, "ConfEntry"] = {}
_REGISTRY_LOCK = threading.Lock()


@dataclass(frozen=True)
class ConfEntry:
    """One typed configuration key with default + documentation.

    Mirrors reference ``ConfEntry``/``ConfBuilder`` (RapidsConf.scala:180-281).
    """

    key: str
    default: Any
    doc: str
    value_type: type
    internal: bool = False
    # converter applied to raw (string or typed) values at lookup time
    converter: Optional[Callable[[Any], Any]] = None

    def get(self, conf: "RapidsTpuConf") -> Any:
        raw = conf._settings.get(self.key, self.default)
        if raw is None:
            return None
        if self.converter is not None:
            return self.converter(raw)
        if self.value_type is bool and isinstance(raw, str):
            return raw.strip().lower() in ("true", "1", "yes")
        if self.value_type in (int, float) and isinstance(raw, str):
            return self.value_type(raw)
        return raw


def _register(entry: ConfEntry) -> ConfEntry:
    with _REGISTRY_LOCK:
        if entry.key in _REGISTRY:
            raise ValueError(f"duplicate conf key {entry.key}")
        _REGISTRY[entry.key] = entry
    return entry


def conf(key: str, default: Any, doc: str, value_type: type = str,
         internal: bool = False,
         converter: Optional[Callable[[Any], Any]] = None) -> ConfEntry:
    return _register(ConfEntry(key=key, default=default, doc=doc,
                               value_type=value_type, internal=internal,
                               converter=converter))


# ---------------------------------------------------------------------------
# Core keys (subset mirrors reference RapidsConf.scala; grows with features)
# ---------------------------------------------------------------------------

SQL_ENABLED = conf(
    "spark.rapids.tpu.sql.enabled", True,
    "Enable or disable TPU acceleration of SQL operators entirely.", bool)

EXPLAIN = conf(
    "spark.rapids.tpu.sql.explain", "NONE",
    "Explain why parts of a query were or were not placed on the TPU: "
    "NONE, NOT_ON_TPU, ALL. (reference: RapidsConf.scala:747, "
    "GpuOverrides.scala:2054-2060)")

INCOMPATIBLE_OPS = conf(
    "spark.rapids.tpu.sql.incompatibleOps.enabled", False,
    "Enable operators that produce results that differ from Spark in corner "
    "cases (e.g. float aggregation ordering). (reference: RapidsConf.scala:424)",
    bool)

HAS_NANS = conf(
    "spark.rapids.tpu.sql.hasNans", True,
    "Assume floating point data may contain NaNs; disables some ops unless "
    "false. (reference: RapidsConf.scala:431)", bool)

VARIABLE_FLOAT_AGG = conf(
    "spark.rapids.tpu.sql.variableFloatAgg.enabled", False,
    "Allow float/double aggregations whose result may vary run-to-run due to "
    "reduction ordering. (reference: RapidsConf.scala:437)", bool)

IMPROVED_FLOAT_OPS = conf(
    "spark.rapids.tpu.sql.improvedFloatOps.enabled", False,
    "Enable float ops that are more accurate than Spark's but differ bit-wise.",
    bool)

BATCH_SIZE_BYTES = conf(
    "spark.rapids.tpu.sql.batchSizeBytes", 2 << 30,
    "Target size in bytes for coalesced columnar batches handed to one XLA "
    "program invocation. (reference: RapidsConf.scala:364)", int)

BATCH_SIZE_ROWS = conf(
    "spark.rapids.tpu.sql.batchSizeRows", 1 << 21,
    "Soft cap on rows per coalesced batch.", int)

MIN_BUCKET_ROWS = conf(
    "spark.rapids.tpu.sql.shape.minBucketRows", 16,
    "Smallest padded row-capacity bucket. Batches are padded up to "
    "power-of-two buckets so XLA recompiles are bounded (TPU static-shape "
    "requirement; no reference analog — cudf tolerates dynamic shapes).", int)

CONCURRENT_TPU_TASKS = conf(
    "spark.rapids.tpu.sql.concurrentTpuTasks", 2,
    "Number of tasks that may hold the TPU semaphore concurrently. "
    "(reference: GpuSemaphore.scala:101, RapidsConf.scala)", int)

TEST_ENABLED = conf(
    "spark.rapids.tpu.sql.test.enabled", False,
    "Test mode: assert that every supported operator actually ran on the TPU. "
    "(reference: RapidsConf.scala:607-621, assertIsOnTheGpu)", bool)

TEST_ALLOWED_NON_TPU = conf(
    "spark.rapids.tpu.sql.test.allowedNonTpu", "",
    "Comma-separated exec/expr class names allowed to stay on CPU in test "
    "mode.")

CAST_STRING_TO_FLOAT = conf(
    "spark.rapids.tpu.sql.castStringToFloat.enabled", False,
    "Enable string-to-float casts on TPU. The device parse "
    "(mantissa x 10^exp in float64) can differ from strtod in the last "
    "ulp for full-precision decimal strings (reference flags GPU "
    "castStringToFloat incompatible for the same reason).", bool)

CAST_FLOAT_TO_STRING = conf(
    "spark.rapids.tpu.sql.castFloatToString.enabled", True,
    "Enable float-to-string casts on TPU. The device Ryu kernel "
    "(expr/ryu.py) produces the engine's exact shortest-round-trip "
    "repr formatting, bit-identical to the CPU path; disable only to "
    "force the CPU fallback (reference gates GPU castFloatToString "
    "behind the same kind of flag because Java formatting differs).",
    bool)

ALLOW_INCOMPAT_UTC_ONLY = conf(
    "spark.rapids.tpu.sql.castStringToTimestamp.enabled", False,
    "Enable string-to-timestamp casts (UTC only).", bool)

MAX_READER_BATCH_SIZE_ROWS = conf(
    "spark.rapids.tpu.sql.reader.batchSizeRows", 1 << 21,
    "Max rows a file reader emits per batch. (reference: RapidsConf.scala:378)",
    int)

MAX_READER_BATCH_SIZE_BYTES = conf(
    "spark.rapids.tpu.sql.reader.batchSizeBytes", 2 << 30,
    "Max bytes a file reader emits per batch.", int)

PARQUET_DEVICE_DECODE = conf(
    "spark.rapids.tpu.sql.format.parquet.deviceDecode.enabled", True,
    "Decode parquet pages in HBM (RLE/dictionary/def-level expansion on "
    "device; reference: GpuParquetScan.scala:1022 Table.readParquet).",
    bool)

CSV_DEVICE_DECODE = conf(
    "spark.rapids.tpu.sql.format.csv.deviceDecode.enabled", True,
    "Decode CSV files in HBM: one byte-tensor kernel scans delimiters "
    "and parses fields per file (reference: GpuBatchScanExec.scala:465 "
    "Table.readCSV). Quoted/ragged/exotic files fall back to the host "
    "Arrow reader.", bool)

PARQUET_DEVICE_ENCODE = conf(
    "spark.rapids.tpu.sql.format.parquet.deviceEncode.enabled", True,
    "Encode parquet writes from device batches: per-column null "
    "compaction on device, one packed download, host page/footer "
    "assembly (reference: GpuParquetFileFormat.scala:281 "
    "Table.writeParquetChunked). Unsupported types or partitioned "
    "writes fall back to the host Arrow writer.", bool)

ORC_DEVICE_ENCODE = conf(
    "spark.rapids.tpu.sql.format.orc.deviceEncode.enabled", True,
    "Encode ORC writes from device batches: per-column null compaction "
    "on device, one packed download, host RLEv1/protobuf stripe "
    "assembly (reference: GpuOrcFileFormat.scala:103 "
    "Table.writeORCChunked). Unsupported types or partitioned writes "
    "fall back to the host Arrow writer.", bool)

CACHE_DEVICE_ENCODE = conf(
    "spark.rapids.tpu.sql.cache.deviceEncode.enabled", True,
    "Compress df.cache() batches to parquet blobs with the DEVICE "
    "encoder instead of host Arrow (reference: "
    "ParquetCachedBatchSerializer.scala:333 "
    "compressColumnarBatchWithParquet encodes cached batches on GPU).",
    bool)

PARQUET_FUSED_DECODE = conf(
    "spark.rapids.tpu.sql.format.parquet.fusedDecode.enabled", True,
    "Decode ALL columns of ALL coalesced row groups in one XLA program "
    "(the multi-file coalescing reader; reference: "
    "GpuParquetScan.scala:489 MultiFileParquetPartitionReader packs "
    "many files into one Table.readParquet call). Falls back to "
    "per-column decode per row group when off or when "
    "input_file_name() is used.", bool)

SCAN_METADATA_CACHE_ENABLED = conf(
    "spark.rapids.tpu.sql.scan.metadataCache.enabled", True,
    "Cache scan host-prep artifacts (parsed parquet footers, Thrift "
    "page descriptors, RLE run tables) process-wide, keyed on (path, "
    "mtime, size, column, options) so repeat scans of unchanged files "
    "skip the page-header walks entirely (the footer-cache analog of "
    "the reference's multi-file reader; host-side sibling of the "
    "compiled-kernel cache).", bool)

SCAN_METADATA_CACHE_MAX_BYTES = conf(
    "spark.rapids.tpu.sql.scan.metadataCache.maxBytes", 256 << 20,
    "Byte budget for the scan metadata/plan cache; least-recently-used "
    "files evict (whole-file granularity) when cached run tables and "
    "packed page buffers exceed it.", int)

SCAN_HOST_PREP_THREADS = conf(
    "spark.rapids.tpu.sql.scan.hostPrep.threads", 4,
    "Thread-pool size for parallel scan host prep: page-header and RLE "
    "run-boundary walks across (column, row-group) pairs run "
    "concurrently instead of sequentially (page reads and codec "
    "decompression release the GIL). 1 disables the pool.", int)

SCAN_PREFETCH_DEPTH = conf(
    "spark.rapids.tpu.sql.scan.prefetch.depth", 2,
    "Bounded look-ahead for the fused parquet scan: up to this many "
    "batches' host prep + packed-page upload run ahead of the "
    "dispatch-only device decode of the current batch (prep of batch "
    "k+1 overlaps decode of batch k; no device->host read happens "
    "before the terminal barrier). 0 disables pipelining.", int)

SCAN_SHARED_ENABLED = conf(
    "spark.rapids.tpu.sql.scan.shared.enabled", True,
    "Multicast decoded scan batches across concurrent queries: when "
    "two plans decode the same (file, row-group, column-set, stamp) "
    "key at the same time, one decodes and every subscriber receives "
    "the decoded batch (refcounted retention window; eviction is "
    "always correctness-safe — a miss just re-decodes). Off reverts "
    "to per-query decoding.", bool)

SCAN_SHARED_WINDOW_BYTES = conf(
    "spark.rapids.tpu.sql.scan.shared.windowBytes", 64 << 20,
    "Byte budget for the shared-scan multicast retention window "
    "(decoded batches kept briefly so a slightly-behind subscriber "
    "still shares the decode). LRU eviction; the window also registers "
    "as a pressure spiller so HBM pressure drops retained batches "
    "first.", int)

ORC_DEVICE_DECODE = conf(
    "spark.rapids.tpu.sql.format.orc.deviceDecode.enabled", True,
    "Decode ORC stripes on the TPU: CPU parses stripe footers and RLEv2 "
    "run boundaries, device kernels expand runs/PRESENT streams and "
    "gather string dictionaries in HBM. Columns with unsupported "
    "encodings fall back to host Arrow decode individually. (reference: "
    "GpuOrcScan.scala:206 device decode via libcudf)", bool)

PARQUET_READER_TYPE = conf(
    "spark.rapids.tpu.sql.format.parquet.reader.type", "AUTO",
    "Parquet reader strategy: AUTO, PERFILE, COALESCING, MULTITHREADED. "
    "(reference: RapidsConf.scala:513)")

PARQUET_MULTITHREAD_READ_NUM_THREADS = conf(
    "spark.rapids.tpu.sql.format.parquet.multiThreadedRead.numThreads", 20,
    "Thread pool size for the MULTITHREADED cloud reader. "
    "(reference: RapidsConf.scala:540)", int)

CLOUD_SCHEMES = conf(
    "spark.rapids.tpu.cloudSchemes", "gs,s3,s3a,s3n,wasbs,abfs",
    "URI schemes treated as high-latency cloud stores (selects the "
    "MULTITHREADED reader under AUTO).")

MEM_POOL_FRACTION = conf(
    "spark.rapids.tpu.memory.pool.fraction", 0.9,
    "Fraction of free HBM the arena manages for columnar batches. "
    "(reference: GpuDeviceManager.scala:196-262 RMM pool init)", float)

MEM_DEVICE_LIMIT = conf(
    "spark.rapids.tpu.memory.device.batchStorageSize", 4 << 30,
    "Bytes of HBM budget for registered spillable batches; exceeding it "
    "triggers synchronous device->host spill (RMM pool + event-handler "
    "analog).", int)

MEM_SPILL_ENABLED = conf(
    "spark.rapids.tpu.memory.spill.enabled", True,
    "Enable device->host->disk spill of registered batches under memory "
    "pressure. (reference: RapidsBufferCatalog.scala:128-142)", bool)

MEM_HOST_SPILL_LIMIT = conf(
    "spark.rapids.tpu.memory.host.spillStorageSize", 8 << 30,
    "Bytes of host memory used to cache spilled device batches before "
    "falling through to disk.", int)

MEM_SPILL_DIR = conf(
    "spark.rapids.tpu.memory.spill.dir", "",
    "Directory for the disk spill tier (defaults to a temp dir).")

SHUFFLE_TRANSPORT = conf(
    "spark.rapids.tpu.shuffle.transport", "local",
    "Shuffle transport implementation: 'local' (in-process Arrow IPC store, "
    "the default-path analog), 'device' (HBM-resident slices, one process), "
    "'manager' (accelerated TpuShuffleManager: device-resident catalog + "
    "tag-matched client/server transport), 'ici' (device-resident "
    "all_to_all over a jax Mesh; reference: shuffle-plugin UCX "
    "transport), or 'ici_ring' (like 'ici' but broadcast builds "
    "replicate via collective_permute ring hops — the point-to-point "
    "plane; reference: tag-matched per-peer pulls, "
    "UCXConnection.scala:385), or 'process' (map stages execute in "
    "spawned executor OS processes that serve their catalogs over the "
    "TCP transport; the cross-process executor-fleet data plane, "
    "RapidsShuffleInternalManager.scala:90-186).")

COLUMN_PRUNING = conf(
    "spark.rapids.tpu.sql.columnPruning.enabled", True,
    "Prune unreferenced columns out of file and in-memory scans before "
    "physical planning (Catalyst ColumnPruning analog; on TPU this "
    "skips whole device parquet column-chunk decodes and HBM uploads).",
    bool)

SHUFFLE_PROCESS_EXECUTORS = conf(
    "spark.rapids.tpu.shuffle.transport.processExecutors", 2,
    "Number of executor processes the 'process' shuffle transport "
    "spawns (the executor fleet the RapidsShuffleManager spans).", int)

SHUFFLE_PROCESS_NESTED_TRANSPORT = conf(
    "spark.rapids.tpu.shuffle.transport.processNestedTransport", "local",
    "Data plane for exchanges NESTED inside a shipped map stage when "
    "shuffle.transport=process: 'local' (in-process store) or 'ici' / "
    "'ici_ring' (each executor runs the nested exchange as collectives "
    "over its own device mesh — the DCN-over-ICI composition: "
    "intra-slice collectives per executor, TCP between executors).")

SHUFFLE_FETCH_MAX_RETRIES = conf(
    "spark.rapids.tpu.shuffle.fetch.maxRetries", 3,
    "Max per-peer fetch retries in the shuffle iterator before the "
    "failure escalates (to the CPU fallback when enabled, else to a "
    "fetch-failed exception that re-runs the map stage). 0 disables "
    "retries: any transport fault fails the fetch immediately with the "
    "typed shuffle exceptions.", int)

SHUFFLE_FETCH_RETRY_BACKOFF_MS = conf(
    "spark.rapids.tpu.shuffle.fetch.retryBackoffMs", 50,
    "Base backoff between shuffle fetch retries; doubles per attempt "
    "with deterministic jitter (exponential backoff).", int)

SHUFFLE_CONNECT_TIMEOUT_MS = conf(
    "spark.rapids.tpu.shuffle.connectTimeoutMs", 5000,
    "TCP shuffle transport connect timeout per attempt. A failed "
    "connect is redialed once with backoff within a fetch attempt; the "
    "overall retry budget is governed by fetch.maxRetries at the fetch "
    "layer.", int)

SHUFFLE_READ_TIMEOUT_MS = conf(
    "spark.rapids.tpu.shuffle.readTimeoutMs", 10000,
    "TCP shuffle transport read-watchdog window: a connection with "
    "in-flight requests or posted receives that stays silent for two "
    "consecutive windows fails them all (surfacing as a retryable "
    "fetch failure); the double window guarantees an operation posted "
    "mid-window a full window of budget. 0 disables.", int)

SHUFFLE_CPU_FALLBACK = conf(
    "spark.rapids.tpu.shuffle.fetch.cpuFallbackEnabled", True,
    "After shuffle fetch retries and map-stage re-runs are exhausted, "
    "re-read the affected partitions through the CPU shuffle block "
    "store (recomputing the map side in-process) instead of failing "
    "the query — the fall-back-to-Spark-shuffle contract.", bool)

SHUFFLE_FAULT_PLAN = conf(
    "spark.rapids.tpu.shuffle.test.faultPlan", "",
    "Deterministic fault-injection plan for chaos testing, e.g. "
    "'seed=7;tcp.server.data:drop@2;procpool.map_stage:kill@1:i0'. "
    "See spark_rapids_tpu/shuffle/faults.py for the grammar and the "
    "named injection points. Empty disables injection.")

PYWORKER_HANDSHAKE_TIMEOUT_MS = conf(
    "spark.rapids.tpu.python.worker.handshakeTimeoutMs", 20000,
    "How long to wait for a spawned python worker to connect back and "
    "authenticate before the spawn fails with PythonWorkerError.", int)

PYWORKER_CLOSE_TIMEOUT_MS = conf(
    "spark.rapids.tpu.python.worker.closeTimeoutMs", 5000,
    "How long to wait for a python worker to exit cleanly on close "
    "before it is hard-killed.", int)

PYWORKER_MAX_RESPAWNS = conf(
    "spark.rapids.tpu.python.worker.maxRespawns", 1,
    "How many times a python-worker batch is transparently replayed on "
    "a fresh worker after the worker process crashes mid-batch. 0 "
    "disables replay (a crash surfaces as PythonWorkerError).", int)

SHUFFLE_COMPRESSION_CODEC = conf(
    "spark.rapids.tpu.shuffle.compression.codec", "none",
    "Codec for shuffle data: none, lz4, zstd, zlib (zlib compresses "
    "the wire leg only — Arrow IPC has no zlib buffer compression, so "
    "block stores hold those blocks uncompressed). "
    "Applies to serialized shuffle partitions (pyarrow IPC buffer "
    "compression in the block stores) AND, on the TCP/DCN process "
    "transport, to the per-frame DATA wire leg — the driver's clients "
    "negotiate the codec in their HELLO handshake and executor servers "
    "wrap every DATA payload back to them (flag + uncompressed-size + "
    "body; incompressible or empty frames ride uncompressed inside the "
    "wrapper). See docs/shuffle_wire_format.md. (reference: "
    "TableCompressionCodec.scala:41)")

SHUFFLE_PIPELINE_DEPTH = conf(
    "spark.rapids.tpu.shuffle.pipeline.depth", 2,
    "Bounded look-ahead of the pipelined process-transport exchange: "
    "up to this many reduce partitions are fetched + decoded + "
    "uploaded ahead of the consumer (the ScanPrefetcher shape), with "
    "per-map completion notifications letting reducers fetch a map "
    "task's output the moment that map id finishes instead of "
    "barriering on the whole map stage. Prepared partitions register "
    "with the spill catalog at shuffle-input priority, so memory "
    "pressure spills them to host/disk instead of stalling admission. "
    "0 disables the pipeline (the sequential map->fetch->decode "
    "exchange, bit-identical results — the CI parity gate diffs the "
    "two).", int)

SHUFFLE_PIPELINE_TIMEOUT_MS = conf(
    "spark.rapids.tpu.shuffle.pipeline.timeoutMs", 120000,
    "No-progress bound on the pipelined exchange's wait for the next "
    "map-task completion: if no new map id lands within this window "
    "the read escalates through the standard recovery ladder "
    "(map-stage re-run of dead executors, then the CPU fallback when "
    "enabled). Raise for map stages whose single tasks legitimately "
    "run longer, or set 0 to wait indefinitely (the sequential "
    "barrier's semantics: a dead executor still surfaces promptly "
    "through its submit thread; only a wedged-but-alive one blocks, "
    "exactly as it blocks the depth=0 pipe read).", int)

AUTO_BROADCAST_THRESHOLD = conf(
    "spark.rapids.tpu.sql.autoBroadcastJoinThreshold", 10 << 20,
    "Max estimated byte size of a join side to broadcast it "
    "(spark.sql.autoBroadcastJoinThreshold analog; -1 disables).", int)

CACHE_COMPRESSION = conf(
    "spark.rapids.tpu.sql.cache.compression", "snappy",
    "Parquet compression codec for df.cache() blobs "
    "(ParquetCachedBatchSerializer analog; none|snappy|zstd|gzip|lz4).")

CACHE_DEVICE_DECODE = conf(
    "spark.rapids.tpu.sql.cache.deviceDecode.enabled", True,
    "Decode cached parquet blobs on device (HBM RLE/dictionary "
    "expansion), falling back per column like file scans.", bool)

ADAPTIVE_ENABLED = conf(
    "spark.rapids.tpu.sql.adaptive.enabled", True,
    "Adaptive shuffle reads: after an exchange materializes, coalesce "
    "undersized reduce partitions and split skewed ones using the "
    "measured per-partition sizes (AQE CustomShuffleReaderExec analog; "
    "reference: GpuCustomShuffleReaderExec.scala:38).", bool)

ADAPTIVE_ADVISORY_PARTITION_SIZE = conf(
    "spark.rapids.tpu.sql.adaptive.advisoryPartitionSizeInBytes",
    64 << 20,
    "Target output partition size for adaptive coalescing and skew "
    "splitting.", int)

ADAPTIVE_MIN_PARTITION_NUM = conf(
    "spark.rapids.tpu.sql.adaptive.coalescePartitions.minPartitionNum", 1,
    "Lower bound on the post-coalesce partition count.", int)

ADAPTIVE_SKEW_FACTOR = conf(
    "spark.rapids.tpu.sql.adaptive.skewJoin.skewedPartitionFactor", 5,
    "A partition is skewed if its bytes exceed this multiple of the "
    "median partition size (and the absolute threshold).", int)

ADAPTIVE_SKEW_THRESHOLD = conf(
    "spark.rapids.tpu.sql.adaptive.skewJoin."
    "skewedPartitionThresholdInBytes", 256 << 20,
    "Absolute minimum bytes for a partition to be considered skewed.",
    int)

SHUFFLE_PARTITIONS = conf(
    "spark.rapids.tpu.sql.shuffle.partitions", 8,
    "Default number of shuffle partitions (spark.sql.shuffle.partitions "
    "analog).", int)

JOIN_OOCORE_ENABLED = conf(
    "spark.rapids.tpu.sql.join.oocore.enabled", True,
    "Out-of-core grace hash join (exec/join_partition.py): when a "
    "join's per-partition build side exceeds join.buildSideBudgetBytes "
    "it is hash-partitioned (a different murmur seed per recursion "
    "level, decorrelated from the exchange's bucketing) into 2^k grace "
    "partitions together with its probe side; build partitions spill "
    "through the device->host->disk tiers and each grace partition is "
    "re-streamed and joined alone, recursing on a still-oversized "
    "partition. Under-budget joins take the unpartitioned path "
    "byte-for-byte; off reverts entirely (the one-knob revert).", bool)

JOIN_BUILD_BUDGET = conf(
    "spark.rapids.tpu.sql.join.buildSideBudgetBytes", 0,
    "Per-partition build-side byte budget that activates the "
    "out-of-core grace join. 0 (default) derives it from the admission "
    "machinery: the scheduler memory budget (sched.memoryBudget or its "
    "HBM-pool derivation) divided by sched.maxConcurrent — one "
    "admitted query's fair share. -1 disables the budget check "
    "entirely (build sides gather unconditionally, today's behavior).",
    int)

JOIN_OOCORE_PARTITIONS_LOG2 = conf(
    "spark.rapids.tpu.sql.join.oocore.partitionsLog2", 0,
    "Explicit grace fan-out exponent: partition both sides into 2^k "
    "pieces when the build side exceeds the budget. 0 (default) picks "
    "the smallest k whose expected per-partition build size fits the "
    "budget, capped at 5 (32-way).", int)

JOIN_OOCORE_MAX_RECURSION = conf(
    "spark.rapids.tpu.sql.join.oocore.maxRecursion", 3,
    "Recursion-depth bound for grace partitions that stay over budget "
    "after a split (duplicate-heavy keys). At the bound — or as soon "
    "as a level fails to shrink the partition (a single hot key cannot "
    "hash-split) — the join falls back to streaming the probe side in "
    "chunks against the oversized build partition, which is always "
    "correct and always terminates.", int)

JOIN_SKEW_ENABLED = conf(
    "spark.rapids.tpu.sql.join.skew.enabled", False,
    "Runtime hot-bucket splitting at the shuffle boundary: the "
    "map-output tracker aggregates per-(map, reduce-bucket) sizes as "
    "map tasks complete; a probe-side bucket projected over "
    "join.skew.bucketFactor x the median splits into sub-readers over "
    "disjoint map-output ranges BEFORE the reduce fetch, each joined "
    "against a replica (or broadcast, when small) of the matching "
    "build bucket — one hot key no longer serializes the reduce stage "
    "on a single reducer. Takes over the skew half of the adaptive "
    "reader for eligible joins; off (default) keeps today's plan "
    "shape exactly.", bool)

JOIN_SKEW_FACTOR = conf(
    "spark.rapids.tpu.sql.join.skew.bucketFactor", 4.0,
    "A reduce bucket is hot when its projected probe-side bytes exceed "
    "this multiple of the median nonzero bucket size (and the "
    "join.skew.minBucketBytes floor).", float)

JOIN_SKEW_MIN_BUCKET_BYTES = conf(
    "spark.rapids.tpu.sql.join.skew.minBucketBytes", 4 << 20,
    "Absolute floor for hot-bucket detection: buckets under this many "
    "bytes are never split regardless of the factor (splitting tiny "
    "buckets buys scheduling overhead, not wall time).", int)

JOIN_SKEW_MAX_SPLITS = conf(
    "spark.rapids.tpu.sql.join.skew.maxSplits", 8,
    "Upper bound on the sub-readers one hot bucket splits into (the "
    "split count otherwise targets the median bucket size).", int)

JOIN_SKEW_BROADCAST_THRESHOLD = conf(
    "spark.rapids.tpu.sql.join.skew.broadcastThresholdBytes", 8 << 20,
    "When the hot bucket's matching build-side bucket is under this "
    "many bytes it is broadcast (one shared device batch reused by "
    "every sub-join, zero copies); over it the bucket is still "
    "replicated by reference but counted as a replication so the "
    "memory cost is observable.", int)

KERNEL_BACKEND = conf(
    "spark.rapids.tpu.kernel.backend", "pallas",
    "Kernel backend for the gather-bound decode/aggregate hot paths: "
    "'pallas' (default — hand-written Pallas kernels: dense phase-"
    "decomposed RLE/bit-unpack, fused dictionary-decode+filter, "
    "single-pass segmented reduction — spark_rapids_tpu/kernels/, "
    "streaming arbitrarily large buffers through VMEM in double-"
    "buffered tiles of kernel.pallas.tileBytes) or 'xla' (the composed "
    "array-op formulations, demoted to correctness oracle — the "
    "one-knob revert). Selection is per call site with automatic "
    "per-kernel fallback to the XLA path when a shape/dtype isn't "
    "covered (never whole-query; counted in "
    "kernel.backend.pallas.hits/.fallbacks with reason tags), and CI "
    "diffs the two backends bit-for-bit.")

KERNEL_PALLAS_INTERPRET = conf(
    "spark.rapids.tpu.kernel.pallas.interpret", "auto",
    "Run Pallas kernels in interpreter mode: 'auto' (interpret unless "
    "the active jax backend is a real TPU — so CPU CI executes the "
    "real kernel bodies and parity gates are genuine, not skips), "
    "'true' (always interpret, for debugging), 'false' (always compile "
    "via Mosaic).")

KERNEL_PALLAS_TILE_BYTES = conf(
    "spark.rapids.tpu.kernel.pallas.tileBytes", 4 << 20,
    "Per-tile byte budget for the HBM->VMEM streaming tiler "
    "(kernels/tiling.py): gather-source buffers (dense decoded values, "
    "dictionaries, segmented-reduction sources) larger than one tile "
    "stream through the Pallas kernels as a second grid dimension of "
    "fixed-size tiles (double-buffered by the Pallas pipeline emitter) "
    "instead of requiring whole-buffer VMEM residency — this replaced "
    "the retired dense_too_large/dict_too_large/src_too_large fallback "
    "gates. Tile counts/bytes are observable as "
    "kernel.pallas.tiles[.family] / kernel.pallas.tileBytes[.family]; "
    "tile plans memoize per (kernel, shape) in the kernel cache "
    "(kernel.tilePlan.hits/misses). Must leave room for two resident "
    "tiles plus the element blocks in ~16 MiB VMEM/core.", int)

KERNEL_ABI_ENABLED = conf(
    "spark.rapids.tpu.kernel.abi.enabled", True,
    "Shape-erased kernel ABI (exec/kernel_abi.py): batches are renamed "
    "to canonical positional column names, value-range hints re-bucket "
    "to the coarse ABI table, and row-capacity / var-len-width ladders "
    "quantize to capacity tiers (with host-side pad at dispatch for "
    "batches not born at a tier) before every kernel dispatch, so "
    "queries that differ only in schema names, value ranges, or "
    "near-miss batch sizes share one compiled program. Every erased "
    "shape is a subset of the legacy power-of-two ladder, so disabling "
    "this only multiplies compiles — it never changes results (the "
    "bench_compile_bill --abi-report gate diffs the two).", bool)

KERNEL_ABI_TIER_STRIDE = conf(
    "spark.rapids.tpu.kernel.abi.tierStride", 2,
    "Row-capacity tier ladder stride: capacities quantize to every "
    "2^stride-th power-of-two rung (stride 1 = the legacy every-pow2 "
    "ladder; the default 2 gives tiers 16, 64, 256, 1024, ... — at "
    "most 4x padding for at most half the distinct capacity programs "
    "per family).", int)

KERNEL_ABI_WIDTH_STRIDE = conf(
    "spark.rapids.tpu.kernel.abi.widthStride", 2,
    "String/list max-width tier ladder stride (same scheme as "
    "tierStride; default tiers 1, 4, 16, 64, ...). Wide-string padding "
    "costs capacity x width bytes, so raise with care on string-heavy "
    "workloads.", int)

KERNEL_ABI_BUCKET_HINTS = conf(
    "spark.rapids.tpu.kernel.abi.bucketHints", True,
    "Re-bucket DeviceColumn.vbits value-range hints to the coarse ABI "
    "table {16, 32, 56} at the dispatch boundary (and at scan/upload "
    "hint derivation). The narrow fast paths only branch on coarse "
    "thresholds (<=16 single-digit sorts, <=32 i32 gathers, <64 packed "
    "radix fields), so the precise buckets buy program churn, not "
    "speed. A weaker vbits bound is always sound.", bool)

AGG_FUSED_FILTER = conf(
    "spark.rapids.tpu.sql.agg.fusedFilter.enabled", True,
    "Fuse a Filter directly under a hash aggregate into the "
    "aggregate's update kernel as a row mask instead of a compact "
    "(the sort-based grouping is capacity-proportional either way; "
    "compaction costs one full-capacity gather per column — measured "
    "~315 ms of the 738 ms round-4 q6 pipeline).", bool)

FUSION_ENABLED = conf(
    "spark.rapids.tpu.sql.fusion.enabled", True,
    "Whole-stage kernel fusion: collapse maximal chains of dispatch-only "
    "execs (Project/Filter) into a single TpuFusedStageExec whose one "
    "cached kernel evaluates the composed expression DAG with at most "
    "one stream compaction, and inline projection prologues directly "
    "under a hash aggregate into the aggregate's own update kernel. "
    "Each per-exec jit dispatch costs ~72 ms on the tunneled runtime "
    "(PERF.md), so an N-exec chain pays N-1 fewer dispatches per batch. "
    "Disable for parity testing against the unfused per-node path "
    "(Spark's whole-stage codegen / the reference's tiered project, "
    "basicPhysicalOperators.scala).", bool)

FUSION_MAX_EXPRS = conf(
    "spark.rapids.tpu.sql.fusion.maxExprs", 256,
    "Ceiling on the total expression-node count of one fused stage's "
    "composed output+condition DAG.  Substituting a projection into "
    "its consumers duplicates shared subtrees, so unguarded fusion "
    "could blow up trace time and compile breadth (the TPC-DS compile "
    "bill is pure breadth, PERF.md round 5); past the ceiling the "
    "chain stays unfused.", int)

FUSION_DONATE = conf(
    "spark.rapids.tpu.sql.fusion.donateInputs", True,
    "Donate the input batch's device buffers to fused-stage / project / "
    "filter dispatches (jax donate_argnums) when the producing exec is "
    "known not to retain them, letting XLA reuse the input HBM for the "
    "output and cutting peak memory for deep chains.  Donated "
    "dispatches skip the HBM-OOM retry path (the retry would replay "
    "consumed buffers).  Donating kernels compile OUTSIDE the "
    "persistent XLA compilation cache (never written, never reloaded "
    "— cache-RELOADED executables mis-apply the donation aliasing "
    "table on this jax; tests/test_fusion."
    "test_donation_persistent_cache_repro pins the minimal repro), so "
    "donation stays armed alongside warm compiles for every other "
    "program; each donating program pays one fresh compile per "
    "process (kernel.cache.noPersistCompiles counts them).", bool)

AGG_EXCHANGE = conf(
    "spark.rapids.tpu.sql.agg.exchange.enabled", False,
    "Plan grouped aggregates as a hash exchange on the grouping keys "
    "followed by a per-partition aggregate (Spark's partial/final "
    "aggregate split restructured so the exchange can ride a distributed "
    "data plane; auto-enabled when shuffle.transport=ici).", bool)

SORT_EXCHANGE = conf(
    "spark.rapids.tpu.sql.sort.exchange.enabled", False,
    "Plan global ORDER BY as a range exchange on the sort keys followed "
    "by per-partition sorts (partition p holds range-bucket p, so "
    "partition-ordered concatenation IS the total order; auto-enabled "
    "when shuffle.transport=ici/ici_ring so the exchange rides the "
    "mesh; reference: GpuRangePartitioning + GpuSortExec per shard).",
    bool)

WINDOW_EXCHANGE = conf(
    "spark.rapids.tpu.sql.window.exchange.enabled", False,
    "Plan window functions over PARTITION BY keys as a hash exchange on "
    "those keys followed by per-partition window evaluation "
    "(auto-enabled when shuffle.transport=ici/ici_ring; reference: "
    "Spark requires ClusteredDistribution(partitionSpec) under "
    "GpuWindowExec).", bool)

ENABLE_FLOAT_SORT = conf(
    "spark.rapids.tpu.sql.sort.float.enabled", True,
    "Enable sorting on float columns (NaN ordering matches Spark: NaN sorts "
    "greatest).", bool)

UDF_COMPILER_ENABLED = conf(
    "spark.rapids.tpu.sql.udfCompiler.enabled", True,
    "Compile Python UDF bytecode into the expression IR so UDFs run on TPU. "
    "(reference: udf-compiler Plugin.scala:29-34)", bool)

METRICS_ENABLED = conf(
    "spark.rapids.tpu.metrics.enabled", True,
    "Collect per-operator metrics (totalTime, numOutputRows/Batches, "
    "peakDevMemory). (reference: GpuExec.scala:27-56)", bool)

OBS_TRACE_ENABLED = conf(
    "spark.rapids.tpu.obs.trace.enabled", False,
    "Record execution spans (scan prep/upload/dispatch, exchange "
    "phases, semaphore waits, pyworker batches) into the bounded "
    "in-process ring buffer. Disabled, the instrumented paths take a "
    "single-bool-check no-op. Spans surface through the per-query "
    "profile and the Chrome trace exporter "
    "(obs/trace.py; open in Perfetto or chrome://tracing).", bool)

OBS_TRACE_BUFFER_SPANS = conf(
    "spark.rapids.tpu.obs.trace.bufferSpans", 65536,
    "Capacity of the span ring buffer; when a query outruns it the "
    "oldest spans drop (bounded memory, never the process).", int)

OBS_TRACE_CHROME_PATH = conf(
    "spark.rapids.tpu.obs.trace.chromePath", "",
    "When set (and tracing is enabled), every query's span window is "
    "also written to this path as Chrome trace-event JSON, overwriting "
    "the previous query's file.")

SCHED_MEMORY_BUDGET = conf(
    "spark.rapids.tpu.sched.memoryBudget", 0,
    "HBM byte budget the admission controller packs query estimates "
    "into: queries are admitted while the sum of their declared "
    "working-set estimates stays under it (sched.maxConcurrent is the "
    "hard count cap); excess queries queue instead of OOMing. 0 "
    "derives the budget from the device manager's HBM pool "
    "(bytes_limit x memory.pool.fraction; 8 GiB when the backend "
    "reports no limit).", int)

SCHED_MAX_CONCURRENT = conf(
    "spark.rapids.tpu.sched.maxConcurrent", 4,
    "Hard cap on concurrently RUNNING queries in the per-session "
    "QueryService, regardless of memory estimates (the inter-query "
    "layer above sql.concurrentTpuTasks, which still bounds "
    "device-task concurrency inside admitted queries).", int)

SCHED_DEFAULT_TIMEOUT_MS = conf(
    "spark.rapids.tpu.sched.defaultTimeoutMs", 0,
    "Default per-query deadline in milliseconds, covering queue wait "
    "AND execution; on expiry the query's CancelToken fires with "
    "timed_out=true and the query unwinds (admission slot released, "
    "prefetcher drained, shuffle fetches cancelled, spill entries "
    "freed), raising QueryTimeoutError from result(). 0 disables; "
    "submit(timeout_ms=...) overrides per query.", int)

SCHED_MAX_QUEUED = conf(
    "spark.rapids.tpu.sched.maxQueued", 1024,
    "Bound on the admission wait queue; submissions past it are "
    "rejected with QueryRejectedError (back-pressure instead of an "
    "unbounded thread pile-up).", int)

SCHED_QUERY_ESTIMATE_BYTES = conf(
    "spark.rapids.tpu.sched.queryEstimateBytes", 0,
    "Fixed HBM working-set estimate per query for admission control. "
    "0 (default) derives batchSizeBytes x (concurrentTpuTasks + "
    "scan.prefetch.depth), then refines per plan shape from the spill "
    "catalog's device-bytes high-water mark of prior runs; "
    "submit(estimate_bytes=...) overrides per query.", int)

SCHED_DEDUP_ENABLED = conf(
    "spark.rapids.tpu.sched.dedup.enabled", True,
    "Single-flight execution: concurrent submissions of the same "
    "deterministic plan (same canonical digest + output names) join "
    "one in-flight execution instead of running N copies — followers' "
    "futures resolve from the leader's result, leader cancellation "
    "promotes a follower instead of killing the flight. "
    "Non-deterministic / uncacheable plans always bypass "
    "(PlanFingerprint.cacheable gate). Off reverts to "
    "one-execution-per-submission.", bool)

SCHED_PROFILE_RING = conf(
    "spark.rapids.tpu.sched.profileRing", 64,
    "How many completed QueryProfiles the session retains, keyed by "
    "query id (concurrent collects no longer race one last-profile "
    "slot; last_query_profile() returns the most recently COMPLETED "
    "query's profile).", int)

OBS_HTTP_ENABLED = conf(
    "spark.rapids.tpu.obs.http.enabled", False,
    "Serve the live operational telemetry endpoint from a background "
    "daemon thread: /metrics (Prometheus text exposition of the "
    "MetricsRegistry plus live scheduler gauges), /queries (the "
    "QueryService's queued/running/recently-completed table), and "
    "/profiles/<qid> (QueryProfile JSON from the profile ring). Off by "
    "default: nothing binds a socket and the serving path costs "
    "nothing.", bool)

OBS_HTTP_PORT = conf(
    "spark.rapids.tpu.obs.http.port", 0,
    "TCP port for the telemetry endpoint when obs.http.enabled=true. "
    "0 binds an ephemeral port (discover it via "
    "session.obs_server.port — the CI scrape idiom).", int)

OBS_HTTP_HOST = conf(
    "spark.rapids.tpu.obs.http.host", "127.0.0.1",
    "Bind address for the telemetry endpoint (loopback by default; "
    "widen deliberately, the endpoint is unauthenticated).")

OBS_RECORDER_DIR = conf(
    "spark.rapids.tpu.obs.recorder.dir", "",
    "Directory for flight-recorder diagnostic bundles. Non-empty "
    "enables the recorder: a bounded in-memory ring of recent engine "
    "events (admission decisions, spill/arena traffic, OOM retries, "
    "query lifecycle) is kept, and on query failure, timeout, "
    "cancellation, or an OOM-retried success a self-contained bundle "
    "(profile.json + trace.json + events.jsonl + config.json + "
    "registry.json) is written here. Empty (default) disables the "
    "recorder entirely; event hooks cost one bool check. Bundles ride "
    "the QueryProfile assembly path, so obs.profile.enabled must stay "
    "true (its default) for them to fire.")

OBS_RECORDER_MAX_EVENTS = conf(
    "spark.rapids.tpu.obs.recorder.maxEvents", 4096,
    "Capacity of the flight recorder's in-memory event ring; the "
    "oldest events drop when a busy engine outruns it (bounded memory, "
    "never the process).", int)

OBS_SLOW_QUERY_MS = conf(
    "spark.rapids.tpu.obs.slowQueryMs", 0,
    "Wall-clock threshold in milliseconds for the structured "
    "slow-query log: a completed (or failed) query at or over it emits "
    "ONE JSONL record (ts, query_id, status, error, wall_s, "
    "queue_wait_s, result_rows, phases, wall_breakdown) to "
    "obs.slowQueryPath, or through the "
    "'spark_rapids_tpu.obs.slowquery' python logger when no path is "
    "set. 0 (default) disables. Rides the QueryProfile assembly path, "
    "so obs.profile.enabled must stay true (its default).", int)

OBS_SLOW_QUERY_PATH = conf(
    "spark.rapids.tpu.obs.slowQueryPath", "",
    "Append-mode file for slow-query JSONL records (one JSON object "
    "per line). Empty routes records to the python logger instead.")

OBS_SLOW_QUERY_MAX_BYTES = conf(
    "spark.rapids.tpu.obs.slowQueryMaxBytes", 16 * 1024 * 1024,
    "Size-based rotation for the slow-query JSONL file (and the drift "
    "sentinel's breach log): when an append would push the file past "
    "this many bytes, it is atomically renamed to <path>.1 (replacing "
    "the previous .1) and a fresh file starts — the keep-1 logrotate "
    "shape, at most 2x this size on disk per log. 0 disables rotation "
    "(unbounded append, the pre-rotation behaviour).", int)

OBS_ACCOUNTING_ENABLED = conf(
    "spark.rapids.tpu.obs.accounting.enabled", True,
    "Per-tenant resource metering (obs/accounting.py): attributes "
    "kernel dispatches, compile wall, scan bytes walked/uploaded, "
    "shuffle wire bytes, result-cache hits/misses, HBM byte-seconds "
    "and queue wait to the owning (session, statement template | plan "
    "digest) tenant, served on the obs endpoint's /tenants route. "
    "Single-flight followers and batched-statement members are billed "
    "their fair share of the execution they joined. Off: every "
    "charging hook is one bool check (the obs.compile pattern).", bool)

OBS_SENTINEL_ENABLED = conf(
    "spark.rapids.tpu.obs.sentinel.enabled", False,
    "Drift sentinel (obs/sentinel.py): a background watcher sampling "
    "the metrics registry every obs.sentinel.intervalMs, comparing "
    "windowed rates against a trailing EWMA baseline, and on a "
    "sustained breach (p95 latency regression, slow-query spike, "
    "result-cache hit-rate collapse, compile storm, spill surge) "
    "emitting ONE flight-recorder bundle per episode (reason 'slo') "
    "with per-tenant top-talkers attached, obs.sentinel.breaches[.rule]"
    " counters, and a structured JSONL line. Off by default: no "
    "thread runs.", bool)

OBS_SENTINEL_INTERVAL_MS = conf(
    "spark.rapids.tpu.obs.sentinel.intervalMs", 1000,
    "Sampling window of the drift sentinel in milliseconds; each tick "
    "evaluates the rule set against the delta since the previous "
    "tick.", int)

OBS_SENTINEL_RULES = conf(
    "spark.rapids.tpu.obs.sentinel.rules", "",
    "Rule spec for the drift sentinel: semicolon-separated "
    "rule:key=val,key=val entries — e.g. "
    "'latency:factor=2,sustain=2;slow:min=5' enables ONLY those rules "
    "with the given overrides. Empty (default) enables every rule "
    "(latency, slow, cacheHit, compile, spill) at its defaults; a "
    "typo'd rule or parameter raises at session init rather than "
    "silently disarming the watcher.")

OBS_SENTINEL_PATH = conf(
    "spark.rapids.tpu.obs.sentinel.path", "",
    "JSONL file for the sentinel's structured breach records (rotated "
    "by obs.slowQueryMaxBytes, the slow-query log's writer). Empty "
    "disables the breach log; flight-recorder bundles and counters "
    "still fire.")

SERVE_ENABLED = conf(
    "spark.rapids.tpu.serve.enabled", False,
    "Start the multi-tenant SQL serving front-end (serve/server.py): a "
    "background TCP server multiplexing remote client sessions onto "
    "this session's QueryService — length-prefixed wire protocol, "
    "per-session conf overlays and fair-share caps, prepared "
    "statements, a stamped result-set cache, and chunked streaming "
    "result delivery with client-credit backpressure. Off by default: "
    "nothing binds a socket.", bool)

SERVE_PORT = conf(
    "spark.rapids.tpu.serve.port", 0,
    "TCP port for the serving front-end when serve.enabled=true. 0 "
    "binds an ephemeral port (discover it via "
    "session.serve_server.port — the CI smoke idiom).", int)

SERVE_HOST = conf(
    "spark.rapids.tpu.serve.host", "127.0.0.1",
    "Bind address for the serving front-end (loopback by default; the "
    "protocol is unauthenticated, widen deliberately).")

SERVE_SESSION_IDLE_TIMEOUT_MS = conf(
    "spark.rapids.tpu.serve.session.idleTimeoutMs", 600_000,
    "Evict a client session after this much inactivity with no query "
    "in flight (prepared statements and the session conf overlay go "
    "with it; the next request on an evicted session gets a typed "
    "SessionExpired error and must re-hello).", int)

SERVE_SESSION_MAX_INFLIGHT = conf(
    "spark.rapids.tpu.serve.session.maxInFlight", 4,
    "Fair-share cap on concurrently in-flight queries per client "
    "session; past it a request is refused with FairShareExceeded "
    "(back-pressure to that client) so one greedy client cannot "
    "monopolize sched.memoryBudget or the admission queue.", int)

SERVE_RESULT_CACHE_ENABLED = conf(
    "spark.rapids.tpu.serve.resultCache.enabled", True,
    "Cache materialized query results keyed on (canonical plan digest, "
    "output names, source file stamps): a repeated deterministic query "
    "over unchanged files is served straight from host memory — zero "
    "device dispatches — and invalidates automatically when a source "
    "file's (mtime, size) stamp moves (the scan-cache contract applied "
    "to whole results). Non-deterministic plans (rand, UDFs) and "
    "unstampable sources never enter.", bool)

SERVE_RESULT_CACHE_MAX_BYTES = conf(
    "spark.rapids.tpu.serve.resultCache.maxBytes", 256 << 20,
    "Byte budget for the serving result-set cache; least-recently-used "
    "results evict past it. A single result larger than the whole "
    "budget is never cached.", int)

SERVE_INCREMENTAL_ENABLED = conf(
    "spark.rapids.tpu.serve.incremental.enabled", True,
    "Incremental maintenance of the serving result cache "
    "(exec/incremental.py): for deterministic cacheable plans whose "
    "root chain is a TPU hash aggregate over stampable parquet "
    "sources, the pre-final MERGED aggregate partial state is retained "
    "alongside the result (both under serve.resultCache.maxBytes). "
    "When a later lookup finds the sources drifted by pure APPEND "
    "(every old file's (path, mtime_ns, size) stamp unchanged, new "
    "files added), the SAME plan re-runs its update phase over only "
    "the delta files, merges with the retained partials, and "
    "finalizes — recompute cost proportional to the delta, not the "
    "dataset. Any other drift (rewrite / shrink / delete / mtime-only "
    "touch) falls back to the full recompute, which stays the "
    "bit-identical correctness oracle (flip this off to revert to "
    "all-or-nothing caching in one knob, the sql.fusion.enabled "
    "pattern).", bool)

SERVE_INCREMENTAL_REFRESH_MS = conf(
    "spark.rapids.tpu.serve.incremental.refreshMs", 0,
    "Poll interval for the background incremental refresher: every "
    "refreshMs it re-stamps the sources of retained cache entries and "
    "delta-refreshes any that drifted by pure append, at low priority "
    "and only while the scheduler has no live queries (the "
    "sched.precompile idle-wait contract) — so interactive hits stay "
    "warm instead of paying the delta on first touch. 0 (default) "
    "disables the thread; lookups still delta-refresh on demand.", int)

SERVE_INCREMENTAL_MAX_TRACKED = conf(
    "spark.rapids.tpu.serve.incremental.maxTracked", 64,
    "How many distinct (plan digest, output names) entries the "
    "incremental maintainer tracks for delta refresh (LRU past it). "
    "Each tracked entry pins its logical plan template; the retained "
    "partial-state tables themselves live in the result cache under "
    "serve.resultCache.maxBytes.", int)

SERVE_STREAM_CHUNK_ROWS = conf(
    "spark.rapids.tpu.serve.stream.chunkRows", 65536,
    "Rows per streamed Arrow result chunk. Each chunk costs one CHUNK "
    "frame and one client credit, so this knob trades per-frame "
    "overhead against backpressure granularity (a slow consumer bounds "
    "the server's read-ahead to its credit window times this).", int)

SERVE_WIRE_MAX_FRAME_BYTES = conf(
    "spark.rapids.tpu.serve.wire.maxFrameBytes", 256 << 20,
    "Upper bound on a single serving wire frame's declared payload "
    "length. A frame header claiming more is a protocol violation "
    "(a hostile or desynced length prefix): the connection is answered "
    "with a typed ServeWireError ERR (reason 'oversized') and torn "
    "down BEFORE any payload allocation happens — body bytes only "
    "ever allocate after the declared length validates under this "
    "bound.", int)

SERVE_WIRE_READ_TIMEOUT_MS = conf(
    "spark.rapids.tpu.serve.wire.readTimeoutMs", 30_000,
    "Per-connection frame-progress deadline on the serving reader: "
    "once the first byte of a frame has arrived, the rest of the "
    "frame must arrive within this bound or the connection is "
    "answered with a typed ERR (reason 'timeout') and closed — the "
    "slowloris defense (a client holding a half-sent frame open "
    "cannot pin a reader thread forever). A connection IDLE at a "
    "frame boundary is never timed out by this knob; idle sessions "
    "are serve.session.idleTimeoutMs territory.", int)

SERVE_WIRE_WRITE_STALL_MS = conf(
    "spark.rapids.tpu.serve.wire.writeStallMs", 60_000,
    "Write-stall deadline on serving-side frame sends (result "
    "streamers and control responses): a send that makes zero "
    "progress for this long — a client that stopped draining its "
    "socket — aborts the connection with a typed ServeWireError "
    "instead of pinning a streamer thread (and its retained result) "
    "in sendall forever. Progress resets the deadline, so a slow but "
    "live consumer is never killed.", int)

SERVE_WIRE_STORM_THRESHOLD = conf(
    "spark.rapids.tpu.serve.wire.stormThreshold", 16,
    "Malformed-frame storm threshold: once this server instance has "
    "counted this many malformed wire frames "
    "(serve.wire.malformedFrames), ONE flight-recorder bundle with "
    "reason 'protocol' is dumped (when obs.recorder.dir is set) so a "
    "hostile or desynced client storm is diagnosable post-hoc. 0 "
    "disables the bundle (counters still move).", int)

SERVE_DRAIN_DEADLINE_MS = conf(
    "spark.rapids.tpu.serve.drain.deadlineMs", 10_000,
    "Default deadline for ServeServer.drain(): the server stops "
    "accepting connections, refuses new queries with a typed "
    "'Draining' error, and gives in-flight result streams this long "
    "to finish; past it they are cancelled with the same typed error "
    "and every connection is torn down leak-audited (streamer threads "
    "joined, admission slots released, credit state dropped). Clients "
    "resume interrupted streams after reconnecting (resume tokens + "
    "chunk sequence numbers).", int)

SERVE_STREAM_RETAIN_BYTES = conf(
    "spark.rapids.tpu.serve.stream.retainBytes", 128 << 20,
    "Byte budget for the retained-stream window: materialized result "
    "tables of in-flight and recently finished streams are retained "
    "(LRU, process-wide — they survive a drain/restart cycle) so a "
    "client that reconnects can resume a stream from its last "
    "received chunk sequence number instead of re-running the query. "
    "An entry is dropped when the client acknowledges the completed "
    "stream, on LRU pressure, or when its session's resume token "
    "ages out.", int)

SERVE_BATCH_ENABLED = conf(
    "spark.rapids.tpu.serve.batch.enabled", True,
    "Coalesce prepared-statement executions: when the same statement "
    "template is bound with different parameters within the batching "
    "window, eligible plan shapes (projection over a parameterized "
    "filter) merge into ONE vectorized execution — each binding's "
    "predicate rides along as a marker column and results split per "
    "client host-side. Literal erasure in the kernel ABI means the "
    "coalesced run is compile-free across binding values. Off reverts "
    "to one execution per bind.", bool)

SERVE_BATCH_WINDOW_MS = conf(
    "spark.rapids.tpu.serve.batch.windowMs", 2,
    "How long an execute of a batch-eligible prepared statement waits "
    "for siblings before flushing (the micro-batching window). A full "
    "batch (batch.maxStatements) flushes immediately.", int)

SERVE_BATCH_MAX_STATEMENTS = conf(
    "spark.rapids.tpu.serve.batch.maxStatements", 16,
    "Upper bound on bindings coalesced into one vectorized execution; "
    "arrivals past it start the next batch.", int)

SERVE_FAULT_PLAN = conf(
    "spark.rapids.tpu.serve.test.faultPlan", "",
    "Deterministic fault-injection plan for serving-plane chaos "
    "testing, e.g. 'seed=7;stream.chunk:drop@3;accept:close@2;"
    "frame.body:corrupt@1'. Same grammar as shuffle.test.faultPlan; "
    "see spark_rapids_tpu/serve/faults.py for the serving injection "
    "points (accept, frame.header, frame.body, stream.chunk, "
    "client.read, session.lookup) and actions (drop, delay, close, "
    "corrupt, truncate, oversize, unknown, slow, fail). Empty "
    "disables injection.")

SERVE_AUTH_TOKENS = conf(
    "spark.rapids.tpu.serve.auth.tokens", "",
    "Comma-separated bearer-token allowlist for the serving wire. "
    "Non-empty: every hello must carry an 'auth_token' field matching "
    "one entry or the connection is refused with a typed AuthFailed "
    "ERR (counted in serve.authFailures) before a session exists. "
    "Empty (default) disables auth — the pre-fleet loopback posture. "
    "The token doubles as the tenant identity the fleet router keys "
    "its per-tenant in-flight quotas on.")

SERVE_TLS_CERT_FILE = conf(
    "spark.rapids.tpu.serve.tls.certFile", "",
    "PEM certificate chain for TLS on the serving listener. Set "
    "together with serve.tls.keyFile to ssl-wrap every accepted "
    "serving connection (clients connect with tls=True); empty "
    "(default) serves plaintext. The obs HTTP endpoint is unaffected.")

SERVE_TLS_KEY_FILE = conf(
    "spark.rapids.tpu.serve.tls.keyFile", "",
    "PEM private key matching serve.tls.certFile. Both must be set "
    "for TLS to engage; setting exactly one raises at server start "
    "rather than silently serving plaintext.")

FLEET_ENABLED = conf(
    "spark.rapids.tpu.fleet.enabled", False,
    "Join this session to a serve fleet: attach the shared cache "
    "plane at fleet.store.url — statement-template registry, "
    "plan-digest result cache (stamp-validated at lookup, so "
    "catalog/file drift invalidates fleet-wide), retained aggregate "
    "partials, and the persistent XLA compile cache directory — so N "
    "replicas behind fleet/router.py serve as one tier. Off "
    "(default): no store is attached and the single-process serve "
    "path is byte-for-byte unchanged.", bool)

FLEET_STORE_URL = conf(
    "spark.rapids.tpu.fleet.store.url", "",
    "Shared-store endpoint for the fleet cache plane: "
    "'file:///path/to/dir' (file-backed, the default deployment "
    "shape — atomic temp+rename puts, safe for same-host and "
    "shared-filesystem fleets) or 'tcp://host:port' (the in-memory "
    "fleet.store.StoreServer, for tests). Required when "
    "fleet.enabled=true.")

FLEET_STORE_MAX_ENTRY_BYTES = conf(
    "spark.rapids.tpu.fleet.store.maxEntryBytes", 64 << 20,
    "Largest single result-cache entry published to the shared "
    "store; bigger results stay local-only (they still serve local "
    "hits). Bounds both the store's disk/memory footprint and the "
    "deserialization cost a sibling replica pays on a shared hit.",
    int)

FLEET_ROUTER_HEALTH_POLL_MS = conf(
    "spark.rapids.tpu.fleet.router.healthPollMs", 500,
    "How often the fleet router polls each replica's /healthz and "
    "/metrics: drain state takes a replica out of placement rotation "
    "(satellite: /healthz now reports "
    "{state: serving|draining|drained, inflight}), and the sched "
    "queued/running gauges feed least-loaded placement for new "
    "sessions.", int)

FLEET_TENANT_MAX_INFLIGHT = conf(
    "spark.rapids.tpu.fleet.tenant.maxInFlight", 0,
    "Router-level cap on concurrently in-flight queries per tenant "
    "identity (the auth token, or the client address when auth is "
    "off) ACROSS the whole fleet — a layer above the per-session "
    "serve.session.maxInFlight each replica enforces. Past it the "
    "router answers the request with a typed TenantQuotaExceeded ERR "
    "without forwarding. 0 (default) disables the fleet-level "
    "quota.", int)

OBS_COMPILE_ENABLED = conf(
    "spark.rapids.tpu.obs.compile.enabled", True,
    "Record a CompileEvent for every first (kernel, arg-shape) call "
    "through the process kernel cache — the compile observatory "
    "(obs/compile.py): kernel family, canonical shape/dtype signature, "
    "backend, compile wall, cache tier (in-memory hit / persistent-"
    "XLA-cache reload / fresh compile), and the triggering query id + "
    "plan digest. Events land in a bounded ring with process-lifetime "
    "per-family aggregates, surface as kernel.compile spans in the "
    "Chrome trace, a 'compile' QueryProfile section, kernel.compile.* "
    "registry counters, and the /compiles endpoint route. Disabled, "
    "the kernel dispatch path pays one bool check.", bool)

OBS_COMPILE_RING_EVENTS = conf(
    "spark.rapids.tpu.obs.compile.ringEvents", 4096,
    "Capacity of the compile observatory's event ring; the oldest "
    "events drop past it (process-lifetime aggregates — per-family "
    "program/signature counts, compile wall — are unaffected).", int)

OBS_COMPILE_STORM_THRESHOLD = conf(
    "spark.rapids.tpu.obs.compile.stormThreshold", 64,
    "Programs one query may compile before the observatory flags a "
    "'compile storm': a flight-recorder compile.storm event (once per "
    "query) plus the kernel.compile.storms counter. The TPC-DS-99 "
    "suite averages ~27 programs/query cold (PERF.md compile bill), "
    "so a query past this threshold is hitting pathological shape "
    "churn.", int)

OBS_COMPILE_CORPUS_PATH = conf(
    "spark.rapids.tpu.obs.compile.corpusPath", "",
    "Append-mode JSONL file for the precompile corpus: on the first "
    "completion of each distinct plan digest that compiled at least "
    "one program, one record {plan_digest, query_id, programs: "
    "[{family, key, signature, backend}]} is appended — exactly the "
    "replay artifact an AOT precompile service needs to warm the "
    "persistent XLA cache off the serving path (ROADMAP item 2). "
    "Empty (default) disables corpus emission.")

OBS_COMPILE_CORPUS_REPLAY = conf(
    "spark.rapids.tpu.obs.compile.corpusReplay", True,
    "Attach a replay payload (pickled traceable + abstract argument "
    "shapes, base64) to each corpus program record so the AOT "
    "precompile service (sched/precompile.py) can re-lower and "
    "re-compile the exact program in a fresh process without data or "
    "plans. Costs one pickle per first (kernel, shape) call while a "
    "corpusPath is configured; programs whose traceable cannot pickle "
    "are recorded without a payload and counted as skipped at replay. "
    "Donation-built kernels never carry a payload — they are barred "
    "from the persistent cache (see sql.fusion.donateInputs).", bool)

SCHED_PRECOMPILE_ENABLED = conf(
    "spark.rapids.tpu.sched.precompile.enabled", False,
    "Start the background AOT precompile service at session init "
    "(sched/precompile.py): replays the precompile corpus "
    "(sched.precompile.corpusPath, falling back to "
    "obs.compile.corpusPath) through jax lower+compile at low priority "
    "— pausing whenever the scheduler has live queries — so a replica "
    "restart warms the persistent XLA cache off the serving path and "
    "serves warm from query one.", bool)

SCHED_PRECOMPILE_CORPUS_PATH = conf(
    "spark.rapids.tpu.sched.precompile.corpusPath", "",
    "Corpus JSONL the precompile service replays (a file written by a "
    "previous process via obs.compile.corpusPath). A DIRECTORY "
    "replays every *.jsonl inside it — the fleet warm-join shape, "
    "where each replica appends its own corpus file under the shared "
    "store's corpus/ directory and a joining replica replays them "
    "all. Empty: falls back to this session's obs.compile.corpusPath.")

SCHED_PRECOMPILE_IDLE_WAIT_MS = conf(
    "spark.rapids.tpu.sched.precompile.idleWaitMs", 25,
    "How long the precompile service sleeps between corpus programs, "
    "and while waiting for the scheduler to drain live queries before "
    "compiling the next one — the low-priority contract keeping "
    "replay off the serving path.", int)

OBS_PROFILE_ENABLED = conf(
    "spark.rapids.tpu.obs.profile.enabled", True,
    "Assemble a QueryProfile after every action (annotated plan tree, "
    "wall breakdown, per-query registry delta, explain report) — "
    "surfaced via session.last_query_profile(), "
    "DataFrame.explain('profile'), and query listeners.", bool)


class RapidsTpuConf:
    """Accessor over a settings map; analog of ``new RapidsConf(conf)``."""

    def __init__(self, settings: Optional[Dict[str, Any]] = None):
        self._settings: Dict[str, Any] = dict(settings or {})

    def get(self, entry: ConfEntry) -> Any:
        return entry.get(self)

    def get_raw(self, key: str, default: Any = None) -> Any:
        return self._settings.get(key, default)

    def set(self, key: str, value: Any) -> "RapidsTpuConf":
        self._settings[key] = value
        return self

    def is_operator_enabled(self, key: str, incompat: bool,
                            disabled_by_default: bool) -> bool:
        """Per-operator kill-switch lookup (reference: GpuOverrides.scala:131)."""
        raw = self._settings.get(key)
        if raw is not None:
            if isinstance(raw, str):
                return raw.strip().lower() in ("true", "1", "yes")
            return bool(raw)
        if disabled_by_default:
            return False
        if incompat:
            return self.get(INCOMPATIBLE_OPS)
        return True

    # -- convenience properties used widely ---------------------------------
    @property
    def sql_enabled(self) -> bool:
        return self.get(SQL_ENABLED)

    @property
    def explain(self) -> str:
        return str(self.get(EXPLAIN)).upper()

    @property
    def batch_size_bytes(self) -> int:
        return self.get(BATCH_SIZE_BYTES)

    @property
    def shuffle_partitions(self) -> int:
        return self.get(SHUFFLE_PARTITIONS)

    @property
    def test_enabled(self) -> bool:
        return self.get(TEST_ENABLED)

    @property
    def test_allowed_non_tpu(self) -> List[str]:
        raw = self.get(TEST_ALLOWED_NON_TPU) or ""
        return [s.strip() for s in raw.split(",") if s.strip()]


def registered_entries() -> List[ConfEntry]:
    with _REGISTRY_LOCK:
        return sorted(_REGISTRY.values(), key=lambda e: e.key)


def generate_docs() -> str:
    """Emit markdown docs for all keys.

    Analog of ``RapidsConf.main`` -> docs/configs.md ("Generated by
    RapidsConf.help. DO NOT EDIT!", reference RapidsConf.scala:885).
    """
    lines = [
        "# spark-rapids-tpu Configuration",
        "",
        "<!-- Generated by spark_rapids_tpu.config.generate_docs. DO NOT EDIT! -->",
        "",
        "| Name | Default | Description |",
        "|---|---|---|",
    ]
    for e in registered_entries():
        if e.internal:
            continue
        doc = e.doc.replace("|", "\\|")
        lines.append(f"| `{e.key}` | {e.default!r} | {doc} |")
    return "\n".join(lines) + "\n"


if __name__ == "__main__":  # python -m spark_rapids_tpu.config > docs/configs.md
    print(generate_docs(), end="")
