"""HBM-resident columnar batches: the device data currency of the engine.

TPU-native analog of the reference's ``GpuColumnVector``/``ColumnarBatch``
(reference: sql-plugin/src/main/java/.../GpuColumnVector.java:40-576 wrapping a
cudf device column, and Table<->ColumnarBatch conversions at
GpuColumnVector.java:261,293).

Design differences forced by TPU/XLA (see SURVEY.md §7 hard part #1):
cudf tolerates dynamic row counts; XLA compiles per static shape.  So a
``DeviceBatch`` carries

  * ``capacity`` — the padded, power-of-two-bucketed physical row count that
    XLA sees (bounds recompiles to O(log max_rows) shapes per schema), and
  * ``num_rows`` — the true logical row count, held host-side.

Rows in ``[num_rows, capacity)`` are padding: validity False, data zeroed.
Kernels must treat ``row_mask()`` as the ground truth for "row exists".

Strings are Arrow-var-len on host but fixed-width on device: a
``uint8 [capacity, max_len]`` byte matrix plus an ``int32 [capacity]`` length
vector (max_len itself is bucketed).  This is the TPU-friendly layout for the
byte-tensor string kernels (SURVEY.md §7 hard part #3).
"""

from __future__ import annotations

from dataclasses import dataclass, replace
from typing import List, Optional, Sequence, Tuple

import numpy as np
import pyarrow as pa

import jax
import jax.numpy as jnp

from spark_rapids_tpu import dtypes as dt


def compact_arrays(keep: "jnp.ndarray", dest: "jnp.ndarray", data,
                   validity, lengths=None, elem_validity=None):
    """Stable-compaction scatter shared by every compact path (filter
    compact, fused-filter value compact, ICI reassemble): row i moves
    to dest[i] when keep[i], rows with dest >= len drop.  Returns
    (data, validity, lengths, elem_validity)."""
    d = jnp.zeros_like(data).at[dest].set(data, mode="drop")
    v = jnp.zeros_like(validity).at[dest].set(validity & keep,
                                              mode="drop")
    ln = None if lengths is None else \
        jnp.zeros_like(lengths).at[dest].set(
            jnp.where(keep, lengths, 0), mode="drop")
    ev = None if elem_validity is None else \
        jnp.zeros_like(elem_validity).at[dest].set(
            elem_validity & keep[:, None], mode="drop")
    return d, v, ln, ev


def bucket_rows(n: int, min_bucket: int = 16) -> int:
    """Smallest capacity tier >= n (>= min_bucket).

    Delegates to the shape-erased ABI's capacity ladder
    (exec/kernel_abi.py): every 2^tierStride-th power-of-two rung under
    the default ABI, the legacy every-pow2 ladder when the ABI is
    disabled.  Batches BORN at tier capacities make the dispatch-time
    pad of kernel_abi.erase a no-op on the hot path."""
    from spark_rapids_tpu.exec import kernel_abi
    return kernel_abi.tier_rows(n, min_bucket)


def _bucket_strlen(n: int) -> int:
    from spark_rapids_tpu.exec import kernel_abi
    return kernel_abi.tier_strlen(n)


@jax.tree_util.register_pytree_node_class
@dataclass(frozen=True)
class DeviceColumn:
    """One column: device buffers + validity. Analog of GpuColumnVector.

    STRING and LIST share the var-len layout: a padded 2-D payload
    ``[capacity, max_len]`` + per-row ``lengths``; LIST additionally
    carries ``elem_validity`` (null elements inside a list)."""

    dtype: dt.DType
    data: jnp.ndarray              # [capacity] or [capacity, max_len]
    validity: jnp.ndarray          # bool [capacity]
    lengths: Optional[jnp.ndarray] = None  # int32 [capacity], string/list
    elem_validity: Optional[jnp.ndarray] = None  # bool [cap, max_len], list
    # static value-range hint for integer-backed columns: every VALID
    # value v satisfies -2^(vbits-1) <= v < 2^(vbits-1).  Set by scans
    # from host-known facts (dictionary pages, parquet chunk statistics),
    # bucketed to {8,16,...,56} so jit cache keys stay stable across
    # files; None = unknown.  Lets the aggregate/sort layers encode
    # narrow radix keys or direct-bin group ids (the analog of cudf's
    # hash-vs-sort groupby choice, which this engine makes per compile).
    vbits: Optional[int] = None
    # static no-nulls hint: validity is True at every live row (i < the
    # batch row count).  Set by scans when every page's def levels were
    # all-valid; lets reductions skip validity gathers entirely.
    nonnull: bool = False

    # -- pytree protocol so columns/batches can cross jit boundaries --------
    def tree_flatten(self):
        leaves = [self.data, self.validity]
        if self.lengths is not None:
            leaves.append(self.lengths)
        if self.elem_validity is not None:
            leaves.append(self.elem_validity)
        return tuple(leaves), (self.dtype, self.lengths is not None,
                               self.elem_validity is not None, self.vbits,
                               self.nonnull)

    @classmethod
    def tree_unflatten(cls, aux, children):
        dtype, has_len, has_ev = aux[0], aux[1], aux[2]
        vbits = aux[3] if len(aux) > 3 else None
        nonnull = aux[4] if len(aux) > 4 else False
        it = iter(children)
        data, validity = next(it), next(it)
        lengths = next(it) if has_len else None
        ev = next(it) if has_ev else None
        return cls(dtype, data, validity, lengths, ev, vbits, nonnull)

    @property
    def capacity(self) -> int:
        return int(self.data.shape[0])

    @property
    def max_len(self) -> int:
        assert self.dtype.has_lengths
        return int(self.data.shape[1])

    def nbytes(self) -> int:
        n = self.data.size * self.data.dtype.itemsize + self.validity.size
        if self.lengths is not None:
            n += self.lengths.size * 4
        if self.elem_validity is not None:
            n += self.elem_validity.size
        return int(n)

    def gather(self, indices: jnp.ndarray, valid: jnp.ndarray) -> "DeviceColumn":
        """Row gather; `valid` masks rows whose source index is meaningful.

        vbits<=32 integer-backed 8-byte columns gather through an i32
        view and widen after — an emulated-i64 gather costs 3x an i32
        one on TPU (PERF.md) and the hint guarantees losslessness."""
        if (self.vbits is not None and self.vbits <= 32 and
                self.data.ndim == 1 and
                self.data.dtype.itemsize == 8 and
                jnp.issubdtype(self.data.dtype, jnp.integer)):
            data = jnp.take(self.data.astype(jnp.int32), indices
                            ).astype(self.data.dtype)
        else:
            data = jnp.take(self.data, indices, axis=0)
        validity = jnp.take(self.validity, indices, axis=0) & valid
        lengths = None
        ev = None
        if self.lengths is not None:
            lengths = jnp.where(valid, jnp.take(self.lengths, indices), 0)
            data = jnp.where(valid[:, None], data,
                             jnp.zeros((), data.dtype))
        else:
            # zeros typed like data: a bare 0 would PROMOTE bool columns
            # to int under numpy rules and change the output schema
            data = jnp.where(_bcast(valid, data), data,
                             jnp.zeros((), data.dtype))
        if self.elem_validity is not None:
            ev = jnp.take(self.elem_validity, indices, axis=0) & \
                valid[:, None]
        return DeviceColumn(self.dtype, data, validity, lengths, ev,
                            self.vbits)


def _bcast(mask: jnp.ndarray, like: jnp.ndarray) -> jnp.ndarray:
    if like.ndim == 2:
        return mask[:, None]
    return mask


@jax.tree_util.register_pytree_node_class
class DeviceBatch:
    """A batch of device columns with a host-side logical row count."""

    def __init__(self, names: Sequence[str], columns: Sequence[DeviceColumn],
                 num_rows):
        self.names: List[str] = list(names)
        self.columns: List[DeviceColumn] = list(columns)
        # num_rows may be a host int OR a traced jnp scalar (inside jit);
        # host-side code that needs a concrete count calls int(batch.num_rows)
        self.num_rows = int(num_rows) if isinstance(
            num_rows, (int, np.integer)) else num_rows
        if self.columns:
            caps = {c.capacity for c in self.columns}
            assert len(caps) == 1, f"ragged capacities {caps}"
            self._capacity = caps.pop()
        else:
            self._capacity = bucket_rows(int(num_rows))

    # num_rows travels as a leaf so jit does NOT specialize on it — only on
    # capacity/schema (the XLA static-shape bucketing contract)
    def tree_flatten(self):
        # flatten must be purely structural: transforms (lax.cond, vmap)
        # round-trip pytrees through abstract values, and coercing here
        # would call jnp.asarray on an aval.  Coerce only host ints.
        nr = self.num_rows
        if isinstance(nr, (int, np.integer)):
            nr = jnp.asarray(nr, dtype=jnp.int32)
        leaves = tuple(self.columns) + (nr,)
        return leaves, (tuple(self.names), self._capacity)

    @classmethod
    def tree_unflatten(cls, aux, children):
        names, capacity = aux
        *cols, num_rows = children
        b = cls.__new__(cls)
        b.names = list(names)
        b.columns = list(cols)
        b.num_rows = num_rows
        b._capacity = capacity
        return b

    # ----------------------------------------------------------------------
    @property
    def capacity(self) -> int:
        return self._capacity

    @property
    def num_cols(self) -> int:
        return len(self.columns)

    @property
    def dtypes(self) -> List[dt.DType]:
        return [c.dtype for c in self.columns]

    def schema_key(self) -> Tuple:
        """Hashable (schema, shape-bucket) key — the XLA compile-cache key."""
        return (tuple(self.names),
                tuple(c.dtype.name for c in self.columns),
                self._capacity,
                tuple(c.max_len if c.dtype.has_lengths else 0
                      for c in self.columns))

    def column(self, name: str) -> DeviceColumn:
        return self.columns[self.names.index(name)]

    def row_mask(self) -> jnp.ndarray:
        return jnp.arange(self._capacity) < self.num_rows

    def nbytes(self) -> int:
        return sum(c.nbytes() for c in self.columns)

    def with_columns(self, names: Sequence[str],
                     columns: Sequence[DeviceColumn]) -> "DeviceBatch":
        return DeviceBatch(names, columns, self.num_rows)

    def select(self, names: Sequence[str]) -> "DeviceBatch":
        return DeviceBatch(names, [self.column(n) for n in names],
                           self.num_rows)

    def __repr__(self) -> str:
        cols = ", ".join(f"{n}:{c.dtype.name}" for n, c in
                         zip(self.names, self.columns))
        return (f"DeviceBatch(rows={int(self.num_rows)}/{self._capacity}, "
                f"[{cols}])")


# ---------------------------------------------------------------------------
# Host (Arrow) <-> device conversion.  Analog of HostColumnarToGpu /
# GpuColumnarToRowExec device<->host copies (reference:
# HostColumnarToGpu.scala:30-291, GpuColumnarToRowExec.scala:38-306).
# ---------------------------------------------------------------------------

def _np_column_from_arrow(arr: pa.ChunkedArray | pa.Array,
                          dtype: dt.DType, capacity: int
                          ) -> Tuple[np.ndarray, np.ndarray,
                                     Optional[np.ndarray],
                                     Optional[np.ndarray]]:
    if isinstance(arr, pa.ChunkedArray):
        arr = arr.combine_chunks()
    n = len(arr)
    validity = np.zeros(capacity, dtype=np.bool_)
    validity[:n] = ~np.asarray(arr.is_null())

    if dtype.is_list:
        # padded [capacity, max_len] element payload + lengths + element
        # validity (the device mirror of Arrow's offsets+values+nulls)
        py = arr.to_pylist()
        lens = [len(v) if v is not None else 0 for v in py]
        max_len = _bucket_strlen(max(lens, default=0))
        el_np = dtype.element.to_np()
        data = np.zeros((capacity, max_len), dtype=el_np)
        ev = np.zeros((capacity, max_len), dtype=np.bool_)
        lengths = np.zeros(capacity, dtype=np.int32)
        for i, v in enumerate(py):
            if v is None:
                continue
            lengths[i] = len(v)
            for j, x in enumerate(v):
                if x is None:
                    continue  # null element: ev stays False, data stays 0
                ev[i, j] = True
                data[i, j] = x
        return data, validity, lengths, ev

    if dtype.is_string:
        py = arr.to_pylist()
        blens = [len(s.encode("utf-8")) if s is not None else 0 for s in py]
        max_len = _bucket_strlen(max(blens, default=0))
        data = np.zeros((capacity, max_len), dtype=np.uint8)
        lengths = np.zeros(capacity, dtype=np.int32)
        for i, s in enumerate(py):
            if s is None:
                continue
            b = s.encode("utf-8")
            lengths[i] = len(b)
            if b:
                data[i, :len(b)] = np.frombuffer(b, dtype=np.uint8)
        return data, validity, lengths, None

    np_dtype = dtype.to_np()
    data = np.zeros(capacity, dtype=np_dtype)
    if pa.types.is_timestamp(arr.type):
        arr = arr.cast(pa.timestamp("us"))
        vals = arr.to_numpy(zero_copy_only=False)
        ints = vals.astype("datetime64[us]").astype(np.int64)
        ints = np.where(validity[:n], ints, 0)
        data[:n] = ints
    elif pa.types.is_date32(arr.type):
        vals = arr.to_numpy(zero_copy_only=False)
        ints = vals.astype("datetime64[D]").astype(np.int64).astype(np.int32)
        ints = np.where(validity[:n], ints, 0)
        data[:n] = ints
    else:
        vals = arr.fill_null(_zero_value(dtype)).to_numpy(zero_copy_only=False)
        data[:n] = vals.astype(np_dtype, copy=False)
    return data, validity, None, None


def _zero_value(dtype: dt.DType):
    if dtype.is_bool:
        return False
    if dtype.is_floating:
        return 0.0
    return 0


def from_arrow(table: pa.Table, min_bucket: int = 16,
               capacity: Optional[int] = None) -> DeviceBatch:
    """Upload an Arrow table into a padded DeviceBatch."""
    n = table.num_rows
    cap = capacity or bucket_rows(n, min_bucket)
    names, cols = [], []
    for field_, col in zip(table.schema, table.columns):
        dtype = dt.from_arrow(field_.type)
        if dtype is None:
            raise TypeError(f"unsupported Arrow type {field_.type} "
                            f"for column {field_.name}")
        if dtype == dt.NULL:
            dtype = dt.BOOL  # void columns materialize as all-null bool
        data, validity, lengths, ev = _np_column_from_arrow(col, dtype, cap)
        names.append(field_.name)
        vb, nn = _upload_hints(dtype, data, validity, n)
        cols.append(DeviceColumn(
            dtype,
            jnp.asarray(data),
            jnp.asarray(validity),
            jnp.asarray(lengths) if lengths is not None else None,
            jnp.asarray(ev) if ev is not None else None,
            vbits=vb, nonnull=nn))
    return DeviceBatch(names, cols, n)


_VBIT_BUCKETS = (8, 16, 24, 32, 40, 48, 56)


def bits_for_range(lo: int, hi: int):
    """Smallest vbits bucket whose signed range covers [lo, hi]
    (None when none does); the shared bucket table keeps jit cache
    keys stable across files/uploads with nearby ranges."""
    for b in _VBIT_BUCKETS:
        if -(1 << (b - 1)) <= lo and hi < (1 << (b - 1)):
            return b
    return None


def _upload_hints(dtype: dt.DType, data: np.ndarray,
                  validity: np.ndarray, n: int):
    """Static hints for an uploaded column: one O(n) host pass over the
    numpy buffers bounds the valid values (see DeviceColumn.vbits) —
    negligible next to the upload itself, and it unlocks the narrow
    sort/aggregate/gather fast paths for in-memory DataFrames the same
    way parquet statistics do for scans."""
    if n == 0:
        return None, True
    live_valid = validity[:n]
    nn = bool(live_valid.all())
    if (dtype.is_string or dtype.is_bool or dtype.is_list or
            not np.issubdtype(np.asarray(data).dtype, np.integer)):
        return None, nn
    vals = data[:n][live_valid] if not nn else data[:n]
    from spark_rapids_tpu.exec import kernel_abi
    if vals.size == 0:
        return kernel_abi.bucket_vbits(_VBIT_BUCKETS[0]), nn
    # the ABI re-buckets upload-derived hints to its coarse table so
    # data-dependent value ranges stop minting per-range programs
    return kernel_abi.bucket_vbits(
        bits_for_range(int(vals.min()), int(vals.max()))), nn


def _pack_wire_key(d: jnp.ndarray) -> str:
    if d.dtype == jnp.bool_:
        return "uint8"
    return str(d.dtype)


def _pack_batch_impl(batch: DeviceBatch):
    """Serialize a whole DeviceBatch (num_rows + every column's
    data/validity/lengths/elem_validity at FULL capacity) into ONE
    device buffer per wire dtype — no cross-width bitcasts (the TPU X64
    rewriter rejects 64-bit bitcast-convert in larger graphs)."""
    bufs: Dict[str, List[jnp.ndarray]] = {}

    def put(key: str, arr: jnp.ndarray) -> None:
        bufs.setdefault(key, []).append(arr.reshape(-1))

    put("int32", jnp.asarray(batch.num_rows,
                             dtype=jnp.int32).reshape(1))
    for c in batch.columns:
        d = c.data
        put(_pack_wire_key(d),
            d.astype(jnp.uint8) if d.dtype == jnp.bool_ else d)
        put("uint8", c.validity.astype(jnp.uint8))
        if c.lengths is not None:
            put("int32", c.lengths.astype(jnp.int32))
        if c.elem_validity is not None:
            put("uint8", c.elem_validity.astype(jnp.uint8))
    return {k: (v[0] if len(v) == 1 else jnp.concatenate(v))
            for k, v in bufs.items()}


def _dispatch_pack(batch: DeviceBatch) -> jnp.ndarray:
    """Dispatch (async) the pack kernel for one batch; no host read.

    Pack is a pure column-container kernel (names never reach the
    emitted HLO), so it keys on the ABI's positional layout and runs
    over the name/hint-erased batch — any two batches with one
    physical layout share one program.  pad=False: the host download
    epilogue reads the ORIGINAL buffer shapes back out of the packed
    buffer, so dispatch-time capacity padding must not apply here."""
    from spark_rapids_tpu.exec import kernel_abi, kernel_cache as kc
    key = ("pack_batch", kernel_abi.erased_key(batch))
    fn = kc.get_kernel(key, lambda: _pack_batch_impl)
    # strip_hints: pack never reads vbits/nonnull, so even bucketed
    # hints on the treedef would re-trace an identical program
    return fn(kernel_abi.erase(batch, pad=False, strip_hints=True))


def _download_batch(batch: DeviceBatch, packed: Optional[jnp.ndarray]
                    = None):
    """ONE device->host transfer for the whole batch.

    The first download permanently degrades the dispatch path on
    tunneled device runtimes, and every post-download device op (even a
    ``[:n]`` slice) becomes a synchronous round trip — so the terminal
    collect packs everything device-side and reads one buffer.

    Returns (num_rows, [(data, validity, lengths, ev), ...]) as numpy
    arrays at full capacity."""
    if packed is None:
        packed = _dispatch_pack(batch)
    for arr in packed.values():  # overlap the (few) transfers
        try:
            arr.copy_to_host_async()
        except Exception:
            pass
    host = {k: np.asarray(v) for k, v in packed.items()}
    pos = {k: 0 for k in host}

    def take(key: str, count: int):
        off = pos[key]
        pos[key] = off + count
        return host[key][off:off + count]

    n = int(take("int32", 1)[0])
    cap = batch.capacity
    cols = []
    for c in batch.columns:
        count = int(np.prod(c.data.shape))
        data = take(_pack_wire_key(c.data), count).reshape(c.data.shape)
        if c.data.dtype == jnp.bool_:
            data = data.astype(bool)
        validity = take("uint8", cap).astype(bool)
        lengths = ev = None
        if c.lengths is not None:
            lengths = take("int32", cap)
        if c.elem_validity is not None:
            cnt = int(np.prod(c.elem_validity.shape))
            ev = take("uint8", cnt).reshape(
                c.elem_validity.shape).astype(bool)
        cols.append((data, validity, lengths, ev))
    return n, cols


def _strings_to_arrow(data: np.ndarray, lengths: np.ndarray,
                      validity: np.ndarray, n: int) -> pa.Array:
    """Vectorized padded-byte-matrix -> Arrow utf8 (no per-row Python)."""
    data, lengths, validity = data[:n], lengths[:n], validity[:n]
    lens = np.where(validity, lengths, 0).astype(np.int64)
    offsets = np.zeros(n + 1, dtype=np.int64)
    np.cumsum(lens, out=offsets[1:])
    if offsets[-1] > np.iinfo(np.int32).max:
        # >2 GiB of string payload overflows utf8's int32 offsets;
        # build row-by-row into a (chunked-friendly) python list
        py = [bytes(data[i, :lens[i]]).decode("utf-8", errors="replace")
              if validity[i] else None for i in range(n)]
        return pa.array(py, type=pa.string())
    mask = np.arange(data.shape[1])[None, :] < lens[:, None]
    flat = np.ascontiguousarray(data)[mask]
    offsets = offsets.astype(np.int32)
    null_bitmap = pa.py_buffer(
        np.packbits(validity, bitorder="little").tobytes())
    return pa.Array.from_buffers(
        pa.utf8(), n,
        [None if validity.all() else null_bitmap,
         pa.py_buffer(offsets.tobytes()), pa.py_buffer(flat.tobytes())])


# Fixed compaction tiers: a batch with a huge capacity but few rows
# compacts to the smallest tier >= its row count.  Tiers (not exact
# buckets) keep the candidate kernel set tiny so every compact/pack
# program can be dispatched BEFORE the first device->host download —
# after it, loading an executable costs seconds on a tunneled runtime.
_DL_TIERS = (4096, 65536, 1048576)
_WARMED_TIERS: set = set()


def _dl_tier(n: int, capacity: int):
    for t in _DL_TIERS:
        if n <= t and capacity > 4 * t:
            return t
    return None


def _compact_kernels(b: DeviceBatch):
    """(tier -> (slice kernel, pack kernel)) for one batch, loading every
    candidate executable now (pre-download).  Keys and dispatch are
    schema-erased like pack (the slice gathers by position only); the
    caller restamps real names on the compacted batch."""
    from spark_rapids_tpu.exec import kernel_abi, kernel_cache as kc
    out = {}
    for t in _DL_TIERS:
        if b.capacity > 4 * t:
            key = ("dl_compact", kernel_abi.erased_key(b), t)
            out[t] = kc.get_kernel(key, lambda: _slice_head,
                                   static_argnames=("cap",))
    return out


def _run_compact(b: DeviceBatch, fn, t: int) -> DeviceBatch:
    """One erased dl_compact dispatch + host-side name restamp."""
    from spark_rapids_tpu.exec import kernel_abi
    nb = fn(kernel_abi.erase(b, pad=False), cap=t)
    return DeviceBatch(b.names, nb.columns, nb.num_rows)


def _compact_for_download(batches: Sequence[DeviceBatch]):
    """Re-bucket batches whose capacity vastly exceeds their row count
    (e.g. an aggregate output that inherited a multi-million-row concat
    capacity) so the terminal download moves rows, not padding.

    Returns (batches, packed_or_None per batch).  EVERY pack/compact
    kernel — including the plain full-capacity pack of batches that end
    up uncompacted — is built and dispatched BEFORE the single fused
    row-count read, so nothing compiles or loads after the first
    (dispatch-degrading) download."""
    traced = [b for b in batches
              if not isinstance(b.num_rows, (int, np.integer))]
    candidates = {}
    full_packed = []
    for b in batches:
        if any(b.capacity > 4 * t for t in _DL_TIERS):
            candidates[id(b)] = _compact_kernels(b)
            # warm the slice+pack kernels for each possible compacted
            # schema ONCE per (schema, tier) per process — mid-query
            # to_arrow callers (shuffle slices) must not re-pay the
            # discarded warm-up compute on every call
            from spark_rapids_tpu.exec import kernel_abi
            for t, fn in candidates[id(b)].items():
                wkey = (kernel_abi.erased_key(b), t)
                if wkey not in _WARMED_TIERS:
                    _WARMED_TIERS.add(wkey)
                    _dispatch_pack(_run_compact(b, fn, t))
        # full-capacity pack, reused if this batch stays uncompacted
        full_packed.append(_dispatch_pack(b))
    if traced:
        # distributed (ICI) readers hand out batches committed to
        # different mesh devices; colocate the count scalars before the
        # fused stack+read
        scalars = [jnp.asarray(b.num_rows, dtype=jnp.int32)
                   for b in traced]
        devs = {d for s in scalars for d in s.devices()}
        if len(devs) > 1:
            tgt = sorted(devs, key=lambda d: d.id)[0]
            scalars = [jax.device_put(s, tgt) for s in scalars]
        counts = np.asarray(jnp.stack(scalars))
        for b, n in zip(traced, counts):
            b.num_rows = int(n)
    out, out_packed = [], []
    for b, fp in zip(batches, full_packed):
        n = int(b.num_rows)
        tier = _dl_tier(n, b.capacity)
        if tier is not None and id(b) in candidates and \
                tier in candidates[id(b)]:
            nb = _run_compact(b, candidates[id(b)][tier], tier)
            nb.num_rows = n
            out.append(nb)
            out_packed.append(_dispatch_pack(nb))
        else:
            out.append(b)
            out_packed.append(fp)
    return out, out_packed


def _slice_head(batch: DeviceBatch, cap: int) -> DeviceBatch:
    idx = jnp.arange(cap)
    valid = idx < jnp.asarray(batch.num_rows, dtype=jnp.int32)
    idx = jnp.clip(idx, 0, batch.capacity - 1)
    cols = [c.gather(idx, valid) for c in batch.columns]
    return DeviceBatch(batch.names, cols, batch.num_rows)


def to_arrow_all(batches: Sequence[DeviceBatch]) -> List[pa.Table]:
    """Convert many batches: ALL pack kernels dispatch before the first
    download, so every device op runs on the fast pre-download path."""
    batches, packed = _compact_for_download(batches)
    return [to_arrow(b, p) for b, p in zip(batches, packed)]


def to_arrow(batch: DeviceBatch,
             packed: Optional[jnp.ndarray] = None) -> pa.Table:
    """Download a DeviceBatch back to an Arrow table (strips padding),
    via a single packed device->host transfer."""
    if packed is None:
        (batch,), (packed,) = _compact_for_download([batch])
    n, host_cols = _download_batch(batch, packed)
    arrays, fields = [], []
    for name, col, (data, validity, lengths, ev) in zip(
            batch.names, batch.columns, host_cols):
        validity = validity[:n]
        mask = ~validity
        if col.dtype.is_string:
            arr = _strings_to_arrow(data, lengths, validity, n)
        elif col.dtype.is_list:
            data = data[:n]
            lengths = lengths[:n]
            if ev is None:
                ev = np.ones(data.shape, dtype=bool)
            else:
                ev = ev[:n]
            py = []
            for i in range(n):
                if not validity[i]:
                    py.append(None)
                else:
                    py.append([data[i, j].item() if ev[i, j] else None
                               for j in range(lengths[i])])
            arr = pa.array(py, type=col.dtype.to_arrow())
        elif col.dtype.id == dt.TypeId.TIMESTAMP_US:
            ints = data[:n].astype("datetime64[us]")
            arr = pa.array(ints, type=pa.timestamp("us", tz="UTC"),
                           mask=mask)
        elif col.dtype.id == dt.TypeId.DATE32:
            days = data[:n].astype("datetime64[D]")
            arr = pa.array(days, type=pa.date32(), mask=mask)
        else:
            arr = pa.array(data[:n], mask=mask)
        arrays.append(arr)
        fields.append(pa.field(name, arr.type))
    return pa.Table.from_arrays(arrays, schema=pa.schema(fields))


def _combined_hints(cols: Sequence[DeviceColumn]):
    """Hint union for concatenated columns: the widest vbits if every
    input carries one, nonnull only if every input is."""
    vbs = [c.vbits for c in cols]
    vb = max(vbs) if all(v is not None for v in vbs) else None
    return vb, all(c.nonnull for c in cols)


def concat_batches(batches: Sequence[DeviceBatch],
                   min_bucket: int = 16) -> DeviceBatch:
    """Device-side concatenation (analog of Table.concatenate used by
    GpuCoalesceBatches, reference: GpuCoalesceBatches.scala:40-711).

    Batches whose ``num_rows`` is a device scalar (output of a jitted
    kernel that hasn't been read back) concatenate WITHOUT any
    device->host sync — an ``int(num_rows)`` here would serialize the
    whole async pipeline per batch (the r2 bench's 8.4 s hot spot)."""
    if any(not isinstance(b.num_rows, (int, np.integer))
           for b in batches):
        return _concat_batches_nosync(batches, min_bucket)
    batches = [b for b in batches if int(b.num_rows) > 0] or list(batches[:1])
    if len(batches) == 1:
        return batches[0]
    # distributed readers (shuffle/ici.py) hand out batches committed to
    # their owning mesh device; concatenating across partitions must first
    # colocate them or XLA rejects the mixed-device concat
    devs = set()
    for b in batches:
        if b.columns:
            devs |= set(b.columns[0].data.devices())
    if len(devs) > 1:
        target = sorted(devs, key=lambda d: d.id)[0]
        batches = [jax.device_put(b, target) for b in batches]
    total = sum(int(b.num_rows) for b in batches)
    cap = bucket_rows(total, min_bucket)
    names = batches[0].names
    out_cols: List[DeviceColumn] = []
    for ci, name in enumerate(names):
        dtype = batches[0].columns[ci].dtype
        if dtype.has_lengths:
            max_len = max(b.columns[ci].max_len for b in batches)
            has_ev = any(b.columns[ci].elem_validity is not None
                         for b in batches)
            datas, vals, lens, evs = [], [], [], []
            for b in batches:
                c = b.columns[ci]
                nb = int(b.num_rows)
                d = c.data[:nb]
                if c.max_len < max_len:
                    d = jnp.pad(d, ((0, 0), (0, max_len - c.max_len)))
                datas.append(d)
                vals.append(c.validity[:nb])
                lens.append(c.lengths[:nb])
                if has_ev:
                    e = c.elem_validity if c.elem_validity is not None \
                        else jnp.ones((c.capacity, c.max_len),
                                      dtype=jnp.bool_)
                    e = e[:nb]
                    if c.max_len < max_len:
                        e = jnp.pad(e, ((0, 0), (0, max_len - c.max_len)))
                    evs.append(e)
            data = jnp.concatenate(datas, axis=0)
            data = jnp.pad(data, ((0, cap - total), (0, 0)))
            validity = jnp.pad(jnp.concatenate(vals), (0, cap - total))
            lengths = jnp.pad(jnp.concatenate(lens), (0, cap - total))
            ev = None
            if has_ev:
                ev = jnp.pad(jnp.concatenate(evs, axis=0),
                             ((0, cap - total), (0, 0)))
            out_cols.append(DeviceColumn(dtype, data, validity, lengths,
                                         ev))
        else:
            vb, nn = _combined_hints([b.columns[ci] for b in batches])
            data = jnp.concatenate([b.columns[ci].data[:int(b.num_rows)]
                                    for b in batches])
            data = jnp.pad(data, (0, cap - total))
            validity = jnp.pad(
                jnp.concatenate([b.columns[ci].validity[:int(b.num_rows)]
                                 for b in batches]), (0, cap - total))
            out_cols.append(DeviceColumn(dtype, data, validity, None,
                                         vbits=vb, nonnull=nn))
    return DeviceBatch(names, out_cols, total)


def _concat_batches_nosync(batches: Sequence[DeviceBatch],
                           min_bucket: int = 16) -> DeviceBatch:
    """Concatenate without reading any device value: output capacity is
    the (static) bucketed sum of input capacities, valid rows compact to
    the front with one stable argsort, and the result's num_rows is the
    traced sum — so the async dispatch stream never blocks."""
    # host-known empties can still be dropped for free
    kept = [b for b in batches
            if not (isinstance(b.num_rows, (int, np.integer))
                    and int(b.num_rows) == 0)]
    batches = kept or list(batches[:1])
    if len(batches) == 1:
        return batches[0]
    devs = set()
    for b in batches:
        if b.columns:
            devs |= set(b.columns[0].data.devices())
    if len(devs) > 1:
        target = sorted(devs, key=lambda d: d.id)[0]
        batches = [jax.device_put(b, target) for b in batches]

    from spark_rapids_tpu.exec import kernel_abi, kernel_cache as kc
    cap = bucket_rows(sum(b.capacity for b in batches), min_bucket)
    key = ("concat_nosync", cap,
           tuple(kernel_abi.erased_key(b) for b in batches))
    fn = kc.get_kernel(key, lambda: _concat_nosync_impl,
                       static_argnames=("cap",))
    # schema-erased dispatch (concat is positional); restamp the real
    # names host-side — callers read the output's names
    out = fn(tuple(kernel_abi.erase(b, pad=False) for b in batches),
             cap=cap)
    return DeviceBatch(batches[0].names, out.columns, out.num_rows)


def _concat_nosync_impl(batches, cap: int) -> DeviceBatch:
    exists = jnp.concatenate([b.row_mask() for b in batches])
    exists = jnp.pad(exists, (0, cap - exists.shape[0]))
    # valid rows to the front WITHOUT a sort (XLA sort compiles are
    # minutes-scale): scatter an identity map at cumsum ranks, then
    # gather through it
    dest = jnp.where(exists, jnp.cumsum(exists.astype(jnp.int32)) - 1,
                     cap)
    src = jnp.arange(cap, dtype=jnp.int32)
    order = jnp.zeros((cap,), dtype=jnp.int32).at[dest].set(
        src, mode="drop")
    sorted_exists = jnp.take(exists, order) & \
        (jnp.arange(cap) < jnp.sum(exists.astype(jnp.int32)))
    names = batches[0].names
    out_cols: List[DeviceColumn] = []
    for ci in range(len(names)):
        dtype = batches[0].columns[ci].dtype
        if dtype.has_lengths:
            max_len = max(b.columns[ci].max_len for b in batches)
            has_ev = any(b.columns[ci].elem_validity is not None
                         for b in batches)
            datas, vals, lens, evs = [], [], [], []
            for b in batches:
                c = b.columns[ci]
                d = c.data
                if c.max_len < max_len:
                    d = jnp.pad(d, ((0, 0), (0, max_len - c.max_len)))
                datas.append(d)
                vals.append(c.validity)
                lens.append(c.lengths)
                if has_ev:
                    e = c.elem_validity if c.elem_validity is not None \
                        else jnp.ones((c.capacity, c.max_len),
                                      dtype=jnp.bool_)
                    if c.max_len < max_len:
                        e = jnp.pad(e, ((0, 0), (0, max_len - c.max_len)))
                    evs.append(e)
            col = DeviceColumn(
                dtype,
                jnp.pad(jnp.concatenate(datas, axis=0),
                        ((0, cap - sum(d.shape[0] for d in datas)),
                         (0, 0))),
                jnp.pad(jnp.concatenate(vals),
                        (0, cap - sum(v.shape[0] for v in vals))),
                jnp.pad(jnp.concatenate(lens),
                        (0, cap - sum(x.shape[0] for x in lens))),
                jnp.pad(jnp.concatenate(evs, axis=0),
                        ((0, cap - sum(e.shape[0] for e in evs)),
                         (0, 0))) if has_ev else None)
        else:
            data = jnp.concatenate([b.columns[ci].data for b in batches])
            col = DeviceColumn(
                dtype,
                jnp.pad(data, (0, cap - data.shape[0])),
                jnp.pad(jnp.concatenate([b.columns[ci].validity
                                         for b in batches]),
                        (0, cap - data.shape[0])),
                None)
        # gather() zeroes data/lengths/ev where the mask is False, so
        # the padding-rows-are-zeroed batch contract holds as-is
        gcol = col.gather(order, sorted_exists)
        if not dtype.has_lengths:
            # the compaction maps live outputs to live inputs, so the
            # inputs' hints survive (gather() alone can't know that)
            vb, nn = _combined_hints([b.columns[ci] for b in batches])
            gcol = replace(gcol, vbits=vb, nonnull=nn)
        out_cols.append(gcol)
    total = sum(jnp.asarray(b.num_rows, dtype=jnp.int32)
                for b in batches)
    return DeviceBatch(names, out_cols, total)
