"""HBM-resident columnar batches: the device data currency of the engine.

TPU-native analog of the reference's ``GpuColumnVector``/``ColumnarBatch``
(reference: sql-plugin/src/main/java/.../GpuColumnVector.java:40-576 wrapping a
cudf device column, and Table<->ColumnarBatch conversions at
GpuColumnVector.java:261,293).

Design differences forced by TPU/XLA (see SURVEY.md §7 hard part #1):
cudf tolerates dynamic row counts; XLA compiles per static shape.  So a
``DeviceBatch`` carries

  * ``capacity`` — the padded, power-of-two-bucketed physical row count that
    XLA sees (bounds recompiles to O(log max_rows) shapes per schema), and
  * ``num_rows`` — the true logical row count, held host-side.

Rows in ``[num_rows, capacity)`` are padding: validity False, data zeroed.
Kernels must treat ``row_mask()`` as the ground truth for "row exists".

Strings are Arrow-var-len on host but fixed-width on device: a
``uint8 [capacity, max_len]`` byte matrix plus an ``int32 [capacity]`` length
vector (max_len itself is bucketed).  This is the TPU-friendly layout for the
byte-tensor string kernels (SURVEY.md §7 hard part #3).
"""

from __future__ import annotations

from dataclasses import dataclass, replace
from typing import List, Optional, Sequence, Tuple

import numpy as np
import pyarrow as pa

import jax
import jax.numpy as jnp

from spark_rapids_tpu import dtypes as dt


def bucket_rows(n: int, min_bucket: int = 16) -> int:
    """Next power-of-two capacity >= n (>= min_bucket)."""
    cap = max(int(min_bucket), 1)
    n = max(int(n), 1)
    while cap < n:
        cap <<= 1
    return cap


def _bucket_strlen(n: int) -> int:
    if n <= 0:
        return 1
    cap = 1
    while cap < n:
        cap <<= 1
    return cap


@jax.tree_util.register_pytree_node_class
@dataclass(frozen=True)
class DeviceColumn:
    """One column: device buffers + validity. Analog of GpuColumnVector.

    STRING and LIST share the var-len layout: a padded 2-D payload
    ``[capacity, max_len]`` + per-row ``lengths``; LIST additionally
    carries ``elem_validity`` (null elements inside a list)."""

    dtype: dt.DType
    data: jnp.ndarray              # [capacity] or [capacity, max_len]
    validity: jnp.ndarray          # bool [capacity]
    lengths: Optional[jnp.ndarray] = None  # int32 [capacity], string/list
    elem_validity: Optional[jnp.ndarray] = None  # bool [cap, max_len], list

    # -- pytree protocol so columns/batches can cross jit boundaries --------
    def tree_flatten(self):
        leaves = [self.data, self.validity]
        if self.lengths is not None:
            leaves.append(self.lengths)
        if self.elem_validity is not None:
            leaves.append(self.elem_validity)
        return tuple(leaves), (self.dtype, self.lengths is not None,
                               self.elem_validity is not None)

    @classmethod
    def tree_unflatten(cls, aux, children):
        dtype, has_len, has_ev = aux
        it = iter(children)
        data, validity = next(it), next(it)
        lengths = next(it) if has_len else None
        ev = next(it) if has_ev else None
        return cls(dtype, data, validity, lengths, ev)

    @property
    def capacity(self) -> int:
        return int(self.data.shape[0])

    @property
    def max_len(self) -> int:
        assert self.dtype.has_lengths
        return int(self.data.shape[1])

    def nbytes(self) -> int:
        n = self.data.size * self.data.dtype.itemsize + self.validity.size
        if self.lengths is not None:
            n += self.lengths.size * 4
        if self.elem_validity is not None:
            n += self.elem_validity.size
        return int(n)

    def gather(self, indices: jnp.ndarray, valid: jnp.ndarray) -> "DeviceColumn":
        """Row gather; `valid` masks rows whose source index is meaningful."""
        data = jnp.take(self.data, indices, axis=0)
        validity = jnp.take(self.validity, indices, axis=0) & valid
        lengths = None
        ev = None
        if self.lengths is not None:
            lengths = jnp.where(valid, jnp.take(self.lengths, indices), 0)
            data = jnp.where(valid[:, None], data, 0)
        else:
            data = jnp.where(_bcast(valid, data), data, 0)
        if self.elem_validity is not None:
            ev = jnp.take(self.elem_validity, indices, axis=0) & \
                valid[:, None]
        return DeviceColumn(self.dtype, data, validity, lengths, ev)


def _bcast(mask: jnp.ndarray, like: jnp.ndarray) -> jnp.ndarray:
    if like.ndim == 2:
        return mask[:, None]
    return mask


@jax.tree_util.register_pytree_node_class
class DeviceBatch:
    """A batch of device columns with a host-side logical row count."""

    def __init__(self, names: Sequence[str], columns: Sequence[DeviceColumn],
                 num_rows):
        self.names: List[str] = list(names)
        self.columns: List[DeviceColumn] = list(columns)
        # num_rows may be a host int OR a traced jnp scalar (inside jit);
        # host-side code that needs a concrete count calls int(batch.num_rows)
        self.num_rows = int(num_rows) if isinstance(
            num_rows, (int, np.integer)) else num_rows
        if self.columns:
            caps = {c.capacity for c in self.columns}
            assert len(caps) == 1, f"ragged capacities {caps}"
            self._capacity = caps.pop()
        else:
            self._capacity = bucket_rows(int(num_rows))

    # num_rows travels as a leaf so jit does NOT specialize on it — only on
    # capacity/schema (the XLA static-shape bucketing contract)
    def tree_flatten(self):
        leaves = tuple(self.columns) + (
            jnp.asarray(self.num_rows, dtype=jnp.int32),)
        return leaves, (tuple(self.names), self._capacity)

    @classmethod
    def tree_unflatten(cls, aux, children):
        names, capacity = aux
        *cols, num_rows = children
        b = cls.__new__(cls)
        b.names = list(names)
        b.columns = list(cols)
        b.num_rows = num_rows
        b._capacity = capacity
        return b

    # ----------------------------------------------------------------------
    @property
    def capacity(self) -> int:
        return self._capacity

    @property
    def num_cols(self) -> int:
        return len(self.columns)

    @property
    def dtypes(self) -> List[dt.DType]:
        return [c.dtype for c in self.columns]

    def schema_key(self) -> Tuple:
        """Hashable (schema, shape-bucket) key — the XLA compile-cache key."""
        return (tuple(self.names),
                tuple(c.dtype.name for c in self.columns),
                self._capacity,
                tuple(c.max_len if c.dtype.has_lengths else 0
                      for c in self.columns))

    def column(self, name: str) -> DeviceColumn:
        return self.columns[self.names.index(name)]

    def row_mask(self) -> jnp.ndarray:
        return jnp.arange(self._capacity) < self.num_rows

    def nbytes(self) -> int:
        return sum(c.nbytes() for c in self.columns)

    def with_columns(self, names: Sequence[str],
                     columns: Sequence[DeviceColumn]) -> "DeviceBatch":
        return DeviceBatch(names, columns, self.num_rows)

    def select(self, names: Sequence[str]) -> "DeviceBatch":
        return DeviceBatch(names, [self.column(n) for n in names],
                           self.num_rows)

    def __repr__(self) -> str:
        cols = ", ".join(f"{n}:{c.dtype.name}" for n, c in
                         zip(self.names, self.columns))
        return (f"DeviceBatch(rows={int(self.num_rows)}/{self._capacity}, "
                f"[{cols}])")


# ---------------------------------------------------------------------------
# Host (Arrow) <-> device conversion.  Analog of HostColumnarToGpu /
# GpuColumnarToRowExec device<->host copies (reference:
# HostColumnarToGpu.scala:30-291, GpuColumnarToRowExec.scala:38-306).
# ---------------------------------------------------------------------------

def _np_column_from_arrow(arr: pa.ChunkedArray | pa.Array,
                          dtype: dt.DType, capacity: int
                          ) -> Tuple[np.ndarray, np.ndarray,
                                     Optional[np.ndarray],
                                     Optional[np.ndarray]]:
    if isinstance(arr, pa.ChunkedArray):
        arr = arr.combine_chunks()
    n = len(arr)
    validity = np.zeros(capacity, dtype=np.bool_)
    validity[:n] = ~np.asarray(arr.is_null())

    if dtype.is_list:
        # padded [capacity, max_len] element payload + lengths + element
        # validity (the device mirror of Arrow's offsets+values+nulls)
        py = arr.to_pylist()
        lens = [len(v) if v is not None else 0 for v in py]
        max_len = _bucket_strlen(max(lens, default=0))
        el_np = dtype.element.to_np()
        data = np.zeros((capacity, max_len), dtype=el_np)
        ev = np.zeros((capacity, max_len), dtype=np.bool_)
        lengths = np.zeros(capacity, dtype=np.int32)
        for i, v in enumerate(py):
            if v is None:
                continue
            lengths[i] = len(v)
            for j, x in enumerate(v):
                if x is None:
                    continue  # null element: ev stays False, data stays 0
                ev[i, j] = True
                data[i, j] = x
        return data, validity, lengths, ev

    if dtype.is_string:
        py = arr.to_pylist()
        blens = [len(s.encode("utf-8")) if s is not None else 0 for s in py]
        max_len = _bucket_strlen(max(blens, default=0))
        data = np.zeros((capacity, max_len), dtype=np.uint8)
        lengths = np.zeros(capacity, dtype=np.int32)
        for i, s in enumerate(py):
            if s is None:
                continue
            b = s.encode("utf-8")
            lengths[i] = len(b)
            if b:
                data[i, :len(b)] = np.frombuffer(b, dtype=np.uint8)
        return data, validity, lengths, None

    np_dtype = dtype.to_np()
    data = np.zeros(capacity, dtype=np_dtype)
    if pa.types.is_timestamp(arr.type):
        arr = arr.cast(pa.timestamp("us"))
        vals = arr.to_numpy(zero_copy_only=False)
        ints = vals.astype("datetime64[us]").astype(np.int64)
        ints = np.where(validity[:n], ints, 0)
        data[:n] = ints
    elif pa.types.is_date32(arr.type):
        vals = arr.to_numpy(zero_copy_only=False)
        ints = vals.astype("datetime64[D]").astype(np.int64).astype(np.int32)
        ints = np.where(validity[:n], ints, 0)
        data[:n] = ints
    else:
        vals = arr.fill_null(_zero_value(dtype)).to_numpy(zero_copy_only=False)
        data[:n] = vals.astype(np_dtype, copy=False)
    return data, validity, None, None


def _zero_value(dtype: dt.DType):
    if dtype.is_bool:
        return False
    if dtype.is_floating:
        return 0.0
    return 0


def from_arrow(table: pa.Table, min_bucket: int = 16,
               capacity: Optional[int] = None) -> DeviceBatch:
    """Upload an Arrow table into a padded DeviceBatch."""
    n = table.num_rows
    cap = capacity or bucket_rows(n, min_bucket)
    names, cols = [], []
    for field_, col in zip(table.schema, table.columns):
        dtype = dt.from_arrow(field_.type)
        if dtype is None:
            raise TypeError(f"unsupported Arrow type {field_.type} "
                            f"for column {field_.name}")
        if dtype == dt.NULL:
            dtype = dt.BOOL  # void columns materialize as all-null bool
        data, validity, lengths, ev = _np_column_from_arrow(col, dtype, cap)
        names.append(field_.name)
        cols.append(DeviceColumn(
            dtype,
            jnp.asarray(data),
            jnp.asarray(validity),
            jnp.asarray(lengths) if lengths is not None else None,
            jnp.asarray(ev) if ev is not None else None))
    return DeviceBatch(names, cols, n)


def to_arrow(batch: DeviceBatch) -> pa.Table:
    """Download a DeviceBatch back to an Arrow table (strips padding)."""
    n = int(batch.num_rows)
    arrays, fields = [], []
    for name, col in zip(batch.names, batch.columns):
        validity = np.asarray(col.validity[:n])
        mask = ~validity
        if col.dtype.is_string:
            data = np.asarray(col.data[:n])
            lengths = np.asarray(col.lengths[:n])
            py = []
            for i in range(n):
                if not validity[i]:
                    py.append(None)
                else:
                    py.append(bytes(data[i, :lengths[i]]).decode(
                        "utf-8", errors="replace"))
            arr = pa.array(py, type=pa.string())
        elif col.dtype.is_list:
            data = np.asarray(col.data[:n])
            lengths = np.asarray(col.lengths[:n])
            ev = np.asarray(col.elem_validity[:n]) \
                if col.elem_validity is not None else \
                np.ones(data.shape, dtype=bool)
            py = []
            for i in range(n):
                if not validity[i]:
                    py.append(None)
                else:
                    py.append([data[i, j].item() if ev[i, j] else None
                               for j in range(lengths[i])])
            arr = pa.array(py, type=col.dtype.to_arrow())
        elif col.dtype.id == dt.TypeId.TIMESTAMP_US:
            ints = np.asarray(col.data[:n]).astype("datetime64[us]")
            arr = pa.array(ints, type=pa.timestamp("us", tz="UTC"),
                           mask=mask)
        elif col.dtype.id == dt.TypeId.DATE32:
            days = np.asarray(col.data[:n]).astype("datetime64[D]")
            arr = pa.array(days, type=pa.date32(), mask=mask)
        else:
            arr = pa.array(np.asarray(col.data[:n]), mask=mask)
        arrays.append(arr)
        fields.append(pa.field(name, arr.type))
    return pa.Table.from_arrays(arrays, schema=pa.schema(fields))


def concat_batches(batches: Sequence[DeviceBatch],
                   min_bucket: int = 16) -> DeviceBatch:
    """Device-side concatenation (analog of Table.concatenate used by
    GpuCoalesceBatches, reference: GpuCoalesceBatches.scala:40-711)."""
    batches = [b for b in batches if int(b.num_rows) > 0] or list(batches[:1])
    if len(batches) == 1:
        return batches[0]
    # distributed readers (shuffle/ici.py) hand out batches committed to
    # their owning mesh device; concatenating across partitions must first
    # colocate them or XLA rejects the mixed-device concat
    devs = set()
    for b in batches:
        if b.columns:
            devs |= set(b.columns[0].data.devices())
    if len(devs) > 1:
        target = sorted(devs, key=lambda d: d.id)[0]
        batches = [jax.device_put(b, target) for b in batches]
    total = sum(int(b.num_rows) for b in batches)
    cap = bucket_rows(total, min_bucket)
    names = batches[0].names
    out_cols: List[DeviceColumn] = []
    for ci, name in enumerate(names):
        dtype = batches[0].columns[ci].dtype
        if dtype.has_lengths:
            max_len = max(b.columns[ci].max_len for b in batches)
            has_ev = any(b.columns[ci].elem_validity is not None
                         for b in batches)
            datas, vals, lens, evs = [], [], [], []
            for b in batches:
                c = b.columns[ci]
                nb = int(b.num_rows)
                d = c.data[:nb]
                if c.max_len < max_len:
                    d = jnp.pad(d, ((0, 0), (0, max_len - c.max_len)))
                datas.append(d)
                vals.append(c.validity[:nb])
                lens.append(c.lengths[:nb])
                if has_ev:
                    e = c.elem_validity if c.elem_validity is not None \
                        else jnp.ones((c.capacity, c.max_len),
                                      dtype=jnp.bool_)
                    e = e[:nb]
                    if c.max_len < max_len:
                        e = jnp.pad(e, ((0, 0), (0, max_len - c.max_len)))
                    evs.append(e)
            data = jnp.concatenate(datas, axis=0)
            data = jnp.pad(data, ((0, cap - total), (0, 0)))
            validity = jnp.pad(jnp.concatenate(vals), (0, cap - total))
            lengths = jnp.pad(jnp.concatenate(lens), (0, cap - total))
            ev = None
            if has_ev:
                ev = jnp.pad(jnp.concatenate(evs, axis=0),
                             ((0, cap - total), (0, 0)))
            out_cols.append(DeviceColumn(dtype, data, validity, lengths,
                                         ev))
        else:
            data = jnp.concatenate([b.columns[ci].data[:int(b.num_rows)]
                                    for b in batches])
            data = jnp.pad(data, (0, cap - total))
            validity = jnp.pad(
                jnp.concatenate([b.columns[ci].validity[:int(b.num_rows)]
                                 for b in batches]), (0, cap - total))
            out_cols.append(DeviceColumn(dtype, data, validity, None))
    return DeviceBatch(names, out_cols, total)
