from spark_rapids_tpu.columnar.batch import (  # noqa: F401
    DeviceColumn,
    DeviceBatch,
    bucket_rows,
    from_arrow,
    to_arrow,
    concat_batches,
)
