"""Column expression builder — the PySpark ``Column`` analog.

The reference sits under Spark SQL's DataFrame API; standalone, we provide
the same user surface so "a user of the reference can switch".
"""

from __future__ import annotations

from typing import Any, Optional, Sequence

from spark_rapids_tpu import dtypes as dt
from spark_rapids_tpu.expr import ir


def _to_expr(v: Any) -> ir.Expression:
    if isinstance(v, Column):
        return v.expr
    if isinstance(v, ir.Expression):
        return v
    return ir.Literal(v)


class Column:
    def __init__(self, expr: ir.Expression):
        self.expr = expr

    # naming ---------------------------------------------------------------
    def getItem(self, key) -> "Column":
        """col[key]: array ordinal (0-based) or map key lookup."""
        return Column(ir.GetItem(self.expr, _to_expr(key)))

    get_item = getItem
    __getitem__ = getItem

    def alias(self, name: str) -> "Column":
        return Column(ir.Alias(self.expr, name))

    name = alias

    # arithmetic -----------------------------------------------------------
    def __add__(self, o):
        return Column(ir.Add(self.expr, _to_expr(o)))

    def __radd__(self, o):
        return Column(ir.Add(_to_expr(o), self.expr))

    def __sub__(self, o):
        return Column(ir.Subtract(self.expr, _to_expr(o)))

    def __rsub__(self, o):
        return Column(ir.Subtract(_to_expr(o), self.expr))

    def __mul__(self, o):
        return Column(ir.Multiply(self.expr, _to_expr(o)))

    def __rmul__(self, o):
        return Column(ir.Multiply(_to_expr(o), self.expr))

    def __truediv__(self, o):
        return Column(ir.Divide(self.expr, _to_expr(o)))

    def __rtruediv__(self, o):
        return Column(ir.Divide(_to_expr(o), self.expr))

    def __mod__(self, o):
        return Column(ir.Remainder(self.expr, _to_expr(o)))

    def __neg__(self):
        return Column(ir.UnaryMinus(self.expr))

    # comparisons ----------------------------------------------------------
    def __eq__(self, o):  # type: ignore[override]
        return Column(ir.EqualTo(self.expr, _to_expr(o)))

    def __ne__(self, o):  # type: ignore[override]
        return Column(ir.Not(ir.EqualTo(self.expr, _to_expr(o))))

    def __lt__(self, o):
        return Column(ir.LessThan(self.expr, _to_expr(o)))

    def __le__(self, o):
        return Column(ir.LessThanOrEqual(self.expr, _to_expr(o)))

    def __gt__(self, o):
        return Column(ir.GreaterThan(self.expr, _to_expr(o)))

    def __ge__(self, o):
        return Column(ir.GreaterThanOrEqual(self.expr, _to_expr(o)))

    # logic ----------------------------------------------------------------
    def __and__(self, o):
        return Column(ir.And(self.expr, _to_expr(o)))

    def __or__(self, o):
        return Column(ir.Or(self.expr, _to_expr(o)))

    def __invert__(self):
        return Column(ir.Not(self.expr))

    # null / membership ----------------------------------------------------
    def is_null(self):
        return Column(ir.IsNull(self.expr))

    isNull = is_null

    def is_not_null(self):
        return Column(ir.IsNotNull(self.expr))

    isNotNull = is_not_null

    def isin(self, *items):
        if len(items) == 1 and isinstance(items[0], (list, tuple, set)):
            items = tuple(items[0])
        return Column(ir.In(self.expr, items))

    # strings --------------------------------------------------------------
    def startswith(self, o):
        return Column(ir.StartsWith(self.expr, _to_expr(o)))

    def endswith(self, o):
        return Column(ir.EndsWith(self.expr, _to_expr(o)))

    def contains(self, o):
        return Column(ir.Contains(self.expr, _to_expr(o)))

    def like(self, pattern: str):
        return Column(ir.Like(self.expr, ir.Literal(pattern)))

    def rlike(self, pattern: str):
        return Column(ir.RLike(self.expr, ir.Literal(pattern)))

    def substr(self, start, length):
        return Column(ir.Substring(self.expr, _to_expr(start),
                                   _to_expr(length)))

    # cast -----------------------------------------------------------------
    def cast(self, to) -> "Column":
        if isinstance(to, str):
            to = _TYPE_NAMES[to]
        return Column(ir.Cast(self.expr, to))

    astype = cast

    # windowing ------------------------------------------------------------
    def over(self, spec) -> "Column":
        return Column(ir.WindowExpression(
            self.expr, spec._partition_by, spec._order_by, spec._frame))

    # sort orders ----------------------------------------------------------
    def asc(self):
        from spark_rapids_tpu.plan.logical import SortOrder
        return SortOrder(self.expr, True, None)

    def desc(self):
        from spark_rapids_tpu.plan.logical import SortOrder
        return SortOrder(self.expr, False, None)

    def asc_nulls_last(self):
        from spark_rapids_tpu.plan.logical import SortOrder
        return SortOrder(self.expr, True, False)

    def desc_nulls_first(self):
        from spark_rapids_tpu.plan.logical import SortOrder
        return SortOrder(self.expr, False, True)

    def __repr__(self):
        return f"Column<{self.expr.sql()}>"

    def __hash__(self):
        return id(self)


_TYPE_NAMES = {
    "boolean": dt.BOOL, "bool": dt.BOOL,
    "tinyint": dt.INT8, "byte": dt.INT8,
    "smallint": dt.INT16, "short": dt.INT16,
    "int": dt.INT32, "integer": dt.INT32,
    "bigint": dt.INT64, "long": dt.INT64,
    "float": dt.FLOAT32, "double": dt.FLOAT64,
    "string": dt.STRING, "date": dt.DATE32, "timestamp": dt.TIMESTAMP_US,
}


def col(name: str) -> Column:
    return Column(ir.UnresolvedAttribute(name))


def lit(value: Any) -> Column:
    return Column(ir.Literal(value))
