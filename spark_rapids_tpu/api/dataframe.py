"""DataFrame API: the user surface over logical plans.

Mirrors the PySpark DataFrame surface the reference accelerates, so a
spark-rapids user can switch: select/filter/groupBy/agg/join/sort/limit/
union/collect/explain, plus ``collect_device`` — the zero-copy
``ColumnarRdd``-style handoff to ML frameworks (reference:
ColumnarRdd.scala:49, north-star config #5).
"""

from __future__ import annotations

from typing import Any, List, Optional, Sequence, Union

import pyarrow as pa

from spark_rapids_tpu.api.column import Column, _to_expr, col
from spark_rapids_tpu import dtypes as dt
from spark_rapids_tpu.expr import ir
from spark_rapids_tpu.plan import logical as lp
from spark_rapids_tpu.plan.logical import SortOrder


def _as_expr(c: Union[str, Column, ir.Expression]) -> ir.Expression:
    if isinstance(c, str):
        return ir.UnresolvedAttribute(c)
    return _to_expr(c)


class DataFrame:
    def __init__(self, plan: lp.LogicalPlan, session: "TpuSparkSession"):
        self.plan = plan
        self.session = session

    # -- transformations ---------------------------------------------------
    def select(self, *cols) -> "DataFrame":
        exprs = [_as_expr(c) for c in cols]

        # generators (explode/posexplode) compute in a Generate node below
        # the projection (the Spark analyzer's ExtractGenerator role)
        gens = [e for e in exprs
                if isinstance(e, ir.Generator) or
                (isinstance(e, ir.Alias) and
                 isinstance(e.children[0], ir.Generator))]
        if len(gens) > 1:
            raise ValueError("only one generator (explode/posexplode) is "
                             "allowed per select")
        if gens and any(ir.collect(e, lambda n: isinstance(
                n, ir.WindowExpression)) for e in exprs):
            raise ValueError("a generator and a window expression cannot "
                             "share one select; explode first, then window "
                             "over the result")
        if gens:
            g = gens[0]
            alias = None
            if isinstance(g, ir.Alias):
                alias, g = g.alias, g.children[0]
            if isinstance(g, ir.PosExplode):
                out_names = ["pos", alias or "col"]
            else:
                out_names = [alias or "col"]
            child = lp.Generate(self.plan, g, out_names)
            plain = []
            for e in exprs:
                inner = e.children[0] if isinstance(e, ir.Alias) else e
                if inner is g:
                    for n in out_names:
                        plain.append(ir.UnresolvedAttribute(n))
                else:
                    plain.append(e)
            return DataFrame(lp.Project(child, plain), self.session)

        # window expressions compute in a Window node below the projection
        wins: List[ir.WindowExpression] = []

        def repl(node):
            if isinstance(node, ir.WindowExpression):
                name = f"__w{len(wins)}"
                wins.append(node)
                return ir.UnresolvedAttribute(name)
            return None

        plain = [ir.transform(e, repl) for e in exprs]
        child = self.plan
        if wins:
            child = lp.Window(child, wins,
                              [f"__w{i}" for i in range(len(wins))])
            # preserve user-facing output names
            plain = [p if isinstance(p, ir.Alias) or
                     not isinstance(p, ir.UnresolvedAttribute) or
                     not p.attr_name.startswith("__w")
                     else ir.Alias(p, ir.output_name(orig))
                     for p, orig in zip(plain, exprs)]
        return DataFrame(lp.Project(child, plain), self.session)

    def with_column(self, name: str, c: Column) -> "DataFrame":
        cols: List = []
        replaced = False
        for n in self.plan.schema.names:
            if n == name:
                cols.append(Column(ir.Alias(_as_expr(c), name)))
                replaced = True
            else:
                cols.append(Column(ir.UnresolvedAttribute(n)))
        if not replaced:
            cols.append(Column(ir.Alias(_as_expr(c), name)))
        return self.select(*cols)

    withColumn = with_column

    def filter(self, condition: Union[Column, ir.Expression]) -> "DataFrame":
        return DataFrame(lp.Filter(self.plan, _as_expr(condition)),
                         self.session)

    where = filter

    def group_by(self, *cols) -> "GroupedData":
        return GroupedData(self, [_as_expr(c) for c in cols])

    groupBy = group_by

    def rollup(self, *cols) -> "GroupedData":
        """Hierarchical subtotals: grouping sets over every key prefix
        (reference: rollup lowered through GpuExpandExec,
        GpuExpandExec.scala:67)."""
        exprs = [_as_expr(c) for c in cols]
        k = len(exprs)
        sets = [tuple(range(i)) for i in range(k, -1, -1)]
        return _grouping_sets(self, exprs, sets)

    def cube(self, *cols) -> "GroupedData":
        """All grouping-set combinations of the keys (GpuExpandExec
        lowering, as rollup)."""
        import itertools
        exprs = [_as_expr(c) for c in cols]
        k = len(exprs)
        sets = [s for n in range(k, -1, -1)
                for s in itertools.combinations(range(k), n)]
        return _grouping_sets(self, exprs, sets)

    def agg(self, *aggs) -> "DataFrame":
        return GroupedData(self, []).agg(*aggs)

    def sort(self, *cols, ascending: Optional[Any] = None) -> "DataFrame":
        orders: List[SortOrder] = []
        for i, c in enumerate(cols):
            if isinstance(c, SortOrder):
                orders.append(c)
                continue
            asc = True
            if ascending is not None:
                asc = ascending[i] if isinstance(ascending, (list, tuple)) \
                    else bool(ascending)
            orders.append(SortOrder(_as_expr(c), asc, None))
        return DataFrame(lp.Sort(self.plan, orders), self.session)

    orderBy = sort
    order_by = sort

    def limit(self, n: int) -> "DataFrame":
        return DataFrame(lp.Limit(self.plan, n), self.session)

    def union(self, other: "DataFrame") -> "DataFrame":
        return DataFrame(lp.Union([self.plan, other.plan]), self.session)

    unionAll = union

    def join(self, other: "DataFrame", on=None, how: str = "inner"
             ) -> "DataFrame":
        how = {"left_outer": "left", "right_outer": "right",
               "outer": "full", "full_outer": "full",
               "leftsemi": "semi", "left_semi": "semi",
               "leftanti": "anti", "left_anti": "anti",
               "cross": "cross"}.get(how, how)
        condition: Optional[ir.Expression] = None
        if on is None:
            left_keys: List[str] = []
            right_keys: List[str] = []
        elif isinstance(on, str):
            left_keys, right_keys = [on], [on]
        elif isinstance(on, (list, tuple)) and all(
                isinstance(c, str) for c in on):
            left_keys = list(on)
            right_keys = list(on)
        elif isinstance(on, (Column, ir.Expression)) or (
                isinstance(on, (list, tuple)) and all(
                    isinstance(c, (Column, ir.Expression)) for c in on)):
            # Expression join condition: equi conjuncts become key pairs,
            # the rest a residual condition (shared analyzer policy —
            # lp.split_join_condition).
            exprs = [_as_expr(e) for e in
                     (on if isinstance(on, (list, tuple)) else [on])]
            whole = exprs[0]
            for e in exprs[1:]:
                whole = ir.And(whole, e)
            left_keys, right_keys, condition = lp.split_join_condition(
                whole, self.plan.schema.names, other.plan.schema.names)
        else:
            raise TypeError("join on must be a column name, list of names, "
                            "or a Column join condition")
        hint = None
        if getattr(other, "_broadcast_hint", False):
            hint = "broadcast_right"
        elif getattr(self, "_broadcast_hint", False):
            hint = "broadcast_left"
        return DataFrame(lp.Join(self.plan, other.plan, left_keys,
                                 right_keys, how, condition=condition,
                                 hint=hint), self.session)

    crossJoin = lambda self, other: self.join(other, how="cross")  # noqa

    def map_in_pandas(self, fn, schema) -> "DataFrame":
        """fn(pdf) -> pdf per batch, via an Arrow IPC worker process
        (GpuMapInPandasExec analog)."""
        return DataFrame(lp.MapInPandas(self.plan, fn, schema),
                         self.session)

    def window_in_pandas(self, partition_by, fn, args, out_name: str,
                         out_type="double") -> "DataFrame":
        """Unbounded-frame pandas window UDF: fn(*series) -> scalar per
        partition, broadcast to its rows (GpuWindowInPandasExec analog)."""
        from spark_rapids_tpu.api.column import _TYPE_NAMES
        out_dtype = _TYPE_NAMES[out_type] if isinstance(out_type, str) \
            else out_type
        keys = [partition_by] if isinstance(partition_by, str) \
            else list(partition_by)
        return DataFrame(
            lp.WindowInPandas(self.plan, keys, fn,
                              [_as_expr(a) for a in args], out_name,
                              out_dtype),
            self.session)

    def repartition(self, num_partitions: int, *cols) -> "DataFrame":
        """Hash exchange on cols, or round-robin without cols
        (GpuShuffleExchangeExec + GpuHashPartitioning/
        GpuRoundRobinPartitioning analog)."""
        if cols:
            return DataFrame(lp.Repartition(
                self.plan, "hash", num_partitions,
                exprs=[_as_expr(c) for c in cols]), self.session)
        return DataFrame(lp.Repartition(self.plan, "roundrobin",
                                        num_partitions), self.session)

    def repartition_by_range(self, num_partitions: int, *cols
                             ) -> "DataFrame":
        """Range exchange (GpuRangePartitioning analog)."""
        orders = [c if isinstance(c, SortOrder)
                  else SortOrder(_as_expr(c), True, None) for c in cols]
        return DataFrame(lp.Repartition(self.plan, "range", num_partitions,
                                        orders=orders), self.session)

    repartitionByRange = repartition_by_range

    def coalesce(self, num_partitions: int) -> "DataFrame":
        """Reduce the partition count by merging contiguous partitions —
        no shuffle, and never increases the count (GpuCoalesceExec analog,
        reference: basicPhysicalOperators.scala:346)."""
        return DataFrame(lp.CoalescePartitions(self.plan, num_partitions),
                         self.session)

    def cache(self) -> "DataFrame":
        """Materialize this plan's output once as parquet blobs and serve
        later executions from them (ParquetCachedBatchSerializer /
        InMemoryTableScan analog; materialization is lazy — it happens on
        the first action).  Only this DataFrame and ones derived from it
        afterwards see the cache."""
        if not isinstance(self.plan, lp.CachedRelation):
            self.plan = lp.CachedRelation(self.plan)
        return self

    persist = cache

    def unpersist(self) -> "DataFrame":
        if isinstance(self.plan, lp.CachedRelation):
            # free the blobs for every dependent (derived DataFrames
            # holding this CachedRelation re-materialize on next action)
            self.plan.blobs = None
            self.plan = self.plan.children[0]
        return self

    @property
    def is_cached(self) -> bool:
        return isinstance(self.plan, lp.CachedRelation)

    def create_or_replace_temp_view(self, name: str) -> None:
        self.session.register_view(name, self)

    createOrReplaceTempView = create_or_replace_temp_view

    def distinct(self) -> "DataFrame":
        names = self.plan.schema.names
        return DataFrame(
            lp.Aggregate(self.plan,
                         [ir.UnresolvedAttribute(n) for n in names], []),
            self.session)

    # -- properties --------------------------------------------------------
    @property
    def schema(self) -> lp.Schema:
        return self.plan.schema

    @property
    def columns(self) -> List[str]:
        return self.plan.schema.names

    @property
    def write(self):
        from spark_rapids_tpu.io.writers import DataFrameWriter
        return DataFrameWriter(self)

    # -- actions -----------------------------------------------------------
    def collect(self) -> pa.Table:
        """Execute and return an Arrow table (the terminal device->host
        transition, GpuBringBackToHost analog).  Runs through the
        concurrent query scheduler — literally
        ``collect_async().result()`` — so admission control and
        deadlines govern blocking collects too."""
        return self.session._execute(self.plan)

    def collect_async(self, priority: int = 0,
                      timeout_ms: Optional[int] = None,
                      estimate_bytes: Optional[int] = None):
        """Submit this query to the session's QueryService and return a
        QueryFuture immediately (sched/service.py): ``result(timeout)``
        blocks for the Arrow table, ``cancel()`` unwinds the query at
        its next cooperative checkpoint, ``done()``/``state`` inspect,
        and ``profile`` carries the QueryProfile once complete.  Higher
        ``priority`` admits first; ``timeout_ms`` overrides
        ``sched.defaultTimeoutMs``; ``estimate_bytes`` overrides the
        admission HBM estimate for this submission."""
        return self.session.submit(self.plan, priority=priority,
                                   timeout_ms=timeout_ms,
                                   estimate_bytes=estimate_bytes)

    collectAsync = collect_async

    def to_pandas(self):
        return self.collect().to_pandas()

    toPandas = to_pandas

    def collect_device(self):
        """Execute and return device-resident batches — the ColumnarRdd /
        ML-handoff path (reference: ColumnarRdd.scala:49,
        InternalColumnarRddConverter.scala:579): jax arrays stay in HBM for
        a downstream ML framework, no host round-trip."""
        return self.session._execute_device(self.plan)

    def count(self) -> int:
        from spark_rapids_tpu.api import functions as F
        t = self.agg(F.count("*").alias("count")).collect()
        return t.column("count")[0].as_py()

    def show(self, n: int = 20) -> None:
        print(self.limit(n).collect().to_pandas().to_string(index=False))

    def explain(self, mode: str = "physical") -> None:
        print(self.explain_string(mode))

    def explain_string(self, mode: str = "physical") -> str:
        if mode == "logical":
            return self.plan.tree_string()
        if mode == "profile":
            # metrics-annotated plan of the last executed action
            # (obs/profile.py; run collect() first)
            prof = self.session.last_query_profile()
            if prof is None:
                return ("no query profile recorded — run an action "
                        "(collect) first, with "
                        "spark.rapids.tpu.obs.profile.enabled=true")
            return prof.tree_string()
        result = self.session._plan_physical(self.plan)
        if mode == "tpu":
            return result.explain_string(all_=True)
        return result.plan.tree_string()

    def __repr__(self):
        inner = ", ".join(f"{f.name}: {f.dtype.name}"
                          for f in self.plan.schema.fields)
        return f"DataFrame[{inner}]"


def _grouping_sets(df: DataFrame, exprs: List[ir.Expression],
                   sets: List[tuple]) -> "GroupedData":
    """Lower rollup/cube to Expand + Aggregate (shared helper
    lp.expand_grouping_sets); agg() renames the internal key columns
    back and drops the gid."""
    expanded, refs, renames = lp.expand_grouping_sets(df.plan, exprs,
                                                      sets)
    gd = GroupedData(DataFrame(expanded, df.session), refs)
    gd._gset_renames = renames
    return gd


class GroupedData:
    def __init__(self, df: DataFrame, groupings: List[ir.Expression]):
        self.df = df
        self.groupings = groupings
        # rollup/cube: internal grouping-set key names -> public names;
        # agg() renames them and drops the __gid column
        self._gset_renames: Optional[dict] = None

    def agg(self, *aggs) -> DataFrame:
        res = self._agg_impl(*aggs)
        if self._gset_renames:
            # rollup/cube epilogue: public key names back, gid dropped
            final = [ir.Alias(ir.UnresolvedAttribute(n),
                              self._gset_renames.get(n, n))
                     for n in res.plan.schema.names if n != "__gid"]
            res = DataFrame(lp.Project(res.plan, final), res.session)
        return res

    def _agg_impl(self, *aggs) -> DataFrame:
        agg_exprs = [_as_expr(a) for a in aggs]

        # DISTINCT aggregates: shared double-aggregate rewrite (pre-alias
        # so output names survive the strip)
        plan2, groupings2, exprs2 = lp.rewrite_distinct_aggregates(
            self.df.plan, self.groupings,
            [e if isinstance(e, ir.Alias)
             else ir.Alias(e, ir.output_name(e)) for e in agg_exprs])
        if plan2 is not self.df.plan:
            return GroupedData(DataFrame(plan2, self.df.session),
                               groupings2)._agg_impl(*exprs2)

        if all(isinstance(e.children[0] if isinstance(e, ir.Alias) else e,
                          ir.AggregateExpression) for e in agg_exprs):
            return DataFrame(
                lp.Aggregate(self.df.plan, self.groupings, agg_exprs),
                self.df.session)
        # Compound post-aggregation expressions (sum(a)/sum(b), ...):
        # decompose into plain aggregates + a final projection, the same
        # split the reference's final-projection stage performs
        # (reference: aggregate.scala:326-421 "final projection").
        leaves: List[ir.Expression] = []

        def repl(node):
            if isinstance(node, ir.AggregateExpression):
                name = f"__agg{len(leaves)}"
                leaves.append(ir.Alias(node, name))
                return ir.UnresolvedAttribute(name)
            return None

        projected = []
        for e in agg_exprs:
            name = ir.output_name(e)
            inner = e.children[0] if isinstance(e, ir.Alias) else e
            projected.append(ir.Alias(ir.transform(inner, repl), name))
        agg_plan = lp.Aggregate(self.df.plan, self.groupings, leaves)
        final = [ir.UnresolvedAttribute(ir.output_name(g))
                 for g in self.groupings] + projected
        return DataFrame(lp.Project(agg_plan, final), self.df.session)

    def _simple(self, fn, cols) -> DataFrame:
        from spark_rapids_tpu.api import functions as F
        if not cols:
            cols = [f.name for f in self.df.plan.schema.fields
                    if f.dtype.is_numeric]
        builder = {"count": F.count, "sum": F.sum, "min": F.min,
                   "max": F.max, "avg": F.avg}[fn]
        if fn == "count":
            return self.agg(F.count("*").alias("count"))
        return self.agg(*[
            builder(c).alias(f"{fn}({c})") for c in cols])

    def count(self) -> DataFrame:
        return self._simple("count", [])

    def sum(self, *cols) -> DataFrame:
        return self._simple("sum", cols)

    def min(self, *cols) -> DataFrame:
        return self._simple("min", cols)

    def max(self, *cols) -> DataFrame:
        return self._simple("max", cols)

    def avg(self, *cols) -> DataFrame:
        return self._simple("avg", cols)

    mean = avg

    # -- pandas-UDF entry points (reference: SURVEY.md §2d python execs) ---
    def _key_names(self) -> List[str]:
        names = []
        for g in self.groupings:
            if isinstance(g, ir.UnresolvedAttribute):
                names.append(g.attr_name)
            elif isinstance(g, ir.BoundReference) and g.ref_name:
                names.append(g.ref_name)
            else:
                raise TypeError(
                    "pandas group operations require plain column "
                    "grouping keys")
        return names

    def apply_in_pandas(self, fn, schema) -> DataFrame:
        """fn(group_pdf) -> pdf per group
        (GpuFlatMapGroupsInPandasExec analog)."""
        return DataFrame(
            lp.FlatMapGroupsInPandas(self.df.plan, self._key_names(), fn,
                                     schema),
            self.df.session)

    def agg_in_pandas(self, fn, args, out_name: str,
                      out_type="double") -> DataFrame:
        """fn(*series) -> scalar per group
        (GpuAggregateInPandasExec analog)."""
        from spark_rapids_tpu.api.column import _TYPE_NAMES
        out_dtype = _TYPE_NAMES[out_type] if isinstance(out_type, str) \
            else out_type
        return DataFrame(
            lp.AggregateInPandas(self.df.plan, self._key_names(), fn,
                                 [_as_expr(a) for a in args], out_name,
                                 out_dtype),
            self.df.session)

    def cogroup(self, other: "GroupedData") -> "CoGroupedData":
        """PySpark df.groupBy(k).cogroup(df2.groupBy(k)) analog."""
        return CoGroupedData(self, other)


class CoGroupedData:
    def __init__(self, left: GroupedData, right: GroupedData):
        self.left = left
        self.right = right

    def apply_in_pandas(self, fn, schema) -> DataFrame:
        """fn(left_pdf, right_pdf) -> pdf per co-grouped key
        (GpuFlatMapCoGroupsInPandasExec analog)."""
        return DataFrame(
            lp.CoGroupedMapInPandas(
                self.left.df.plan, self.right.df.plan,
                self.left._key_names(), self.right._key_names(), fn,
                schema),
            self.left.df.session)
