"""``pyspark.sql.functions``-style builder API over the expression IR."""

from __future__ import annotations

from typing import Any, Optional, Union

from spark_rapids_tpu import dtypes as dt
from spark_rapids_tpu.api.column import Column, _to_expr, col, lit  # noqa
from spark_rapids_tpu.expr import ir


def _c(v) -> ir.Expression:
    if isinstance(v, str):
        return ir.UnresolvedAttribute(v)
    return _to_expr(v)


# -- conditionals -----------------------------------------------------------

def when(cond, value) -> "CaseWhenBuilder":
    return CaseWhenBuilder([(cond, value)])


class CaseWhenBuilder(Column):
    def __init__(self, branches):
        self.branches = branches
        super().__init__(self._build(None))

    def _build(self, else_value):
        return ir.CaseWhen(
            [(_to_expr(c), _to_expr(v)) for c, v in self.branches],
            _to_expr(else_value) if else_value is not None else None)

    def when(self, cond, value) -> "CaseWhenBuilder":
        return CaseWhenBuilder(self.branches + [(cond, value)])

    def otherwise(self, value) -> Column:
        return Column(self._build(value))


def if_(cond, t, f) -> Column:
    return Column(ir.If(_to_expr(cond), _to_expr(t), _to_expr(f)))


def coalesce(*cols) -> Column:
    return Column(ir.Coalesce(*[_c(c) for c in cols]))


def isnull(c) -> Column:
    return Column(ir.IsNull(_c(c)))


def isnan(c) -> Column:
    return Column(ir.IsNan(_c(c)))


def nanvl(a, b) -> Column:
    return Column(ir.NaNvl(_c(a), _c(b)))


# -- math -------------------------------------------------------------------

def _u(cls):
    def f(c) -> Column:
        return Column(cls(_c(c)))
    return f


abs = _u(ir.Abs)  # noqa: A001
sqrt = _u(ir.Sqrt)
exp = _u(ir.Exp)
log = _u(ir.Log)
log2 = _u(ir.Log2)
log10 = _u(ir.Log10)
log1p = _u(ir.Log1p)
expm1 = _u(ir.Expm1)
sin = _u(ir.Sin)
cos = _u(ir.Cos)
tan = _u(ir.Tan)
sinh = _u(ir.Sinh)
cosh = _u(ir.Cosh)
tanh = _u(ir.Tanh)
asin = _u(ir.Asin)
acos = _u(ir.Acos)
atan = _u(ir.Atan)
cbrt = _u(ir.Cbrt)
degrees = _u(ir.ToDegrees)
radians = _u(ir.ToRadians)
rint = _u(ir.Rint)
signum = _u(ir.Signum)
ceil = _u(ir.Ceil)
floor = _u(ir.Floor)


def pow(a, b) -> Column:  # noqa: A001
    return Column(ir.Pow(_c(a), _c(b)))


def atan2(a, b) -> Column:
    return Column(ir.Atan2(_c(a), _c(b)))


def shiftleft(c, n) -> Column:
    return Column(ir.ShiftLeft(_c(c), _to_expr(n)))


def shiftright(c, n) -> Column:
    return Column(ir.ShiftRight(_c(c), _to_expr(n)))


def shiftrightunsigned(c, n) -> Column:
    return Column(ir.ShiftRightUnsigned(_c(c), _to_expr(n)))


def pmod(a, b) -> Column:
    return Column(ir.Pmod(_c(a), _c(b)))


def rand(seed: Optional[int] = None) -> Column:
    return Column(ir.Rand(seed))


# -- strings ----------------------------------------------------------------

upper = _u(ir.Upper)
lower = _u(ir.Lower)
length = _u(ir.Length)
trim = _u(ir.StringTrim)
ltrim = _u(ir.StringTrimLeft)
rtrim = _u(ir.StringTrimRight)
initcap = _u(ir.InitCap)
reverse = _u(ir.StringReverse)


def substring(c, pos, length_) -> Column:
    return Column(ir.Substring(_c(c), _to_expr(pos), _to_expr(length_)))


def concat(*cols) -> Column:
    return Column(ir.Concat(*[_c(c) for c in cols]))


def locate(substr: str, c, pos: int = 1) -> Column:
    return Column(ir.StringLocate(ir.Literal(substr), _c(c),
                                  ir.Literal(pos)))


def lpad(c, length_: int, pad: str) -> Column:
    return Column(ir.LPad(_c(c), ir.Literal(length_), ir.Literal(pad)))


def rpad(c, length_: int, pad: str) -> Column:
    return Column(ir.RPad(_c(c), ir.Literal(length_), ir.Literal(pad)))


def replace(c, search: str, replacement: str) -> Column:
    return Column(ir.StringReplace(_c(c), ir.Literal(search),
                                   ir.Literal(replacement)))


def substring_index(c, delim: str, count: int) -> Column:
    return Column(ir.SubstringIndex(_c(c), ir.Literal(delim),
                                    ir.Literal(count)))


def split(c, pattern: str, limit: int = -1) -> Column:
    return Column(ir.StringSplit(_c(c), ir.Literal(pattern),
                                 ir.Literal(limit)))


def regexp_replace(c, pattern: str, replacement) -> Column:
    return Column(ir.RegExpReplace(_c(c), ir.Literal(pattern),
                                   _c(replacement) if isinstance(
                                       replacement, Column)
                                   else ir.Literal(replacement)))


def md5(c) -> Column:
    return Column(ir.Md5(_c(c)))


def atleast_n_nonnulls(n: int, *cols) -> Column:
    return Column(ir.AtLeastNNonNulls(n, [_c(c) for c in cols]))


def from_unixtime(c) -> Column:
    return Column(ir.FromUnixTime(_c(c)))


def input_file_name() -> Column:
    return Column(ir.InputFileName())


# -- temporal ---------------------------------------------------------------

year = _u(ir.Year)
month = _u(ir.Month)
dayofmonth = _u(ir.DayOfMonth)
dayofyear = _u(ir.DayOfYear)
dayofweek = _u(ir.DayOfWeek)
weekofyear = _u(ir.WeekOfYear)
quarter = _u(ir.Quarter)
hour = _u(ir.Hour)
minute = _u(ir.Minute)
second = _u(ir.Second)


def date_add(c, days) -> Column:
    return Column(ir.DateAdd(_c(c), _to_expr(days)))


def date_sub(c, days) -> Column:
    return Column(ir.DateSub(_c(c), _to_expr(days)))


def datediff(end, start) -> Column:
    return Column(ir.DateDiff(_c(end), _c(start)))


def unix_timestamp(c) -> Column:
    return Column(ir.UnixTimestampFromTs(_c(c)))


# -- hash / ids -------------------------------------------------------------

def hash(*cols) -> Column:  # noqa: A001
    return Column(ir.Murmur3Hash([_c(c) for c in cols]))


def spark_partition_id() -> Column:
    return Column(ir.SparkPartitionID())


def monotonically_increasing_id() -> Column:
    return Column(ir.MonotonicallyIncreasingID())


# -- aggregates -------------------------------------------------------------

def count(c="*") -> Column:
    if isinstance(c, str) and c == "*":
        return Column(ir.Count(None))
    return Column(ir.Count(_c(c)))


def sum(c) -> Column:  # noqa: A001
    return Column(ir.Sum(_c(c)))


def min(c) -> Column:  # noqa: A001
    return Column(ir.Min(_c(c)))


def max(c) -> Column:  # noqa: A001
    return Column(ir.Max(_c(c)))


def avg(c) -> Column:
    return Column(ir.Average(_c(c)))


mean = avg


def count_distinct(c) -> Column:
    return Column(ir.Count(_c(c), distinct=True))


countDistinct = count_distinct


def sum_distinct(c) -> Column:
    return Column(ir.Sum(_c(c), distinct=True))


sumDistinct = sum_distinct


def avg_distinct(c) -> Column:
    return Column(ir.Average(_c(c), distinct=True))


# -- UDFs -------------------------------------------------------------------

def udf(f=None, returnType="string"):
    """Create a user-defined function.

    The function's bytecode is translated to the expression IR when possible
    (so it runs on TPU like any built-in expression); otherwise it becomes a
    row-wise ``PythonUDF`` that executes on CPU — mirroring the reference's
    udf-compiler with CPU-UDF fallback (udf-compiler/.../Plugin.scala:36-94).
    The result is cast to ``returnType`` in both paths, like PySpark.

    Supports all PySpark call forms: ``udf(f)``, ``udf(f, "long")``,
    ``@udf``, ``@udf("long")``, ``@udf(returnType="long")``.
    """
    from spark_rapids_tpu.api.column import _TYPE_NAMES
    if isinstance(f, (str, dt.DType)):  # @udf("long") decorator form
        return lambda fn: udf(fn, f)
    if f is None:
        return lambda fn: udf(fn, returnType)
    rt = _TYPE_NAMES[returnType] if isinstance(returnType, str) \
        else returnType

    def wrapper(*cols) -> Column:
        # compilation is attempted at bind time, when argument dtypes are
        # known (ir._try_compile_python_udf); until then this is a row-wise
        # Python UDF node
        return Column(ir.PythonUDF(f, [_c(c) for c in cols], rt,
                                   try_compile=True))
    wrapper.__name__ = getattr(f, "__name__", "udf")
    return wrapper


def pandas_udf(f=None, returnType="double"):
    """Create a vectorized (series -> series) pandas UDF.

    Evaluated in a Python worker process over Arrow IPC — the
    GpuArrowEvalPythonExec path (reference:
    GpuArrowEvalPythonExec.scala:422-435, python/rapids/worker.py).
    Supports ``pandas_udf(f)``, ``pandas_udf(f, "long")``, ``@pandas_udf``,
    ``@pandas_udf("long")`` call forms like PySpark.
    """
    from spark_rapids_tpu.api.column import _TYPE_NAMES
    if isinstance(f, (str, dt.DType)):
        return lambda fn: pandas_udf(fn, f)
    if f is None:
        return lambda fn: pandas_udf(fn, returnType)
    rt = _TYPE_NAMES[returnType] if isinstance(returnType, str) \
        else returnType

    def wrapper(*cols) -> Column:
        return Column(ir.PythonUDF(f, [_c(c) for c in cols], rt,
                                   vectorized=True))
    wrapper.__name__ = getattr(f, "__name__", "pandas_udf")
    return wrapper


# -- window functions -------------------------------------------------------

def row_number() -> Column:
    return Column(ir.RowNumber())


def rank() -> Column:
    return Column(ir.Rank())


def dense_rank() -> Column:
    return Column(ir.DenseRank())


def lead(c, offset: int = 1, default=None) -> Column:
    return Column(ir.Lead(_c(c), offset, default))


def lag(c, offset: int = 1, default=None) -> Column:
    return Column(ir.Lag(_c(c), offset, default))


def first(c, ignorenulls: bool = False) -> Column:
    return Column(ir.First(_c(c), ignorenulls))


def last(c, ignorenulls: bool = False) -> Column:
    return Column(ir.Last(_c(c), ignorenulls))


def broadcast(df):
    """Broadcast hint: mark df as the build side of its next join
    (pyspark functions.broadcast analog; drives BroadcastHashJoinExec
    selection like Spark's ResolvedHint)."""
    out = df.__class__(df.plan, df.session)
    out._broadcast_hint = True
    return out


# -- complex types -----------------------------------------------------------

def explode(c) -> Column:
    return Column(ir.Explode(_c(c)))


def explode_outer(c) -> Column:
    return Column(ir.Explode(_c(c), outer=True))


def posexplode(c) -> Column:
    return Column(ir.PosExplode(_c(c)))


def posexplode_outer(c) -> Column:
    return Column(ir.PosExplode(_c(c), outer=True))


def size(c) -> Column:
    return Column(ir.Size(_c(c)))


def array(*cols) -> Column:
    return Column(ir.CreateArray(*[_c(c) for c in cols]))


def array_contains(c, value) -> Column:
    return Column(ir.ArrayContains(_c(c), _to_expr(value)))


def sort_array(c, asc: bool = True) -> Column:
    return Column(ir.SortArray(_c(c), asc))


def element_at(c, extraction) -> Column:
    """Arrays: 1-based index, negative counts from the end (Spark
    element_at); maps: key lookup."""
    return Column(ir.ElementAt(_c(c), _to_expr(extraction)))
