"""TpuSparkSession: the engine entry point.

Plays the combined role of SparkSession + the reference's plugin bootstrap
(reference: SQLPlugin.scala:28-31, Plugin.scala:111-212): holds the conf,
initializes the device and concurrency semaphore, plans queries, and applies
the TPU overrides.
"""

from __future__ import annotations

import collections
import contextlib
import itertools
import os
import threading
from typing import Any, Dict, List, Optional, Sequence, Union

import pyarrow as pa

from spark_rapids_tpu import config as cfg
from spark_rapids_tpu.config import RapidsTpuConf
from spark_rapids_tpu.api.dataframe import DataFrame
from spark_rapids_tpu.exec.base import PhysicalPlan
from spark_rapids_tpu.exec.cpu import concat_tables
from spark_rapids_tpu.mem import device as devmgr
from spark_rapids_tpu.plan import logical as lp
from spark_rapids_tpu.plan.overrides import (OverrideResult, TpuOverrides,
                                             assert_is_on_tpu)
from spark_rapids_tpu.plan.planner import plan_cpu


class TpuSparkSession:
    _active: Optional["TpuSparkSession"] = None
    _lock = threading.Lock()
    # shared across sessions — see the note at self._query_ids
    _QUERY_IDS = itertools.count(1)

    def __init__(self, conf: Optional[Dict[str, Any]] = None):
        self.conf = RapidsTpuConf(conf)
        devmgr.initialize(self.conf.get(cfg.CONCURRENT_TPU_TASKS))
        # -- fleet shared cache plane (fleet/store.py): attach BEFORE
        # the compile cache and compile observatory configure, so the
        # shared compile-cache directory and corpus directory take
        # effect for this whole session.  fleet.enabled=false (default)
        # leaves every downstream path byte-for-byte unchanged.
        self._fleet_store = None
        if self.conf.get(cfg.FLEET_ENABLED):
            from spark_rapids_tpu.fleet.store import store_from_url
            self._fleet_store = store_from_url(
                str(self.conf.get(cfg.FLEET_STORE_URL) or ""))
            from spark_rapids_tpu.serve import result_cache as _rc
            _rc.configure_store(
                self._fleet_store,
                int(self.conf.get(cfg.FLEET_STORE_MAX_ENTRY_BYTES)))
            corpus_dir = self._fleet_store.corpus_dir()
            if corpus_dir and not str(self.conf.get(
                    cfg.OBS_COMPILE_CORPUS_PATH) or ""):
                # each replica appends its OWN corpus file under the
                # shared corpus/ dir; a joining replica replays the
                # whole directory (sched/precompile.py)
                self.conf.set(
                    cfg.OBS_COMPILE_CORPUS_PATH.key,
                    os.path.join(corpus_dir,
                                 f"corpus-{os.getpid()}.jsonl"))
        import spark_rapids_tpu as _pkg
        _pkg._enable_compile_cache(  # accelerator backends only
            self._fleet_store.compile_cache_dir()
            if self._fleet_store is not None else None)
        from spark_rapids_tpu.mem import spill
        if self.conf.get(cfg.MEM_SPILL_ENABLED):
            spill.init_catalog(
                self.conf.get(cfg.MEM_DEVICE_LIMIT),
                self.conf.get(cfg.MEM_HOST_SPILL_LIMIT),
                self.conf.get(cfg.MEM_SPILL_DIR) or None)
        else:
            spill.disable_catalog()
        from spark_rapids_tpu.io import scan_cache
        scan_cache.configure(
            self.conf.get(cfg.SCAN_METADATA_CACHE_ENABLED),
            self.conf.get(cfg.SCAN_METADATA_CACHE_MAX_BYTES))
        from spark_rapids_tpu.kernels import backend as kernel_backend
        kernel_backend.configure(self.conf)
        from spark_rapids_tpu.exec import kernel_abi
        kernel_abi.configure(self.conf)
        from spark_rapids_tpu.pyworker import pool as pyworker_pool
        pyworker_pool.configure(self.conf)
        from spark_rapids_tpu.shuffle import faults
        faults.install_plan_from_conf(self.conf, fresh=True)
        from spark_rapids_tpu.obs import trace as obs_trace
        obs_trace.configure(
            bool(self.conf.get(cfg.OBS_TRACE_ENABLED)),
            int(self.conf.get(cfg.OBS_TRACE_BUFFER_SPANS)))
        from spark_rapids_tpu.obs import compile as obs_compile
        obs_compile.configure(
            bool(self.conf.get(cfg.OBS_COMPILE_ENABLED)),
            ring_events=int(self.conf.get(cfg.OBS_COMPILE_RING_EVENTS)),
            storm_threshold=int(self.conf.get(
                cfg.OBS_COMPILE_STORM_THRESHOLD)),
            corpus_path=str(self.conf.get(
                cfg.OBS_COMPILE_CORPUS_PATH) or ""),
            corpus_replay=bool(self.conf.get(
                cfg.OBS_COMPILE_CORPUS_REPLAY)))
        from spark_rapids_tpu.obs import accounting as obs_accounting
        obs_accounting.configure(
            bool(self.conf.get(cfg.OBS_ACCOUNTING_ENABLED)))
        with TpuSparkSession._lock:
            TpuSparkSession._active = self
        self._plan_listeners: List = []
        self._query_listeners: List = []
        self._views: Dict[str, lp.LogicalPlan] = {}
        # PROCESS-global query ids (class attribute): the compile
        # observatory, profile ring and /queries table key on qid, and
        # per-session counters made two sessions' query 1 collide in
        # the observatory's per-query attribution — session 2's corpus
        # record inherited session 1's programs (found by
        # tests/test_precompile.py's corpusReplay-knob test)
        self._query_ids = TpuSparkSession._QUERY_IDS
        # per-query profiles: bounded ring keyed by query id, plus the
        # most recently COMPLETED one — concurrent collects no longer
        # race a single last-profile slot
        self._profile_lock = threading.Lock()
        self._profiles: "collections.OrderedDict[int, Any]" = \
            collections.OrderedDict()
        self._profile_ring = max(1, int(self.conf.get(
            cfg.SCHED_PROFILE_RING)))
        self._last_profile = None
        from spark_rapids_tpu.sched.service import QueryService
        self._query_service = QueryService(self)
        # -- always-on operational layer (obs/server.py, obs/recorder.py):
        # both fully off by default — no socket, no recorder ring, the
        # event hooks cost one bool check
        from spark_rapids_tpu.obs import recorder as obs_recorder
        self._recorder = None
        rec_dir = str(self.conf.get(cfg.OBS_RECORDER_DIR) or "")
        if rec_dir:
            # configuring REPLACES any previous session's recorder
            # (whose listener then stands down via _stale()); a session
            # with no recorder dir leaves an existing recorder alone —
            # helper sessions (bench oracles, tests) must not disarm a
            # live sibling's flight recorder
            self._recorder = obs_recorder.configure(
                rec_dir,
                max_events=int(self.conf.get(
                    cfg.OBS_RECORDER_MAX_EVENTS)),
                config_snapshot=dict(self.conf._settings))
            self._query_listeners.append(self._recorder)
        if not self.conf.get(cfg.OBS_PROFILE_ENABLED) and (
                rec_dir or int(self.conf.get(cfg.OBS_SLOW_QUERY_MS))):
            # both features ride the QueryProfile assembly path; with
            # profiling off they would be silently inert
            import logging
            logging.getLogger("spark_rapids_tpu.obs").warning(
                "obs.recorder.dir / obs.slowQueryMs are configured but "
                "obs.profile.enabled=false: flight-recorder bundles "
                "and the slow-query log require per-query profiles "
                "and will not fire")
        self._obs_server = None
        if self.conf.get(cfg.OBS_HTTP_ENABLED):
            from spark_rapids_tpu.obs.server import ObsHttpServer
            self._obs_server = ObsHttpServer(
                self, host=str(self.conf.get(cfg.OBS_HTTP_HOST)),
                port=int(self.conf.get(cfg.OBS_HTTP_PORT)))
        # -- multi-tenant serving front-end (serve/server.py): off by
        # default — no socket, no threads, no result-cache mutation
        self._serve_server = None
        if self.conf.get(cfg.SERVE_ENABLED):
            from spark_rapids_tpu.serve.server import ServeServer
            self._serve_server = ServeServer(self)
        # -- AOT precompile service (sched/precompile.py): off by
        # default — replays a previous process's compile corpus through
        # lower+compile at low priority so a replica restart warms the
        # persistent XLA cache off the serving path
        # -- drift sentinel (obs/sentinel.py): off by default — no
        # thread runs; on, it samples the registry on an interval and
        # emits one "slo" bundle per sustained-breach episode
        self._sentinel = None
        if self.conf.get(cfg.OBS_SENTINEL_ENABLED):
            from spark_rapids_tpu.obs.sentinel import DriftSentinel
            self._sentinel = DriftSentinel(
                interval_ms=int(self.conf.get(
                    cfg.OBS_SENTINEL_INTERVAL_MS)),
                rules=str(self.conf.get(cfg.OBS_SENTINEL_RULES) or ""),
                jsonl_path=str(self.conf.get(
                    cfg.OBS_SENTINEL_PATH) or ""),
                jsonl_max_bytes=int(self.conf.get(
                    cfg.OBS_SLOW_QUERY_MAX_BYTES)))
            self._sentinel.start()
        self._precompile_service = None
        if self.conf.get(cfg.SCHED_PRECOMPILE_ENABLED):
            from spark_rapids_tpu.sched.precompile import \
                PrecompileService
            corpus = (str(self.conf.get(
                cfg.SCHED_PRECOMPILE_CORPUS_PATH) or "") or
                str(self.conf.get(cfg.OBS_COMPILE_CORPUS_PATH) or ""))
            if self._fleet_store is not None:
                # warm-join: replay the WHOLE shared corpus directory
                # (every replica's appends), not just this replica's
                # own emission file
                shared = self._fleet_store.corpus_dir()
                if shared and not str(self.conf.get(
                        cfg.SCHED_PRECOMPILE_CORPUS_PATH) or ""):
                    corpus = shared
            self._precompile_service = PrecompileService(
                self, corpus,
                idle_wait_ms=int(self.conf.get(
                    cfg.SCHED_PRECOMPILE_IDLE_WAIT_MS)))
            self._precompile_service.start()

    # -- builder-compatible construction -----------------------------------
    class Builder:
        def __init__(self):
            self._conf: Dict[str, Any] = {}

        def config(self, key: str, value: Any) -> "TpuSparkSession.Builder":
            self._conf[key] = value
            return self

        def getOrCreate(self) -> "TpuSparkSession":
            return TpuSparkSession(self._conf)

        get_or_create = getOrCreate

    builder = Builder()

    @classmethod
    def active(cls) -> "TpuSparkSession":
        if cls._active is None:
            cls._active = TpuSparkSession()
        return cls._active

    # -- conf --------------------------------------------------------------
    def set_conf(self, key: str, value: Any) -> None:
        self.conf.set(key, value)

    def get_conf(self, key: str, default: Any = None) -> Any:
        return self.conf.get_raw(key, default)

    # -- data sources ------------------------------------------------------
    def create_dataframe(self, data, schema: Optional[Sequence[str]] = None,
                         num_partitions: int = 1) -> DataFrame:
        if isinstance(data, pa.Table):
            table = data
        elif hasattr(data, "to_dict") and hasattr(data, "columns"):
            table = pa.Table.from_pandas(data)  # pandas DataFrame
        elif isinstance(data, dict):
            table = pa.table(data)
        elif isinstance(data, list):
            if schema is None:
                raise ValueError("schema (column names) required for lists")
            cols = list(zip(*data)) if data else [[] for _ in schema]
            table = pa.table({n: list(c) for n, c in zip(schema, cols)})
        else:
            raise TypeError(f"cannot create DataFrame from {type(data)}")
        return DataFrame(lp.InMemoryScan(table, num_partitions), self)

    createDataFrame = create_dataframe

    def range(self, start: int, end: Optional[int] = None, step: int = 1,
              num_partitions: int = 1) -> DataFrame:
        if end is None:
            start, end = 0, start
        return DataFrame(lp.Range(start, end, step, num_partitions), self)

    @property
    def read(self) -> "DataFrameReader":
        return DataFrameReader(self)

    # -- SQL ---------------------------------------------------------------
    def sql(self, query: str) -> DataFrame:
        """Parse and plan a SQL query against registered temp views
        (the ``spark.sql(...)`` surface; in the reference Spark's own
        parser runs and the plugin only sees physical plans)."""
        from spark_rapids_tpu.sql import parse_sql
        return DataFrame(parse_sql(query, self._views), self)

    def register_view(self, name: str, df: DataFrame) -> None:
        self._views[name.lower()] = df.plan

    def drop_view(self, name: str) -> None:
        self._views.pop(name.lower(), None)

    @property
    def catalog(self) -> Dict[str, lp.LogicalPlan]:
        return dict(self._views)

    # -- planning & execution ----------------------------------------------
    def _plan_physical(self, plan: lp.LogicalPlan) -> OverrideResult:
        if self.conf.get(cfg.COLUMN_PRUNING):
            from spark_rapids_tpu.plan.optimizer import prune_columns
            plan = prune_columns(plan)
        cpu_plan = plan_cpu(plan, self.conf)
        result = TpuOverrides.apply(cpu_plan, self.conf)
        if self.conf.test_enabled:
            assert_is_on_tpu(result.plan, self.conf.test_allowed_non_tpu)
        for listener in self._plan_listeners:
            listener(result)
        return result

    def _drain_partitions(self, its) -> List:
        """Drain partition iterators, one task per partition on a thread
        pool sized by ``concurrentTpuTasks`` (the Spark task model:
        executor task slots gated by GpuSemaphore, reference:
        GpuSemaphore.scala:101-135).  Output preserves partition order.
        """
        n_tasks = int(self.conf.get(cfg.CONCURRENT_TPU_TASKS))
        if len(its) <= 1 or n_tasks <= 1:
            out: List = []
            for it in its:
                out.extend(it)
            return out
        from concurrent.futures import ThreadPoolExecutor
        from spark_rapids_tpu.sched import cancel as sched_cancel
        tok = sched_cancel.current()

        def drain(it):
            # task threads inherit the query's CancelToken explicitly
            # (pool threads don't propagate thread-locals)
            with sched_cancel.install(tok):
                return list(it)
        with ThreadPoolExecutor(
                max_workers=min(n_tasks, len(its)),
                thread_name_prefix="tpu-task") as pool:
            parts = list(pool.map(drain, its))
        return [x for p in parts for x in p]

    # -- scheduler surface ---------------------------------------------------
    def _next_query_id(self) -> int:
        return next(self._query_ids)

    @property
    def scheduler(self):
        """The session's QueryService (sched/service.py): admission
        stats, controller, estimate book."""
        return self._query_service

    def submit(self, df_or_plan, priority: int = 0,
               timeout_ms: Optional[int] = None,
               estimate_bytes: Optional[int] = None):
        """Submit a query for asynchronous execution; returns a
        QueryFuture (result/cancel/done, profile attached on
        completion).  Accepts a DataFrame or a logical plan.  Higher
        ``priority`` admits first; ``timeout_ms`` overrides
        ``sched.defaultTimeoutMs``; ``estimate_bytes`` overrides the
        admission working-set estimate."""
        plan = getattr(df_or_plan, "plan", df_or_plan)
        return self._query_service.submit(
            plan, priority=priority, timeout_ms=timeout_ms,
            estimate_bytes=estimate_bytes)

    def _execute(self, plan: lp.LogicalPlan) -> pa.Table:
        """The blocking action path: literally ``submit().result()``
        through the concurrent query scheduler (sched/service.py) —
        admission control, deadline, cancellation, and per-query
        profile attribution all apply to plain ``collect()`` too.

        An interrupt of the blocking wait (Ctrl-C in a REPL) cancels
        the submitted query: pre-scheduler, collect ran on the calling
        thread and unwound with the interrupt — a worker that kept
        running headless, holding its admission slot, would regress
        that.  (cancel() is a no-op when the raise came from the query
        itself, which has already finished.)"""
        fut = self._query_service.submit(plan)
        try:
            return fut.result()
        except BaseException:
            fut.cancel("blocking collect interrupted")
            raise

    def _execute_attributed(self, plan: lp.LogicalPlan,
                            query_id: Optional[int] = None,
                            sched_extra: Optional[Dict[str, Any]] = None,
                            plan_digest: Optional[str] = None):
        """Execute an action with the observability envelope: a
        QueryRun captures wall phases, the per-query registry delta and
        span window; the assembled QueryProfile lands in the profile
        ring / ``last_query_profile()`` and fans out to the registered
        query listeners (on success AND on failure).  Returns
        ``(table, profile)`` (profile None when profiling is off).
        Called by the QueryService worker with the query's CancelToken
        already installed on the thread."""
        run = None
        if self.conf.get(cfg.OBS_PROFILE_ENABLED):
            from spark_rapids_tpu.obs.profile import QueryRun
            run = QueryRun(query_id if query_id is not None
                           else self._next_query_id(),
                           sched_extra=sched_extra,
                           plan_digest=plan_digest)
        try:
            result, table = self._execute_inner(plan, run)
        except BaseException as e:
            if run is not None:
                # run.planned was stashed right after planning, so a
                # failure profile still carries the plan tree and the
                # explain report whenever planning itself succeeded
                self._finish_query(run, run.planned, None, e)
            raise
        prof = None
        if run is not None:
            prof = self._finish_query(run, result, table, None)
        elif self.conf.get(cfg.OBS_TRACE_ENABLED):
            # tracing without profiling: the chromePath contract still
            # holds (the whole ring stands in for the query window)
            from spark_rapids_tpu.obs import trace as obs_trace
            chrome = str(self.conf.get(cfg.OBS_TRACE_CHROME_PATH) or "")
            if chrome and obs_trace.is_enabled():
                with contextlib.suppress(OSError):
                    obs_trace.dump_chrome_trace(chrome)
        return table, prof

    def _finish_query(self, run, result, table,
                      error: Optional[BaseException]):
        from spark_rapids_tpu.obs import listener as obs_listener
        from spark_rapids_tpu.obs import trace as obs_trace
        prof = run.finish(result=result, table=table, error=error)
        with self._profile_lock:
            self._profiles[run.query_id] = prof
            while len(self._profiles) > self._profile_ring:
                self._profiles.popitem(last=False)
            # completion order under the lock: "last" is the most
            # recently COMPLETED query, stable under concurrent collects
            self._last_profile = prof
        obs_listener.notify(self._query_listeners, prof, error)
        self._maybe_log_slow_query(prof)
        chrome = str(self.conf.get(cfg.OBS_TRACE_CHROME_PATH) or "")
        if chrome and obs_trace.is_enabled():
            with contextlib.suppress(OSError):
                prof.dump_chrome_trace(chrome)
        return prof

    def _record_rejection(self, query_id: int,
                          error: BaseException, req,
                          meta: Optional[Dict[str, Any]] = None) -> None:
        """A query refused BEFORE admission (queue-full rejection)
        never reaches the profile assembly path, so without this hook
        neither the flight recorder nor the slow-query log would ever
        see it — serving overload would be undiagnosable.  Build a
        stub QueryProfile with the same schema (status ``rejected``),
        put it through the ring, the listener fan-out (the flight
        recorder bundles it under reason ``rejected``) and the
        slow-query log.  Never raises."""
        try:
            from spark_rapids_tpu.obs import listener as obs_listener
            from spark_rapids_tpu.obs.profile import QueryProfile
            meta = dict(meta or {})
            sched = {"sched.estimateBytes": getattr(req, "estimate", 0),
                     "sched.priority": getattr(req, "priority", 0)}
            if meta.get("session_id") is not None:
                sched["sched.sessionId"] = meta["session_id"]
            prof = QueryProfile(
                query_id=query_id,
                status="rejected",
                error=f"{type(error).__name__}: {error}",
                result_rows=None, wall_ns=0, phases={}, plan=None,
                metrics={"sched": sched},
                wall_breakdown={}, explain_lines=[], spans=[],
                plan_digest=meta.get("plan_digest"))
            with self._profile_lock:
                self._profiles[query_id] = prof
                while len(self._profiles) > self._profile_ring:
                    self._profiles.popitem(last=False)
            obs_listener.notify(self._query_listeners, prof, error)
            self._maybe_log_slow_query(prof)
        except Exception:
            pass

    def _record_dedup_follower(self, query_id: int, leader_qid: int,
                               state, error: Optional[BaseException],
                               meta: Optional[Dict[str, Any]],
                               wall_ns: int, result) -> Any:
        """Stub QueryProfile for a single-flight follower: the follower
        never executed, so instead of an empty or duplicated profile it
        records a pointer at the leader's query id
        (``sched.dedup.leaderQueryId``) whose profile holds the real
        execution.  Rings, notifies the listener fan-out and the
        slow-query log (rows carry ``deduped: true``) exactly like the
        rejection stub.  Returns the profile (None on any failure) —
        the caller attaches it to the follower future."""
        try:
            from spark_rapids_tpu.obs import listener as obs_listener
            from spark_rapids_tpu.obs.profile import QueryProfile
            meta = dict(meta or {})
            sched = {"sched.dedup.leaderQueryId": leader_qid,
                     "sched.deduped": 1}
            if meta.get("session_id") is not None:
                sched["sched.sessionId"] = meta["session_id"]
            status = getattr(state, "value", str(state))
            nrows = None
            try:
                if result is not None:
                    nrows = int(result.num_rows)
            except Exception:
                nrows = None
            prof = QueryProfile(
                query_id=query_id,
                status=status,
                error=None if error is None
                else f"{type(error).__name__}: {error}",
                result_rows=nrows, wall_ns=int(wall_ns), phases={},
                plan=None,
                metrics={"sched": sched,
                         "sharing": {"sched.dedup.leaderQueryId":
                                     leader_qid}},
                wall_breakdown={}, explain_lines=[], spans=[],
                plan_digest=meta.get("plan_digest"))
            with self._profile_lock:
                self._profiles[query_id] = prof
                while len(self._profiles) > self._profile_ring:
                    self._profiles.popitem(last=False)
                self._last_profile = prof
            obs_listener.notify(self._query_listeners, prof, error)
            self._maybe_log_slow_query(prof)
            return prof
        except Exception:
            return None

    def _maybe_log_slow_query(self, prof) -> None:
        """Structured slow-query log: one JSONL record per query at or
        over ``obs.slowQueryMs`` (failures included — a query that died
        slowly is still slow; ``rejected`` queries log regardless of
        wall, an instant rejection being exactly the overload signal
        the log exists for), appended to ``obs.slowQueryPath`` or
        routed through the ``spark_rapids_tpu.obs.slowquery`` logger.
        Never fails the query."""
        threshold_ms = int(self.conf.get(cfg.OBS_SLOW_QUERY_MS))
        if threshold_ms <= 0:
            return
        if prof.status != "rejected" and \
                prof.wall_ns < threshold_ms * 1e6:
            return
        try:
            import json as _json
            import time as _time
            # one rendering of the profile exists (to_dict): the log
            # record is a field subset of it plus the log-only extras,
            # so the two JSON surfaces cannot drift apart
            d = prof.to_dict()
            # exact token-based attribution (obs/compile.row_fields —
            # the same derivation the /queries rows use, so the two
            # surfaces cannot drift), NOT the profile's registry-window
            # delta: a concurrent neighbour's compiles would bleed into
            # the window and misidentify this query as compile-bound
            from spark_rapids_tpu.obs import compile as obs_compile
            record = {"ts_unix": _time.time(),
                      "threshold_ms": threshold_ms,
                      "session_id": prof.metrics.get("sched", {}).get(
                          "sched.sessionId"),
                      "queue_wait_s": prof.metrics.get("sched", {}).get(
                          "sched.queueWaitNs", 0) / 1e9}
            record.update(obs_compile.row_fields(prof.query_id))
            for key in ("query_id", "plan_digest", "status", "error",
                        "wall_s", "result_rows", "phases",
                        "wall_breakdown"):
                record[key] = d[key]
            leader = prof.metrics.get("sched", {}).get(
                "sched.dedup.leaderQueryId")
            if leader is not None:
                record["deduped"] = True
                record["leader_query_id"] = leader
            line = _json.dumps(record, default=str)
            from spark_rapids_tpu.obs import recorder as obs_recorder
            from spark_rapids_tpu.obs import registry as obsreg
            obsreg.get_registry().inc("obs.slowQueries")
            obs_recorder.record_event("query.slow",
                                      query=prof.query_id,
                                      wall_s=record["wall_s"])
            path = str(self.conf.get(cfg.OBS_SLOW_QUERY_PATH) or "")
            if path:
                from spark_rapids_tpu.obs import jsonl as obs_jsonl
                obs_jsonl.rotating_append(
                    path, line,
                    int(self.conf.get(cfg.OBS_SLOW_QUERY_MAX_BYTES)))
            else:
                import logging
                logging.getLogger(
                    "spark_rapids_tpu.obs.slowquery").warning(line)
        except Exception:
            pass

    def _phase(self, run, name: str):
        return run.phase(name) if run is not None \
            else contextlib.nullcontext()

    def _execute_inner(self, plan: lp.LogicalPlan, run):
        # executor-longevity guard (see kernel_cache docstring)
        from spark_rapids_tpu.exec import kernel_cache
        kernel_cache.maybe_clear_for_map_pressure()
        from spark_rapids_tpu.exec.context import set_input_file
        set_input_file("")  # fresh query: no stale input_file_name()
        with self._phase(run, "plan"):
            result = self._plan_physical(plan)
        if run is not None:
            run.planned = result
        p = result.plan
        from spark_rapids_tpu.exec.tpu_basic import DeviceToHostExec
        if isinstance(p, DeviceToHostExec):
            # defer ALL device->host downloads behind one completion
            # barrier: the async pipeline runs dispatch-only end to end
            # (a mid-stream read-back would serialize it — and on
            # remote-device runtimes permanently degrade dispatch)
            from spark_rapids_tpu.columnar.batch import to_arrow_all
            with self._phase(run, "execute"):
                batches = self._drain_partitions(p.children[0].execute())
            with self._phase(run, "collect"):
                tables = to_arrow_all(batches)
                table = concat_tables(tables, p.schema)
            # the terminal download exec never ran execute(); stamp it
            # with the collected result so the profile's root rows are
            # the rows the user got
            p.metrics.add_rows(table.num_rows)
            p.metrics.add_batches(len(tables))
            return result, table
        with self._phase(run, "execute"):
            tables = self._drain_partitions(p.execute())
        with self._phase(run, "collect"):
            table = concat_tables(tables, result.plan.schema)
        return result, table

    def _execute_device(self, plan: lp.LogicalPlan):
        """ColumnarRdd-style handoff: device batches, no host round-trip."""
        from spark_rapids_tpu.exec.tpu_basic import (DeviceToHostExec,
                                                     HostToDeviceExec)
        result = self._plan_physical(plan)
        p = result.plan
        if isinstance(p, DeviceToHostExec):
            p = p.children[0]  # strip the terminal download
        else:
            p = HostToDeviceExec(p, self.conf.get(cfg.MIN_BUCKET_ROWS))
        return self._drain_partitions(p.execute())

    # plan-capture hook for tests (ExecutionPlanCaptureCallback analog,
    # reference: Plugin.scala:214-303)
    def add_plan_listener(self, fn) -> None:
        self._plan_listeners.append(fn)

    def remove_plan_listener(self, fn) -> None:
        self._plan_listeners.remove(fn)

    # -- observability surface ---------------------------------------------
    @property
    def obs_server(self):
        """The live telemetry endpoint (obs/server.ObsHttpServer) when
        ``obs.http.enabled=true``; None otherwise.  ``obs_server.port``
        is the bound port (ephemeral under ``obs.http.port=0``)."""
        return self._obs_server

    @property
    def flight_recorder(self):
        """The flight recorder (obs/recorder.FlightRecorder) when
        ``obs.recorder.dir`` is set; None otherwise."""
        return self._recorder

    @property
    def fleet_store(self):
        """The shared fleet store (fleet/store.FleetStore) when this
        session was created with ``fleet.enabled=true``; None
        otherwise.  The serve tier shares its statement registry and
        result cache through it; the compile cache and precompile
        corpus ride its directories when file-backed."""
        return self._fleet_store

    @property
    def serve_server(self):
        """The multi-tenant serving front-end (serve/server.ServeServer)
        when ``serve.enabled=true``; None otherwise.
        ``serve_server.port`` is the bound port (ephemeral under
        ``serve.port=0``)."""
        return self._serve_server

    def restart_serve_server(self, drain_deadline_ms=None):
        """Drain the current serving front-end and start a successor on
        the SAME port — the in-process replica-swap primitive the drain/
        resume contract exists for.  The drain lets in-flight streams
        finish (then cancels stragglers with a typed ``Draining``
        error); resume tokens, the retained-stream window and the
        result cache survive, so clients reconnect, re-attach their
        sessions and resume streams against the successor.  Returns the
        new ServeServer."""
        from spark_rapids_tpu.serve.server import ServeServer
        old = self._serve_server
        port = None
        if old is not None:
            port = old.port
            old.drain(drain_deadline_ms)
        self._serve_server = ServeServer(self, port=port)
        return self._serve_server

    @property
    def sentinel(self):
        """The drift sentinel (obs/sentinel.DriftSentinel) when this
        session was created with ``obs.sentinel.enabled=true``; None
        otherwise.  ``sentinel.stats()`` reports
        ticks/breaches/episodes; ``sentinel.stop()`` halts the
        watcher thread."""
        return self._sentinel

    @property
    def precompile_service(self):
        """The background AOT precompile service
        (sched/precompile.PrecompileService) when this session was
        created with ``sched.precompile.enabled=true``; None otherwise.
        ``precompile_service.wait()`` blocks until the initial corpus
        replay finishes; ``.stats()`` reports plans/programs/warmed/
        skipped/failed."""
        return self._precompile_service

    def last_query_profile(self):
        """The QueryProfile of the most recently COMPLETED action (None
        before the first action, or while
        ``obs.profile.enabled=false`` has kept new profiles from being
        assembled).  Under concurrent collects this is completion
        order, not submission order — use :meth:`query_profile` with a
        QueryFuture's ``query_id`` for a specific query."""
        with self._profile_lock:
            return self._last_profile

    def query_profile(self, query_id: int):
        """The QueryProfile for ``query_id`` from the bounded per-query
        ring (``sched.profileRing`` entries; None once evicted or when
        profiling is off)."""
        with self._profile_lock:
            return self._profiles.get(query_id)

    def register_query_listener(self, listener) -> None:
        """Register a QueryExecutionListener analog: ``on_success(
        profile)`` / ``on_failure(profile, exception)`` fire after
        every action (obs/listener.py)."""
        self._query_listeners.append(listener)

    def remove_query_listener(self, listener) -> None:
        self._query_listeners.remove(listener)


class DataFrameReader:
    def __init__(self, session: TpuSparkSession):
        self.session = session
        self._options: Dict[str, Any] = {}

    def option(self, key: str, value: Any) -> "DataFrameReader":
        self._options[key] = value
        return self

    def _scan(self, fmt: str, paths) -> DataFrame:
        from spark_rapids_tpu.io.readers import (expand_paths, infer_schema,
                                                 _partition_fields)
        from spark_rapids_tpu.plan.logical import Field, Schema
        if isinstance(paths, str):
            paths = [paths]
        files, part_values = expand_paths(fmt, list(paths))
        if not files:
            raise FileNotFoundError(f"no {fmt} files under {paths}")
        schema = infer_schema(fmt, files, self._options)
        pfields = _partition_fields(part_values)
        if pfields:
            schema = Schema(list(schema.fields) +
                            [Field(k, d, True) for k, d in pfields])
        if self._options.get("columns"):
            schema = Schema([schema.field(c)
                             for c in self._options["columns"]])
        opts = dict(self._options)
        opts["part_values"] = part_values
        opts["part_fields"] = pfields
        # the pre-expansion roots: the serving tier's incremental
        # maintenance re-expands them at lookup time so files appended
        # to a watched directory appear in the stamp set instead of
        # being invisible to this frozen file list
        # (exec/incremental.current_files)
        opts["source_roots"] = [os.path.abspath(p) for p in paths]
        return DataFrame(
            lp.FileScan(fmt, files, schema, opts), self.session)

    def parquet(self, *paths) -> DataFrame:
        return self._scan("parquet", list(paths))

    def csv(self, *paths, header: bool = True, sep: str = ","
            ) -> DataFrame:
        self._options.setdefault("header", header)
        self._options.setdefault("sep", sep)
        return self._scan("csv", list(paths))

    def orc(self, *paths) -> DataFrame:
        return self._scan("orc", list(paths))
