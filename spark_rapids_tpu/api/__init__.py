from spark_rapids_tpu.api.session import TpuSparkSession  # noqa: F401
from spark_rapids_tpu.api.column import Column, col, lit  # noqa: F401
from spark_rapids_tpu.api import functions  # noqa: F401
