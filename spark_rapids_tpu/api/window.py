"""Window specification API (pyspark.sql.Window analog)."""

from __future__ import annotations

from spark_rapids_tpu.api.column import _to_expr
from spark_rapids_tpu.expr import ir


class WindowSpec:
    def __init__(self, partition_by=(), order_by=(), frame=None):
        self._partition_by = tuple(partition_by)
        self._order_by = tuple(order_by)
        self._frame = frame

    def partition_by(self, *cols) -> "WindowSpec":
        return WindowSpec(tuple(_as_expr(c) for c in cols),
                          self._order_by, self._frame)

    partitionBy = partition_by

    def order_by(self, *cols) -> "WindowSpec":
        from spark_rapids_tpu.plan.logical import SortOrder
        orders = []
        for c in cols:
            if isinstance(c, SortOrder):
                orders.append(c)
            else:
                orders.append(SortOrder(_as_expr(c), True, None))
        return WindowSpec(self._partition_by, tuple(orders), self._frame)

    orderBy = order_by

    def rows_between(self, start, end) -> "WindowSpec":
        return WindowSpec(self._partition_by, self._order_by,
                          ir.WindowFrame("rows", _bound(start), _bound(end)))

    rowsBetween = rows_between

    def range_between(self, start, end) -> "WindowSpec":
        return WindowSpec(self._partition_by, self._order_by,
                          ir.WindowFrame("range", _bound(start),
                                         _bound(end)))

    rangeBetween = range_between


def _as_expr(c):
    if isinstance(c, str):
        return ir.UnresolvedAttribute(c)
    return _to_expr(c)


def _bound(v):
    if v is None or (isinstance(v, int) and abs(v) >= (1 << 62)):
        return None  # unbounded
    return int(v)


class Window:
    unbounded_preceding = -(1 << 63)
    unbounded_following = (1 << 63)
    current_row = 0
    unboundedPreceding = unbounded_preceding
    unboundedFollowing = unbounded_following
    currentRow = current_row

    @staticmethod
    def partition_by(*cols) -> WindowSpec:
        return WindowSpec().partition_by(*cols)

    partitionBy = partition_by

    @staticmethod
    def order_by(*cols) -> WindowSpec:
        return WindowSpec().order_by(*cols)

    orderBy = order_by
