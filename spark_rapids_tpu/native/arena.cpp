// Host staging arena: first-fit allocator with free-list coalescing.
//
// Native analog of the reference's RMM arena + AddressSpaceAllocator
// (reference: GpuDeviceManager.scala:196-262 RMM ARENA init;
// sql-plugin/.../AddressSpaceAllocator.scala — first-fit allocator inside a
// pinned bounce buffer).  On TPU, XLA owns HBM, so the arena manages *host*
// staging memory: spill destinations, shuffle serialization buffers, and IO
// reassembly buffers all sub-allocate from one big mapping instead of
// churning malloc.  Exposed through a C ABI consumed via ctypes
// (mem/host_arena.py).
//
// Thread-safe; alloc failure returns nullptr so Python can trigger a spill
// (DeviceMemoryEventHandler analog) and retry.

#include <cstddef>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <map>
#include <mutex>
#include <new>

namespace {

struct Arena {
  uint8_t* base = nullptr;
  size_t size = 0;
  // free blocks: offset -> length (kept coalesced)
  std::map<size_t, size_t> free_blocks;
  // live allocations: offset -> length
  std::map<size_t, size_t> live;
  size_t allocated_bytes = 0;
  size_t peak_bytes = 0;
  size_t alignment = 64;
  std::mutex mu;
};

size_t align_up(size_t v, size_t a) { return (v + a - 1) & ~(a - 1); }

}  // namespace

extern "C" {

Arena* arena_create(size_t size, size_t alignment) {
  auto* a = new (std::nothrow) Arena();
  if (!a) return nullptr;
  a->base = static_cast<uint8_t*>(std::malloc(size));
  if (!a->base) {
    delete a;
    return nullptr;
  }
  a->size = size;
  if (alignment >= 8 && (alignment & (alignment - 1)) == 0)
    a->alignment = alignment;
  a->free_blocks[0] = size;
  return a;
}

void arena_destroy(Arena* a) {
  if (!a) return;
  std::free(a->base);
  delete a;
}

// Returns pointer into the arena, or nullptr when no block fits
// (caller should spill and retry — the RMM alloc-failure callback shape).
void* arena_alloc(Arena* a, size_t size) {
  if (!a || size == 0) return nullptr;
  size = align_up(size, a->alignment);
  std::lock_guard<std::mutex> lock(a->mu);
  for (auto it = a->free_blocks.begin(); it != a->free_blocks.end(); ++it) {
    if (it->second >= size) {  // first fit
      size_t off = it->first;
      size_t remain = it->second - size;
      a->free_blocks.erase(it);
      if (remain > 0) a->free_blocks[off + size] = remain;
      a->live[off] = size;
      a->allocated_bytes += size;
      if (a->allocated_bytes > a->peak_bytes)
        a->peak_bytes = a->allocated_bytes;
      return a->base + off;
    }
  }
  return nullptr;
}

int arena_free(Arena* a, void* ptr) {
  if (!a || !ptr) return -1;
  size_t off = static_cast<uint8_t*>(ptr) - a->base;
  std::lock_guard<std::mutex> lock(a->mu);
  auto it = a->live.find(off);
  if (it == a->live.end()) return -1;  // double free / bad pointer
  size_t len = it->second;
  a->live.erase(it);
  a->allocated_bytes -= len;
  // insert into free list and coalesce with neighbours
  auto ins = a->free_blocks.emplace(off, len).first;
  if (ins != a->free_blocks.begin()) {
    auto prev = std::prev(ins);
    if (prev->first + prev->second == ins->first) {
      prev->second += ins->second;
      a->free_blocks.erase(ins);
      ins = prev;
    }
  }
  auto next = std::next(ins);
  if (next != a->free_blocks.end() &&
      ins->first + ins->second == next->first) {
    ins->second += next->second;
    a->free_blocks.erase(next);
  }
  return 0;
}

size_t arena_allocated(Arena* a) {
  if (!a) return 0;
  std::lock_guard<std::mutex> lock(a->mu);
  return a->allocated_bytes;
}

size_t arena_peak(Arena* a) {
  if (!a) return 0;
  std::lock_guard<std::mutex> lock(a->mu);
  return a->peak_bytes;
}

size_t arena_capacity(Arena* a) { return a ? a->size : 0; }

size_t arena_largest_free(Arena* a) {
  if (!a) return 0;
  std::lock_guard<std::mutex> lock(a->mu);
  size_t best = 0;
  for (auto& kv : a->free_blocks)
    if (kv.second > best) best = kv.second;
  return best;
}

int arena_num_live(Arena* a) {
  if (!a) return 0;
  std::lock_guard<std::mutex> lock(a->mu);
  return static_cast<int>(a->live.size());
}

}  // extern "C"
