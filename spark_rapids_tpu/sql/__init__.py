"""SQL frontend: ``session.sql("SELECT ...")``.

The reference rides Spark's own SQL parser/analyzer and only rewrites
physical plans; as a standalone engine we provide the SQL surface its
integration suite exercises (reference analog: the qa_nightly_select_test
/ *_test.py SQL texts in integration_tests): SELECT with joins, WHERE,
GROUP BY / HAVING, ORDER BY / LIMIT, CTEs, UNION [ALL], DISTINCT, CASE,
CAST, IN, BETWEEN, LIKE, and the function registry.
"""

from spark_rapids_tpu.sql.parser import parse_sql  # noqa: F401
