"""SQL tokenizer + recursive-descent parser + lowering to logical plans.

Grammar (subset, case-insensitive keywords):

  query     := [WITH name AS (query) [, ...]] select
  select    := SELECT [DISTINCT] proj [, ...] [FROM from] [WHERE expr]
               [GROUP BY expr [, ...]] [HAVING expr]
               [ORDER BY order [, ...]] [LIMIT n]
               [UNION [ALL] select]
  from      := relation (("," | [INNER|LEFT|RIGHT|FULL|CROSS|
               LEFT SEMI|LEFT ANTI] JOIN) relation [ON expr |
               USING (col [, ...])])*
  relation  := name [[AS] alias] | "(" query ")" [AS] alias
  proj      := "*" | name ".*" | expr [[AS] alias]
  expr      := the usual precedence chain: OR, AND, NOT, comparison
               (=, <>, !=, <, <=, >, >=, [NOT] BETWEEN, [NOT] IN,
               [NOT] LIKE, IS [NOT] NULL), additive, multiplicative,
               unary -, atoms (literal, DATE '...', TIMESTAMP '...',
               CAST(e AS type), CASE [e] WHEN .. THEN .. ELSE .. END,
               function(args), [qualifier.]column, "(" expr ")")

Lowering targets the DataFrame-layer plan builders so SQL and DataFrame
queries share one planning/override path (the reference's position: Spark
parses, the plugin only sees physical plans).
"""

from __future__ import annotations

import datetime as _dt
import re
from typing import Dict, List, Optional, Tuple

from spark_rapids_tpu.expr import ir
from spark_rapids_tpu.plan import logical as lp
from spark_rapids_tpu.plan.logical import SortOrder

# ---------------------------------------------------------------------------
# Tokenizer
# ---------------------------------------------------------------------------

_TOKEN_RE = re.compile(r"""
    (?P<ws>\s+|--[^\n]*)
  | (?P<num>\d+\.\d*(?:[eE][+-]?\d+)?|\.\d+(?:[eE][+-]?\d+)?
      |\d+(?:[eE][+-]?\d+)?)
  | (?P<str>'(?:[^']|'')*')
  | (?P<name>[A-Za-z_][A-Za-z_0-9]*|`[^`]+`)
  | (?P<param>:[A-Za-z_][A-Za-z_0-9]*)
  | (?P<op><=|>=|<>|!=|\|\||[=<>+\-*/%(),.])
""", re.VERBOSE)

_KEYWORDS = {
    "select", "distinct", "from", "where", "group", "by", "having",
    "order", "limit", "union", "all", "as", "and", "or", "not", "in",
    "between", "like", "rlike", "regexp", "is", "null", "case", "when",
    "then", "else",
    "end", "cast", "join", "inner", "left", "right", "full", "outer",
    "cross", "semi", "anti", "on", "using", "with", "asc", "desc",
    "date", "timestamp", "interval", "true", "false", "exists",
    "nulls", "first", "last",
}


class Token:
    __slots__ = ("kind", "value", "pos")

    def __init__(self, kind: str, value: str, pos: int):
        self.kind = kind          # num | str | name | kw | op | eof
        self.value = value
        self.pos = pos

    def __repr__(self):
        return f"Token({self.kind},{self.value!r})"


def tokenize(text: str) -> List[Token]:
    out: List[Token] = []
    i = 0
    while i < len(text):
        m = _TOKEN_RE.match(text, i)
        if not m:
            raise SqlParseError(f"unexpected character {text[i]!r} at "
                                f"position {i}")
        i = m.end()
        if m.lastgroup == "ws":
            continue
        v = m.group()
        if m.lastgroup == "name":
            if v.startswith("`"):
                out.append(Token("name", v[1:-1], m.start()))
            elif v.lower() in _KEYWORDS:
                out.append(Token("kw", v.lower(), m.start()))
            else:
                out.append(Token("name", v, m.start()))
        elif m.lastgroup == "str":
            out.append(Token("str", v[1:-1].replace("''", "'"),
                             m.start()))
        else:
            out.append(Token(m.lastgroup, v, m.start()))
    out.append(Token("eof", "", len(text)))
    return out


class SqlParseError(ValueError):
    pass


class SqlParam:
    """Placeholder VALUE carried by a ``:name`` parameter's Literal in a
    prepared-statement plan template (serve/statements.py).  The
    template parses and plans once with these markers in place; each
    execution deep-copies the template and swaps the markers for the
    bound values — the Literal's declared dtype (and therefore every
    downstream type resolution) never changes, so binding is a value
    substitution, not a re-plan.  Executing a template with an unbound
    SqlParam still in it is a bug; kernels fail loudly on the marker.
    """

    def __init__(self, name_: str):
        self.name = name_

    def __repr__(self) -> str:
        return f":{self.name}"

    def __eq__(self, other) -> bool:
        return isinstance(other, SqlParam) and other.name == self.name

    def __hash__(self) -> int:
        return hash(("SqlParam", self.name))


# ---------------------------------------------------------------------------
# Parser → logical plan (parse and lower in one pass; scopes carry the
# alias → column-name mapping so qualified references resolve)
# ---------------------------------------------------------------------------

_TYPE_NAMES = {
    "boolean": "boolean", "bool": "boolean",
    "tinyint": "byte", "byte": "byte",
    "smallint": "short", "short": "short",
    "int": "int", "integer": "int",
    "bigint": "long", "long": "long",
    "float": "float", "real": "float",
    "double": "double",
    "string": "string", "varchar": "string", "char": "string",
    "date": "date", "timestamp": "timestamp",
}

_FUNCTIONS = {}  # name -> builder(args: List[ir.Expression]) -> Expression


def _fn(name):
    def deco(f):
        _FUNCTIONS[name] = f
        return f
    return deco


def _register_functions():
    from spark_rapids_tpu.api import functions as F
    from spark_rapids_tpu.api.column import Column

    def wrap(builder, arity=None):
        def b(args):
            if arity is not None and len(args) != arity:
                raise SqlParseError(
                    f"wrong argument count for function (expected "
                    f"{arity}, got {len(args)})")
            cols = [Column(a) for a in args]
            return builder(*cols).expr
        return b

    simple = {
        "abs": F.abs, "sqrt": F.sqrt, "exp": F.exp, "ln": F.log,
        "log": F.log, "log2": F.log2, "log10": F.log10,
        "sin": F.sin, "cos": F.cos, "tan": F.tan, "asin": F.asin,
        "acos": F.acos, "atan": F.atan, "cbrt": F.cbrt,
        "degrees": F.degrees, "radians": F.radians,
        "ceil": F.ceil, "ceiling": F.ceil, "floor": F.floor,
        "signum": F.signum, "sign": F.signum,
        "upper": F.upper, "ucase": F.upper,
        "lower": F.lower, "lcase": F.lower,
        "length": F.length, "char_length": F.length,
        "trim": F.trim, "ltrim": F.ltrim, "rtrim": F.rtrim,
        "initcap": F.initcap, "reverse": F.reverse,
        "year": F.year, "month": F.month,
        "day": F.dayofmonth, "dayofmonth": F.dayofmonth,
        "dayofyear": F.dayofyear, "dayofweek": F.dayofweek,
        "weekofyear": F.weekofyear, "quarter": F.quarter,
        "hour": F.hour, "minute": F.minute, "second": F.second,
        "isnull": F.isnull, "isnan": F.isnan,
    }
    for n, f in simple.items():
        _FUNCTIONS[n] = wrap(f, 1)
    _FUNCTIONS["substring"] = _FUNCTIONS["substr"] = wrap(F.substring, 3)
    _FUNCTIONS["concat"] = wrap(F.concat)
    _FUNCTIONS["coalesce"] = wrap(F.coalesce)
    _FUNCTIONS["nanvl"] = wrap(F.nanvl, 2)
    _FUNCTIONS["pow"] = _FUNCTIONS["power"] = wrap(F.pow, 2)
    _FUNCTIONS["atan2"] = wrap(F.atan2, 2)
    _FUNCTIONS["pmod"] = wrap(F.pmod, 2)
    _FUNCTIONS["shiftleft"] = wrap(F.shiftleft, 2)
    _FUNCTIONS["shiftright"] = wrap(F.shiftright, 2)
    # these F helpers take raw python scalars for some arguments, so
    # unwrap the parsed Literal expressions instead of Column-wrapping
    def _lit(e: ir.Expression, what: str):
        if not isinstance(e, ir.Literal):
            raise SqlParseError(f"{what} must be a literal")
        return e.value

    def _locate(args):
        if len(args) not in (2, 3):
            raise SqlParseError("locate takes 2 or 3 arguments")
        pos = _lit(args[2], "locate position") if len(args) == 3 else 1
        return F.locate(_lit(args[0], "locate substring"),
                        Column(args[1]), pos).expr

    def _pad(f):
        def b(args):
            if len(args) != 3:
                raise SqlParseError("pad takes 3 arguments")
            return f(Column(args[0]), _lit(args[1], "pad length"),
                     _lit(args[2], "pad string")).expr
        return b

    def _replace(args):
        if len(args) != 3:
            raise SqlParseError("replace takes 3 arguments")
        return F.replace(Column(args[0]), _lit(args[1], "search"),
                         _lit(args[2], "replacement")).expr

    _FUNCTIONS["md5"] = wrap(F.md5, 1)
    _FUNCTIONS["from_unixtime"] = wrap(F.from_unixtime, 1)
    _FUNCTIONS["input_file_name"] = wrap(
        lambda: F.input_file_name(), 0)

    def _substring_index(args):
        if len(args) != 3:
            raise SqlParseError("substring_index takes 3 arguments")
        return F.substring_index(
            Column(args[0]), _lit(args[1], "delimiter"),
            _lit(args[2], "count")).expr

    def _regexp_replace(args):
        if len(args) != 3:
            raise SqlParseError("regexp_replace takes 3 arguments")
        return F.regexp_replace(Column(args[0]),
                                _lit(args[1], "pattern"),
                                _lit(args[2], "replacement")).expr

    def _split(args):
        if len(args) not in (2, 3):
            raise SqlParseError("split takes 2 or 3 arguments")
        limit = _lit(args[2], "limit") if len(args) == 3 else -1
        return F.split(Column(args[0]), _lit(args[1], "pattern"),
                       limit).expr

    _FUNCTIONS["substring_index"] = _substring_index
    _FUNCTIONS["regexp_replace"] = _regexp_replace
    _FUNCTIONS["split"] = _split
    _FUNCTIONS["locate"] = _locate
    _FUNCTIONS["lpad"] = _pad(F.lpad)
    _FUNCTIONS["rpad"] = _pad(F.rpad)
    _FUNCTIONS["replace"] = _replace
    _FUNCTIONS["date_add"] = wrap(F.date_add, 2)
    _FUNCTIONS["date_sub"] = wrap(F.date_sub, 2)
    _FUNCTIONS["datediff"] = wrap(F.datediff, 2)
    _FUNCTIONS["unix_timestamp"] = wrap(F.unix_timestamp, 1)
    _FUNCTIONS["hash"] = wrap(F.hash)
    _FUNCTIONS["if"] = wrap(F.if_, 3)
    # aggregates
    _FUNCTIONS["sum"] = lambda a: ir.Sum(a[0])
    _FUNCTIONS["min"] = lambda a: ir.Min(a[0])
    _FUNCTIONS["max"] = lambda a: ir.Max(a[0])
    _FUNCTIONS["avg"] = _FUNCTIONS["mean"] = lambda a: ir.Average(a[0])
    _FUNCTIONS["first"] = lambda a: ir.First(a[0])
    _FUNCTIONS["last"] = lambda a: ir.Last(a[0])


_register_functions()


class _Scope:
    """Column resolution scope: output column names + alias→names map."""

    def __init__(self, names: List[str],
                 by_alias: Optional[Dict[str, List[str]]] = None):
        self.names = list(names)
        self.by_alias = dict(by_alias or {})


class Parser:
    def __init__(self, text: str, catalog, param_types=None):
        self.toks = tokenize(text)
        self.i = 0
        self.catalog = catalog        # name -> LogicalPlan
        self.ctes: Dict[str, lp.LogicalPlan] = {}
        # prepared-statement parameter declarations: name -> DType
        # (``:name`` atoms lower to SqlParam-valued Literals of the
        # declared dtype; undeclared parameters are parse errors)
        self.param_types = dict(param_types or {})
        self.params_seen: Dict[str, object] = {}

    # -- token helpers ----------------------------------------------------
    def peek(self, k: int = 0) -> Token:
        return self.toks[min(self.i + k, len(self.toks) - 1)]

    def next(self) -> Token:
        t = self.toks[self.i]
        self.i += 1
        return t

    def accept(self, kind: str, value: Optional[str] = None
               ) -> Optional[Token]:
        t = self.peek()
        if t.kind == kind and (value is None or t.value == value):
            return self.next()
        return None

    def expect(self, kind: str, value: Optional[str] = None) -> Token:
        t = self.accept(kind, value)
        if t is None:
            got = self.peek()
            raise SqlParseError(
                f"expected {value or kind}, got {got.value!r} at "
                f"position {got.pos}")
        return t

    def kw(self, *words) -> bool:
        """Accept a keyword sequence."""
        for k, w in enumerate(words):
            t = self.peek(k)
            if not (t.kind == "kw" and t.value == w):
                return False
        for _ in words:
            self.next()
        return True

    # -- entry ------------------------------------------------------------
    def parse(self) -> lp.LogicalPlan:
        plan = self.query()
        self.expect("eof")
        return plan

    def query(self) -> lp.LogicalPlan:
        if self.kw("with"):
            while True:
                name = self.expect("name").value
                self.expect("kw", "as")
                self.expect("op", "(")
                self.ctes[name.lower()] = self.query()
                self.expect("op", ")")
                if not self.accept("op", ","):
                    break
        return self.select_stmt()

    # -- SELECT -----------------------------------------------------------
    def select_stmt(self) -> lp.LogicalPlan:
        """UNION chain of select cores, then ORDER BY / LIMIT binding to
        the whole result (standard SQL; left-associative UNIONs)."""
        plan, out_scope = self.select_core()
        while self.kw("union"):
            all_ = bool(self.kw("all"))
            right, _ = self.select_core()
            if len(right.schema.names) != len(plan.schema.names):
                raise SqlParseError(
                    "UNION requires the same number of columns")
            if right.schema.names != plan.schema.names:
                # Spark takes the left side's column names
                right = lp.Project(right, [
                    ir.Alias(ir.UnresolvedAttribute(rn), ln)
                    for rn, ln in zip(right.schema.names,
                                      plan.schema.names)])
            plan = lp.Union([plan, right])
            if not all_:
                plan = lp.Aggregate(
                    plan, [ir.UnresolvedAttribute(n)
                           for n in plan.schema.names], [])

        if self.kw("order", "by"):
            orders = []
            while True:
                orders.append(self.order_item(out_scope, plan))
                if not self.accept("op", ","):
                    break
            # Spark resolves sort refs against the SELECT output first,
            # then against the projection's INPUT, carrying missing
            # input columns through as hidden sort columns and dropping
            # them after the sort (ResolveSortReferences)
            missing = []
            for o in orders:
                for a in ir.collect(
                        o.expr,
                        lambda n: isinstance(n, ir.UnresolvedAttribute)):
                    if a.attr_name not in plan.schema.names and \
                            a.attr_name not in missing:
                        missing.append(a.attr_name)
            visible = list(plan.schema.names)
            if missing and isinstance(plan, lp.Project) and \
                    len(set(visible)) == len(visible) and all(
                    m in plan.children[0].schema.names for m in missing):
                inner = plan.children[0]
                aug = lp.Project(
                    inner,
                    [ir.Alias(e, n) for e, n in
                     zip(plan.exprs, visible)] +
                    [ir.UnresolvedAttribute(m) for m in missing])
                srt = lp.Sort(aug, orders)
                plan = lp.Project(
                    srt, [ir.UnresolvedAttribute(n) for n in visible])
            else:
                plan = lp.Sort(plan, orders)

        if self.kw("limit"):
            n = self.expect("num").value
            plan = lp.Limit(plan, int(n))
        return plan

    def select_core(self) -> Tuple[lp.LogicalPlan, "_Scope"]:
        self.expect("kw", "select")
        distinct = bool(self.kw("distinct"))
        proj = self.select_list()

        plan: Optional[lp.LogicalPlan] = None
        scope = _Scope([])
        if self.kw("from"):
            plan, scope = self.from_clause()
        else:
            # FROM-less SELECT of literals: single-row relation
            import pyarrow as pa
            plan = lp.InMemoryScan(pa.table({"__one": [1]}))
            scope = _Scope([])

        if self.kw("where"):
            cond = self.expr(scope)
            plan = lp.Filter(plan, cond)

        group_exprs: List[ir.Expression] = []
        has_group = False
        rollup_kind = None
        if self.kw("group", "by"):
            has_group = True
            t = self.peek()
            if t.kind in ("name", "kw") and \
                    t.value.lower() in ("rollup", "cube") and \
                    self.peek(1).kind == "op" and \
                    self.peek(1).value == "(":
                rollup_kind = t.value.lower()
                self.next()
                self.expect("op", "(")
                while True:
                    group_exprs.append(self.expr(scope))
                    if not self.accept("op", ","):
                        break
                self.expect("op", ")")
            else:
                while True:
                    group_exprs.append(self.expr(scope))
                    if not self.accept("op", ","):
                        break

        having = None
        if self.kw("having"):
            having = self.expr(scope)

        # aggregate vs plain projection
        proj_exprs = self.resolve_projection(proj, scope)
        # GROUP BY a select alias (GROUP BY y for year(d) AS y) resolves
        # to the aliased expression, as Spark's analyzer does
        alias_map = {e.alias: e.children[0] for e in proj_exprs
                     if isinstance(e, ir.Alias)}
        group_exprs = [
            alias_map[g.attr_name]
            if (isinstance(g, ir.UnresolvedAttribute)
                and g.attr_name not in scope.names
                and g.attr_name in alias_map) else g
            for g in group_exprs]
        is_agg = has_group or having is not None or any(
            ir.collect(e, lambda n: isinstance(n, ir.AggregateExpression))
            for e in proj_exprs)

        if rollup_kind is not None:
            # GROUP BY ROLLUP/CUBE (...): lower through the shared
            # Expand grouping-sets helper; key references anywhere in
            # the projection/HAVING resolve to the NULLED grouping-set
            # key columns, not the pass-through inputs
            import itertools
            k = len(group_exprs)
            if rollup_kind == "rollup":
                sets = [tuple(range(i)) for i in range(k, -1, -1)]
            else:
                sets = [s for n in range(k, -1, -1)
                        for s in itertools.combinations(range(k), n)]
            plan, refs, _renames = lp.expand_grouping_sets(
                plan, group_exprs, sets)
            keys = list(group_exprs)

            def _key_repl(node):
                for i, g in enumerate(keys):
                    if ir.expr_eq(node, g):
                        return ir.UnresolvedAttribute(f"__gset{i}")
                return None

            def _fix(e):
                if isinstance(e, ir.Alias):
                    return ir.Alias(
                        ir.transform(e.children[0], _key_repl), e.alias)
                return ir.Alias(ir.transform(e, _key_repl),
                                ir.output_name(e))

            proj_exprs = [_fix(e) for e in proj_exprs]
            if having is not None:
                having = ir.transform(having, _key_repl)
            group_exprs = refs
            scope = _Scope(plan.schema.names)

        plan, out_scope = self.lower_select(
            plan, scope, proj_exprs, group_exprs, having, is_agg)
        # qualified refs (p.name) in ORDER BY still resolve via the FROM
        # aliases, provided the column survived into the output
        out_scope.by_alias = {
            a: [n for n in ns if n in out_scope.names]
            for a, ns in scope.by_alias.items()}

        if distinct:
            plan = lp.Aggregate(
                plan, [ir.UnresolvedAttribute(n)
                       for n in plan.schema.names], [])
        return plan, out_scope

    def select_list(self):
        """Parse the projection as raw items; resolution happens once the
        FROM scope is known.  Items: '*', ('qualified_star', alias),
        ('expr', tokens-slice bounds, alias)."""
        items = []
        while True:
            if self.accept("op", "*"):
                items.append("*")
            elif (self.peek().kind == "name"
                  and self.peek(1).kind == "op"
                  and self.peek(1).value == "."
                  and self.peek(2).kind == "op"
                  and self.peek(2).value == "*"):
                alias = self.next().value
                self.next()
                self.next()
                items.append(("qstar", alias))
            else:
                start = self.i
                self.skip_expr()
                end = self.i
                alias = None
                if self.kw("as"):
                    alias = self.expect_name_or_kw()
                items.append(("expr", start, end, alias))
            if not self.accept("op", ","):
                break
        return items

    def expect_name_or_kw(self) -> str:
        t = self.peek()
        if t.kind in ("name", "kw"):
            self.next()
            return t.value
        raise SqlParseError(f"expected identifier, got {t.value!r}")

    def skip_expr(self) -> None:
        """Skip one expression at the token level (used to defer select-
        list parsing until the FROM scope exists): consume until a
        top-level ',' / FROM / EOF, tracking parens."""
        depth = 0
        while True:
            t = self.peek()
            if t.kind == "eof":
                return
            if t.kind == "op":
                if t.value == "(":
                    depth += 1
                elif t.value == ")":
                    if depth == 0:
                        return
                    depth -= 1
                elif t.value == "," and depth == 0:
                    return
            if depth == 0 and t.kind == "kw" and t.value in (
                    "from", "where", "group", "having", "order", "limit",
                    "union", "as"):
                return
            # a bare alias (name following a complete expression) also
            # terminates, but distinguishing it requires real parsing;
            # select_list re-parses the slice, so just stop on names that
            # directly follow a complete atom: handled by re-parse length
            self.next()

    def resolve_projection(self, items, scope: _Scope
                           ) -> List[ir.Expression]:
        out: List[ir.Expression] = []
        for it in items:
            if it == "*":
                out.extend(ir.UnresolvedAttribute(n) for n in scope.names)
            elif isinstance(it, tuple) and it[0] == "qstar":
                alias = it[1].lower()
                if alias not in scope.by_alias:
                    raise SqlParseError(f"unknown table alias '{it[1]}'")
                out.extend(ir.UnresolvedAttribute(n)
                           for n in scope.by_alias[alias])
            else:
                _, start, end, alias = it
                save = self.i
                self.i = start
                e = self.expr(scope)
                # tolerate a trailing bare alias inside the slice
                if self.i < end and self.peek().kind == "name":
                    alias = alias or self.next().value
                if self.i != end:
                    bad = self.peek()
                    raise SqlParseError(
                        f"could not parse select item near "
                        f"{bad.value!r} at position {bad.pos}")
                self.i = save
                out.append(ir.Alias(e, alias) if alias else e)
        return out

    def lower_select(self, plan, scope, proj_exprs, group_exprs, having,
                     is_agg) -> Tuple[lp.LogicalPlan, _Scope]:
        if not is_agg:
            plan = lp.Project(plan, proj_exprs)
            return plan, _Scope(plan.schema.names)

        # DISTINCT aggregates: shared double-aggregate rewrite before
        # the leaf split (lp.rewrite_distinct_aggregates); pre-alias so
        # output names survive the strip
        proj_exprs = [e if isinstance(e, ir.Alias)
                      else ir.Alias(e, ir.output_name(e))
                      for e in proj_exprs]
        rw_exprs = list(proj_exprs) + ([having] if having is not None
                                       else [])
        plan2, groupings2, exprs2 = lp.rewrite_distinct_aggregates(
            plan, group_exprs, rw_exprs)
        if plan2 is not plan:
            plan = plan2
            group_exprs = groupings2
            if having is not None:
                having = exprs2[-1]
                proj_exprs = exprs2[:-1]
            else:
                proj_exprs = exprs2

        # aggregate: groupings = GROUP BY exprs; select items that are
        # bare group refs pass through, others must be aggregates (the
        # compound/post-projection split mirrors GroupedData.agg)
        leaves: List[ir.Expression] = []

        def repl(node):
            if isinstance(node, ir.AggregateExpression):
                name = f"__agg{len(leaves)}"
                leaves.append(ir.Alias(node, name))
                return ir.UnresolvedAttribute(name)
            return None

        group_names = []
        group_keys = []
        for g in group_exprs:
            name = ir.output_name(g)
            group_names.append(name)
            group_keys.append(g)

        projected = []
        for e in proj_exprs:
            name = ir.output_name(e)
            inner = e.children[0] if isinstance(e, ir.Alias) else e
            if any(_expr_eq(inner, g) for g in group_keys):
                projected.append(ir.Alias(_group_ref(inner, group_keys,
                                                     group_names), name))
                continue
            projected.append(ir.Alias(ir.transform(inner, repl), name))

        having_expr = None
        if having is not None:
            having_expr = ir.transform(having, repl)

        agg_plan = lp.Aggregate(plan, group_keys, leaves)
        if having_expr is not None:
            agg_plan = lp.Filter(agg_plan, having_expr)
        final = lp.Project(agg_plan, projected)
        return final, _Scope(final.schema.names)

    def order_item(self, scope: _Scope, plan) -> SortOrder:
        # positional ORDER BY n
        if self.peek().kind == "num":
            t = self.next()
            idx = int(t.value) - 1
            if not (0 <= idx < len(plan.schema.names)):
                raise SqlParseError(f"ORDER BY position {t.value} out of "
                                    f"range")
            e: ir.Expression = ir.UnresolvedAttribute(
                plan.schema.names[idx])
        else:
            e = self.expr(_Scope(plan.schema.names, scope.by_alias))
        asc = True
        if self.kw("desc"):
            asc = False
        else:
            self.kw("asc")
        nulls: Optional[bool] = None   # SortOrder.nulls_first is a BOOL
        if self.kw("nulls", "first"):
            nulls = True
        elif self.kw("nulls", "last"):
            nulls = False
        return SortOrder(e, asc, nulls)

    # -- FROM -------------------------------------------------------------
    def from_clause(self) -> Tuple[lp.LogicalPlan, _Scope]:
        plan, scope = self.relation()
        while True:
            if self.accept("op", ","):
                right, rscope = self.relation()
                plan, scope = self.join_plans(plan, scope, right, rscope,
                                              "cross", None, None)
                continue
            how = None
            if self.kw("cross", "join"):
                how = "cross"
            elif self.kw("inner", "join"):
                how = "inner"
            elif self.kw("left", "semi", "join"):
                how = "semi"
            elif self.kw("left", "anti", "join"):
                how = "anti"
            elif self.kw("left", "outer", "join") or self.kw(
                    "left", "join"):
                how = "left"
            elif self.kw("right", "outer", "join") or self.kw(
                    "right", "join"):
                how = "right"
            elif self.kw("full", "outer", "join") or self.kw(
                    "full", "join"):
                how = "full"
            elif self.kw("join"):
                how = "inner"
            if how is None:
                return plan, scope
            right, rscope = self.relation()
            on = None
            using = None
            if self.kw("on"):
                joint = _Scope(scope.names + rscope.names,
                               {**scope.by_alias, **rscope.by_alias})
                on = self.expr(joint)
            elif self.kw("using"):
                self.expect("op", "(")
                using = [self.expect("name").value]
                while self.accept("op", ","):
                    using.append(self.expect("name").value)
                self.expect("op", ")")
            plan, scope = self.join_plans(plan, scope, right, rscope,
                                          how, on, using)

    def relation(self) -> Tuple[lp.LogicalPlan, _Scope]:
        if self.accept("op", "("):
            sub = self.query()
            self.expect("op", ")")
            alias = None
            if self.kw("as"):
                alias = self.expect("name").value
            elif self.peek().kind == "name":
                alias = self.next().value
            scope = _Scope(sub.schema.names)
            if alias:
                scope.by_alias[alias.lower()] = list(sub.schema.names)
            return sub, scope
        name = self.expect("name").value
        plan = self.lookup(name)
        alias = name
        if self.kw("as"):
            alias = self.expect("name").value
        elif self.peek().kind == "name":
            alias = self.next().value
        scope = _Scope(plan.schema.names,
                       {alias.lower(): list(plan.schema.names)})
        return plan, scope

    def lookup(self, name: str) -> lp.LogicalPlan:
        key = name.lower()
        if key in self.ctes:
            return self.ctes[key]
        plan = self.catalog.get(key)
        if plan is None:
            raise SqlParseError(f"table or view not found: {name}")
        return plan

    def join_plans(self, left, lscope: _Scope, right, rscope: _Scope,
                   how, on, using) -> Tuple[lp.LogicalPlan, _Scope]:
        dup = set(left.schema.names) & set(right.schema.names)
        if using:
            left_keys = right_keys = list(using)
            condition = None
        elif on is not None:
            left_keys, right_keys, condition = lp.split_join_condition(
                on, left.schema.names, right.schema.names)
        elif how == "cross":
            left_keys, right_keys, condition = [], [], None
        else:
            raise SqlParseError("JOIN requires ON or USING")
        overlap = dup - set(u for u in (using or []))
        if overlap and how != "semi" and how != "anti":
            raise SqlParseError(
                f"duplicate column names across join inputs: "
                f"{sorted(overlap)}; alias them apart (the engine keeps "
                f"flat output schemas)")
        if using:
            # drop the right copy of USING columns, Spark-style
            proj = [ir.UnresolvedAttribute(n) for n in left.schema.names]
            proj += [ir.UnresolvedAttribute(n)
                     for n in right.schema.names if n not in using]
            if how in ("semi", "anti"):
                out = lp.Join(left, right, left_keys, right_keys, how,
                              condition=condition)
            else:
                # rename right key columns before join to avoid dup names
                rename = {n: f"__r_{n}" for n in using}
                rproj = [ir.Alias(ir.UnresolvedAttribute(n), rename[n])
                         if n in rename else ir.UnresolvedAttribute(n)
                         for n in right.schema.names]
                right2 = lp.Project(right, rproj)
                joined = lp.Join(left, right2, left_keys,
                                 [rename[k] for k in right_keys], how,
                                 condition=condition)
                out = lp.Project(joined, proj)
            scope = _Scope(out.schema.names,
                           {**lscope.by_alias, **rscope.by_alias})
            return out, scope
        joined = lp.Join(left, right, left_keys, right_keys, how,
                         condition=condition)
        scope = _Scope(joined.schema.names,
                       {**lscope.by_alias, **rscope.by_alias})
        return joined, scope

    # -- expressions ------------------------------------------------------
    def expr(self, scope: _Scope) -> ir.Expression:
        return self.or_expr(scope)

    def or_expr(self, scope) -> ir.Expression:
        e = self.and_expr(scope)
        while self.kw("or"):
            e = ir.Or(e, self.and_expr(scope))
        return e

    def and_expr(self, scope) -> ir.Expression:
        e = self.not_expr(scope)
        while self.kw("and"):
            e = ir.And(e, self.not_expr(scope))
        return e

    def not_expr(self, scope) -> ir.Expression:
        if self.kw("not"):
            return ir.Not(self.not_expr(scope))
        return self.comparison(scope)

    def comparison(self, scope) -> ir.Expression:
        e = self.additive(scope)
        while True:
            t = self.peek()
            if t.kind == "op" and t.value in ("=", "<>", "!=", "<", "<=",
                                              ">", ">="):
                self.next()
                rhs = self.additive(scope)
                cls = {"=": ir.EqualTo, "<": ir.LessThan,
                       "<=": ir.LessThanOrEqual, ">": ir.GreaterThan,
                       ">=": ir.GreaterThanOrEqual}.get(t.value)
                if cls:
                    e = cls(e, rhs)
                else:
                    e = ir.Not(ir.EqualTo(e, rhs))
                continue
            negate = False
            save = self.i
            if self.kw("not"):
                negate = True
            if self.kw("between"):
                lo = self.additive(scope)
                self.expect("kw", "and")
                hi = self.additive(scope)
                base = ir.And(ir.GreaterThanOrEqual(e, lo),
                              ir.LessThanOrEqual(e, hi))
                e = ir.Not(base) if negate else base
                continue
            if self.kw("in"):
                self.expect("op", "(")
                vals = [self.expr(scope)]
                while self.accept("op", ","):
                    vals.append(self.expr(scope))
                self.expect("op", ")")
                lits = []
                for v in vals:
                    if not isinstance(v, ir.Literal):
                        raise SqlParseError(
                            "IN list must be literals")
                    lits.append(v.value)
                base = ir.In(e, lits)
                e = ir.Not(base) if negate else base
                continue
            if self.kw("like"):
                pat = self.expect("str").value
                base = ir.Like(e, ir.Literal(pat))
                e = ir.Not(base) if negate else base
                continue
            if self.kw("rlike") or self.kw("regexp"):
                pat = self.expect("str").value
                base = ir.RLike(e, ir.Literal(pat))
                e = ir.Not(base) if negate else base
                continue
            if negate:
                self.i = save
            if self.kw("is"):
                if self.kw("not"):
                    self.expect("kw", "null")
                    e = ir.IsNotNull(e)
                else:
                    self.expect("kw", "null")
                    e = ir.IsNull(e)
                continue
            return e

    def additive(self, scope) -> ir.Expression:
        e = self.multiplicative(scope)
        while True:
            t = self.peek()
            if t.kind == "op" and t.value in ("+", "-"):
                self.next()
                rhs = self.multiplicative(scope)
                e = (ir.Add if t.value == "+" else ir.Subtract)(e, rhs)
            elif t.kind == "op" and t.value == "||":
                self.next()
                rhs = self.multiplicative(scope)
                e = ir.Concat(e, rhs)
            else:
                return e

    def multiplicative(self, scope) -> ir.Expression:
        e = self.unary(scope)
        while True:
            t = self.peek()
            if t.kind == "op" and t.value in ("*", "/", "%"):
                self.next()
                rhs = self.unary(scope)
                cls = {"*": ir.Multiply, "/": ir.Divide,
                       "%": ir.Remainder}[t.value]
                e = cls(e, rhs)
            else:
                return e

    def unary(self, scope) -> ir.Expression:
        if self.accept("op", "-"):
            return ir.UnaryMinus(self.unary(scope))
        if self.accept("op", "+"):
            return self.unary(scope)
        return self.atom(scope)

    def atom(self, scope) -> ir.Expression:
        t = self.peek()
        if t.kind == "param":
            self.next()
            pname = t.value[1:]
            dtype = self.param_types.get(pname)
            if dtype is None:
                raise SqlParseError(
                    f"undeclared parameter :{pname} at position {t.pos}; "
                    f"declare its type when preparing the statement")
            lit = ir.Literal(SqlParam(pname), dtype)
            # a parameter may be bound to NULL; plan it nullable so the
            # template's null-handling doesn't depend on the binding
            lit.nullable = True
            self.params_seen[pname] = dtype
            return lit
        if t.kind == "num":
            self.next()
            if re.fullmatch(r"\d+", t.value):
                return ir.Literal(int(t.value))
            return ir.Literal(float(t.value))
        if t.kind == "str":
            self.next()
            return ir.Literal(t.value)
        if self.kw("true"):
            return ir.Literal(True)
        if self.kw("false"):
            return ir.Literal(False)
        if self.kw("null"):
            return ir.Literal(None)
        if t.kind == "kw" and t.value == "date" \
                and self.peek(1).kind == "str":
            self.next()
            s = self.next().value
            return ir.Literal(_dt.date.fromisoformat(s))
        if t.kind == "kw" and t.value == "timestamp" \
                and self.peek(1).kind == "str":
            self.next()
            s = self.next().value
            v = _dt.datetime.fromisoformat(s)
            if v.tzinfo is None:
                v = v.replace(tzinfo=_dt.timezone.utc)
            return ir.Literal(v)
        if self.kw("cast"):
            self.expect("op", "(")
            e = self.expr(scope)
            self.expect("kw", "as")
            ty = self.expect_name_or_kw().lower()
            self.expect("op", ")")
            if ty not in _TYPE_NAMES:
                raise SqlParseError(f"unknown type in CAST: {ty}")
            from spark_rapids_tpu.api.column import _TYPE_NAMES as TN
            return ir.Cast(e, TN[_TYPE_NAMES[ty]])
        if self.kw("case"):
            return self.case_expr(scope)
        if self.accept("op", "("):
            e = self.expr(scope)
            self.expect("op", ")")
            return e
        if t.kind in ("name", "kw"):
            # function call?
            nxt = self.peek(1)
            if nxt.kind == "op" and nxt.value == "(":
                return self.func_call(scope)
            if t.kind == "name":
                return self.column_ref(scope)
        raise SqlParseError(f"unexpected token {t.value!r} at position "
                            f"{t.pos}")

    def case_expr(self, scope) -> ir.Expression:
        # CASE [operand] WHEN v THEN r ... [ELSE d] END
        operand = None
        if not (self.peek().kind == "kw" and self.peek().value == "when"):
            operand = self.expr(scope)
        branches = []
        while self.kw("when"):
            cond = self.expr(scope)
            if operand is not None:
                cond = ir.EqualTo(operand, cond)
            self.expect("kw", "then")
            val = self.expr(scope)
            branches.append((cond, val))
        default = None
        if self.kw("else"):
            default = self.expr(scope)
        self.expect("kw", "end")
        return ir.CaseWhen(branches, default)

    def func_call(self, scope) -> ir.Expression:
        name = self.expect_name_or_kw().lower()
        self.expect("op", "(")
        # count(*) / aggregate(DISTINCT x)
        if name == "count":
            if self.accept("op", "*"):
                self.expect("op", ")")
                return ir.Count(None)
            distinct = bool(self.kw("distinct"))
            arg = self.expr(scope)
            self.expect("op", ")")
            return ir.Count(arg, distinct=distinct)
        if name in ("sum", "avg", "mean") and self.kw("distinct"):
            arg = self.expr(scope)
            self.expect("op", ")")
            cls = ir.Sum if name == "sum" else ir.Average
            return cls(arg, distinct=True)
        args: List[ir.Expression] = []
        if not (self.peek().kind == "op" and self.peek().value == ")"):
            args.append(self.expr(scope))
            while self.accept("op", ","):
                args.append(self.expr(scope))
        self.expect("op", ")")
        fn = _FUNCTIONS.get(name)
        if fn is None:
            raise SqlParseError(f"unknown function: {name}")
        return fn(args)

    def column_ref(self, scope: _Scope) -> ir.Expression:
        name = self.expect("name").value
        if self.peek().kind == "op" and self.peek().value == "." \
                and self.peek(1).kind == "name":
            self.next()
            colname = self.expect("name").value
            alias = name.lower()
            if alias not in scope.by_alias:
                raise SqlParseError(f"unknown table alias '{name}'")
            if colname not in scope.by_alias[alias]:
                raise SqlParseError(
                    f"column '{colname}' not found in '{name}'")
            return ir.UnresolvedAttribute(colname)
        return ir.UnresolvedAttribute(name)


_expr_eq = ir.expr_eq


def _group_ref(e: ir.Expression, group_keys, group_names
               ) -> ir.Expression:
    for g, n in zip(group_keys, group_names):
        if _expr_eq(e, g):
            return ir.UnresolvedAttribute(n)
    return e


def parse_sql(text: str, catalog, param_types=None) -> lp.LogicalPlan:
    """Parse one SQL query against ``catalog`` (name→LogicalPlan).

    ``param_types`` (name → DType) declares ``:name`` prepared-statement
    parameters; without it a ``:name`` token is a parse error."""
    return Parser(text, catalog, param_types=param_types).parse()


def parse_prepared(text: str, catalog, param_types) -> Tuple[
        lp.LogicalPlan, Dict[str, object]]:
    """Parse a parameterized statement once; returns the plan template
    (with SqlParam-valued Literals in place) and the parameters it
    actually references (name → DType) — the serve layer's
    prepared-statement entry point."""
    p = Parser(text, catalog, param_types=param_types)
    plan = p.parse()
    return plan, dict(p.params_seen)
