"""UDF support: bytecode→IR compiler + row-wise CPU fallback.

Reference analog: the ``udf-compiler`` module (bytecode → Catalyst) and
``GpuScalaUDF`` bridge (udf-compiler/.../GpuScalaUDF.scala:28).
"""

from spark_rapids_tpu.udf.compiler import (UdfCompileError,  # noqa: F401
                                           compile_udf)
