"""UDF compiler: CPython bytecode -> expression IR.

Analog of the reference's ``udf-compiler`` module, which reflects a Scala
lambda's JVM bytecode (reference: udf-compiler/.../LambdaReflection.scala:
98-138), builds a basic-block CFG (CFG.scala:44-141), and symbolically
executes JVM opcodes into Catalyst expressions (Instruction.scala:122-830,
CatalystExpressionBuilder.scala:45-242) so the result can be accelerated by
the planner like any other expression; any untranslatable opcode keeps the
original UDF on CPU.

Here the input is CPython bytecode via :mod:`dis` (the 3.10 through 3.12
opcode families: 3.10's ``BINARY_ADD``/``CALL_FUNCTION``/``LOAD_METHOD``
fixed-opcode forms and 3.11+'s parameterized ``BINARY_OP``/``CALL`` forms
are both interpreted, so the same UDF compiles on every interpreter the
engine supports) and the output is :mod:`spark_rapids_tpu.expr.ir`.  The
symbolic executor interprets the instruction stream over a stack of IR
expressions; at a conditional jump it recursively evaluates both
successors and merges them with ``ir.If`` (the reference does the same
merge through CatalystExpressionBuilder's condition propagation,
State.scala:78).  Loops (backward jumps) and unknown opcodes raise
:class:`UdfCompileError`, which callers turn into a row-wise CPU
``ir.PythonUDF`` fallback — matching the reference's fallback behavior.

Known, documented semantic divergence (shared with the reference, whose
udf-compiler lowers JVM idiv to Catalyst ``Divide``): compiled ``/``, ``//``
and ``%`` follow Spark SQL's null-on-zero-divisor semantics, whereas the
row-wise Python function would raise ``ZeroDivisionError`` and fail the job.
A job that would crash under plain Python instead yields null for those rows
when compiled.
"""

from __future__ import annotations

import builtins
import dis
import math
import sys
from typing import Any, Dict, List, Optional, Sequence

from spark_rapids_tpu.expr import ir

_MAX_VISITED = 4096
_MAX_DEPTH = 64


class UdfCompileError(Exception):
    """Raised when a Python function cannot be translated to IR."""


class _Raw:
    """A plain Python value on the symbolic stack (const, module, fn)."""

    __slots__ = ("value",)

    def __init__(self, value: Any):
        self.value = value

    def __repr__(self) -> str:
        return f"_Raw({self.value!r})"


class _Null:
    """The NULL slot pushed by LOAD_GLOBAL/LOAD_ATTR for plain calls."""

    def __repr__(self) -> str:
        return "_NULL"


_NULL = _Null()


class _Method:
    """A method loaded off an expression receiver (e.g. ``s.upper``)."""

    __slots__ = ("name",)

    def __init__(self, name: str):
        self.name = name


def _as_expr(v: Any) -> ir.Expression:
    if isinstance(v, ir.Expression):
        return v
    if isinstance(v, _Raw):
        return ir.Literal(v.value)
    raise UdfCompileError(f"cannot use {v!r} as a column expression")


def _as_bool(v: Any) -> ir.Expression:
    """Branch-condition check. Only boolean expressions are supported;
    Python truthiness of strings/numbers is not reproduced — such UDFs
    stay on the row-wise CPU path."""
    e = _as_expr(v)
    try:
        ir.transform(e, lambda n: n.resolve())
    except Exception:
        return e  # unbound leaves (direct compile_udf calls): defer
    from spark_rapids_tpu import dtypes as dt
    if e.dtype is not None and e.dtype not in (dt.BOOL, dt.NULL):
        raise UdfCompileError(
            f"branch condition has type {e.dtype.id.value}, not boolean "
            "(Python truthiness is not translated)")
    return e


# -- callable translation ---------------------------------------------------

_MATH_UNARY = {
    math.sqrt: ir.Sqrt, math.exp: ir.Exp, math.expm1: ir.Expm1,
    math.log2: ir.Log2, math.log10: ir.Log10, math.log1p: ir.Log1p,
    math.sin: ir.Sin, math.cos: ir.Cos, math.tan: ir.Tan,
    math.sinh: ir.Sinh, math.cosh: ir.Cosh, math.tanh: ir.Tanh,
    math.asin: ir.Asin, math.acos: ir.Acos, math.atan: ir.Atan,
    math.degrees: ir.ToDegrees, math.radians: ir.ToRadians,
    math.fabs: ir.Abs, math.floor: ir.Floor, math.ceil: ir.Ceil,
}
if hasattr(math, "cbrt"):  # 3.11+
    _MATH_UNARY[math.cbrt] = ir.Cbrt

_STR_METHODS_0 = {
    "upper": ir.Upper, "lower": ir.Lower, "strip": ir.StringTrim,
    "lstrip": ir.StringTrimLeft, "rstrip": ir.StringTrimRight,
}


def _translate_call(callable_obj: Any, receiver: Any,
                    args: List[Any]) -> Any:
    """Map a resolved Python callable (+receiver for methods) to IR."""
    if isinstance(callable_obj, _Method):
        recv = _as_expr(receiver)
        name = callable_obj.name
        if name in _STR_METHODS_0 and not args:
            return _STR_METHODS_0[name](recv)
        if name == "startswith" and len(args) == 1:
            return ir.StartsWith(recv, _as_expr(args[0]))
        if name == "endswith" and len(args) == 1:
            return ir.EndsWith(recv, _as_expr(args[0]))
        if name == "replace" and len(args) == 2:
            return ir.StringReplace(recv, _as_expr(args[0]),
                                    _as_expr(args[1]))
        if name == "find" and len(args) == 1:
            # Python str.find is 0-based (-1 missing); Spark locate is
            # 1-based (0 missing) — shift by one.
            return ir.Subtract(
                ir.StringLocate(_as_expr(args[0]), recv, ir.Literal(1)),
                ir.Literal(1))
        raise UdfCompileError(f"unsupported method .{name}()")

    if not isinstance(callable_obj, _Raw):
        raise UdfCompileError(f"cannot call {callable_obj!r}")
    fn = callable_obj.value
    if fn in _MATH_UNARY and len(args) == 1:
        return _MATH_UNARY[fn](_as_expr(args[0]))
    if fn is math.log:
        if len(args) == 1:
            return ir.Log(_as_expr(args[0]))
        raise UdfCompileError("math.log with base is not supported")
    if fn is math.atan2 and len(args) == 2:
        return ir.Atan2(_as_expr(args[0]), _as_expr(args[1]))
    if fn is math.pow and len(args) == 2:
        return ir.Pow(_as_expr(args[0]), _as_expr(args[1]))
    if fn is builtins.abs and len(args) == 1:
        return ir.Abs(_as_expr(args[0]))
    if fn is builtins.len and len(args) == 1:
        return ir.Length(_as_expr(args[0]))
    if fn is builtins.float and len(args) == 1:
        from spark_rapids_tpu import dtypes as dt
        return ir.Cast(_as_expr(args[0]), dt.FLOAT64)
    if fn is builtins.int and len(args) == 1:
        from spark_rapids_tpu import dtypes as dt
        return ir.Cast(_as_expr(args[0]), dt.INT64)
    if fn is builtins.bool and len(args) == 1:
        from spark_rapids_tpu import dtypes as dt
        return ir.Cast(_as_expr(args[0]), dt.BOOL)
    if fn is builtins.str and len(args) == 1:
        from spark_rapids_tpu import dtypes as dt
        return ir.Cast(_as_expr(args[0]), dt.STRING)
    raise UdfCompileError(f"unsupported callable {fn!r}")


# -- binary / compare ops ---------------------------------------------------

# BINARY_OP oparg -> builder (CPython Include/opcode_ids / _operator docs).
# In-place variants (oparg >= 13) reuse the same semantics.
def _resolve_all(e: ir.Expression) -> None:
    for c in e.children:
        _resolve_all(c)
    if e.dtype is None:
        e.resolve()


def _floordiv(a: ir.Expression, b: ir.Expression) -> ir.Expression:
    # Python // floors. For integer operands stay in the integer domain:
    # a - pmod(a, b) is exactly divisible by b (Python % == Spark pmod for
    # all sign combos), so IntegralDivide's truncation is exact and values
    # beyond 2^53 are not corrupted by a float64 round-trip. Overflow at
    # INT64_MIN-adjacent inputs wraps like Spark arithmetic does.
    try:
        _resolve_all(a)
        _resolve_all(b)
        int_int = a.dtype is not None and b.dtype is not None and \
            a.dtype.is_integral and b.dtype.is_integral
    except Exception:
        int_int = False
    if int_int:
        return ir.IntegralDivide(ir.Subtract(a, ir.Pmod(a, b)), b)
    # float operands: Python returns the floored float
    return ir.Floor(ir.Divide(a, b))


_BINARY_OPS = {
    0: ir.Add,          # +
    2: _floordiv,       # //
    5: ir.Multiply,     # *
    6: ir.Pmod,         # %  (Python % == Spark pmod for all sign combos)
    8: ir.Pow,          # **
    10: ir.Subtract,    # -
    11: ir.Divide,      # /
}

# CPython <= 3.10 spells each arithmetic op as its own opcode instead of
# BINARY_OP's oparg; the INPLACE_* variants share semantics exactly as
# the oparg-13 aliasing does on 3.11+
_NAMED_BINARY_OPS = {}
for _name, _builder in (("ADD", ir.Add), ("SUBTRACT", ir.Subtract),
                        ("MULTIPLY", ir.Multiply),
                        ("TRUE_DIVIDE", ir.Divide),
                        ("FLOOR_DIVIDE", _floordiv),
                        ("MODULO", ir.Pmod), ("POWER", ir.Pow)):
    _NAMED_BINARY_OPS[f"BINARY_{_name}"] = _builder
    _NAMED_BINARY_OPS[f"INPLACE_{_name}"] = _builder

# LOAD_GLOBAL's oparg low bit became a push-NULL flag in 3.11;
# LOAD_ATTR's low bit became a method-load flag only in 3.12 (3.11
# still uses LOAD_METHOD).  On older interpreters the arg is a plain
# name index and reading the bit would misinterpret every odd-indexed
# name — so each opcode gates on the version that introduced ITS flag.
_GLOBAL_NULL_FLAG = sys.version_info >= (3, 11)
_ATTR_METHOD_FLAG = sys.version_info >= (3, 12)

_COMPARE_OPS = {
    "<": ir.LessThan, "<=": ir.LessThanOrEqual, "==": ir.EqualTo,
    ">": ir.GreaterThan, ">=": ir.GreaterThanOrEqual,
}


def _compare(op: str, left: Any, right: Any) -> ir.Expression:
    op = op.removeprefix("bool(").removesuffix(")")
    le, re_ = _as_expr(left), _as_expr(right)
    if op == "!=":
        return ir.Not(ir.EqualTo(le, re_))
    if op in _COMPARE_OPS:
        return _COMPARE_OPS[op](le, re_)
    raise UdfCompileError(f"unsupported comparison {op!r}")


# -- the symbolic executor --------------------------------------------------

class _Compiler:
    def __init__(self, func, arg_exprs: Sequence[ir.Expression]):
        self.func = func
        code = func.__code__
        if code.co_argcount != len(arg_exprs):
            raise UdfCompileError(
                f"UDF takes {code.co_argcount} args, got {len(arg_exprs)}")
        if code.co_kwonlyargcount or \
                code.co_flags & 0x0C:  # *args / **kwargs
            raise UdfCompileError("var-args UDFs are not supported")
        if func.__defaults__:
            raise UdfCompileError("default arguments are not supported")
        self.instrs = list(dis.get_instructions(func))
        self.by_offset = {i.offset: idx for idx, i in enumerate(self.instrs)}
        self.locals: Dict[int, Any] = dict(enumerate(arg_exprs))
        self.visited = 0

    def resolve_global(self, name: str) -> _Raw:
        g = self.func.__globals__
        if name in g:
            return _Raw(g[name])
        if hasattr(builtins, name):
            return _Raw(getattr(builtins, name))
        raise UdfCompileError(f"unresolvable global {name!r}")

    def run(self, idx: int, stack: List[Any], locals_: Dict[int, Any],
            depth: int = 0) -> ir.Expression:
        if depth > _MAX_DEPTH:
            raise UdfCompileError("control flow too deep")
        stack = list(stack)
        locals_ = dict(locals_)
        while True:
            self.visited += 1
            if self.visited > _MAX_VISITED:
                raise UdfCompileError("bytecode too large")
            instr = self.instrs[idx]
            op = instr.opname
            if op in ("RESUME", "NOP", "CACHE", "PRECALL", "EXTENDED_ARG"):
                idx += 1
            elif op in ("LOAD_FAST", "LOAD_FAST_CHECK",
                        "LOAD_FAST_AND_CLEAR"):
                if instr.arg not in locals_:
                    raise UdfCompileError(
                        f"read of unassigned local {instr.argval!r}")
                stack.append(locals_[instr.arg])
                idx += 1
            elif op == "STORE_FAST":
                locals_[instr.arg] = stack.pop()
                idx += 1
            elif op == "LOAD_CONST":
                stack.append(_Raw(instr.argval))
                idx += 1
            elif op == "RETURN_CONST":
                return ir.Literal(instr.argval)
            elif op == "RETURN_VALUE":
                return _as_expr(stack.pop())
            elif op == "LOAD_GLOBAL":
                if _GLOBAL_NULL_FLAG and instr.arg & 1:
                    stack.append(_NULL)
                stack.append(self.resolve_global(instr.argval))
                idx += 1
            elif op == "PUSH_NULL":            # 3.11+
                stack.append(_NULL)
                idx += 1
            elif op == "LOAD_ATTR":
                obj = stack.pop()
                if isinstance(obj, _Raw):
                    try:
                        attr = getattr(obj.value, instr.argval)
                    except AttributeError as e:
                        raise UdfCompileError(str(e))
                    if _ATTR_METHOD_FLAG and instr.arg & 1:
                        stack.append(_NULL)
                    stack.append(_Raw(attr))
                elif isinstance(obj, ir.Expression) and \
                        _ATTR_METHOD_FLAG and instr.arg & 1:
                    stack.append(_Method(instr.argval))
                    stack.append(obj)
                else:
                    raise UdfCompileError(
                        f"unsupported attribute load .{instr.argval}")
                idx += 1
            elif op == "LOAD_METHOD":          # <= 3.11
                obj = stack.pop()
                if isinstance(obj, ir.Expression):
                    # the (method, self) pair CALL/CALL_METHOD pops
                    stack.append(_Method(instr.argval))
                    stack.append(obj)
                elif isinstance(obj, _Raw):
                    try:
                        attr = getattr(obj.value, instr.argval)
                    except AttributeError as e:
                        raise UdfCompileError(str(e))
                    stack.append(_NULL)
                    stack.append(_Raw(attr))
                else:
                    raise UdfCompileError(
                        f"unsupported method load .{instr.argval}")
                idx += 1
            elif op in ("CALL", "CALL_METHOD"):
                argc = instr.arg or 0
                args = stack[len(stack) - argc:]
                del stack[len(stack) - argc:]
                b = stack.pop()
                a = stack.pop()
                if isinstance(a, _Null):
                    result = _translate_call(b, None, args)
                else:
                    result = _translate_call(a, b, args)
                stack.append(result)
                idx += 1
            elif op == "CALL_FUNCTION":        # <= 3.10: no NULL slot
                argc = instr.arg or 0
                args = stack[len(stack) - argc:]
                del stack[len(stack) - argc:]
                stack.append(_translate_call(stack.pop(), None, args))
                idx += 1
            elif op in _NAMED_BINARY_OPS:      # <= 3.10
                r = stack.pop()
                le = stack.pop()
                stack.append(_NAMED_BINARY_OPS[op](_as_expr(le),
                                                   _as_expr(r)))
                idx += 1
            elif op == "BINARY_OP":
                r = stack.pop()
                le = stack.pop()
                key = (instr.arg or 0) % 13  # inplace variants alias
                builder = _BINARY_OPS.get(key)
                if builder is None:
                    raise UdfCompileError(
                        f"unsupported binary op {instr.argrepr!r}")
                stack.append(builder(_as_expr(le), _as_expr(r)))
                idx += 1
            elif op == "COMPARE_OP":
                r = stack.pop()
                le = stack.pop()
                stack.append(_compare(instr.argrepr, le, r))
                idx += 1
            elif op == "IS_OP":
                r = stack.pop()
                le = stack.pop()
                operand, none_side = (le, r) if _is_none(r) else (r, le)
                if not _is_none(none_side):
                    raise UdfCompileError("`is` only supported against None")
                e = ir.IsNull(_as_expr(operand))
                stack.append(ir.Not(e) if instr.arg else e)
                idx += 1
            elif op == "CONTAINS_OP":
                container = stack.pop()
                item = stack.pop()
                if isinstance(container, _Raw) and \
                        isinstance(container.value, (tuple, list, set,
                                                     frozenset)):
                    e: ir.Expression = ir.In(_as_expr(item),
                                             list(container.value))
                else:
                    e = ir.Contains(_as_expr(container), _as_expr(item))
                stack.append(ir.Not(e) if instr.arg else e)
                idx += 1
            elif op == "UNARY_NEGATIVE":
                stack.append(ir.UnaryMinus(_as_expr(stack.pop())))
                idx += 1
            elif op == "UNARY_NOT":
                stack.append(ir.Not(_as_bool(stack.pop())))
                idx += 1
            elif op == "COPY":
                stack.append(stack[-(instr.arg or 1)])
                idx += 1
            elif op == "DUP_TOP":              # <= 3.10
                stack.append(stack[-1])
                idx += 1
            elif op == "SWAP":
                n = instr.arg or 2
                stack[-1], stack[-n] = stack[-n], stack[-1]
                idx += 1
            elif op == "ROT_TWO":              # <= 3.10
                stack[-1], stack[-2] = stack[-2], stack[-1]
                idx += 1
            elif op == "ROT_THREE":            # <= 3.10
                stack[-1], stack[-2], stack[-3] = \
                    stack[-2], stack[-3], stack[-1]
                idx += 1
            elif op == "POP_TOP":
                stack.pop()
                idx += 1
            elif op in ("JUMP_FORWARD", "JUMP_ABSOLUTE"):
                idx = self._jump_target(instr)
            elif op == "JUMP_BACKWARD":
                raise UdfCompileError("loops are not supported")
            elif op.startswith("POP_JUMP") and \
                    ("_IF_" in op or op.startswith("POP_JUMP_IF")):
                # POP_JUMP_IF_* (3.10/3.12) and the 3.11-only
                # POP_JUMP_{FORWARD,BACKWARD}_IF_* spellings
                cond = stack.pop()
                if op.endswith("NONE"):
                    pred: ir.Expression = ir.IsNull(_as_expr(cond))
                    jump_when = not op.endswith("NOT_NONE")
                else:
                    pred = _as_bool(cond)
                    jump_when = op.endswith("TRUE")
                target = self._jump_target(instr)
                taken = self.run(target, stack, locals_, depth + 1)
                fallthrough = self.run(idx + 1, stack, locals_, depth + 1)
                if jump_when:
                    return ir.If(pred, taken, fallthrough)
                return ir.If(pred, fallthrough, taken)
            elif op in ("JUMP_IF_FALSE_OR_POP", "JUMP_IF_TRUE_OR_POP"):
                # <= 3.11 `and`/`or` chains: the jump KEEPS the
                # condition as the expression value, the fallthrough
                # pops it and keeps evaluating
                pred = _as_bool(stack[-1])
                target = self._jump_target(instr)
                taken = self.run(target, stack, locals_, depth + 1)
                fallthrough = self.run(idx + 1, stack[:-1], locals_,
                                       depth + 1)
                if op == "JUMP_IF_TRUE_OR_POP":
                    return ir.If(pred, taken, fallthrough)
                return ir.If(pred, fallthrough, taken)
            else:
                raise UdfCompileError(f"unsupported opcode {op}")

    def _jump_target(self, instr) -> int:
        """Instruction index of a jump's target; backward targets are
        loops, which the compiler refuses (matching JUMP_BACKWARD on
        3.12 — 3.10 spells loop back-edges as JUMP_ABSOLUTE)."""
        target = self.by_offset.get(instr.argval)
        if target is None:
            raise UdfCompileError(
                f"jump to unknown offset {instr.argval}")
        if instr.argval <= instr.offset:
            raise UdfCompileError("loops are not supported")
        return target


def _is_none(v: Any) -> bool:
    return isinstance(v, _Raw) and v.value is None


def compile_udf(func, arg_exprs: Sequence[ir.Expression]) -> ir.Expression:
    """Translate ``func``'s bytecode into an IR expression over
    ``arg_exprs``. Raises :class:`UdfCompileError` when untranslatable."""
    if not hasattr(func, "__code__"):
        raise UdfCompileError(f"{func!r} has no bytecode")
    c = _Compiler(func, arg_exprs)
    return c.run(0, [], c.locals)
