"""Device regex subset engine: literal patterns compile at trace time to
an epsilon-free Thompson NFA whose active-state sets travel as uint64
BITMASKS advanced over the padded byte matrix — one fused vector step
per character position inside a ``lax.scan``, no per-row Python.

Reference analog: the plugin runs RLike / RegExpReplace on the GPU via
cudf's regex engine (shims/spark300/src/main/scala/com/nvidia/spark/
rapids/shims/spark300/Spark300Shims.scala:183-247, GpuRegExpReplace /
GpuRLike) and likewise incompat-flags regex for dialect deltas.  The
TPU formulation avoids cudf-style per-thread backtracking entirely:
with at most 64 NFA states, "which states are alive" is one uint64 per
(row [, start-position]) lane, and each input byte advances every lane
with a handful of shift/mask ops XLA fuses into one kernel.

Supported subset (everything else raises ``Unsupported`` so the planner
falls back to CPU with a tagged reason):
  - literal ASCII bytes, ``.`` (any byte except newline, like Java)
  - character classes ``[a-z0-9_]``, negated ``[^...]``, ranges,
    and the escapes ``\\d \\D \\w \\W \\s \\S`` inside or outside classes
  - escaped metacharacters ``\\. \\\\ \\+ ...``, ``\\n \\t \\r \\f \\a \\e``
  - anchors ``^`` (pattern start only) and ``$`` (pattern end only)
  - greedy quantifiers ``? * + {m} {m,} {m,n}`` (lazy ``*?`` etc. are
    not; bounded repeats expand by fragment copying)
  - grouping ``(...)`` / ``(?:...)`` and alternation ``|``

Not supported: backreferences, lookaround, inline flags, named groups,
non-ASCII pattern characters, patterns needing more than 64 NFA states.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

MAX_STATES = 64          # active set must fit one uint64 lane
_NL = ord("\n")


class Unsupported(Exception):
    """Pattern outside the device subset — caller falls back to CPU."""


# ---------------------------------------------------------------------------
# parse: pattern -> AST
# ---------------------------------------------------------------------------
# AST nodes (tuples):
#   ("cls", frozenset_of_bytes)      one byte from the set
#   ("cat", [nodes])                 concatenation
#   ("alt", [nodes])                 alternation
#   ("rep", node, lo, hi)            hi=None means unbounded

_DIGITS = frozenset(range(ord("0"), ord("9") + 1))
_WORD = frozenset(
    list(range(ord("a"), ord("z") + 1)) +
    list(range(ord("A"), ord("Z") + 1)) +
    list(range(ord("0"), ord("9") + 1)) + [ord("_")])
_SPACE = frozenset(b" \t\n\r\f\v")
_ALL = frozenset(range(256))
_DOT = _ALL - {_NL}

_ESC_CLS = {"d": _DIGITS, "D": _ALL - _DIGITS, "w": _WORD,
            "W": _ALL - _WORD, "s": _SPACE, "S": _ALL - _SPACE}
_ESC_LIT = {"n": _NL, "t": ord("\t"), "r": ord("\r"), "f": ord("\f"),
            "a": ord("\a"), "e": 0x1B, "0": 0}


class _Parser:
    def __init__(self, pat: str):
        if any(ord(ch) > 127 for ch in pat):
            raise Unsupported("non-ASCII pattern")
        self.p = pat
        self.i = 0
        self.anchor_start = False
        self.anchor_end = False

    def peek(self) -> Optional[str]:
        return self.p[self.i] if self.i < len(self.p) else None

    def take(self) -> str:
        ch = self.p[self.i]
        self.i += 1
        return ch

    def parse(self):
        if self.peek() == "^":
            self.anchor_start = True
            self.take()
        node = self.alt(top=True)
        if self.i != len(self.p):
            raise Unsupported(f"unexpected '{self.p[self.i]}' at "
                              f"{self.i}")
        if (self.anchor_start or self.anchor_end) and node[0] == "alt":
            # '^a|b' anchors only the FIRST branch in Java ('$' only the
            # last); flag-style anchors would wrongly anchor every
            # branch — group it as '^(a|b)' to anchor the whole pattern
            raise Unsupported("anchor with top-level alternation")
        return node

    def alt(self, top: bool = False):
        branches = [self.cat(top)]
        while self.peek() == "|":
            self.take()
            branches.append(self.cat(top))
        return branches[0] if len(branches) == 1 else ("alt", branches)

    def cat(self, top: bool):
        parts = []
        while True:
            ch = self.peek()
            if ch is None or ch == "|" or ch == ")":
                break
            if ch == "$":
                # only valid as the very last pattern character
                if top and self.i == len(self.p) - 1:
                    self.anchor_end = True
                    self.take()
                    break
                raise Unsupported("'$' not at pattern end")
            if ch == "^":
                raise Unsupported("'^' not at pattern start")
            parts.append(self.quantified())
        return ("cat", parts)

    def quantified(self):
        node = self.atom()
        ch = self.peek()
        lo = hi = None
        if ch == "?":
            self.take()
            lo, hi = 0, 1
        elif ch == "*":
            self.take()
            lo, hi = 0, None
        elif ch == "+":
            self.take()
            lo, hi = 1, None
        elif ch == "{":
            save = self.i
            self.take()
            digits = ""
            while self.peek() is not None and self.peek().isdigit():
                digits += self.take()
            if not digits:
                self.i = save          # '{' literal, like Java
                return node
            m = int(digits)
            if self.peek() == "}":
                self.take()
                lo, hi = m, m
            elif self.peek() == ",":
                self.take()
                digits2 = ""
                while self.peek() is not None and self.peek().isdigit():
                    digits2 += self.take()
                if self.peek() != "}":
                    self.i = save
                    return node
                self.take()
                lo, hi = m, (int(digits2) if digits2 else None)
            else:
                self.i = save
                return node
            if hi is not None and hi < lo:
                raise Unsupported("{m,n} with n < m")
            if (hi or lo) > 32:
                raise Unsupported("bounded repeat > 32")
        if lo is None:
            return node
        if self.peek() in ("?", "+"):
            raise Unsupported("lazy/possessive quantifiers")
        return ("rep", node, lo, hi)

    def atom(self):
        ch = self.take()
        if ch == "(":
            if self.peek() == "?":
                self.take()
                if self.peek() != ":":
                    raise Unsupported("only (?:...) groups")
                self.take()
            node = self.alt()
            if self.peek() != ")":
                raise Unsupported("unbalanced group")
            self.take()
            return node
        if ch == "[":
            return ("cls", self.char_class())
        if ch == ".":
            return ("cls", _DOT)
        if ch == "\\":
            return ("cls", self.escape(in_class=False))
        if ch in "*+?)":
            raise Unsupported(f"dangling '{ch}'")
        return ("cls", frozenset({ord(ch)}))

    def escape(self, in_class: bool) -> frozenset:
        if self.peek() is None:
            raise Unsupported("trailing backslash")
        ch = self.take()
        if ch in _ESC_CLS:
            return _ESC_CLS[ch]
        if ch in _ESC_LIT:
            return frozenset({_ESC_LIT[ch]})
        if not ch.isalnum():
            return frozenset({ord(ch)})
        raise Unsupported(f"escape \\{ch}")

    def char_class(self) -> frozenset:
        negate = False
        if self.peek() == "^":
            negate = True
            self.take()
        members: set = set()
        first = True
        while True:
            ch = self.peek()
            if ch is None:
                raise Unsupported("unterminated class")
            if ch == "]" and not first:
                self.take()
                break
            first = False
            if ch == "\\":
                self.take()
                members |= self.escape(in_class=True)
                if self.peek() == "-" and self.i + 1 < len(self.p) \
                        and self.p[self.i + 1] != "]":
                    raise Unsupported("class escape as range bound")
                continue
            lo = ord(self.take())
            if self.peek() == "-" and self.i + 1 < len(self.p) and \
                    self.p[self.i + 1] != "]":
                self.take()
                nxt = self.peek()
                if nxt == "\\":
                    raise Unsupported("escape as range bound")
                hi = ord(self.take())
                if hi < lo:
                    raise Unsupported("reversed class range")
                members |= set(range(lo, hi + 1))
            else:
                members.add(lo)
        if not members:
            raise Unsupported("empty class")
        return frozenset(_ALL - members) if negate else frozenset(members)


# ---------------------------------------------------------------------------
# compile: AST -> epsilon-free NFA with bitmask states
# ---------------------------------------------------------------------------

@dataclass
class CompiledRegex:
    pattern: str
    classes: np.ndarray           # [C, 256] bool lookup tables
    transitions: List[Tuple[int, int, int]]   # (src_state, cls, tgt)
    start_mask: int               # closure of the start state
    accept_mask: int
    anchor_start: bool
    anchor_end: bool
    min_len: int                  # shortest possible match, 0 if empty ok
    has_alt: bool                 # pattern contains alternation
    n_variable: int               # variable-length elements (see below)
    n_states: int

    @property
    def replace_safe(self) -> bool:
        """True when the LONGEST match per start (what match_ends
        computes) provably equals Java's greedy-backtracking match for
        every input: no alternation, at most ONE variable-length
        element, and at least one consumed byte.  With a single
        variable element all matches at a start differ only in its
        repeat count, so greedy-max == longest; with two (e.g.
        a{1,2}(ab)? on 'aab') Java's earlier-greedy choice can force a
        SHORTER overall match than the longest."""
        return (not self.has_alt and self.min_len >= 1
                and self.n_variable <= 1)


class _NfaBuilder:
    """Glushkov-style position automaton: one state per character-class
    occurrence (plus state 0 = start), which is epsilon-free by
    construction and linear in pattern size."""

    def __init__(self):
        self.classes: List[frozenset] = []
        self._cls_ids: Dict[frozenset, int] = {}
        self.state_cls: List[int] = []     # class consumed ENTERING state
        self.follow: List[Tuple[int, set]] = []   # (state, next-states)

    def cls_id(self, s: frozenset) -> int:
        if s not in self._cls_ids:
            self._cls_ids[s] = len(self.classes)
            self.classes.append(s)
        return self._cls_ids[s]

    def new_state(self, cls: int) -> int:
        sid = len(self.state_cls) + 1      # state 0 is reserved start
        if sid >= MAX_STATES:
            raise Unsupported(f"pattern needs > {MAX_STATES - 1} states")
        self.state_cls.append(cls)
        return sid

    # each build returns (first, last, nullable):
    #   first: set of states that can consume the fragment's 1st byte
    #   last:  set of states a completed fragment can end in
    #   nullable: fragment can match empty
    def build(self, node):
        kind = node[0]
        if kind == "cls":
            sid = self.new_state(self.cls_id(node[1]))
            return {sid}, {sid}, False
        if kind == "cat":
            first: set = set()
            last: set = set()
            nullable = True
            for child in node[1]:
                f, l, nu = self.build(child)
                # link: every last-so-far flows into child's first
                self.follow.extend((p, f) for p in last)
                if nullable:
                    first |= f
                if nu:
                    last |= l
                else:
                    last = set(l)
                nullable = nullable and nu
            return first, last, nullable
        if kind == "alt":
            first, last = set(), set()
            nullable = False
            for child in node[1]:
                f, l, nu = self.build(child)
                first |= f
                last |= l
                nullable = nullable or nu
            return first, last, nullable
        if kind == "rep":
            _, child, lo, hi = node
            # expand to lo required copies + optional tail
            first, last, nullable = set(), set(), True
            copies: List[Tuple[set, set, bool]] = []
            n_req = lo if lo > 0 else 0
            if hi is None:
                n_copies = max(n_req, 1)
            else:
                n_copies = hi
            if n_copies == 0:          # {0,0}
                return set(), set(), True
            for k in range(n_copies):
                f, l, nu = self.build(child)
                copies.append((f, l, nu))
            # link consecutive copies
            for k in range(n_copies - 1):
                for p in copies[k][1]:
                    self.follow.append((p, copies[k + 1][0]))
            if hi is None:
                # last copy loops to itself
                f, l, _nu = copies[-1]
                for p in l:
                    self.follow.append((p, f))
            # firsts: copy k's first reachable if copies 0..k-1 nullable
            reach_nullable = True
            for k in range(n_copies):
                if reach_nullable:
                    first |= copies[k][0]
                reach_nullable = reach_nullable and copies[k][2]
            # lasts: copy k's last is a fragment end if k >= lo-1 OR
            # all copies after k are optional (k >= lo-1 covers both
            # since copies beyond lo are the optional tail)
            for k in range(n_copies):
                if k >= lo - 1:
                    last |= copies[k][1]
            frag_nullable = (lo == 0) or all(c[2] for c in copies[:lo])
            return first, last, frag_nullable
        raise AssertionError(kind)


def _min_len(node) -> int:
    kind = node[0]
    if kind == "cls":
        return 1
    if kind == "cat":
        return sum(_min_len(c) for c in node[1])
    if kind == "alt":
        return min(_min_len(c) for c in node[1])
    if kind == "rep":
        return node[2] * _min_len(node[1])
    raise AssertionError(kind)


def _n_variable(node) -> int:
    """Count variable-length elements, conservatively: a rep with
    lo != hi (or unbounded) is one, plus double-weight for any variable
    content it repeats; a fixed rep multiplies its child's count by the
    copies made."""
    kind = node[0]
    if kind == "cls":
        return 0
    if kind == "cat":
        return sum(_n_variable(c) for c in node[1])
    if kind == "alt":
        return max((_n_variable(c) for c in node[1]), default=0)
    if kind == "rep":
        _, child, lo, hi = node
        inner = _n_variable(child)
        if hi is not None and hi == lo:
            return min(lo, 2) * inner
        return 1 + 2 * inner
    raise AssertionError(kind)


def _has_alt(node) -> bool:
    kind = node[0]
    if kind == "cls":
        return False
    if kind == "alt":
        return True
    if kind == "cat":
        return any(_has_alt(c) for c in node[1])
    if kind == "rep":
        return _has_alt(node[1])
    raise AssertionError(kind)


def compile_pattern(pattern: str) -> CompiledRegex:
    """Parse+compile; raises Unsupported outside the subset."""
    if not pattern:
        raise Unsupported("empty pattern")
    parser = _Parser(pattern)
    ast = parser.parse()
    b = _NfaBuilder()
    first, last, nullable = b.build(ast)

    n_states = len(b.state_cls) + 1
    transitions: List[Tuple[int, int, int]] = []
    # start (state 0) -> first positions
    for tgt in sorted(first):
        transitions.append((0, b.state_cls[tgt - 1], tgt))
    # follow links: src state -> targets (consuming target's class)
    seen = set()
    for src, tgts in b.follow:
        for tgt in sorted(tgts):
            key = (src, tgt)
            if key in seen:
                continue
            seen.add(key)
            transitions.append((src, b.state_cls[tgt - 1], tgt))

    accept_mask = 0
    for s in last:
        accept_mask |= 1 << s
    if nullable:
        accept_mask |= 1       # start state accepts (empty match)

    cls_arr = np.zeros((len(b.classes), 256), dtype=bool)
    for i, s in enumerate(b.classes):
        cls_arr[i, list(s)] = True

    return CompiledRegex(
        pattern=pattern, classes=cls_arr, transitions=transitions,
        start_mask=1, accept_mask=accept_mask,
        anchor_start=parser.anchor_start, anchor_end=parser.anchor_end,
        min_len=_min_len(ast), has_alt=_has_alt(ast),
        n_variable=_n_variable(ast), n_states=n_states)


# ---------------------------------------------------------------------------
# device evaluation
# ---------------------------------------------------------------------------

def _step_masks(cr: CompiledRegex, active: jnp.ndarray,
                cls_byte: jnp.ndarray) -> jnp.ndarray:
    """One NFA step: advance uint64 active-state masks by one byte.
    ``cls_byte`` is [..., C] bool (does this lane's byte match class c);
    ``active`` is uint64 of the same leading shape."""
    nxt = jnp.zeros_like(active)
    one = jnp.uint64(1)
    for src, cls, tgt in cr.transitions:
        alive = (active >> jnp.uint64(src)) & one != 0
        fire = alive & cls_byte[..., cls]
        nxt = nxt | jnp.where(fire, jnp.uint64(1 << tgt),
                              jnp.uint64(0))
    return nxt


def rlike(cr: CompiledRegex, data: jnp.ndarray,
          lengths: jnp.ndarray) -> jnp.ndarray:
    """Java Matcher.find() semantics: does any substring match?
    [n] bool over the padded byte matrix."""
    n, w = data.shape
    cls_tab = jnp.asarray(cr.classes.T)          # [256, C]
    start = jnp.uint64(cr.start_mask)
    accept = jnp.uint64(cr.accept_mask)
    u0 = jnp.uint64(0)

    def body(carry, xs):
        active, hit = carry
        j, byte = xs
        can_start = j <= lengths
        if cr.anchor_start:
            can_start = can_start & (j == 0)
        act = active | jnp.where(can_start, start, u0)
        ok = (act & accept) != 0
        if cr.anchor_end:
            ok = ok & (j == lengths)
        hit = hit | ok
        cls_byte = jnp.take(cls_tab, byte, axis=0)   # [n, C]
        cls_byte = cls_byte & (j < lengths)[:, None]
        return (_step_masks(cr, act, cls_byte), hit), None

    init = (jnp.zeros((n,), jnp.uint64), jnp.zeros((n,), jnp.bool_))
    (active, hit), _ = jax.lax.scan(
        body, init, (jnp.arange(w, dtype=jnp.int32), data.T))
    # final step at j == w: empty-match injection + accept check
    can_start = lengths == w if not cr.anchor_start else \
        (lengths == w) & (w == 0)
    act = active | jnp.where(can_start, start, u0)
    ok = (act & accept) != 0
    if cr.anchor_end:
        ok = ok & (lengths == w)
    return hit | ok


def match_ends(cr: CompiledRegex, data: jnp.ndarray,
               lengths: jnp.ndarray) -> jnp.ndarray:
    """Longest-match table: E[r, p] = exclusive end of the LONGEST match
    of the pattern starting at byte p of row r, or -1.  Requires
    ``cr.min_len >= 1`` (no empty matches) — callers gate on it.

    One uint64 active-mask lane per (row, start position): the scan
    over byte positions advances ALL w parallel start hypotheses at
    once (w+1'th hypothesis — empty match at end — excluded by
    min_len >= 1)."""
    assert cr.min_len >= 1, "empty-matchable pattern"
    n, w = data.shape
    cls_tab = jnp.asarray(cr.classes.T)
    start = jnp.uint64(cr.start_mask)
    accept = jnp.uint64(cr.accept_mask)

    def body(carry, xs):
        active, ends = carry
        j, byte = xs
        if cr.anchor_start:
            inject = jnp.where(j == 0, start, jnp.uint64(0))
            active = active.at[:, 0].set(active[:, 0] | inject)
        else:
            active = active.at[:, j].set(active[:, j] | start)
        cls_byte = jnp.take(cls_tab, byte, axis=0)       # [n, C]
        cls_byte = (cls_byte & (j < lengths)[:, None])[:, None, :]
        nxt = _step_masks(cr, active, cls_byte)          # [n, w]
        acc = (nxt & accept) != 0
        if cr.anchor_end:
            acc = acc & ((j + 1) == lengths)[:, None]
        ends = jnp.where(acc, j + 1, ends)
        return (nxt, ends), None

    init = (jnp.zeros((n, w), jnp.uint64),
            jnp.full((n, w), -1, jnp.int32))
    (_, ends), _ = jax.lax.scan(
        body, init, (jnp.arange(w, dtype=jnp.int32), data.T))
    return ends
