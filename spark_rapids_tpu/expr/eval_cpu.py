"""CPU columnar expression evaluator — the fallback path and parity oracle.

Role analog: in the reference, anything not on the GPU runs on stock Spark
CPU execution (reference: README.md:28-31, RapidsMeta convertIfNeeded keeps
original CPU nodes).  We are standalone, so this module *is* our "stock CPU
Spark": an independent implementation of the same SQL semantics used both as
the CPU fallback execution path and as the oracle in the dual-session parity
test harness (reference: SparkQueryCompareTestSuite.scala:153-161).

Deliberately different algorithms from eval_tpu (Python str ops instead of
byte matrices, numpy datetime64 instead of civil-day math, scalar murmur3) so
shared bugs between the two paths are unlikely.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Any, Optional

import numpy as np
import pyarrow as pa

from spark_rapids_tpu import dtypes as dt
from spark_rapids_tpu.expr import ir


@dataclass
class CpuVal:
    dtype: dt.DType
    data: np.ndarray      # numeric np array, or object array of str for STRING
    valid: np.ndarray     # bool

    def masked(self) -> np.ndarray:
        return self.data


def evaluate(e: ir.Expression, table: pa.Table) -> CpuVal:
    fn = _DISPATCH.get(type(e))
    if fn is None:
        raise NotImplementedError(f"CPU eval for {type(e).__name__}")
    return fn(e, table)


def to_arrow_array(v: CpuVal) -> pa.Array:
    mask = ~v.valid
    if v.dtype.is_nested:
        py = [None if not v.valid[i] else v.data[i]
              for i in range(len(v.data))]
        return pa.array(py, type=v.dtype.to_arrow())
    if v.dtype.is_string:
        py = [None if mask[i] else v.data[i] for i in range(len(v.data))]
        return pa.array(py, type=pa.string())
    if v.dtype.id == dt.TypeId.TIMESTAMP_US:
        return pa.array(v.data.astype("datetime64[us]"),
                        type=pa.timestamp("us", tz="UTC"), mask=mask)
    if v.dtype.id == dt.TypeId.DATE32:
        return pa.array(v.data.astype(np.int32).astype("datetime64[D]"),
                        type=pa.date32(), mask=mask)
    return pa.array(v.data, type=v.dtype.to_arrow(), mask=mask)


def from_arrow_array(arr, dtype: dt.DType) -> CpuVal:
    if isinstance(arr, pa.ChunkedArray):
        arr = arr.combine_chunks()
    n = len(arr)
    valid = ~np.asarray(arr.is_null())
    if dtype.is_nested:
        py = arr.to_pylist()
        data = np.empty(n, dtype=object)
        for i, v in enumerate(py):
            data[i] = v
        return CpuVal(dtype, data, valid)
    if dtype.is_string:
        data = np.array([s if s is not None else "" for s in arr.to_pylist()],
                        dtype=object)
        return CpuVal(dtype, data, valid)
    if pa.types.is_timestamp(arr.type):
        vals = arr.cast(pa.timestamp("us")).to_numpy(zero_copy_only=False)
        data = vals.astype("datetime64[us]").astype(np.int64)
        data = np.where(valid, data, 0)
        return CpuVal(dtype, data, valid)
    if pa.types.is_date32(arr.type):
        vals = arr.to_numpy(zero_copy_only=False)
        data = vals.astype("datetime64[D]").astype(np.int64).astype(np.int32)
        data = np.where(valid, data, 0)
        return CpuVal(dtype, data, valid)
    filled = arr.fill_null(False if dtype.is_bool else 0)
    data = filled.to_numpy(zero_copy_only=False).astype(dtype.to_np())
    return CpuVal(dtype, data, valid)


# ---------------------------------------------------------------------------

def _lit(e: ir.Literal, table: pa.Table) -> CpuVal:
    n = table.num_rows
    d = e.dtype
    if e.value is None:
        dtype = d if d != dt.NULL else dt.BOOL
        data = np.array([""] * n, dtype=object) if dtype.is_string else \
            np.zeros(n, dtype=dtype.to_np())
        return CpuVal(dtype, data, np.zeros(n, dtype=bool))
    if d.is_string:
        return CpuVal(d, np.array([e.value] * n, dtype=object),
                      np.ones(n, dtype=bool))
    v = e.value
    if d.id == dt.TypeId.DATE32 and not isinstance(v, (int, np.integer)):
        v = (np.datetime64(v, "D") - np.datetime64(0, "D")).astype(int)
    if d.id == dt.TypeId.TIMESTAMP_US and not isinstance(v, (int, np.integer)):
        v = (np.datetime64(v, "us") - np.datetime64(0, "us")).astype(int)
    return CpuVal(d, np.full(n, v, dtype=d.to_np()), np.ones(n, dtype=bool))


def _bound(e: ir.BoundReference, table: pa.Table) -> CpuVal:
    return from_arrow_array(table.column(e.ordinal), e.dtype)


def _alias(e, table):
    return evaluate(e.children[0], table)


def _bin_arith(op):
    def f(e, table):
        l, r = evaluate(e.left, table), evaluate(e.right, table)
        tgt = e.dtype.to_np()
        with np.errstate(all="ignore"):
            out = op(l.data.astype(tgt), r.data.astype(tgt)).astype(tgt)
        return CpuVal(e.dtype, out, l.valid & r.valid)
    return f


def _div(e, table):
    l, r = evaluate(e.left, table), evaluate(e.right, table)
    a, b = l.data.astype(np.float64), r.data.astype(np.float64)
    nz = b != 0
    with np.errstate(all="ignore"):
        out = np.where(nz, a / np.where(nz, b, 1), 0.0)
    return CpuVal(e.dtype, out, l.valid & r.valid & nz)


def _idiv(e, table):
    l, r = evaluate(e.left, table), evaluate(e.right, table)
    a, b = l.data.astype(np.int64), r.data.astype(np.int64)
    nz = b != 0
    bb = np.where(nz, b, 1)
    q = np.trunc(a / bb).astype(np.int64)  # trunc toward zero like Java
    # large int64 precision: redo exactly with floor then fix
    qf = a // bb
    rem = a - qf * bb
    qf = np.where((rem != 0) & ((a < 0) != (b < 0)), qf + 1, qf)
    return CpuVal(e.dtype, np.where(nz, qf, 0), l.valid & r.valid & nz)


def _mod(e, table):
    l, r = evaluate(e.left, table), evaluate(e.right, table)
    tgt = e.dtype.to_np()
    a, b = l.data.astype(tgt), r.data.astype(tgt)
    if e.dtype.is_floating:
        nz = b != 0
        with np.errstate(all="ignore"):
            m = np.fmod(a, np.where(nz, b, 1))
    else:
        nz = b != 0
        bb = np.where(nz, b, 1)
        m = np.where(nz, np.fmod(a, bb), 0)
    return CpuVal(e.dtype, np.where(nz, m, 0), l.valid & r.valid & nz)


def _pmod(e, table):
    l, r = evaluate(e.left, table), evaluate(e.right, table)
    tgt = e.dtype.to_np()
    a, b = l.data.astype(tgt), r.data.astype(tgt)
    nz = b != 0
    bb = np.where(nz, b, 1)
    with np.errstate(all="ignore"):
        m = np.fmod(a, bb)
        m = np.where((m != 0) & ((m < 0) != (bb < 0)), m + bb, m)
    return CpuVal(e.dtype, np.where(nz, m, 0), l.valid & r.valid & nz)


def _neg(e, table):
    c = evaluate(e.child, table)
    return CpuVal(e.dtype, -c.data, c.valid)


def _abs(e, table):
    c = evaluate(e.child, table)
    return CpuVal(e.dtype, np.abs(c.data), c.valid)


def _cmp(op_name):
    def f(e, table):
        l, r = evaluate(e.left, table), evaluate(e.right, table)
        if l.dtype.is_string:
            a, b = l.data, r.data
            if op_name == "eq":
                out = np.array([x == y for x, y in zip(a, b)])
            elif op_name == "lt":
                out = np.array([x < y for x, y in zip(a, b)])
            elif op_name == "le":
                out = np.array([x <= y for x, y in zip(a, b)])
            elif op_name == "gt":
                out = np.array([x > y for x, y in zip(a, b)])
            else:
                out = np.array([x >= y for x, y in zip(a, b)])
            if len(out) == 0:
                out = np.zeros(0, dtype=bool)
            return CpuVal(dt.BOOL, out, l.valid & r.valid)
        tgt = dt.promote(l.dtype, r.dtype).to_np() if l.dtype != r.dtype \
            else l.dtype.to_np()
        a, b = l.data.astype(tgt), r.data.astype(tgt)
        if np.issubdtype(tgt, np.floating):
            an, bn = np.isnan(a), np.isnan(b)
            with np.errstate(invalid="ignore"):
                if op_name == "eq":
                    out = np.where(an | bn, an & bn, a == b)
                elif op_name == "lt":
                    out = np.where(an, False, np.where(bn, True, a < b))
                elif op_name == "le":
                    out = np.where(bn, True, np.where(an, False, a <= b))
                elif op_name == "gt":
                    out = np.where(bn, False, np.where(an, True, a > b))
                else:
                    out = np.where(an, True, np.where(bn, False, a >= b))
        else:
            ops = {"eq": np.equal, "lt": np.less, "le": np.less_equal,
                   "gt": np.greater, "ge": np.greater_equal}
            out = ops[op_name](a, b)
        return CpuVal(dt.BOOL, out, l.valid & r.valid)
    return f


def _and(e, table):
    l, r = evaluate(e.left, table), evaluate(e.right, table)
    known_false = (l.valid & ~l.data.astype(bool)) | \
                  (r.valid & ~r.data.astype(bool))
    valid = (l.valid & r.valid) | known_false
    val = l.data.astype(bool) & r.data.astype(bool) & ~known_false
    return CpuVal(dt.BOOL, val, valid)


def _or(e, table):
    l, r = evaluate(e.left, table), evaluate(e.right, table)
    known_true = (l.valid & l.data.astype(bool)) | \
                 (r.valid & r.data.astype(bool))
    valid = (l.valid & r.valid) | known_true
    val = (l.data.astype(bool) | r.data.astype(bool)) | known_true
    return CpuVal(dt.BOOL, val, valid)


def _not(e, table):
    c = evaluate(e.child, table)
    return CpuVal(dt.BOOL, ~c.data.astype(bool), c.valid)


def _in(e, table):
    v = evaluate(e.children[0], table)
    n = len(v.data)
    hit = np.zeros(n, dtype=bool)
    has_null = any(i is None for i in e.items)
    for item in e.items:
        if item is None:
            continue
        if v.dtype.is_string:
            hit |= np.array([x == item for x in v.data], dtype=bool) \
                if n else np.zeros(0, bool)
        elif v.dtype.is_floating and isinstance(item, float) and \
                math.isnan(item):
            hit |= np.isnan(v.data)
        else:
            if v.dtype.id == dt.TypeId.DATE32 and \
                    not isinstance(item, (int, np.integer)):
                item = int((np.datetime64(item, "D") -
                            np.datetime64(0, "D")).astype(int))
            elif v.dtype.id == dt.TypeId.TIMESTAMP_US and \
                    not isinstance(item, (int, np.integer)):
                item = int((np.datetime64(item, "us") -
                            np.datetime64(0, "us")).astype(int))
            hit |= (v.data == np.array(item).astype(v.data.dtype))
    valid = v.valid & (hit | (not has_null))
    return CpuVal(dt.BOOL, hit, valid)


def _isnull(e, table):
    c = evaluate(e.child, table)
    return CpuVal(dt.BOOL, ~c.valid, np.ones_like(c.valid))


def _isnotnull(e, table):
    c = evaluate(e.child, table)
    return CpuVal(dt.BOOL, c.valid.copy(), np.ones_like(c.valid))


def _isnan(e, table):
    c = evaluate(e.child, table)
    out = np.isnan(c.data) if c.dtype.is_floating else \
        np.zeros_like(c.valid)
    return CpuVal(dt.BOOL, out & c.valid, np.ones_like(c.valid))


def _coalesce(e, table):
    vals = [evaluate(c, table) for c in e.children]
    out = vals[0]
    if e.dtype.is_string:
        data = out.data.copy()
    else:
        data = out.data.astype(e.dtype.to_np())
    valid = out.valid.copy()
    for v in vals[1:]:
        take = ~valid & v.valid
        if e.dtype.is_string:
            data[take] = v.data[take]
        else:
            data = np.where(take, v.data.astype(data.dtype), data)
        valid |= v.valid
    return CpuVal(e.dtype, data, valid)


def _nanvl(e, table):
    l, r = evaluate(e.left, table), evaluate(e.right, table)
    tgt = e.dtype.to_np()
    a, b = l.data.astype(tgt), r.data.astype(tgt)
    use_b = np.isnan(a)
    return CpuVal(e.dtype, np.where(use_b, b, a),
                  np.where(use_b, r.valid, l.valid))


def _if(e, table):
    p = evaluate(e.children[0], table)
    t = evaluate(e.children[1], table)
    f = evaluate(e.children[2], table)
    cond = p.data.astype(bool) & p.valid
    if e.dtype.is_string:
        data = np.where(cond, t.data, f.data).astype(object)
    else:
        tgt = e.dtype.to_np()
        data = np.where(cond, t.data.astype(tgt), f.data.astype(tgt))
    return CpuVal(e.dtype, data, np.where(cond, t.valid, f.valid))


def _casewhen(e, table):
    n = table.num_rows
    els = e.else_value()
    if els is not None:
        cur = evaluate(els, table)
        data, valid = cur.data.copy(), cur.valid.copy()
        if not e.dtype.is_string:
            data = data.astype(e.dtype.to_np())
    else:
        data = np.array([""] * n, dtype=object) if e.dtype.is_string \
            else np.zeros(n, dtype=e.dtype.to_np())
        valid = np.zeros(n, dtype=bool)
    undecided = np.ones(n, dtype=bool)
    for cond_e, val_e in e.branches():
        c = evaluate(cond_e, table)
        v = evaluate(val_e, table)
        take = undecided & c.data.astype(bool) & c.valid
        if e.dtype.is_string:
            data[take] = v.data[take]
        else:
            data = np.where(take, v.data.astype(data.dtype), data)
        valid = np.where(take, v.valid, valid)
        undecided &= ~(c.data.astype(bool) & c.valid)
    return CpuVal(e.dtype, data, valid)


def _dunary(fn):
    def f(e, table):
        c = evaluate(e.child, table)
        with np.errstate(all="ignore"):
            out = fn(c.data.astype(np.float64))
        return CpuVal(e.dtype, out, c.valid)
    return f


def _log(e, table):
    c = evaluate(e.child, table)
    x = c.data.astype(np.float64)
    ok = x > 0
    with np.errstate(all="ignore"):
        out = np.where(ok, np.log(np.where(ok, x, 1)), 0.0)
    return CpuVal(e.dtype, out, c.valid & ok)


def _logbase(base):
    def f(e, table):
        c = evaluate(e.child, table)
        x = c.data.astype(np.float64)
        ok = x > 0
        with np.errstate(all="ignore"):
            out = np.where(ok, np.log(np.where(ok, x, 1)) / math.log(base),
                           0.0)
        return CpuVal(e.dtype, out, c.valid & ok)
    return f


def _log1p(e, table):
    c = evaluate(e.child, table)
    x = c.data.astype(np.float64)
    ok = x > -1
    with np.errstate(all="ignore"):
        out = np.where(ok, np.log1p(np.where(ok, x, 0)), 0.0)
    return CpuVal(e.dtype, out, c.valid & ok)


def _java_long_cast(x: np.ndarray) -> np.ndarray:
    """Java (long) cast: NaN->0, saturate exactly at int64 bounds.

    float64 cannot represent INT64_MAX (rounds up to 2^63), so the
    saturation must be done with explicit masks, not clip+astype.
    """
    imin, imax = np.iinfo(np.int64).min, np.iinfo(np.int64).max
    x = np.nan_to_num(x, nan=0.0, posinf=np.inf, neginf=-np.inf)
    hi = x >= 2.0 ** 63
    lo = x <= -(2.0 ** 63)
    safe = np.clip(x, -(2.0 ** 63), np.nextafter(2.0 ** 63, 0))
    with np.errstate(invalid="ignore"):
        out = safe.astype(np.int64)
    return np.where(hi, imax, np.where(lo, imin, out))


def _ceil(e, table):
    c = evaluate(e.child, table)
    return CpuVal(e.dtype,
                  _java_long_cast(np.ceil(c.data.astype(np.float64))),
                  c.valid)


def _floor(e, table):
    c = evaluate(e.child, table)
    return CpuVal(e.dtype,
                  _java_long_cast(np.floor(c.data.astype(np.float64))),
                  c.valid)


def _pow(e, table):
    l, r = evaluate(e.left, table), evaluate(e.right, table)
    with np.errstate(all="ignore"):
        out = np.power(l.data.astype(np.float64), r.data.astype(np.float64))
    return CpuVal(e.dtype, out, l.valid & r.valid)


def _atan2(e, table):
    l, r = evaluate(e.left, table), evaluate(e.right, table)
    return CpuVal(e.dtype, np.arctan2(l.data.astype(np.float64),
                                      r.data.astype(np.float64)),
                  l.valid & r.valid)


def _shift(kind):
    def f(e, table):
        l, r = evaluate(e.left, table), evaluate(e.right, table)
        nbits = l.data.dtype.itemsize * 8
        sh = (r.data.astype(np.int64) % nbits)
        if kind == "left":
            out = np.left_shift(l.data, sh.astype(l.data.dtype))
        elif kind == "right":
            out = np.right_shift(l.data, sh.astype(l.data.dtype))
        else:
            u = l.data.view(np.uint32 if nbits == 32 else np.uint64)
            out = np.right_shift(u, sh.astype(u.dtype)).view(l.data.dtype)
        return CpuVal(e.dtype, out, l.valid & r.valid)
    return f


_US_PER_DAY = 86400 * 1000 * 1000


def _cast(e, table):
    c = evaluate(e.child, table)
    src, tgt = c.dtype, e.to
    if e.child.dtype == dt.NULL:
        # a void child materializes as an all-null placeholder column
        # whose runtime dtype is arbitrary — the STATIC type is the truth
        src = dt.NULL
    if src == tgt:
        return CpuVal(tgt, c.data, c.valid)
    if src == dt.NULL:
        n = len(c.data)
        data = np.array([""] * n, dtype=object) if tgt.is_string \
            else np.zeros(n, dtype=tgt.to_np())
        return CpuVal(tgt, data, np.zeros(n, dtype=bool))
    if src.is_string and tgt.is_integral:
        n = len(c.data)
        out = np.zeros(n, dtype=tgt.to_np())
        valid = c.valid.copy()
        for i in range(n):
            if not valid[i]:
                continue
            s = c.data[i].strip()
            try:
                out[i] = np.array(int(s)).astype(tgt.to_np())
            except (ValueError, OverflowError):
                valid[i] = False
        return CpuVal(tgt, out, valid)
    if src.is_string and tgt.is_floating:
        n = len(c.data)
        out = np.zeros(n, dtype=tgt.to_np())
        valid = c.valid.copy()
        for i in range(n):
            if not valid[i]:
                continue
            s = c.data[i].strip()
            # Python float() accepts '_' separators; Spark does not
            if "_" in s:
                valid[i] = False
                continue
            try:
                out[i] = float(s)
            except ValueError:
                valid[i] = False
        return CpuVal(tgt, out, valid)
    if src.is_string and tgt.is_bool:
        n = len(c.data)
        out = np.zeros(n, dtype=bool)
        valid = c.valid.copy()
        for i in range(n):
            if not valid[i]:
                continue
            s = c.data[i].strip().lower()
            if s in ("t", "true", "y", "yes", "1"):
                out[i] = True
            elif s in ("f", "false", "n", "no", "0"):
                out[i] = False
            else:
                valid[i] = False
        return CpuVal(tgt, out, valid)
    if src.is_string and tgt.id == dt.TypeId.DATE32:
        import datetime as _dtm
        n = len(c.data)
        out = np.zeros(n, dtype=np.int32)
        valid = c.valid.copy()
        for i in range(n):
            if not valid[i]:
                continue
            s = c.data[i].strip()
            try:
                d = _dtm.date.fromisoformat(s)
                if len(s) != 10:
                    raise ValueError(s)  # Spark needs zero-padded
                out[i] = (d - _dtm.date(1970, 1, 1)).days
            except ValueError:
                valid[i] = False
        return CpuVal(tgt, out, valid)
    if src.is_string and tgt.id == dt.TypeId.TIMESTAMP_US:
        # the engine's documented (incompat-gated) surface:
        # 'yyyy-MM-dd[ HH:mm:ss[.f{1,6}]]', UTC only — the oracle
        # implements EXACTLY that grammar so CPU/TPU agree
        import datetime as _dtm
        import re as _re
        pat = _re.compile(
            r"(\d{4})-(\d{2})-(\d{2})"
            r"(?:[ T](\d{2}):(\d{2}):(\d{2})(?:\.(\d{1,6}))?)?")
        n = len(c.data)
        out = np.zeros(n, dtype=np.int64)
        valid = c.valid.copy()
        epoch = _dtm.datetime(1970, 1, 1, tzinfo=_dtm.timezone.utc)
        us_td = _dtm.timedelta(microseconds=1)
        for i in range(n):
            if not valid[i]:
                continue
            s = c.data[i].strip()
            mo = pat.fullmatch(s)
            if not mo:
                valid[i] = False
                continue
            try:
                frac = (mo.group(7) or "").ljust(6, "0")
                ts = _dtm.datetime(
                    int(mo.group(1)), int(mo.group(2)),
                    int(mo.group(3)), int(mo.group(4) or 0),
                    int(mo.group(5) or 0), int(mo.group(6) or 0),
                    int(frac or 0), tzinfo=_dtm.timezone.utc)
                out[i] = (ts - epoch) // us_td
            except ValueError:
                valid[i] = False
        return CpuVal(tgt, out, valid)
    if tgt.is_string:
        out = np.array([_spark_str(x, src) for x in
                        (c.data if not src.is_string else c.data)],
                       dtype=object)
        return CpuVal(tgt, out, c.valid)
    if src.id == dt.TypeId.DATE32 and tgt.id == dt.TypeId.TIMESTAMP_US:
        return CpuVal(tgt, c.data.astype(np.int64) * _US_PER_DAY, c.valid)
    if src.id == dt.TypeId.TIMESTAMP_US and tgt.id == dt.TypeId.DATE32:
        return CpuVal(tgt, (c.data // _US_PER_DAY).astype(np.int32), c.valid)
    if src.is_bool and tgt.is_numeric:
        return CpuVal(tgt, c.data.astype(tgt.to_np()), c.valid)
    if src.is_numeric and tgt.is_bool:
        return CpuVal(tgt, c.data != 0, c.valid)
    if src.is_floating and tgt.is_integral:
        x = np.nan_to_num(c.data, nan=0.0)
        info = np.iinfo(tgt.to_np())
        x = np.clip(np.trunc(x), float(info.min), float(info.max))
        return CpuVal(tgt, x.astype(tgt.to_np()), c.valid)
    if src.is_numeric and tgt.is_numeric:
        return CpuVal(tgt, c.data.astype(tgt.to_np()), c.valid)
    if src.id == dt.TypeId.TIMESTAMP_US and tgt.id == dt.TypeId.INT64:
        return CpuVal(tgt, c.data // (1000 * 1000), c.valid)
    raise NotImplementedError(f"CPU cast {src.name}->{tgt.name}")


def _spark_str(x, src: dt.DType) -> str:
    if src.is_bool:
        return "true" if x else "false"
    if src.is_floating:
        if math.isnan(x):
            return "NaN"
        if math.isinf(x):
            return "Infinity" if x > 0 else "-Infinity"
        return repr(float(x))
    if src.id == dt.TypeId.DATE32:
        return str(np.datetime64(int(x), "D"))
    if src.id == dt.TypeId.TIMESTAMP_US:
        # Spark: space separator, fraction trimmed of trailing zeros
        s = str(np.datetime64(int(x), "us")).replace("T", " ")
        if "." in s:
            s = s.rstrip("0").rstrip(".")
        return s
    return str(x)


# strings — Python str ops (independent of the byte-matrix kernels)

def _str_unary(fn):
    def f(e, table):
        c = evaluate(e.child, table)
        out = np.array([fn(s) for s in c.data], dtype=object)
        return CpuVal(dt.STRING, out, c.valid)
    return f


def _ascii_upper(s: str) -> str:
    return "".join(chr(ord(ch) - 32) if "a" <= ch <= "z" else ch
                   for ch in s)


def _ascii_lower(s: str) -> str:
    return "".join(chr(ord(ch) + 32) if "A" <= ch <= "Z" else ch
                   for ch in s)


def _length(e, table):
    c = evaluate(e.child, table)
    out = np.array([len(s) for s in c.data], dtype=np.int32) \
        if len(c.data) else np.zeros(0, np.int32)
    return CpuVal(dt.INT32, out, c.valid)


def _substring(e, table):
    s = evaluate(e.children[0], table)
    pos = evaluate(e.children[1], table)
    ln = evaluate(e.children[2], table)
    n = len(s.data)
    out = np.empty(n, dtype=object)
    for i in range(n):
        st, p, L = s.data[i], int(pos.data[i]), int(ln.data[i])
        if p > 0:
            start = p - 1
        elif p < 0:
            start = max(len(st) + p, 0)
        else:
            start = 0
        out[i] = st[start:start + max(L, 0)]
    return CpuVal(dt.STRING, out, s.valid & pos.valid & ln.valid)


def _str_pred(fn):
    def f(e, table):
        l, r = evaluate(e.left, table), evaluate(e.right, table)
        n = len(l.data)
        valid = l.valid & r.valid
        out = np.array(
            [fn(a, b) if valid[i] else False
             for i, (a, b) in enumerate(zip(l.data, r.data))],
            dtype=bool) if n else np.zeros(0, bool)
        return CpuVal(dt.BOOL, out, valid)
    return f


def _like_match(s: str, pat: str) -> bool:
    import re
    rx = re.escape(pat).replace("%", ".*").replace("_", ".")
    # re.escape escapes % as %, _ as _ in py3.7+: they are not escaped
    return re.fullmatch(rx, s, flags=re.DOTALL) is not None


def _rlike_match(s: str, pat: str) -> bool:
    import re
    return re.search(pat, s) is not None


def _concat(e, table):
    vals = [evaluate(c, table) for c in e.children]
    n = len(vals[0].data)
    out = np.array(["".join(v.data[i] for v in vals) for i in range(n)],
                   dtype=object) if n else np.zeros(0, object)
    valid = np.ones(n, dtype=bool)
    for v in vals:
        valid &= v.valid
    return CpuVal(dt.STRING, out, valid)


def _locate(e, table):
    sub = evaluate(e.children[0], table)
    s = evaluate(e.children[1], table)
    start = evaluate(e.children[2], table)
    n = len(s.data)
    out = np.zeros(n, dtype=np.int32)
    for i in range(n):
        st = int(start.data[i])
        if sub.data[i] == "":
            out[i] = st
        else:
            out[i] = s.data[i].find(sub.data[i], max(st - 1, 0)) + 1
    return CpuVal(dt.INT32, out, sub.valid & s.valid & start.valid)


def _pad(left: bool):
    def f(e, table):
        s = evaluate(e.children[0], table)
        ln = evaluate(e.children[1], table)
        pad = evaluate(e.children[2], table)
        n = len(s.data)
        out = np.empty(n, dtype=object)
        for i in range(n):
            st, L, p = s.data[i], max(int(ln.data[i]), 0), pad.data[i]
            if len(st) >= L:
                out[i] = st[:L]
            elif not p:
                out[i] = st
            else:
                fill = (p * ((L - len(st)) // len(p) + 1))[:L - len(st)]
                out[i] = fill + st if left else st + fill
        return CpuVal(dt.STRING, out, s.valid & ln.valid & pad.valid)
    return f


def _str_replace(e, table):
    s = evaluate(e.children[0], table)
    search = evaluate(e.children[1], table)
    repl = evaluate(e.children[2], table)
    n = len(s.data)
    out = np.empty(n, dtype=object)
    for i in range(n):
        out[i] = s.data[i].replace(search.data[i], repl.data[i]) \
            if search.data[i] else s.data[i]
    return CpuVal(dt.STRING, out, s.valid & search.valid & repl.valid)


def _substring_index(e, table):
    s = evaluate(e.children[0], table)
    delim = e.children[1].value
    count = int(e.children[2].value)
    n = len(s.data)
    out = np.empty(n, dtype=object)
    for i in range(n):
        st = s.data[i]
        if not delim or count == 0:
            out[i] = ""
        elif count > 0:
            out[i] = delim.join(st.split(delim)[:count])
        else:
            out[i] = delim.join(st.split(delim)[count:])
    return CpuVal(dt.STRING, out, s.valid.copy())


def _string_split(e, table):
    import re as _re
    s = evaluate(e.children[0], table)
    pattern = e.children[1].value
    limit = int(e.children[2].value)
    rx = _re.compile(pattern)
    n = len(s.data)
    out = np.empty(n, dtype=object)
    for i in range(n):
        # Spark: limit<=0 keeps all (dropping no trailing empties for
        # limit<0, dropping them for limit=0); limit>0 caps the count
        # (note limit=1 = no split; re.split's maxsplit=0 means unlimited)
        if limit == 1:
            parts = [s.data[i]]
        else:
            parts = rx.split(s.data[i], maxsplit=limit - 1 if limit > 0
                             else 0)
        if limit == 0:
            while parts and parts[-1] == "":
                parts.pop()
        out[i] = parts
    return CpuVal(e.dtype, out, s.valid.copy())


def _java_replacement(template: str):
    """Parse a Java Matcher-style replacement ($N group refs, backslash
    escapes the next char) into a function(match) -> str, so Python's
    template rules (octal escapes, bad-escape errors) never apply."""
    segments = []   # str literal | int group index
    i, buf = 0, []
    while i < len(template):
        ch = template[i]
        if ch == "\\" and i + 1 < len(template):
            buf.append(template[i + 1])
            i += 2
        elif ch == "$" and i + 1 < len(template) \
                and template[i + 1].isdigit():
            if buf:
                segments.append("".join(buf))
                buf = []
            j = i + 1
            while j < len(template) and template[j].isdigit():
                j += 1
            segments.append(int(template[i + 1:j]))
            i = j
        else:
            buf.append(ch)
            i += 1
    if buf:
        segments.append("".join(buf))

    def expand(m):
        return "".join(seg if isinstance(seg, str)
                       else (m.group(seg) or "") for seg in segments)
    return expand


def _regexp_replace(e, table):
    import re as _re
    s = evaluate(e.children[0], table)
    rx = _re.compile(e.children[1].value)
    repl = evaluate(e.children[2], table)
    n = len(s.data)
    out = np.empty(n, dtype=object)
    if isinstance(e.children[2], ir.Literal):
        fn = _java_replacement(e.children[2].value)
        for i in range(n):
            out[i] = rx.sub(fn, s.data[i])
    else:
        for i in range(n):
            out[i] = rx.sub(_java_replacement(repl.data[i]), s.data[i])
    return CpuVal(dt.STRING, out, s.valid & repl.valid)


def _md5(e, table):
    import hashlib
    s = evaluate(e.child, table)
    n = len(s.data)
    out = np.empty(n, dtype=object)
    for i in range(n):
        out[i] = hashlib.md5(
            s.data[i].encode("utf-8")).hexdigest()
    return CpuVal(dt.STRING, out, s.valid.copy())


def _at_least_n_non_nulls(e, table):
    n = table.num_rows
    count = np.zeros(n, dtype=np.int32)
    for c in e.children:
        v = evaluate(c, table)
        ok = v.valid.copy()
        if v.dtype.id in (dt.TypeId.FLOAT32, dt.TypeId.FLOAT64):
            ok &= ~np.isnan(np.where(v.valid, v.data, 0.0))
        count += ok.astype(np.int32)
    return CpuVal(dt.BOOL, count >= e.n, np.ones(n, dtype=bool))


def _from_unixtime(e, table):
    v = evaluate(e.child, table)
    secs = v.data.astype(np.int64)
    days = secs // 86400
    rem = secs - days * 86400
    dates = days.astype("datetime64[D]")
    n = len(secs)
    out = np.empty(n, dtype=object)
    for i in range(n):
        r = int(rem[i])
        out[i] = (f"{str(dates[i])} "
                  f"{r // 3600:02d}:{(r // 60) % 60:02d}:{r % 60:02d}")
    return CpuVal(dt.STRING, out, v.valid.copy())


def _input_file_name(e, table):
    from spark_rapids_tpu.exec import context
    n = table.num_rows
    return CpuVal(dt.STRING,
                  np.full(n, context.input_file(), dtype=object),
                  np.ones(n, dtype=bool))


def _initcap(e, table):
    def cap(s: str) -> str:
        out = []
        prev_sep = True
        for ch in s:
            if prev_sep and "a" <= ch <= "z":
                out.append(chr(ord(ch) - 32))
            elif not prev_sep and "A" <= ch <= "Z":
                out.append(chr(ord(ch) + 32))
            else:
                out.append(ch)
            prev_sep = ch == " "
        return "".join(out)
    return _str_unary(cap)(e, table)


# temporal via numpy datetime64 (independent of civil-day math)

def _datefield(which):
    def f(e, table):
        c = evaluate(e.child, table)
        if c.dtype.id == dt.TypeId.TIMESTAMP_US:
            days = (c.data // _US_PER_DAY).astype("datetime64[D]")
        else:
            days = c.data.astype(np.int64).astype("datetime64[D]")
        Y = days.astype("datetime64[Y]")
        M = days.astype("datetime64[M]")
        if which == "year":
            out = Y.astype(int) + 1970
        elif which == "month":
            out = (M - Y).astype(int) + 1
        elif which == "day":
            out = (days - M).astype(int) + 1
        elif which == "quarter":
            out = ((M - Y).astype(int)) // 3 + 1
        elif which == "dayofweek":
            # numpy: 1970-01-01 is Thursday
            out = ((days.astype(int) + 4) % 7) + 1
        elif which == "dayofyear":
            out = (days - Y).astype(int) + 1
        elif which == "weekofyear":
            di = days.astype(int)
            wd = (di + 3) % 7
            thursday = di - wd + 3
            td = thursday.astype("datetime64[D]")
            ty = td.astype("datetime64[Y]")
            jan1 = ty.astype("datetime64[D]").astype(int)
            out = (thursday - jan1) // 7 + 1
        else:
            raise AssertionError(which)
        return CpuVal(dt.INT32, out.astype(np.int32), c.valid)
    return f


def _timefield(which):
    def f(e, table):
        c = evaluate(e.child, table)
        us = np.mod(c.data, _US_PER_DAY)
        if which == "hour":
            out = us // (3600 * 1000 * 1000)
        elif which == "minute":
            out = (us // (60 * 1000 * 1000)) % 60
        else:
            out = (us // (1000 * 1000)) % 60
        return CpuVal(dt.INT32, out.astype(np.int32), c.valid)
    return f


def _dateadd(sign):
    def f(e, table):
        l, r = evaluate(e.left, table), evaluate(e.right, table)
        out = (l.data.astype(np.int64) +
               sign * r.data.astype(np.int64)).astype(np.int32)
        return CpuVal(dt.DATE32, out, l.valid & r.valid)
    return f


def _datediff(e, table):
    l, r = evaluate(e.left, table), evaluate(e.right, table)

    def days(v):
        if v.dtype.id == dt.TypeId.TIMESTAMP_US:
            return v.data // _US_PER_DAY
        return v.data.astype(np.int64)
    return CpuVal(dt.INT32, (days(l) - days(r)).astype(np.int32),
                  l.valid & r.valid)


def _unix_ts(e, table):
    c = evaluate(e.child, table)
    return CpuVal(dt.INT64, c.data // (1000 * 1000), c.valid)


# scalar Spark murmur3 (independent reference implementation)

def _m3_mix_k1(k1: int) -> int:
    k1 = (k1 * 0xCC9E2D51) & 0xFFFFFFFF
    k1 = ((k1 << 15) | (k1 >> 17)) & 0xFFFFFFFF
    return (k1 * 0x1B873593) & 0xFFFFFFFF


def _m3_mix_h1(h1: int, k1: int) -> int:
    h1 ^= k1
    h1 = ((h1 << 13) | (h1 >> 19)) & 0xFFFFFFFF
    return (h1 * 5 + 0xE6546B64) & 0xFFFFFFFF


def _m3_fmix(h1: int, length: int) -> int:
    h1 ^= length
    h1 ^= h1 >> 16
    h1 = (h1 * 0x85EBCA6B) & 0xFFFFFFFF
    h1 ^= h1 >> 13
    h1 = (h1 * 0xC2B2AE35) & 0xFFFFFFFF
    h1 ^= h1 >> 16
    return h1


def murmur3_int(v: int, seed: int) -> int:
    return _m3_fmix(_m3_mix_h1(seed & 0xFFFFFFFF,
                               _m3_mix_k1(v & 0xFFFFFFFF)), 4)


def murmur3_long(v: int, seed: int) -> int:
    lo = v & 0xFFFFFFFF
    hi = (v >> 32) & 0xFFFFFFFF
    h1 = _m3_mix_h1(seed & 0xFFFFFFFF, _m3_mix_k1(lo))
    h1 = _m3_mix_h1(h1, _m3_mix_k1(hi))
    return _m3_fmix(h1, 8)


def murmur3_bytes(b: bytes, seed: int) -> int:
    h1 = seed & 0xFFFFFFFF
    nfull = len(b) // 4
    for i in range(nfull):
        word = int.from_bytes(b[i * 4:i * 4 + 4], "little")
        h1 = _m3_mix_h1(h1, _m3_mix_k1(word))
    for i in range(nfull * 4, len(b)):
        byte = b[i]
        if byte >= 128:
            byte -= 256  # sign extension like the JVM byte
        h1 = _m3_mix_h1(h1, _m3_mix_k1(byte & 0xFFFFFFFF))
    return _m3_fmix(h1, len(b))


def _to_signed32(v: int) -> int:
    v &= 0xFFFFFFFF
    return v - (1 << 32) if v >= (1 << 31) else v


def _murmur3(e: ir.Murmur3Hash, table):
    import struct
    n = table.num_rows
    out = np.zeros(n, dtype=np.int32)
    vals = [evaluate(c, table) for c in e.children]
    for i in range(n):
        h = e.seed
        for v in vals:
            if not v.valid[i]:
                continue
            d = v.dtype
            if d.is_string:
                h = murmur3_bytes(v.data[i].encode("utf-8"), h)
            elif d.id in (dt.TypeId.INT64, dt.TypeId.TIMESTAMP_US):
                h = murmur3_long(int(v.data[i]), h)
            elif d.id == dt.TypeId.FLOAT64:
                x = float(v.data[i])
                if x == 0.0:
                    x = 0.0
                bits = struct.unpack("<q", struct.pack("<d", x))[0]
                h = murmur3_long(bits, h)
            elif d.id == dt.TypeId.FLOAT32:
                x = np.float32(v.data[i])
                if x == 0.0:
                    x = np.float32(0.0)
                bits = struct.unpack("<i", struct.pack("<f", x))[0]
                h = murmur3_int(bits, h)
            elif d.is_bool:
                h = murmur3_int(1 if v.data[i] else 0, h)
            else:
                h = murmur3_int(int(v.data[i]), h)
        out[i] = _to_signed32(h)
    return CpuVal(dt.INT32, out, np.ones(n, dtype=bool))


def _knownfloat(e, table):
    c = evaluate(e.child, table)
    if c.dtype.is_floating:
        x = np.where(np.isnan(c.data), np.nan, c.data)
        x = np.where(x == 0.0, 0.0, x)
        return CpuVal(c.dtype, x.astype(c.data.dtype), c.valid)
    return c


def _partition_id(e, table):
    from spark_rapids_tpu.exec import context
    pid, _ = context.get()
    n = table.num_rows
    return CpuVal(dt.INT32, np.full(n, int(pid), dtype=np.int32),
                  np.ones(n, dtype=bool))


def _monotonic_id(e, table):
    from spark_rapids_tpu.exec import context
    pid, off = context.get()
    n = table.num_rows
    base = (int(pid) << 33) + int(off)
    return CpuVal(dt.INT64, base + np.arange(n, dtype=np.int64),
                  np.ones(n, dtype=bool))


def _rand(e: ir.Rand, table):
    # parity with the TPU path is impossible (different RNG); Rand is tagged
    # nondeterministic and excluded from parity comparisons
    rng = np.random.default_rng(e.seed)
    return CpuVal(dt.FLOAT64, rng.random(table.num_rows),
                  np.ones(table.num_rows, dtype=bool))


def _py_value(v: CpuVal, i: int) -> Any:
    """Row i of a CpuVal as the Python value a UDF would receive."""
    if not v.valid[i]:
        return None
    if v.dtype.is_string:
        return str(v.data[i])
    if v.dtype.id == dt.TypeId.DATE32:
        return (np.datetime64(0, "D") +
                np.timedelta64(int(v.data[i]), "D")).astype(object)
    if v.dtype.id == dt.TypeId.TIMESTAMP_US:
        return (np.datetime64(0, "us") +
                np.timedelta64(int(v.data[i]), "us")).astype(object)
    if v.dtype.is_bool:
        return bool(v.data[i])
    if v.dtype.is_floating:
        return float(v.data[i])
    return int(v.data[i])


def _python_udf(e: "ir.PythonUDF", table):
    if getattr(e, "vectorized", False):
        # a pandas UDF must be extracted into an ArrowEvalPython exec by
        # the planner; evaluating it row-wise would hand scalars to a
        # function expecting Series — fail loudly instead of silently
        raise NotImplementedError(
            f"pandas UDF {e.udf_name!r} in an unsupported position "
            "(supported: projections, filters, sort keys, aggregate args)")
    args = [evaluate(c, table) for c in e.children]
    n = table.num_rows
    rt = e.return_type
    valid = np.ones(n, dtype=bool)
    if rt.is_string:
        data: np.ndarray = np.empty(n, dtype=object)
    else:
        data = np.zeros(n, dtype=rt.to_np())
    for i in range(n):
        # PySpark semantics: null inputs are passed to the function as None
        # (so None-aware UDFs behave identically here and when compiled to
        # IR `is None` checks); a UDF that cannot handle None raises, as it
        # would under PySpark
        out = e.func(*[_py_value(a, i) for a in args])
        if out is None:
            valid[i] = False
            if rt.is_string:
                data[i] = ""
        elif rt.is_string:
            data[i] = str(out)
        else:
            # a result that does not fit the declared type becomes null,
            # matching PySpark's per-row coercion behavior rather than
            # failing the job
            try:
                if rt.id == dt.TypeId.DATE32:
                    data[i] = (np.datetime64(out, "D") -
                               np.datetime64(0, "D")).astype(np.int64)
                elif rt.id == dt.TypeId.TIMESTAMP_US:
                    data[i] = (np.datetime64(out, "us") -
                               np.datetime64(0, "us")).astype(np.int64)
                else:
                    data[i] = out
            except (OverflowError, ValueError, TypeError):
                valid[i] = False
    return CpuVal(rt, data, valid)



# ---------------------------------------------------------------------------
# complex types (reference: complexTypeExtractors.scala, collectionOps)
# ---------------------------------------------------------------------------

def _size(e: ir.Size, table):
    v = evaluate(e.children[0], table)
    n = len(v.data)
    out = np.full(n, -1, dtype=np.int32)   # Spark 3.0 legacy: size(null)=-1
    for i in range(n):
        if v.valid[i]:
            out[i] = len(v.data[i])
    return CpuVal(dt.INT32, out, np.ones(n, dtype=bool))


def _get_array_item(e: ir.GetArrayItem, table):
    v = evaluate(e.children[0], table)
    o = evaluate(e.children[1], table)
    el = e.dtype
    n = len(v.data)
    valid = np.zeros(n, dtype=bool)
    if el.is_string or el.is_nested:
        data = np.empty(n, dtype=object)
        data[:] = "" if el.is_string else None
    else:
        data = np.zeros(n, dtype=el.to_np())
    for i in range(n):
        if not (v.valid[i] and o.valid[i]):
            continue
        idx = int(o.data[i])
        lst = v.data[i]
        if 0 <= idx < len(lst) and lst[idx] is not None:
            x = lst[idx]
            if el.id == dt.TypeId.DATE32 and not isinstance(x, (int, np.integer)):
                x = (np.datetime64(x, "D") - np.datetime64(0, "D")).astype(int)
            if el.id == dt.TypeId.TIMESTAMP_US and not isinstance(x, (int, np.integer)):
                x = (np.datetime64(x, "us") - np.datetime64(0, "us")).astype(int)
            data[i] = x
            valid[i] = True
    return CpuVal(el, data, valid)


def _get_map_value(e: ir.GetMapValue, table):
    v = evaluate(e.children[0], table)
    k = evaluate(e.children[1], table)
    val_t = e.dtype
    n = len(v.data)
    valid = np.zeros(n, dtype=bool)
    if val_t.is_string or val_t.is_nested:
        data = np.empty(n, dtype=object)
        data[:] = "" if val_t.is_string else None
    else:
        data = np.zeros(n, dtype=val_t.to_np())
    for i in range(n):
        if not (v.valid[i] and k.valid[i]):
            continue
        for kk, vv in (v.data[i] or []):
            if kk == k.data[i] and vv is not None:
                data[i] = vv
                valid[i] = True
                break
    return CpuVal(val_t, data, valid)


def _element_at(e: ir.ElementAt, table):
    v = evaluate(e.children[0], table)
    o = evaluate(e.children[1], table)
    el = e.dtype
    n = len(v.data)
    valid = np.zeros(n, dtype=bool)
    if el.is_string or el.is_nested:
        data = np.empty(n, dtype=object)
        data[:] = "" if el.is_string else None
    else:
        data = np.zeros(n, dtype=el.to_np())
    for i in range(n):
        if not (v.valid[i] and o.valid[i]):
            continue
        k = int(o.data[i])
        lst = v.data[i]
        idx = k - 1 if k > 0 else (len(lst) + k if k < 0 else -1)
        if 0 <= idx < len(lst) and lst[idx] is not None:
            data[i] = lst[idx]
            valid[i] = True
    return CpuVal(el, data, valid)


def _array_contains(e: ir.ArrayContains, table):
    v = evaluate(e.children[0], table)
    x = evaluate(e.children[1], table)
    n = len(v.data)
    data = np.zeros(n, dtype=bool)
    valid = np.zeros(n, dtype=bool)
    for i in range(n):
        if not (v.valid[i] and x.valid[i]):
            continue
        lst = v.data[i]
        if x.data[i] in [y for y in lst if y is not None]:
            data[i] = True
            valid[i] = True
        elif any(y is None for y in lst):
            valid[i] = False   # 3-valued: unknown
        else:
            valid[i] = True
    return CpuVal(dt.BOOL, data, valid)


def _create_array(e: ir.CreateArray, table):
    vals = [evaluate(c, table) for c in e.children]
    n = table.num_rows
    el = e.dtype.element
    data = np.empty(n, dtype=object)
    for i in range(n):
        row = []
        for v in vals:
            if not v.valid[i]:
                row.append(None)
            else:
                x = v.data[i]
                row.append(x.item() if isinstance(x, np.generic) else x)
        data[i] = row
    return CpuVal(e.dtype, data, np.ones(n, dtype=bool))


def _sort_array(e: ir.SortArray, table):
    v = evaluate(e.children[0], table)
    n = len(v.data)
    data = np.empty(n, dtype=object)
    for i in range(n):
        if not v.valid[i]:
            data[i] = None
            continue
        lst = v.data[i]
        nulls = [x for x in lst if x is None]
        rest = sorted([x for x in lst if x is not None],
                      reverse=not e.ascending)
        data[i] = (nulls + rest) if e.ascending else (rest + nulls)
    return CpuVal(v.dtype, data, v.valid.copy())


_DISPATCH = {
    ir.Literal: _lit,
    ir.BoundReference: _bound,
    ir.Alias: _alias,
    ir.Add: _bin_arith(np.add),
    ir.Subtract: _bin_arith(np.subtract),
    ir.Multiply: _bin_arith(np.multiply),
    ir.Divide: _div,
    ir.IntegralDivide: _idiv,
    ir.Remainder: _mod,
    ir.Pmod: _pmod,
    ir.UnaryMinus: _neg,
    ir.UnaryPositive: lambda e, t: evaluate(e.child, t),
    ir.Abs: _abs,
    ir.EqualTo: _cmp("eq"),
    ir.LessThan: _cmp("lt"),
    ir.LessThanOrEqual: _cmp("le"),
    ir.GreaterThan: _cmp("gt"),
    ir.GreaterThanOrEqual: _cmp("ge"),
    ir.And: _and,
    ir.Or: _or,
    ir.Not: _not,
    ir.In: _in,
    ir.IsNull: _isnull,
    ir.IsNotNull: _isnotnull,
    ir.IsNan: _isnan,
    ir.Coalesce: _coalesce,
    ir.NaNvl: _nanvl,
    ir.If: _if,
    ir.CaseWhen: _casewhen,
    ir.Sqrt: _dunary(np.sqrt),
    ir.Exp: _dunary(np.exp),
    ir.Log: _log,
    ir.Log2: _logbase(2.0),
    ir.Log10: _logbase(10.0),
    ir.Log1p: _log1p,
    ir.Expm1: _dunary(np.expm1),
    ir.Sin: _dunary(np.sin),
    ir.Cos: _dunary(np.cos),
    ir.Tan: _dunary(np.tan),
    ir.Sinh: _dunary(np.sinh),
    ir.Cosh: _dunary(np.cosh),
    ir.Tanh: _dunary(np.tanh),
    ir.Asin: _dunary(np.arcsin),
    ir.Acos: _dunary(np.arccos),
    ir.Atan: _dunary(np.arctan),
    ir.Cbrt: _dunary(np.cbrt),
    ir.ToDegrees: _dunary(np.degrees),
    ir.ToRadians: _dunary(np.radians),
    ir.Rint: _dunary(np.round),
    ir.Signum: _dunary(np.sign),
    ir.Ceil: _ceil,
    ir.Floor: _floor,
    ir.Pow: _pow,
    ir.Atan2: _atan2,
    ir.ShiftLeft: _shift("left"),
    ir.ShiftRight: _shift("right"),
    ir.ShiftRightUnsigned: _shift("unsigned"),
    ir.Cast: _cast,
    ir.Upper: _str_unary(_ascii_upper),
    ir.Lower: _str_unary(_ascii_lower),
    ir.Length: _length,
    ir.Substring: _substring,
    ir.StartsWith: _str_pred(lambda a, b: a.startswith(b)),
    ir.EndsWith: _str_pred(lambda a, b: a.endswith(b)),
    ir.Contains: _str_pred(lambda a, b: b in a),
    ir.Like: _str_pred(_like_match),
    ir.RLike: _str_pred(_rlike_match),
    ir.Concat: _concat,
    ir.StringTrim: _str_unary(lambda s: s.strip(" ")),
    ir.StringTrimLeft: _str_unary(lambda s: s.lstrip(" ")),
    ir.StringTrimRight: _str_unary(lambda s: s.rstrip(" ")),
    ir.InitCap: _initcap,
    ir.StringReverse: _str_unary(lambda s: s[::-1]),
    ir.StringReplace: _str_replace,
    ir.SubstringIndex: _substring_index,
    ir.StringSplit: _string_split,
    ir.RegExpReplace: _regexp_replace,
    ir.Md5: _md5,
    ir.AtLeastNNonNulls: _at_least_n_non_nulls,
    ir.FromUnixTime: _from_unixtime,
    ir.InputFileName: _input_file_name,
    ir.StringLocate: _locate,
    ir.LPad: _pad(True),
    ir.RPad: _pad(False),
    ir.Year: _datefield("year"),
    ir.Month: _datefield("month"),
    ir.DayOfMonth: _datefield("day"),
    ir.DayOfYear: _datefield("dayofyear"),
    ir.DayOfWeek: _datefield("dayofweek"),
    ir.WeekOfYear: _datefield("weekofyear"),
    ir.Quarter: _datefield("quarter"),
    ir.Hour: _timefield("hour"),
    ir.Minute: _timefield("minute"),
    ir.Second: _timefield("second"),
    ir.DateAdd: _dateadd(1),
    ir.DateSub: _dateadd(-1),
    ir.DateDiff: _datediff,
    ir.UnixTimestampFromTs: _unix_ts,
    ir.Murmur3Hash: _murmur3,
    ir.PythonUDF: _python_udf,
    ir.KnownFloatingPointNormalized: _knownfloat,
    ir.SparkPartitionID: _partition_id,
    ir.MonotonicallyIncreasingID: _monotonic_id,
    ir.Rand: _rand,
    ir.Size: _size,
    ir.GetArrayItem: _get_array_item,
    ir.GetMapValue: _get_map_value,
    ir.ArrayContains: _array_contains,
    ir.ElementAt: _element_at,
    ir.CreateArray: _create_array,
    ir.SortArray: _sort_array,
}
