from spark_rapids_tpu.expr import ir  # noqa: F401
