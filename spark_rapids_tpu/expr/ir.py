"""Expression IR: the engine's Catalyst-expression analog.

The reference wraps Spark Catalyst expressions in ``GpuExpression`` shape-class
bases (reference: sql-plugin/.../GpuExpressions.scala:63-230 —
GpuUnaryExpression/GpuBinaryExpression/CudfUnaryExpression) and registers ~150
per-class replacement rules (reference: GpuOverrides.scala:586-1714).

Here the IR *is* the expression tree (we are standalone — there is no Catalyst
above us).  Two independent evaluators consume it:

  * :mod:`spark_rapids_tpu.expr.eval_tpu` — jax/XLA, device columnar
  * :mod:`spark_rapids_tpu.expr.eval_cpu` — pyarrow.compute, host columnar
    (the CPU-fallback execution path AND the parity oracle for tests)

Null semantics follow Spark SQL: most ops propagate null; AND/OR use
three-valued logic; division by zero yields null; NaN handling follows Spark's
"NaN is greatest, NaN == NaN" total order in comparisons/sorts.
"""

from __future__ import annotations

import datetime as _dt
from typing import Any, Iterable, List, Optional, Sequence, Tuple

from spark_rapids_tpu import dtypes as dt


class Expression:
    """Base IR node. After ``bind``, every node has .dtype and .nullable."""

    children: Tuple["Expression", ...] = ()
    dtype: Optional[dt.DType] = None
    nullable: bool = True

    def with_children(self, children: Sequence["Expression"]) -> "Expression":
        clone = self.__class__.__new__(self.__class__)
        clone.__dict__.update(self.__dict__)
        clone.children = tuple(children)
        return clone

    # resolution ------------------------------------------------------------
    def resolve(self) -> None:
        """Compute dtype/nullable from resolved children. Override."""
        raise NotImplementedError(type(self).__name__)

    @property
    def name(self) -> str:
        return type(self).__name__

    def sql(self) -> str:
        args = ", ".join(c.sql() for c in self.children)
        return f"{self.name}({args})"

    def __repr__(self) -> str:
        return self.sql()


# ---------------------------------------------------------------------------
# Leaves
# ---------------------------------------------------------------------------

class Literal(Expression):
    def __init__(self, value: Any, dtype: Optional[dt.DType] = None):
        self.value = value
        self.dtype = dtype if dtype is not None else infer_literal_type(value)
        self.nullable = value is None

    def resolve(self) -> None:
        pass

    def sql(self) -> str:
        return repr(self.value)


def infer_literal_type(value: Any) -> dt.DType:
    if value is None:
        return dt.NULL
    if isinstance(value, bool):
        return dt.BOOL
    if isinstance(value, int):
        return dt.INT32 if -(2 ** 31) <= value < 2 ** 31 else dt.INT64
    if isinstance(value, float):
        return dt.FLOAT64
    if isinstance(value, str):
        return dt.STRING
    if isinstance(value, _dt.datetime):
        return dt.TIMESTAMP_US
    if isinstance(value, _dt.date):
        return dt.DATE32
    raise TypeError(f"cannot infer literal type for {value!r}")


class UnresolvedAttribute(Expression):
    """API-level column reference, replaced by BoundReference at bind time."""

    def __init__(self, name_: str):
        self.attr_name = name_

    def resolve(self) -> None:
        raise RuntimeError(f"unresolved attribute '{self.attr_name}'")

    def sql(self) -> str:
        return self.attr_name


class BoundReference(Expression):
    """Column bound to an ordinal in the input batch.

    Analog of GpuBoundReference (reference: GpuBoundAttribute.scala).
    """

    def __init__(self, ordinal: int, dtype: dt.DType, nullable: bool = True,
                 name_: str = ""):
        self.ordinal = ordinal
        self.dtype = dtype
        self.nullable = nullable
        self.ref_name = name_

    def resolve(self) -> None:
        pass

    def sql(self) -> str:
        return self.ref_name or f"input[{self.ordinal}]"


class Alias(Expression):
    def __init__(self, child: Expression, alias: str):
        self.children = (child,)
        self.alias = alias

    def resolve(self) -> None:
        self.dtype = self.children[0].dtype
        self.nullable = self.children[0].nullable

    def sql(self) -> str:
        return f"{self.children[0].sql()} AS {self.alias}"


def output_name(e: Expression) -> str:
    if isinstance(e, Alias):
        return e.alias
    if isinstance(e, UnresolvedAttribute):
        return e.attr_name
    if isinstance(e, BoundReference) and e.ref_name:
        return e.ref_name
    return e.sql()


# ---------------------------------------------------------------------------
# Shape-class bases (GpuUnaryExpression / GpuBinaryExpression analogs)
# ---------------------------------------------------------------------------

class UnaryExpression(Expression):
    def __init__(self, child: Expression):
        self.children = (child,)

    @property
    def child(self) -> Expression:
        return self.children[0]


class BinaryExpression(Expression):
    def __init__(self, left: Expression, right: Expression):
        self.children = (left, right)

    @property
    def left(self) -> Expression:
        return self.children[0]

    @property
    def right(self) -> Expression:
        return self.children[1]


# ---------------------------------------------------------------------------
# Arithmetic (reference: org/.../rapids/arithmetic.scala)
# ---------------------------------------------------------------------------

class _NumericBinary(BinaryExpression):
    def resolve(self) -> None:
        l, r = self.left.dtype, self.right.dtype
        if not (l.is_numeric and r.is_numeric):
            raise TypeError(f"{self.name} requires numeric args, got {l},{r}")
        self.dtype = dt.promote(l, r)
        self.nullable = self.left.nullable or self.right.nullable


class Add(_NumericBinary):
    pass


class Subtract(_NumericBinary):
    pass


class Multiply(_NumericBinary):
    pass


class Divide(BinaryExpression):
    """Spark `/`: always double; x/0 -> null."""

    def resolve(self) -> None:
        self.dtype = dt.FLOAT64
        self.nullable = True


class IntegralDivide(BinaryExpression):
    """Spark `div`: long division; x div 0 -> null."""

    def resolve(self) -> None:
        self.dtype = dt.INT64
        self.nullable = True


class Remainder(_NumericBinary):
    def resolve(self) -> None:
        super().resolve()
        self.nullable = True  # x % 0 -> null


class Pmod(_NumericBinary):
    def resolve(self) -> None:
        super().resolve()
        self.nullable = True


class UnaryMinus(UnaryExpression):
    def resolve(self) -> None:
        self.dtype = self.child.dtype
        self.nullable = self.child.nullable


class UnaryPositive(UnaryExpression):
    def resolve(self) -> None:
        self.dtype = self.child.dtype
        self.nullable = self.child.nullable


class Abs(UnaryExpression):
    def resolve(self) -> None:
        self.dtype = self.child.dtype
        self.nullable = self.child.nullable


# ---------------------------------------------------------------------------
# Predicates & logic (reference: org/.../rapids/predicates.scala)
# ---------------------------------------------------------------------------

class _Comparison(BinaryExpression):
    def resolve(self) -> None:
        self.dtype = dt.BOOL
        self.nullable = self.left.nullable or self.right.nullable


class EqualTo(_Comparison):
    pass


class LessThan(_Comparison):
    pass


class LessThanOrEqual(_Comparison):
    pass


class GreaterThan(_Comparison):
    pass


class GreaterThanOrEqual(_Comparison):
    pass


class And(BinaryExpression):
    def resolve(self) -> None:
        self.dtype = dt.BOOL
        self.nullable = self.left.nullable or self.right.nullable


class Or(BinaryExpression):
    def resolve(self) -> None:
        self.dtype = dt.BOOL
        self.nullable = self.left.nullable or self.right.nullable


class Not(UnaryExpression):
    def resolve(self) -> None:
        self.dtype = dt.BOOL
        self.nullable = self.child.nullable


class In(Expression):
    """value IN (literals...). Analog of GpuInSet (GpuInSet.scala:98)."""

    def __init__(self, value: Expression, items: Sequence[Any]):
        self.children = (value,)
        self.items = tuple(items)

    def resolve(self) -> None:
        self.dtype = dt.BOOL
        self.nullable = (self.children[0].nullable or
                         any(i is None for i in self.items))

    def sql(self) -> str:
        return f"{self.children[0].sql()} IN {self.items}"


# ---------------------------------------------------------------------------
# Null handling (reference: nullExpressions.scala)
# ---------------------------------------------------------------------------

class IsNull(UnaryExpression):
    def resolve(self) -> None:
        self.dtype = dt.BOOL
        self.nullable = False


class IsNotNull(UnaryExpression):
    def resolve(self) -> None:
        self.dtype = dt.BOOL
        self.nullable = False


class IsNan(UnaryExpression):
    def resolve(self) -> None:
        self.dtype = dt.BOOL
        self.nullable = False


class Coalesce(Expression):
    def __init__(self, *exprs: Expression):
        self.children = tuple(exprs)

    def resolve(self) -> None:
        dtypes = [c.dtype for c in self.children if c.dtype != dt.NULL]
        self.dtype = dtypes[0] if dtypes else dt.NULL
        self.nullable = all(c.nullable for c in self.children)


class NaNvl(BinaryExpression):
    def resolve(self) -> None:
        self.dtype = dt.promote(self.left.dtype, self.right.dtype) \
            if self.left.dtype != self.right.dtype else self.left.dtype
        self.nullable = self.left.nullable or self.right.nullable


# ---------------------------------------------------------------------------
# Conditionals (reference: conditionalExpressions.scala — GpuIf/GpuCaseWhen,
# side-effect-free whole-column eval of all branches + ifElse merge)
# ---------------------------------------------------------------------------

def _common_branch_type(dtypes: List[dt.DType]) -> dt.DType:
    """Coerce conditional-branch result types (Spark's analysis-time
    TypeCoercion/findWiderTypeForTwo for If/CaseWhen): equal types pass
    through, numerics promote, string absorbs numerics (Spark renders the
    numeric branch as a string), anything else is an analysis error."""
    non_null = [d for d in dtypes if d != dt.NULL]
    if not non_null:
        return dt.NULL
    if any(d.is_string for d in non_null):
        if all(d.is_string or d.is_numeric for d in non_null):
            return dt.STRING
        raise TypeError(f"incompatible IF/CASE branch types {non_null}")
    out = non_null[0]
    for d in non_null[1:]:
        out = dt.promote(out, d)  # identity for equal types
    return out


def _coerce_branch(v: Expression, target: dt.DType) -> Expression:
    """Wrap a branch value in a resolved Cast when its type is narrower than
    the coerced branch type (the evaluators then see uniform branch types)."""
    if v.dtype == target or target == dt.NULL:
        return v
    c = Cast(v, target)
    c.resolve()
    return c


class If(Expression):
    def __init__(self, pred: Expression, t: Expression, f: Expression):
        self.children = (pred, t, f)

    def resolve(self) -> None:
        pred, t, f = self.children
        self.dtype = _common_branch_type([t.dtype, f.dtype])
        self.children = (pred, _coerce_branch(t, self.dtype),
                         _coerce_branch(f, self.dtype))
        self.nullable = t.nullable or f.nullable


class CaseWhen(Expression):
    """branches: [(cond, value), ...], else_value optional."""

    def __init__(self, branches: Sequence[Tuple[Expression, Expression]],
                 else_value: Optional[Expression] = None):
        self.n_branches = len(branches)
        flat: List[Expression] = []
        for c, v in branches:
            flat.extend((c, v))
        if else_value is not None:
            flat.append(else_value)
        self.has_else = else_value is not None
        self.children = tuple(flat)

    def branches(self) -> List[Tuple[Expression, Expression]]:
        return [(self.children[2 * i], self.children[2 * i + 1])
                for i in range(self.n_branches)]

    def else_value(self) -> Optional[Expression]:
        return self.children[-1] if self.has_else else None

    def resolve(self) -> None:
        vals = [v for _, v in self.branches()]
        if self.has_else:
            vals.append(self.children[-1])
        self.dtype = _common_branch_type([v.dtype for v in vals])
        new_children = list(self.children)
        for i in range(self.n_branches):
            new_children[2 * i + 1] = _coerce_branch(self.children[2 * i + 1],
                                                     self.dtype)
        if self.has_else:
            new_children[-1] = _coerce_branch(self.children[-1], self.dtype)
        self.children = tuple(new_children)
        self.nullable = (not self.has_else) or any(v.nullable for v in vals)


# ---------------------------------------------------------------------------
# Math (reference: org/.../rapids/mathExpressions.scala)
# ---------------------------------------------------------------------------

class _DoubleUnary(UnaryExpression):
    def resolve(self) -> None:
        self.dtype = dt.FLOAT64
        self.nullable = True  # domain errors -> null in Spark for some


class Sqrt(_DoubleUnary):
    pass


class Exp(_DoubleUnary):
    pass


class Log(_DoubleUnary):
    pass


class Log2(_DoubleUnary):
    pass


class Log10(_DoubleUnary):
    pass


class Log1p(_DoubleUnary):
    pass


class Expm1(_DoubleUnary):
    pass


class Sin(_DoubleUnary):
    pass


class Cos(_DoubleUnary):
    pass


class Tan(_DoubleUnary):
    pass


class Sinh(_DoubleUnary):
    pass


class Cosh(_DoubleUnary):
    pass


class Tanh(_DoubleUnary):
    pass


class Asin(_DoubleUnary):
    pass


class Acos(_DoubleUnary):
    pass


class Atan(_DoubleUnary):
    pass


class Cbrt(_DoubleUnary):
    pass


class ToDegrees(_DoubleUnary):
    pass


class ToRadians(_DoubleUnary):
    pass


class Rint(_DoubleUnary):
    pass


class Signum(_DoubleUnary):
    pass


class Ceil(UnaryExpression):
    def resolve(self) -> None:
        self.dtype = dt.INT64
        self.nullable = self.child.nullable


class Floor(UnaryExpression):
    def resolve(self) -> None:
        self.dtype = dt.INT64
        self.nullable = self.child.nullable


class Pow(BinaryExpression):
    def resolve(self) -> None:
        self.dtype = dt.FLOAT64
        self.nullable = self.left.nullable or self.right.nullable


class Atan2(BinaryExpression):
    def resolve(self) -> None:
        self.dtype = dt.FLOAT64
        self.nullable = self.left.nullable or self.right.nullable


class ShiftLeft(BinaryExpression):
    def resolve(self) -> None:
        self.dtype = self.left.dtype
        self.nullable = self.left.nullable or self.right.nullable


class ShiftRight(BinaryExpression):
    def resolve(self) -> None:
        self.dtype = self.left.dtype
        self.nullable = self.left.nullable or self.right.nullable


class ShiftRightUnsigned(BinaryExpression):
    def resolve(self) -> None:
        self.dtype = self.left.dtype
        self.nullable = self.left.nullable or self.right.nullable


# ---------------------------------------------------------------------------
# Cast (reference: GpuCast.scala:190-861)
# ---------------------------------------------------------------------------

class Cast(UnaryExpression):
    def __init__(self, child: Expression, to: dt.DType, ansi: bool = False):
        super().__init__(child)
        self.to = to
        self.ansi = ansi

    def resolve(self) -> None:
        self.dtype = self.to
        # string->numeric etc. can produce null on malformed input
        self.nullable = self.child.nullable or self.child.dtype.is_string

    def sql(self) -> str:
        return f"CAST({self.child.sql()} AS {self.to.name})"


# ---------------------------------------------------------------------------
# Strings (reference: org/.../rapids/stringFunctions.scala)
# ---------------------------------------------------------------------------

class Upper(UnaryExpression):
    def resolve(self) -> None:
        self.dtype = dt.STRING
        self.nullable = self.child.nullable


class Lower(UnaryExpression):
    def resolve(self) -> None:
        self.dtype = dt.STRING
        self.nullable = self.child.nullable


class Length(UnaryExpression):
    def resolve(self) -> None:
        self.dtype = dt.INT32
        self.nullable = self.child.nullable


class Substring(Expression):
    """1-based start like Spark substring(str, pos, len)."""

    def __init__(self, s: Expression, pos: Expression, length: Expression):
        self.children = (s, pos, length)

    def resolve(self) -> None:
        self.dtype = dt.STRING
        self.nullable = any(c.nullable for c in self.children)


class StartsWith(BinaryExpression):
    def resolve(self) -> None:
        self.dtype = dt.BOOL
        self.nullable = self.left.nullable or self.right.nullable


class EndsWith(BinaryExpression):
    def resolve(self) -> None:
        self.dtype = dt.BOOL
        self.nullable = self.left.nullable or self.right.nullable


class Contains(BinaryExpression):
    def resolve(self) -> None:
        self.dtype = dt.BOOL
        self.nullable = self.left.nullable or self.right.nullable


class Like(BinaryExpression):
    """SQL LIKE with % and _ wildcards; pattern must be a literal."""

    def resolve(self) -> None:
        self.dtype = dt.BOOL
        self.nullable = self.left.nullable or self.right.nullable


class RLike(BinaryExpression):
    """SQL RLIKE / regexp predicate: Java Matcher.find semantics with a
    literal pattern (reference: Spark300Shims.scala:183-247 GpuRLike —
    likewise incompat-flagged for regex dialect deltas).  On TPU the
    pattern compiles to the bitmask NFA of expr/device_regex.py; the
    planner falls back for patterns outside that subset."""

    def resolve(self) -> None:
        self.dtype = dt.BOOL
        self.nullable = self.left.nullable or self.right.nullable


class Concat(Expression):
    def __init__(self, *parts: Expression):
        self.children = tuple(parts)

    def resolve(self) -> None:
        self.dtype = dt.STRING
        self.nullable = any(c.nullable for c in self.children)


class StringTrim(UnaryExpression):
    def resolve(self) -> None:
        self.dtype = dt.STRING
        self.nullable = self.child.nullable


class StringTrimLeft(UnaryExpression):
    def resolve(self) -> None:
        self.dtype = dt.STRING
        self.nullable = self.child.nullable


class StringTrimRight(UnaryExpression):
    def resolve(self) -> None:
        self.dtype = dt.STRING
        self.nullable = self.child.nullable


class StringReverse(UnaryExpression):
    """reverse(str): bytes reversed within the string length (ASCII;
    reference: GpuStringReverse via cudf strings::reverse)."""

    def resolve(self) -> None:
        self.dtype = dt.STRING
        self.nullable = self.child.nullable


class StringLocate(Expression):
    """locate(substr, str, start) -> 1-based position or 0."""

    def __init__(self, substr: Expression, s: Expression, start: Expression):
        self.children = (substr, s, start)

    def resolve(self) -> None:
        self.dtype = dt.INT32
        self.nullable = any(c.nullable for c in self.children)


class StringReplace(Expression):
    def __init__(self, s: Expression, search: Expression, replace: Expression):
        self.children = (s, search, replace)

    def resolve(self) -> None:
        self.dtype = dt.STRING
        self.nullable = any(c.nullable for c in self.children)


class InitCap(UnaryExpression):
    def resolve(self) -> None:
        self.dtype = dt.STRING
        self.nullable = self.child.nullable


class SubstringIndex(Expression):
    """substring_index(str, delim, count) — reference:
    stringFunctions.scala GpuSubstringIndex (literal delim/count)."""

    def __init__(self, s: Expression, delim: Expression,
                 count: Expression):
        self.children = (s, delim, count)

    def resolve(self) -> None:
        if not (isinstance(self.children[1], Literal)
                and isinstance(self.children[2], Literal)):
            raise TypeError("substring_index delimiter and count must be "
                            "literals")
        self.dtype = dt.STRING
        self.nullable = self.children[0].nullable


class StringSplit(Expression):
    """split(str, regex[, limit]) -> array<string> — reference:
    stringFunctions.scala GpuStringSplit (literal pattern)."""

    def __init__(self, s: Expression, pattern: Expression,
                 limit: Expression):
        self.children = (s, pattern, limit)

    def resolve(self) -> None:
        if not (isinstance(self.children[1], Literal)
                and isinstance(self.children[2], Literal)):
            raise TypeError("split pattern and limit must be literals")
        self.dtype = dt.DType(dt.TypeId.LIST, element=dt.STRING)
        self.nullable = self.children[0].nullable


class RegExpReplace(Expression):
    """regexp_replace(str, pattern, replacement) with a literal pattern
    (reference: shims Spark300Shims.scala:183-247 GpuRegExpReplace —
    likewise incompat-flagged for regex dialect differences)."""

    def __init__(self, s: Expression, pattern: Expression,
                 replacement: Expression):
        self.children = (s, pattern, replacement)

    def resolve(self) -> None:
        if not isinstance(self.children[1], Literal):
            raise TypeError("regexp_replace pattern must be a literal")
        self.dtype = dt.STRING
        self.nullable = self.children[0].nullable or \
            self.children[2].nullable


class Md5(UnaryExpression):
    """md5(col) -> 32-char hex string (reference: HashFunctions.scala
    GpuMd5)."""

    def resolve(self) -> None:
        if self.child.dtype != dt.STRING:
            raise TypeError("md5 requires a string input")
        self.dtype = dt.STRING
        self.nullable = self.child.nullable


class LPad(Expression):
    def __init__(self, s: Expression, length: Expression, pad: Expression):
        self.children = (s, length, pad)

    def resolve(self) -> None:
        self.dtype = dt.STRING
        self.nullable = any(c.nullable for c in self.children)


class RPad(Expression):
    def __init__(self, s: Expression, length: Expression, pad: Expression):
        self.children = (s, length, pad)

    def resolve(self) -> None:
        self.dtype = dt.STRING
        self.nullable = any(c.nullable for c in self.children)


# ---------------------------------------------------------------------------
# Date/time (reference: org/.../rapids/datetimeExpressions.scala; UTC only)
# ---------------------------------------------------------------------------

class _TemporalField(UnaryExpression):
    def resolve(self) -> None:
        self.dtype = dt.INT32
        self.nullable = self.child.nullable


class Year(_TemporalField):
    pass


class Month(_TemporalField):
    pass


class DayOfMonth(_TemporalField):
    pass


class DayOfYear(_TemporalField):
    pass


class DayOfWeek(_TemporalField):
    pass


class WeekOfYear(_TemporalField):
    pass


class Quarter(_TemporalField):
    pass


class Hour(_TemporalField):
    pass


class Minute(_TemporalField):
    pass


class Second(_TemporalField):
    pass


class DateAdd(BinaryExpression):
    def resolve(self) -> None:
        self.dtype = dt.DATE32
        self.nullable = self.left.nullable or self.right.nullable


class DateSub(BinaryExpression):
    def resolve(self) -> None:
        self.dtype = dt.DATE32
        self.nullable = self.left.nullable or self.right.nullable


class DateDiff(BinaryExpression):
    def resolve(self) -> None:
        self.dtype = dt.INT32
        self.nullable = self.left.nullable or self.right.nullable


class UnixTimestampFromTs(UnaryExpression):
    """timestamp -> seconds since epoch (int64)."""

    def resolve(self) -> None:
        self.dtype = dt.INT64
        self.nullable = self.child.nullable


class FromUnixTime(UnaryExpression):
    """seconds since epoch -> 'yyyy-MM-dd HH:mm:ss' string, UTC only
    (reference: datetimeExpressions.scala GpuFromUnixTime — the default
    format only, like the reference's supported subset)."""

    def resolve(self) -> None:
        if not self.child.dtype.is_numeric:
            raise TypeError("from_unixtime requires numeric seconds")
        self.dtype = dt.STRING
        self.nullable = self.child.nullable


class AtLeastNNonNulls(Expression):
    """true when >= n of the children are non-null (and non-NaN for
    floats) — reference: nullExpressions.scala GpuAtLeastNNonNulls."""

    def __init__(self, n: int, children: Sequence[Expression]):
        self.n = int(n)
        self.children = tuple(children)

    def resolve(self) -> None:
        self.dtype = dt.BOOL
        self.nullable = False


class InputFileName(Expression):
    """input_file_name(): path of the file feeding the current batch, or
    '' outside a file scan (reference: GpuInputFileBlock.scala
    GpuInputFileName; value threaded through a scan-scoped context)."""

    def __init__(self):
        self.children = ()

    def resolve(self) -> None:
        self.dtype = dt.STRING
        self.nullable = False


# ---------------------------------------------------------------------------
# Hash & misc (reference: HashFunctions.scala, GpuMurmur3Hash,
# GpuSparkPartitionID, GpuMonotonicallyIncreasingID, GpuRand)
# ---------------------------------------------------------------------------

class Murmur3Hash(Expression):
    """Spark-compatible murmur3_x86_32 over child columns; seed 42."""

    def __init__(self, children: Sequence[Expression], seed: int = 42):
        self.children = tuple(children)
        self.seed = seed

    def resolve(self) -> None:
        self.dtype = dt.INT32
        self.nullable = False


class SparkPartitionID(Expression):
    def resolve(self) -> None:
        self.dtype = dt.INT32
        self.nullable = False


class MonotonicallyIncreasingID(Expression):
    def resolve(self) -> None:
        self.dtype = dt.INT64
        self.nullable = False


class Rand(Expression):
    def __init__(self, seed: Optional[int] = None):
        self.seed = seed if seed is not None else 0

    def resolve(self) -> None:
        self.dtype = dt.FLOAT64
        self.nullable = False


class KnownFloatingPointNormalized(UnaryExpression):
    """NaN/-0.0 canonicalization marker (reference: NormalizeFloatingNumbers,
    FloatUtils.scala — parity-critical for agg/join keys)."""

    def resolve(self) -> None:
        self.dtype = self.child.dtype
        self.nullable = self.child.nullable


class PythonUDF(Expression):
    """Row-wise Python UDF — the CPU fallback when the UDF compiler cannot
    translate the function's bytecode into IR (the reference keeps the
    original ScalaUDF on CPU in the same case, udf-compiler/.../Plugin.scala:
    36-94).  Evaluated only by eval_cpu; the planner tags any node containing
    one as not-on-TPU."""

    def __init__(self, func, children: Sequence[Expression],
                 return_type: dt.DType, name_: str = "",
                 try_compile: bool = False, vectorized: bool = False):
        self.func = func
        self.children = tuple(children)
        self.return_type = return_type
        self.udf_name = name_ or getattr(func, "__name__", "udf")
        # when True, ``bind`` attempts bytecode->IR compilation once the
        # argument dtypes are known (the reference compiles at plan time via
        # a resolution rule, udf-compiler/.../Plugin.scala:36-94)
        self.try_compile = try_compile
        # when True this is a pandas (series->series) UDF: the planner
        # extracts it into an ArrowEvalPython exec that feeds a worker
        # process over Arrow IPC (GpuArrowEvalPythonExec analog)
        self.vectorized = vectorized

    def resolve(self) -> None:
        self.dtype = self.return_type
        self.nullable = True

    def sql(self) -> str:
        args = ", ".join(c.sql() for c in self.children)
        return f"{self.udf_name}({args})"


# ---------------------------------------------------------------------------
# Aggregate functions (reference: org/.../rapids/AggregateFunctions.scala —
# each is an update/merge CudfAggregate pair + final projection)
# ---------------------------------------------------------------------------

class AggregateExpression(Expression):
    """Base for aggregate functions; evaluated by the aggregate exec, never
    by the row-wise evaluators.

    ``distinct=True`` never reaches an exec: GroupedData.agg rewrites
    distinct aggregates into a double aggregate (dedup on (keys, child)
    first, plain aggregate second) — Spark's RewriteDistinctAggregates
    single-distinct shape."""

    def __init__(self, child: Optional[Expression],
                 distinct: bool = False):
        self.children = (child,) if child is not None else ()
        self.distinct = distinct

    @property
    def child(self) -> Optional[Expression]:
        return self.children[0] if self.children else None


class Count(AggregateExpression):
    def resolve(self) -> None:
        self.dtype = dt.INT64
        self.nullable = False


class Sum(AggregateExpression):
    def resolve(self) -> None:
        c = self.child.dtype
        self.dtype = dt.FLOAT64 if c.is_floating else dt.INT64
        self.nullable = True


class Min(AggregateExpression):
    def resolve(self) -> None:
        self.dtype = self.child.dtype
        self.nullable = True


class Max(AggregateExpression):
    def resolve(self) -> None:
        self.dtype = self.child.dtype
        self.nullable = True


class Average(AggregateExpression):
    def resolve(self) -> None:
        self.dtype = dt.FLOAT64
        self.nullable = True


class First(AggregateExpression):
    def __init__(self, child: Expression, ignore_nulls: bool = False):
        super().__init__(child)
        self.ignore_nulls = ignore_nulls

    def resolve(self) -> None:
        self.dtype = self.child.dtype
        self.nullable = True


class Last(AggregateExpression):
    def __init__(self, child: Expression, ignore_nulls: bool = False):
        super().__init__(child)
        self.ignore_nulls = ignore_nulls

    def resolve(self) -> None:
        self.dtype = self.child.dtype
        self.nullable = True


# ---------------------------------------------------------------------------
# Window functions (reference: GpuWindowExec.scala:92,
# GpuWindowExpression.scala:171-834 — count/sum/min/max/row_number/lead/lag
# over row frames and range frames)
# ---------------------------------------------------------------------------

class WindowFrame:
    """Frame spec. bounds: int offset, or None for UNBOUNDED;
    kind: 'rows' or 'range'. Defaults follow Spark: with ORDER BY ->
    RANGE UNBOUNDED PRECEDING..CURRENT ROW; without -> whole partition."""

    def __init__(self, kind: str = "rows",
                 start: Optional[int] = None, end: Optional[int] = 0):
        self.kind = kind
        self.start = start  # None = unbounded preceding
        self.end = end      # None = unbounded following; 0 = current row

    @property
    def is_unbounded_whole(self) -> bool:
        return self.start is None and self.end is None

    @property
    def is_unbounded_to_current(self) -> bool:
        return self.start is None and self.end == 0

    def __repr__(self):
        def b(v, side):
            if v is None:
                return f"UNBOUNDED {side}"
            if v == 0:
                return "CURRENT ROW"
            return f"{abs(v)} {'PRECEDING' if v < 0 else 'FOLLOWING'}"
        return (f"{self.kind.upper()} BETWEEN {b(self.start, 'PRECEDING')} "
                f"AND {b(self.end, 'FOLLOWING')}")


class WindowFunction(Expression):
    """Base for ranking/offset window functions (not plain aggregates)."""


class RowNumber(WindowFunction):
    def resolve(self) -> None:
        self.dtype = dt.INT32
        self.nullable = False


class Rank(WindowFunction):
    def resolve(self) -> None:
        self.dtype = dt.INT32
        self.nullable = False


class DenseRank(WindowFunction):
    def resolve(self) -> None:
        self.dtype = dt.INT32
        self.nullable = False


class Lead(WindowFunction):
    def __init__(self, child: Expression, offset: int = 1,
                 default: Optional[Any] = None):
        self.children = (child,)
        self.offset = offset
        self.default = default

    def resolve(self) -> None:
        self.dtype = self.children[0].dtype
        self.nullable = True


class Lag(WindowFunction):
    def __init__(self, child: Expression, offset: int = 1,
                 default: Optional[Any] = None):
        self.children = (child,)
        self.offset = offset
        self.default = default

    def resolve(self) -> None:
        self.dtype = self.children[0].dtype
        self.nullable = True


class WindowExpression(Expression):
    """function OVER (PARTITION BY ... ORDER BY ... frame)."""

    def __init__(self, function: Expression,
                 partition_by: Sequence[Expression],
                 order_by: Sequence = (),
                 frame: Optional[WindowFrame] = None):
        self.n_partition = len(partition_by)
        # store directions separately; expressions live in children so
        # binding rewrites them (SortOrder objects would go stale)
        self.order_dirs = tuple(
            (o.ascending, o.nulls_first_resolved) for o in order_by)
        order_exprs = [o.expr for o in order_by]
        if isinstance(function, AggregateExpression) and \
                getattr(function, "distinct", False):
            # the double-aggregate rewrite cannot apply inside a window
            raise NotImplementedError(
                "DISTINCT aggregates are not supported in window "
                "functions")
        self.children = (function, *partition_by, *order_exprs)
        if frame is None:
            if self.order_dirs:
                frame = WindowFrame("range", None, 0)
            else:
                frame = WindowFrame("rows", None, None)
        self.frame = frame

    @property
    def function(self) -> Expression:
        return self.children[0]

    @property
    def partition_exprs(self) -> Tuple[Expression, ...]:
        return self.children[1:1 + self.n_partition]

    @property
    def order_exprs(self) -> Tuple[Expression, ...]:
        return self.children[1 + self.n_partition:]

    def resolve(self) -> None:
        self.dtype = self.function.dtype
        self.nullable = self.function.nullable

    def sql(self) -> str:
        return (f"{self.function.sql()} OVER (...)")


# ---------------------------------------------------------------------------
# Binding & traversal
# ---------------------------------------------------------------------------

def transform(e: Expression, fn) -> Expression:
    """Bottom-up transform."""
    new_children = [transform(c, fn) for c in e.children]
    if new_children != list(e.children):
        e = e.with_children(new_children)
    out = fn(e)
    return out if out is not None else e


def bind(e: Expression, names: Sequence[str],
         dtypes: Sequence[dt.DType],
         nullables: Optional[Sequence[bool]] = None) -> Expression:
    """Replace UnresolvedAttribute with BoundReference and resolve types
    bottom-up.  Analog of GpuBindReferences (GpuBoundAttribute.scala)."""
    nullables = nullables if nullables is not None else [True] * len(names)

    def _bind(node: Expression) -> Expression:
        if isinstance(node, UnresolvedAttribute):
            if node.attr_name not in names:
                raise KeyError(f"column '{node.attr_name}' not in "
                               f"{list(names)}")
            i = list(names).index(node.attr_name)
            return BoundReference(i, dtypes[i], nullables[i], node.attr_name)
        if isinstance(node, GetItem):
            base = node.children[0]
            repl = GetMapValue(base, node.children[1]) \
                if base.dtype is not None and base.dtype.is_map \
                else GetArrayItem(base, node.children[1])
            repl.resolve()
            return repl
        if isinstance(node, ElementAt):
            base = node.children[0]
            if base.dtype is not None and base.dtype.is_map:
                repl = GetMapValue(base, node.children[1])
                repl.resolve()
                return repl
        if isinstance(node, PythonUDF) and node.try_compile:
            compiled = _try_compile_python_udf(node)
            if compiled is not None:
                return compiled
        node.resolve()
        return node

    return transform(e, _bind)


def _try_compile_python_udf(node: "PythonUDF") -> Optional[Expression]:
    """Bind-time UDF compilation: the node's children are already bound, so
    argument dtypes are known and the compiled tree can be fully resolved —
    any compile or type-resolution failure keeps the row-wise CPU UDF."""
    try:
        from spark_rapids_tpu import config as cfg
        from spark_rapids_tpu.api.session import TpuSparkSession
        s = TpuSparkSession._active
        if s is not None and not s.conf.get(cfg.UDF_COMPILER_ENABLED):
            return None
    except ImportError:
        pass
    from spark_rapids_tpu.udf import compiler
    try:
        compiled = compiler.compile_udf(node.func, list(node.children))
        out = Cast(compiled, node.return_type)
        transform(out, lambda n: n.resolve())
        return out
    except Exception:
        return None


def expr_eq(a: Expression, b: Expression) -> bool:
    """Structural equality on unresolved expression trees (the analyzer's
    semanticEquals role for our purposes).  Compares node type, children,
    and every non-child instance attribute (so Cast targets, ignore_nulls
    flags, distinct flags etc. participate)."""
    if type(a) is not type(b):
        return False
    if isinstance(a, BoundReference):
        return a.ordinal == b.ordinal
    skip = ("children", "nullable")
    ka = {k: v for k, v in a.__dict__.items() if k not in skip}
    kb = {k: v for k, v in b.__dict__.items() if k not in skip}
    if ka.keys() != kb.keys():
        return False
    for k in ka:
        va, vb = ka[k], kb[k]
        if isinstance(va, Expression) or isinstance(vb, Expression):
            if not (isinstance(va, Expression)
                    and isinstance(vb, Expression)
                    and expr_eq(va, vb)):
                return False
        elif va != vb:
            return False
    if len(a.children) != len(b.children):
        return False
    return all(expr_eq(x, y) for x, y in zip(a.children, b.children))


def collect(e: Expression, pred) -> List[Expression]:
    out = []

    def walk(n: Expression):
        if pred(n):
            out.append(n)
        for c in n.children:
            walk(c)

    walk(e)
    return out


def has_aggregates(e: Expression) -> bool:
    return bool(collect(e, lambda n: isinstance(n, AggregateExpression)))


# ---------------------------------------------------------------------------
# Complex types (reference: complexTypeExtractors.scala — GetArrayItem,
# GetMapValue; collectionOperations — Size; CreateArray; GpuGenerateExec's
# explode/posexplode generators, GpuGenerateExec.scala:101)
# ---------------------------------------------------------------------------

class Size(UnaryExpression):
    """size(array|map). Spark 3.0 default (legacy sizeOfNull): null -> -1."""

    def resolve(self) -> None:
        self.dtype = dt.INT32
        self.nullable = False  # null input yields -1, not null


class GetArrayItem(Expression):
    """array[ordinal] (0-based); null for out-of-range / null input."""

    def __init__(self, child: Expression, ordinal: Expression):
        self.children = (child, ordinal)

    def resolve(self) -> None:
        self.dtype = self.children[0].dtype.element
        self.nullable = True


class GetMapValue(Expression):
    """map[key]; null when absent. CPU-only (reference limits GPU maps to
    string->string literal-key lookups)."""

    def __init__(self, child: Expression, key: Expression):
        self.children = (child, key)

    def resolve(self) -> None:
        self.dtype = self.children[0].dtype.value
        self.nullable = True


class ArrayContains(Expression):
    """array_contains(arr, value): 3-valued like Spark (null if the value
    is not found but the array has null elements, or inputs are null)."""

    def __init__(self, child: Expression, value: Expression):
        self.children = (child, value)

    def resolve(self) -> None:
        self.dtype = dt.BOOL
        self.nullable = True


class CreateArray(Expression):
    """array(e1, e2, ...) of a common element type."""

    def __init__(self, *exprs: Expression):
        self.children = tuple(exprs)

    def resolve(self) -> None:
        dtypes = [c.dtype for c in self.children if c.dtype != dt.NULL]
        if not dtypes:
            el = dt.NULL
        else:
            el = dtypes[0]
            for d in dtypes[1:]:
                if d != el:
                    el = dt.promote(el, d)
        self.dtype = dt.list_of(el)
        self.nullable = False


class SortArray(Expression):
    """sort_array(arr, asc): nulls first when ascending, last otherwise."""

    def __init__(self, child: Expression, ascending: bool = True):
        self.children = (child,)
        self.ascending = ascending

    def resolve(self) -> None:
        self.dtype = self.children[0].dtype
        self.nullable = self.children[0].nullable


class ElementAt(Expression):
    """element_at(array, i): 1-based, negative counts from the end, 0 ->
    null (Spark raises; we stay non-ANSI-lenient).  element_at(map, key)
    resolves to GetMapValue at bind."""

    def __init__(self, child: Expression, key: Expression):
        self.children = (child, key)

    def resolve(self) -> None:
        self.dtype = self.children[0].dtype.element
        self.nullable = True


class GetItem(Expression):
    """Unresolved col[key]: bind() rewrites to GetArrayItem or GetMapValue
    based on the child's resolved type (UnresolvedExtractValue analog)."""

    def __init__(self, child: Expression, key: Expression):
        self.children = (child, key)

    def resolve(self) -> None:  # pragma: no cover - replaced at bind
        self.dtype = None
        self.nullable = True


class Generator(Expression):
    """Base for row-multiplying expressions; consumed by the Generate
    plan node, never evaluated row-wise."""


class Explode(Generator):
    """explode(array): one output row per element.  ``outer`` keeps rows
    whose array is null/empty (with a null element), matching Spark's
    explode_outer."""

    def __init__(self, child: Expression, outer: bool = False):
        self.children = (child,)
        self.outer = outer

    def resolve(self) -> None:
        self.dtype = self.children[0].dtype.element
        self.nullable = True


class PosExplode(Generator):
    """posexplode(array): (pos, col) per element."""

    def __init__(self, child: Expression, outer: bool = False):
        self.children = (child,)
        self.outer = outer

    def resolve(self) -> None:
        self.dtype = self.children[0].dtype.element
        self.nullable = True
