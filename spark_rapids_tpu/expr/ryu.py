"""Device float->string: exact shortest round-trip decimal (Ryu).

The engine's documented cast semantics for float->string is Python's
``repr`` (shortest decimal that parses back to the same double; the
CPU oracle is ``repr(float(x))`` in expr/eval_cpu.py::_spark_str — a
deliberate, documented delta from Spark's Java ``Double.toString``,
whose digit selection is identical and whose formatting thresholds
differ).  The reference runs this cast on device (GpuCast.scala:190-861
castFloatingPointToString); round 3 left it CPU-only because shortest
repr needs exact 128-bit arithmetic.  This module implements the Ryu
algorithm (Adams, PLDI 2018) with vectorized 64-bit lanes:

  * all per-row state is ``uint64`` vectors (XLA emulates them as u32
    pairs on TPU — elementwise, so throughput stays vector-shaped),
  * the 64x128->top-64 ``mulShift`` is built from 32x32->64 partial
    products (`_umul128`),
  * divisions by 5/10 use multiply-high magic constants (no emulated
    64-bit division anywhere),
  * the data-dependent digit-removal loops become fixed 18-trip
    ``fori_loop``s with per-row active masks,
  * the 5^q / 5^-q tables (326 + 292 x 128-bit) are computed exactly
    with Python ints at import and uploaded once as [n, 2] u64.

Output is the engine's device string layout: (bytes [n, 32] u8,
lengths i32).
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

_MANT_BITS = 52
_BIAS = 1023
_POW5_INV_BITCOUNT = 125
_POW5_BITCOUNT = 125
_MAX_LEN = 32          # "-2.2250738585072014e-308" is 24; bucket 32


def _pow5bits(e: int) -> int:
    return ((e * 1217359) >> 19) + 1


def _build_tables():
    inv = np.zeros((292, 2), dtype=np.uint64)    # floor(2^k/5^q)+1
    for q in range(292):
        pow5 = 5 ** q
        k = _pow5bits(q) + _POW5_INV_BITCOUNT - 1
        v = (1 << k) // pow5 + 1
        inv[q, 0] = v & 0xFFFFFFFFFFFFFFFF
        inv[q, 1] = v >> 64
    pw = np.zeros((326, 2), dtype=np.uint64)     # floor(5^i/2^(b-121))
    for i in range(326):
        pow5 = 5 ** i
        k = _pow5bits(i) - _POW5_BITCOUNT
        v = pow5 >> k if k >= 0 else pow5 << -k
        pw[i, 0] = v & 0xFFFFFFFFFFFFFFFF
        pw[i, 1] = v >> 64
    # multipleOfPowerOf5 via modular inverse: value % 5^p == 0 iff
    # value * inv5^p (mod 2^64) <= (2^64 - 1) / 5^p
    inv5 = pow(5, -1, 1 << 64)
    minv = np.zeros((24,), dtype=np.uint64)
    mbound = np.zeros((24,), dtype=np.uint64)
    for p in range(24):
        minv[p] = pow(inv5, p, 1 << 64)
        mbound[p] = ((1 << 64) - 1) // (5 ** p)
    return inv, pw, minv, mbound


_INV_TAB, _POW_TAB, _MODINV5, _MODBOUND5 = _build_tables()

_M32 = np.uint64(0xFFFFFFFF)


def _umul128(a, b):
    """Full 64x64 -> (lo, hi) via four 32x32->64 partials."""
    a0 = a & _M32
    a1 = a >> np.uint64(32)
    b0 = b & _M32
    b1 = b >> np.uint64(32)
    p00 = a0 * b0
    p01 = a0 * b1
    p10 = a1 * b0
    p11 = a1 * b1
    mid = (p00 >> np.uint64(32)) + (p01 & _M32) + (p10 & _M32)
    lo = (p00 & _M32) | (mid << np.uint64(32))
    hi = p11 + (p01 >> np.uint64(32)) + (p10 >> np.uint64(32)) + \
        (mid >> np.uint64(32))
    return lo, hi


def _umulhi(a, b):
    return _umul128(a, b)[1]


_DIV_MAGIC = np.uint64(0xCCCCCCCCCCCCCCCD)


def _div5(x):
    return _umulhi(x, _DIV_MAGIC) >> np.uint64(2)


def _div10(x):
    return _umulhi(x, _DIV_MAGIC) >> np.uint64(3)


def _mod10(x):
    return x - np.uint64(10) * _div10(x)


def _mul_shift64(m, mul_lo, mul_hi, j):
    """(m * (mul_hi<<64 | mul_lo)) >> j, for 64 < j < 128."""
    lo0, hi0 = _umul128(m, mul_lo)
    lo2, hi2 = _umul128(m, mul_hi)
    s_lo = hi0 + lo2
    carry = (s_lo < hi0).astype(jnp.uint64)
    s_hi = hi2 + carry
    dist = (j - np.uint64(64)).astype(jnp.uint64)
    # 0 < dist < 64 for all double inputs
    return (s_hi << (np.uint64(64) - dist)) | (s_lo >> dist)


def _multiple_of_pow5(value, p):
    """value % 5^p == 0 for p in [0, 23], via the mod-inverse trick."""
    inv = jnp.take(jnp.asarray(_MODINV5), p)
    bound = jnp.take(jnp.asarray(_MODBOUND5), p)
    prod = value * inv      # mod 2^64
    return prod <= bound


def _log10_pow2(e):
    return (e * 78913) >> 18


def _log10_pow5(e):
    return (e * 732923) >> 20


def _d2d(bits):
    """Core Ryu: IEEE754 bits (u64, finite nonzero) -> (digits u64,
    exp i32) with digits the shortest decimal mantissa and
    value == digits * 10^exp."""
    ieee_mant = bits & jnp.uint64((1 << 52) - 1)
    ieee_exp = ((bits >> jnp.uint64(52)) &
                jnp.uint64(0x7FF)).astype(jnp.int32)

    subnormal = ieee_exp == 0
    e2 = jnp.where(subnormal, 1 - _BIAS - _MANT_BITS - 2,
                   ieee_exp - _BIAS - _MANT_BITS - 2)
    m2 = jnp.where(subnormal, ieee_mant,
                   ieee_mant | jnp.uint64(1 << 52))
    even = (m2 & jnp.uint64(1)) == 0
    accept = even
    mv = jnp.uint64(4) * m2
    mm_shift = ((ieee_mant != 0) | (ieee_exp <= 1)).astype(jnp.uint64)

    # ---- e2 >= 0 branch --------------------------------------------
    e2u = jnp.maximum(e2, 0)
    q_a = _log10_pow2(e2u) - (e2u > 3).astype(jnp.int32)
    q_a_u = jnp.maximum(q_a, 0)
    pb_a = ((q_a_u * 1217359) >> 19) + 1
    k_a = _POW5_INV_BITCOUNT + pb_a - 1
    i_a = (-e2u + q_a_u + k_a).astype(jnp.uint64)
    mul_a = jnp.asarray(_INV_TAB)
    qa_idx = jnp.clip(q_a_u, 0, _INV_TAB.shape[0] - 1)
    a_lo = jnp.take(mul_a[:, 0], qa_idx)
    a_hi = jnp.take(mul_a[:, 1], qa_idx)
    vr_a = _mul_shift64(mv, a_lo, a_hi, i_a)
    vp_a = _mul_shift64(mv + jnp.uint64(2), a_lo, a_hi, i_a)
    vm_a = _mul_shift64(mv - jnp.uint64(1) - mm_shift, a_lo, a_hi, i_a)
    qp = jnp.clip(q_a_u, 0, 23)
    mv_mod5 = mv - jnp.uint64(5) * _div5(mv)
    vr_tz_a = (q_a_u <= 21) & (mv_mod5 == 0) & \
        _multiple_of_pow5(mv, qp)
    vm_tz_a = (q_a_u <= 21) & (mv_mod5 != 0) & accept & \
        _multiple_of_pow5(mv - jnp.uint64(1) - mm_shift, qp)
    vp_a = vp_a - jnp.where(
        (q_a_u <= 21) & (mv_mod5 != 0) & ~accept &
        _multiple_of_pow5(mv + jnp.uint64(2), qp),
        jnp.uint64(1), jnp.uint64(0))
    e10_a = q_a

    # ---- e2 < 0 branch ---------------------------------------------
    ne2 = jnp.maximum(-e2, 0)
    q_b = _log10_pow5(ne2) - (ne2 > 1).astype(jnp.int32)
    q_b_u = jnp.maximum(q_b, 0)
    i_b = ne2 - q_b_u
    i_b_idx = jnp.clip(i_b, 0, _POW_TAB.shape[0] - 1)
    pb_b = ((i_b_idx * 1217359) >> 19) + 1
    k_b = pb_b - _POW5_BITCOUNT
    j_b = jnp.maximum(q_b_u - k_b, 65).astype(jnp.uint64)
    mul_b = jnp.asarray(_POW_TAB)
    b_lo = jnp.take(mul_b[:, 0], i_b_idx)
    b_hi = jnp.take(mul_b[:, 1], i_b_idx)
    vr_b = _mul_shift64(mv, b_lo, b_hi, j_b)
    vp_b = _mul_shift64(mv + jnp.uint64(2), b_lo, b_hi, j_b)
    vm_b = _mul_shift64(mv - jnp.uint64(1) - mm_shift, b_lo, b_hi, j_b)
    vr_tz_b = jnp.where(
        q_b_u <= 1, jnp.ones_like(even),
        (q_b_u < 63) &
        ((mv & ((jnp.uint64(1) << jnp.clip(q_b_u, 0, 63)
                 .astype(jnp.uint64)) - jnp.uint64(1))) == 0))
    vm_tz_b = (q_b_u <= 1) & accept & (mm_shift == 1)
    vp_b = vp_b - jnp.where((q_b_u <= 1) & ~accept,
                            jnp.uint64(1), jnp.uint64(0))
    e10_b = q_b + e2

    pos = e2 >= 0
    vr = jnp.where(pos, vr_a, vr_b)
    vp = jnp.where(pos, vp_a, vp_b)
    vm = jnp.where(pos, vm_a, vm_b)
    vr_tz = jnp.where(pos, vr_tz_a, vr_tz_b)
    vm_tz = jnp.where(pos, vm_tz_a, vm_tz_b)
    e10 = jnp.where(pos, e10_a, e10_b)

    # ---- digit removal ---------------------------------------------
    any_tz = vm_tz | vr_tz

    def body1(_, st):
        vr, vp, vm, vm_tz, vr_tz, last, removed = st
        go = _div10(vp) > _div10(vm)
        vm_tz2 = vm_tz & (_mod10(vm) == 0)
        vr_tz2 = vr_tz & (last == 0)
        last2 = _mod10(vr).astype(jnp.int32)
        return (jnp.where(go, _div10(vr), vr),
                jnp.where(go, _div10(vp), vp),
                jnp.where(go, _div10(vm), vm),
                jnp.where(go, vm_tz2, vm_tz),
                jnp.where(go, vr_tz2, vr_tz),
                jnp.where(go, last2, last),
                removed + go.astype(jnp.int32))

    st = (vr, vp, vm, vm_tz, vr_tz, jnp.zeros_like(e10),
          jnp.zeros_like(e10))
    vr, vp, vm, vm_tz, vr_tz, last, removed = jax.lax.fori_loop(
        0, 18, body1, st)

    def body2(_, st):
        vr, vp, vm, vr_tz, last, removed = st
        go = _mod10(vm) == 0
        vr_tz2 = vr_tz & (last == 0)
        last2 = _mod10(vr).astype(jnp.int32)
        return (jnp.where(go, _div10(vr), vr),
                jnp.where(go, _div10(vp), vp),
                jnp.where(go, _div10(vm), vm),
                jnp.where(go, vr_tz2, vr_tz),
                jnp.where(go, last2, last),
                removed + go.astype(jnp.int32))

    # second loop only runs for rows where vm had trailing zeros
    st2 = (vr, vp, vm, vr_tz, last, removed)
    vr2, _vp2, vm2, vr_tz2, last2, removed2 = jax.lax.fori_loop(
        0, 18, body2, st2)
    use2 = vm_tz
    vr = jnp.where(use2, vr2, vr)
    vm = jnp.where(use2, vm2, vm)
    vr_tz = jnp.where(use2, vr_tz2, vr_tz)
    last = jnp.where(use2, last2, last)
    removed = jnp.where(use2, removed2, removed)

    # round-to-even correction for exact halves
    last = jnp.where(vr_tz & (last == 5) & ((vr & jnp.uint64(1)) == 0),
                     jnp.int32(4), last)
    need_inc = ((vr == vm) & (~accept | ~vm_tz)) | (last >= 5)
    out = vr + need_inc.astype(jnp.uint64)
    del any_tz
    return out, e10 + removed


def _digits_of(out):
    """out u64 (1..17 digits) -> ([n, 17] u8 digit chars MSD-first
    right-aligned is awkward; return LSD-indexable digits + count)."""
    ds = []
    x = out
    for _ in range(17):
        ds.append(_mod10(x).astype(jnp.uint8))
        x = _div10(x)
    dig = jnp.stack(ds, axis=-1)          # [n, 17], LSD first
    length = jnp.ones(out.shape, jnp.int32)
    p = out
    for i in range(1, 17):
        p = _div10(p)
        length = length + (p > 0).astype(jnp.int32)
    return dig, length


def f64_to_string(data: jnp.ndarray, validity: jnp.ndarray):
    """Python-repr format of f64 -> (bytes [n, 32] u8, lengths i32).

    Specials: NaN / Infinity / -Infinity / 0.0 / -0.0 (repr style).
    Finite nonzero: shortest digits D of length L with decimal point
    exponent dexp; fixed notation for -4 <= dexp < 16, else
    scientific  d[.ddd]e(+|-)XX  with >= 2 exponent digits.
    """
    from spark_rapids_tpu.expr.eval_tpu import f64_bits
    n = data.shape[0]
    bits = f64_bits(data)
    sign = (bits >> jnp.uint64(63)) != 0
    absbits = bits & jnp.uint64((1 << 63) - 1)
    ieee_exp = (absbits >> jnp.uint64(52)).astype(jnp.int32)
    is_nan = (ieee_exp == 0x7FF) & ((absbits &
                                     jnp.uint64((1 << 52) - 1)) != 0)
    is_inf = (ieee_exp == 0x7FF) & ~is_nan
    is_zero = absbits == 0

    digits, exp = _d2d(absbits)
    dig, L = _digits_of(digits)
    dexp = exp + L - 1                    # exponent of first digit

    sci = (dexp < -4) | (dexp >= 16)
    cols = jnp.arange(_MAX_LEN, dtype=jnp.int32)[None, :]
    s_off = sign.astype(jnp.int32)[:, None]     # '-' column shift
    Lc = L[:, None]
    dx = dexp[:, None]

    def dchar(idx_from_msd):
        """ASCII digit k positions after the most significant digit."""
        sel = jnp.clip(Lc - 1 - idx_from_msd, 0, 16)
        d = jnp.take_along_axis(dig, sel, axis=1)
        return d + np.uint8(ord("0"))

    zero_ch = np.uint8(ord("0"))
    dot = np.uint8(ord("."))

    # ---- fixed notation --------------------------------------------
    # dexp >= 0:  D[0..dexp] (zero-padded) '.' D[dexp+1..] (or '0')
    # dexp < 0 :  '0' '.' zeros(-dexp-1) D[0..]
    ip_len = jnp.where(dx >= 0, dx + 1, 1)          # integer digits
    fr_len = jnp.where(dx >= 0, jnp.maximum(Lc - (dx + 1), 1),
                       (-dx - 1) + Lc)
    fix_len = ip_len + 1 + fr_len
    j = cols - s_off
    in_int = (j >= 0) & (j < ip_len)
    at_dot = j == ip_len
    in_frac = (j > ip_len) & (j < fix_len)
    fj = j - ip_len - 1                              # fraction index
    int_digit = jnp.where((dx >= 0) & (j < Lc), dchar(j), zero_ch)
    # for dexp >= 0 the k-th fraction char is digit (dexp+1+k); for
    # dexp < 0 it's zeros until k == -dexp-1 then digit (k + dexp + 1)
    frac_pos = fj + dx + 1
    frac_digit = jnp.where(
        (frac_pos >= 0) & (frac_pos < Lc), dchar(frac_pos), zero_ch)
    fixed_ch = jnp.where(
        in_int, int_digit,
        jnp.where(at_dot, dot, jnp.where(in_frac, frac_digit,
                                         np.uint8(0))))

    # ---- scientific notation ---------------------------------------
    # d '.' rest | 'e' sign dd[d]
    has_frac = Lc > 1
    mant_len = jnp.where(has_frac, Lc + 1, 1)
    aexp = jnp.abs(dx)
    e_digits = jnp.where(aexp >= 100, 3, 2)
    sci_len = mant_len + 2 + e_digits
    at_d0 = j == 0
    at_sdot = (j == 1) & has_frac
    in_mant = (j >= 2) & (j < mant_len)
    at_e = j == mant_len
    at_esign = j == mant_len + 1
    in_exp = (j >= mant_len + 2) & (j < sci_len)
    mant_digit = dchar(j - 1)
    ej = j - mant_len - 2
    e1 = aexp // 100
    e2_ = (aexp // 10) % 10
    e3 = aexp % 10
    exp_digit = jnp.where(
        e_digits == 3,
        jnp.where(ej == 0, e1, jnp.where(ej == 1, e2_, e3)),
        jnp.where(ej == 0, e2_, e3)).astype(jnp.uint8) + zero_ch
    sci_ch = jnp.where(
        at_d0, dchar(jnp.zeros_like(j)),
        jnp.where(at_sdot, dot,
                  jnp.where(in_mant, mant_digit,
                            jnp.where(at_e, np.uint8(ord("e")),
                                      jnp.where(at_esign,
                                                jnp.where(dx < 0,
                                                          np.uint8(ord("-")),
                                                          np.uint8(ord("+"))),
                                                jnp.where(in_exp, exp_digit,
                                                          np.uint8(0)))))))

    ch = jnp.where(sci[:, None], sci_ch, fixed_ch)
    length = jnp.where(sci, sci_len[:, 0], fix_len[:, 0]) + \
        sign.astype(jnp.int32)
    # sign column
    ch = jnp.where((cols == 0) & sign[:, None], np.uint8(ord("-")), ch)

    # ---- specials ---------------------------------------------------
    def _lit(s):
        b = np.zeros((_MAX_LEN,), np.uint8)
        b[:len(s)] = np.frombuffer(s.encode(), dtype=np.uint8)
        return jnp.asarray(b)[None, :], len(s)

    nan_b, nan_l = _lit("NaN")
    inf_b, inf_l = _lit("Infinity")
    ninf_b, ninf_l = _lit("-Infinity")
    z_b, z_l = _lit("0.0")
    nz_b, nz_l = _lit("-0.0")

    for m, b, le in ((is_nan, nan_b, nan_l),
                     (is_inf & ~sign, inf_b, inf_l),
                     (is_inf & sign, ninf_b, ninf_l),
                     (is_zero & ~sign, z_b, z_l),
                     (is_zero & sign, nz_b, nz_l)):
        ch = jnp.where(m[:, None], b, ch)
        length = jnp.where(m, le, length)

    valid = validity
    ch = jnp.where(valid[:, None], ch, np.uint8(0))
    length = jnp.where(valid, length, 0)
    # zero out columns past each row's length (device string contract)
    ch = jnp.where(cols < length[:, None], ch, np.uint8(0))
    return ch, length
