"""TPU (jax/XLA) columnar expression evaluator.

Analog of ``GpuExpression.columnarEval`` (reference:
sql-plugin/.../GpuExpressions.scala:63-230) with the cudf kernel calls replaced
by jnp ops that XLA fuses into the surrounding program.  Where cudf has a
dedicated kernel (strings, hash), the jnp formulation here is written to lower
to MXU/VPU-friendly code: fixed-width byte matrices for strings, unrolled
static loops over bucketed max-lengths, no data-dependent shapes.

Spark semantics implemented here (parity-critical; reference taxonomy at
GpuOverrides.scala:336-342):
  * null propagation on binary ops; AND/OR three-valued logic
  * x / 0 and x % 0 yield NULL (non-ANSI mode)
  * NaN: comparisons use Spark's total order (NaN greatest, NaN == NaN)
  * -0.0 == 0.0; hash/normalize canonicalizes -0.0 -> 0.0 and NaNs
  * integer casts wrap (two's complement), matching Spark non-ANSI
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from functools import partial
from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp

from spark_rapids_tpu import dtypes as dt
from spark_rapids_tpu.columnar.batch import DeviceBatch, DeviceColumn
from spark_rapids_tpu.expr import ir


@dataclass
class ColVal:
    """Evaluated column value: data + validity (+ lengths for string/list,
    elem_validity for list)."""

    dtype: dt.DType
    data: jnp.ndarray
    validity: jnp.ndarray
    lengths: Optional[jnp.ndarray] = None
    elem_validity: Optional[jnp.ndarray] = None
    # static value-range hint (see DeviceColumn.vbits); survives only
    # range-preserving ops (column refs, gathers, aliases)
    vbits: Optional[int] = None
    # static no-nulls hint (see DeviceColumn.nonnull)
    nonnull: bool = False

    def to_column(self) -> DeviceColumn:
        return DeviceColumn(self.dtype, self.data, self.validity,
                            self.lengths, self.elem_validity, self.vbits,
                            self.nonnull)


def evaluate(e: ir.Expression, batch: DeviceBatch) -> ColVal:
    """Evaluate a bound expression against a DeviceBatch."""
    fn = _DISPATCH.get(type(e))
    if fn is None:
        raise NotImplementedError(f"TPU eval for {type(e).__name__}")
    v = fn(e, batch)
    # padding rows are never valid
    v = ColVal(v.dtype, v.data, v.validity & batch.row_mask(), v.lengths,
               v.elem_validity, v.vbits, v.nonnull)
    return v


# ---------------------------------------------------------------------------
# helpers
# ---------------------------------------------------------------------------

def _const(batch: DeviceBatch, value, dtype: dt.DType) -> ColVal:
    cap = batch.capacity
    if dtype.is_string:
        b = (value or "").encode("utf-8")
        max_len = max(1, 1 << (len(b) - 1).bit_length() if b else 1)
        data = np.zeros((cap, max_len), dtype=np.uint8)
        if b:
            data[:, :len(b)] = np.frombuffer(b, dtype=np.uint8)
        lengths = jnp.full((cap,), len(b), dtype=jnp.int32)
        valid = jnp.full((cap,), value is not None)
        return ColVal(dtype, jnp.asarray(data), valid, lengths)
    if value is None:
        np_dt = dtype.to_np() if dtype != dt.NULL else np.bool_
        return ColVal(dtype,
                      jnp.zeros((cap,), dtype=np_dt),
                      jnp.zeros((cap,), dtype=jnp.bool_))
    if dtype.id == dt.TypeId.DATE32 and not isinstance(value, (int, np.integer)):
        value = (np.datetime64(value, "D") - np.datetime64(0, "D")).astype(int)
    if dtype.id == dt.TypeId.TIMESTAMP_US and not isinstance(value, (int, np.integer)):
        value = (np.datetime64(value, "us") - np.datetime64(0, "us")).astype(int)
    data = jnp.full((cap,), value, dtype=dtype.to_np())
    return ColVal(dtype, data, jnp.ones((cap,), dtype=jnp.bool_))


def f64_bits(x: jnp.ndarray) -> jnp.ndarray:
    """IEEE-754 bit pattern of a float64 as uint64 (NaN canonicalized to
    the positive quiet pattern).

    On CPU backends this is one bitcast.  TPU runtimes emulate x64
    ("X64 rewriting") and reject 64-bit bitcast-convert HLOs, so there
    the bits are reconstructed arithmetically: frexp gives (m, e) with
    ax = m * 2^e, m in [0.5, 1); for normals the exponent field is
    e + 1022 and the mantissa field is m * 2^53 - 2^52 (exact — m has
    <= 53 significant bits).  Subnormals flush to ±0's pattern — the
    accelerator flushes subnormal operands throughout, so they cannot
    survive device arithmetic anyway (documented incompat)."""
    if jax.default_backend() == "cpu":
        bits = x.view(jnp.uint64)
        return jnp.where(jnp.isnan(x),
                         np.uint64(0x7FF8000000000000), bits)
    return _f64_bits_arith(x)


def _f64_bits_arith(x: jnp.ndarray) -> jnp.ndarray:
    """Arithmetic-only IEEE reconstruction (exact for normals).

    No frexp/signbit either — both lower to 64-bit bitcasts.  The
    exponent comes from a greedy power-of-two ladder (exact multiplies),
    the mantissa from (m - 1) * 2^52 once m is normalized into [1, 2).
    Callers canonicalize -0.0 and NaN first, so sign is just x < 0."""
    neg = x < 0.0
    ax = jnp.abs(x)
    normal = ax >= np.float64(2.0 ** -1022)
    m = jnp.where(normal & jnp.isfinite(ax), ax, np.float64(1.0))
    e = jnp.zeros(x.shape, dtype=jnp.int64)
    for k in (512, 256, 128, 64, 32, 16, 8, 4, 2, 1):
        hi = m >= np.float64(2.0 ** k)
        m = jnp.where(hi, m * np.float64(2.0 ** -k), m)
        e = e + jnp.where(hi, k, 0)
        lo = m < np.float64(2.0 ** (1 - k))
        m = jnp.where(lo, m * np.float64(2.0 ** k), m)
        e = e - jnp.where(lo, k, 0)
    frac = ((m - np.float64(1.0))
            * np.float64(2.0 ** 52)).astype(jnp.uint64)
    ebits = (e + 1023).astype(jnp.uint64)
    bits = (ebits << np.uint64(52)) | frac
    # subnormals flush to 0 on accelerators (documented incompat)
    bits = jnp.where(normal, bits, jnp.uint64(0))
    bits = jnp.where(jnp.isinf(ax), np.uint64(0x7FF0000000000000), bits)
    bits = jnp.where(jnp.isnan(x), np.uint64(0x7FF8000000000000), bits)
    return jnp.where(neg, bits | (np.uint64(1) << np.uint64(63)), bits)


def _binary_null(l: ColVal, r: ColVal):
    return l.validity & r.validity


def _is_nan(v: ColVal) -> jnp.ndarray:
    if v.dtype.is_floating:
        return jnp.isnan(v.data)
    return jnp.zeros_like(v.validity)


def _promote_pair(e, l: ColVal, r: ColVal):
    out = e.dtype
    tgt = out.to_np()
    return l.data.astype(tgt), r.data.astype(tgt)


# ---------------------------------------------------------------------------
# leaves
# ---------------------------------------------------------------------------

def _eval_literal(e: ir.Literal, batch: DeviceBatch) -> ColVal:
    return _const(batch, e.value, e.dtype)


def _eval_bound(e: ir.BoundReference, batch: DeviceBatch) -> ColVal:
    c = batch.columns[e.ordinal]
    return ColVal(c.dtype, c.data, c.validity, c.lengths, c.elem_validity,
                  c.vbits, c.nonnull)


def _eval_alias(e: ir.Alias, batch: DeviceBatch) -> ColVal:
    return evaluate(e.children[0], batch)


# ---------------------------------------------------------------------------
# arithmetic
# ---------------------------------------------------------------------------

def _eval_add(e, batch):
    l, r = evaluate(e.left, batch), evaluate(e.right, batch)
    a, b = _promote_pair(e, l, r)
    return ColVal(e.dtype, a + b, _binary_null(l, r))


def _eval_sub(e, batch):
    l, r = evaluate(e.left, batch), evaluate(e.right, batch)
    a, b = _promote_pair(e, l, r)
    return ColVal(e.dtype, a - b, _binary_null(l, r))


def _eval_mul(e, batch):
    l, r = evaluate(e.left, batch), evaluate(e.right, batch)
    a, b = _promote_pair(e, l, r)
    return ColVal(e.dtype, a * b, _binary_null(l, r))


def _eval_div(e, batch):
    l, r = evaluate(e.left, batch), evaluate(e.right, batch)
    a = l.data.astype(jnp.float64)
    b = r.data.astype(jnp.float64)
    nz = b != 0.0
    out = jnp.where(nz, a / jnp.where(nz, b, 1.0), 0.0)
    return ColVal(e.dtype, out, _binary_null(l, r) & nz)


def _eval_idiv(e, batch):
    l, r = evaluate(e.left, batch), evaluate(e.right, batch)
    a = l.data.astype(jnp.int64)
    b = r.data.astype(jnp.int64)
    nz = b != 0
    bb = jnp.where(nz, b, 1)
    # Spark `div` truncates toward zero; jnp floor-divides
    q = a // bb
    rem = a - q * bb
    q = jnp.where((rem != 0) & ((a < 0) != (b < 0)), q + 1, q)
    return ColVal(e.dtype, jnp.where(nz, q, 0), _binary_null(l, r) & nz)


def _trunc_mod(a, b, floating):
    if floating:
        nz = b != 0.0
        bb = jnp.where(nz, b, 1.0)
        m = jnp.fmod(a, bb)  # fmod truncates toward zero like Spark %
        return m, nz
    nz = b != 0
    bb = jnp.where(nz, b, 1)
    q = a // bb
    rem = a - q * bb
    # convert floored remainder to truncated remainder
    fix = (rem != 0) & ((a < 0) != (b < 0))
    rem = jnp.where(fix, rem - b, rem)
    return rem, nz


def _eval_mod(e, batch):
    l, r = evaluate(e.left, batch), evaluate(e.right, batch)
    a, b = _promote_pair(e, l, r)
    m, nz = _trunc_mod(a, b, e.dtype.is_floating)
    return ColVal(e.dtype, jnp.where(nz, m, 0), _binary_null(l, r) & nz)


def _eval_pmod(e, batch):
    l, r = evaluate(e.left, batch), evaluate(e.right, batch)
    a, b = _promote_pair(e, l, r)
    m, nz = _trunc_mod(a, b, e.dtype.is_floating)
    m = jnp.where((m != 0) & ((m < 0) != (b < 0)), m + b, m)
    return ColVal(e.dtype, jnp.where(nz, m, 0), _binary_null(l, r) & nz)


def _eval_neg(e, batch):
    c = evaluate(e.child, batch)
    return ColVal(e.dtype, -c.data, c.validity)


def _eval_pos(e, batch):
    return evaluate(e.child, batch)


def _eval_abs(e, batch):
    c = evaluate(e.child, batch)
    return ColVal(e.dtype, jnp.abs(c.data), c.validity)


# ---------------------------------------------------------------------------
# comparisons (Spark total order for floats: NaN greatest, NaN == NaN)
# ---------------------------------------------------------------------------

def _string_eq(l: ColVal, r: ColVal) -> jnp.ndarray:
    wl, wr = l.data.shape[1], r.data.shape[1]
    w = max(wl, wr)
    a = jnp.pad(l.data, ((0, 0), (0, w - wl)))
    b = jnp.pad(r.data, ((0, 0), (0, w - wr)))
    return jnp.all(a == b, axis=1) & (l.lengths == r.lengths)


def _string_cmp(l: ColVal, r: ColVal) -> jnp.ndarray:
    """Lexicographic compare -> int {-1,0,1} per row."""
    wl, wr = l.data.shape[1], r.data.shape[1]
    w = max(wl, wr)
    a = jnp.pad(l.data, ((0, 0), (0, w - wl))).astype(jnp.int32)
    b = jnp.pad(r.data, ((0, 0), (0, w - wr))).astype(jnp.int32)
    # mask bytes beyond each string's length to -1 so shorter sorts first
    idx = jnp.arange(w)[None, :]
    a = jnp.where(idx < l.lengths[:, None], a, -1)
    b = jnp.where(idx < r.lengths[:, None], b, -1)
    diff = jnp.sign(a - b)
    nz = diff != 0
    first = jnp.argmax(nz, axis=1)
    any_nz = jnp.any(nz, axis=1)
    return jnp.where(any_nz, jnp.take_along_axis(
        diff, first[:, None], axis=1)[:, 0], 0)


def _cmp_vals(e, l: ColVal, r: ColVal, op: str) -> jnp.ndarray:
    if l.dtype.is_string:
        if op == "eq":
            return _string_eq(l, r)
        c = _string_cmp(l, r)
        return {"lt": c < 0, "le": c <= 0, "gt": c > 0, "ge": c >= 0}[op]
    tgt = dt.promote(l.dtype, r.dtype).to_np() if l.dtype != r.dtype \
        else l.dtype.to_np()
    a, b = l.data.astype(tgt), r.data.astype(tgt)
    if l.dtype.is_floating or r.dtype.is_floating:
        an, bn = jnp.isnan(a), jnp.isnan(b)
        if op == "eq":
            return jnp.where(an | bn, an & bn, a == b)
        if op == "lt":
            return jnp.where(an, False, jnp.where(bn, True, a < b))
        if op == "le":
            return jnp.where(bn, True, jnp.where(an, False, a <= b))
        if op == "gt":
            return jnp.where(bn, False, jnp.where(an, True, a > b))
        if op == "ge":
            return jnp.where(an, True, jnp.where(bn, False, a >= b))
    return {"eq": a == b, "lt": a < b, "le": a <= b,
            "gt": a > b, "ge": a >= b}[op]


def _mk_cmp(op):
    def f(e, batch):
        l, r = evaluate(e.left, batch), evaluate(e.right, batch)
        return ColVal(dt.BOOL, _cmp_vals(e, l, r, op), _binary_null(l, r))
    return f


def _eval_and(e, batch):
    l, r = evaluate(e.left, batch), evaluate(e.right, batch)
    val = l.data & r.data
    known_false = (l.validity & ~l.data) | (r.validity & ~r.data)
    valid = (l.validity & r.validity) | known_false
    return ColVal(dt.BOOL, val & ~known_false | jnp.zeros_like(val), valid)


def _eval_or(e, batch):
    l, r = evaluate(e.left, batch), evaluate(e.right, batch)
    val = l.data | r.data
    known_true = (l.validity & l.data) | (r.validity & r.data)
    valid = (l.validity & r.validity) | known_true
    return ColVal(dt.BOOL, val | known_true, valid)


def _eval_not(e, batch):
    c = evaluate(e.child, batch)
    return ColVal(dt.BOOL, ~c.data, c.validity)


def _eval_in(e, batch):
    v = evaluate(e.children[0], batch)
    hit = jnp.zeros_like(v.validity)
    has_null_item = any(i is None for i in e.items)
    for item in e.items:
        if item is None:
            continue
        lit = _const(batch, item, v.dtype)
        hit = hit | _cmp_vals(e, v, lit, "eq")
    # Spark: if no match and set contains null -> null
    valid = v.validity & (hit | jnp.full_like(hit, not has_null_item))
    return ColVal(dt.BOOL, hit, valid)


# ---------------------------------------------------------------------------
# nulls & conditionals
# ---------------------------------------------------------------------------

def _eval_isnull(e, batch):
    c = evaluate(e.child, batch)
    return ColVal(dt.BOOL, ~c.validity & batch.row_mask(),
                  jnp.ones_like(c.validity))


def _eval_isnotnull(e, batch):
    c = evaluate(e.child, batch)
    return ColVal(dt.BOOL, c.validity, jnp.ones_like(c.validity))


def _eval_isnan(e, batch):
    c = evaluate(e.child, batch)
    return ColVal(dt.BOOL, _is_nan(c) & c.validity, jnp.ones_like(c.validity))


def _eval_at_least_n_non_nulls(e, batch):
    count = jnp.zeros((batch.capacity,), dtype=jnp.int32)
    for c in e.children:
        v = evaluate(c, batch)
        ok = v.validity & ~_is_nan(v)
        count = count + ok.astype(jnp.int32)
    return ColVal(dt.BOOL, count >= e.n,
                  jnp.ones((batch.capacity,), dtype=jnp.bool_))


def _eval_coalesce(e, batch):
    vals = [evaluate(c, batch) for c in e.children]
    out = vals[0]
    data, valid = out.data.astype(e.dtype.to_np()), out.validity
    lengths = out.lengths
    for v in vals[1:]:
        take_new = ~valid & v.validity
        if e.dtype.is_string:
            w = max(data.shape[1], v.data.shape[1])
            data = jnp.pad(data, ((0, 0), (0, w - data.shape[1])))
            vd = jnp.pad(v.data, ((0, 0), (0, w - v.data.shape[1])))
            data = jnp.where(take_new[:, None], vd, data)
            lengths = jnp.where(take_new, v.lengths, lengths)
        else:
            data = jnp.where(take_new, v.data.astype(data.dtype), data)
        valid = valid | v.validity
    return ColVal(e.dtype, data, valid, lengths)


def _eval_nanvl(e, batch):
    l, r = evaluate(e.left, batch), evaluate(e.right, batch)
    tgt = e.dtype.to_np()
    a, b = l.data.astype(tgt), r.data.astype(tgt)
    use_b = jnp.isnan(a)
    return ColVal(e.dtype, jnp.where(use_b, b, a),
                  jnp.where(use_b, r.validity, l.validity))


def _merge_branch(dtype, data, lengths, valid, cond, v: ColVal):
    """where(cond) take branch value v."""
    if dtype.is_string:
        w = max(data.shape[1], v.data.shape[1])
        data = jnp.pad(data, ((0, 0), (0, w - data.shape[1])))
        vd = jnp.pad(v.data, ((0, 0), (0, w - v.data.shape[1])))
        data = jnp.where(cond[:, None], vd, data)
        lengths = jnp.where(cond, v.lengths, lengths)
    else:
        data = jnp.where(cond, v.data.astype(data.dtype), data)
    valid = jnp.where(cond, v.validity, valid)
    return data, lengths, valid


def _eval_if(e, batch):
    p = evaluate(e.children[0], batch)
    t = evaluate(e.children[1], batch)
    f = evaluate(e.children[2], batch)
    cond = p.data & p.validity
    tgt = e.dtype.to_np()
    if e.dtype.is_string:
        data, lengths, valid = f.data, f.lengths, f.validity
        data, lengths, valid = _merge_branch(e.dtype, data, lengths, valid,
                                             cond, t)
        return ColVal(e.dtype, data, valid, lengths)
    data = jnp.where(cond, t.data.astype(tgt), f.data.astype(tgt))
    valid = jnp.where(cond, t.validity, f.validity)
    return ColVal(e.dtype, data, valid)


def _eval_casewhen(e, batch):
    cap = batch.capacity
    els = e.else_value()
    if els is not None:
        cur = evaluate(els, batch)
        data = cur.data.astype(e.dtype.to_np()) if not e.dtype.is_string \
            else cur.data
        lengths, valid = cur.lengths, cur.validity
    else:
        z = _const(batch, None, e.dtype)
        data, lengths, valid = z.data, z.lengths, z.validity
    undecided = jnp.ones((cap,), dtype=jnp.bool_)
    # evaluate branches first-match-wins, walking in order
    for cond_e, val_e in e.branches():
        c = evaluate(cond_e, batch)
        v = evaluate(val_e, batch)
        take = undecided & c.data & c.validity
        data, lengths, valid = _merge_branch(e.dtype, data, lengths, valid,
                                             take, v)
        undecided = undecided & ~(c.data & c.validity)
    return ColVal(e.dtype, data, valid, lengths)


# ---------------------------------------------------------------------------
# math
# ---------------------------------------------------------------------------

def _mk_double_unary(fn, domain=None):
    def f(e, batch):
        c = evaluate(e.child, batch)
        x = c.data.astype(jnp.float64)
        out = fn(x)
        return ColVal(e.dtype, out, c.validity)
    return f


def _eval_log(e, batch):
    c = evaluate(e.child, batch)
    x = c.data.astype(jnp.float64)
    ok = x > 0
    out = jnp.where(ok, jnp.log(jnp.where(ok, x, 1.0)), 0.0)
    return ColVal(e.dtype, out, c.validity & ok)  # Spark: log(<=0) -> null


def _mk_logbase(base_log):
    def f(e, batch):
        c = evaluate(e.child, batch)
        x = c.data.astype(jnp.float64)
        ok = x > 0
        out = jnp.where(ok, jnp.log(jnp.where(ok, x, 1.0)) / base_log, 0.0)
        return ColVal(e.dtype, out, c.validity & ok)
    return f


def _eval_log1p(e, batch):
    c = evaluate(e.child, batch)
    x = c.data.astype(jnp.float64)
    ok = x > -1
    out = jnp.where(ok, jnp.log1p(jnp.where(ok, x, 0.0)), 0.0)
    return ColVal(e.dtype, out, c.validity & ok)


def _f64_to_i64(x: jnp.ndarray) -> jnp.ndarray:
    """Java (long) cast semantics: NaN -> 0, saturate exactly at int64
    bounds (float64 can't represent INT64_MAX, so mask explicitly)."""
    imin, imax = np.iinfo(np.int64).min, np.iinfo(np.int64).max
    x = jnp.nan_to_num(x, nan=0.0, posinf=np.inf, neginf=-np.inf)
    hi = x >= 2.0 ** 63
    lo = x <= -(2.0 ** 63)
    safe = jnp.clip(x, -(2.0 ** 63), float(np.nextafter(2.0 ** 63, 0)))
    return jnp.where(hi, imax, jnp.where(lo, imin,
                                         safe.astype(jnp.int64)))


def _eval_ceil(e, batch):
    c = evaluate(e.child, batch)
    x = c.data.astype(jnp.float64)
    return ColVal(e.dtype, _f64_to_i64(jnp.ceil(x)), c.validity)


def _eval_floor(e, batch):
    c = evaluate(e.child, batch)
    x = c.data.astype(jnp.float64)
    return ColVal(e.dtype, _f64_to_i64(jnp.floor(x)), c.validity)


def _eval_pow(e, batch):
    l, r = evaluate(e.left, batch), evaluate(e.right, batch)
    a = l.data.astype(jnp.float64)
    b = r.data.astype(jnp.float64)
    return ColVal(e.dtype, jnp.power(a, b), _binary_null(l, r))


def _eval_atan2(e, batch):
    l, r = evaluate(e.left, batch), evaluate(e.right, batch)
    return ColVal(e.dtype, jnp.arctan2(l.data.astype(jnp.float64),
                                       r.data.astype(jnp.float64)),
                  _binary_null(l, r))


def _eval_shiftleft(e, batch):
    l, r = evaluate(e.left, batch), evaluate(e.right, batch)
    nbits = l.data.dtype.itemsize * 8
    sh = r.data.astype(jnp.int32) % nbits
    return ColVal(e.dtype, l.data << sh.astype(l.data.dtype),
                  _binary_null(l, r))


def _eval_shiftright(e, batch):
    l, r = evaluate(e.left, batch), evaluate(e.right, batch)
    nbits = l.data.dtype.itemsize * 8
    sh = r.data.astype(jnp.int32) % nbits
    return ColVal(e.dtype, l.data >> sh.astype(l.data.dtype),
                  _binary_null(l, r))


def _eval_shiftright_unsigned(e, batch):
    l, r = evaluate(e.left, batch), evaluate(e.right, batch)
    nbits = l.data.dtype.itemsize * 8
    sh = (r.data.astype(jnp.int32) % nbits).astype(jnp.uint32)
    unsigned = l.data.view(jnp.uint32 if nbits == 32 else jnp.uint64)
    out = (unsigned >> sh.astype(unsigned.dtype)).view(l.data.dtype)
    return ColVal(e.dtype, out, _binary_null(l, r))


# ---------------------------------------------------------------------------
# cast (reference: GpuCast.scala)
# ---------------------------------------------------------------------------

_US_PER_DAY = 86400 * 1000 * 1000


def _eval_cast(e, batch):
    c = evaluate(e.child, batch)
    src, tgt = c.dtype, e.to
    if e.child.dtype == dt.NULL:
        # void child: the all-null placeholder's runtime dtype is
        # arbitrary — the static type decides (all-null of the target)
        src = dt.NULL
    if src == tgt:
        return ColVal(tgt, c.data, c.validity, c.lengths)
    if src == dt.NULL:
        return _const(batch, None, tgt)
    if src.is_string and tgt.is_integral:
        return _cast_string_to_int(c, tgt)
    if src.is_string and tgt.is_floating:
        return _cast_string_to_float(c, tgt)
    if src.is_string and tgt.is_bool:
        return _cast_string_to_bool(c)
    if src.is_string and tgt.id == dt.TypeId.DATE32:
        return _cast_string_to_date(c)
    if src.is_string and tgt.id == dt.TypeId.TIMESTAMP_US:
        return _cast_string_to_timestamp(c)
    if src.is_string:
        raise NotImplementedError(f"cast string->{tgt.name} on TPU")
    if tgt.is_string:
        if src.is_bool:
            return _cast_bool_to_string(c)
        if src.is_integral:
            return _cast_int_to_string(c)
        if src.id == dt.TypeId.DATE32:
            return _cast_date_to_string(c)
        if src.id == dt.TypeId.TIMESTAMP_US:
            return _cast_timestamp_to_string(c)
        if src.is_floating:
            return _cast_float_to_string(c)
        raise NotImplementedError(f"cast {src.name}->string on TPU")
    if src.id == dt.TypeId.DATE32 and tgt.id == dt.TypeId.TIMESTAMP_US:
        return ColVal(tgt, c.data.astype(jnp.int64) * _US_PER_DAY, c.validity)
    if src.id == dt.TypeId.TIMESTAMP_US and tgt.id == dt.TypeId.DATE32:
        return ColVal(tgt, (c.data // _US_PER_DAY).astype(jnp.int32),
                      c.validity)
    if src.is_bool and tgt.is_numeric:
        return ColVal(tgt, c.data.astype(tgt.to_np()), c.validity)
    if src.is_numeric and tgt.is_bool:
        return ColVal(tgt, c.data != 0, c.validity)
    if src.is_floating and tgt.is_integral:
        # Spark non-ANSI: truncate toward zero; NaN -> 0 is actually null-ish
        # in Spark it's cast to 0? Spark casts NaN->0 for int casts.
        x = jnp.nan_to_num(c.data, nan=0.0, posinf=np.inf, neginf=-np.inf)
        x = jnp.trunc(x)
        # clamp like Spark (overflow saturates to min/max for float->int)
        info = np.iinfo(tgt.to_np())
        x = jnp.clip(x, float(info.min), float(info.max))
        return ColVal(tgt, x.astype(tgt.to_np()), c.validity)
    if src.is_numeric and tgt.is_numeric:
        return ColVal(tgt, c.data.astype(tgt.to_np()), c.validity)
    if src.is_temporal and tgt.is_numeric:
        if src.id == dt.TypeId.TIMESTAMP_US and tgt.id == dt.TypeId.INT64:
            return ColVal(tgt, c.data // (1000 * 1000), c.validity)
        return ColVal(tgt, c.data.astype(tgt.to_np()), c.validity)
    raise NotImplementedError(f"cast {src.name}->{tgt.name} on TPU")


def _cast_string_to_int(c: ColVal, tgt: dt.DType) -> ColVal:
    """Parse optionally-signed decimal integers from the byte matrix.

    Spark trims surrounding whitespace before parsing (UTF8String.trimAll).
    """
    data, lengths = c.data, c.lengths
    w = data.shape[1]
    idx = jnp.arange(w)[None, :]
    # trim: first/last non-space position
    in_str = idx < lengths[:, None]
    non_space = in_str & (data != ord(" "))
    any_ns = jnp.any(non_space, axis=1)
    first_ns = jnp.argmax(non_space, axis=1)
    last_ns = (w - 1) - jnp.argmax(non_space[:, ::-1], axis=1)
    t_start = jnp.where(any_ns, first_ns, 0).astype(jnp.int32)
    t_end = jnp.where(any_ns, last_ns + 1, 0).astype(jnp.int32)

    first = jnp.take_along_axis(
        data, jnp.clip(t_start, 0, w - 1)[:, None], axis=1)[:, 0]
    neg = first == ord("-")
    plus = first == ord("+")
    start = t_start + (neg | plus).astype(jnp.int32)
    in_range = (idx >= start[:, None]) & (idx < t_end[:, None])
    digit = data.astype(jnp.int64) - ord("0")
    is_digit = (digit >= 0) & (digit <= 9)
    ok = jnp.all(~in_range | is_digit, axis=1) & (t_end > start)
    acc = jnp.zeros((data.shape[0],), dtype=jnp.int64)
    for j in range(w):  # static unrolled loop over bucketed width
        take = in_range[:, j]
        acc = jnp.where(take, acc * 10 + digit[:, j], acc)
    acc = jnp.where(neg, -acc, acc)
    return ColVal(tgt, acc.astype(tgt.to_np()), c.validity & ok)


def _trimmed(c: ColVal):
    """(start, end) of the whitespace-trimmed span per row (ASCII
    whitespace set of str.strip(): space, \t, \n, \r, \v, \f)."""
    data, lengths = c.data, c.lengths
    w = data.shape[1]
    idx = jnp.arange(w)[None, :]
    in_str = idx < lengths[:, None]
    is_ws = (data == 32) | ((data >= 9) & (data <= 13))
    non_space = in_str & ~is_ws
    any_ns = jnp.any(non_space, axis=1)
    first_ns = jnp.argmax(non_space, axis=1)
    last_ns = (w - 1) - jnp.argmax(non_space[:, ::-1], axis=1)
    start = jnp.where(any_ns, first_ns, 0).astype(jnp.int32)
    end = jnp.where(any_ns, last_ns + 1, 0).astype(jnp.int32)
    return start, end


def _cast_string_to_float(c: ColVal, tgt: dt.DType) -> ColVal:
    """[+-]digits[.digits][eE[+-]digits], plus Infinity/NaN keywords
    (GpuCast.scala castStringToFloats analog; invalid -> null)."""
    data = c.data
    w = data.shape[1]
    idx = jnp.arange(w)[None, :]
    t_start, t_end = _trimmed(c)
    first = jnp.take_along_axis(
        data, jnp.clip(t_start, 0, w - 1)[:, None], axis=1)[:, 0]
    neg = first == ord("-")
    signed = neg | (first == ord("+"))
    start = t_start + signed.astype(jnp.int32)

    def _kw(word: bytes, s):
        m = len(word)
        okk = (t_end - s) == m
        for j, byte in enumerate(word):
            p = jnp.clip(s + j, 0, w - 1)
            got = jnp.take_along_axis(data, p[:, None], axis=1)[:, 0]
            lo = got | 0x20  # case-insensitive ASCII
            okk = okk & (lo == (byte | 0x20))
        return okk
    is_inf = _kw(b"infinity", start) | _kw(b"inf", start)
    is_nan = _kw(b"nan", t_start)

    digit = data.astype(jnp.int64) - ord("0")
    is_digit = (digit >= 0) & (digit <= 9)
    is_dot = data == ord(".")
    is_e = (data == ord("e")) | (data == ord("E"))
    in_tok = (idx >= start[:, None]) & (idx < t_end[:, None])
    e_pos = jnp.min(jnp.where(is_e & in_tok, idx,
                              jnp.int32(w)), axis=1)
    mant_end = jnp.minimum(t_end, e_pos)
    in_mant = in_tok & (idx < mant_end[:, None])
    dot_pos = jnp.min(jnp.where(is_dot & in_mant, idx,
                                jnp.int32(w)), axis=1)
    # exponent part: optional sign then digits
    es = e_pos + 1
    efirst = jnp.take_along_axis(
        data, jnp.clip(es, 0, w - 1)[:, None], axis=1)[:, 0]
    eneg = efirst == ord("-")
    es = es + ((efirst == ord("-")) | (efirst == ord("+"))
               ).astype(jnp.int32)
    in_exp = (idx >= es[:, None]) & (idx < t_end[:, None])

    legal = ~in_mant | is_digit | is_dot
    legal_e = ~in_exp | is_digit
    one_dot = jnp.sum((is_dot & in_mant).astype(jnp.int32), axis=1) <= 1
    has_digit = jnp.any(is_digit & in_mant, axis=1)
    has_exp = e_pos < jnp.int32(w)
    exp_digits = jnp.any(is_digit & in_exp, axis=1)
    ok = (jnp.all(legal, axis=1) & jnp.all(legal_e, axis=1) & one_dot &
          has_digit & (t_end > start) &
          (~has_exp | (exp_digits & (e_pos < t_end))))

    mant = jnp.zeros((data.shape[0],), dtype=jnp.float64)
    frac_n = jnp.zeros((data.shape[0],), dtype=jnp.int32)
    exp_v = jnp.zeros((data.shape[0],), dtype=jnp.int32)
    for j in range(w):
        d = digit[:, j]
        tk = is_digit[:, j] & in_mant[:, j]
        mant = jnp.where(tk, mant * 10 + d.astype(jnp.float64), mant)
        frac_n = frac_n + (tk & (j > dot_pos)).astype(jnp.int32)
        te = is_digit[:, j] & in_exp[:, j]
        exp_v = jnp.where(te, jnp.minimum(exp_v * 10 + d, 99999)
                          .astype(jnp.int32), exp_v)
    exp_v = jnp.where(eneg & has_exp, -exp_v, exp_v)
    p10 = (exp_v - frac_n).astype(jnp.float64)
    v = mant * jnp.power(jnp.float64(10.0), p10)
    v = jnp.where(is_inf, jnp.inf, v)
    v = jnp.where(neg, -v, v)
    v = jnp.where(is_nan, jnp.nan, v)
    ok = ok | is_inf | is_nan
    v = jnp.where(ok, v, 0.0)
    return ColVal(tgt, v.astype(tgt.to_np()), c.validity & ok)


def _cast_string_to_bool(c: ColVal) -> ColVal:
    """Spark StringUtils: t/true/y/yes/1 and f/false/n/no/0."""
    t_start, t_end = _trimmed(c)
    data = c.data
    w = data.shape[1]

    def word(wd: bytes):
        okk = (t_end - t_start) == len(wd)
        for j, byte in enumerate(wd):
            p = jnp.clip(t_start + j, 0, w - 1)
            got = jnp.take_along_axis(data, p[:, None], axis=1)[:, 0]
            okk = okk & ((got | 0x20) == (byte | 0x20))
        return okk
    is_t = word(b"t") | word(b"true") | word(b"y") | word(b"yes") | \
        word(b"1")
    is_f = word(b"f") | word(b"false") | word(b"n") | word(b"no") | \
        word(b"0")
    return ColVal(dt.BOOL, is_t, c.validity & (is_t | is_f))


def _parse_ymd(c: ColVal):
    """'yyyy-MM-dd' (4-2-2 fixed layout) -> (y, m, d, ok, end_pos)."""
    data = c.data
    w = data.shape[1]
    t_start, t_end = _trimmed(c)

    def at(off):
        p = jnp.clip(t_start + off, 0, w - 1)
        return jnp.take_along_axis(data, p[:, None], axis=1)[:, 0]

    def num(offs):
        v = jnp.zeros((data.shape[0],), dtype=jnp.int32)
        okk = jnp.ones((data.shape[0],), dtype=jnp.bool_)
        for o in offs:
            b = at(o).astype(jnp.int32) - ord("0")
            okk = okk & (b >= 0) & (b <= 9)
            v = v * 10 + b
        return v, okk
    y, ok_y = num((0, 1, 2, 3))
    m, ok_m = num((5, 6))
    d, ok_d = num((8, 9))
    ok = (ok_y & ok_m & ok_d & (at(4) == ord("-")) &
          (at(7) == ord("-")) & ((t_end - t_start) >= 10) &
          (m >= 1) & (m <= 12) & (d >= 1) & (d <= 31))
    # calendar-exact day check (Feb 30, Apr 31, non-leap Feb 29, ...):
    # round-trip through the civil-days conversion and compare
    days = _days_from_civil(y, m, d)
    y2, m2, d2 = _civil_from_days(days)
    ok = ok & (y2 == y) & (m2 == m) & (d2 == d)
    return y, m, d, ok, t_start + 10, t_end


def _days_from_civil(y, m, d):
    """Hinnant's civil-days algorithm, pure vector int math."""
    y = y.astype(jnp.int64)
    m = m.astype(jnp.int64)
    d = d.astype(jnp.int64)
    y = y - (m <= 2)
    era = jnp.where(y >= 0, y, y - 399) // 400
    yoe = y - era * 400
    mp = (m + 9) % 12
    doy = (153 * mp + 2) // 5 + d - 1
    doe = yoe * 365 + yoe // 4 - yoe // 100 + doy
    return era * 146097 + doe - 719468


def _cast_string_to_date(c: ColVal) -> ColVal:
    y, m, d, ok, end10, t_end = _parse_ymd(c)
    ok = ok & (t_end == end10)    # exact 'yyyy-MM-dd'
    days = _days_from_civil(y, m, d)
    days = jnp.where(ok, days, 0)
    return ColVal(dt.DATE32, days.astype(jnp.int32), c.validity & ok)


def _cast_string_to_timestamp(c: ColVal) -> ColVal:
    """'yyyy-MM-dd[ HH:mm:ss[.f{1..6}]]' in UTC (the incompat UTC-only
    surface gated by castStringToTimestamp.enabled)."""
    data = c.data
    w = data.shape[1]
    y, m, d, ok, pos10, t_end = _parse_ymd(c)

    def at(off):
        p = jnp.clip(off, 0, w - 1)
        return jnp.take_along_axis(data, p[:, None], axis=1)[:, 0]

    def num2(off):
        v = jnp.zeros((data.shape[0],), dtype=jnp.int32)
        okk = jnp.ones((data.shape[0],), dtype=jnp.bool_)
        for k in (0, 1):
            b = at(off + k).astype(jnp.int32) - ord("0")
            okk = okk & (b >= 0) & (b <= 9)
            v = v * 10 + b
        return v, okk
    has_time = t_end > pos10
    sep_ok = (at(pos10) == ord(" ")) | (at(pos10) == ord("T"))
    hh, ok_h = num2(pos10 + 1)
    mm, ok_mi = num2(pos10 + 4)
    ss, ok_s = num2(pos10 + 7)
    colon_ok = (at(pos10 + 3) == ord(":")) & (at(pos10 + 6) == ord(":"))
    time_ok = sep_ok & ok_h & ok_mi & ok_s & colon_ok & \
        (hh < 24) & (mm < 60) & (ss < 60) & ((t_end - pos10) >= 9)
    # optional .fraction (1-6 digits)
    dot_ok = at(pos10 + 9) == ord(".")
    micros = jnp.zeros((data.shape[0],), dtype=jnp.int64)
    fdigits = jnp.zeros((data.shape[0],), dtype=jnp.int32)
    for k in range(6):
        p = pos10 + 10 + k
        b = at(p).astype(jnp.int64) - ord("0")
        tk = (p < t_end) & (b >= 0) & (b <= 9)
        micros = jnp.where(tk, micros * 10 + b, micros)
        fdigits = fdigits + tk.astype(jnp.int32)
    has_frac = has_time & (t_end > (pos10 + 9))
    frac_ok = ~has_frac | (dot_ok & (fdigits ==
                                     (t_end - pos10 - 10)) &
                           (fdigits >= 1) & (fdigits <= 6))
    micros = micros * jnp.power(jnp.int64(10),
                                (6 - fdigits).astype(jnp.int64))
    hh = jnp.where(has_time, hh, 0)
    mm = jnp.where(has_time, mm, 0)
    ss = jnp.where(has_time, ss, 0)
    micros = jnp.where(has_time, micros, 0)
    ok = ok & (~has_time | (time_ok & frac_ok))
    days = _days_from_civil(y, m, d)
    us = (days * 86400 + hh.astype(jnp.int64) * 3600 +
          mm.astype(jnp.int64) * 60 + ss.astype(jnp.int64)
          ) * 1000000 + micros
    us = jnp.where(ok, us, 0)
    return ColVal(dt.TIMESTAMP_US, us, c.validity & ok)


def _digits_matrix(v: jnp.ndarray, width: int):
    """abs(v) -> right-aligned digit matrix [n, width] + digit count."""
    u = jnp.abs(v.astype(jnp.int64)).astype(jnp.uint64)
    # int64 min: abs overflows; uint64 space handles it
    u = jnp.where(v == jnp.iinfo(jnp.int64).min,
                  jnp.uint64(9223372036854775808), u)
    digs = []
    x = u
    for _ in range(width):
        digs.append((x % 10).astype(jnp.uint8) + ord("0"))
        x = x // 10
    mat = jnp.stack(digs[::-1], axis=1)        # [n, width], right-aligned
    nz = mat != ord("0")
    first = jnp.argmax(nz, axis=1)
    any_nz = jnp.any(nz, axis=1)
    ndig = jnp.where(any_nz, width - first, 1).astype(jnp.int32)
    return mat, ndig


def _left_align(mat, start, out_w):
    """Gather columns starting at per-row offset into [n, out_w]."""
    idx = jnp.clip(start[:, None] + jnp.arange(out_w)[None, :], 0,
                   mat.shape[1] - 1)
    return jnp.take_along_axis(mat, idx, axis=1)


def _cast_int_to_string(c: ColVal) -> ColVal:
    v = c.data.astype(jnp.int64)
    mat, ndig = _digits_matrix(v, 19)   # int64 abs max has 19 digits
    neg = v < 0
    out_w = 20
    body = _left_align(mat, (mat.shape[1] - ndig), out_w - 1)
    data = jnp.concatenate(
        [jnp.full((v.shape[0], 1), ord("-"), jnp.uint8), body], axis=1)
    # shift right rows that are not negative (drop the '-')
    nonneg_view = jnp.concatenate(
        [body, jnp.zeros((v.shape[0], 1), jnp.uint8)], axis=1)
    data = jnp.where(neg[:, None], data, nonneg_view)
    lens = ndig + neg.astype(jnp.int32)
    keep = jnp.arange(out_w)[None, :] < lens[:, None]
    data = jnp.where(keep & c.validity[:, None], data, 0)
    return ColVal(dt.STRING, data,
                  c.validity, jnp.where(c.validity, lens, 0))


def _cast_float_to_string(c: ColVal) -> ColVal:
    """Exact Python-repr shortest decimal on device (expr/ryu.py; the
    reference's GpuCast.scala:190-861 castFloatingPointToString analog).
    float32 widens to f64 first — the CPU oracle is repr(float(x)),
    which sees the widened double."""
    from spark_rapids_tpu.expr import ryu
    data, lens = ryu.f64_to_string(c.data.astype(jnp.float64),
                                   c.validity)
    return ColVal(dt.STRING, data, c.validity, lens)


def _cast_bool_to_string(c: ColVal) -> ColVal:
    n = c.data.shape[0]
    t = jnp.asarray(np.frombuffer(b"true\0", np.uint8))
    f = jnp.asarray(np.frombuffer(b"false", np.uint8))
    data = jnp.where(c.data.astype(bool)[:, None],
                     jnp.broadcast_to(t, (n, 5)),
                     jnp.broadcast_to(f, (n, 5)))
    lens = jnp.where(c.data.astype(bool), 4, 5).astype(jnp.int32)
    keep = jnp.arange(5)[None, :] < lens[:, None]
    data = jnp.where(keep & c.validity[:, None], data, 0)
    return ColVal(dt.STRING, data, c.validity,
                  jnp.where(c.validity, lens, 0))


def _civil_from_days(z):
    """days since epoch -> (y, m, d); Hinnant's civil_from_days."""
    z = z.astype(jnp.int64) + 719468
    era = jnp.where(z >= 0, z, z - 146096) // 146097
    doe = z - era * 146097
    yoe = (doe - doe // 1460 + doe // 36524 - doe // 146096) // 365
    y = yoe + era * 400
    doy = doe - (365 * yoe + yoe // 4 - yoe // 100)
    mp = (5 * doy + 2) // 153
    d = doy - (153 * mp + 2) // 5 + 1
    m = jnp.where(mp < 10, mp + 3, mp - 9)
    y = y + (m <= 2)
    return y, m, d


def _two(v):
    v = v.astype(jnp.int64)
    return jnp.stack([(v // 10 % 10).astype(jnp.uint8) + ord("0"),
                      (v % 10).astype(jnp.uint8) + ord("0")], axis=1)


def _four(v):
    v = v.astype(jnp.int64)
    return jnp.stack([(v // 1000 % 10).astype(jnp.uint8) + ord("0"),
                      (v // 100 % 10).astype(jnp.uint8) + ord("0"),
                      (v // 10 % 10).astype(jnp.uint8) + ord("0"),
                      (v % 10).astype(jnp.uint8) + ord("0")], axis=1)


def _cast_date_to_string(c: ColVal) -> ColVal:
    y, m, d = _civil_from_days(c.data)
    n = c.data.shape[0]
    dash = jnp.full((n, 1), ord("-"), jnp.uint8)
    data = jnp.concatenate([_four(y), dash, _two(m), dash, _two(d)],
                           axis=1)
    lens = jnp.full((n,), 10, jnp.int32)
    data = jnp.where(c.validity[:, None], data, 0)
    return ColVal(dt.STRING, data, c.validity,
                  jnp.where(c.validity, lens, 0))


def _cast_timestamp_to_string(c: ColVal) -> ColVal:
    """'yyyy-MM-dd HH:mm:ss[.ffffff]' with trailing fraction zeros
    trimmed (Spark timestamp formatting, UTC)."""
    us = c.data.astype(jnp.int64)
    days = jnp.where(us >= 0, us // 86400000000,
                     -((-us + 86399999999) // 86400000000))
    rem = us - days * 86400000000
    y, m, d = _civil_from_days(days)
    hh = rem // 3600000000
    mm = rem // 60000000 % 60
    ss = rem // 1000000 % 60
    frac = (rem % 1000000).astype(jnp.int64)
    n = us.shape[0]
    dash = jnp.full((n, 1), ord("-"), jnp.uint8)
    sp = jnp.full((n, 1), ord(" "), jnp.uint8)
    col = jnp.full((n, 1), ord(":"), jnp.uint8)
    dot = jnp.full((n, 1), ord("."), jnp.uint8)
    fd = []
    x = frac
    for _ in range(6):
        fd.append((x % 10).astype(jnp.uint8) + ord("0"))
        x = x // 10
    fmat = jnp.stack(fd[::-1], axis=1)
    data = jnp.concatenate([_four(y), dash, _two(m), dash, _two(d), sp,
                            _two(hh), col, _two(mm), col, _two(ss),
                            dot, fmat], axis=1)
    # trim trailing zeros of the fraction; no fraction -> no dot
    nz = fmat != ord("0")
    any_nz = jnp.any(nz, axis=1)
    last_nz = 5 - jnp.argmax(nz[:, ::-1], axis=1)
    flen = jnp.where(any_nz, last_nz + 1, 0).astype(jnp.int32)
    lens = 19 + jnp.where(flen > 0, flen + 1, 0)
    keep = jnp.arange(data.shape[1])[None, :] < lens[:, None]
    data = jnp.where(keep & c.validity[:, None], data, 0)
    return ColVal(dt.STRING, data, c.validity,
                  jnp.where(c.validity, lens, 0))


# ---------------------------------------------------------------------------
# strings (byte-matrix kernels; ASCII case ops like cudf's default path)
# ---------------------------------------------------------------------------

def _eval_upper(e, batch):
    c = evaluate(e.child, batch)
    is_lower = (c.data >= ord("a")) & (c.data <= ord("z"))
    return ColVal(dt.STRING, jnp.where(is_lower, c.data - 32, c.data),
                  c.validity, c.lengths)


def _eval_lower(e, batch):
    c = evaluate(e.child, batch)
    is_upper = (c.data >= ord("A")) & (c.data <= ord("Z"))
    return ColVal(dt.STRING, jnp.where(is_upper, c.data + 32, c.data),
                  c.validity, c.lengths)


def _eval_reverse(e, batch):
    c = evaluate(e.child, batch)
    w = c.data.shape[1]
    pos = jnp.arange(w)[None, :]
    src = jnp.clip(c.lengths[:, None] - 1 - pos, 0, w - 1)
    out = jnp.take_along_axis(c.data, src, axis=1)
    out = jnp.where(pos < c.lengths[:, None], out, 0)
    return ColVal(dt.STRING, out, c.validity, c.lengths)


def _eval_length(e, batch):
    c = evaluate(e.child, batch)
    # NOTE: byte length == char length for ASCII; UTF-8 char count needs a
    # continuation-byte discount
    cont = ((c.data & 0xC0) == 0x80)
    idx = jnp.arange(c.data.shape[1])[None, :]
    cont = cont & (idx < c.lengths[:, None])
    n_cont = jnp.sum(cont.astype(jnp.int32), axis=1)
    return ColVal(dt.INT32, c.lengths - n_cont, c.validity)


def _eval_substring(e, batch):
    s = evaluate(e.children[0], batch)
    pos = evaluate(e.children[1], batch)
    ln = evaluate(e.children[2], batch)
    w = s.data.shape[1]
    p = pos.data.astype(jnp.int32)
    n = ln.data.astype(jnp.int32)
    slen = s.lengths
    # Spark: 1-based; pos 0 behaves like 1; negative counts from end
    start = jnp.where(p > 0, p - 1, jnp.where(p < 0, slen + p, 0))
    start = jnp.clip(start, 0, slen)
    n = jnp.clip(n, 0, None)
    end = jnp.clip(start + n, 0, slen)
    out_len = end - start
    idx = jnp.arange(w)[None, :]
    src_idx = jnp.clip(start[:, None] + idx, 0, w - 1)
    gathered = jnp.take_along_axis(s.data, src_idx, axis=1)
    keep = idx < out_len[:, None]
    data = jnp.where(keep, gathered, 0)
    valid = s.validity & pos.validity & ln.validity
    return ColVal(dt.STRING, data, valid, jnp.where(valid, out_len, 0))


def _needle_bytes(e_right) -> bytes:
    if not isinstance(e_right, ir.Literal) or e_right.value is None:
        raise NotImplementedError("string search needle must be a literal")
    return e_right.value.encode("utf-8")


def _match_at(l: ColVal, r: ColVal, offs: jnp.ndarray) -> jnp.ndarray:
    """[n] bool: needle column r matches l starting at per-row offset
    offs (clipped); caller guards length feasibility."""
    wl, wr = l.data.shape[1], r.data.shape[1]
    ok = jnp.ones((l.data.shape[0],), dtype=jnp.bool_)
    for j in range(wr):
        in_needle = j < r.lengths
        p = jnp.clip(offs + j, 0, wl - 1)
        got = jnp.take_along_axis(l.data, p[:, None], axis=1)[:, 0]
        ok = ok & (~in_needle | (got == r.data[:, j]))
    return ok


def _eval_startswith(e, batch):
    l = evaluate(e.left, batch)
    if isinstance(e.right, ir.Literal) and e.right.value is not None:
        needle = _needle_bytes(e.right)
        m = len(needle)
        w = l.data.shape[1]
        ok = l.lengths >= m
        for j, byte in enumerate(needle):
            if j < w:
                ok = ok & (l.data[:, j] == byte)
            else:
                ok = jnp.zeros_like(ok)
        return ColVal(dt.BOOL, ok, l.validity)
    r = evaluate(e.right, batch)     # column needle
    ok = (l.lengths >= r.lengths) & _match_at(
        l, r, jnp.zeros_like(l.lengths))
    return ColVal(dt.BOOL, ok, l.validity & r.validity)


def _eval_endswith(e, batch):
    l = evaluate(e.left, batch)
    if isinstance(e.right, ir.Literal) and e.right.value is not None:
        needle = _needle_bytes(e.right)
        m = len(needle)
        w = l.data.shape[1]
        ok = l.lengths >= m
        for j, byte in enumerate(needle):
            # position from the end: lengths - m + j
            p = jnp.clip(l.lengths - m + j, 0, w - 1)
            got = jnp.take_along_axis(l.data, p[:, None], axis=1)[:, 0]
            ok = ok & (got == byte)
        return ColVal(dt.BOOL, ok, l.validity)
    r = evaluate(e.right, batch)
    ok = (l.lengths >= r.lengths) & _match_at(
        l, r, l.lengths - r.lengths)
    return ColVal(dt.BOOL, ok, l.validity & r.validity)


def _contains_mask(l: ColVal, needle: bytes) -> jnp.ndarray:
    m = len(needle)
    w = l.data.shape[1]
    if m == 0:
        return jnp.ones_like(l.validity)
    if m > w:
        return jnp.zeros_like(l.validity)
    # windows: for each start p in [0, w-m], all needle bytes match
    match = jnp.ones((l.data.shape[0], w - m + 1), dtype=jnp.bool_)
    for j, byte in enumerate(needle):
        match = match & (l.data[:, j:j + (w - m + 1)] == byte)
    starts = jnp.arange(w - m + 1)[None, :]
    match = match & (starts + m <= l.lengths[:, None])
    return jnp.any(match, axis=1)


def _eval_contains(e, batch):
    l = evaluate(e.left, batch)
    if isinstance(e.right, ir.Literal) and e.right.value is not None:
        return ColVal(dt.BOOL, _contains_mask(l, _needle_bytes(e.right)),
                      l.validity)
    r = evaluate(e.right, batch)     # column needle: fori over offsets
    wl = l.data.shape[1]

    def body(s, acc):
        feasible = (s + r.lengths <= l.lengths)
        return acc | (feasible & _match_at(
            l, r, jnp.full_like(l.lengths, 1) * s))
    ok = jax.lax.fori_loop(0, wl, body,
                           r.lengths == 0)
    ok = ok & (r.lengths <= l.lengths)
    return ColVal(dt.BOOL, ok, l.validity & r.validity)


def _seg_match_positions(l: ColVal, seg: bytes) -> jnp.ndarray:
    """[n, w] bool: the segment (with '_' single-char wildcards) matches
    starting at byte position p and fits inside the string."""
    m = len(seg)
    w = l.data.shape[1]
    n = l.data.shape[0]
    if m > w:
        return jnp.zeros((n, w), dtype=jnp.bool_)
    span = w - m + 1
    match = jnp.ones((n, span), dtype=jnp.bool_)
    for j, byte in enumerate(seg):
        if byte == ord("_"):
            continue
        match = match & (l.data[:, j:j + span] == byte)
    starts = jnp.arange(span)[None, :]
    match = match & (starts + m <= l.lengths[:, None])
    return jnp.pad(match, ((0, 0), (0, w - span)))


def _eval_like(e, batch):
    """Full SQL LIKE: literal pattern with '%' multi-char and '_'
    single-char wildcards (GpuLike analog, reference:
    stringFunctions.scala:506), evaluated as a greedy leftmost
    segment-placement scan over the byte matrix."""
    l = evaluate(e.left, batch)
    if isinstance(e.right, ir.Literal) and e.right.value is None:
        n0 = l.data.shape[0]
        return ColVal(dt.BOOL, jnp.zeros((n0,), jnp.bool_),
                      jnp.zeros((n0,), jnp.bool_))   # LIKE NULL -> NULL
    pat = _needle_bytes(e.right)
    w = l.data.shape[1]
    n = l.data.shape[0]
    segs = pat.split(b"%")
    lead = not pat.startswith(b"%")
    trail = not pat.endswith(b"%")
    nonempty = [(k, s) for k, s in enumerate(segs) if s]
    if not nonempty:
        # '', '%', '%%', ...: empty pattern matches only empty string
        ok = jnp.ones((n,), jnp.bool_) if b"%" in pat \
            else (l.lengths == 0)
        return ColVal(dt.BOOL, ok, l.validity)

    ok = jnp.ones((n,), dtype=jnp.bool_)
    pos = jnp.zeros((n,), dtype=jnp.int32)
    for i, (k, seg) in enumerate(nonempty):
        m = len(seg)
        is_first = k == 0 and lead
        is_last = (k == len(segs) - 1) and trail
        mp = _seg_match_positions(l, seg)
        if is_first and is_last and len(nonempty) == 1:
            ok = ok & mp[:, 0] & (l.lengths == m) if m <= w \
                else jnp.zeros_like(ok)
            break
        if is_first:
            ok = ok & (mp[:, 0] if m <= w else jnp.zeros_like(ok))
            pos = jnp.full((n,), m, dtype=jnp.int32)
            continue
        if is_last:
            p = l.lengths - m
            got = jnp.take_along_axis(
                mp, jnp.clip(p, 0, w - 1)[:, None], axis=1)[:, 0]
            ok = ok & got & (p >= pos)
            continue
        # middle (or leading-%%) segment: leftmost occurrence >= pos
        cand = mp & (jnp.arange(w)[None, :] >= pos[:, None])
        found = jnp.any(cand, axis=1)
        first = jnp.argmax(cand, axis=1).astype(jnp.int32)
        ok = ok & found
        pos = first + m
    return ColVal(dt.BOOL, ok, l.validity)


def _eval_concat(e, batch):
    vals = [evaluate(c, batch) for c in e.children]
    total_w = sum(v.data.shape[1] for v in vals)
    out_w = 1 << max(0, (total_w - 1)).bit_length()
    rows = vals[0].data.shape[0]
    out = jnp.zeros((rows, out_w), dtype=jnp.uint8)
    out_len = jnp.zeros((rows,), dtype=jnp.int32)
    valid = jnp.ones((rows,), dtype=jnp.bool_)
    idx = jnp.arange(out_w)[None, :]
    for v in vals:
        w = v.data.shape[1]
        # scatter v at offset out_len: out[i, out_len[i]+j] = v[i, j]
        src_idx = jnp.clip(idx - out_len[:, None], 0, w - 1)
        sv = jnp.take_along_axis(v.data, src_idx, axis=1)
        write = (idx >= out_len[:, None]) & \
                (idx < (out_len + v.lengths)[:, None])
        out = jnp.where(write, sv, out)
        out_len = out_len + v.lengths
        valid = valid & v.validity
    return ColVal(dt.STRING, out, valid, jnp.where(valid, out_len, 0))


def _trim_bounds(c: ColVal, left: bool, right: bool):
    w = c.data.shape[1]
    idx = jnp.arange(w)[None, :]
    in_str = idx < c.lengths[:, None]
    is_space = (c.data == ord(" ")) & in_str
    non_space = in_str & ~is_space
    any_ns = jnp.any(non_space, axis=1)
    first_ns = jnp.argmax(non_space, axis=1)
    last_ns = (w - 1) - jnp.argmax(non_space[:, ::-1], axis=1)
    start = jnp.where(any_ns & left, first_ns, 0) if left else \
        jnp.zeros_like(c.lengths)
    end = jnp.where(any_ns, last_ns + 1, 0) if right else c.lengths
    start = jnp.where(any_ns, start, 0)
    end = jnp.where(any_ns, end, 0) if (left or right) else end
    return start.astype(jnp.int32), end.astype(jnp.int32)


def _mk_trim(left: bool, right: bool):
    def f(e, batch):
        c = evaluate(e.child, batch)
        w = c.data.shape[1]
        start, end = _trim_bounds(c, left, right)
        out_len = end - start
        idx = jnp.arange(w)[None, :]
        src = jnp.clip(start[:, None] + idx, 0, w - 1)
        data = jnp.take_along_axis(c.data, src, axis=1)
        data = jnp.where(idx < out_len[:, None], data, 0)
        return ColVal(dt.STRING, data, c.validity,
                      jnp.where(c.validity, out_len, 0))
    return f


def _eval_initcap(e, batch):
    c = evaluate(e.child, batch)
    w = c.data.shape[1]
    prev_is_sep = jnp.concatenate(
        [jnp.ones((c.data.shape[0], 1), dtype=jnp.bool_),
         c.data[:, :-1] == ord(" ")], axis=1)
    lower = (c.data >= ord("a")) & (c.data <= ord("z"))
    upper = (c.data >= ord("A")) & (c.data <= ord("Z"))
    data = jnp.where(prev_is_sep & lower, c.data - 32,
                     jnp.where(~prev_is_sep & upper, c.data + 32, c.data))
    return ColVal(dt.STRING, data, c.validity, c.lengths)


def _eval_locate(e, batch):
    sub_e, str_e, start_e = e.children
    s = evaluate(str_e, batch)
    needle = _needle_bytes(sub_e)
    if not isinstance(start_e, ir.Literal):
        raise NotImplementedError("locate start must be literal")
    start = int(start_e.value or 1)
    m, w = len(needle), s.data.shape[1]
    if m == 0:
        pos = jnp.full((s.data.shape[0],), start, dtype=jnp.int32)
        return ColVal(dt.INT32, pos, s.validity)
    if m > w:
        return ColVal(dt.INT32, jnp.zeros((s.data.shape[0],), jnp.int32),
                      s.validity)
    match = jnp.ones((s.data.shape[0], w - m + 1), dtype=jnp.bool_)
    for j, byte in enumerate(needle):
        match = match & (s.data[:, j:j + (w - m + 1)] == byte)
    starts = jnp.arange(w - m + 1)[None, :]
    match = match & (starts + m <= s.lengths[:, None]) & \
        (starts >= start - 1)
    any_m = jnp.any(match, axis=1)
    first = jnp.argmax(match, axis=1)
    return ColVal(dt.INT32, jnp.where(any_m, first + 1, 0), s.validity)


def _mk_pad(left: bool):
    def f(e, batch):
        s = evaluate(e.children[0], batch)
        len_e, pad_e = e.children[1], e.children[2]
        if not isinstance(len_e, ir.Literal) or \
           not isinstance(pad_e, ir.Literal):
            raise NotImplementedError("pad length/fill must be literals")
        target = max(int(len_e.value), 0)  # Spark: len<=0 -> empty string
        pad = (pad_e.value or "").encode("utf-8")
        rows, w = s.data.shape
        out_w = max(1, 1 << max(0, (max(target, 1) - 1)).bit_length())
        idx = jnp.arange(out_w)[None, :]
        pad_arr = jnp.asarray(np.frombuffer(pad, dtype=np.uint8) if pad
                              else np.zeros(1, dtype=np.uint8))
        src = jnp.pad(s.data, ((0, 0), (0, max(0, out_w - w))))[:, :out_w]
        cur = jnp.minimum(s.lengths, target)
        if not pad:
            # empty pad string: Spark returns the (possibly truncated) input
            out_len = cur
            data = jnp.where(idx < out_len[:, None], src, 0)
        else:
            out_len = jnp.full_like(s.lengths, target)
            n_pad = jnp.maximum(target - s.lengths, 0)
            if left:
                body_idx = jnp.clip(idx - n_pad[:, None], 0, out_w - 1)
                body = jnp.take_along_axis(src, body_idx, axis=1)
                fill_pos = idx  # pad cycles from position 0
                in_pad = idx < n_pad[:, None]
            else:
                body = src
                fill_pos = jnp.clip(idx - cur[:, None], 0, None)
                in_pad = idx >= cur[:, None]
            fill = pad_arr[jnp.mod(fill_pos, len(pad))]
            data = jnp.where(in_pad, fill, body)
            data = jnp.where(idx < out_len[:, None], data, 0)
        return ColVal(dt.STRING, data, s.validity,
                      jnp.where(s.validity, out_len, 0))
    return f


# ---------------------------------------------------------------------------
# date/time (UTC only; civil-calendar math after Howard Hinnant's algorithms)
# ---------------------------------------------------------------------------

def _civil_from_days(days: jnp.ndarray):
    z = days.astype(jnp.int64) + 719468
    era = jnp.floor_divide(z, 146097)
    doe = z - era * 146097
    yoe = (doe - doe // 1460 + doe // 36524 - doe // 146096) // 365
    y = yoe + era * 400
    doy = doe - (365 * yoe + yoe // 4 - yoe // 100)
    mp = (5 * doy + 2) // 153
    d = doy - (153 * mp + 2) // 5 + 1
    m = jnp.where(mp < 10, mp + 3, mp - 9)
    y = jnp.where(m <= 2, y + 1, y)
    return y.astype(jnp.int32), m.astype(jnp.int32), d.astype(jnp.int32)


def _days_from_civil(y, m, d):
    y = y - (m <= 2)
    era = jnp.floor_divide(y, 400)
    yoe = y - era * 400
    mp = jnp.where(m > 2, m - 3, m + 9)
    doy = (153 * mp + 2) // 5 + d - 1
    doe = yoe * 365 + yoe // 4 - yoe // 100 + doy
    return era * 146097 + doe - 719468


def _to_days(c: ColVal) -> jnp.ndarray:
    if c.dtype.id == dt.TypeId.TIMESTAMP_US:
        return jnp.floor_divide(c.data, _US_PER_DAY)
    return c.data.astype(jnp.int64)


def _mk_datefield(which: str):
    def f(e, batch):
        c = evaluate(e.child, batch)
        days = _to_days(c)
        y, m, d = _civil_from_days(days)
        if which == "year":
            out = y
        elif which == "month":
            out = m
        elif which == "day":
            out = d
        elif which == "quarter":
            out = (m - 1) // 3 + 1
        elif which == "dayofweek":   # Sun=1..Sat=7
            out = (jnp.mod(days + 4, 7) + 1).astype(jnp.int32)
        elif which == "dayofyear":
            jan1 = _days_from_civil(y, jnp.ones_like(m), jnp.ones_like(d))
            out = (days - jan1 + 1).astype(jnp.int32)
        elif which == "weekofyear":  # ISO 8601
            wd = jnp.mod(days + 3, 7)  # Mon=0..Sun=6
            thursday = days - wd + 3
            ty, tm, td = _civil_from_days(thursday)
            jan1 = _days_from_civil(ty, jnp.ones_like(tm), jnp.ones_like(td))
            out = ((thursday - jan1) // 7 + 1).astype(jnp.int32)
        else:
            raise AssertionError(which)
        return ColVal(dt.INT32, out.astype(jnp.int32), c.validity)
    return f


def _mk_timefield(which: str):
    def f(e, batch):
        c = evaluate(e.child, batch)
        us = jnp.mod(c.data, _US_PER_DAY)
        if which == "hour":
            out = us // (3600 * 1000 * 1000)
        elif which == "minute":
            out = (us // (60 * 1000 * 1000)) % 60
        else:
            out = (us // (1000 * 1000)) % 60
        return ColVal(dt.INT32, out.astype(jnp.int32), c.validity)
    return f


def _eval_dateadd(e, batch):
    l, r = evaluate(e.left, batch), evaluate(e.right, batch)
    return ColVal(dt.DATE32,
                  (l.data.astype(jnp.int64) +
                   r.data.astype(jnp.int64)).astype(jnp.int32),
                  _binary_null(l, r))


def _eval_datesub(e, batch):
    l, r = evaluate(e.left, batch), evaluate(e.right, batch)
    return ColVal(dt.DATE32,
                  (l.data.astype(jnp.int64) -
                   r.data.astype(jnp.int64)).astype(jnp.int32),
                  _binary_null(l, r))


def _eval_datediff(e, batch):
    l, r = evaluate(e.left, batch), evaluate(e.right, batch)
    return ColVal(dt.INT32,
                  (_to_days(l) - _to_days(r)).astype(jnp.int32),
                  _binary_null(l, r))


def _eval_unix_ts(e, batch):
    c = evaluate(e.child, batch)
    return ColVal(dt.INT64, jnp.floor_divide(c.data, 1000 * 1000), c.validity)


# ---------------------------------------------------------------------------
# hash: Spark-compatible murmur3_x86_32 (seed 42), vectorized
# (reference: GpuMurmur3Hash via cudf murmur3; Spark Murmur3_x86_32)
# ---------------------------------------------------------------------------

_C1 = np.int32(np.uint32(0xCC9E2D51))
_C2 = np.int32(np.uint32(0x1B873593))


def _rotl(x, r):
    ux = x.astype(jnp.uint32)
    return ((ux << r) | (ux >> (32 - r))).astype(jnp.int32)


def _mix_k1(k1):
    k1 = (k1.astype(jnp.int32) * _C1)
    k1 = _rotl(k1, 15)
    return k1 * _C2


def _mix_h1(h1, k1):
    h1 = h1 ^ k1
    h1 = _rotl(h1, 13)
    return (h1 * np.int32(5) + np.int32(np.uint32(0xE6546B64))).astype(
        jnp.int32)


def _fmix(h1, length):
    h1 = h1 ^ length
    u = h1.astype(jnp.uint32)
    u = u ^ (u >> 16)
    u = u * np.uint32(0x85EBCA6B)
    u = u ^ (u >> 13)
    u = u * np.uint32(0xC2B2AE35)
    u = u ^ (u >> 16)
    return u.astype(jnp.int32)


def _hash_int(v32: jnp.ndarray, seed: jnp.ndarray) -> jnp.ndarray:
    h1 = _mix_h1(seed, _mix_k1(v32))
    return _fmix(h1, jnp.int32(4))


def _hash_long(v64: jnp.ndarray, seed: jnp.ndarray) -> jnp.ndarray:
    lo = v64.astype(jnp.int32)
    hi = (v64 >> 32).astype(jnp.int32)
    h1 = _mix_h1(seed, _mix_k1(lo))
    h1 = _mix_h1(h1, _mix_k1(hi))
    return _fmix(h1, jnp.int32(8))


def _hash_bytes(data: jnp.ndarray, lengths: jnp.ndarray,
                seed: jnp.ndarray) -> jnp.ndarray:
    """Spark hashUnsafeBytes over each row of a byte matrix (tail-safe)."""
    rows, w = data.shape
    nwords = (w + 3) // 4
    padded = jnp.pad(data, ((0, 0), (0, nwords * 4 - w))).astype(jnp.int32)
    h1 = seed if seed.ndim else jnp.full((rows,), seed, dtype=jnp.int32)
    # Spark's Murmur3_x86_32.hashUnsafeBytes processes 4-byte words in
    # little-endian order, then the tail bytes one at a time (signed!).
    for wi in range(nwords):
        b0 = padded[:, wi * 4 + 0]
        b1 = padded[:, wi * 4 + 1]
        b2 = padded[:, wi * 4 + 2]
        b3 = padded[:, wi * 4 + 3]
        word = (b0 | (b1 << 8) | (b2 << 16) | (b3 << 24)).astype(jnp.int32)
        full = lengths >= (wi + 1) * 4
        h1 = jnp.where(full, _mix_h1(h1, _mix_k1(word)), h1)
    # tail: bytes beyond the last full word, one at a time (sign-extended)
    for bi in range(nwords * 4):
        in_tail = (bi >= (lengths // 4) * 4) & (bi < lengths)
        byte = padded[:, bi].astype(jnp.int8).astype(jnp.int32)
        h1 = jnp.where(in_tail, _mix_h1(h1, _mix_k1(byte)), h1)
    return _fmix(h1, lengths.astype(jnp.int32))


def hash_colval(v: ColVal, seed: jnp.ndarray) -> jnp.ndarray:
    """One murmur3 step for one column; null keeps the previous seed
    (Spark semantics: null columns are skipped)."""
    d = v.dtype
    if d.is_string:
        h = _hash_bytes(v.data, v.lengths, seed)
    elif d.id in (dt.TypeId.INT64, dt.TypeId.TIMESTAMP_US):
        h = _hash_long(v.data, seed)
    elif d.id == dt.TypeId.FLOAT64:
        x = jnp.where(v.data == 0.0, 0.0, v.data)  # -0.0 -> 0.0
        x = jnp.where(jnp.isnan(x), jnp.float64(np.nan), x)
        h = _hash_long(f64_bits(x).astype(jnp.int64), seed)
    elif d.id == dt.TypeId.FLOAT32:
        x = jnp.where(v.data == 0.0, jnp.float32(0.0), v.data)
        x = jnp.where(jnp.isnan(x), jnp.float32(np.nan), x)
        h = _hash_int(x.view(jnp.int32), seed)
    elif d.is_bool:
        h = _hash_int(v.data.astype(jnp.int32), seed)
    else:  # int8/16/32/date32 hash as int
        h = _hash_int(v.data.astype(jnp.int32), seed)
    return jnp.where(v.validity, h, seed)


def _eval_murmur3(e: ir.Murmur3Hash, batch):
    seed = jnp.full((batch.capacity,), np.int32(e.seed), dtype=jnp.int32)
    h = seed
    for c in e.children:
        v = evaluate(c, batch)
        h = hash_colval(v, h)
    return ColVal(dt.INT32, h, jnp.ones((batch.capacity,), dtype=jnp.bool_))


def _eval_knownfloat(e, batch):
    c = evaluate(e.child, batch)
    if c.dtype.is_floating:
        nan = jnp.array(np.nan, dtype=c.data.dtype)
        x = jnp.where(jnp.isnan(c.data), nan, c.data)
        x = jnp.where(x == 0.0, jnp.zeros_like(x), x)  # -0.0 -> +0.0
        return ColVal(c.dtype, x, c.validity)
    return c


def _eval_partition_id(e, batch):
    from spark_rapids_tpu.exec import context
    pid, _ = context.get()
    data = jnp.full((batch.capacity,), 0, dtype=jnp.int32) + \
        jnp.asarray(pid, dtype=jnp.int32)
    return ColVal(dt.INT32, data,
                  jnp.ones((batch.capacity,), dtype=jnp.bool_))


def _eval_monotonic_id(e, batch):
    # Spark: (partitionId << 33) + row offset within partition
    from spark_rapids_tpu.exec import context
    pid, off = context.get()
    base = (jnp.asarray(pid, dtype=jnp.int64) << 33) + \
        jnp.asarray(off, dtype=jnp.int64)
    data = base + jnp.arange(batch.capacity, dtype=jnp.int64)
    return ColVal(dt.INT64, data,
                  jnp.ones((batch.capacity,), dtype=jnp.bool_))


def _eval_rand(e: ir.Rand, batch):
    key = jax.random.PRNGKey(e.seed)
    vals = jax.random.uniform(key, (batch.capacity,), dtype=jnp.float64)
    return ColVal(dt.FLOAT64, vals,
                  jnp.ones((batch.capacity,), dtype=jnp.bool_))




# ---------------------------------------------------------------------------
# complex types: list columns are (padded [cap, max_len] payload, lengths,
# elem_validity) — the same fixed-width device layout as strings, so these
# kernels are masked gathers/reductions XLA fuses (reference:
# complexTypeExtractors.scala on cudf list columns)
# ---------------------------------------------------------------------------

def _eval_size(e: ir.Size, batch: DeviceBatch) -> ColVal:
    v = evaluate(e.children[0], batch)
    out = jnp.where(v.validity, v.lengths.astype(jnp.int32),
                    np.int32(-1))   # Spark 3.0 legacy: size(null) = -1
    return ColVal(dt.INT32, out,
                  jnp.ones((batch.capacity,), dtype=jnp.bool_))


def _eval_get_array_item(e: ir.GetArrayItem, batch: DeviceBatch) -> ColVal:
    v = evaluate(e.children[0], batch)
    o = evaluate(e.children[1], batch)
    idx = o.data.astype(jnp.int32)
    in_range = (idx >= 0) & (idx < v.lengths) & v.validity & o.validity
    safe = jnp.clip(idx, 0, v.data.shape[1] - 1)
    data = jnp.take_along_axis(v.data, safe[:, None], axis=1)[:, 0]
    ev = jnp.take_along_axis(v.elem_validity, safe[:, None], axis=1)[:, 0] \
        if v.elem_validity is not None else jnp.ones_like(in_range)
    valid = in_range & ev
    el = e.dtype
    return ColVal(el, jnp.where(valid, data, 0).astype(el.to_np()), valid)


def _eval_array_contains(e: ir.ArrayContains, batch: DeviceBatch) -> ColVal:
    v = evaluate(e.children[0], batch)
    x = evaluate(e.children[1], batch)
    max_len = v.data.shape[1]
    slot = jnp.arange(max_len)[None, :] < v.lengths[:, None]
    ev = v.elem_validity if v.elem_validity is not None else \
        jnp.ones(v.data.shape, dtype=jnp.bool_)
    live = slot & ev
    # compare in the promoted type so fractional probes never truncate
    # (matches the CPU engine: 2.5 vs array<int> finds nothing)
    el = v.dtype.element
    if el != x.dtype and el.is_numeric and x.dtype.is_numeric:
        cmp_np = dt.promote(el, x.dtype).to_np()
    else:
        cmp_np = v.data.dtype
    eq = (v.data.astype(cmp_np) == x.data.astype(cmp_np)[:, None]) & live
    found = jnp.any(eq, axis=1)
    has_null_elem = jnp.any(slot & ~ev, axis=1)
    valid = v.validity & x.validity & (found | ~has_null_elem)
    return ColVal(dt.BOOL, found & v.validity & x.validity, valid)


def _eval_element_at(e: ir.ElementAt, batch: DeviceBatch) -> ColVal:
    v = evaluate(e.children[0], batch)
    o = evaluate(e.children[1], batch)
    k = o.data.astype(jnp.int32)
    idx = jnp.where(k > 0, k - 1, v.lengths.astype(jnp.int32) + k)
    in_range = (k != 0) & (idx >= 0) & (idx < v.lengths) & \
        v.validity & o.validity
    safe = jnp.clip(idx, 0, v.data.shape[1] - 1)
    data = jnp.take_along_axis(v.data, safe[:, None], axis=1)[:, 0]
    ev = jnp.take_along_axis(v.elem_validity, safe[:, None], axis=1)[:, 0] \
        if v.elem_validity is not None else jnp.ones_like(in_range)
    valid = in_range & ev
    el = e.dtype
    return ColVal(el, jnp.where(valid, data, 0).astype(el.to_np()), valid)


def _eval_create_array(e: ir.CreateArray, batch: DeviceBatch) -> ColVal:
    el = e.dtype.element
    np_dt = el.to_np()
    vals = [evaluate(c, batch) for c in e.children]
    data = jnp.stack([v.data.astype(np_dt) for v in vals], axis=1)
    ev = jnp.stack([v.validity for v in vals], axis=1)
    n = len(vals)
    lengths = jnp.full((batch.capacity,), n, dtype=jnp.int32)
    return ColVal(e.dtype, data,
                  jnp.ones((batch.capacity,), dtype=jnp.bool_),
                  lengths, ev)


# ---------------------------------------------------------------------------
# dispatch table
# ---------------------------------------------------------------------------

_DISPATCH = {
    ir.Literal: _eval_literal,
    ir.BoundReference: _eval_bound,
    ir.Alias: _eval_alias,
    ir.Size: _eval_size,
    ir.GetArrayItem: _eval_get_array_item,
    ir.ArrayContains: _eval_array_contains,
    ir.ElementAt: _eval_element_at,
    ir.CreateArray: _eval_create_array,
    ir.Add: _eval_add,
    ir.Subtract: _eval_sub,
    ir.Multiply: _eval_mul,
    ir.Divide: _eval_div,
    ir.IntegralDivide: _eval_idiv,
    ir.Remainder: _eval_mod,
    ir.Pmod: _eval_pmod,
    ir.UnaryMinus: _eval_neg,
    ir.UnaryPositive: _eval_pos,
    ir.Abs: _eval_abs,
    ir.EqualTo: _mk_cmp("eq"),
    ir.LessThan: _mk_cmp("lt"),
    ir.LessThanOrEqual: _mk_cmp("le"),
    ir.GreaterThan: _mk_cmp("gt"),
    ir.GreaterThanOrEqual: _mk_cmp("ge"),
    ir.And: _eval_and,
    ir.Or: _eval_or,
    ir.Not: _eval_not,
    ir.In: _eval_in,
    ir.IsNull: _eval_isnull,
    ir.IsNotNull: _eval_isnotnull,
    ir.IsNan: _eval_isnan,
    ir.Coalesce: _eval_coalesce,
    ir.AtLeastNNonNulls: _eval_at_least_n_non_nulls,
    ir.NaNvl: _eval_nanvl,
    ir.If: _eval_if,
    ir.CaseWhen: _eval_casewhen,
    ir.Sqrt: _mk_double_unary(jnp.sqrt),
    ir.Exp: _mk_double_unary(jnp.exp),
    ir.Log: _eval_log,
    ir.Log2: _mk_logbase(math.log(2.0)),
    ir.Log10: _mk_logbase(math.log(10.0)),
    ir.Log1p: _eval_log1p,
    ir.Expm1: _mk_double_unary(jnp.expm1),
    ir.Sin: _mk_double_unary(jnp.sin),
    ir.Cos: _mk_double_unary(jnp.cos),
    ir.Tan: _mk_double_unary(jnp.tan),
    ir.Sinh: _mk_double_unary(jnp.sinh),
    ir.Cosh: _mk_double_unary(jnp.cosh),
    ir.Tanh: _mk_double_unary(jnp.tanh),
    ir.Asin: _mk_double_unary(jnp.arcsin),
    ir.Acos: _mk_double_unary(jnp.arccos),
    ir.Atan: _mk_double_unary(jnp.arctan),
    ir.Cbrt: _mk_double_unary(jnp.cbrt),
    ir.ToDegrees: _mk_double_unary(jnp.degrees),
    ir.ToRadians: _mk_double_unary(jnp.radians),
    ir.Rint: _mk_double_unary(jnp.round),
    ir.Signum: _mk_double_unary(jnp.sign),
    ir.Ceil: _eval_ceil,
    ir.Floor: _eval_floor,
    ir.Pow: _eval_pow,
    ir.Atan2: _eval_atan2,
    ir.ShiftLeft: _eval_shiftleft,
    ir.ShiftRight: _eval_shiftright,
    ir.ShiftRightUnsigned: _eval_shiftright_unsigned,
    ir.Cast: _eval_cast,
    ir.Upper: _eval_upper,
    ir.Lower: _eval_lower,
    ir.Length: _eval_length,
    ir.Substring: _eval_substring,
    ir.StartsWith: _eval_startswith,
    ir.EndsWith: _eval_endswith,
    ir.Contains: _eval_contains,
    ir.Like: _eval_like,
    ir.Concat: _eval_concat,
    ir.StringTrim: _mk_trim(True, True),
    ir.StringTrimLeft: _mk_trim(True, False),
    ir.StringTrimRight: _mk_trim(False, True),
    ir.InitCap: _eval_initcap,
    ir.StringLocate: _eval_locate,
    ir.LPad: _mk_pad(True),
    ir.RPad: _mk_pad(False),
    ir.Year: _mk_datefield("year"),
    ir.Month: _mk_datefield("month"),
    ir.DayOfMonth: _mk_datefield("day"),
    ir.DayOfYear: _mk_datefield("dayofyear"),
    ir.DayOfWeek: _mk_datefield("dayofweek"),
    ir.WeekOfYear: _mk_datefield("weekofyear"),
    ir.Quarter: _mk_datefield("quarter"),
    ir.Hour: _mk_timefield("hour"),
    ir.Minute: _mk_timefield("minute"),
    ir.Second: _mk_timefield("second"),
    ir.DateAdd: _eval_dateadd,
    ir.DateSub: _eval_datesub,
    ir.DateDiff: _eval_datediff,
    ir.UnixTimestampFromTs: _eval_unix_ts,
    ir.Murmur3Hash: _eval_murmur3,
    ir.KnownFloatingPointNormalized: _eval_knownfloat,
    ir.SparkPartitionID: _eval_partition_id,
    ir.MonotonicallyIncreasingID: _eval_monotonic_id,
    ir.Rand: _eval_rand,
}


def supported_on_tpu(cls) -> bool:
    return cls in _DISPATCH


# ---------------------------------------------------------------------------
# md5 (reference: HashFunctions.scala GpuMd5 via cudf; here the full MD5
# block function vectorized over rows, fori-looped over 64-byte blocks)
# ---------------------------------------------------------------------------

_MD5_S = np.array(
    [7, 12, 17, 22] * 4 + [5, 9, 14, 20] * 4 + [4, 11, 16, 23] * 4 +
    [6, 10, 15, 21] * 4, dtype=np.int32)
_MD5_K = np.array([int(abs(np.sin(i + 1)) * (1 << 32)) & 0xFFFFFFFF
                   for i in range(64)], dtype=np.uint32)
_MD5_G = np.array(
    [i for i in range(16)] +
    [(5 * i + 1) % 16 for i in range(16)] +
    [(3 * i + 5) % 16 for i in range(16)] +
    [(7 * i) % 16 for i in range(16)], dtype=np.int32)


def _eval_md5(e, batch):
    c = evaluate(e.child, batch)
    if not c.dtype.is_string:
        raise NotImplementedError("md5 over non-string on TPU")
    data, lengths = c.data, c.lengths.astype(jnp.int64)
    n, w = data.shape
    # padded message: data + 0x80 + zeros + 8-byte little-endian bitlen
    n_blocks = (w + 9 + 63) // 64
    total = n_blocks * 64
    idx = jnp.arange(total)[None, :]
    msg = jnp.zeros((n, total), dtype=jnp.uint32)
    msg = msg.at[:, :w].set(
        jnp.where(jnp.arange(w)[None, :] < lengths[:, None],
                  data.astype(jnp.uint32), 0))
    msg = jnp.where(idx == lengths[:, None], jnp.uint32(0x80), msg)
    # per-row block count: message fits in ceil((len+9)/64) blocks
    row_blocks = (lengths + 9 + 63) // 64
    bitlen = (lengths * 8).astype(jnp.uint64)
    lenpos = row_blocks * 64 - 8
    for k in range(8):
        byte = ((bitlen >> jnp.uint64(8 * k)) &
                jnp.uint64(0xFF)).astype(jnp.uint32)
        msg = jnp.where(idx == (lenpos + k)[:, None], byte[:, None],
                        msg)
    # bytes -> 16 little-endian u32 words per block
    words = (msg[:, 0::4] | (msg[:, 1::4] << 8) | (msg[:, 2::4] << 16) |
             (msg[:, 3::4] << 24))          # [n, n_blocks*16]

    def rotl(x, s):
        return ((x << s) | (x >> (32 - s))) & jnp.uint32(0xFFFFFFFF)

    a0 = jnp.full((n,), 0x67452301, jnp.uint32)
    b0 = jnp.full((n,), 0xEFCDAB89, jnp.uint32)
    c0 = jnp.full((n,), 0x98BADCFE, jnp.uint32)
    d0 = jnp.full((n,), 0x10325476, jnp.uint32)

    def block(bi, carry):
        a0, b0, c0, d0 = carry
        base = bi * 16
        m = jax.lax.dynamic_slice_in_dim(words, base, 16, axis=1)
        A, B, C, D = a0, b0, c0, d0
        for i in range(64):
            if i < 16:
                F = (B & C) | (~B & D)
            elif i < 32:
                F = (D & B) | (~D & C)
            elif i < 48:
                F = B ^ C ^ D
            else:
                F = C ^ (B | ~D)
            F = (F + A + jnp.uint32(_MD5_K[i]) +
                 m[:, int(_MD5_G[i])]) & jnp.uint32(0xFFFFFFFF)
            A = D
            D = C
            C = B
            B = (B + rotl(F, int(_MD5_S[i]))) & jnp.uint32(0xFFFFFFFF)
        active = bi < row_blocks
        return (jnp.where(active, (a0 + A) & jnp.uint32(0xFFFFFFFF), a0),
                jnp.where(active, (b0 + B) & jnp.uint32(0xFFFFFFFF), b0),
                jnp.where(active, (c0 + C) & jnp.uint32(0xFFFFFFFF), c0),
                jnp.where(active, (d0 + D) & jnp.uint32(0xFFFFFFFF), d0))

    a0, b0, c0, d0 = jax.lax.fori_loop(0, n_blocks, block,
                                       (a0, b0, c0, d0))
    # digest: a,b,c,d little-endian bytes -> 32 hex chars
    digest_bytes = []
    for word in (a0, b0, c0, d0):
        for k in range(4):
            digest_bytes.append((word >> (8 * k)) & jnp.uint32(0xFF))
    hexmat = []
    for byte in digest_bytes:
        hi = byte >> 4
        lo = byte & 0xF
        hexmat.append(jnp.where(hi < 10, hi + ord("0"),
                                hi - 10 + ord("a")).astype(jnp.uint8))
        hexmat.append(jnp.where(lo < 10, lo + ord("0"),
                                lo - 10 + ord("a")).astype(jnp.uint8))
    out = jnp.stack(hexmat, axis=1)
    lens = jnp.where(c.validity, 32, 0).astype(jnp.int32)
    out = jnp.where(c.validity[:, None], out, 0)
    return ColVal(dt.STRING, out, c.validity, lens)


_DISPATCH[ir.Md5] = _eval_md5


_REGEX_META = set(".^$*+?()[]{}|\\")


def _emit_replaced(s, starts, covered, rep_bytes, w_out):
    """Shared regexp_replace emission: given per-position start flags
    (emit the replacement) and covered flags (emit nothing; starts take
    precedence), scatter copy-through characters and replacement bytes
    into a fresh byte matrix."""
    n, w = s.data.shape
    lr = len(rep_bytes)
    pos = jnp.arange(w)[None, :]
    in_str = pos < s.lengths[:, None]
    emit = jnp.where(starts, lr,
                     jnp.where(covered, 0, 1)) * in_str.astype(jnp.int32)
    out_pos = jnp.cumsum(emit, axis=1) - emit
    out_len = jnp.sum(emit, axis=1).astype(jnp.int32)

    row = jnp.arange(n)[:, None]
    flat = jnp.zeros((n * w_out,), dtype=jnp.uint8)
    # copy-through characters
    plain = in_str & ~covered & ~starts
    tgt = jnp.where(plain, row * w_out + out_pos, n * w_out)
    flat = flat.at[tgt.reshape(-1)].set(
        s.data.reshape(-1), mode="drop")
    # replacement bytes
    for k, byte in enumerate(rep_bytes):
        tgt = jnp.where(starts & in_str, row * w_out + out_pos + k,
                        n * w_out)
        flat = flat.at[tgt.reshape(-1)].set(jnp.uint8(byte),
                                            mode="drop")
    data = flat.reshape(n, w_out)
    keep = jnp.arange(w_out)[None, :] < out_len[:, None]
    data = jnp.where(keep & s.validity[:, None], data, 0)
    return ColVal(dt.STRING, data, s.validity,
                  jnp.where(s.validity, out_len, 0))


def _replace_out_width(w: int, min_match: int, lr: int) -> int:
    from spark_rapids_tpu.columnar.batch import _bucket_strlen
    w_out = w if lr <= min_match else \
        (w // max(min_match, 1)) * lr + w
    return _bucket_strlen(w_out)


def _eval_regexp_replace(e, batch):
    """regexp_replace with a literal pattern: metacharacter-free
    patterns use the direct occurrence scan; real regex in the
    device_regex.py subset (char classes, anchors, greedy quantifiers,
    groups — no alternation, which diverges from Java's leftmost-branch
    semantics, and no empty-matchable patterns) runs the bitmask NFA
    and replaces the LONGEST match per start.  The planner falls back
    for everything else (reference: Spark300Shims.scala:183-247
    GpuRegExpReplace, likewise restricted/incompat-flagged).  Greedy
    leftmost non-overlapping, like java.util.regex.
    """
    s = evaluate(e.children[0], batch)
    pat = e.children[1]
    rep = e.children[2]
    if not isinstance(pat, ir.Literal) or pat.value is None or \
            not isinstance(rep, ir.Literal) or rep.value is None:
        raise NotImplementedError("regexp_replace pattern/replacement "
                                  "must be literals on TPU")
    needle = pat.value.encode("utf-8")
    r = rep.value.encode("utf-8")
    n, w = s.data.shape
    pos = jnp.arange(w)[None, :]

    if needle and not any(chr(b) in _REGEX_META for b in needle):
        # -- literal fast path: occurrence candidates via shifted
        # equality (needle fits at p, inside the string)
        m = len(needle)
        if m > w:
            occ = jnp.zeros((n, w), dtype=jnp.bool_)
        else:
            span = w - m + 1
            match = jnp.ones((n, span), dtype=jnp.bool_)
            for j, byte in enumerate(needle):
                match = match & (s.data[:, j:j + span] == byte)
            match = match & (jnp.arange(span)[None, :] + m <=
                             s.lengths[:, None])
            occ = jnp.pad(match, ((0, 0), (0, w - span)))

        # greedy leftmost non-overlap: a start is real if no real start
        # in the previous m-1 positions — sequential scan via fori
        def body(p, carry):
            starts, next_free = carry
            here = occ[:, p] & (p >= next_free)
            starts = jax.lax.dynamic_update_index_in_dim(
                starts, here, p, axis=1)
            next_free = jnp.where(here, p + m, next_free)
            return starts, next_free
        starts, _ = jax.lax.fori_loop(
            0, w, body, (jnp.zeros((n, w), jnp.bool_),
                         jnp.zeros((n,), jnp.int32)))

        sstart = jnp.where(starts, pos, -(1 << 30))
        last = jax.lax.associative_scan(jnp.maximum, sstart, axis=1)
        covered = (pos - last) < m
        return _emit_replaced(s, starts, covered, r,
                              _replace_out_width(w, m, len(r)))

    # -- NFA subset path -------------------------------------------------
    from spark_rapids_tpu.expr import device_regex as dr
    try:
        cr = dr.compile_pattern(pat.value)
    except dr.Unsupported as ex:
        raise NotImplementedError(f"regex pattern outside the device "
                                  f"subset: {ex}")
    if not cr.replace_safe:
        # Java's greedy-backtracking match (leftmost alternation
        # branch; earlier quantifiers maximized first) only provably
        # equals the longest-end table for single-variable-element
        # patterns — see CompiledRegex.replace_safe
        raise NotImplementedError("regexp_replace pattern where Java "
                                  "greedy semantics may differ from "
                                  "longest-match")
    if b"$" in r or b"\\" in r:
        raise NotImplementedError("group references in replacement")
    ends = dr.match_ends(cr, s.data, s.lengths)   # [n, w] excl, -1

    def body(p, carry):
        starts, covered, cur_end = carry
        cov_p = p < cur_end
        here = (ends[:, p] >= 0) & ~cov_p
        starts = jax.lax.dynamic_update_index_in_dim(
            starts, here, p, axis=1)
        covered = jax.lax.dynamic_update_index_in_dim(
            covered, cov_p, p, axis=1)
        cur_end = jnp.where(here, ends[:, p], cur_end)
        return starts, covered, cur_end
    starts, covered, _ = jax.lax.fori_loop(
        0, w, body, (jnp.zeros((n, w), jnp.bool_),
                     jnp.zeros((n, w), jnp.bool_),
                     jnp.zeros((n,), jnp.int32)))
    return _emit_replaced(s, starts, covered, r,
                          _replace_out_width(w, cr.min_len, len(r)))


def _eval_rlike(e, batch):
    """RLIKE / regexp find-anywhere predicate over the bitmask NFA
    (device_regex.py); pattern must be a literal in the device subset
    (reference: Spark300Shims.scala:183-247 GpuRLike)."""
    l = evaluate(e.left, batch)
    if isinstance(e.right, ir.Literal) and e.right.value is None:
        n0 = l.data.shape[0]
        return ColVal(dt.BOOL, jnp.zeros((n0,), jnp.bool_),
                      jnp.zeros((n0,), jnp.bool_))  # RLIKE NULL -> NULL
    if not isinstance(e.right, ir.Literal):
        raise NotImplementedError("rlike pattern must be a literal")
    from spark_rapids_tpu.expr import device_regex as dr
    try:
        cr = dr.compile_pattern(e.right.value)
    except dr.Unsupported as ex:
        raise NotImplementedError(f"regex pattern outside the device "
                                  f"subset: {ex}")
    hit = dr.rlike(cr, l.data, l.lengths)
    return ColVal(dt.BOOL, hit, l.validity)


_DISPATCH[ir.RLike] = _eval_rlike
_DISPATCH[ir.StringReverse] = _eval_reverse
_DISPATCH[ir.RegExpReplace] = _eval_regexp_replace
