"""Executor process daemon: runs shipped map stages, serves their output.

Reference analog: a Spark executor JVM running ShuffleMapTasks whose
``RapidsCachingWriter`` registers map output in the executor-local
``ShuffleBufferCatalog``, then serves remote reducer pulls over the
transport (RapidsShuffleInternalManager.scala:90-155, UCX.scala:53-533).
Here the "task ship" is a pickled physical subplan over a length-prefixed
pipe protocol (the pyworker framing idiom), the catalog/server/transport
stack is the engine's own (shuffle/catalogs.py, shuffle/server.py,
shuffle/tcp.py), and the parent's reducers pull through the standard
client/iterator state machines — a planned query genuinely crossing OS
process boundaries.

Protocol (stdin/stdout, binary): frame := u32 len, len pickle bytes.
First frame OUT is the hello ``{"port": p, "pid": n}``.  Frames IN:
``{"op": "map_stage", ...}`` -> runs the exchange's map side against the
local catalog, replies ``{"ok": True, "maps": [...]}``; with
``"stream": True`` the reply is preceded by one
``{"event": "map_done", "map_id": m}`` frame per completed map task
(the pipelined exchange's per-map completion notifications — readers
consume them via ``ExecutorHandle.call_stream``);
``{"op": "ping"}`` -> ``{"ok": True}``; ``{"op": "stop"}`` -> exits.
"""

from __future__ import annotations

import pickle
import struct
import sys
from typing import BinaryIO, Optional

_LEN = struct.Struct("<I")


def write_frame(stream: BinaryIO, obj) -> None:
    payload = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    stream.write(_LEN.pack(len(payload)))
    stream.write(payload)
    stream.flush()


def read_frame(stream: BinaryIO) -> Optional[dict]:
    hdr = stream.read(_LEN.size)
    if len(hdr) < _LEN.size:
        return None
    (n,) = _LEN.unpack(hdr)
    payload = stream.read(n)
    if len(payload) < n:
        return None
    return pickle.loads(payload)


def _run_map_stage(task: dict, catalog, nested_transport: str,
                   notify=None) -> dict:
    """Execute the shipped exchange's map side for this executor's share
    of input partitions, registering slices in the local catalog.

    With ``task["stream"]`` set (the pipelined exchange), ``notify`` is
    called with a ``{"event": "map_done", "map_id": m}`` frame as each
    map task's output lands in the catalog — BEFORE the final reply —
    so the driver's reducers can start fetching that map's blocks while
    later maps are still running (per-map completion notifications, the
    map/fetch overlap leg)."""
    exch = task["exchange"]
    # cross-process trace stitching: when the driver traces, this
    # executor records its own span window for the stage and ships it
    # home with the reply (the collect_plan_metrics idiom for spans);
    # the driver aligns clocks from the request/reply envelope and
    # merges the spans as executor lanes (obs/trace.record_foreign)
    span_mark = None
    from spark_rapids_tpu.obs import trace as obstrace
    if task.get("trace"):
        obstrace.configure(True)
        span_mark = obstrace.mark()
    elif obstrace.is_enabled():
        # the driver stopped tracing: stand the executor tracer back
        # down (and free its ring) — a sticky enable would pay the
        # record() path and hold spans forever on untraced tasks
        obstrace.configure(False)
        obstrace.clear()
    # nested exchanges inside the shipped fragment execute in-process —
    # an executor must not recursively spawn its own executor fleet.
    # With --nested-transport=ici they ride the executor's OWN device
    # mesh instead (the DCN-over-ICI composition: collectives inside
    # each executor, TCP between executors — a TPU pod slice per
    # executor host with DCN across slices).
    nested: list = []

    def _localize(n):
        if getattr(n, "transport", None) == "process" and n is not exch:
            n.transport = nested_transport
            nested.append(nested_transport)
    exch.foreach(_localize)
    on_map_done = None
    if task.get("stream") and notify is not None:
        def on_map_done(map_id: int) -> None:
            notify({"event": "map_done", "map_id": map_id})
    maps = exch.run_map_stage(
        shuffle_id=task["shuffle_id"], catalog=catalog,
        n_execs=task["n_execs"], exec_idx=task["exec_idx"],
        on_map_done=on_map_done)
    # per-node Metrics accumulated while running this fragment go home
    # with the reply (keyed by pre-order node id) — the driver merges
    # them into its own tree so executor-side work is not dropped from
    # the query profile (exec/base.merge_plan_metrics)
    from spark_rapids_tpu.exec.base import collect_plan_metrics
    reply = {"ok": True, "maps": maps, "nested_transports": nested,
             "metrics": collect_plan_metrics(exch)}
    if span_mark is not None:
        import os
        import time
        from spark_rapids_tpu.obs import trace as obstrace
        reply["spans"] = obstrace.spans_since(span_mark)
        # this process's clock at reply construction — the driver's
        # zero-transit fallback alignment when the clock op was lost
        reply["clock_ns"] = time.perf_counter_ns()
        reply["pid"] = os.getpid()
    return reply


def main() -> None:
    import jax
    if "--cpu" in sys.argv:
        jax.config.update("jax_platforms", "cpu")
    executor_id = sys.argv[sys.argv.index("--executor-id") + 1]
    nested_transport = "local"
    if "--nested-transport" in sys.argv:
        nested_transport = sys.argv[
            sys.argv.index("--nested-transport") + 1]

    from spark_rapids_tpu.shuffle.catalogs import ShuffleBufferCatalog
    from spark_rapids_tpu.shuffle.server import ShuffleServer
    from spark_rapids_tpu.shuffle.tcp import TcpShuffleTransport

    inp = sys.stdin.buffer
    out = sys.stdout.buffer
    # anything the shipped plan prints must not corrupt the frame stream
    sys.stdout = sys.stderr

    catalog = ShuffleBufferCatalog()
    transport = TcpShuffleTransport(executor_id, {"listen_port": 0})
    srv_conn = transport.server()
    ShuffleServer(executor_id, catalog, srv_conn)
    write_frame(out, {"port": srv_conn.port, "pid": __import__("os").getpid()})

    while True:
        msg = read_frame(inp)
        if msg is None or msg.get("op") == "stop":
            break
        try:
            if msg["op"] == "map_stage":
                write_frame(out, _run_map_stage(
                    msg, catalog, nested_transport,
                    notify=lambda ev: write_frame(out, ev)))
            elif msg["op"] == "unregister":
                catalog.unregister_shuffle(msg["shuffle_id"])
                write_frame(out, {"ok": True})
            elif msg["op"] == "stats":
                with catalog._lock:
                    nblocks = len(catalog._blocks)
                write_frame(out, {"ok": True, "blocks": nblocks})
            elif msg["op"] == "clock":
                # NTP-style clock alignment probe: the driver brackets
                # this short round trip with its own perf_counter_ns
                # reads and maps executor time as midpoint - t_ns
                import time
                write_frame(out, {"ok": True,
                                  "t_ns": time.perf_counter_ns()})
            elif msg["op"] == "ping":
                write_frame(out, {"ok": True})
            else:
                write_frame(out, {"ok": False,
                                  "error": f"unknown op {msg['op']!r}"})
        except Exception as e:   # surface task failures, keep serving
            import traceback
            write_frame(out, {"ok": False,
                              "error": f"{type(e).__name__}: {e}",
                              "traceback": traceback.format_exc()})
    transport.shutdown()


if __name__ == "__main__":
    main()
