"""Accelerated-shuffle client: fetch metadata, receive buffer windows.

Reference analog (SURVEY.md §2f): ``RapidsShuffleClient.scala:96-483`` —
``doFetch`` (:196) requests TableMetas, then ``issueBufferReceives``
(:293) walks a ``BufferReceiveState`` (BufferReceiveState.scala:222) of
bounce-buffer windows, reassembling each block and registering it in the
received-buffer catalog.  The state machine is driven purely by
transaction callbacks, which is what makes it unit-testable with a fake
transport (RapidsShuffleClientSuite pattern, SURVEY.md §4.2).
"""

from __future__ import annotations

import itertools
import threading
from typing import Callable, List, Optional, Set

from spark_rapids_tpu.obs import registry as obsreg
from spark_rapids_tpu.shuffle import meta as wire
from spark_rapids_tpu.shuffle.catalogs import ShuffleReceivedBufferCatalog
from spark_rapids_tpu.shuffle.transport import (BounceBufferManager,
                                                ClientConnection,
                                                InflightLimiter,
                                                Transaction,
                                                TransactionStatus,
                                                WindowedBlockIterator)

# window sequencing: window i of a transfer moves under tag base+i, so
# a lost window leaves its posted receive unmatched (a clean, detectable
# hole) instead of silently misaligning every later window.  The stride
# keeps concurrent fetches' tag ranges disjoint (up to 2^20 windows per
# transfer).
_TAG_STRIDE = 1 << 20
_tags = itertools.count(0x7100_0000, _TAG_STRIDE)


def _once(fn):
    """Exactly-once completion guard for the fetch's done callback."""
    fired = [False]
    lock = threading.Lock()

    def wrapper(arg):
        with lock:
            if fired[0]:
                return
            fired[0] = True
        fn(arg)
    return wrapper


class ShuffleClientException(Exception):
    pass


class FetchHandle:
    """Live state of one ``do_fetch`` attempt.

    Retry support: ``completed_buffer_ids`` records every block that was
    fully received and registered (its wire ``buffer_id``), so a retry
    can re-issue the fetch for only the missing map outputs.
    ``cancel()`` detaches the attempt — late windows are dropped instead
    of being registered, and the outstanding receive's bounce buffer /
    inflight budget are returned (satisfying the iterator's
    cancel-outstanding-fetches contract).
    """

    def __init__(self):
        self.completed_buffer_ids: Set[int] = set()
        self._live = True
        self._lock = threading.Lock()
        self._pending_tx: Optional[Transaction] = None
        self._on_cancel: Optional[Callable[[], None]] = None

    @property
    def live(self) -> bool:
        with self._lock:
            return self._live

    def set_cleanup(self, fn: Callable[[], None]) -> None:
        """Install the cancel-time cleanup, mutually exclusive with
        cancel(): if the attempt is already cancelled, run it now
        instead of dropping it on the floor."""
        with self._lock:
            if self._live:
                self._on_cancel = fn
                return
        fn()

    def record_completed(self, buffer_id: int) -> bool:
        """Atomically record a fully-received block — mutually exclusive
        with :meth:`cancel`, so a retry's skip-set snapshot taken after
        cancel() can never miss a block that is about to be delivered
        (which would deliver it twice) nor include one that was dropped.
        Returns False when the attempt was already cancelled: the caller
        must discard the block instead of delivering it."""
        with self._lock:
            if not self._live:
                return False
            self.completed_buffer_ids.add(buffer_id)
            return True

    def _track(self, tx: Optional[Transaction]) -> None:
        with self._lock:
            if self._live:
                self._pending_tx = tx
                return
        # posted concurrently with cancel(): the receive must not
        # escape cancellation (it would pin its bounce buffer/inflight
        # budget and hold the idle watchdog's has-pending check true)
        if tx is not None and tx.status == TransactionStatus.IN_PROGRESS:
            tx.complete(TransactionStatus.CANCELLED)

    def finish(self) -> None:
        """Mark the attempt complete: later cancel()/cleanup become
        no-ops, so a SUCCESSFUL fetch (whose iterator still aborts in
        its finally) never pays the cancel-time straggler discard."""
        with self._lock:
            self._live = False
            self._on_cancel = None
            self._pending_tx = None

    def cancel(self) -> None:
        with self._lock:
            if not self._live:
                return
            self._live = False
            tx, self._pending_tx = self._pending_tx, None
            cleanup, self._on_cancel = self._on_cancel, None
        if tx is not None and tx.status == TransactionStatus.IN_PROGRESS:
            tx.complete(TransactionStatus.CANCELLED)
        if cleanup is not None:
            cleanup()


class BufferReceiveState:
    """Receiver side of the window stream: knows every block's wire size
    from its TableMeta, walks the same WindowedBlockIterator as the
    sender, and splits each received window back into per-block payloads
    (reference: BufferReceiveState.scala:222)."""

    def __init__(self, table_metas: List[wire.TableMeta], window_size: int):
        self.table_metas = table_metas
        self.window_size = window_size
        sizes = [tm.buffer_meta.compressed_size for tm in table_metas]
        self._iter = WindowedBlockIterator(sizes, window_size)
        self._bufs = [bytearray() for _ in table_metas]
        self._completed = [False] * len(table_metas)

    def has_next(self) -> bool:
        return self._iter.has_next()

    def consume_window(self, data: bytes) -> List[int]:
        """Feed one received window; returns indices of blocks that just
        completed."""
        ranges = next(self._iter)
        expect = sum(r.range_size for r in ranges)
        if len(data) != expect:
            raise ShuffleClientException(
                f"short window: got {len(data)}, expected {expect}")
        done: List[int] = []
        off = 0
        for r in ranges:
            self._bufs[r.block] += data[off:off + r.range_size]
            off += r.range_size
            size = self.table_metas[r.block].buffer_meta.compressed_size
            if len(self._bufs[r.block]) == size:
                self._completed[r.block] = True
                done.append(r.block)
        return done

    def payload(self, block: int) -> bytes:
        assert self._completed[block]
        return bytes(self._bufs[block])


class RapidsShuffleClient:
    """Per-peer fetch driver."""

    def __init__(self, connection: ClientConnection,
                 received_catalog: ShuffleReceivedBufferCatalog,
                 bounce_window: int = 1 << 20,
                 recv_bounce: Optional[BounceBufferManager] = None,
                 inflight: Optional[InflightLimiter] = None):
        self.connection = connection
        self.received = received_catalog
        self.bounce_window = bounce_window
        self.recv_bounce = recv_bounce
        self.inflight = inflight

    def do_fetch(self, shuffle_id: int, reduce_id: int,
                 map_ids: Optional[List[int]],
                 on_batch: Callable[[int], None],
                 on_done: Callable[[Optional[str]], None],
                 skip_buffer_ids: Optional[Set[int]] = None
                 ) -> FetchHandle:
        """Fetch all of this peer's blocks for (shuffle, reduce).

        ``on_batch(temp_id)`` fires per arrived block (already in the
        received catalog); ``on_done(error)`` fires once at the end with
        None on success (reference: RapidsShuffleFetchHandler).

        ``skip_buffer_ids`` supports per-peer retry: blocks whose wire
        buffer id is in the set were already delivered by a previous
        attempt and are neither re-requested nor re-delivered, so only
        the missing map outputs move again.  Returns a
        :class:`FetchHandle` tracking the attempt.
        """
        user_done = _once(on_done)
        handle = FetchHandle()

        def on_done(err: Optional[str]) -> None:
            if err is None:
                handle.finish()
            user_done(err)

        req = wire.MetadataRequest(shuffle_id, reduce_id, map_ids or [])

        def on_meta(tx: Transaction) -> None:
            if not handle.live:
                return
            if tx.status != TransactionStatus.SUCCESS:
                on_done(f"metadata fetch failed: {tx.error_message}")
                return
            try:
                resp = wire.MetadataResponse.unpack(tx.payload)
            except Exception as e:  # malformed frame = fetch failure
                on_done(f"bad metadata response: {e}")
                return
            self._issue_buffer_receives(resp.tables, on_batch, on_done,
                                        handle, skip_buffer_ids)

        self.connection.request(req.pack(), on_meta)
        return handle

    # -- phase 2: buffer receives -----------------------------------------
    def _issue_buffer_receives(self, tables: List[wire.TableMeta],
                               on_batch, on_done, handle: FetchHandle,
                               skip_buffer_ids: Optional[Set[int]] = None
                               ) -> None:
        """issueBufferReceives analog (RapidsShuffleClient.scala:293)."""
        # degenerate batches carry no payload: complete immediately.
        # They have no buffer id to track, so only the first attempt
        # (skip_buffer_ids is None; retries pass a set, possibly empty)
        # delivers them — a retry would duplicate them otherwise.
        real: List[wire.TableMeta] = []
        for tm in tables:
            if tm.is_degenerate:
                if skip_buffer_ids is None:
                    on_batch(self.received.add(tm, b""))
            elif skip_buffer_ids and \
                    tm.buffer_meta.buffer_id in skip_buffer_ids:
                # through the handle lock: the iterator's retry-time
                # set union must never race a bare set.add
                handle.record_completed(tm.buffer_meta.buffer_id)
            else:
                real.append(tm)
        if not real:
            on_done(None)
            return

        state = BufferReceiveState(real, self.bounce_window)
        tag = next(_tags)
        win = {"i": 0}

        # a cancelled transfer's stale windows (the server may keep
        # streaming the old tag sequence) must not pin payload bytes on
        # a healthy connection: drop this attempt's whole tag range
        discard = getattr(self.connection, "discard_tag_range", None)
        if discard is not None:
            handle.set_cleanup(lambda: discard(tag, tag + _TAG_STRIDE))

        def post_receive() -> None:
            if not state.has_next():
                on_done(None)
                return
            wtag = tag + win["i"]
            win["i"] += 1
            if self.inflight is not None:
                self.inflight.acquire(self.bounce_window)
            bounce = (self.recv_bounce.acquire() if self.recv_bounce
                      else None)

            def on_window(tx: Transaction) -> None:
                # resources are released on EVERY completion path —
                # success, error, or cancellation after a failed transfer
                if bounce is not None:
                    bounce.close()
                if self.inflight is not None:
                    self.inflight.release(self.bounce_window)
                if tx.status == TransactionStatus.CANCELLED:
                    return
                if not handle.live:
                    return  # cancelled attempt: drop late windows
                try:
                    if tx.status != TransactionStatus.SUCCESS:
                        on_done(f"buffer receive failed: {tx.error_message}")
                        return
                    obsreg.get_registry().inc_many(
                        ("shuffle.fetchBytes", len(tx.payload)),
                        ("shuffle.fetchFrames", 1))
                    for idx in state.consume_window(tx.payload):
                        tm = real[idx]
                        if not handle.record_completed(
                                tm.buffer_meta.buffer_id):
                            return  # cancelled mid-window: drop the rest
                        on_batch(self.received.add(tm, state.payload(idx)))
                except ShuffleClientException as e:
                    on_done(str(e))
                    return
                post_receive()

            handle._track(self.connection.receive(
                wtag, self.bounce_window, on_window))

        def abort(message: str) -> None:
            """Fail the fetch and cancel the outstanding receive so its
            bounce buffer and inflight budget are returned to the pools."""
            on_done(message)
            handle.cancel()

        # post the first window's receive BEFORE asking the server to
        # stream, so no window can race past an unposted receive
        post_receive()
        xfer = wire.TransferRequest(
            tag, self.bounce_window,
            [tm.buffer_meta.buffer_id for tm in real])

        def on_xfer(tx: Transaction) -> None:
            if tx.status != TransactionStatus.SUCCESS:
                abort(f"transfer request failed: {tx.error_message}")
                return
            resp = wire.TransferResponse.unpack(tx.payload)
            if resp.error_code != 0:
                abort(f"server refused transfer: {resp.error_code}")

        self.connection.request(xfer.pack(), on_xfer)
