"""Accelerated-shuffle client: fetch metadata, receive buffer windows.

Reference analog (SURVEY.md §2f): ``RapidsShuffleClient.scala:96-483`` —
``doFetch`` (:196) requests TableMetas, then ``issueBufferReceives``
(:293) walks a ``BufferReceiveState`` (BufferReceiveState.scala:222) of
bounce-buffer windows, reassembling each block and registering it in the
received-buffer catalog.  The state machine is driven purely by
transaction callbacks, which is what makes it unit-testable with a fake
transport (RapidsShuffleClientSuite pattern, SURVEY.md §4.2).
"""

from __future__ import annotations

import itertools
import threading
from typing import Callable, List, Optional

from spark_rapids_tpu.shuffle import meta as wire
from spark_rapids_tpu.shuffle.catalogs import ShuffleReceivedBufferCatalog
from spark_rapids_tpu.shuffle.transport import (BounceBufferManager,
                                                ClientConnection,
                                                InflightLimiter,
                                                Transaction,
                                                TransactionStatus,
                                                WindowedBlockIterator)

_tags = itertools.count(0x7100_0000)


def _once(fn):
    """Exactly-once completion guard for the fetch's done callback."""
    fired = [False]
    lock = threading.Lock()

    def wrapper(arg):
        with lock:
            if fired[0]:
                return
            fired[0] = True
        fn(arg)
    return wrapper


class ShuffleClientException(Exception):
    pass


class BufferReceiveState:
    """Receiver side of the window stream: knows every block's wire size
    from its TableMeta, walks the same WindowedBlockIterator as the
    sender, and splits each received window back into per-block payloads
    (reference: BufferReceiveState.scala:222)."""

    def __init__(self, table_metas: List[wire.TableMeta], window_size: int):
        self.table_metas = table_metas
        self.window_size = window_size
        sizes = [tm.buffer_meta.compressed_size for tm in table_metas]
        self._iter = WindowedBlockIterator(sizes, window_size)
        self._bufs = [bytearray() for _ in table_metas]
        self._completed = [False] * len(table_metas)

    def has_next(self) -> bool:
        return self._iter.has_next()

    def consume_window(self, data: bytes) -> List[int]:
        """Feed one received window; returns indices of blocks that just
        completed."""
        ranges = next(self._iter)
        expect = sum(r.range_size for r in ranges)
        if len(data) != expect:
            raise ShuffleClientException(
                f"short window: got {len(data)}, expected {expect}")
        done: List[int] = []
        off = 0
        for r in ranges:
            self._bufs[r.block] += data[off:off + r.range_size]
            off += r.range_size
            size = self.table_metas[r.block].buffer_meta.compressed_size
            if len(self._bufs[r.block]) == size:
                self._completed[r.block] = True
                done.append(r.block)
        return done

    def payload(self, block: int) -> bytes:
        assert self._completed[block]
        return bytes(self._bufs[block])


class RapidsShuffleClient:
    """Per-peer fetch driver."""

    def __init__(self, connection: ClientConnection,
                 received_catalog: ShuffleReceivedBufferCatalog,
                 bounce_window: int = 1 << 20,
                 recv_bounce: Optional[BounceBufferManager] = None,
                 inflight: Optional[InflightLimiter] = None):
        self.connection = connection
        self.received = received_catalog
        self.bounce_window = bounce_window
        self.recv_bounce = recv_bounce
        self.inflight = inflight

    def do_fetch(self, shuffle_id: int, reduce_id: int,
                 map_ids: Optional[List[int]],
                 on_batch: Callable[[int], None],
                 on_done: Callable[[Optional[str]], None]) -> None:
        """Fetch all of this peer's blocks for (shuffle, reduce).

        ``on_batch(temp_id)`` fires per arrived block (already in the
        received catalog); ``on_done(error)`` fires once at the end with
        None on success (reference: RapidsShuffleFetchHandler).
        """
        on_done = _once(on_done)
        req = wire.MetadataRequest(shuffle_id, reduce_id, map_ids or [])

        def on_meta(tx: Transaction) -> None:
            if tx.status != TransactionStatus.SUCCESS:
                on_done(f"metadata fetch failed: {tx.error_message}")
                return
            try:
                resp = wire.MetadataResponse.unpack(tx.payload)
            except Exception as e:  # malformed frame = fetch failure
                on_done(f"bad metadata response: {e}")
                return
            self._issue_buffer_receives(resp.tables, on_batch, on_done)

        self.connection.request(req.pack(), on_meta)

    # -- phase 2: buffer receives -----------------------------------------
    def _issue_buffer_receives(self, tables: List[wire.TableMeta],
                               on_batch, on_done) -> None:
        """issueBufferReceives analog (RapidsShuffleClient.scala:293)."""
        # degenerate batches carry no payload: complete immediately
        real: List[wire.TableMeta] = []
        for tm in tables:
            if tm.is_degenerate:
                on_batch(self.received.add(tm, b""))
            else:
                real.append(tm)
        if not real:
            on_done(None)
            return

        state = BufferReceiveState(real, self.bounce_window)
        tag = next(_tags)
        pending: dict = {"tx": None}

        def post_receive() -> None:
            if not state.has_next():
                on_done(None)
                return
            if self.inflight is not None:
                self.inflight.acquire(self.bounce_window)
            bounce = (self.recv_bounce.acquire() if self.recv_bounce
                      else None)

            def on_window(tx: Transaction) -> None:
                # resources are released on EVERY completion path —
                # success, error, or cancellation after a failed transfer
                if bounce is not None:
                    bounce.close()
                if self.inflight is not None:
                    self.inflight.release(self.bounce_window)
                if tx.status == TransactionStatus.CANCELLED:
                    return
                try:
                    if tx.status != TransactionStatus.SUCCESS:
                        on_done(f"buffer receive failed: {tx.error_message}")
                        return
                    for idx in state.consume_window(tx.payload):
                        tm = real[idx]
                        on_batch(self.received.add(tm, state.payload(idx)))
                except ShuffleClientException as e:
                    on_done(str(e))
                    return
                post_receive()

            pending["tx"] = self.connection.receive(
                tag, self.bounce_window, on_window)

        def abort(message: str) -> None:
            """Fail the fetch and cancel the outstanding receive so its
            bounce buffer and inflight budget are returned to the pools."""
            on_done(message)
            tx = pending["tx"]
            if tx is not None and tx.status == TransactionStatus.IN_PROGRESS:
                tx.complete(TransactionStatus.CANCELLED)

        # post the first window's receive BEFORE asking the server to
        # stream, so no window can race past an unposted receive
        post_receive()
        xfer = wire.TransferRequest(
            tag, self.bounce_window,
            [tm.buffer_meta.buffer_id for tm in real])

        def on_xfer(tx: Transaction) -> None:
            if tx.status != TransactionStatus.SUCCESS:
                abort(f"transfer request failed: {tx.error_message}")
                return
            resp = wire.TransferResponse.unpack(tx.payload)
            if resp.error_code != 0:
                abort(f"server refused transfer: {resp.error_code}")

        self.connection.request(xfer.pack(), on_xfer)
