"""Shuffle exchange execs + the four partitionings.

Reference analogs:
  * ``GpuShuffleExchangeExec`` (reference:
    org/.../execution/GpuShuffleExchangeExec.scala:143) — partitions each
    batch on-device, then moves slices through a shuffle data plane.
  * The four partitionings — ``GpuHashPartitioning`` (murmur3 pmod,
    GpuHashPartitioning.scala:29), ``GpuRangePartitioning`` (sampled bounds,
    GpuRangePartitioning.scala:169), ``GpuRoundRobinPartitioning``
    (GpuRoundRobinPartitioning.scala:97), ``GpuSinglePartitioning``
    (GpuSinglePartitioning.scala:61), sliced on device exactly like
    ``GpuPartitioning.sliceInternalOnGpu`` (GpuPartitioning.scala:45).
  * The local block store + Arrow IPC serializer is the default data plane
    (Spark sort-shuffle + GpuColumnarBatchSerializer analog); the reader
    side concatenates slices per output partition, the
    ``ShuffleCoalesceExec`` role (ShuffleCoalesceExec.scala:199).

TPU-first departures from the reference:
  * Slicing is one reorder + contiguous ranges (a stable argsort by target
    partition), not N cudf ``contiguous_split`` buffers — XLA keeps it one
    fused gather.
  * Range partitioning needs no reservoir sampling (reference:
    SamplingUtils.scala:120): the exchange materializes its input anyway,
    so bounds come from an exact rank — a total-order lexsort rank split
    into even spans, with each equal-key group snapped to one partition
    (segment-head cohesion). Exactly balanced, same contract as Spark's
    RangePartitioner (equal keys co-located, partitions ordered).
"""

from __future__ import annotations

import itertools
import threading
from dataclasses import dataclass
from typing import Any, Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np
import pyarrow as pa

import jax
import jax.numpy as jnp

from spark_rapids_tpu import config as cfg
from spark_rapids_tpu import dtypes as dt
from spark_rapids_tpu.columnar.batch import (DeviceBatch, DeviceColumn,
                                             bucket_rows, concat_batches,
                                             from_arrow, to_arrow)
from spark_rapids_tpu.exec import sortkeys
from spark_rapids_tpu.exec.base import (PhysicalPlan, TpuExec, timed,
                                        timed_extra)
from spark_rapids_tpu.exec.cpu import concat_tables, _empty_table
from spark_rapids_tpu.expr import eval_cpu, eval_tpu, ir
from spark_rapids_tpu.expr.eval_tpu import ColVal
from spark_rapids_tpu.plan.logical import Schema, SortOrder
from spark_rapids_tpu.sched import cancel as _cancel
from spark_rapids_tpu.shuffle.serializer import (deserialize_table,
                                                 get_codec, serialize_table)


# ---------------------------------------------------------------------------
# Partitioning specs
# ---------------------------------------------------------------------------

@dataclass
class Partitioning:
    num_partitions: int

    def exprs(self) -> List[ir.Expression]:
        return []

    def cache_sig(self) -> Any:
        """Kernel-cache signature: everything the compiled target kernel
        closes over.  Subclasses with extra compile-time state (sort
        direction, null ordering) must extend this."""
        from spark_rapids_tpu.exec import kernel_cache as kc
        return kc.exprs_sig(self.exprs())


@dataclass
class SinglePartitioning(Partitioning):
    pass


@dataclass
class HashPartitioning(Partitioning):
    keys: List[ir.Expression] = None

    def exprs(self) -> List[ir.Expression]:
        return list(self.keys)


@dataclass
class RoundRobinPartitioning(Partitioning):
    pass


@dataclass
class RangePartitioning(Partitioning):
    orders: List[SortOrder] = None

    def exprs(self) -> List[ir.Expression]:
        return [o.expr for o in self.orders]

    def cache_sig(self) -> Any:
        # ascending / nulls-first are baked into the compiled range-target
        # kernel (sortkeys.encode_keys) — they must be part of the key or
        # an ASC kernel gets reused for a DESC order on the same expr.
        from spark_rapids_tpu.exec import kernel_cache as kc
        return tuple((kc.expr_sig(o.expr), o.ascending,
                      o.nulls_first_resolved) for o in self.orders)


class _ReleasingIter:
    """Partition-reader wrapper that fires a release callback exactly once
    — on exhaustion, on ``close()``, or at garbage collection — so an
    abandoned (never-iterated) reader still gives up its claim on the
    exchange's device-resident shards."""

    def __init__(self, gen, release):
        self._gen = gen
        self._release = release
        self._released = False

    def __iter__(self):
        return self

    def __next__(self):
        try:
            return next(self._gen)
        except BaseException:
            self._do_release()
            raise

    def _do_release(self):
        if not self._released:
            self._released = True
            self._release()

    def close(self):
        self._gen.close()
        self._do_release()

    def __del__(self):
        self._do_release()


# ---------------------------------------------------------------------------
# Device-side target computation
# ---------------------------------------------------------------------------

def hash_targets(batch: DeviceBatch, keys: Sequence[ir.Expression],
                 n_parts: int) -> jnp.ndarray:
    """Spark murmur3(seed=42) pmod targets (GpuHashPartitioning analog)."""
    from spark_rapids_tpu.expr.eval_tpu import hash_colval
    cap = batch.capacity
    h = jnp.full((cap,), np.int32(42), dtype=jnp.int32)
    for k in keys:
        v = eval_tpu.evaluate(k, batch)
        h = hash_colval(v, h)
    m = h % np.int32(n_parts)
    return jnp.where(m < 0, m + n_parts, m).astype(jnp.int32)


def range_targets_from_order(batch: DeviceBatch,
                             orders: Sequence[SortOrder],
                             order: jnp.ndarray,
                             n_parts: int) -> jnp.ndarray:
    """Exact-rank range targets with equal-key group cohesion, with the
    (expensive, shared-kernel) sort already done; re-derives key groups
    for boundary detection only."""
    key_groups = []
    for o in orders:
        v = eval_tpu.evaluate(o.expr, batch)
        key_groups.append(sortkeys.encode_keys(
            v, o.ascending, o.nulls_first_resolved))
    return _range_spans(batch, key_groups, order, n_parts)


def _range_spans(batch: DeviceBatch, key_groups, order: jnp.ndarray,
                 n_parts: int) -> jnp.ndarray:
    exists = batch.row_mask()
    cap = batch.capacity
    n = batch.num_rows
    # rank r of sorted position -> span r*n_parts//n; group cohesion: every
    # row of an equal-key group takes the group head's span
    new_group = sortkeys.group_boundaries(key_groups, order, exists)
    seg = jnp.cumsum(new_group.astype(jnp.int32)) - 1
    pos = jnp.arange(cap, dtype=jnp.int64)
    head_pos = jax.ops.segment_min(
        jnp.where(jnp.take(exists, order), pos, np.int64(1 << 62)), seg,
        num_segments=cap)
    span = (jnp.take(head_pos, seg) * n_parts) // jnp.maximum(n, 1)
    span = jnp.clip(span, 0, n_parts - 1).astype(jnp.int32)
    # scatter back to original row order
    target = jnp.zeros((cap,), dtype=jnp.int32).at[order].set(span)
    return target


def round_robin_targets(batch: DeviceBatch, n_parts: int,
                        start: jnp.ndarray) -> jnp.ndarray:
    cap = batch.capacity
    return ((jnp.arange(cap, dtype=jnp.int32) + start.astype(jnp.int32))
            % np.int32(n_parts))


def partition_batch(batch: DeviceBatch, target: jnp.ndarray, n_parts: int
                    ) -> Tuple[DeviceBatch, jnp.ndarray]:
    """Reorder rows so each output partition is one contiguous span.

    Returns (reordered batch, per-partition counts).  One stable argsort —
    the XLA formulation of cudf contiguous_split
    (GpuPartitioning.sliceInternalOnGpu analog).
    """
    cap = batch.capacity
    exists = batch.row_mask()
    t = jnp.where(exists, target, n_parts)  # padding parks after all spans
    counts = jnp.zeros((n_parts,), dtype=jnp.int32).at[t].add(
        exists.astype(jnp.int32), mode="drop")
    order = jnp.argsort(t, stable=True)
    cols = [c.gather(order, jnp.take(exists, order))
            for c in batch.columns]
    return DeviceBatch(batch.names, cols, batch.num_rows), counts


def slice_span(batch: DeviceBatch, offset: jnp.ndarray, count: jnp.ndarray,
               out_cap: int) -> DeviceBatch:
    """Extract rows [offset, offset+count) into a fresh bucketed batch."""
    idx = offset + jnp.arange(out_cap, dtype=jnp.int32)
    valid = jnp.arange(out_cap, dtype=jnp.int32) < count
    idx = jnp.clip(idx, 0, batch.capacity - 1)
    cols = [c.gather(idx, valid) for c in batch.columns]
    return DeviceBatch(batch.names, cols, count)


# ---------------------------------------------------------------------------
# Local shuffle block store (default data plane)
# ---------------------------------------------------------------------------

class ShuffleBlockStore:
    """In-process map-output store of serialized Arrow slices.

    Plays the role of Spark's sort-shuffle files + block manager for the
    default path (one executor); blocks are keyed (map_idx, reduce_idx)
    like shuffle block ids.
    """

    def __init__(self, codec_name: str):
        self.codec = get_codec(codec_name)
        self._blocks: Dict[Tuple[int, int], bytes] = {}
        self.bytes_written = 0

    def put(self, map_idx: int, reduce_idx: int, table: pa.Table) -> None:
        if table.num_rows == 0:
            return
        data = serialize_table(table, self.codec)
        self.bytes_written += len(data)
        from spark_rapids_tpu.obs import registry as obsreg
        obsreg.get_registry().inc_many(
            ("shuffle.bytesWritten", len(data)),
            ("shuffle.blocksWritten", 1))
        self._blocks[(map_idx, reduce_idx)] = data

    def fetch(self, reduce_idx: int) -> List[pa.Table]:
        out = []
        for (m, r), data in sorted(self._blocks.items()):
            if r == reduce_idx:
                out.append(deserialize_table(data))
        return out


class ShuffleMapTaskError(Exception):
    """A shipped map stage failed deterministically: the executor is
    healthy and replied ``ok=False`` (task exception, unknown op).
    Deliberately NOT a RuntimeError/OSError: the pipelined submit
    ladder retries (and hard-kills + respawns) only on those transport
    shapes — killing a healthy shared executor over a task bug would
    wipe concurrent exchanges' map output for a failure a re-run
    cannot fix — and the read side propagates this raw instead of
    degrading to the CPU block store, exactly as the sequential
    (depth=0) barrier path surfaces the same failure."""


class _MapOutputTracker:
    """Per-map completion book for the pipelined exchange (the
    MapOutputTracker role at map-task granularity).

    Submit threads report each ``(executor_id, map_id)`` the moment the
    executor's ``map_done`` event lands (the blocks are already in its
    catalog); reducers iterate :meth:`events` and fetch each completed
    map's output immediately instead of barriering on the whole map
    stage.  The completed list is append-only and deduplicated, so a
    map-stage RE-RUN after an executor death re-announces the same pairs
    harmlessly — readers key their fetched state by the pair.
    """

    def __init__(self):
        self._cond = threading.Condition(threading.Lock())
        self._completed: List[Tuple[str, int]] = []
        self._seen = set()
        self._open_execs = 0
        self._failed: Optional[BaseException] = None
        self._bucket_bytes: Optional[List[int]] = None

    def record_sizes(self, map_id: int, sizes: Sequence[int]) -> None:
        """Aggregate one map task's per-reduce-bucket output sizes as it
        completes (MapOutputStatistics accumulation,
        MapOutputTracker.registerMapOutput analog).  The running totals
        are what skew detection consults BEFORE any reducer fetches, so
        a hot bucket can be split while its blocks are still per-map."""
        with self._cond:
            if self._bucket_bytes is None:
                self._bucket_bytes = [0] * len(sizes)
            for i, s in enumerate(sizes):
                self._bucket_bytes[i] += int(s)

    def bucket_totals(self) -> Optional[List[int]]:
        """Aggregated per-reduce-bucket bytes across all completed maps
        (None until the first map reports)."""
        with self._cond:
            return None if self._bucket_bytes is None \
                else list(self._bucket_bytes)

    def open_exec(self) -> None:
        with self._cond:
            self._open_execs += 1

    def map_done(self, executor_id: str, map_id: int) -> None:
        with self._cond:
            key = (executor_id, map_id)
            if key not in self._seen:
                self._seen.add(key)
                self._completed.append(key)
            self._cond.notify_all()

    def exec_done(self, executor_id: str, map_ids) -> None:
        """Final (authoritative) map list for one executor's stage —
        covers a stage whose events were lost or a non-streaming
        re-submit."""
        with self._cond:
            for m in map_ids:
                key = (executor_id, m)
                if key not in self._seen:
                    self._seen.add(key)
                    self._completed.append(key)
            self._open_execs -= 1
            self._cond.notify_all()

    def fail(self, exc: BaseException) -> None:
        """A submit thread died (task failure / respawn crash-loop):
        readers must surface it instead of waiting out the timeout."""
        with self._cond:
            if self._failed is None:
                self._failed = exc
            self._open_execs -= 1
            self._cond.notify_all()

    @property
    def open_execs(self) -> int:
        """Map stages still in flight (submit thread neither finished
        nor failed) — the read-side recovery ladder checks this before
        degrading: a fetch that raced a mid-stage death should spend
        its retry budget on the submit thread's in-flight re-run, not
        prematurely fall back."""
        with self._cond:
            return self._open_execs

    def batches(self, timeout_s: float, token=None):
        """Yield LISTS of ``(executor_id, map_id)`` completions in
        announce order — everything newly available per step, blocking
        only when nothing is — until every opened executor's stage
        finished.  Batching lets a reader fetch all of one executor's
        already-completed maps in ONE do_fetch round trip (the
        per-peer fetch pattern of the sequential path), paying per-map
        round trips only for maps that genuinely trickle in.
        ``timeout_s`` bounds the NO-PROGRESS wait (a wedged-but-alive
        executor surfaces as a shuffle timeout, which escalates
        through the standard recovery ladder); ``None`` waits
        indefinitely (``pipeline.timeoutMs=0`` — dead executors still
        surface through :meth:`fail`).  A fired CancelToken raises at
        the next wait tick (the wait is chunked so cancellation lands
        promptly)."""
        import time as _time
        from spark_rapids_tpu.shuffle.iterator import \
            RapidsShuffleTimeoutException
        i = 0
        while True:
            with self._cond:
                # wall-clock no-progress deadline, re-stamped only per
                # DELIVERED batch (each yield step re-enters here): a
                # condition wakeup that brought no new completion —
                # e.g. a crash-looping executor's re-run re-announcing
                # already-seen map ids — must not push the bound out,
                # or a genuinely wedged sibling stage never escalates
                t0 = _time.monotonic()
                while (i >= len(self._completed) and
                       self._open_execs > 0 and self._failed is None):
                    if token is not None and token.is_cancelled:
                        token.check()
                    self._cond.wait(timeout=0.1)
                    if i < len(self._completed):
                        break   # real progress: deliver it
                    if timeout_s is not None and \
                            _time.monotonic() - t0 >= timeout_s:
                        raise RapidsShuffleTimeoutException(
                            "pipelined shuffle: no map completion "
                            f"for {timeout_s}s "
                            f"({self._open_execs} stages open)")
                if i < len(self._completed):
                    batch = self._completed[i:]
                    i = len(self._completed)
                else:
                    if self._failed is not None:
                        exc = self._failed
                        if isinstance(exc, (RuntimeError, OSError)) \
                                and not isinstance(
                                    exc, _cancel.QueryCancelledError):
                            # transport-side map-stage loss that
                            # exhausted the submit retry ladder:
                            # surface as fetch-failed so the read
                            # side's ONE recovery ladder
                            # (fetch_with_recovery) owns it — re-run
                            # anything recoverable, else degrade to
                            # the CPU block store when cpuFallback
                            # allows, matching the depth=0 path's
                            # behavior for a lost executor.  Task
                            # failures (ShuffleMapTaskError) and
                            # cancellation stay raw: both must fail
                            # the query exactly like the sequential
                            # barrier path, never fall back.
                            from spark_rapids_tpu.shuffle.iterator \
                                import RapidsShuffleFetchFailedException
                            raise RapidsShuffleFetchFailedException(
                                "pipelined shuffle: map stage lost: "
                                f"{exc}") from exc
                        raise exc
                    return
            yield batch


# per-exchange reduce-bucket size distribution (bytes) — byte-scaled
# bounds, not the registry's default ms bounds
_BUCKET_BYTE_BOUNDS = (1 << 10, 16 << 10, 64 << 10, 256 << 10, 1 << 20,
                       4 << 20, 16 << 20, 64 << 20, 256 << 20)

# skew_map_side() is idempotent under this module lock (a per-instance
# lock would break plan-fragment pickling for the process transport)
_SKEW_MAP_LOCK = threading.Lock()


class SkewMapOutput:
    """Map output held back at per-(map, reduce-bucket) granularity.

    The default reduce path concats every bucket before the join sees
    it; this keeps blocks separate through the map-output tracker so a
    hot bucket can be re-planned (split / replicated) BEFORE the reduce
    concat — the window Spark's AQE exploits via MapOutputStatistics
    (OptimizeSkewedJoin reads them between stages).  ``totals`` /
    ``row_counts`` come from the tracker's aggregation, not a second
    pass over the blocks."""

    def __init__(self, exchange: "TpuShuffleExchangeExec", host: bool,
                 store: Optional[ShuffleBlockStore],
                 dev: Optional[List[List[DeviceBatch]]],
                 totals: List[int], row_counts: List[int]):
        self.exchange = exchange
        self.host = host
        self.store = store
        self.dev = dev
        self.totals = totals
        self.row_counts = row_counts

    def fetch(self, pidx: int) -> List[DeviceBatch]:
        """All of reduce bucket ``pidx`` as device batches (one uploaded
        batch for the host plane, the raw slices for the device plane)."""
        ex = self.exchange
        if self.host:
            tables = [t for t in self.store.fetch(pidx) if t.num_rows]
            if not tables:
                return []
            t = concat_tables(tables, ex.schema)
            with timed(ex.metrics, "exchange.upload"):
                return [from_arrow(t, ex.min_bucket)]
        return [s for s in self.dev[pidx] if int(s.num_rows)]

class CpuShuffleExchangeExec(PhysicalPlan):
    """Host-side exchange (the stock-Spark role for fallback parity)."""

    def __init__(self, child: PhysicalPlan, partitioning: Partitioning):
        super().__init__()
        self.children = (child,)
        self.partitioning = partitioning

    @property
    def schema(self) -> Schema:
        return self.children[0].schema

    def _targets(self, table: pa.Table, start: int) -> np.ndarray:
        p = self.partitioning
        n = table.num_rows
        if isinstance(p, SinglePartitioning):
            return np.zeros(n, dtype=np.int64)
        if isinstance(p, RoundRobinPartitioning):
            # `start` carries the running row offset so the round-robin
            # wheel keeps turning across input batches
            return (np.arange(n, dtype=np.int64) + start) % p.num_partitions
        if isinstance(p, HashPartitioning):
            h = eval_cpu.evaluate(ir.Murmur3Hash(list(p.keys), 42), table)
            m = np.asarray(h.data, dtype=np.int64) % p.num_partitions
            return np.where(m < 0, m + p.num_partitions, m)
        if isinstance(p, RangePartitioning):
            # same exact-rank + group-cohesion contract as the device path
            import pyarrow.compute as pc
            vals = [eval_cpu.evaluate(o.expr, table) for o in p.orders]
            # stable multi-key order built least-significant-key-first
            # (identical technique to CpuSortExec)
            order = np.arange(n)
            for v, o in zip(reversed(vals), reversed(p.orders)):
                arr = eval_cpu.to_arrow_array(v).take(pa.array(order))
                oi = pc.sort_indices(
                    arr,
                    sort_keys=[("", "ascending" if o.ascending
                                else "descending")],
                    null_placement="at_start" if o.nulls_first_resolved
                    else "at_end")
                order = order[np.asarray(oi)]

            # vectorized equal-key group heads over the sorted order:
            # adjacent-row equality per key (nulls equal, NaN==NaN,
            # -0.0==0.0), then a prefix-max of new-group positions
            same = np.ones(n, dtype=bool)
            for v in vals:
                sv = v.data[order]
                sm = v.valid[order]
                if np.issubdtype(np.asarray(v.data).dtype, np.floating):
                    x = sv.astype(np.float64)
                    x = np.where(x == 0.0, 0.0, x)  # fold -0.0
                    eq = (x[1:] == x[:-1]) | (np.isnan(x[1:]) &
                                              np.isnan(x[:-1]))
                else:
                    eq = sv[1:] == sv[:-1]
                pair_eq = np.concatenate(
                    [[True], (sm[1:] & sm[:-1] & eq) |
                     (~sm[1:] & ~sm[:-1])])
                same &= pair_eq
            pos = np.arange(n, dtype=np.int64)
            heads = np.maximum.accumulate(np.where(same, 0, pos))
            heads[0] = 0
            span = (heads * p.num_partitions) // max(n, 1)
            target = np.zeros(n, dtype=np.int64)
            target[order] = np.clip(span, 0, p.num_partitions - 1)
            return target
        raise NotImplementedError(type(p).__name__)

    def execute(self):
        n_parts = self.partitioning.num_partitions
        state = {"slices": None}
        lock = threading.Lock()

        def input_batches():
            """(map_idx, table) pairs; range partitioning needs the global
            rank, so its whole input coalesces into one logical map task."""
            if isinstance(self.partitioning, RangePartitioning):
                all_t = []
                for it in self.children[0].execute():
                    all_t.extend(t for t in it if t.num_rows)
                t = concat_tables(all_t, self.schema)
                if t.num_rows:
                    yield 0, t
                return
            for m, it in enumerate(self.children[0].execute()):
                for t in it:
                    if t.num_rows:
                        yield m, t

        def materialize():
            # readers may run on concurrent tasks; one thread materializes
            with lock:
                return _materialize_locked()

        def _materialize_locked():
            if state["slices"] is not None:
                return state["slices"]
            slices: List[List[pa.Table]] = [[] for _ in range(n_parts)]
            rows_seen = 0
            for m, t in input_batches():
                tgt = self._targets(t, rows_seen)
                rows_seen += t.num_rows
                order = np.argsort(tgt, kind="stable")
                sorted_t = t.take(pa.array(order))
                counts = np.bincount(tgt, minlength=n_parts)
                off = 0
                for pidx in range(n_parts):
                    c = int(counts[pidx])
                    if c:
                        slices[pidx].append(sorted_t.slice(off, c))
                    off += c
            state["slices"] = slices
            return slices

        def reader(pidx: int) -> Iterator[pa.Table]:
            parts = materialize()[pidx]
            out = concat_tables(parts, self.schema)
            self.metrics.num_output_rows += out.num_rows
            yield out

        return [reader(p) for p in range(n_parts)]


class TpuShuffleExchangeExec(TpuExec):
    """Device-side exchange.

    transport='device': slices stay HBM-resident, handed to readers as
    DeviceBatches (the RapidsShuffleManager device-store analog for one
    process, RapidsShuffleInternalManager.scala:90-155).
    transport='local': each slice is downloaded, Arrow-IPC-serialized with
    the configured codec into the block store, and re-uploaded on read (the
    default sort-shuffle path analog, honest about the host round trip).
    transport='manager': slices are written through the accelerated
    TpuShuffleManager — device-resident ShuffleBufferCatalog on simulated
    executors, fetched back over the transport SPI's tag-matched
    client/server protocol (the full RapidsShuffleManager data plane,
    RapidsShuffleInternalManager.scala:90-186).
    transport='process': map stages execute in spawned executor OS
    processes (shuffle/executor_proc.py) that register output in their
    own catalogs and serve reducer pulls over TcpShuffleTransport, with
    fetch-failed -> map-stage-retry on executor death — the planned
    query genuinely crosses process boundaries (the executor-JVM fleet,
    RapidsShuffleInternalManager.scala:90-186 + UCX.scala:53-533).
    """

    def __init__(self, child: PhysicalPlan, partitioning: Partitioning,
                 conf_obj):
        super().__init__()
        self.children = (child,)
        self.partitioning = partitioning
        self.conf_obj = conf_obj
        self.transport = str(conf_obj.get(cfg.SHUFFLE_TRANSPORT))
        self.codec_name = str(conf_obj.get(cfg.SHUFFLE_COMPRESSION_CODEC))
        self.min_bucket = conf_obj.get(cfg.MIN_BUCKET_ROWS)
        self._kernels: Dict[Any, Any] = {}
        self._skew_out: Optional[SkewMapOutput] = None

    @property
    def schema(self) -> Schema:
        return self.children[0].schema

    def _target_fn(self):
        """(batch, start) -> per-row target partition ids; `start` is the
        running row offset (only round-robin consumes it, as a traced
        operand so one compiled kernel serves every batch)."""
        p = self.partitioning
        if isinstance(p, SinglePartitioning):
            return lambda b, st: jnp.zeros((b.capacity,), dtype=jnp.int32)
        if isinstance(p, RoundRobinPartitioning):
            return lambda b, st: round_robin_targets(b, p.num_partitions,
                                                     st)
        if isinstance(p, HashPartitioning):
            return lambda b, st: hash_targets(b, p.keys, p.num_partitions)
        # RangePartitioning never reaches here: _compute_targets routes
        # it through the shared-sort split (keys kernel ->
        # sortkeys.shared_lexsort -> range_targets_from_order) so the
        # minutes-scale XLA sort compile is never embedded per-schema
        raise NotImplementedError(type(p).__name__)

    def _compute_targets(self, batch: DeviceBatch,
                         rows_seen: int) -> jnp.ndarray:
        """Per-row target partition ids (padding rows -> n_parts), with
        any sort routed through the SHARED per-capacity kernels
        (sortkeys.shared_lexsort) instead of recompiling a sort inside
        every (partitioning, schema) kernel."""
        from spark_rapids_tpu.exec import kernel_cache as kc
        p = self.partitioning
        n_parts = p.num_partitions
        if isinstance(p, RangePartitioning):
            rkey = ("exch_rkeys", p.cache_sig(), batch.schema_key())
            if rkey not in self._kernels:
                orders = p.orders

                def keys_impl(b):
                    groups = [sortkeys.encode_keys(
                        eval_tpu.evaluate(o.expr, b), o.ascending,
                        o.nulls_first_resolved) for o in orders]
                    return sortkeys.stack_sort_words(groups,
                                                     b.row_mask())
                self._kernels[rkey] = kc.get_kernel(rkey,
                                                    lambda: keys_impl)
            wm = self._kernels[rkey](batch)
            order = sortkeys.shared_lexsort(wm)
            skey = ("exch_rspan", p.cache_sig(), n_parts,
                    batch.schema_key())
            if skey not in self._kernels:
                orders = p.orders

                def span_impl(b, o):
                    t = range_targets_from_order(b, orders, o, n_parts)
                    return jnp.where(b.row_mask(), t,
                                     jnp.int32(n_parts))
                self._kernels[skey] = kc.get_kernel(skey,
                                                    lambda: span_impl)
            return self._kernels[skey](batch, order)
        key = ("exch_target", type(p).__name__, n_parts,
               p.cache_sig(), batch.schema_key())
        if key not in self._kernels:
            tf = self._target_fn()

            def adj_targets(b, st):
                return jnp.where(b.row_mask(), tf(b, st),
                                 jnp.int32(n_parts))
            self._kernels[key] = kc.get_kernel(key,
                                               lambda: adj_targets)
        return self._kernels[key](
            batch, jnp.asarray(rows_seen, dtype=jnp.int32))

    def _partition_one(self, batch: DeviceBatch, rows_seen: int
                       ) -> Tuple[DeviceBatch, np.ndarray]:
        from spark_rapids_tpu.exec import kernel_cache as kc
        n_parts = self.partitioning.num_partitions
        akey = ("exch_apply", n_parts, batch.schema_key())
        if akey not in self._kernels:
            def apply_order(b, t, order):
                counts = jnp.zeros((n_parts,), dtype=jnp.int32
                                   ).at[t].add(
                    (t < n_parts).astype(jnp.int32), mode="drop")
                exists = b.row_mask()
                cols = [c.gather(order, jnp.take(exists, order))
                        for c in b.columns]
                return DeviceBatch(b.names, cols, b.num_rows), counts
            self._kernels[akey] = kc.get_kernel(akey,
                                                lambda: apply_order)
        with timed(self.metrics, "exchange.partition"):
            t = self._compute_targets(batch, rows_seen)
            order = sortkeys.shared_partition_order(t)
            reordered, counts = self._kernels[akey](batch, t, order)
        return reordered, np.asarray(counts)

    def _slice(self, reordered: DeviceBatch, offset: int, count: int
               ) -> DeviceBatch:
        from spark_rapids_tpu.exec import kernel_cache as kc
        out_cap = bucket_rows(count, self.min_bucket)
        key = ("exch_slice", out_cap, reordered.schema_key())
        if key not in self._kernels:
            self._kernels[key] = kc.get_kernel(
                key, lambda: lambda b, o, c: slice_span(b, o, c,
                                                        out_cap))
        return self._kernels[key](reordered,
                                  jnp.asarray(offset, dtype=jnp.int32),
                                  jnp.asarray(count, dtype=jnp.int32))

    def _input_batches(self):
        """Device input batches for an in-process map side; range
        partitioning needs the global rank, so its whole input coalesces
        into one batch (same contract as total sort)."""
        if isinstance(self.partitioning, RangePartitioning):
            all_b = []
            for it in self.children[0].execute():
                all_b.extend(b for b in it if int(b.num_rows))
            if all_b:
                yield concat_batches(all_b)
            return
        for it in self.children[0].execute():
            for b in it:
                if int(b.num_rows):
                    yield b

    def skew_map_side(self) -> SkewMapOutput:
        """Run this exchange's map side WITHOUT the reduce-side concat:
        the same device partition/slice pipeline as :meth:`execute`, but
        blocks stay per (map, reduce-bucket) and every map's per-bucket
        sizes aggregate at a map-output tracker as it completes.  The
        skew join reader consults the tracker's totals to split hot
        buckets before any reduce fetch.  Supported for the in-process
        planes only ('local', 'device') — the shipped transports fall
        back to the adaptive reader at planning time."""
        with _SKEW_MAP_LOCK:
            if self._skew_out is not None:
                return self._skew_out
            from spark_rapids_tpu.obs import registry as obsreg
            n_parts = self.partitioning.num_partitions
            host = self.transport == "local"
            store = ShuffleBlockStore(self.codec_name) if host else None
            dev: List[List[DeviceBatch]] = [[] for _ in range(n_parts)]
            tracker = _MapOutputTracker()
            tracker.open_exec()
            rows = [0] * n_parts
            m = 0
            rows_seen = 0
            for batch in self._input_batches():
                _cancel.check_current()  # per-batch map-side checkpoint
                reordered, counts = self._partition_one(batch, rows_seen)
                rows_seen += int(batch.num_rows)
                off = 0
                sizes = [0] * n_parts
                for pidx in range(n_parts):
                    c = int(counts[pidx])
                    if c:
                        s = self._slice(reordered, off, c)
                        if host:
                            t = to_arrow(s)
                            store.put(m, pidx, t)
                            sizes[pidx] = int(t.nbytes)
                        else:
                            dev[pidx].append(s)
                            # occupancy-scaled: bucket padding must not
                            # mask (or fake) a size skew
                            sizes[pidx] = int(
                                s.nbytes() * (c / max(int(s.capacity),
                                                      1)))
                        rows[pidx] += c
                    off += c
                tracker.record_sizes(m, sizes)
                tracker.map_done("local", m)
                m += 1
            tracker.exec_done("local", range(m))
            totals = tracker.bucket_totals() or [0] * n_parts
            reg = obsreg.get_registry()
            for tb in totals:
                reg.observe_bucket("shuffle.exchange.bucketBytes",
                                   float(tb),
                                   bounds=_BUCKET_BYTE_BOUNDS)
            if store is not None:
                self.metrics.extra["bytes_written"] = store.bytes_written
            self._skew_out = SkewMapOutput(self, host, store, dev,
                                           totals, rows)
            return self._skew_out

    # two simulated executors: map task m lands on exec-(m % 2), so every
    # read exercises both the local-catalog and the remote-fetch paths
    _MANAGER_EXECUTORS = 2

    def run_map_stage(self, shuffle_id: int, catalog, n_execs: int,
                      exec_idx: int, on_map_done=None) -> List[int]:
        """Map side of this exchange inside ONE executor process
        (RapidsCachingWriter.write analog,
        RapidsShuffleInternalManager.scala:90-155): executes this
        executor's share of input partitions (map task = input partition,
        ``p % n_execs == exec_idx``), partitions each batch on device,
        and registers the slices in the executor-local catalog.  Returns
        the completed map ids.

        ``on_map_done(map_id)`` fires after EACH map task's slices are
        fully registered (the pipelined exchange's per-map completion
        notification: reducers may start fetching that map id the moment
        it fires, while later maps are still running)."""
        n_parts = self.partitioning.num_partitions
        its = self.children[0].execute()
        if isinstance(self.partitioning, RangePartitioning):
            # global-rank bounds need the whole input (same contract as
            # the in-process path): one map task, on executor 0
            if exec_idx != 0:
                return []
            batches = []
            for it in its:
                batches.extend(b for b in it if int(b.num_rows))
            shares = [(0, batches and [concat_batches(batches)] or [])]
        else:
            shares = [(p, its[p]) for p in range(len(its))
                      if p % n_execs == exec_idx]
        maps: List[int] = []
        for map_id, it in shares:
            rows_seen = 0
            for batch in it:
                _cancel.check_current()  # per-batch map-side checkpoint
                if not int(batch.num_rows):
                    continue
                reordered, counts = self._partition_one(batch, rows_seen)
                rows_seen += int(batch.num_rows)
                off = 0
                for pidx in range(n_parts):
                    c = int(counts[pidx])
                    if c:
                        catalog.register_batch(
                            shuffle_id, map_id, pidx,
                            self._slice(reordered, off, c))
                    off += c
            maps.append(map_id)
            if on_map_done is not None:
                on_map_done(map_id)
        return maps

    _process_sids = itertools.count(1)

    def _execute_process(self):
        """Cross-process data plane: map stages run in spawned executor
        daemons (shuffle/executor_proc.py) whose catalogs serve reducer
        pulls over ``TcpShuffleTransport``; this (driver) process runs
        only the reduce side through the standard client/iterator state
        machines.  A dead executor surfaces as fetch-failed and its map
        stage is re-run on a respawned executor (the Spark stage-retry
        semantics, RapidsShuffleIterator.scala:188)."""
        from spark_rapids_tpu.shuffle import faults
        from spark_rapids_tpu.shuffle.catalogs import \
            ShuffleReceivedBufferCatalog
        from spark_rapids_tpu.shuffle.client import RapidsShuffleClient
        from spark_rapids_tpu.shuffle.iterator import (
            RapidsShuffleFetchFailedException, RapidsShuffleIterator,
            RapidsShuffleTimeoutException, RemoteSource)
        from spark_rapids_tpu.shuffle.procpool import get_executor_pool
        from spark_rapids_tpu.shuffle.tcp import TcpShuffleTransport

        n_parts = self.partitioning.num_partitions
        n_execs = max(int(self.conf_obj.get(
            cfg.SHUFFLE_PROCESS_EXECUTORS)), 1)
        nested_transport = str(self.conf_obj.get(
            cfg.SHUFFLE_PROCESS_NESTED_TRANSPORT))
        max_retries = int(self.conf_obj.get(cfg.SHUFFLE_FETCH_MAX_RETRIES))
        backoff_ms = float(self.conf_obj.get(
            cfg.SHUFFLE_FETCH_RETRY_BACKOFF_MS))
        cpu_fallback = bool(self.conf_obj.get(cfg.SHUFFLE_CPU_FALLBACK))
        pipeline_depth = max(0, int(self.conf_obj.get(
            cfg.SHUFFLE_PIPELINE_DEPTH)))
        _pipeline_timeout_ms = float(self.conf_obj.get(
            cfg.SHUFFLE_PIPELINE_TIMEOUT_MS))
        # 0 = wait indefinitely, the sequential barrier's semantics: a
        # DEAD executor still surfaces promptly (its submit thread
        # fails the tracker); only a wedged-but-alive one waits — the
        # same hang depth=0 has always had on its pipe reads
        pipeline_timeout_s = None if _pipeline_timeout_ms <= 0 \
            else max(1.0, _pipeline_timeout_ms / 1000.0)
        tcp_conf_extra = {
            "connect_timeout_ms": self.conf_obj.get(
                cfg.SHUFFLE_CONNECT_TIMEOUT_MS),
            "read_timeout_ms": self.conf_obj.get(
                cfg.SHUFFLE_READ_TIMEOUT_MS),
            # the iterator already retries whole fetch attempts
            # (fetch.maxRetries); nesting the full budget here would
            # square the connect attempts to a dead peer
            "connect_max_retries": 1 if max_retries > 0 else 0,
            "connect_backoff_ms": backoff_ms,
            # compressed wire leg: the driver's clients negotiate the
            # per-frame DATA codec in their HELLO; executor servers
            # honor whatever the client announced (tcp.wire_codec)
            "data_codec": self.codec_name,
        }
        faults.install_plan_from_conf(self.conf_obj)
        stats = faults.get_fault_stats()
        # per-exchange recovery-stats attribution: every thread doing
        # work for THIS exchange (submit threads, readers, pipeline
        # thunks, the TCP reader threads of connections they dial)
        # increments this scope alongside the process counters, so the
        # stamped per-query view is exact even with concurrent
        # exchanges in one process (the old snapshot-delta bled)
        scope = faults.StatsScope()
        state = {"done": False, "sid": None, "pool": None,
                 "transport": None, "received": None, "maps": {},
                 "clients": {}, "reads_left": n_parts, "epoch": 0,
                 "fb_store": None}
        lock = threading.Lock()
        fb_lock = threading.Lock()  # guards only the fallback store

        def stamp_fault_stats() -> None:
            """Per-query ShuffleFaultStats view, attributed exactly:
            the counts in this exchange's StatsScope (incremented by
            its own threads and connections), into Metrics.extra (the
            explain/metrics surface)."""
            snap = scope.snapshot()
            for k in faults.ShuffleFaultStats.FIELDS:
                self.metrics.extra[f"shuffle.{k}"] = snap.get(k, 0)
            if state.get("recover_error"):
                self.metrics.extra["shuffle.recover_error"] = \
                    state["recover_error"]

        def check_map_stage_faults(pool, submitted_idx) -> None:
            """FaultPlan consultation per completed map-stage submission
            (generalizes the old one-off procpool.kill test hook): a
            KILL event hard-kills the targeted executor (rule arg) or
            the one that just ran."""
            plan = faults.get_fault_plan()
            if plan is None:
                return
            ev = plan.check("procpool.map_stage")
            if ev is not None and ev.action == faults.FaultAction.KILL:
                pool.kill(ev.arg if ev.arg is not None else submitted_idx)

        def client_for(eid: str):
            """One RapidsShuffleClient per peer (its transfer-tag counter
            must be shared by every fetch on the connection); rebuilt if
            the connection died (ShuffleEnv.client_for idiom).  The dial
            itself (connect timeouts + backoff sleeps) runs OUTSIDE the
            exchange lock so a dead peer can't serialize every reader
            behind its connect attempts; only cache access locks."""
            with lock:
                c = state["clients"].get(eid)
                if c is not None and not getattr(c.connection, "closed",
                                                 False):
                    return c
                state["clients"].pop(eid, None)
                transport = state["transport"]
                received = state["received"]
            try:
                conn = transport.make_client(eid)
            except KeyError:
                # peer vanished from the address book (killed before it
                # was ever dialed): a data-plane error, so the fetch
                # fails and recovery runs — not a caller crash
                from spark_rapids_tpu.shuffle.tcp import \
                    _DeadClientConnection
                conn = _DeadClientConnection(f"unknown peer {eid}")
            c = RapidsShuffleClient(conn, received)
            with lock:
                cur = state["clients"].get(eid)
                if cur is not None and not getattr(
                        cur.connection, "closed", False):
                    winner = cur  # a concurrent dial won; use its client
                else:
                    state["clients"][eid] = c
                    winner = None
            if winner is not None:
                # don't leak the losing dial's socket — but the
                # transport may have deduped and handed us the winner's
                # own connection, which must stay open
                close = getattr(conn, "close", None)
                if conn is not winner.connection and close is not None:
                    try:
                        close()
                    except OSError:
                        pass
                return winner
            return c

        def submit(pool, exec_idx: int, sid: int, on_map=None):
            """Ship this exchange's map stage for executor ``exec_idx``;
            returns completed map ids (raises on task failure).  With
            ``on_map`` set, the task streams per-map completion events
            and ``on_map(map_id)`` fires for each BEFORE the final
            reply — the pipelined map/fetch overlap signal."""
            import time as _time
            from spark_rapids_tpu.obs import trace as obstrace
            h = pool.handle(exec_idx)
            trace_on = obstrace.is_enabled()
            clock_offset = None
            if trace_on:
                # NTP-style alignment: the handle brackets a
                # lightweight clock op INSIDE its per-call lock (so
                # another query's in-flight map stage can't inflate the
                # measured round trip) and maps the executor clock into
                # the driver domain as midpoint - t_ns, error bounded
                # by half a pipe round trip — microseconds, vs the
                # multi-ms spans it places
                clock_offset = h.clock_sync()
            task = {"op": "map_stage", "exchange": self,
                    "shuffle_id": sid, "n_execs": n_execs,
                    "exec_idx": exec_idx, "trace": trace_on,
                    "stream": on_map is not None}
            if on_map is None:
                reply = h.call(task)
            else:
                reply = h.call_stream(
                    task, lambda ev: on_map(int(ev["map_id"]))
                    if ev.get("event") == "map_done" else None)
            t_recv = _time.perf_counter_ns()
            if not reply.get("ok"):
                msg = (f"map stage on {h.executor_id} failed: "
                       f"{reply.get('error')}\n"
                       f"{reply.get('traceback', '')}")
                if reply.get("transport"):
                    # pipe/process death: retryable (the pipelined
                    # ladder kills + respawns + re-runs on this shape)
                    raise RuntimeError(msg)
                raise ShuffleMapTaskError(msg)
            # executor-side Metrics come home with the map results and
            # merge into THIS driver-side tree by plan node id — without
            # this, everything timed/counted inside the shipped fragment
            # is invisible to the query profile.  skip_root: the driver
            # already times the whole map stage on this exchange node
            # (exchange.mapStages), so the executor copy's own node time
            # must not land on top.  Merging is additive across submits:
            # a map stage RE-RUN after an executor death re-executed the
            # work, so its metrics count again — the
            # shuffle.mapStageReruns stamp (recover()) flags profiles
            # where subtree rows exceed rows delivered for that reason.
            from spark_rapids_tpu.exec.base import merge_plan_metrics
            merge_plan_metrics(self, reply.get("metrics"),
                               skip_root=True)
            # executor-side SPANS come home too (trace stitching): shift
            # them into the driver's clock domain and merge as labeled
            # executor lanes, so map stages render as real lanes in the
            # query's Chrome trace.  Fallback alignment when the clock
            # probe failed: assume zero reply transit (clock_ns was
            # stamped at reply construction).
            if trace_on and reply.get("spans"):
                off = clock_offset
                if off is None and reply.get("clock_ns"):
                    off = t_recv - int(reply["clock_ns"])
                if off is not None:
                    obstrace.record_foreign(
                        reply["spans"], off,
                        label=f"executor-{exec_idx} "
                              f"pid={reply.get('pid', '?')}")
            return h, reply["maps"]

        def install_exchange_state(pool, sid, peers) -> None:
            """The ONE state-setup block both launch modes share (the
            sequential barrier and the pipelined start_maps must not
            drift): received catalog, transport with the complete
            address book, and the process_executors stamp — fleet
            size, identically in both modes regardless of how many
            executors end up owning map output.  Caller holds
            ``lock``."""
            state["sid"] = sid
            state["pool"] = pool
            state["received"] = ShuffleReceivedBufferCatalog()
            state["transport"] = TcpShuffleTransport(
                f"driver-{sid}",
                dict(tcp_conf_extra, peers=peers, seed=sid))
            self.metrics.extra["process_executors"] = n_execs

        def materialize():
            """Sequential (depth=0) map-side barrier: every map stage
            completes before any reducer fetches."""
            with lock:
                if state["done"]:
                    return
                pool = get_executor_pool(n_execs, nested_transport)
                sid = next(self._process_sids)
                with timed(self.metrics), \
                        timed_extra(self.metrics, "exchange.mapStages"):
                    # map stages run concurrently across the fleet; each
                    # handle's pipe is independent; the submit threads
                    # inherit this query's CancelToken explicitly
                    results: List[Any] = [None] * n_execs
                    tok = _cancel.current()

                    def run(e):
                        try:
                            with _cancel.install(tok), \
                                    faults.attribute_to(scope):
                                results[e] = submit(pool, e, sid)
                        except BaseException as ex:
                            results[e] = ex
                    ts = [threading.Thread(target=run, args=(e,))
                          for e in range(n_execs)]
                    for t in ts:
                        t.start()
                    for t in ts:
                        t.join()
                    for e, r in enumerate(results):
                        if isinstance(r, BaseException):
                            raise r
                        h, mids = r
                        if mids:
                            state["maps"][h.executor_id] = (e, list(mids))
                    # address book BEFORE fault consultation: a killed
                    # executor must stay addressable so its death
                    # surfaces as a (recoverable) connect failure, not
                    # an unknown peer
                    peers = pool.peers()
                    # deterministic consultation order: after the join,
                    # sequentially per executor index
                    for e in range(n_execs):
                        check_map_stage_faults(pool, e)
                install_exchange_state(pool, sid, peers)
                state["done"] = True

        tracker = _MapOutputTracker()

        def start_maps():
            """Pipelined map-side launch: spawn the fleet, install the
            address book (executor ports are known at spawn), and ship
            every map stage WITHOUT joining — per-map completions flow
            into the tracker, and reducers begin fetching a map id the
            moment it lands.  Submit threads are daemons: a wedged
            executor must not pin interpreter exit (the tracker's
            no-progress timeout escalates the read side through the
            standard recovery ladder instead)."""
            with lock:
                if state["done"]:
                    return
                pool = get_executor_pool(n_execs, nested_transport)
                sid = next(self._process_sids)
                # spawn all handles up front: the address book must be
                # complete before any reducer dials a peer
                for e in range(n_execs):
                    pool.handle(e)
                peers = pool.peers()
                install_exchange_state(pool, sid, peers)
                tok = _cancel.current()
                import time as _time
                map_t0 = _time.perf_counter_ns()
                map_done_lock = threading.Lock()
                map_remaining = [n_execs]

                def mark_submit_done():
                    # ONE fleet-wide map-stage wall (first launch ->
                    # last submit out), stamped by the last thread:
                    # the sequential path times its barrier as one
                    # wall, and the profile's shuffle_map_s must stay
                    # comparable across modes — per-thread sums would
                    # inflate it ~n_execs-fold for concurrent stages.
                    # Called strictly BEFORE the tracker event that
                    # can release the last reader, so a finished
                    # query's profile always carries the stamp.
                    with map_done_lock:
                        map_remaining[0] -= 1
                        last = map_remaining[0] == 0
                    if last:
                        self.metrics.add_extra(
                            "exchange.mapStages",
                            _time.perf_counter_ns() - map_t0)

                def run(e):
                    eid = f"exec-{e}"
                    try:
                        with _cancel.install(tok), \
                                faults.attribute_to(scope):
                            run_attempts(e, eid)
                    except BaseException as ex:
                        mark_submit_done()
                        tracker.fail(ex)

                def run_attempts(e: int, eid: str) -> None:
                    # A submit can die MID-map-stage here (the
                    # sequential path can't: its kills land after the
                    # join barrier) — a chaos kill or crash takes the
                    # pipe down while maps are still streaming.  Retry
                    # bounded like the read ladder: pool.handle()
                    # respawns the executor (same id, fresh catalog,
                    # NEW port) and the re-run re-registers every map —
                    # the tracker dedupes re-announced ids, and readers
                    # whose fetches raced the death retry through their
                    # own ladder once add_peer repoints the address
                    # book.  EVERY retry starts by hard-killing the
                    # executor: the re-run is idempotent only against
                    # a FRESH catalog (register_batch appends, never
                    # dedupes — re-running into a surviving catalog
                    # would duplicate the failed attempt's partial
                    # registrations and silently double rows), and the
                    # forced respawn's NEW port means readers racing
                    # the window fail loudly on the stale address
                    # instead of silently fetching from a half-empty
                    # catalog.  An aliveness check can't replace this:
                    # Popen.poll() reads stale None while the killing
                    # thread holds the waitpid lock.  Cancellation is
                    # never retried.
                    last: Optional[BaseException] = None
                    for _attempt in range(n_execs + 2):
                        try:
                            h, mids = submit(
                                pool, e, sid,
                                on_map=lambda m: tracker.map_done(
                                    eid, m))
                        except _cancel.QueryCancelledError:
                            raise
                        except (RuntimeError, OSError) as ex:
                            last = ex
                            self.metrics.add_extra(
                                "shuffle.mapStageReruns", 1)
                            try:
                                pool.kill(e)
                            except Exception:
                                pass   # already gone
                            continue
                        with lock:
                            if mids:
                                state["maps"][h.executor_id] = \
                                    (e, list(mids))
                            # respawn = same executor id, new port
                            state["transport"].add_peer(
                                h.executor_id, "127.0.0.1", h.port)
                        check_map_stage_faults(pool, e)
                        mark_submit_done()
                        tracker.exec_done(h.executor_id, mids)
                        return
                    raise last
                for e in range(n_execs):
                    tracker.open_exec()
                    threading.Thread(target=run, args=(e,),
                                     daemon=True,
                                     name=f"shuffle-map-{e}").start()
                state["done"] = True

        def recover(seen_epoch: int) -> bool:
            """Re-run map stages lost with dead executors on respawned
            ones (MapOutputTracker invalidation + stage retry).  Returns
            True if the caller should retry its read — because this call
            recovered something, or a concurrent reader already did."""
            with lock:
                if state["epoch"] != seen_epoch:
                    return True
                pool = state["pool"]
                live = {h.executor_id for h in
                        pool.live_handles().values()}
                lost = [(eid, ei) for eid, (ei, _) in state["maps"].items()
                        if eid not in live]
                for eid, exec_idx in lost:
                    # re-submit BEFORE dropping the dead entry: if the
                    # respawn itself fails, readers must keep seeing the
                    # dead peer and failing loudly — removing it first
                    # would let them silently return partial results
                    h, mids = submit(pool, exec_idx, state["sid"])
                    self.metrics.add_extra("shuffle.mapStageReruns", 1)
                    del state["maps"][eid]
                    if mids:
                        state["maps"][h.executor_id] = (exec_idx,
                                                        list(mids))
                    state["transport"].add_peer(h.executor_id,
                                                "127.0.0.1", h.port)
                    check_map_stage_faults(pool, exec_idx)
                if lost:
                    state["epoch"] += 1
                return bool(lost)

        def fallback_tables(pidx: int) -> List[pa.Table]:
            """CPU-fallback read: recompute the map side in-process into
            a host ShuffleBlockStore (the stock sort-shuffle path) and
            serve the partition from it — the reference's
            fall-back-to-Spark-shuffle contract when the accelerated
            data plane is unrecoverable."""
            stats.incr("fallbacks")

            class _StoreCatalog:
                """register_batch adapter: lets run_map_stage write the
                host block store, so the fallback recompute shares the
                EXACT distributed map-side code path — identical
                row->partition mapping by construction (round-robin's
                per-map-task rows_seen reset included).  The store key
                is a fresh sequence number per registered block: the
                store's (map, reduce) key would otherwise overwrite
                earlier batches of a multi-batch map task (the real
                catalog appends a new block per call)."""

                def __init__(self, store):
                    self.store = store
                    self._seq = itertools.count()

                def register_batch(self, _sid, _map_id, reduce_id,
                                   batch):
                    self.store.put(next(self._seq), reduce_id,
                                   to_arrow(batch))

            # dedicated lock: the (potentially long) map-side recompute
            # must not stall healthy readers that only need the
            # exchange-wide lock for cache/bookkeeping accesses
            with fb_lock:
                store = state["fb_store"]
                if store is None:
                    store = ShuffleBlockStore(self.codec_name)
                    self.run_map_stage(0, _StoreCatalog(store),
                                       n_execs=1, exec_idx=0)
                    state["fb_store"] = store
            return store.fetch(pidx)

        def release():
            with lock:
                state["reads_left"] -= 1
                if state["reads_left"] != 0:
                    return
                pf = state.get("prefetcher")
            # last reader out.  Drain the pipeline FIRST, outside the
            # exchange lock (running thunks acquire it): abandoned
            # partition iterators release without ever consuming, and
            # tearing the transport down under a still-fetching
            # background thunk would drive it through the whole
            # recovery ladder (retries, map-stage re-runs, CPU-fallback
            # recompute) for a result nobody reads — close() cancels
            # pending thunks and waits out + cleans up running ones.
            if pf is not None:
                pf.close()
            with lock:
                # free the executor-resident map output
                # (ShuffleManager.unregisterShuffle analog — the pool is
                # a long-lived fleet, so blocks must not accumulate)
                if state["pool"] is not None:
                    for h in state["pool"].live_handles().values():
                        h.call({"op": "unregister",
                                "shuffle_id": state["sid"]})
                if state["transport"] is not None:
                    state["transport"].shutdown()

        def fetch_with_recovery(pidx: int, attempt) -> List[pa.Table]:
            """The ONE read-side recovery ladder — both the sequential
            reader and the pipelined read_partition run their fetch
            attempts through it, so the depth=0 oracle path and the
            pipelined path cannot diverge: retry ``attempt()`` up to
            ``n_execs + 2`` times, re-running dead executors' map
            stages between attempts, then degrade to the CPU block
            store (or raise the typed exceptions)."""
            for _attempt in range(n_execs + 2):
                with lock:
                    epoch = state["epoch"]
                try:
                    return attempt()
                except (RapidsShuffleFetchFailedException,
                        RapidsShuffleTimeoutException):
                    try:
                        recovered = recover(epoch)
                    except Exception as rec_exc:
                        # respawn itself crash-looped: not recovered,
                        # but keep the cause visible (the fallback or
                        # the raise below must not erase a product bug)
                        recovered = False
                        state["recover_error"] = (
                            f"{type(rec_exc).__name__}: {rec_exc}")
                    if not recovered and tracker.open_execs > 0:
                        # nothing recover() can re-run, but a submit
                        # thread is STILL mid-ladder on this stage (a
                        # mid-stage death races the readers before
                        # state["maps"] carries the executor): its
                        # kill+respawn+re-run will re-announce the
                        # maps and repoint the address book — keep
                        # the bounded read retries pointed at that
                        # instead of prematurely degrading
                        continue
                    if not recovered:
                        # nothing dead: a real protocol failure —
                        # degrade to the CPU block store instead of
                        # failing the query (fall-back-to-Spark-shuffle
                        # contract)
                        if cpu_fallback:
                            return [t for t in fallback_tables(pidx)
                                    if t.num_rows]
                        stamp_fault_stats()
                        raise
            # map-stage retries exhausted (crash-looping executor):
            # CPU fallback if allowed, else surface the failure — an
            # empty yield would silently drop rows
            if cpu_fallback:
                return [t for t in fallback_tables(pidx)
                        if t.num_rows]
            stamp_fault_stats()
            raise RapidsShuffleFetchFailedException(
                f"shuffle {state['sid']} reduce {pidx}: map stage "
                f"retries exhausted after {n_execs + 2} attempts")

        def reader(pidx: int) -> Iterator[DeviceBatch]:
            materialize()

            def attempt() -> List[pa.Table]:
                with lock:
                    sid = state["sid"]
                    recv = state["received"]
                    maps = dict(state["maps"])
                # clients dialed outside the lock (client_for locks
                # only around its cache accesses)
                remotes = [
                    RemoteSource(eid, client_for(eid), list(mids),
                                 refresh=lambda e=eid: client_for(e))
                    for eid, (_ei, mids) in sorted(maps.items())]
                if not remotes:
                    return []
                it = RapidsShuffleIterator(
                    sid, pidx, None, remotes, recv, timeout_s=30.0,
                    max_retries=max_retries,
                    retry_backoff_ms=backoff_ms)
                with timed_extra(self.metrics, "exchange.transfer"):
                    return [t for t in it if t.num_rows]

            with faults.attribute_to(scope):
                tables = fetch_with_recovery(pidx, attempt)
            stamp_fault_stats()
            if not tables:
                return
            t = concat_tables(tables, self.schema)
            with timed(self.metrics), \
                    timed_extra(self.metrics, "exchange.upload"):
                b = from_arrow(t, self.min_bucket)
            self.metrics.num_output_rows += t.num_rows
            self.metrics.add_batches()
            yield b

        # ------------------------------------------------------------------
        # Pipelined read side (shuffle.pipeline.depth > 0): one bounded
        # look-ahead stage fetches + decodes + uploads reduce partition
        # k+1 while partition k is being consumed (the ScanPrefetcher
        # shape), and each partition's fetch starts per map id as the
        # tracker announces it — map compute, DCN transfer, and reduce-
        # side decode overlap instead of paying three sequential walls.
        # ------------------------------------------------------------------

        def fetch_maps(eid: str, mids: List[int],
                       pidx: int) -> List[pa.Table]:
            """Fetch a batch of completed map tasks' blocks for
            ``pidx`` from one executor through the standard per-peer
            iterator state machine (all of PR 1's retry/cancel/
            leak-free paths apply; one metadata + transfer round trip
            covers the whole batch)."""
            with lock:
                sid = state["sid"]
                recv = state["received"]
            it = RapidsShuffleIterator(
                sid, pidx, None,
                [RemoteSource(eid, client_for(eid), list(mids),
                              refresh=lambda: client_for(eid))],
                recv, timeout_s=30.0, max_retries=max_retries,
                retry_backoff_ms=backoff_ms)
            return [t for t in it if t.num_rows]

        def read_partition(pidx: int):
            """Pipeline thunk body for one reduce partition: stream map
            completions, fetch each map's output as it lands, then
            decode + upload once and register the prepared batch with
            the spill catalog (pressure-aware: the admission
            controller's handle_memory_pressure can push prepared
            partitions to host/disk instead of stalling admission).
            Returns (spillable-or-plain handle, row count), or (None, 0)
            for an empty partition."""
            start_maps()
            token = _cancel.current()
            # per-executor accumulation: fetched map ids (dedup across
            # retry attempts — the tracker replays announcements) and
            # their tables in map-execution order.  A map task's blocks
            # register in catalog order and one executor's map_done
            # events announce in execution order, so per-eid table
            # order is deterministic regardless of how the completions
            # were batched into fetches.
            fetched: Dict[str, set] = {}
            got: Dict[str, List[pa.Table]] = {}

            def attempt() -> List[pa.Table]:
                for batch in tracker.batches(pipeline_timeout_s,
                                             token=token):
                    by_eid: Dict[str, List[int]] = {}
                    for eid, mid in batch:
                        if mid not in fetched.setdefault(eid, set()):
                            by_eid.setdefault(eid, []).append(mid)
                    for eid in sorted(by_eid):
                        mids = sorted(by_eid[eid])
                        # only the fetch itself is transfer wall;
                        # waiting on the tracker is map-side time
                        with timed_extra(self.metrics,
                                         "exchange.transfer"):
                            ts = fetch_maps(eid, mids, pidx)
                        # mark fetched only on success: a failed group
                        # fetch delivers nothing (the iterator's error
                        # path frees partials) and retries whole
                        fetched[eid].update(mids)
                        got.setdefault(eid, []).extend(ts)
                # deterministic assembly — executors sorted, each
                # executor's stream in map-execution order — matching
                # the per-peer registration order the sequential path
                # fetches in, so depth=0 and pipelined results agree
                return [t for eid in sorted(got) for t in got[eid]]

            with faults.attribute_to(scope):
                tables = fetch_with_recovery(pidx, attempt)
            if not tables:
                return (None, 0)
            t = concat_tables(tables, self.schema)
            with timed_extra(self.metrics, "exchange.upload"):
                b = from_arrow(t, self.min_bucket)
            # in-flight prepared partitions register at shuffle-input
            # priority: under memory pressure they spill device->host->
            # disk through the standard tiers instead of pinning HBM
            # while the consumer is still partitions away
            from spark_rapids_tpu.mem import spill as _spill
            handle = _spill.register_or_hold(
                b, priority=_spill.INPUT_FROM_SHUFFLE_PRIORITY)
            return (handle, t.num_rows)

        def _cleanup_prepared(res) -> None:
            handle = res[0] if isinstance(res, tuple) else None
            if handle is not None:
                handle.close()

        def pipelined_readers():
            from spark_rapids_tpu.exec.scans import (
                SHUFFLE_PIPELINE_KEYS, ScanPrefetcher)
            prefetcher = ScanPrefetcher(
                [lambda p=p: read_partition(p) for p in range(n_parts)],
                depth=pipeline_depth, metrics=self.metrics,
                cleanup=_cleanup_prepared,
                labels=[f"reduce{p}" for p in range(n_parts)],
                keys=SHUFFLE_PIPELINE_KEYS,
                thread_name="shuffle-pipeline")
            with lock:
                # release() drains this before transport teardown, so
                # abandoned readers can't strand a mid-fetch thunk
                state["prefetcher"] = prefetcher

            def piped_reader(pidx: int) -> Iterator[DeviceBatch]:
                try:
                    handle, nrows = prefetcher.get(pidx)
                finally:
                    prefetcher.part_done()
                stamp_fault_stats()
                if handle is None:
                    return
                try:
                    with timed(self.metrics):
                        b = handle.get()  # unspills if pressure moved
                finally:
                    # close() even when the unspill raises (HBM OOM /
                    # disk-tier IO error): the catalog entry and any
                    # disk payload must not stay pinned until GC
                    handle.close()
                self.metrics.num_output_rows += nrows
                self.metrics.add_batches()
                yield b

            return [_ReleasingIter(piped_reader(p), release)
                    for p in range(n_parts)]

        if pipeline_depth > 0:
            return pipelined_readers()
        return [_ReleasingIter(reader(p), release)
                for p in range(n_parts)]

    def _execute_ici(self):
        """ICI data plane: the whole exchange is ONE lax.all_to_all over
        the device mesh (reference: the UCX peer-to-peer transport,
        UCX.scala:53-533, restructured as a collective per SURVEY.md §5).

        Rows route to the device owning their target partition
        (partition p lives on device p % n_dev); reducer p's reader then
        sub-splits its device's received rows by the carried '__part__'
        column, staying on that device — so downstream per-partition
        kernels (join probe, per-partition aggregate) execute distributed
        across the mesh.
        """
        from spark_rapids_tpu.shuffle import ici
        n_parts = self.partitioning.num_partitions
        state = {"done": False, "dev": None, "n_dev": 1,
                 "reads_left": n_parts}
        lock = threading.Lock()

        def materialize():
            with lock:
                return _materialize_locked()

        def _materialize_locked():
            if state["done"]:
                return
            batches = []
            for it in self.children[0].execute():
                batches.extend(b for b in it if int(b.num_rows))
            if batches:
                g = concat_batches(batches)
                with timed(self.metrics, "exchange.ici"):
                    targets = self._compute_targets(g, 0)
                    dev, mesh = ici.exchange_batch(g, targets,
                                                   self.min_bucket)
                state["dev"] = dev
                state["n_dev"] = mesh.shape["shuffle"]
                self.metrics.extra["ici_devices"] = state["n_dev"]
            state["done"] = True

        def release():
            # last reducer out (iterated, closed, OR collected unread)
            # drops the device-resident shards so a multi-stage query —
            # including early-exit/limit plans that abandon partition
            # iterators — doesn't pin every exchange in HBM
            with lock:
                state["reads_left"] -= 1
                if state["reads_left"] == 0:
                    state["dev"] = None

        def reader(pidx: int) -> Iterator[DeviceBatch]:
            materialize()
            if state["dev"] is None:
                return
            b = state["dev"][pidx % state["n_dev"]]
            if b is None:
                return
            from spark_rapids_tpu.exec import kernel_cache as kc
            key = ("ici_extract", b.schema_key())
            if key not in self._kernels:
                def extract(batch, pid):
                    from spark_rapids_tpu.exec.tpu_basic import compact
                    part = batch.columns[-1].data
                    return compact(batch, part == pid)
                self._kernels[key] = kc.get_kernel(
                    key, lambda: extract)
            with timed(self.metrics, "exchange.iciExtract"):
                out = self._kernels[key](b, jnp.int32(pidx))
            if int(out.num_rows) == 0:
                return
            out = DeviceBatch(out.names[:-1], out.columns[:-1],
                              out.num_rows)  # drop __part__
            self.metrics.add_rows(out.num_rows)
            self.metrics.add_batches()
            yield out

        return [_ReleasingIter(reader(p), release)
                for p in range(n_parts)]

    def execute(self):
        if self.transport in ("ici", "ici_ring"):
            return self._execute_ici()
        if self.transport == "process":
            return self._execute_process()
        n_parts = self.partitioning.num_partitions
        state = {"done": False, "store": None, "dev_slices": None,
                 "mgr": None, "sid": None, "reads_left": n_parts}
        lock = threading.Lock()

        def materialize():
            with lock:
                return _materialize_locked()

        def _materialize_locked():
            if state["done"]:
                return
            host = self.transport == "local"
            mgr_mode = self.transport == "manager"
            store = ShuffleBlockStore(self.codec_name) if host else None
            if mgr_mode:
                from spark_rapids_tpu.shuffle.manager import \
                    get_shuffle_manager
                state["mgr"] = get_shuffle_manager(self.conf_obj)
                state["sid"] = state["mgr"].new_shuffle_id()
            dev_slices: List[List[DeviceBatch]] = \
                [[] for _ in range(n_parts)]

            m = 0
            rows_seen = 0
            for batch in self._input_batches():
                _cancel.check_current()  # per-batch map-side checkpoint
                reordered, counts = self._partition_one(batch, rows_seen)
                rows_seen += int(batch.num_rows)
                off = 0
                map_parts: List[Optional[DeviceBatch]] = [None] * n_parts
                for pidx in range(n_parts):
                    c = int(counts[pidx])
                    if c:
                        s = self._slice(reordered, off, c)
                        if host:
                            store.put(m, pidx, to_arrow(s))
                        elif mgr_mode:
                            map_parts[pidx] = s
                        else:
                            dev_slices[pidx].append(s)
                    off += c
                if mgr_mode:
                    state["mgr"].write_map_output(
                        f"exec-{m % self._MANAGER_EXECUTORS}",
                        state["sid"], m, map_parts)
                m += 1
            state["store"] = store
            state["dev_slices"] = dev_slices
            state["done"] = True
            if store is not None:
                self.metrics.extra["bytes_written"] = store.bytes_written

        def reader(pidx: int) -> Iterator[DeviceBatch]:
            materialize()
            if self.transport == "manager":
                # reducer pidx runs "on" exec-(pidx % N): its local blocks
                # come straight from the device catalog, the rest arrive
                # via the tag-matched transport protocol
                try:
                    tables = list(state["mgr"].read_partition(
                        f"exec-{pidx % self._MANAGER_EXECUTORS}",
                        state["sid"], pidx))
                    tables = [t for t in tables if t.num_rows]
                    if not tables:
                        return
                    t = concat_tables(tables, self.schema)
                    with timed(self.metrics, "exchange.upload"):
                        b = from_arrow(t, self.min_bucket)
                    self.metrics.num_output_rows += t.num_rows
                    self.metrics.add_batches()
                finally:
                    # last reducer out frees the device-resident blocks
                    # (ShuffleManager.unregisterShuffle analog)
                    with lock:
                        state["reads_left"] -= 1
                        if state["reads_left"] == 0:
                            state["mgr"].unregister_shuffle(state["sid"])
                yield b
            elif self.transport == "local":
                tables = state["store"].fetch(pidx)
                if not tables:
                    return
                # ShuffleCoalesce: concat host-serialized slices, upload once
                t = concat_tables(tables, self.schema)
                with timed(self.metrics, "exchange.upload"):
                    b = from_arrow(t, self.min_bucket)
                self.metrics.num_output_rows += t.num_rows
                self.metrics.add_batches()
                yield b
            else:
                slices = state["dev_slices"][pidx]
                if not slices:
                    return
                with timed(self.metrics, "exchange.concat"):
                    b = concat_batches(slices)
                self.metrics.add_rows(b.num_rows)
                self.metrics.add_batches()
                yield b

        return [reader(p) for p in range(n_parts)]


class CpuCoalescePartitionsExec(PhysicalPlan):
    """Merge contiguous input partitions into at most n output partitions
    by chaining their iterators — no shuffle, no data movement
    (GpuCoalesceExec analog)."""

    def __init__(self, child: PhysicalPlan, num_partitions: int):
        super().__init__()
        self.children = (child,)
        self.num_partitions = num_partitions

    @property
    def schema(self) -> Schema:
        return self.children[0].schema

    def execute(self):
        its = self.children[0].execute()
        n = min(self.num_partitions, len(its)) or 1
        groups = np.array_split(np.arange(len(its)), n)
        return [itertools.chain.from_iterable(its[i] for i in g)
                for g in groups if len(g)]


class TpuCoalescePartitionsExec(TpuExec):
    """Device-currency twin of CpuCoalescePartitionsExec."""

    def __init__(self, child: PhysicalPlan, num_partitions: int):
        super().__init__()
        self.children = (child,)
        self.num_partitions = num_partitions

    @property
    def schema(self) -> Schema:
        return self.children[0].schema

    def execute(self):
        its = self.children[0].execute()
        n = min(self.num_partitions, len(its)) or 1
        groups = np.array_split(np.arange(len(its)), n)
        return [itertools.chain.from_iterable(its[i] for i in g)
                for g in groups if len(g)]
