"""Pool of executor processes for the cross-process shuffle data plane.

Reference analog: the Spark executor fleet the RapidsShuffleManager
spans — each executor serves its cached map output over the transport
while the driver tracks MapStatus topology
(RapidsShuffleInternalManager.scala:163-186).  The pool spawns
``spark_rapids_tpu.shuffle.executor_proc`` daemons, ships map-stage
tasks over the pipe protocol, and hands out TCP clients for the reduce
side.  ``kill(i)`` exists so tests can exercise the fetch-failed ->
map-stage-retry path (RapidsShuffleIterator.scala:188 semantics).
"""

from __future__ import annotations

import subprocess
import sys
import threading
from typing import Dict, List, Optional

from spark_rapids_tpu.shuffle.executor_proc import read_frame, write_frame


class ExecutorHandle:
    """One live executor daemon."""

    def __init__(self, executor_id: str, proc: subprocess.Popen, port: int):
        self.executor_id = executor_id
        self.proc = proc
        self.port = port
        self._lock = threading.Lock()

    @property
    def alive(self) -> bool:
        return self.proc.poll() is None

    def call(self, msg: dict) -> dict:
        """One request/response over the pipe (serialized per handle)."""
        return self.call_stream(msg, None)

    def call_stream(self, msg: dict, on_event) -> dict:
        """Request/response that also surfaces interleaved EVENT frames
        (dicts carrying an ``"event"`` key) to ``on_event`` before the
        final reply — the pipelined map stage streams one ``map_done``
        event per completed map task this way.  ``on_event=None``
        silently discards events, which keeps plain :meth:`call` safe
        against a streaming reply."""
        with self._lock:
            if not self.alive:
                # "transport": the pipe/process is gone, not the task —
                # the submit side kills + respawns and re-runs on this
                # flag; its absence means the executor itself replied
                # ok=False (a deterministic task failure, not retried)
                return {"ok": False, "transport": True,
                        "error": f"executor {self.executor_id} is dead"}
            try:
                write_frame(self.proc.stdin, msg)
                while True:
                    reply = read_frame(self.proc.stdout)
                    if reply is None or "event" not in reply:
                        break
                    if on_event is not None:
                        try:
                            on_event(reply)
                        except Exception:
                            pass   # a consumer bug must not desync the pipe
            except (BrokenPipeError, OSError) as e:
                return {"ok": False, "transport": True,
                        "error": f"pipe: {e}"}
            if reply is None:
                return {"ok": False, "transport": True,
                        "error": f"executor {self.executor_id} closed the "
                                 "pipe mid-request"}
            return reply

    def clock_sync(self) -> Optional[int]:
        """NTP-midpoint clock offset (driver perf_counter_ns domain
        minus this executor's), or None when the probe fails.  The
        round trip is bracketed INSIDE the handle lock: under
        concurrent queries ``call`` waits behind another query's
        multi-second map stage, and an offset computed around that
        wait would mis-place stitched spans by seconds — bracketed
        here, the error is bounded by half a pipe round trip."""
        import time
        with self._lock:
            if not self.alive:
                return None
            try:
                t_req = time.perf_counter_ns()
                write_frame(self.proc.stdin, {"op": "clock"})
                reply = read_frame(self.proc.stdout)
                t_rsp = time.perf_counter_ns()
            except (BrokenPipeError, OSError):
                return None
        if not reply or not reply.get("ok"):
            return None
        return (t_req + t_rsp) // 2 - int(reply["t_ns"])

    def kill(self) -> None:
        self.proc.kill()
        self.proc.wait()


class ExecutorPool:
    """Spawns and tracks N executor daemons on this host.

    ``nested_transport='ici'`` gives every executor an n-device virtual
    mesh and keeps nested exchanges on it — the DCN-over-ICI
    composition (collectives inside each executor process, TCP between
    them; one pod slice per executor host with DCN across slices)."""

    def __init__(self, n_execs: int, cpu_jax: bool = True,
                 nested_transport: str = "local",
                 mesh_devices: int = 8):
        self.n_execs = n_execs
        self.cpu_jax = cpu_jax
        self.nested_transport = nested_transport
        self.mesh_devices = mesh_devices
        self._handles: List[Optional[ExecutorHandle]] = [None] * n_execs
        self._lock = threading.Lock()

    def _spawn(self, idx: int) -> ExecutorHandle:
        import os
        eid = f"exec-{idx}"
        args = [sys.executable, "-m",
                "spark_rapids_tpu.shuffle.executor_proc",
                "--executor-id", eid,
                "--nested-transport", self.nested_transport]
        if self.cpu_jax:
            args.append("--cpu")
        env = dict(os.environ)
        if self.nested_transport in ("ici", "ici_ring"):
            flags = [f for f in env.get("XLA_FLAGS", "").split()
                     if "host_platform_device_count" not in f]
            flags.append("--xla_force_host_platform_device_count="
                         f"{self.mesh_devices}")
            env["XLA_FLAGS"] = " ".join(flags)
        proc = subprocess.Popen(args, stdin=subprocess.PIPE,
                                stdout=subprocess.PIPE, env=env)
        hello = read_frame(proc.stdout)
        if hello is None:
            proc.kill()
            raise RuntimeError(f"executor {eid} died before hello")
        return ExecutorHandle(eid, proc, hello["port"])

    def handle(self, idx: int) -> ExecutorHandle:
        """The executor at ``idx``, respawning it if dead (Spark's
        executor-replacement; a respawned executor has an empty catalog,
        so callers must re-run lost map stages)."""
        with self._lock:
            h = self._handles[idx]
            if h is None or not h.alive:
                h = self._spawn(idx)
                self._handles[idx] = h
            return h

    def live_handles(self) -> Dict[int, ExecutorHandle]:
        with self._lock:
            return {i: h for i, h in enumerate(self._handles)
                    if h is not None and h.alive}

    def kill(self, idx: int) -> None:
        """Test hook: hard-kill one executor (fetch-failed injection)."""
        with self._lock:
            h = self._handles[idx]
        if h is not None:
            h.kill()

    def peers(self) -> Dict[str, tuple]:
        with self._lock:
            return {h.executor_id: ("127.0.0.1", h.port)
                    for h in self._handles if h is not None and h.alive}

    def shutdown(self) -> None:
        with self._lock:
            handles, self._handles = self._handles, \
                [None] * self.n_execs
        for h in handles:
            if h is not None and h.alive:
                h.call({"op": "stop"})
                h.proc.wait(timeout=5)


_pool: Optional[ExecutorPool] = None
_pool_lock = threading.Lock()


def get_executor_pool(n_execs: int,
                      nested_transport: str = "local") -> ExecutorPool:
    """Process-wide pool (executor-singleton idiom, GpuShuffleEnv.scala:26).
    Rebuilt if a larger fleet or a different nested transport is
    requested."""
    global _pool
    with _pool_lock:
        if _pool is None or _pool.n_execs < n_execs or \
                _pool.nested_transport != nested_transport:
            old, _pool = _pool, ExecutorPool(
                n_execs, nested_transport=nested_transport)
            if old is not None:
                old.shutdown()
        return _pool


def reset_executor_pool() -> None:
    global _pool
    with _pool_lock:
        if _pool is not None:
            _pool.shutdown()
        _pool = None
