"""Host shuffle serialization: Arrow IPC stream + compression codec SPI.

This is the default-path shuffle currency — the role the reference's
``GpuColumnarBatchSerializer`` over ``JCudfSerialization`` plays for its
stock sort-shuffle data plane (reference:
GpuColumnarBatchSerializer.scala:95-265, ShuffleCoalesceExec.scala:199),
with the JCudf host wire format replaced by Arrow IPC (SURVEY.md §2h).

The codec SPI mirrors the reference's ``TableCompressionCodec`` registry
(reference: TableCompressionCodec.scala:41-372) with its nvcomp GPU-LZ4
implementation (NvcompLZ4CompressionCodec.scala) replaced by Arrow-native
buffer compression: shuffle bytes move host-side here, so the codec runs
where the data is. "copy" (no-op) matches the reference's
CopyCompressionCodec test codec.
"""

from __future__ import annotations

import io
from typing import Dict, Optional

import pyarrow as pa


class TableCompressionCodec:
    """SPI: compress/decompress a serialized table partition."""

    name: str = "copy"

    def ipc_compression(self) -> Optional[str]:
        """Arrow IPC body-buffer compression name, or None."""
        return None


class CopyCompressionCodec(TableCompressionCodec):
    name = "copy"


class Lz4CompressionCodec(TableCompressionCodec):
    name = "lz4"

    def ipc_compression(self) -> Optional[str]:
        return "lz4"


class ZstdCompressionCodec(TableCompressionCodec):
    name = "zstd"

    def ipc_compression(self) -> Optional[str]:
        return "zstd"


class ZlibCompressionCodec(TableCompressionCodec):
    """Accepted everywhere the conf reaches so ``codec=zlib`` (the
    TCP wire leg's stdlib-only option) never crashes the block-store
    or CPU-fallback paths — but Arrow IPC has no zlib buffer
    compression, so blocks serialize uncompressed here; only the
    per-frame DATA wire leg (tcp.wire_codec) actually deflates."""

    name = "zlib"


_CODECS: Dict[str, TableCompressionCodec] = {}


def register_codec(codec: TableCompressionCodec) -> None:
    _CODECS[codec.name] = codec


def get_codec(name: str) -> TableCompressionCodec:
    try:
        return _CODECS[name.lower()]
    except KeyError:
        raise ValueError(
            f"unknown shuffle compression codec '{name}'; "
            f"known: {sorted(_CODECS)}") from None


register_codec(CopyCompressionCodec())
_CODECS["none"] = _CODECS["copy"]  # conf alias
register_codec(Lz4CompressionCodec())
register_codec(ZstdCompressionCodec())
register_codec(ZlibCompressionCodec())


def serialize_table(table: pa.Table, codec: TableCompressionCodec) -> bytes:
    """One shuffle block: an Arrow IPC stream holding the partition slice."""
    sink = io.BytesIO()
    opts = pa.ipc.IpcWriteOptions(compression=codec.ipc_compression())
    with pa.ipc.new_stream(sink, table.schema, options=opts) as w:
        w.write_table(table)
    return sink.getvalue()


def deserialize_table(data: bytes) -> pa.Table:
    with pa.ipc.open_stream(pa.py_buffer(data)) as r:
        return r.read_all()
