"""Deterministic fault injection + fault statistics for the shuffle
data plane.

Reference analog: the reference proves its recovery paths by injecting
failures into the transport state machines from tests
(RapidsShuffleClientSuite / RapidsShuffleServerSuite, SURVEY.md §4.2)
and by killing executors to exercise fetch-failed -> map-stage-retry
(RapidsShuffleIterator.scala:188).  This module generalizes the one-off
``procpool.kill(i)`` hook into a reusable, seeded harness: a
config-driven :class:`FaultPlan` that production code consults at named
injection points, so chaos runs are reproducible bit-for-bit.

Injection points (consulted via ``plan.check(point)``):

=====================  =====================================================
point                  consulted
=====================  =====================================================
``tcp.connect``        once per client socket connect attempt (CLOSE =>
                       the attempt fails as if refused)
``tcp.client.data``    once per DATA frame the client reader receives
                       (DROP discards the frame, CLOSE drops the
                       connection, CORRUPT flips payload bytes, DELAY
                       sleeps before delivery)
``tcp.server.data``    once per DATA frame the server streams (DROP
                       silently skips the send, CLOSE closes the peer
                       socket mid-window, DELAY sleeps before sending)
``pyworker.batch``     once per batch shipped to a python worker (KILL
                       hard-kills the worker process mid-batch)
``procpool.map_stage``  once per completed map-stage submission (KILL
                       hard-kills the executor that just finished, or
                       the one named by the rule's ``i<idx>`` field)
=====================  =====================================================

Plan spec grammar (``spark.rapids.tpu.shuffle.test.faultPlan``)::

    spec      := directive (";" directive)*
    directive := "seed=" INT
               | point ":" action [ "@" N ] ( ":" field )*
    field     := "x" M    max fires (default 1)
               | "p" P    fire with probability P per consultation
                          (seeded; alternative to "@N")
               | "d" MS   delay milliseconds (DELAY action)
               | "i" IDX  target index (e.g. executor index for KILL)

``@N`` arms the rule starting at the Nth consultation of its point
(1-based); it then fires on every later consultation until ``x`` fires
have happened.  With neither ``@N`` nor ``pP`` the rule is armed from
the first consultation.  Example::

    seed=7;tcp.server.data:drop@2;tcp.client.data:close@5;pyworker.batch:kill@1

drops the 2nd DATA frame streamed, closes the client socket on what
would be the 5th DATA frame received, and kills the first python worker
batch — identically on every run.
"""

from __future__ import annotations

import contextlib
import enum
import random
import re
import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from spark_rapids_tpu.obs import registry as _obsreg


# ---------------------------------------------------------------------------
# Per-exchange stats attribution (the stamp_fault_stats accounting fix)
# ---------------------------------------------------------------------------

class StatsScope:
    """One exchange's private view of the recovery counters.

    The process-wide :class:`ShuffleFaultStats` block is shared by every
    exchange in the process, so a snapshot delta taken by one exchange
    used to bleed in whatever recovery work CONCURRENT exchanges did in
    the same window.  A scope fixes the attribution: every ``incr`` also
    lands in the scope installed on the incrementing thread (via
    :func:`attribute_to`), and the exchange stamps ITS scope's counts —
    exact per-query recovery work, not a window over shared counters.

    Threads that outlive the installing frame (the TCP client's reader
    thread) capture the scope at connection build time and install it
    themselves — see ``TcpClientConnection``."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counts: Dict[str, int] = {}

    def incr(self, name: str, n: int = 1) -> None:
        with self._lock:
            self._counts[name] = self._counts.get(name, 0) + n

    def get(self, name: str) -> int:
        with self._lock:
            return self._counts.get(name, 0)

    def snapshot(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._counts)


_scope_tls = threading.local()


def current_scope() -> Optional[StatsScope]:
    """The StatsScope installed on this thread, or None."""
    return getattr(_scope_tls, "scope", None)


@contextlib.contextmanager
def attribute_to(scope: Optional[StatsScope]):
    """Install ``scope`` as this thread's stats-attribution target for
    the duration (nestable; None is a no-op passthrough that keeps any
    outer scope in place)."""
    if scope is None:
        yield None
        return
    prev = getattr(_scope_tls, "scope", None)
    _scope_tls.scope = scope
    try:
        yield scope
    finally:
        _scope_tls.scope = prev


class FaultAction(enum.Enum):
    DROP = "drop"
    DELAY = "delay"
    CLOSE = "close"
    CORRUPT = "corrupt"
    KILL = "kill"


@dataclass
class FaultRule:
    point: str
    action: FaultAction
    at: Optional[int] = None      # first consultation (1-based) to arm at
    prob: float = 0.0             # alternative: seeded per-consult chance
    delay_ms: float = 0.0
    max_fires: int = 1
    arg: Optional[int] = None     # action-specific index (e.g. executor)
    fires: int = 0


@dataclass(frozen=True)
class FaultEvent:
    """One fault decision returned by :meth:`FaultPlan.check`."""
    point: str
    action: FaultAction
    delay_s: float = 0.0
    arg: Optional[int] = None


class ShuffleFaultStats:
    """Per-process counter block for the recovery machinery (retries,
    reconnects, fallbacks, ...), surfaced through ``Metrics.extra`` by
    the exchange (the per-query view is a snapshot delta)."""

    FIELDS = ("retries", "reconnects", "fallbacks", "timeouts",
              "injected_faults", "worker_respawns")

    def __init__(self):
        self._lock = threading.Lock()
        self._counts: Dict[str, int] = {k: 0 for k in self.FIELDS}

    def incr(self, name: str, n: int = 1) -> None:
        with self._lock:
            self._counts[name] = self._counts.get(name, 0) + n
        # mirror into the unified metrics registry so the recovery
        # counters appear in per-query profiles next to the scan/spill/
        # semaphore channels (obs/registry.py)
        _obsreg.get_registry().inc(f"shuffle.{name}", n)
        # and into the incrementing thread's attribution scope, so a
        # per-exchange stats view is exact even with concurrent
        # exchanges sharing this process block (see StatsScope)
        scope = current_scope()
        if scope is not None:
            scope.incr(name, n)

    def get(self, name: str) -> int:
        with self._lock:
            return self._counts.get(name, 0)

    def snapshot(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._counts)

    def reset(self) -> None:
        with self._lock:
            self._counts = {k: 0 for k in self.FIELDS}

    def __repr__(self) -> str:
        return f"ShuffleFaultStats({self.snapshot()})"


class FaultPlan:
    """Seeded, deterministic fault schedule.

    ``check(point)`` is cheap and thread-safe: it bumps the point's
    consultation counter and returns the first armed rule's
    :class:`FaultEvent` (or None).  Determinism: occurrence-based rules
    (``@N``) depend only on consultation order at that point;
    probability rules draw from one seeded RNG under the plan lock.
    """

    def __init__(self, rules: List[FaultRule], seed: int = 0):
        self.rules = list(rules)
        self.seed = seed
        self._rng = random.Random(seed)
        self._counts: Dict[str, int] = {}
        self._lock = threading.Lock()

    def check(self, point: str) -> Optional[FaultEvent]:
        with self._lock:
            n = self._counts.get(point, 0) + 1
            self._counts[point] = n
            for r in self.rules:
                if r.point != point or r.fires >= r.max_fires:
                    continue
                if r.prob > 0.0:
                    if self._rng.random() >= r.prob:
                        continue
                elif r.at is not None and n < r.at:
                    continue
                r.fires += 1
                get_fault_stats().incr("injected_faults")
                return FaultEvent(point, r.action, r.delay_ms / 1000.0,
                                  r.arg)
        return None

    def consultations(self, point: str) -> int:
        with self._lock:
            return self._counts.get(point, 0)

    @property
    def total_fires(self) -> int:
        with self._lock:
            return sum(r.fires for r in self.rules)

    @staticmethod
    def corrupt(payload: bytes) -> bytes:
        """Deterministically flip one bit in the middle of the payload."""
        if not payload:
            return payload
        out = bytearray(payload)
        out[len(out) // 2] ^= 0x40
        return bytes(out)

    _DIRECTIVE = re.compile(r"^(?P<point>[\w.]+):(?P<action>[a-z]+)"
                            r"(?:@(?P<at>\d+))?$")

    @classmethod
    def parse(cls, spec: str) -> Optional["FaultPlan"]:
        """Parse the config-string grammar (module docstring); returns
        None for an empty spec, raises ValueError on a malformed one."""
        spec = (spec or "").strip()
        if not spec:
            return None
        seed = 0
        rules: List[FaultRule] = []
        for directive in spec.split(";"):
            directive = directive.strip()
            if not directive:
                continue
            if directive.startswith("seed="):
                seed = int(directive[len("seed="):])
                continue
            parts = directive.split(":")
            head = ":".join(parts[:2])
            m = cls._DIRECTIVE.match(head)
            if m is None:
                raise ValueError(f"bad fault directive {directive!r}")
            rule = FaultRule(
                point=m.group("point"),
                action=FaultAction(m.group("action")),
                at=int(m.group("at")) if m.group("at") else None)
            for f in parts[2:]:
                f = f.strip()
                if f.startswith("x"):
                    rule.max_fires = int(f[1:])
                elif f.startswith("p"):
                    rule.prob = float(f[1:])
                elif f.startswith("d"):
                    rule.delay_ms = float(f[1:])
                elif f.startswith("i"):
                    rule.arg = int(f[1:])
                else:
                    raise ValueError(f"bad fault field {f!r} in "
                                     f"{directive!r}")
            rules.append(rule)
        return cls(rules, seed)


# ---------------------------------------------------------------------------
# Process-wide plan + stats (the executor-singleton idiom)
# ---------------------------------------------------------------------------

_plan: Optional[FaultPlan] = None
_stats = ShuffleFaultStats()
_lock = threading.Lock()


def get_fault_plan() -> Optional[FaultPlan]:
    return _plan


def set_fault_plan(plan: Optional[FaultPlan]) -> Optional[FaultPlan]:
    """Install (or clear, with None) the process-wide fault plan."""
    global _plan
    with _lock:
        _plan = plan
    return plan


def install_plan_from_conf(conf, fresh: bool = False
                           ) -> Optional[FaultPlan]:
    """Parse ``spark.rapids.tpu.shuffle.test.faultPlan`` and install it.

    An empty spec leaves a directly-installed plan alone (tests set
    plans programmatically) but CLEARS a previously conf-installed one
    — a stale chaos plan must not leak into a later session that did
    not ask for injection.  With ``fresh=False`` (the per-exchange
    call) an unchanged spec keeps the installed plan's consultation
    counters — re-installing per exchange would re-arm one-shot rules
    and break determinism.  Session construction passes ``fresh=True``
    so a NEW session with the same spec gets a re-armed plan instead
    of inheriting an exhausted one."""
    from spark_rapids_tpu import config as cfg
    spec = str(conf.get(cfg.SHUFFLE_FAULT_PLAN) or "").strip()
    cur = get_fault_plan()
    if not spec:
        if cur is not None and getattr(cur, "spec", None) is not None:
            set_fault_plan(None)
        return None
    if not fresh and cur is not None and \
            getattr(cur, "spec", None) == spec:
        return cur
    plan = FaultPlan.parse(spec)
    plan.spec = spec
    set_fault_plan(plan)
    return plan


def get_fault_stats() -> ShuffleFaultStats:
    return _stats


def reset_fault_stats() -> None:
    _stats.reset()
