"""Shuffle transport SPI: connections, transactions, bounce buffers.

Reference analog (SURVEY.md §2f): ``RapidsShuffleTransport.scala:38-578``
— the pluggable transport abstraction the UCX plugin implements.  The SPI
is retained so an ICI/DCN C++ transport, the in-process loopback used by
tests, or a socket transport can sit behind the same client/server state
machines (the reference's load-bearing design: the whole protocol is
unit-testable with fake transports, RapidsShuffleTestHelper.scala:26-120).

Pieces, with their reference counterparts:

* ``Transaction`` / ``TransactionStatus``  — RapidsShuffleTransport.scala:270-335
* ``ClientConnection`` / ``ServerConnection`` — tag-matched send/recv surface
* ``BounceBufferManager``  — fixed pool of fixed-size staging buffers
  (BounceBufferManager.scala:166); on TPU these are host staging windows
  for DCN hops (pure-ICI paths don't need them, SURVEY.md §2f note)
* ``WindowedBlockIterator`` — maps many (offset,size) blocks onto bounce
  windows (WindowedBlockIterator.scala:179)
* ``InflightLimiter`` — bounds in-flight receive bytes
  (UCXShuffleTransport.scala:323-346)
* ``make_transport`` — reflective loading by class name
  (RapidsShuffleTransport.makeTransport :542-576)
"""

from __future__ import annotations

import enum
import importlib
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple


class TransactionStatus(enum.Enum):
    NOT_STARTED = 0
    IN_PROGRESS = 1
    SUCCESS = 2
    ERROR = 3
    CANCELLED = 4


@dataclass
class TransactionStats:
    """Reference: TransactionStats (tx time, throughput)
    RapidsShuffleTransport.scala:282-287."""
    start_time: float = 0.0
    end_time: float = 0.0
    bytes_moved: int = 0

    @property
    def tx_time_ms(self) -> float:
        return max(0.0, (self.end_time - self.start_time) * 1000.0)

    @property
    def throughput_mbps(self) -> float:
        dt = max(self.end_time - self.start_time, 1e-9)
        return self.bytes_moved / dt / 1e6


class Transaction:
    """One async request/response or buffer send/receive.

    Callbacks fire exactly once when the transaction completes; the
    client/server state machines are driven entirely from them (the
    reference tests invoke them directly — we keep that property).
    """

    def __init__(self, tag: int = 0):
        self.tag = tag
        self.status = TransactionStatus.NOT_STARTED
        self.error_message: Optional[str] = None
        self.stats = TransactionStats()
        self.payload: Optional[bytes] = None   # response body, if any
        self._cb: Optional[Callable[["Transaction"], None]] = None
        self._done = threading.Event()
        self._complete_lock = threading.Lock()

    def start(self, cb: Optional[Callable[["Transaction"], None]]) -> None:
        self.status = TransactionStatus.IN_PROGRESS
        self.stats.start_time = time.monotonic()
        self._cb = cb

    def complete(self, status: TransactionStatus,
                 payload: Optional[bytes] = None,
                 error: Optional[str] = None) -> None:
        with self._complete_lock:
            if self._done.is_set():
                return  # first completion wins (e.g. cancel vs late data)
            self.status = status
            self.payload = payload
            self.error_message = error
            self.stats.end_time = time.monotonic()
            if payload is not None:
                self.stats.bytes_moved += len(payload)
            self._done.set()
            cb, self._cb = self._cb, None
        if cb is not None:
            cb(self)

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self._done.wait(timeout)


def backoff_delay_s(base_s: float, attempt: int, rng,
                    cap_s: Optional[float] = None) -> float:
    """Shared exponential-backoff schedule: base doubled per attempt
    (1-based) with +0-25% seeded jitter, optionally capped.  One
    implementation so the deterministic-chaos timing policy cannot
    silently diverge between the transport and fetch layers."""
    delay = base_s * (2 ** max(attempt - 1, 0))
    delay *= 1.0 + 0.25 * rng.random()
    return min(delay, cap_s) if cap_s is not None else delay


class ClientConnection:
    """Reducer-side connection to one mapper executor."""

    def request(self, data: bytes,
                cb: Callable[[Transaction], None]) -> Transaction:
        """Send a control frame; the transaction completes with the
        server's response frame in ``payload``."""
        raise NotImplementedError

    def receive(self, tag: int, nbytes: int,
                cb: Callable[[Transaction], None]) -> Transaction:
        """Post a tagged receive for ``nbytes`` of buffer data."""
        raise NotImplementedError


class ServerConnection:
    """Mapper-side connection surface."""

    def send(self, peer_executor_id: str, tag: int, data: bytes,
             cb: Callable[[Transaction], None]) -> Transaction:
        """Send buffer bytes to a peer's tagged receive."""
        raise NotImplementedError

    def register_request_handler(
            self, handler: Callable[[bytes, str], bytes]) -> None:
        """Install the control-frame handler. The transport MUST invoke it
        as ``handler(frame_bytes, peer_executor_id)`` — the peer id is how
        the server addresses its streaming sends back to the requester."""
        raise NotImplementedError


class ShuffleTransport:
    """Transport factory SPI (reference: RapidsShuffleTransport trait)."""

    def __init__(self, executor_id: str, conf=None):
        self.executor_id = executor_id
        self.conf = conf

    def make_client(self, peer_executor_id: str) -> ClientConnection:
        raise NotImplementedError

    def server(self) -> ServerConnection:
        raise NotImplementedError

    def shutdown(self) -> None:
        pass


def make_transport(class_name: str, executor_id: str,
                   conf=None) -> ShuffleTransport:
    """Reflectively instantiate a transport implementation
    (reference: RapidsShuffleTransport.makeTransport :542-576)."""
    mod_name, _, cls_name = class_name.rpartition(".")
    cls = getattr(importlib.import_module(mod_name), cls_name)
    t = cls(executor_id, conf)
    if not isinstance(t, ShuffleTransport):
        raise TypeError(f"{class_name} is not a ShuffleTransport")
    return t


# ---------------------------------------------------------------------------
# Bounce buffers
# ---------------------------------------------------------------------------

class BounceBuffer:
    def __init__(self, index: int, size: int, mgr: "BounceBufferManager"):
        self.index = index
        self.data = bytearray(size)
        self._mgr = mgr

    @property
    def size(self) -> int:
        return len(self.data)

    def close(self) -> None:
        self._mgr.release(self)


class BounceBufferManager:
    """Fixed pool of fixed-size host staging buffers
    (reference: BounceBufferManager.scala:166).  Acquire blocks until a
    buffer frees, mirroring the reference's bounded-staging behavior.
    Allocation is backed by the native host arena when available."""

    def __init__(self, name: str, buffer_size: int, num_buffers: int):
        self.name = name
        self.buffer_size = buffer_size
        self._free: List[BounceBuffer] = [
            BounceBuffer(i, buffer_size, self) for i in range(num_buffers)]
        self._lock = threading.Condition()
        self.num_buffers = num_buffers

    def acquire(self, timeout: Optional[float] = None
                ) -> Optional[BounceBuffer]:
        with self._lock:
            if not self._free and not self._lock.wait_for(
                    lambda: bool(self._free), timeout):
                return None
            return self._free.pop()

    def try_acquire(self) -> Optional[BounceBuffer]:
        with self._lock:
            return self._free.pop() if self._free else None

    def release(self, buf: BounceBuffer) -> None:
        with self._lock:
            self._free.append(buf)
            self._lock.notify()

    @property
    def available(self) -> int:
        with self._lock:
            return len(self._free)


class InflightLimiter:
    """Bounds bytes in flight (reference:
    UCXShuffleTransport.scala:323-346)."""

    def __init__(self, max_bytes: int):
        self.max_bytes = max_bytes
        self._inflight = 0
        self._cv = threading.Condition()

    def acquire(self, nbytes: int, timeout: Optional[float] = None) -> bool:
        nbytes = min(nbytes, self.max_bytes)  # single huge buffer still goes
        with self._cv:
            ok = self._cv.wait_for(
                lambda: self._inflight + nbytes <= self.max_bytes, timeout)
            if not ok:
                return False
            self._inflight += nbytes
            return True

    def release(self, nbytes: int) -> None:
        nbytes = min(nbytes, self.max_bytes)
        with self._cv:
            self._inflight = max(0, self._inflight - nbytes)
            self._cv.notify_all()


# ---------------------------------------------------------------------------
# Windowed block iterator
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class BlockRange:
    """A contiguous range of one logical block mapped into the current
    window: (block index, offset within block, length)."""
    block: int
    range_start: int
    range_size: int

    @property
    def is_complete_for(self) -> bool:
        return True


class WindowedBlockIterator:
    """Maps N variable-size blocks onto fixed-size windows
    (reference: WindowedBlockIterator.scala:179).

    Given block sizes [b0, b1, ...] and a window of W bytes, each ``next``
    yields the list of (block, start, size) ranges that fill the next
    window; a block larger than W spans several windows.
    """

    def __init__(self, block_sizes: Sequence[int], window_size: int):
        if window_size <= 0:
            raise ValueError("window_size must be positive")
        self.block_sizes = list(block_sizes)
        self.window_size = window_size
        self._block = 0
        self._offset = 0

    def __iter__(self):
        return self

    def has_next(self) -> bool:
        return self._block < len(self.block_sizes)

    def __next__(self) -> List[BlockRange]:
        if not self.has_next():
            raise StopIteration
        out: List[BlockRange] = []
        remaining = self.window_size
        while remaining > 0 and self._block < len(self.block_sizes):
            bsize = self.block_sizes[self._block]
            left = bsize - self._offset
            take = min(left, remaining)
            if take > 0:
                out.append(BlockRange(self._block, self._offset, take))
            remaining -= take
            self._offset += take
            if self._offset >= bsize:
                self._block += 1
                self._offset = 0
        return out
