"""In-process loopback transport with UCX-style tag matching.

Reference analog: the UCX transport (``shuffle-plugin/.../UCX.scala:53-533``)
provides (a) a request/response control channel (the TCP management
handshake + active messages, UCX.scala:192-246) and (b) tag-matched buffer
sends/receives (UCX.scala:247-311).  This implementation provides the same
two surfaces over in-process queues, so every state machine above the SPI
(client, server, iterator, manager) runs unmodified; a C++ DCN/socket
transport slots in behind the same interfaces.  Sends posted before their
matching receive are queued, exactly UCX's expected-tag semantics.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Callable, Dict, Optional, Tuple

from spark_rapids_tpu.shuffle.transport import (ClientConnection,
                                                ServerConnection,
                                                ShuffleTransport,
                                                Transaction,
                                                TransactionStatus)

_registry_lock = threading.Lock()
_servers: Dict[str, "LocalServerConnection"] = {}
# client endpoints are keyed (client_executor_id, server_executor_id): one
# executor holds one connection PER peer, and a server streaming to peer P
# must find the P->self connection's channel
_endpoints: Dict[Tuple[str, str], "LocalClientConnection"] = {}


def reset_registry() -> None:
    with _registry_lock:
        _servers.clear()
        _endpoints.clear()


class _TagChannel:
    """Tag-matched rendezvous: unmatched sends and unmatched receives
    queue until their counterpart arrives.

    Completions are dispatched through a trampoline: a callback that
    triggers another send/receive on this channel enqueues the new
    completion instead of nesting a stack frame, so streaming thousands
    of windows stays at constant stack depth (the reference's progress
    thread gives UCX the same property, UCX.scala:140)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._pending_sends: Dict[int, deque] = {}
        self._pending_recvs: Dict[int, deque] = {}
        self._completions: deque = deque()
        self._draining = False
        self._failed: Optional[str] = None
        # tag ranges of cancelled transfers: late sends into them are
        # dropped instead of queued (tags are never reused, so entries
        # stay valid).  Bounded: beyond 256 cancelled transfers on one
        # connection, the oldest ranges age out and their (by then
        # ancient) stragglers merely queue as before.
        self._discarded: deque = deque(maxlen=256)

    def _dispatch(self, completions) -> None:
        """completions: (tx, status, payload, error) 4-tuples."""
        with self._lock:
            self._completions.extend(completions)
            if self._draining:
                return
            self._draining = True
        try:
            while True:
                with self._lock:
                    if not self._completions:
                        return
                    tx, status, payload, error = \
                        self._completions.popleft()
                tx.complete(status, payload=payload, error=error)
        finally:
            with self._lock:
                self._draining = False

    def send(self, tag: int, data: bytes, tx: Transaction) -> None:
        recv = None
        discarded = False
        with self._lock:
            failed = self._failed
            if failed is None:
                discarded = any(lo <= tag < hi
                                for (lo, hi) in self._discarded)
                q = None if discarded else self._pending_recvs.get(tag)
                while q:
                    # skip receives cancelled after posting: they must
                    # not swallow data meant for a live receive
                    cand = q.popleft()
                    if cand[0].status == TransactionStatus.IN_PROGRESS:
                        recv = cand
                        break
                if recv is None and not discarded:
                    self._pending_sends.setdefault(tag, deque()).append(
                        (data, tx))
        if discarded:
            # late window of a cancelled transfer: drop, don't pin
            self._dispatch([(tx, TransactionStatus.CANCELLED, None,
                             None)])
            return
        if failed is not None:
            self._dispatch([(tx, TransactionStatus.ERROR, None, failed)])
        elif recv is not None:
            rtx, _nbytes = recv
            self._dispatch([(tx, TransactionStatus.SUCCESS, None, None),
                            (rtx, TransactionStatus.SUCCESS, data,
                             None)])

    def receive(self, tag: int, nbytes: int, tx: Transaction) -> None:
        send = None
        with self._lock:
            failed = self._failed
            if failed is None:
                q = self._pending_sends.get(tag)
                if q:
                    send = q.popleft()
                else:
                    self._pending_recvs.setdefault(tag, deque()).append(
                        (tx, nbytes))
        if failed is not None:
            self._dispatch([(tx, TransactionStatus.ERROR, None, failed)])
        elif send is not None:
            data, stx = send
            self._dispatch([(stx, TransactionStatus.SUCCESS, None,
                             None),
                            (tx, TransactionStatus.SUCCESS, data,
                             None)])

    def has_pending_recvs(self) -> bool:
        """True if any posted receive is still IN_PROGRESS — the TCP
        reader's watchdog only escalates a read timeout to a failure
        when something is actually in flight.  Cancelled/completed
        entries are purged here, so a cancelled fetch attempt cannot
        pin the watchdog (or leak queue entries) forever."""
        with self._lock:
            live = False
            for tag in list(self._pending_recvs):
                kept = deque(
                    (tx, n) for (tx, n) in self._pending_recvs[tag]
                    if tx.status == TransactionStatus.IN_PROGRESS)
                if kept:
                    self._pending_recvs[tag] = kept
                    live = True
                else:
                    del self._pending_recvs[tag]
            return live

    def discard_tag_range(self, lo: int, hi: int) -> None:
        """Drop queued (unmatched) sends and receives with lo <= tag <
        hi — a cancelled transfer's stale windows must not pin their
        payload bytes on a still-healthy connection until it dies.
        Orphaned send transactions complete CANCELLED (stopping any
        send_next chain); receive transactions were cancelled by the
        caller already."""
        with self._lock:
            self._discarded.append((lo, hi))
            stale = []
            for tag in [t for t in self._pending_sends
                        if lo <= t < hi]:
                stale.extend(self._pending_sends.pop(tag))
            for tag in [t for t in self._pending_recvs
                        if lo <= t < hi]:
                del self._pending_recvs[tag]
        self._dispatch([(tx, TransactionStatus.CANCELLED, None, None)
                        for (_data, tx) in stale])

    def fail_all(self, error: str) -> None:
        """Fail every queued send/receive AND mark the channel terminal:
        operations posted after the failure complete with ERROR instead
        of queueing forever (a disconnect racing a fetch would otherwise
        stall the iterator to its timeout).  Completions route through
        the trampoline like every other path."""
        with self._lock:
            self._failed = error
            pending = [(tx, TransactionStatus.ERROR, None, error)
                       for q in self._pending_sends.values()
                       for (_data, tx) in q]
            pending += [(tx, TransactionStatus.ERROR, None, error)
                        for q in self._pending_recvs.values()
                        for (tx, _n) in q]
            self._pending_sends.clear()
            self._pending_recvs.clear()
        self._dispatch(pending)


class LocalClientConnection(ClientConnection):
    def __init__(self, local_executor_id: str, peer_executor_id: str):
        self.local_executor_id = local_executor_id
        self.peer_executor_id = peer_executor_id
        self.channel = _TagChannel()
        with _registry_lock:
            _endpoints[(local_executor_id, peer_executor_id)] = self

    def request(self, data: bytes, cb) -> Transaction:
        tx = Transaction()
        tx.start(cb)
        with _registry_lock:
            server = _servers.get(self.peer_executor_id)
        if server is None or server.handler is None:
            tx.complete(TransactionStatus.ERROR,
                        error=f"no server at {self.peer_executor_id}")
            return tx
        try:
            resp = server.handler(data, self.local_executor_id)
        except Exception as e:
            tx.complete(TransactionStatus.ERROR, error=str(e))
            return tx
        tx.complete(TransactionStatus.SUCCESS, payload=resp)
        return tx

    def receive(self, tag: int, nbytes: int, cb) -> Transaction:
        tx = Transaction(tag)
        tx.start(cb)
        self.channel.receive(tag, nbytes, tx)
        return tx

    def discard_tag_range(self, lo: int, hi: int) -> None:
        self.channel.discard_tag_range(lo, hi)


class LocalServerConnection(ServerConnection):
    def __init__(self, executor_id: str):
        self.executor_id = executor_id
        self.handler: Optional[Callable] = None
        with _registry_lock:
            _servers[executor_id] = self

    def register_request_handler(self, handler) -> None:
        self.handler = handler

    def send(self, peer_executor_id: str, tag: int, data: bytes,
             cb) -> Transaction:
        tx = Transaction(tag)
        tx.start(cb)
        with _registry_lock:
            ep = _endpoints.get((peer_executor_id, self.executor_id))
        if ep is None:
            tx.complete(TransactionStatus.ERROR,
                        error=f"no endpoint at {peer_executor_id}")
            return tx
        ep.channel.send(tag, data, tx)
        return tx


class LocalShuffleTransport(ShuffleTransport):
    """Default transport for single-host runs and tests; loadable via
    ``make_transport`` just like the UCX plugin is
    (RapidsShuffleTransport.scala:542-576)."""

    def make_client(self, peer_executor_id: str) -> LocalClientConnection:
        return LocalClientConnection(self.executor_id, peer_executor_id)

    def server(self) -> LocalServerConnection:
        return LocalServerConnection(self.executor_id)

    def shutdown(self) -> None:
        with _registry_lock:
            _servers.pop(self.executor_id, None)
            for key in [k for k in _endpoints if k[0] == self.executor_id]:
                _endpoints.pop(key)
